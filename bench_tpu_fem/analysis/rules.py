"""The pluggable rule engine over pallas_call captures.

Five rules, one record per kernel instance per rule (plus config-level
records for the plan cross-check and the collective-axis check):

  R1 tiling      Mosaic BlockSpec divisibility, dtype-aware: the last two
                 block dims must each be divisible by (sublane, 128) —
                 sublane 8 for 4-byte, 16 for 2-byte, 32 for 1-byte
                 dtypes — or equal the full array dim (rank-1: lane only).
                 This is the rule the round-4 kernels violated.
  R2 vmem        Per-kernel VMEM accounting from the CAPTURED specs
                 (blocked operands/outputs double-buffered by the Mosaic
                 pipeline, whole-array operands and scratch single), with
                 two checks: no kernel's accounted footprint may exceed
                 the scoped-VMEM limit its config compiles under, and the
                 config's plan estimator may not undershoot the accounted
                 footprint by more than 10% (estimates are upper-bound
                 models — an undershoot means the plan admits kernels
                 Mosaic will reject) unless a tracked waiver
                 (budgets.R2_WAIVERS) documents why.
  R3 f64         No float64 operand, out_shape, scratch or kernel-jaxpr
                 intermediate may reach a pallas_call: Mosaic has no f64,
                 and the df32 pipeline exists precisely so f64 never hits
                 the TPU.
  R4 lowering    Walk the kernel's closed jaxpr and flag primitives with
                 no Mosaic lowering: a hard denylist (fft/sort/linalg/
                 conv — never lowerable) plus, when this jax build
                 exposes the Mosaic lowering registry, any primitive
                 absent from it.
  R5 collectives shard_map consistency: every ppermute/psum axis name a
                 dist kernel binds must exist in the device mesh AND in
                 the halo layout's declared axes.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from . import ANALYZER_VERSION  # noqa: F401  (re-exported with the engine)
from .budgets import R2_WAIVERS, scoped_limit_bytes
from .capture import CollectiveUse, KernelCapture

RULE_IDS = ("R1", "R2", "R3", "R4", "R5")

# Estimate may exceed the spec-accounted footprint freely (models include
# live values the specs cannot see); it may undershoot by at most this.
R2_TOLERANCE = 0.10


@dataclass
class PlanCheck:
    """What the config's plan function claimed: which estimator, its
    estimate (None = the estimator does not model this form, e.g. the
    chunked retry path), and the scoped-VMEM limit the config compiles
    under (budgets.scoped_limit_bytes of the plan's kib request)."""

    estimator: str
    estimate_bytes: int | None
    scoped_limit: int = scoped_limit_bytes(None)
    notes: str = ""


@dataclass
class ConfigResult:
    """One driven shipped-config instance: its captures plus the plan
    claim and collective uses the rules cross-check."""

    name: str
    tags: dict = field(default_factory=dict)
    captures: list[KernelCapture] = field(default_factory=list)
    collectives: list[CollectiveUse] = field(default_factory=list)
    plan: PlanCheck | None = None
    plan_unsupported: str | None = None  # plan routes this config off
    # Pallas entirely (records as a pass: the fallback is the defense)


@dataclass
class Record:
    """One rule verdict. status: pass | fail | warn | skip."""

    config: str
    rule: str
    kernel: str | None
    status: str
    detail: dict = field(default_factory=dict)


def _records_fail(records: list[Record]) -> bool:
    return any(r.status == "fail" for r in records)


# ---------------------------------------------------------------------------
# R1: Mosaic tiling divisibility, dtype-aware
# ---------------------------------------------------------------------------

def _sublane_quantum(dtype: str) -> int:
    import numpy as np

    itemsize = np.dtype(dtype).itemsize
    return {4: 8, 2: 16, 1: 32}.get(itemsize, 8)


def check_tiling(config: str, cap: KernelCapture) -> Record:
    bad = []
    for rec in cap.specs:
        bs = rec.block_shape
        if bs is None:
            continue
        # None entries are squeezed dims (block size 1 there).
        bs = tuple(1 if d is None else d for d in bs)
        ash = rec.arr_shape
        q_sub = _sublane_quantum(rec.dtype)
        dims = ([(-1, 128)] if len(bs) == 1
                else [(-2, q_sub), (-1, 128)])
        for d, q in dims:
            if len(ash) < -d:
                continue
            if bs[d] != ash[d] and bs[d] % q != 0:
                bad.append({
                    "io": rec.io, "idx": rec.idx, "block": list(bs),
                    "array": list(ash), "dim": d, "dtype": rec.dtype,
                    "quantum": q,
                })
    return Record(config, "R1", cap.name,
                  "fail" if bad else "pass",
                  {"violations": bad} if bad else {})


# ---------------------------------------------------------------------------
# R2: VMEM accounting vs plan estimate and scoped limit
# ---------------------------------------------------------------------------

def _bytes_of(shape: tuple, dtype: str) -> int:
    import numpy as np

    return int(math.prod(int(d) for d in shape) or 1) * np.dtype(dtype).itemsize


def measured_vmem_bytes(cap: KernelCapture) -> dict:
    """Spec-accounted VMEM footprint of one kernel instance: blocked
    operands/outputs count twice (the Mosaic pipeline double-buffers
    every gridded block), whole-array bindings and scratch once. A lower
    bound of the true footprint (live values inside the kernel body are
    invisible to specs) — which is exactly the right direction for the
    undershoot check: a plan estimate below even this bound is provably
    wrong."""
    gridded = math.prod(cap.grid) > 1 if cap.grid else False
    total = 0
    parts = {"in": 0, "out": 0, "scratch": 0}
    spec_by = {("in", r.idx): r for r in cap.specs if r.io == "in"}
    for i, (shape, dtype) in enumerate(cap.operand_avals):
        rec = spec_by.get(("in", i))
        if rec is not None and rec.block_shape is not None:
            blk = tuple(1 if d is None else d for d in rec.block_shape)
            b = _bytes_of(blk, dtype) * (2 if gridded else 1)
        else:
            b = _bytes_of(shape, dtype)
        parts["in"] += b
    for r in cap.specs:
        if r.io != "out":
            continue
        if r.block_shape is not None:
            blk = tuple(1 if d is None else d for d in r.block_shape)
            b = _bytes_of(blk, r.dtype) * (2 if gridded else 1)
        else:
            b = _bytes_of(r.arr_shape, r.dtype)
        parts["out"] += b
    if not any(r.io == "out" for r in cap.specs):
        for shape, dtype in cap.out_avals:
            parts["out"] += _bytes_of(shape, dtype)
    for shape, dtype in cap.scratch:
        parts["scratch"] += _bytes_of(shape, dtype)
    parts["total"] = sum(parts.values())
    return parts


def check_vmem(config: str, captures: list[KernelCapture],
               plan: PlanCheck | None) -> list[Record]:
    records: list[Record] = []
    limit = plan.scoped_limit if plan else scoped_limit_bytes(None)
    peak = 0
    peak_kernel = None
    for cap in captures:
        parts = measured_vmem_bytes(cap)
        status = "pass" if parts["total"] <= limit else "fail"
        records.append(Record(config, "R2", cap.name, status, {
            "accounted_bytes": parts["total"],
            "breakdown": {k: v for k, v in parts.items() if k != "total"},
            "scoped_limit_bytes": limit,
        }))
        if parts["total"] > peak:
            peak, peak_kernel = parts["total"], cap.name
    if plan is not None and plan.estimate_bytes is not None and captures:
        # The estimator models the dominant (engine) kernel — cross-check
        # against the peak accounted footprint in this drive.
        est = plan.estimate_bytes
        gap = (peak - est) / est if est else float("inf")
        waiver = R2_WAIVERS.get((config, plan.estimator))
        if peak > est * (1 + R2_TOLERANCE) and waiver is None:
            status = "fail"
        else:
            status = "pass"
        records.append(Record(config, "R2", None, status, {
            "estimator": plan.estimator,
            "estimate_bytes": est,
            "accounted_peak_bytes": peak,
            "accounted_peak_kernel": peak_kernel,
            "estimate_vs_accounted_gap": round(gap, 4),
            "scoped_limit_bytes": limit,
            **({"waiver": waiver} if waiver else {}),
            **({"notes": plan.notes} if plan.notes else {}),
        }))
    return records


# ---------------------------------------------------------------------------
# R3: f64 leak detection
# ---------------------------------------------------------------------------

def _jaxpr_f64(jaxpr) -> list[str]:
    import jax.core as jc

    leaks: list[str] = []

    def aval_f64(v):
        aval = getattr(v, "aval", None)
        dt = getattr(aval, "dtype", None)
        return dt is not None and str(dt) == "float64"

    def walk(j):
        for v in list(j.invars) + list(j.constvars):
            if aval_f64(v):
                leaks.append(f"var:{v.aval.str_short()}")
        for eqn in j.eqns:
            for v in eqn.outvars:
                if aval_f64(v):
                    leaks.append(
                        f"{eqn.primitive.name}:{v.aval.str_short()}")
            for p in eqn.params.values():
                if isinstance(p, jc.ClosedJaxpr):
                    walk(p.jaxpr)
                elif isinstance(p, jc.Jaxpr):
                    walk(p)

    walk(jaxpr)
    return leaks


def check_f64(config: str, cap: KernelCapture) -> Record:
    leaks = []
    for i, (shape, dtype) in enumerate(cap.operand_avals):
        if dtype == "float64":
            leaks.append({"where": f"operand[{i}]", "shape": list(shape)})
    for i, (shape, dtype) in enumerate(cap.out_avals):
        if dtype == "float64":
            leaks.append({"where": f"out_shape[{i}]", "shape": list(shape)})
    for i, (shape, dtype) in enumerate(cap.scratch):
        if dtype == "float64":
            leaks.append({"where": f"scratch[{i}]", "shape": list(shape)})
    jaxpr = cap.kernel_jaxpr()
    if jaxpr is not None:
        for leak in _jaxpr_f64(jaxpr):
            leaks.append({"where": "jaxpr", "what": leak})
    return Record(config, "R3", cap.name,
                  "fail" if leaks else "pass",
                  {"leaks": leaks} if leaks else {})


# ---------------------------------------------------------------------------
# R4: primitives with no Mosaic lowering
# ---------------------------------------------------------------------------

# Structural primitives Mosaic handles by recursing, not by a per-prim
# lowering rule — descend, never flag.
_STRUCTURAL = {
    "pjit", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "custom_vjp_call_jaxpr", "remat", "remat2",
    "checkpoint", "cond", "while", "scan", "custom_vmap_call",
}

# Never lowerable on the Mosaic TPU backend regardless of jax version.
_DENYLIST = {
    "fft", "sort", "sort_key_val", "top_k", "eig", "eigh", "svd", "qr",
    "lu", "cholesky", "triangular_solve", "conv_general_dilated",
}

# Absent from this jaxlib's Mosaic lowering-rule listing but PROVEN to
# lower: the folded window kernels (gather — the in-kernel window
# gather) and the df kernels (optimization_barrier — the renorm-first
# accumulation pin) both compiled and measured on v5e hardware
# (MEASURE_r04.log / BASELINE_MATRIX_r04.json). Registry listings move
# between jax versions; hardware evidence wins.
_KNOWN_LOWERED = {"gather", "optimization_barrier"}


def _mosaic_registry() -> set[str] | None:
    try:
        from jax._src.pallas.mosaic import lowering as _ml

        return {p.name for p in _ml.lowering_rules}
    except Exception:
        return None


def _jaxpr_prims(jaxpr) -> set[str]:
    import jax.core as jc

    names: set[str] = set()

    def walk(j):
        for eqn in j.eqns:
            names.add(eqn.primitive.name)
            for p in eqn.params.values():
                if isinstance(p, jc.ClosedJaxpr):
                    walk(p.jaxpr)
                elif isinstance(p, jc.Jaxpr):
                    walk(p)

    walk(jaxpr)
    return names


def check_lowering(config: str, cap: KernelCapture,
                   registry: set[str] | None) -> Record:
    jaxpr = cap.kernel_jaxpr()
    if jaxpr is None:
        if cap.jaxpr_error is not None:
            # A real kernel whose jaxpr could not be re-derived is a
            # coverage hole, not a pass — fail loudly.
            return Record(config, "R4", cap.name, "fail",
                          {"jaxpr_error": cap.jaxpr_error})
        return Record(config, "R4", cap.name, "skip",
                      {"reason": "no kernel jaxpr (hand-built capture)"})
    prims = _jaxpr_prims(jaxpr)
    denied = sorted(prims & _DENYLIST)
    unknown: list[str] = []
    if registry is not None:
        unknown = sorted(prims - registry - _STRUCTURAL - _DENYLIST
                         - _KNOWN_LOWERED)
    if denied:
        return Record(config, "R4", cap.name, "fail",
                      {"denied": denied, "unknown": unknown})
    if unknown:
        # Absent from this jax build's Mosaic registry but not provably
        # unlowerable (registries move between versions): surfaced as a
        # warning, not a violation.
        return Record(config, "R4", cap.name, "warn", {"unknown": unknown})
    return Record(config, "R4", cap.name, "pass", {})


# ---------------------------------------------------------------------------
# R5: shard_map collective-axis consistency
# ---------------------------------------------------------------------------

def check_collectives(config: str,
                      uses: list[CollectiveUse]) -> list[Record]:
    records = []
    for u in uses:
        bad = [a for a in u.axes
               if a not in u.mesh_axes or a not in u.declared_axes]
        records.append(Record(config, "R5", None,
                              "fail" if bad else "pass", {
                                  "prim": u.prim, "axes": list(u.axes),
                                  "mesh_axes": list(u.mesh_axes),
                                  "declared_axes": list(u.declared_axes),
                                  **({"bad_axes": bad} if bad else {}),
                              }))
    return records


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------

def run_rules(result: ConfigResult,
              rules: tuple[str, ...] = RULE_IDS) -> list[Record]:
    """All applicable rule records for one driven config."""
    records: list[Record] = []
    if result.plan_unsupported is not None and not result.captures:
        # The plan routes this config off Pallas entirely — that routing
        # is the defense the rule engine exists to verify, so it records
        # as an explicit pass with the reason.
        return [Record(result.name, "R2", None, "pass",
                       {"plan_unsupported": result.plan_unsupported})]
    registry = _mosaic_registry() if "R4" in rules else None
    for cap in result.captures:
        if "R1" in rules:
            records.append(check_tiling(result.name, cap))
        if "R3" in rules:
            records.append(check_f64(result.name, cap))
        if "R4" in rules:
            records.append(check_lowering(result.name, cap, registry))
    if "R2" in rules:
        if result.plan_unsupported is not None:
            # Captures from a variant the plan refuses to ship (e.g.
            # explicit geom='g' where corner is forced): the tiling/
            # dtype/lowering lint above still applies — it is CPU-test
            # coverage of a kernel users can reach with explicit flags —
            # but VMEM accounting does not: the plan already routes the
            # config off this kernel on TPU.
            records.append(Record(result.name, "R2", None, "pass",
                                  {"plan_unsupported":
                                   result.plan_unsupported}))
        else:
            records.extend(
                check_vmem(result.name, result.captures, result.plan))
    if "R5" in rules and result.collectives:
        records.extend(check_collectives(result.name, result.collectives))
    return records


def summarize(records: list[Record]) -> dict:
    by_rule: dict[str, dict] = {}
    for r in records:
        d = by_rule.setdefault(r.rule, {"pass": 0, "fail": 0, "warn": 0,
                                        "skip": 0})
        d[r.status] += 1
    return {
        "analyzer_version": ANALYZER_VERSION,
        "records": len(records),
        "violations": sum(1 for r in records if r.status == "fail"),
        "warnings": sum(1 for r in records if r.status == "warn"),
        "by_rule": by_rule,
    }
