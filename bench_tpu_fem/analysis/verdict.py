"""Fold the analyzer's JSON report into bench artifacts.

"Did static analysis predict this?" must be one grep across artifacts:
bench.py stamps ``static_analysis`` (per-rule pass/fail + analyzer
version) into its JSON line, and the drivers stamp the same block into
the ``error_record``-shaped extras whenever a hardware run falls back to
unfused — consistent with PR 3's ``failure_class`` convention.

The report is produced separately (``python -m bench_tpu_fem.analysis
--json ANALYSIS.json`` — CI's analysis lane, or the measurement agenda's
pre-flight) and read here, NEVER regenerated inside a bench process: the
analyzer forces an 8-virtual-device CPU platform, which a TPU bench
process must not touch. ``BENCH_ANALYSIS_REPORT`` overrides the default
./ANALYSIS.json location.
"""

from __future__ import annotations

import json
import os

_DEFAULT_REPORT = "ANALYSIS.json"


def load_report(path: str | None = None) -> dict | None:
    """The analyzer report, or None when none has been produced (the
    verdict then records unavailability rather than guessing)."""
    path = path or os.environ.get("BENCH_ANALYSIS_REPORT", _DEFAULT_REPORT)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def static_analysis_verdict(report: dict | None = None,
                            path: str | None = None) -> dict:
    """The compact per-rule verdict block bench artifacts carry:
    {"available", "analyzer_version", "rules": {R1: pass|fail, ...},
    "violations"} — one record per rule, pass only when every config's
    record under that rule passed."""
    if report is None:
        report = load_report(path)
    if report is None:
        return {"available": False}
    by_rule = report.get("summary", {}).get("by_rule", {})
    return {
        "available": True,
        "analyzer_version": report.get("analyzer_version"),
        "violations": report.get("summary", {}).get("violations"),
        "rules": {rule: ("fail" if counts.get("fail") else "pass")
                  for rule, counts in sorted(by_rule.items())},
        # identifies WHICH tree the report analyzed (git rev + dirty +
        # timestamp) — an artifact stamped from a stale report is
        # detectable instead of quietly authoritative
        **({"source": report["source"]} if "source" in report else {}),
    }


def stamp_static_analysis(extra: dict) -> None:
    """Attach the verdict to a result/error extras dict (drivers call
    this on every unfused fallback; never raises — a missing report must
    not sink a benchmark)."""
    try:
        extra["static_analysis"] = static_analysis_verdict()
    except Exception:  # defensive: artifact stamping is best-effort
        extra["static_analysis"] = {"available": False}
