"""CLI: drive the full shipped-config matrix through the rule engine and
emit a machine-readable JSON report (one record per kernel instance per
rule).

    python -m bench_tpu_fem.analysis                 # full matrix
    python -m bench_tpu_fem.analysis --configs kron  # name filter
    python -m bench_tpu_fem.analysis --corpus        # + known-bad corpus
    python -m bench_tpu_fem.analysis --json ANALYSIS.json
    python -m bench_tpu_fem.analysis --list

Exit code 0 = zero violations on shipped kernels AND (with --corpus)
100% of the known-bad fixtures flagged; 1 otherwise. Runs on CPU in
seconds: every drive is trace-only (jax.eval_shape / make_jaxpr), no
kernel executes. bench.py picks the report up via BENCH_ANALYSIS_REPORT
(default ./ANALYSIS.json) and stamps the per-rule verdict into its JSON
artifact (analysis.verdict).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    # Must precede any jax backend init: the dist configs need 8 virtual
    # CPU devices, and the axon tunnel hook must be unhooked (hermetic).
    from bench_tpu_fem.utils.hermetic import force_host_cpu_devices

    force_host_cpu_devices(8)
    import jax

    # x64 on, deliberately: R3 (f64-leak) must see any f64 the host code
    # would feed a kernel at full precision, not a silently downcast f32.
    jax.config.update("jax_enable_x64", True)

    ap = argparse.ArgumentParser(prog="python -m bench_tpu_fem.analysis")
    ap.add_argument("--configs", default="", metavar="SUBSTR",
                    help="only drive configs whose name contains SUBSTR")
    ap.add_argument("--rules", default="", metavar="R1,R2,...",
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--corpus", action="store_true",
                    help="also run the known-bad corpus and fail unless "
                         "every fixture is flagged")
    ap.add_argument("--json", default="", metavar="PATH",
                    help="write the JSON report here (default: stdout "
                         "summary only)")
    ap.add_argument("--list", action="store_true",
                    help="list config names and exit")
    args = ap.parse_args(argv)

    from bench_tpu_fem.analysis import ANALYZER_VERSION
    from bench_tpu_fem.analysis.configs import SHIPPED_CONFIGS
    from bench_tpu_fem.analysis.rules import RULE_IDS, run_rules, summarize

    if args.list:
        for c in SHIPPED_CONFIGS:
            print(c.name)
        return 0

    rules = tuple(r for r in args.rules.split(",") if r) or RULE_IDS
    unknown_rules = [r for r in rules if r not in RULE_IDS]
    if unknown_rules:
        # A typo'd rule name must not silently disable the lane and
        # report green — fail loudly instead.
        ap.error(f"unknown rules {unknown_rules}; valid: {list(RULE_IDS)}")
    t0 = time.monotonic()
    all_records = []
    config_reports = []
    ndev = len(jax.devices())
    for spec in SHIPPED_CONFIGS:
        if args.configs and args.configs not in spec.name:
            continue
        if spec.min_devices > ndev:
            config_reports.append({"name": spec.name, "skipped":
                                   f"needs {spec.min_devices} devices"})
            continue
        tc = time.monotonic()
        try:
            result = spec.drive()
            records = run_rules(result, rules)
        except Exception as exc:  # a broken drive is itself a violation:
            # the matrix exists to prove these kernels still trace
            from bench_tpu_fem.analysis.rules import Record

            records = [Record(spec.name, "drive", None, "fail",
                              {"error": f"{type(exc).__name__}: {exc}"[:500]})]
            result = None
        all_records.extend(records)
        config_reports.append({
            "name": spec.name,
            "tags": result.tags if result is not None else {},
            "kernels": ([c.name for c in result.captures]
                        if result is not None else []),
            "plan_unsupported": (result.plan_unsupported
                                 if result is not None else None),
            "seconds": round(time.monotonic() - tc, 2),
            "records": [_rec_json(r) for r in records],
        })
        bad = sum(1 for r in records if r.status == "fail")
        print(f"# {spec.name}: {len(records)} records, {bad} violations",
              flush=True)

    corpus_report = None
    if args.corpus:
        from bench_tpu_fem.analysis.fixtures import run_corpus

        corpus_records, missed = run_corpus()
        corpus_report = {
            "fixtures_flagged": not missed,
            "missed": missed,
            "records": [_rec_json(r) for r in corpus_records],
        }
        print(f"# corpus: {'all flagged' if not missed else missed}",
              flush=True)

    summary = summarize(all_records)
    summary["wall_s"] = round(time.monotonic() - t0, 2)
    report = {
        "analyzer_version": ANALYZER_VERSION,
        # What tree this verdict is ABOUT: a stale committed report must
        # be detectable when bench artifacts stamp it (verdict.py
        # forwards this block), or "static analysis did not predict
        # this" becomes unanswerable.
        "source": _source_identity(),
        "rules": list(rules),
        "summary": summary,
        "configs": config_reports,
        **({"corpus": corpus_report} if corpus_report is not None else {}),
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
        print(f"# report -> {args.json}")
    print(json.dumps({"analyzer_version": ANALYZER_VERSION, **summary}))
    ok = summary["violations"] == 0 and (
        corpus_report is None or corpus_report["fixtures_flagged"])
    return 0 if ok else 1


def _source_identity() -> dict:
    import os
    import subprocess

    ident = {"generated_at":
             time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        rev = subprocess.run(["git", "rev-parse", "HEAD"], cwd=repo,
                             capture_output=True, text=True, timeout=10)
        dirty = subprocess.run(["git", "status", "--porcelain"], cwd=repo,
                               capture_output=True, text=True, timeout=10)
        if rev.returncode == 0:
            ident["git_rev"] = rev.stdout.strip()
            ident["git_dirty"] = bool(dirty.stdout.strip())
    except Exception:
        pass  # not a git checkout (pip install): timestamp still stamps
    return ident


def _rec_json(r) -> dict:
    return {"config": r.config, "rule": r.rule, "kernel": r.kernel,
            "status": r.status, "detail": r.detail}


if __name__ == "__main__":
    sys.exit(main())
