"""Library form of the Mosaic spec recorder (grown out of
tests/test_mosaic_specs.py): intercept every ``pl.pallas_call`` issued
while a session is active and capture, per kernel instance, the block
specs, grid, scratch shapes, out shapes, operand avals and everything
needed to re-derive the kernel's closed jaxpr — the raw material the
rule engine (rules.R1-R4) runs over.

Interception is by swapping the ``pallas_call`` attribute on the
``jax.experimental.pallas`` module: every kernel module holds that module
by reference (``from jax.experimental import pallas as pl``), so one
patch reaches every shipped call site — which is also why the lint ban
(pyproject TID251) keeps raw ``pl.pallas_call`` out of code outside
``ops/``/``dist/``: a kernel issued elsewhere would dodge this recorder.

Capture happens at TRACE time (the wrapper runs when the surrounding
jit/shard_map traces), so driving a config through ``jax.eval_shape`` or
``jax.make_jaxpr`` records every spec without executing a single kernel
— the whole shipped matrix analyzes on CPU in seconds where the old
interpret-mode drive took minutes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import numpy as np
from jax.experimental import pallas as pl

# The unpatched pallas_call, for re-issuing a captured kernel during
# jaxpr extraction (a session may or may not be active by then).
_ORIG_PALLAS_CALL = pl.pallas_call


@dataclass
class SpecRecord:
    """One operand/output of one pallas_call: its BlockSpec block shape
    against the bound array's shape and dtype."""

    io: str  # "in" | "out"
    idx: int
    block_shape: tuple | None  # None = no BlockSpec (whole array)
    arr_shape: tuple
    dtype: str


@dataclass
class KernelCapture:
    """Everything recorded for one pallas_call instance."""

    name: str
    call_index: int
    grid: tuple
    specs: list[SpecRecord]
    operand_avals: list[tuple[tuple, str]]  # (shape, dtype name)
    out_avals: list[tuple[tuple, str]]
    scratch: list[tuple[tuple, str]]  # (shape, dtype name) per VMEM scratch
    kernel_fn: Callable | None = None
    kw: dict | None = None
    _jaxpr: Any = field(default=None, repr=False)
    jaxpr_error: str | None = None  # re-derivation failure, surfaced by R4

    def kernel_jaxpr(self):
        """The kernel body's jaxpr, extracted by re-tracing the captured
        pallas_call against the captured operand avals (abstract only —
        nothing executes) and pulling the ``jaxpr`` param off the
        pallas_call equation. Cached; None when the capture was built
        by hand (fixture records) or re-tracing fails."""
        if self._jaxpr is not None or self.kernel_fn is None:
            return self._jaxpr
        try:
            args = [jax.ShapeDtypeStruct(s, np.dtype(d))
                    for s, d in self.operand_avals]
            closed = jax.make_jaxpr(
                _ORIG_PALLAS_CALL(self.kernel_fn, **self.kw))(*args)
            for eqn in closed.jaxpr.eqns:
                if eqn.primitive.name == "pallas_call":
                    self._jaxpr = eqn.params["jaxpr"]
                    break
        except Exception as exc:
            self.jaxpr_error = f"{type(exc).__name__}: {exc}"[:300]
            return None
        return self._jaxpr


@dataclass
class CollectiveUse:
    """One collective equation found in a sharded apply's jaxpr (rule
    R5's input): which primitive, which axis names it binds, against
    which mesh axes and which axes the halo layout declares."""

    prim: str
    axes: tuple[str, ...]
    mesh_axes: tuple[str, ...]
    declared_axes: tuple[str, ...]


def _aval(x) -> tuple[tuple, str]:
    shape = tuple(getattr(x, "shape", np.shape(x)))
    dtype = getattr(x, "dtype", None)
    if dtype is None:
        dtype = np.asarray(x).dtype
    return shape, np.dtype(dtype).name


def _spec_block(spec) -> tuple | None:
    if spec is None:
        return None
    bs = getattr(spec, "block_shape", None)
    return None if bs is None else tuple(bs)


def _as_list(x) -> list:
    return list(x) if isinstance(x, (list, tuple)) else [x]


class CaptureSession:
    """Context manager that records every pallas_call issued while
    active. Nesting is not needed anywhere and not supported."""

    def __init__(self):
        self.kernels: list[KernelCapture] = []
        self._orig = None

    # -- patching -----------------------------------------------------------
    def __enter__(self):
        self._orig = pl.pallas_call
        orig = self._orig

        def recording_pallas_call(kernel, *args, **kw):
            # Normalize the one positional-or-keyword parameter
            # (out_shape) into kw, so the capture sees it and the
            # jaxpr re-derivation can re-issue the call verbatim — a
            # positionally-written call site must not silently
            # under-capture.
            if args:
                kw = dict(kw)
                kw.setdefault("out_shape", args[0])
                if len(args) > 1:
                    raise TypeError(
                        "pallas_call with >1 positional argument is not "
                        "capturable; pass keyword arguments")
            fn = orig(kernel, **kw)

            def traced(*operands):
                self.kernels.append(self._capture(kernel, kw, operands))
                return fn(*operands)

            return traced

        pl.pallas_call = recording_pallas_call
        return self

    def __exit__(self, *exc):
        pl.pallas_call = self._orig
        return False

    # -- record building ----------------------------------------------------
    def _capture(self, kernel, kw, operands) -> KernelCapture:
        # Kernel bodies are factory closures, so the bare __name__ is
        # always "kernel"; the qualname's enclosing factory is the
        # readable identity (e.g. "_make_cg_apply_kernel.kernel").
        name = getattr(kernel, "__qualname__",
                       getattr(kernel, "__name__", str(kernel)))
        name = name.replace(".<locals>", "")
        specs: list[SpecRecord] = []
        in_specs = kw.get("in_specs")
        if in_specs is not None:
            for i, (s, a) in enumerate(zip(_as_list(in_specs), operands)):
                shape, dt = _aval(a)
                specs.append(SpecRecord("in", i, _spec_block(s), shape, dt))
        out_shape = _as_list(kw.get("out_shape"))
        out_specs = kw.get("out_specs")
        if out_specs is not None:
            for i, (s, a) in enumerate(zip(_as_list(out_specs), out_shape)):
                shape, dt = _aval(a)
                specs.append(SpecRecord("out", i, _spec_block(s), shape, dt))
        scratch = []
        for s in _as_list(kw.get("scratch_shapes") or []):
            shape = tuple(getattr(s, "shape", ()))
            dt = np.dtype(getattr(s, "dtype", np.float32)).name
            scratch.append((shape, dt))
        grid = kw.get("grid", ())
        grid = tuple(grid) if isinstance(grid, (list, tuple)) else (grid,)
        return KernelCapture(
            name=name,
            call_index=len(self.kernels),
            grid=grid,
            specs=specs,
            operand_avals=[_aval(a) for a in operands],
            out_avals=[_aval(a) for a in out_shape if a is not None],
            scratch=scratch,
            kernel_fn=kernel,
            kw=dict(kw),
        )


# ---------------------------------------------------------------------------
# Collective capture (rule R5)
# ---------------------------------------------------------------------------

# Primitives whose params bind mesh axis names.
_COLLECTIVE_PRIMS = {
    "ppermute", "psum", "psum2", "all_gather", "all_to_all", "pmax",
    "pmin", "axis_index", "reduce_scatter",
}


def _axis_names(params: dict) -> tuple[str, ...]:
    names: list[str] = []
    for key in ("axis_name", "axes", "axis_names"):
        v = params.get(key)
        if v is None:
            continue
        for a in v if isinstance(v, (tuple, list)) else (v,):
            if isinstance(a, str):
                names.append(a)
    return tuple(names)


def _walk_jaxpr(jaxpr, found: list):
    import jax.core as jc

    for eqn in jaxpr.eqns:
        if eqn.primitive.name in _COLLECTIVE_PRIMS:
            found.append((eqn.primitive.name, _axis_names(eqn.params)))
        for v in eqn.params.values():
            if isinstance(v, jc.ClosedJaxpr):
                _walk_jaxpr(v.jaxpr, found)
            elif isinstance(v, jc.Jaxpr):
                _walk_jaxpr(v, found)
            elif isinstance(v, (tuple, list)):
                for w in v:
                    if isinstance(w, (jc.ClosedJaxpr, jc.Jaxpr)):
                        _walk_jaxpr(getattr(w, "jaxpr", w), found)


def trace_collectives(fn, *args, mesh_axes: tuple[str, ...],
                      declared_axes: tuple[str, ...]) -> list[CollectiveUse]:
    """Trace ``fn(*args)`` (abstract, nothing executes) and collect every
    collective equation with the axis names it binds, tagged with the
    mesh's axes and the halo layout's declared axes for rules.R5."""
    closed = jax.make_jaxpr(fn)(*args)
    found: list[tuple[str, tuple[str, ...]]] = []
    _walk_jaxpr(closed.jaxpr, found)
    return [CollectiveUse(prim, axes, tuple(mesh_axes), tuple(declared_axes))
            for prim, axes in found]


# Loop primitives a fori_loop/while_loop/scan lowers to: the CG
# iteration body lives inside one of these.
_LOOP_PRIMS = {"while", "scan"}

# The reduction collectives (the "psum count" of the overlap contract)
# vs the permutation/gather collectives, counted separately.
_REDUCTION_PRIMS = {"psum", "psum2", "pmax", "pmin", "reduce_scatter"}


def loop_collective_counts(fn, *args) -> dict[str, int]:
    """Per-iteration collective counts of ``fn``'s loop body: trace
    (abstract — nothing executes), find every while/scan body, and count
    the collective equations inside. This is the CPU-provable invariant
    behind the overlap engine forms — e.g. an overlapped CG must show
    exactly ONE `psum` per iteration where the synchronous form shows
    two, and the weak-scaling journal records these counts next to every
    A/B measurement. Returns a {prim_name: count} dict plus two
    aggregates: ``reductions`` (psum-class) and ``movements``
    (ppermute/all_gather-class)."""
    import jax.core as jc

    closed = jax.make_jaxpr(fn)(*args)
    counts: dict[str, int] = {}

    def walk(j, in_loop: bool):
        for eqn in j.eqns:
            name = eqn.primitive.name
            if in_loop and name in _COLLECTIVE_PRIMS and name != "axis_index":
                counts[name] = counts.get(name, 0) + 1
            sub_in_loop = in_loop or name in _LOOP_PRIMS
            for v in eqn.params.values():
                vs = v if isinstance(v, (tuple, list)) else (v,)
                for w in vs:
                    if isinstance(w, (jc.ClosedJaxpr, jc.Jaxpr)):
                        walk(getattr(w, "jaxpr", w), sub_in_loop)

    walk(closed.jaxpr, False)
    counts["reductions"] = sum(c for p, c in counts.items()
                               if p in _REDUCTION_PRIMS)
    counts["movements"] = sum(c for p, c in counts.items()
                              if p in ("ppermute", "all_gather",
                                       "all_to_all"))
    return counts
