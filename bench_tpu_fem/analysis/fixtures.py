"""Known-bad regression corpus: one synthetic violation per rule R1-R5
plus the EXACT round-4 Mosaic rejection, reproduced from the kernels the
round-4 fused kron CG engine shipped — the (1, 2nb)-over-(NX, 2nb)
coefficient stream every CPU parity test passed and Mosaic rejected on
the chip ("the last two dimensions of your block shape are divisible by
8 and 128 respectively, or be equal to the respective dimensions of the
overall array").

The analyzer must flag 100% of this corpus while passing every shipped
kernel; the corpus runs in CI (``python -m bench_tpu_fem.analysis
--corpus``) and in tests/test_analysis.py, so a rule that silently stops
firing fails the lane the same way a kernel regression does.

Fixtures that a CPU trace can express (R1, R2, R4) really issue
pallas_calls under a CaptureSession; the two a trace CANNOT express
(R3's f64 operand without global x64 side effects, R5's unbound axis
name — shard_map refuses to trace one) are hand-built capture records,
which is legitimate: the rule engine's contract is the capture schema,
not the tracer.
"""

from __future__ import annotations

import numpy as np

from .capture import CaptureSession, CollectiveUse, KernelCapture, SpecRecord
from .rules import (
    ConfigResult,
    PlanCheck,
    Record,
    run_rules,
)


def _trace_fixture_kernel(name, kernel, in_specs, out_specs, out_shape,
                          grid, operands) -> ConfigResult:
    import jax
    from jax.experimental import pallas as pl

    with CaptureSession() as s:
        fn = pl.pallas_call(kernel, grid=grid, in_specs=in_specs,
                            out_specs=out_specs, out_shape=out_shape,
                            interpret=True)
        jax.eval_shape(fn, *operands)
    return ConfigResult(name, {"fixture": True}, s.kernels)


def fixture_r1_round4() -> tuple[str, ConfigResult]:
    """The round-4 bug, verbatim: the fused kron engine streamed its
    banded coefficient tables as (1, 2nb)-over-(NX, 2nb) and
    (nb, CY)-over-(nb, NYB*CY) blocks — block rows of 1 (neither 8-divisible
    nor the full NX) and block lanes of CY=64 (neither 128-divisible nor
    the full NYB*CY)."""
    import jax
    from jax.experimental import pallas as pl

    nb, NX, NYB, CY = 7, 34, 3, 64

    def kernel(c_ref, y_ref, o_ref):
        import jax.numpy as jnp

        o_ref[...] = c_ref[...] + jnp.sum(y_ref[...])

    in_specs = [
        pl.BlockSpec((1, 2 * nb), lambda i: (i, 0)),
        pl.BlockSpec((nb, CY), lambda i: (0, i)),
    ]
    out_specs = pl.BlockSpec((1, 2 * nb), lambda i: (i, 0))
    out_shape = jax.ShapeDtypeStruct((NX, 2 * nb), np.float32)
    operands = (jax.ShapeDtypeStruct((NX, 2 * nb), np.dtype("float32")),
                jax.ShapeDtypeStruct((nb, NYB * CY), np.dtype("float32")))
    return "R1", _trace_fixture_kernel(
        "fixture_r1_round4_coeff_stream", kernel, in_specs, out_specs,
        out_shape, (NX,), operands)


def fixture_r1_bf16() -> tuple[str, ConfigResult]:
    """Dtype-awareness: an (8, 128) block is legal for f32 but NOT for
    bf16, whose sublane quantum is 16 — the rule must flag it."""
    import jax
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    spec = pl.BlockSpec((8, 128), lambda i: (i, 0))
    out_shape = jax.ShapeDtypeStruct((64, 128), np.dtype("bfloat16"))
    operands = (jax.ShapeDtypeStruct((64, 128), np.dtype("bfloat16")),)
    return "R1", _trace_fixture_kernel(
        "fixture_r1_bf16_sublane", kernel, [spec], spec, out_shape,
        (8,), operands)


def fixture_r2_overbudget() -> tuple[str, ConfigResult]:
    """A kernel whose spec-accounted footprint (two double-buffered
    24 MiB blocks) exceeds the default 16 MiB scoped limit AND whose
    claimed plan estimate (1 MiB) undershoots it — both R2 checks must
    fire."""
    import jax
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    spec = pl.BlockSpec((2048, 3072), lambda i: (i, 0))
    out_shape = jax.ShapeDtypeStruct((4096, 3072), np.float32)
    operands = (jax.ShapeDtypeStruct((4096, 3072), np.dtype("float32")),)
    res = _trace_fixture_kernel(
        "fixture_r2_overbudget", kernel, [spec], spec, out_shape,
        (2,), operands)
    res.plan = PlanCheck("fixture.bogus_estimator", 1 * 2**20)
    return "R2", res


def fixture_r3_f64() -> tuple[str, ConfigResult]:
    """An f64 operand reaching a pallas_call (hand-built capture: real
    f64 arrays need global x64 state the analyzer must not toggle)."""
    cap = KernelCapture(
        name="fixture_r3_f64_operand", call_index=0, grid=(4,),
        specs=[SpecRecord("in", 0, (1, 8, 128), (4, 8, 128), "float64")],
        operand_avals=[((4, 8, 128), "float64")],
        out_avals=[((4, 8, 128), "float32")], scratch=[])
    return "R3", ConfigResult("fixture_r3_f64", {"fixture": True}, [cap])


def fixture_r4_unlowerable() -> tuple[str, ConfigResult]:
    """A kernel body containing a primitive Mosaic can never lower (an
    FFT) — the jaxpr walk must flag it."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = jnp.real(
            jnp.fft.fft(x_ref[...].astype(jnp.complex64))
        ).astype(jnp.float32)

    spec = pl.BlockSpec((8, 128), lambda i: (i, 0))
    out_shape = jax.ShapeDtypeStruct((8, 128), np.float32)
    operands = (jax.ShapeDtypeStruct((8, 128), np.dtype("float32")),)
    return "R4", _trace_fixture_kernel(
        "fixture_r4_fft", kernel, [spec], spec, out_shape, (1,), operands)


def fixture_r5_bogus_axis() -> tuple[str, ConfigResult]:
    """A collective bound to an axis name that exists in neither the
    device mesh nor the halo layout's declared axes (hand-built:
    shard_map refuses to even trace an unbound axis name, which is
    exactly why drift arrives via renames — a kernel binding 'x' after
    the mesh was renamed to 'dx' traces fine against ITS mesh and
    deadlocks against ours)."""
    use = CollectiveUse(prim="ppermute", axes=("x",),
                        mesh_axes=("dx", "dy", "dz"),
                        declared_axes=("dx", "dy", "dz"))
    return "R5", ConfigResult("fixture_r5_bogus_axis", {"fixture": True},
                              [], collectives=[use])


def fixture_r5_misaxed_overlap() -> tuple[str, ConfigResult]:
    """The overlap-loop failure mode (ISSUE 7): an overlapped CG whose
    carried-halo y exchange and fused single psum came out of a refactor
    binding a STALE axis name — the ppermute exchange correctly binds
    'dx' but the stacked reduction psums over ('dx', 'dy', 'z') (a
    rename survivor). Hand-built like fixture_r5_bogus_axis (shard_map
    refuses to trace an unbound name — which is exactly how this class
    of drift ships: the kernel traces fine against the mesh it was
    developed on and deadlocks/misreduces against ours). R5 must flag
    the psum while passing the exchange."""
    uses = [
        CollectiveUse(prim="ppermute", axes=("dx",),
                      mesh_axes=("dx", "dy", "dz"),
                      declared_axes=("dx", "dy", "dz")),
        CollectiveUse(prim="psum", axes=("dx", "dy", "z"),
                      mesh_axes=("dx", "dy", "dz"),
                      declared_axes=("dx", "dy", "dz")),
    ]
    return "R5", ConfigResult("fixture_r5_misaxed_overlap",
                              {"fixture": True, "dist": "halo_overlap"},
                              [], collectives=uses)


CORPUS = (
    fixture_r1_round4,
    fixture_r1_bf16,
    fixture_r2_overbudget,
    fixture_r3_f64,
    fixture_r4_unlowerable,
    fixture_r5_bogus_axis,
    fixture_r5_misaxed_overlap,
)


def run_corpus() -> tuple[list[Record], list[str]]:
    """Run the rule engine over every known-bad fixture. Returns (all
    records, names of fixtures the engine FAILED to flag on the targeted
    rule — must be empty)."""
    records: list[Record] = []
    missed: list[str] = []
    for fx in CORPUS:
        rule, result = fx()
        recs = run_rules(result)
        records.extend(recs)
        if not any(r.rule == rule and r.status == "fail" for r in recs):
            missed.append(f"{result.name} (expected {rule} violation)")
    return records, missed
