"""Static-analysis subsystem: CPU-provable hardware-compile safety.

The framework's recurring fatal failure class is "interpret mode accepted
it, Mosaic rejected it on the chip": round 4 shipped kernels with
BlockSpec tiling violations that every CPU parity test passed, and round 5
produced zero hardware numbers because the TPU tunnel was wedged all
session — for long stretches, static analysis on CPU is the only line of
defense between a green tier-1 suite and a silent unfused fallback on
hardware.

This package intercepts every ``pl.pallas_call`` issued by every shipped
kernel configuration (``capture``), and runs a pluggable rule engine
(``rules``) over the captures:

  R1  Mosaic tiling divisibility per dtype (8x128 f32 / 16x128 bf16 /
      32x128 int8, or equal-to-array) on every BlockSpec.
  R2  Per-kernel VMEM accounting: sum operand/out blocks + scratch from
      the captured specs and cross-check against the plan estimators.
  R3  f64-leak detection: no float64 operand, out_shape or jaxpr
      intermediate may reach a pallas_call.
  R4  Jaxpr walk flagging primitives with no Mosaic lowering.
  R5  shard_map consistency: collective axis names must exist in the
      mesh and match the halo layout's declared axes.

``configs`` drives the full shipped-config matrix (every engine form x
geometry mode x df/f32 x single-chip/sharded) at TRACE time only — no
kernel executes, so the whole matrix runs on CPU in seconds.
``fixtures`` is the known-bad regression corpus (including the exact
round-4 tiling bug); the analyzer must flag every fixture and pass every
shipped kernel. ``python -m bench_tpu_fem.analysis`` emits a
machine-readable JSON report with one record per kernel instance per
rule; ``verdict`` folds that report into bench artifacts.
"""

ANALYZER_VERSION = "1.0"

_LAZY = {
    "capture": ".capture",
    "budgets": ".budgets",
    "rules": ".rules",
    "configs": ".configs",
    "fixtures": ".fixtures",
    "verdict": ".verdict",
}


def __getattr__(name):
    # Submodules that import ops/dist are loaded lazily so that
    # `from bench_tpu_fem.analysis.budgets import ...` inside ops modules
    # cannot create an import cycle.
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name], __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(name)
