"""The consolidated VMEM budget constants — one documented derivation each.

Every Pallas plan function in this repo gates a kernel form on an
estimated VMEM footprint against a budget. Until round 6 those budgets
were five independent module-local constants; this module is now the one
place they live, each derived as (scoped-limit / derate factor) from two
hardware facts:

  * Mosaic compiles a kernel against a per-compile SCOPED VMEM limit —
    16 MiB by default on v5e, raisable per compile via
    ``xla_tpu_scoped_vmem_limit_kib`` (utils.compilation); and
  * Mosaic's allocator lands ABOVE our live-value models by a measured
    kernel-family-dependent ratio — the worst observed anywhere in this
    repo is 1.7x (the plane-streamed corner kernels,
    ops.pallas_laplacian), the f32 kron ring's measured ratio is ~1.45x
    (the degree-3 12.8 MiB estimate is rejected at the 16 MiB limit
    while the degree-6 12.35 MiB one compiles).

A too-tight budget costs a (recorded) raised-limit request or chunked
form; a too-loose one costs a recorded Mosaic-reject retry — the drivers
survive both, but the analysis rule engine (rules.R2) cross-checks every
estimate against the footprint actually captured from the specs, so a
drifted model fails CI instead of failing on the chip.

The plan functions import these under their historical module-attribute
names (e.g. ``ops.kron_cg.VMEM_BUDGET``), so existing monkeypatch-based
probes (harness.agenda) keep working.
"""

from __future__ import annotations

# Hardware facts (v5e, MEASURE_r04.log probes).
MOSAIC_DEFAULT_SCOPED_BYTES = 16 * 2**20  # default per-compile scoped limit
MOSAIC_SCOPED_TIER1_BYTES = 64 * 2**20  # first raised tier (65536 KiB)
MOSAIC_SCOPED_TIER2_BYTES = 96 * 2**20  # second raised tier (98304 KiB)
# Worst measured model -> Mosaic-allocator ratio in this repo (the
# plane-streamed corner kernels); used wherever a kernel family's own
# ratio has not been measured on hardware.
MOSAIC_ALLOC_DERATE_WORST = 1.7
# The f32 kron delay-ring family's measured ratio is tighter (~1.45x);
# its ceilings below are direct hardware observations, not derivations.

# --- f32 kron delay-ring engine (ops.kron_cg) ------------------------------
# One-kernel form at the DEFAULT scoped limit: 16 MiB / ~1.45 measured
# ratio => the hardware-validated safe line (12.8 MiB estimate rejected,
# 12.35 MiB compiled => 11 MiB).
KRON_VMEM_BUDGET = 11 * 2**20
# One-kernel form under the raised tiers (hardware-checked admission
# boundaries, MEASURE_r04.log): 64 MiB tier carries estimates to 31 MiB,
# 96 MiB tier to 62 MiB; above that the chunked two-kernel form takes
# over.
KRON_ONE_KERNEL_SCOPED_MAX = 31 * 2**20  # ~64 MiB tier / 2.06 measured
KRON_ONE_KERNEL_SCOPED_KIB = 65536
KRON_ONE_KERNEL_SCOPED_MAX2 = 62 * 2**20  # ~96 MiB tier / 1.55 measured
KRON_ONE_KERNEL_SCOPED_KIB2 = 98304

# --- df32 kron delay-ring engine (ops.kron_cg_df) --------------------------
# The df kernel allocates differently (paired accumulator/ring channels,
# 4-channel coefficient stacks, deeper live df temporaries), so its
# Mosaic stack-to-estimate ratio has NOT been measured; each ceiling is
# its tier's scoped limit / the worst measured ratio (1.7), never f32's
# measured ones (round-5 verdict, weak #3).
DF_VMEM_BUDGET = 9 * 2**20  # 16 MiB default scoped limit / 1.7
DF_ONE_KERNEL_SCOPED_MAX = 30 * 2**20  # 64 MiB tier: min(64/1.7, f32's 31)
DF_ONE_KERNEL_SCOPED_MAX2 = 56 * 2**20  # 96 MiB tier / 1.7

# --- folded window kernels (ops.pallas_laplacian) --------------------------
# G-streaming form at the default scoped limit: 16 MiB minus pipeline
# headroom for the double-buffered G stream (the dominant HBM traffic)
# => 12 MiB against the live-value model in pick_lanes.
PALLAS_STREAM_BUDGET_BYTES = 12 * 1024 * 1024
# Corner form at the default scoped limit: the in-kernel geometry chain
# carries more model risk than the streaming one, but measured closer to
# its estimate => 14 MiB.
PALLAS_CORNER_BUDGET_BYTES = 14 * 1024 * 1024
# Plane-streamed corner form (degrees 5-6 qmode 1) compiles under a
# raised 32 MiB scoped limit (the kernels measure 19-23 MB against the
# 16 MB default — the 1.7x family); admission keeps 2 MiB pipeline
# headroom inside the raised limit, derated by the worst ratio:
# (32 - 2) MiB / 1.7.
PALLAS_STREAMED_SCOPED_KIB = 32768
PALLAS_STREAMED_BUDGET_BYTES = int(30 * 1024 * 1024 / 1.7)

# --- folded df window kernel (ops.folded_df) -------------------------------
# Runs under the 64 MiB tier with a 4 MiB pipeline reserve, derated by
# the worst measured ratio: (64 - 4) MiB / 1.7.
FOLDED_DF_BUDGET_BYTES = int(60 * 1024 * 1024 / 1.7)
FOLDED_DF_SCOPED_KIB = 65536

# --- distributed plan ceilings ---------------------------------------------
# The dist plans deliberately reuse the single-chip ceilings: the halo
# forms stream the same block shapes per shard (dist_kron_engine_plan and
# dist_df_engine_plan follow the kron tiers above on the local grid;
# dist_folded_engine_plan forwards the folded scoped request). Keeping
# them equal IS the policy — a dist-only ceiling would let the sharded
# form ship a kernel its single-chip twin cannot compile. rules.R2
# cross-checks both against the same captures.


def scoped_limit_bytes(kib: int | None) -> int:
    """The scoped-VMEM limit (bytes) a kernel compiles under, given the
    plan's per-compile request (None = Mosaic default)."""
    return MOSAIC_DEFAULT_SCOPED_BYTES if kib is None else kib * 1024


# Tracked waivers for rules.R2's estimate-vs-measured cross-check:
# (config name, estimator name) -> reason. A waiver documents a KNOWN
# gap > the 10% tolerance between a plan estimate and the
# spec-accounted footprint, with why it is acceptable; anything not
# listed here fails the analysis lane.
R2_WAIVERS: dict[tuple[str, str], str] = {}
