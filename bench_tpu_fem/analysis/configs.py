"""The shipped-config matrix: every Pallas kernel configuration the
drivers can route to, as trace-only drive functions the rule engine runs
over — every engine form x geometry mode x df/f32 x single-chip/sharded,
exactly the paths bench/driver.py and dist/driver.py dispatch between.

Every drive runs under a CaptureSession and traces through
``jax.eval_shape`` / ``jax.make_jaxpr`` — nothing executes, so the whole
matrix (including the degree-1/3/6 plan cross-check sweep the acceptance
criteria require) analyzes on CPU in seconds.

Each config also states its plan claim (PlanCheck): which estimator
covers it, the estimate for the driven grid, and the scoped-VMEM limit
the plan requests — rules.R2 cross-checks those against the captured
footprints, converting the plan functions from trusted folklore into
continuously-verified claims. Configs a plan routes OFF Pallas (e.g.
G-streaming at degree 6, where pallas_plan forces corner mode) record
``plan_unsupported``: the routing itself is the verified defense.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import numpy as np

from .budgets import scoped_limit_bytes
from .capture import CaptureSession, trace_collectives
from .rules import ConfigResult, PlanCheck

DEFAULT_NDOFS = 40_000  # matches tests/test_mosaic_specs.py's sizes


def _f32(shape):
    import jax

    return jax.ShapeDtypeStruct(shape, np.dtype("float32"))


def _mesh_op(ndofs, degree, perturb, geom):
    import jax.numpy as jnp

    import bench_tpu_fem.ops.folded as FO
    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size

    nc = compute_mesh_size(ndofs, degree)
    mesh = create_box_mesh(nc, geom_perturb_fact=perturb)
    return FO.build_folded_laplacian(
        mesh, degree, qmode=1, dtype=jnp.float32, geom=geom
    )


# ---------------------------------------------------------------------------
# Plan claims
# ---------------------------------------------------------------------------

def _kron_plan(grid_shape, degree, force_chunked=False) -> PlanCheck:
    from ..ops.kron_cg import engine_plan, engine_vmem_bytes

    form, kib = engine_plan(grid_shape, degree)
    if force_chunked or form != "one":
        return PlanCheck(
            "ops.kron_cg.engine_vmem_bytes", None, scoped_limit_bytes(None),
            notes="chunked two-kernel form: every VMEM object O(CY*NZ), "
                  "outside the one-kernel ring model")
    return PlanCheck("ops.kron_cg.engine_vmem_bytes",
                     engine_vmem_bytes(grid_shape, degree),
                     scoped_limit_bytes(kib))


def _kron_df_plan(grid_shape, degree, force_chunked=False) -> PlanCheck:
    from ..ops.kron_cg_df import engine_plan_df, engine_vmem_bytes_df

    form, kib = engine_plan_df(grid_shape, degree)
    if force_chunked or form != "one":
        return PlanCheck(
            "ops.kron_cg_df.engine_vmem_bytes_df", None,
            scoped_limit_bytes(None),
            notes="chunked df form: every VMEM object O(CY*NZ)")
    return PlanCheck("ops.kron_cg_df.engine_vmem_bytes_df",
                     engine_vmem_bytes_df(grid_shape, degree),
                     scoped_limit_bytes(kib))


def _folded_window_plan(degree: int, nq: int, geom: str) -> PlanCheck:
    """The folded window-kernel models (ops.pallas_laplacian), per the
    geometry form the builder actually uses for (degree, nq, geom)."""
    from ..ops.pallas_laplacian import (
        SUBLANES,
        corner_cell_bytes,
        corner_lanes_ok,
        pick_lanes,
        stream_cell_bytes,
        streamed_cell_bytes,
    )

    nd = degree + 1
    if geom == "g":
        nl = pick_lanes(nd, nq, 4)
        return PlanCheck(
            "ops.pallas_laplacian.stream_cell_bytes",
            stream_cell_bytes(nd, nq, 4) * SUBLANES * nl,
            scoped_limit_bytes(None), notes=f"nl={nl}")
    if corner_lanes_ok(nd, nq, 4):
        return PlanCheck(
            "ops.pallas_laplacian.corner_cell_bytes",
            corner_cell_bytes(nd, nq, 4) * SUBLANES * 128,
            scoped_limit_bytes(None))
    from ..ops.pallas_laplacian import STREAMED_SCOPED_KIB

    return PlanCheck(
        "ops.pallas_laplacian.streamed_cell_bytes",
        streamed_cell_bytes(nd, nq, 4) * SUBLANES * 128,
        scoped_limit_bytes(STREAMED_SCOPED_KIB))


def _folded_df_plan_check(degree: int, nq: int, geom: str) -> PlanCheck:
    from ..ops.folded_df import FOLDED_DF_SCOPED_KIB, _df_cell_bytes
    from ..ops.pallas_laplacian import SUBLANES

    return PlanCheck(
        "ops.folded_df._df_cell_bytes",
        _df_cell_bytes(degree + 1, nq, geom) * SUBLANES * 128,
        scoped_limit_bytes(FOLDED_DF_SCOPED_KIB))


# ---------------------------------------------------------------------------
# Single-chip drives
# ---------------------------------------------------------------------------

def drive_kron_engine(degree: int, chunked: bool) -> ConfigResult:
    import jax
    import jax.numpy as jnp

    import bench_tpu_fem.ops.kron_cg as KC
    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size
    from bench_tpu_fem.ops.kron import build_kron_laplacian

    nc = compute_mesh_size(DEFAULT_NDOFS, degree)
    mesh = create_box_mesh(nc)
    op = build_kron_laplacian(mesh, degree, qmode=1, dtype=jnp.float32)
    shape = tuple(int(a.shape[0]) for a in op.notbc1d)
    r, p = _f32(shape), _f32(shape)
    with CaptureSession() as s:
        jax.eval_shape(
            lambda r, p: KC._kron_cg_call(op, True, True, r, p,
                                          jnp.float32(0.5),
                                          force_chunked=chunked), r, p)
        jax.eval_shape(
            lambda r: KC._kron_cg_call(op, False, True, r,
                                       force_chunked=chunked), r)
    name = f"kron_engine_d{degree}" + ("_chunked" if chunked else "")
    return ConfigResult(
        name, {"engine": "kron", "degree": degree,
               "form": "chunked" if chunked else "auto", "dtype": "f32"},
        s.kernels, plan=_kron_plan(shape, degree, chunked))


def drive_kron_update_pass() -> ConfigResult:
    import jax
    import jax.numpy as jnp

    import bench_tpu_fem.ops.kron_cg as KC

    a = _f32((17, 29, 23))
    with CaptureSession() as s:
        jax.eval_shape(
            lambda x, p, r, y: KC.cg_update_pallas(
                x, p, r, y, jnp.float32(0.3), interpret=True),
            a, a, a, a)
    return ConfigResult("kron_update_pass",
                        {"engine": "kron", "pass": "update", "dtype": "f32"},
                        s.kernels)


def drive_kron_3stage(degree: int = 3) -> ConfigResult:
    import jax
    import jax.numpy as jnp

    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size
    from bench_tpu_fem.ops.kron import build_kron_laplacian
    from bench_tpu_fem.ops.kron_pallas import kron_apply_pallas

    nc = compute_mesh_size(DEFAULT_NDOFS, degree)
    mesh = create_box_mesh(nc)
    op = build_kron_laplacian(mesh, degree, qmode=1, dtype=jnp.float32)
    shape = tuple(int(a.shape[0]) for a in op.notbc1d)
    with CaptureSession() as s:
        jax.eval_shape(
            lambda x: kron_apply_pallas(x, op.Kd, op.Md, op.notbc1d,
                                        op.kappa, degree, interpret=True),
            _f32(shape))
    return ConfigResult(f"kron_3stage_d{degree}",
                        {"engine": "kron", "pass": "3stage", "dtype": "f32"},
                        s.kernels)


def drive_folded_engine(geom: str, degree: int) -> ConfigResult:
    import jax
    import jax.numpy as jnp

    import bench_tpu_fem.ops.folded_cg as FCG
    from bench_tpu_fem.elements.tables import build_operator_tables

    name = f"folded_engine_{geom}_d{degree}"
    t = build_operator_tables(degree, 1, "gll")
    plan, unshipped = _folded_plan_or_unsupported(name, geom, degree, t.nq)
    op = _mesh_op(DEFAULT_NDOFS, degree, 0.1, geom)
    lay = op.layout
    shp = (lay.nblocks, degree ** 3, lay.block)
    r, p = _f32(shp), _f32(shp)
    with CaptureSession() as s:
        jax.eval_shape(
            lambda r, p: FCG._cg_apply_call(
                lay, op.geom, op.kappa,
                np.asarray(op.phi0_c, np.float64),
                np.asarray(op.dphi1_c, np.float64),
                op.is_identity, op.geom_tables, True, True, r, p,
                jnp.float32(0.5)), r, p)
    return ConfigResult(
        name, {"engine": "folded", "geom": geom, "degree": degree,
               "dtype": "f32"},
        s.kernels, plan=plan, plan_unsupported=unshipped)


def _folded_plan_or_unsupported(name, geom, degree, nq):
    """(plan, unshipped_reason) for a folded (geom, degree) variant.
    plan=None with a reason means pallas_plan routes this geometry mode
    off Pallas on TPU (e.g. G-streaming above degree 4: forced corner)
    — the kernel is STILL driven and spec-linted (an explicit --geom g
    request reaches it in CPU interpret mode, and the lint coverage
    predates this package), but no VMEM plan claims it."""
    from ..ops.folded import pallas_plan

    supported, forced, _kib = pallas_plan(degree, nq, 4)
    if not supported:
        return None, (f"pallas_plan: degree {degree} unsupported "
                      "on TPU (driver routes to xla)")
    if geom == "g" and forced is not None:
        return None, (f"pallas_plan forces geom={forced!r} at degree "
                      f"{degree} (G-streaming VMEM model over budget); "
                      "g-mode never ships here")
    return _folded_window_plan(degree, nq, geom), None


def drive_folded_fused_apply(geom: str, degree: int) -> ConfigResult:
    import jax

    from bench_tpu_fem.elements.tables import build_operator_tables

    name = f"folded_apply_{geom}_d{degree}"
    t = build_operator_tables(degree, 1, "gll")
    plan, unshipped = _folded_plan_or_unsupported(name, geom, degree, t.nq)
    op = _mesh_op(DEFAULT_NDOFS, degree, 0.1, geom)
    lay = op.layout
    x = _f32((lay.nblocks, degree ** 3, lay.block))
    with CaptureSession() as s:
        jax.eval_shape(op.apply_cg, x)
    return ConfigResult(
        name, {"engine": "folded", "pass": "fused_apply", "geom": geom,
               "degree": degree, "dtype": "f32"},
        s.kernels, plan=plan, plan_unsupported=unshipped)


def drive_kron_df_engine(degree: int, chunked: bool) -> ConfigResult:
    import jax
    import jax.numpy as jnp

    from bench_tpu_fem.elements.tables import build_operator_tables
    from bench_tpu_fem.la.df64 import DF
    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size
    from bench_tpu_fem.ops.kron_cg_df import (
        _beta4,
        _engine_coeffs,
        _grid_shape,
        _kron_cg_df_call,
        _kron_cg_df_call_chunked,
    )
    from bench_tpu_fem.ops.kron_df import (
        build_kron_laplacian_df,
        device_rhs_uniform_df,
    )

    nc = compute_mesh_size(DEFAULT_NDOFS, degree)
    t = build_operator_tables(degree, 1, "gll")
    mesh = create_box_mesh(nc)
    op = build_kron_laplacian_df(mesh, degree, 1, "gll", tables=t)
    b = device_rhs_uniform_df(t, mesh.n)
    coeffs = _engine_coeffs(op)
    call = _kron_cg_df_call_chunked if chunked else _kron_cg_df_call
    beta = _beta4(DF(jnp.float32(0.5), jnp.float32(0.0)))
    with CaptureSession() as s:
        jax.eval_shape(lambda b: call(op, coeffs, True, True, b, b, beta), b)
        jax.eval_shape(lambda b: call(op, coeffs, False, True, b), b)
    name = f"kron_df_engine_d{degree}" + ("_chunked" if chunked else "")
    return ConfigResult(
        name, {"engine": "kron_df", "degree": degree,
               "form": "chunked" if chunked else "auto", "dtype": "df32"},
        s.kernels, plan=_kron_df_plan(_grid_shape(op), degree, chunked))


def drive_kron_df_update_pass() -> ConfigResult:
    import jax
    import jax.numpy as jnp

    from bench_tpu_fem.la.df64 import DF
    from bench_tpu_fem.ops.kron_cg_df import cg_update_df_pallas

    a = DF(_f32((7, 70, 13)), _f32((7, 70, 13)))
    alpha = DF(jnp.float32(0.3), jnp.float32(0.0))
    with CaptureSession() as s:
        jax.eval_shape(
            lambda x, p, r, y: cg_update_df_pallas(x, p, r, y, alpha,
                                                   interpret=True),
            a, a, a, a)
    return ConfigResult("kron_df_update_pass",
                        {"engine": "kron_df", "pass": "update",
                         "dtype": "df32"},
                        s.kernels)


def drive_folded_df_apply(geom: str, degree: int) -> ConfigResult:
    import jax

    from bench_tpu_fem.elements.tables import build_operator_tables
    from bench_tpu_fem.la.df64 import DF
    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.dofmap import dof_grid_shape
    from bench_tpu_fem.mesh.sizing import compute_mesh_size
    from bench_tpu_fem.ops.folded_df import (
        build_folded_laplacian_df,
        folded_df_plan,
    )

    name = f"folded_df_apply_{geom}_d{degree}"
    t = build_operator_tables(degree, 1, "gll")
    supported, forced, _ = folded_df_plan(degree, t.nq)
    if not supported:
        return ConfigResult(
            name, {"geom": geom, "degree": degree, "dtype": "df32"},
            plan_unsupported=f"folded_df_plan: degree {degree} exceeds the "
                             "df VMEM model in both geometry modes "
                             "(driver records the XLA-emulation fallback)")
    if geom == "g" and forced is not None:
        return ConfigResult(
            name, {"geom": geom, "degree": degree, "dtype": "df32"},
            plan_unsupported=f"folded_df_plan forces geom={forced!r} at "
                             f"degree {degree}; df g-mode never ships here")
    nc = compute_mesh_size(DEFAULT_NDOFS, degree)
    mesh = create_box_mesh(nc, geom_perturb_fact=0.1)
    op = build_folded_laplacian_df(mesh, degree, 1, geom=geom)
    lay = op.layout
    from bench_tpu_fem.ops.folded import fold_vector

    x = np.zeros(dof_grid_shape(nc, degree), np.float32)
    folded_shape = np.shape(fold_vector(x, lay))
    xf = DF(jax.ShapeDtypeStruct(folded_shape, np.dtype("float32")),
            jax.ShapeDtypeStruct(folded_shape, np.dtype("float32")))
    with CaptureSession() as s:
        jax.eval_shape(op.apply, xf)
    return ConfigResult(
        name, {"engine": "folded_df", "geom": geom, "degree": degree,
               "dtype": "df32"},
        s.kernels, plan=_folded_df_plan_check(degree, t.nq, geom))


def drive_serve_batched_apply(geom: str, degree: int,
                              nrhs: int = 4) -> ConfigResult:
    """The serving layer's batched apply: the SAME folded fused-apply
    kernel as folded_apply_*, traced THROUGH `jax.vmap` — the
    bench/serve batched path (cg_solve_batched's vmapped operator).
    vmap batches the pallas grid, never the block shapes, so the
    captured specs must lint identically to the unbatched drive; this
    config keeps that claim continuously verified instead of assumed."""
    import jax

    from bench_tpu_fem.elements.tables import build_operator_tables

    name = f"serve_batched_apply_{geom}_d{degree}"
    t = build_operator_tables(degree, 1, "gll")
    plan, unshipped = _folded_plan_or_unsupported(name, geom, degree, t.nq)
    op = _mesh_op(DEFAULT_NDOFS, degree, 0.1, geom)
    lay = op.layout
    B = _f32((nrhs, lay.nblocks, degree ** 3, lay.block))
    with CaptureSession() as s:
        jax.eval_shape(jax.vmap(op.apply_cg), B)
    return ConfigResult(
        name, {"engine": "folded", "pass": "batched_apply", "geom": geom,
               "degree": degree, "dtype": "f32", "nrhs": nrhs},
        s.kernels, plan=plan, plan_unsupported=unshipped)


def drive_kron_batched_engine(degree: int, nrhs: int) -> ConfigResult:
    """The nrhs-native fused batched delay ring
    (ops.kron_cg._kron_cg_call_batched) — the ISSUE-6 serving kernel
    form. Per-lane ring scratch means the VMEM footprint scales with
    the bucket, so the plan claim uses the per-bucket estimator
    (engine_vmem_bytes_batched) at the scoped limit engine_plan_batched
    requests for this (grid, degree, nrhs). Buckets the plan routes OFF
    the fused form (over the top tier) record plan_unsupported — the
    recorded-unfused fallback is the verified defense — while their
    specs still lint under R1/R3/R4."""
    import jax
    import jax.numpy as jnp

    import bench_tpu_fem.ops.kron_cg as KC
    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size
    from bench_tpu_fem.ops.kron import build_kron_laplacian

    nc = compute_mesh_size(DEFAULT_NDOFS, degree)
    mesh = create_box_mesh(nc)
    op = build_kron_laplacian(mesh, degree, qmode=1, dtype=jnp.float32)
    shape = tuple(int(a.shape[0]) for a in op.notbc1d)
    form, kib = KC.engine_plan_batched(shape, degree, nrhs)
    R = _f32((nrhs, *shape))
    beta = _f32((nrhs,))
    with CaptureSession() as s:
        jax.eval_shape(
            lambda R, Pv, b: KC._kron_cg_call_batched(op, True, R, Pv, b),
            R, R, beta)
    name = f"kron_batched_engine_d{degree}_r{nrhs}"
    if form == "unfused":
        plan, unshipped = None, (
            f"engine_plan_batched: nrhs={nrhs} stacked rings exceed the "
            "top scoped-VMEM tier at this grid; the driver/serve path "
            "records the unfused vmapped fallback")
    else:
        plan, unshipped = PlanCheck(
            "ops.kron_cg.engine_vmem_bytes_batched",
            KC.engine_vmem_bytes_batched(shape, degree, nrhs),
            scoped_limit_bytes(kib)), None
    return ConfigResult(
        name, {"engine": "kron", "pass": "batched_engine",
               "degree": degree, "dtype": "f32", "nrhs": nrhs},
        s.kernels, plan=plan, plan_unsupported=unshipped)


def drive_serve_batched_kron_3stage(degree: int = 3,
                                    nrhs: int = 4) -> ConfigResult:
    """Batched (vmapped) kron 3-stage pallas apply — the uniform-mesh
    serving twin of drive_serve_batched_apply."""
    import jax
    import jax.numpy as jnp

    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size
    from bench_tpu_fem.ops.kron import build_kron_laplacian
    from bench_tpu_fem.ops.kron_pallas import kron_apply_pallas

    nc = compute_mesh_size(DEFAULT_NDOFS, degree)
    mesh = create_box_mesh(nc)
    op = build_kron_laplacian(mesh, degree, qmode=1, dtype=jnp.float32)
    shape = tuple(int(a.shape[0]) for a in op.notbc1d)
    with CaptureSession() as s:
        jax.eval_shape(
            jax.vmap(lambda x: kron_apply_pallas(
                x, op.Kd, op.Md, op.notbc1d, op.kappa, degree,
                interpret=True)),
            _f32((nrhs, *shape)))
    return ConfigResult(
        f"serve_batched_kron_3stage_d{degree}",
        {"engine": "kron", "pass": "batched_apply", "dtype": "f32",
         "nrhs": nrhs},
        s.kernels)


# ---------------------------------------------------------------------------
# Distributed drives (collectives captured from the same trace)
# ---------------------------------------------------------------------------

def drive_dist_kron_engine(degree: int) -> ConfigResult:
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from bench_tpu_fem.dist.kron import build_dist_kron
    from bench_tpu_fem.dist.kron_cg import (
        _dist_kron_cg_call,
        _extend_rp,
        _shard_tables,
        dist_kron_engine_plan,
    )
    from bench_tpu_fem.dist.mesh import AXIS_NAMES, make_device_grid

    dgrid = make_device_grid(dshape=(4, 1, 1))
    op = build_dist_kron((8, 2, 2), dgrid, degree, 1, dtype=jnp.float32)
    Lx, NY, NZ = op.L[0], op.notbc1d[1].shape[0], op.notbc1d[2].shape[0]

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(P(AXIS_NAMES[0]), P(AXIS_NAMES[0]), P()),
             out_specs=P(AXIS_NAMES[0]), check_vma=False)
    def run(r, p, A):
        cx, aux = _shard_tables(A, jnp.float32)
        r_ext, p_ext = _extend_rp(r, p, A.degree)
        _, y, _ = _dist_kron_cg_call(A, cx, aux, True, True,
                                     r_ext, p_ext, jnp.float32(0.5))
        return y

    r = _f32((4 * Lx, NY, NZ))
    with CaptureSession() as s:
        coll = trace_collectives(run, r, r, op,
                                 mesh_axes=dgrid.mesh.axis_names,
                                 declared_axes=AXIS_NAMES)
    supported, kib = dist_kron_engine_plan(op)
    from ..ops.kron_cg import engine_vmem_bytes

    plan = PlanCheck("dist.kron_cg.dist_kron_engine_plan",
                     engine_vmem_bytes((Lx, NY, NZ), degree)
                     if supported else None,
                     scoped_limit_bytes(kib))
    return ConfigResult(
        f"dist_kron_engine_d{degree}",
        {"engine": "kron", "dist": "halo", "degree": degree, "dtype": "f32"},
        s.kernels, collectives=coll, plan=plan)


def drive_dist_kron_engine_3d() -> ConfigResult:
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from bench_tpu_fem.dist.kron import build_dist_kron
    from bench_tpu_fem.dist.kron_cg import (
        dist_kron_apply_ring_local,
        dist_kron_engine_plan,
    )
    from bench_tpu_fem.dist.mesh import AXIS_NAMES, make_device_grid

    dgrid = make_device_grid(dshape=(2, 2, 2))
    op = build_dist_kron((4, 4, 4), dgrid, 3, 1, dtype=jnp.float32)

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(P(*AXIS_NAMES), P()), out_specs=P(*AXIS_NAMES),
             check_vma=False)
    def run(x, A):
        return dist_kron_apply_ring_local(A, x[0, 0, 0],
                                          interpret=True)[None, None, None]

    x = _f32((2, 2, 2, op.L[0], op.L[1], op.L[2]))
    with CaptureSession() as s:
        coll = trace_collectives(run, x, op,
                                 mesh_axes=dgrid.mesh.axis_names,
                                 declared_axes=AXIS_NAMES)
    supported, kib = dist_kron_engine_plan(op)
    from ..ops.kron_cg import engine_vmem_bytes

    P_ = op.degree
    plan = PlanCheck(
        "dist.kron_cg.dist_kron_engine_plan",
        engine_vmem_bytes((op.L[0], op.L[1] + 2 * P_, op.L[2] + 2 * P_),
                          op.degree) if supported else None,
        scoped_limit_bytes(kib))
    return ConfigResult(
        "dist_kron_engine_ext2d",
        {"engine": "kron", "dist": "ext2d", "degree": 3, "dtype": "f32"},
        s.kernels, collectives=coll, plan=plan)


def drive_dist_kron_df(dshape: tuple) -> ConfigResult:
    from functools import partial

    import jax
    from jax.sharding import PartitionSpec as P

    from bench_tpu_fem.dist.kron_cg_df import (
        dist_df_engine_plan,
        dist_kron_df_apply_ring_local,
    )
    from bench_tpu_fem.dist.kron_df import build_dist_kron_df
    from bench_tpu_fem.dist.mesh import AXIS_NAMES, make_device_grid
    from bench_tpu_fem.elements.tables import build_operator_tables
    from bench_tpu_fem.la.df64 import DF

    ext2d = dshape != (4, 1, 1)
    dgrid = make_device_grid(dshape=dshape)
    t = build_operator_tables(3, 1, "gll")
    n = (4, 4, 4) if ext2d else (8, 2, 2)
    op = build_dist_kron_df(n, dgrid, 3, 1, tables=t)

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(P(*AXIS_NAMES), P(*AXIS_NAMES), P()),
             out_specs=P(*AXIS_NAMES), check_vma=False)
    def run(xh, xl, A):
        y = dist_kron_df_apply_ring_local(A, DF(xh[0, 0, 0], xl[0, 0, 0]))
        return y.hi[None, None, None]

    Lx, LY, LZ = op.L
    x = _f32((*dshape, Lx, LY, LZ))
    with CaptureSession() as s:
        coll = trace_collectives(run, x, x, op,
                                 mesh_axes=dgrid.mesh.axis_names,
                                 declared_axes=AXIS_NAMES)
    supported, kib = dist_df_engine_plan(op)
    from ..ops.kron_cg_df import engine_vmem_bytes_df

    P_ = op.degree
    cross = ((op.notbc1d[1].shape[0], op.notbc1d[2].shape[0])
             if not ext2d else (LY + 2 * P_, LZ + 2 * P_))
    plan = PlanCheck("dist.kron_cg_df.dist_df_engine_plan",
                     engine_vmem_bytes_df((Lx, *cross), 3)
                     if supported else None,
                     scoped_limit_bytes(kib))
    name = "dist_kron_df_ext2d" if ext2d else "dist_kron_df_halo"
    return ConfigResult(
        name, {"engine": "kron_df", "dist": "ext2d" if ext2d else "halo",
               "degree": 3, "dtype": "df32"},
        s.kernels, collectives=coll, plan=plan)


def drive_dist_folded_engine() -> ConfigResult:
    import jax
    import jax.numpy as jnp

    from bench_tpu_fem.dist.folded import (
        build_dist_folded,
        make_folded_sharded_fns,
    )
    from bench_tpu_fem.dist.folded_cg import dist_folded_engine_plan
    from bench_tpu_fem.dist.mesh import AXIS_NAMES, make_device_grid
    from bench_tpu_fem.elements.tables import build_operator_tables
    from bench_tpu_fem.mesh.box import create_box_mesh

    dgrid = make_device_grid(dshape=(2, 1, 1))
    mesh = create_box_mesh((4, 2, 2), geom_perturb_fact=0.1)
    t = build_operator_tables(3, 1)
    op = build_dist_folded(mesh, dgrid, 3, t, dtype=jnp.float32, nl=16)
    apply_fn, _, _, sharded_state = make_folded_sharded_fns(
        op, dgrid, 1, engine=True)
    lay = op.layout
    x = _f32((2, 1, 1, lay.nblocks, 27, lay.block))
    state = sharded_state(op)
    with CaptureSession() as s:
        coll = trace_collectives(apply_fn, x, state,
                                 mesh_axes=dgrid.mesh.axis_names,
                                 declared_axes=AXIS_NAMES)
    supported, kib = dist_folded_engine_plan(op)
    plan = PlanCheck("dist.folded_cg.dist_folded_engine_plan",
                     _folded_window_plan(3, t.nq, "g").estimate_bytes
                     if supported else None,
                     scoped_limit_bytes(kib),
                     notes="forwards pallas_plan's window-model bytes")
    return ConfigResult(
        "dist_folded_engine",
        {"engine": "folded", "dist": "halo", "degree": 3, "dtype": "f32"},
        s.kernels, collectives=coll, plan=plan)


def drive_dist_kron_overlap(degree: int, ext2d: bool) -> ConfigResult:
    """The communication-overlapped kron engine forms (halo_overlap /
    ext2d_overlap): the FULL overlapped CG loop traced through shard_map
    — same delay-ring kernel as the synchronous dist forms (R1-R4 must
    lint identically) plus the overlap loop's collectives (R5: the
    carried-halo exchange and the single stacked psum)."""
    from functools import partial

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from bench_tpu_fem.dist.kron import build_dist_kron
    from bench_tpu_fem.dist.kron_cg import (
        dist_kron_cg_solve_local_overlap,
        dist_kron_engine_plan,
    )
    from bench_tpu_fem.dist.mesh import AXIS_NAMES, make_device_grid

    dshape = (2, 2, 2) if ext2d else (4, 1, 1)
    n = (4, 4, 4) if ext2d else (8, 2, 2)
    dgrid = make_device_grid(dshape=dshape)
    op = build_dist_kron(n, dgrid, degree, 1, dtype=jnp.float32)

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(P(*AXIS_NAMES), P()), out_specs=P(*AXIS_NAMES),
             check_vma=False)
    def run(b, A):
        x = dist_kron_cg_solve_local_overlap(A, b[0, 0, 0], 2,
                                             interpret=True)
        return x[None, None, None]

    b = _f32((*dshape, op.L[0], op.L[1], op.L[2]))
    with CaptureSession() as s:
        coll = trace_collectives(run, b, op,
                                 mesh_axes=dgrid.mesh.axis_names,
                                 declared_axes=AXIS_NAMES)
    supported, kib = dist_kron_engine_plan(op)
    from ..ops.kron_cg import engine_vmem_bytes

    P_ = op.degree
    cross = ((op.notbc1d[1].shape[0], op.notbc1d[2].shape[0])
             if not ext2d else (op.L[1] + 2 * P_, op.L[2] + 2 * P_))
    plan = PlanCheck("dist.kron_cg.dist_kron_engine_plan",
                     engine_vmem_bytes((op.L[0], *cross), degree)
                     if supported else None,
                     scoped_limit_bytes(kib),
                     notes="overlap form: same ring as the synchronous "
                           "engine (update_p=False call)")
    name = ("dist_kron_overlap_ext2d" if ext2d
            else f"dist_kron_overlap_d{degree}")
    return ConfigResult(
        name, {"engine": "kron",
               "dist": "ext2d_overlap" if ext2d else "halo_overlap",
               "degree": degree, "dtype": "f32"},
        s.kernels, collectives=coll, plan=plan)


def drive_dist_kron_df_overlap(dshape: tuple) -> ConfigResult:
    """The overlapped df engine forms: full overlapped df CG loop traced
    through shard_map (same df kernel; R5 additionally sees the single
    stacked all-gather fold replacing the per-dot gather chains)."""
    from functools import partial

    import jax
    from jax.sharding import PartitionSpec as P

    from bench_tpu_fem.dist.kron_cg_df import (
        dist_df_engine_plan,
        dist_kron_df_cg_solve_local_overlap,
    )
    from bench_tpu_fem.dist.kron_df import DF, build_dist_kron_df
    from bench_tpu_fem.dist.mesh import AXIS_NAMES, make_device_grid
    from bench_tpu_fem.elements.tables import build_operator_tables

    ext2d = dshape != (4, 1, 1)
    dgrid = make_device_grid(dshape=dshape)
    t = build_operator_tables(3, 1, "gll")
    n = (4, 4, 4) if ext2d else (8, 2, 2)
    op = build_dist_kron_df(n, dgrid, 3, 1, tables=t)

    @partial(jax.shard_map, mesh=dgrid.mesh,
             in_specs=(P(*AXIS_NAMES), P(*AXIS_NAMES), P()),
             out_specs=P(*AXIS_NAMES), check_vma=False)
    def run(bh, bl, A):
        x = dist_kron_df_cg_solve_local_overlap(
            A, DF(bh[0, 0, 0], bl[0, 0, 0]), 2, interpret=True)
        return x.hi[None, None, None]

    Lx, LY, LZ = op.L
    b = _f32((*dshape, Lx, LY, LZ))
    with CaptureSession() as s:
        coll = trace_collectives(run, b, b, op,
                                 mesh_axes=dgrid.mesh.axis_names,
                                 declared_axes=AXIS_NAMES)
    supported, kib = dist_df_engine_plan(op)
    from ..ops.kron_cg_df import engine_vmem_bytes_df

    P_ = op.degree
    cross = ((op.notbc1d[1].shape[0], op.notbc1d[2].shape[0])
             if not ext2d else (LY + 2 * P_, LZ + 2 * P_))
    plan = PlanCheck("dist.kron_cg_df.dist_df_engine_plan",
                     engine_vmem_bytes_df((Lx, *cross), 3)
                     if supported else None,
                     scoped_limit_bytes(kib),
                     notes="overlap form: same df ring as the "
                           "synchronous engine (update_p=False call)")
    name = ("dist_kron_df_overlap_ext2d" if ext2d
            else "dist_kron_df_overlap_halo")
    return ConfigResult(
        name, {"engine": "kron_df",
               "dist": "ext2d_overlap" if ext2d else "halo_overlap",
               "degree": 3, "dtype": "df32"},
        s.kernels, collectives=coll, plan=plan)


def drive_dist_folded_overlap() -> ConfigResult:
    """The overlapped folded engine form (halo_overlap): the full
    overlapped folded CG loop — identical halo-form delay-ring kernel as
    dist_folded_engine, with the forward refresh moved onto y and the
    single stacked psum (R5 sees scatter + refresh + one psum)."""
    import jax
    import jax.numpy as jnp

    from bench_tpu_fem.dist.folded import (
        build_dist_folded,
        make_folded_sharded_fns,
    )
    from bench_tpu_fem.dist.folded_cg import dist_folded_engine_plan
    from bench_tpu_fem.dist.mesh import AXIS_NAMES, make_device_grid
    from bench_tpu_fem.elements.tables import build_operator_tables
    from bench_tpu_fem.mesh.box import create_box_mesh

    dgrid = make_device_grid(dshape=(2, 1, 1))
    mesh = create_box_mesh((4, 2, 2), geom_perturb_fact=0.1)
    t = build_operator_tables(3, 1)
    op = build_dist_folded(mesh, dgrid, 3, t, dtype=jnp.float32, nl=16)
    _, cg_fn, _, sharded_state = make_folded_sharded_fns(
        op, dgrid, 2, engine=True, overlap=True)
    lay = op.layout
    b = _f32((2, 1, 1, lay.nblocks, 27, lay.block))
    state = sharded_state(op)
    with CaptureSession() as s:
        coll = trace_collectives(cg_fn, b, state, op.owned,
                                 mesh_axes=dgrid.mesh.axis_names,
                                 declared_axes=AXIS_NAMES)
    supported, kib = dist_folded_engine_plan(op)
    plan = PlanCheck("dist.folded_cg.dist_folded_engine_plan",
                     _folded_window_plan(3, t.nq, "g").estimate_bytes
                     if supported else None,
                     scoped_limit_bytes(kib),
                     notes="overlap form: same halo-form ring as "
                           "dist_folded_engine")
    return ConfigResult(
        "dist_folded_overlap",
        {"engine": "folded", "dist": "halo_overlap", "degree": 3,
         "dtype": "f32"},
        s.kernels, collectives=coll, plan=plan)


# ---------------------------------------------------------------------------
# bf16 mixed-precision drives (ISSUE 17)
# ---------------------------------------------------------------------------

def _bf16_plan(grid_shape, degree) -> PlanCheck:
    from ..ops.bf16 import engine_vmem_bytes_bf16

    return PlanCheck(
        "ops.bf16.engine_vmem_bytes_bf16",
        engine_vmem_bytes_bf16(grid_shape, degree),
        scoped_limit_bytes(None),
        notes="bf16-stream design estimate: f32 ring at half width, "
              "re-quantised to the (16, 128) bf16 tile; unfused until "
              "the hardware bf16 stage lands a fused ring")


def drive_bf16_apply(degree: int) -> ConfigResult:
    import jax
    import jax.numpy as jnp

    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size
    from bench_tpu_fem.ops.bf16 import to_bf16
    from bench_tpu_fem.ops.kron import build_kron_laplacian

    nc = compute_mesh_size(DEFAULT_NDOFS, degree)
    mesh = create_box_mesh(nc)
    op = to_bf16(build_kron_laplacian(mesh, degree, qmode=1,
                                      dtype=jnp.float32))
    shape = tuple(int(a.shape[0]) for a in op.inner.notbc1d)
    with CaptureSession() as s:
        jax.eval_shape(op.apply, _f32(shape))
    return ConfigResult(
        f"bf16_apply_d{degree}",
        {"engine": "kron_bf16", "degree": degree, "dtype": "bf16"},
        s.kernels, plan=_bf16_plan(shape, degree))


def drive_bf16_apply_perturbed(degree: int) -> ConfigResult:
    import jax
    import jax.numpy as jnp

    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size
    from bench_tpu_fem.ops.bf16 import to_bf16
    from bench_tpu_fem.ops.laplacian import build_laplacian

    nc = compute_mesh_size(DEFAULT_NDOFS, degree)
    mesh = create_box_mesh(nc, geom_perturb_fact=0.1)
    op = to_bf16(build_laplacian(mesh, degree, 1, "gll",
                                 dtype=jnp.float32, backend="xla"))
    shape = tuple(int(v) for v in op.inner.bc_mask.shape)
    with CaptureSession() as s:
        jax.eval_shape(op.apply, _f32(shape))
    return ConfigResult(
        f"bf16_apply_perturbed_d{degree}",
        {"engine": "xla_bf16", "degree": degree, "dtype": "bf16"},
        s.kernels, plan=_bf16_plan(shape, degree))


def drive_bf16_refine(degree: int) -> ConfigResult:
    import jax
    import jax.numpy as jnp

    from bench_tpu_fem.engines.registry import DEFAULT_REFINE_INNER_ITERS
    from bench_tpu_fem.la.refine import _correct, _residual
    from bench_tpu_fem.mesh.box import create_box_mesh
    from bench_tpu_fem.mesh.sizing import compute_mesh_size
    from bench_tpu_fem.ops.bf16 import to_bf16
    from bench_tpu_fem.ops.kron import build_kron_laplacian

    nc = compute_mesh_size(DEFAULT_NDOFS, degree)
    mesh = create_box_mesh(nc)
    op_hi = build_kron_laplacian(mesh, degree, qmode=1, dtype=jnp.float32)
    op_lo = to_bf16(op_hi)
    shape = tuple(int(a.shape[0]) for a in op_hi.notbc1d)
    x = _f32(shape)
    with CaptureSession() as s:
        jax.eval_shape(lambda o, xx, bb: _residual(o, xx, bb),
                       op_hi, x, x)
        jax.eval_shape(
            lambda o, rr: _correct(o, rr, DEFAULT_REFINE_INNER_ITERS),
            op_lo, x)
    return ConfigResult(
        f"bf16_refine_d{degree}",
        {"engine": "bf16_refine", "degree": degree, "dtype": "bf16",
         "inner_iters": DEFAULT_REFINE_INNER_ITERS},
        s.kernels, plan=_bf16_plan(shape, degree))


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ConfigSpec:
    name: str
    drive: Callable[[], ConfigResult]
    min_devices: int = 1


#: drive-key -> trace-only drive function (the registry's AnalysisRef
#: rows name the key; this table is the only analysis-side coupling)
_DRIVES: dict[str, Callable[..., ConfigResult]] = {
    "kron_engine": drive_kron_engine,
    "kron_update_pass": drive_kron_update_pass,
    "kron_3stage": drive_kron_3stage,
    "folded_engine": drive_folded_engine,
    "folded_apply": drive_folded_fused_apply,
    "kron_df_engine": drive_kron_df_engine,
    "kron_df_update_pass": drive_kron_df_update_pass,
    "folded_df_apply": drive_folded_df_apply,
    "serve_batched_apply": drive_serve_batched_apply,
    "serve_batched_kron_3stage": drive_serve_batched_kron_3stage,
    "kron_batched_engine": drive_kron_batched_engine,
    "dist_kron_engine": drive_dist_kron_engine,
    "dist_kron_engine_3d": drive_dist_kron_engine_3d,
    "dist_kron_df": drive_dist_kron_df,
    "dist_folded_engine": drive_dist_folded_engine,
    "dist_kron_overlap": drive_dist_kron_overlap,
    "dist_kron_df_overlap": drive_dist_kron_df_overlap,
    "dist_folded_overlap": drive_dist_folded_overlap,
    "bf16_apply": drive_bf16_apply,
    "bf16_apply_perturbed": drive_bf16_apply_perturbed,
    "bf16_refine": drive_bf16_refine,
}


def _matrix() -> list[ConfigSpec]:
    """The shipped-config matrix, derived from the engine registry's
    declarative rows (engines.registry.analysis_plan — one source of
    truth with the driver routing and the serve capability table). The
    registry parity test pins the rendered names against the frozen
    pre-registry list."""
    from ..engines.registry import analysis_plan

    specs: list[ConfigSpec] = []
    for ref in analysis_plan():
        fn = _DRIVES[ref.drive]
        specs.append(ConfigSpec(
            ref.name,
            (lambda fn=fn, args=tuple(ref.args): fn(*args)),
            min_devices=ref.min_devices))
    return specs


SHIPPED_CONFIGS: list[ConfigSpec] = _matrix()
_BY_NAME = {c.name: c for c in SHIPPED_CONFIGS}


def config_names() -> list[str]:
    return [c.name for c in SHIPPED_CONFIGS]


def run_config(name: str) -> ConfigResult:
    """Drive one shipped config by name and return its captures + plan
    claim (raises KeyError for unknown names)."""
    return _BY_NAME[name].drive()
