"""Operator zoo (ISSUE 20): declarative weak-form registry + the unified
sum-factorised form action. See forms.registry for the rows and
forms.operators for the kernel."""

from .operators import (
    FormOperator,
    build_form_operator,
    kappa_at_quadrature,
)
from .registry import (
    FORM_NAMES,
    FORMS,
    HEAT_DT,
    HEAT_RTOL,
    HELMHOLTZ_KSQ,
    FormSpec,
    form_spec,
    kappa_field,
)

__all__ = [
    "FORM_NAMES",
    "FORMS",
    "FormOperator",
    "FormSpec",
    "HEAT_DT",
    "HEAT_RTOL",
    "HELMHOLTZ_KSQ",
    "build_form_operator",
    "form_spec",
    "kappa_at_quadrature",
    "kappa_field",
]
