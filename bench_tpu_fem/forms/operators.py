"""Sum-factorised form actions sharing the ops.laplacian tensor machinery.

One per-cell kernel serves every registry row: it interpolates to
quadrature points once, then runs up to two independent contraction
chains on the quadrature values,

    y_q = grad_coeff * D^T (G . D u_q)        (the laplacian chain)
        + mass_coeff * wdetJ (.) u_q          (the basis-squared chain)

and back-interpolates once. The gradient chain is byte-for-byte the
einsum sequence of ops.laplacian._sumfact_cell_apply (the Poisson path
itself is NOT routed here — `form="poisson"` stays on the original
operator, bitwise-pinned); the mass chain inserts a single diagonal
scale at the quadrature points, exactly the reference's mass form
(forms.hpp:23-42) expressed in the same tensors. Chains with a zero
coefficient are compiled out via static flags, so the mass form never
touches G and pure-stiffness forms never materialise wdetJ.

Variable-coefficient kappa(x) is sampled at the physical quadrature
points (trilinear corner map, host-side) and folded into the geometry
tensor G: G already carries w*adj(J)adj(J)^T/det(J) per quadrature
point, and kappa enters the integrand as a pointwise scale of exactly
that tensor. On uniform meshes G is diagonal (G01=G02=G12=0), so the
fold degenerates to a diagonal rescale of the kron-path factors — the
perturbed and uniform paths share one code line.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..elements.tables import OperatorTables, build_operator_tables
from ..mesh.box import BoxMesh
from ..mesh.dofmap import boundary_dof_marker
from ..ops.geometry import geometry_factors_jax
from ..ops.laplacian import fold_cells, gather_cells
from .registry import FormSpec, form_spec, kappa_field


def _form_cell_apply(
    u: jnp.ndarray,
    G: jnp.ndarray | None,
    wdetJ: jnp.ndarray | None,
    phi0: jnp.ndarray,
    dphi1: jnp.ndarray,
    grad_coeff,
    mass_coeff,
    is_identity: bool,
    with_grad: bool,
    with_mass: bool,
) -> jnp.ndarray:
    """Unified per-cell form kernel: (C, nd, nd, nd) -> (C, nd, nd, nd).

    precision=HIGHEST for the same reason as the laplacian kernel: TPU
    matmuls default to bf16 passes, fatal to the mat_comp oracle
    contract. The gradient chain mirrors _sumfact_cell_apply exactly;
    the mass chain rides the shared interpolation, adding one diagonal
    quadrature-point scale before the shared back-interpolation.
    """
    hi = jax.lax.Precision.HIGHEST
    if not is_identity:
        u = jnp.einsum("qi,eijk->eqjk", phi0, u, precision=hi)
        u = jnp.einsum("rj,eqjk->eqrk", phi0, u, precision=hi)
        u = jnp.einsum("sk,eqrk->eqrs", phi0, u, precision=hi)
    y = None
    if with_grad:
        du0 = jnp.einsum("xi,eijk->exjk", dphi1, u, precision=hi)
        du1 = jnp.einsum("yj,eijk->eiyk", dphi1, u, precision=hi)
        du2 = jnp.einsum("zk,eijk->eijz", dphi1, u, precision=hi)
        G0, G1, G2, G3, G4, G5 = (G[:, c] for c in range(6))
        f0 = grad_coeff * (G0 * du0 + G1 * du1 + G2 * du2)
        f1 = grad_coeff * (G1 * du0 + G3 * du1 + G4 * du2)
        f2 = grad_coeff * (G2 * du0 + G4 * du1 + G5 * du2)
        y = (
            jnp.einsum("qi,eqjk->eijk", dphi1, f0, precision=hi)
            + jnp.einsum("qj,eiqk->eijk", dphi1, f1, precision=hi)
            + jnp.einsum("qk,eijq->eijk", dphi1, f2, precision=hi)
        )
    if with_mass:
        m = mass_coeff * (wdetJ * u)
        y = m if y is None else y + m
    if not is_identity:
        y = jnp.einsum("qi,eqjk->eijk", phi0, y, precision=hi)
        y = jnp.einsum("qj,eiqk->eijk", phi0, y, precision=hi)
        y = jnp.einsum("qk,eijq->eijk", phi0, y, precision=hi)
    return y


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["G", "wdetJ", "phi0", "dphi1", "bc_mask",
                 "grad_coeff", "mass_coeff"],
    meta_fields=["n", "degree", "is_identity", "form",
                 "with_grad", "with_mass", "spd"],
)
@dataclass(frozen=True)
class FormOperator:
    """Matrix-free weak-form operator state (pytree, xla backend).

    Same grid-in/grid-out contract and Dirichlet handling as
    ops.laplacian.Laplacian: input zeroed on constrained dofs, output
    pass-through rows y[bc] = x[bc]. G is None for mass-only rows and
    wdetJ None for gradient-only rows (the chains are compiled out, so
    the dead operand never ships to device)."""

    G: jnp.ndarray | None  # (ncells, 6, nq, nq, nq), kappa(x) pre-folded
    wdetJ: jnp.ndarray | None  # (ncells, nq, nq, nq)
    phi0: jnp.ndarray  # (nq, nd) interpolation matrix
    dphi1: jnp.ndarray  # (nq, nq) collocation derivative
    bc_mask: jnp.ndarray  # (NX, NY, NZ) bool Dirichlet marker
    grad_coeff: jnp.ndarray  # scalar
    mass_coeff: jnp.ndarray  # scalar
    n: tuple[int, int, int]
    degree: int
    is_identity: bool
    form: str
    with_grad: bool
    with_mass: bool
    spd: bool

    def apply(self, x_grid: jnp.ndarray) -> jnp.ndarray:
        """y = A @ x on the dof grid, with Dirichlet pass-through rows."""
        xm = jnp.where(self.bc_mask, 0, x_grid)
        u = gather_cells(xm, self.n, self.degree)
        y = _form_cell_apply(
            u, self.G, self.wdetJ, self.phi0, self.dphi1,
            self.grad_coeff, self.mass_coeff,
            self.is_identity, self.with_grad, self.with_mass,
        )
        y_grid = fold_cells(y, self.n, self.degree)
        return jnp.where(self.bc_mask, x_grid, y_grid)


def kappa_at_quadrature(corners: np.ndarray, pts1d: np.ndarray) -> np.ndarray:
    """kappa sampled at the PHYSICAL quadrature points: (ncells, nq, nq, nq).

    The trilinear corner map x(xi) = sum_c N_c(xi) X_c is the same map
    whose Jacobian feeds geometry_factors — sampling through it keeps
    the coefficient consistent between uniform and perturbed meshes, and
    between operator and oracle (both call this function)."""
    corners = np.asarray(corners, np.float64).reshape(-1, 2, 2, 2, 3)
    pts = np.asarray(pts1d, np.float64)
    N = np.stack([1.0 - pts, pts], axis=1)  # (nq, 2) linear shapes
    xq = np.einsum("eabci,xa,yb,zc->exyzi", corners, N, N, N)
    return kappa_field(xq[..., 0], xq[..., 1], xq[..., 2])


def build_form_operator(
    mesh: BoxMesh,
    form: str | FormSpec,
    degree: int,
    qmode: int,
    rule: str = "gll",
    dtype=jnp.float64,
    tables: OperatorTables | None = None,
) -> FormOperator:
    """Assemble form-operator state from a registry row: tables host-side
    (f64), geometry tensors on device — the forms counterpart of
    ops.laplacian.build_laplacian, one build path for every row."""
    spec = form_spec(form) if isinstance(form, str) else form
    t = tables or build_operator_tables(degree, qmode, rule)
    corners_np = np.asarray(mesh.cell_corners, np.float64).reshape(
        -1, 2, 2, 2, 3)
    corners = jnp.asarray(corners_np, dtype=dtype)
    with_grad = spec.grad_coeff != 0.0
    with_mass = spec.mass_coeff != 0.0
    G_dev, wdetJ_dev = geometry_factors_jax(corners, t.pts1d, t.wts1d)
    G = wdetJ = None
    if with_grad:
        G = G_dev
        if spec.coefficient == "varkappa":
            kq = jnp.asarray(
                kappa_at_quadrature(corners_np, t.pts1d), dtype=dtype)
            # fold kappa(x_q) into the geometry tensor: a pointwise scale
            # of all 6 packed components (diagonal-only on uniform meshes)
            G = G * kq[:, None]
    if with_mass:
        wdetJ = wdetJ_dev
    bc = jnp.asarray(boundary_dof_marker(mesh.n, degree))
    return FormOperator(
        G=G,
        wdetJ=wdetJ,
        phi0=jnp.asarray(t.phi0, dtype=dtype),
        dphi1=jnp.asarray(t.dphi1, dtype=dtype),
        bc_mask=bc,
        grad_coeff=jnp.asarray(spec.grad_coeff, dtype=dtype),
        mass_coeff=jnp.asarray(spec.mass_coeff, dtype=dtype),
        n=mesh.n,
        degree=degree,
        is_identity=t.is_identity,
        form=spec.name,
        with_grad=with_grad,
        with_mass=with_mass,
        spd=spec.spd,
    )
