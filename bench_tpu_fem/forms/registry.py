"""Declarative weak-form table (ISSUE 20): the operator zoo.

Each row describes one bilinear form as quadrature-point coefficients of
the two contraction chains the sum-factorised kernel knows how to run,

    a(u, v) = grad_coeff * (kappa(x) grad u, grad v) + mass_coeff * (u, v)

mirroring the reference's UFL form layer (poisson64.py -> FFCx kernels,
forms.hpp:23-42) as data instead of generated code: a new PDE is a
registry row plus (at most) a few quadrature-point lines, not a new
operator class. The rows deliberately span the taxonomy the serving and
solver layers care about:

  * poisson    -- the seed benchmark (pure gradient chain, constant kappa)
  * mass       -- L2 projection: basis-squared contraction, NO gradient
                  chain (the degenerate row that proves the kernel's
                  chains really are independently switchable)
  * helmholtz  -- stiffness - k^2 * mass: the first non-SPD operator in
                  the repo; CG on it exercises the breakdown sentinel /
                  s_step fallback / failure_class taxonomy on a real
                  indefinite shift instead of an injected NaN
  * varkappa   -- variable-coefficient kappa(x), sampled at quadrature
                  points and folded into the geometry tensor G (on
                  uniform meshes G is diagonal, so the fold is exactly a
                  diagonal rescale of the kron-path factors)
  * heat       -- (u, v) + dt * (grad u, grad v): one implicit-Euler heat
                  step; SPD, served with an rtol budget so warm-started
                  lanes can retire early (workload/heat.py)

`spd=False` rows must never claim CG convergence: the driver and serve
layers stamp registered failure classes instead of crashing, and
preconditioners gate off (GATE_REASONS["helmholtz-precond"]).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# Helmholtz shift k^2. The unit-cube Dirichlet Laplacian's smallest
# generalized eigenvalues are pi^2*(i^2+j^2+k^2) = {3,6,9,...}*pi^2
# (29.6, 59.2, 88.8, ...), so k^2 = 100 puts several modes below the
# shift: the discrete operator is genuinely indefinite at any mesh that
# resolves those modes, not merely ill-conditioned.
HELMHOLTZ_KSQ = 100.0

# Implicit-Euler step of the heat workload: small enough that
# (M + dt*K) stays mass-dominated and well-conditioned (warm starts
# converge in a handful of iterations), large enough that the stiffness
# chain contributes beyond rounding.
HEAT_DT = 1e-3

# Serve-side relative residual budget for heat steps: lanes freeze once
# rnorm/rnorm0 < rtol^2 (la.cg.make_batched_cg_step), which is what
# makes warm-start iteration savings observable at retire time.
HEAT_RTOL = 1e-5

# varkappa coefficient contrast: kappa(x) in [1-A, 1+A].
VARKAPPA_AMPLITUDE = 0.5


@dataclass(frozen=True)
class FormSpec:
    """One weak form as data.

    grad_coeff   multiplies the gradient chain (kappa grad u, grad v);
                 0.0 compiles the chain out entirely.
    mass_coeff   multiplies the basis-squared chain (u, v); 0.0 compiles
                 it out. Negative values (helmholtz) make the form
                 indefinite.
    spd          CG-safe flag: False routes the breakdown taxonomy and
                 gates preconditioners off.
    coefficient  "constant" or "varkappa" (kappa sampled at quadrature
                 points via kappa_field and folded into G).
    rtol         serve-side relative tolerance baked into the compiled
                 CG step (0.0 = fixed iteration budget, the seed
                 behaviour). Nonzero only where early retirement is the
                 point (heat).
    """

    name: str
    grad_coeff: float
    mass_coeff: float
    spd: bool
    coefficient: str = "constant"
    rtol: float = 0.0
    description: str = ""


FORMS: dict[str, FormSpec] = {
    f.name: f
    for f in (
        FormSpec(
            "poisson", 2.0, 0.0, True,
            description="reference stiffness -div(kappa grad u), kappa=2 "
                        "(the seed benchmark; routed through the original "
                        "ops.laplacian path untouched)"),
        FormSpec(
            "mass", 0.0, 1.0, True,
            description="L2 projection (u, v): basis-squared contraction, "
                        "no gradient chain"),
        FormSpec(
            "helmholtz", 1.0, -HELMHOLTZ_KSQ, False,
            description=f"indefinite shift (grad u, grad v) - k^2 (u, v), "
                        f"k^2={HELMHOLTZ_KSQ:g}"),
        FormSpec(
            "varkappa", 1.0, 0.0, True, coefficient="varkappa",
            description="variable-coefficient (kappa(x) grad u, grad v), "
                        "kappa smooth positive in "
                        f"[{1 - VARKAPPA_AMPLITUDE:g}, "
                        f"{1 + VARKAPPA_AMPLITUDE:g}]"),
        FormSpec(
            "heat", HEAT_DT, 1.0, True, rtol=HEAT_RTOL,
            description=f"implicit-Euler heat step (u, v) + dt (grad u, "
                        f"grad v), dt={HEAT_DT:g} (workload/heat.py)"),
    )
}

FORM_NAMES = tuple(FORMS)


def form_spec(name: str) -> FormSpec:
    """Look up a registry row; unknown names fail loud with the vocabulary."""
    try:
        return FORMS[name]
    except KeyError:
        raise ValueError(
            f"unknown form '{name}' (registered: {', '.join(FORM_NAMES)})"
        ) from None


def kappa_field(x, y, z):
    """Deterministic smooth positive kappa(x) for the varkappa row.

    Shared VERBATIM by the device operator build and the assembled-CSR
    oracle — the parity contract compares two discretisations of the
    same coefficient, so the coefficient itself must be one function.
    """
    return 1.0 + VARKAPPA_AMPLITUDE * (
        np.sin(np.pi * x) * np.sin(np.pi * y) * np.sin(np.pi * z)
    )
