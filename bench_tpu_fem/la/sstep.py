"""s-step (communication-avoiding) CG: one stacked reduction per s
iterations (Carson & Demmel 2014; the natural extension of la.cg's
single-reduction recurrence below ONE psum per iteration).

Standard CG needs two global reductions per iteration; PR 7's fused
recurrence (la.cg.onered_scalars) brought that to one. Going BELOW one
requires restructuring: over s iterations every iterate stays inside the
2s+1-dimensional Krylov space

    V = [p, A p, ..., A^s p,  r, A r, ..., A^{s-1} r]

so all the inner products of s iterations are entries of the Gram matrix
G = V^T V — computable with ONE stacked reduction (sharded: one psum of
the (2s+1, 2s+1) block). The s iterations then run as scalar recurrences
on (2s+1,)-coefficient vectors against G (no collectives at all), and
the full vectors x/r/p are reconstructed from V once per outer step.

Costs and caveats, stamped honestly:

* the R-basis applies are EXTRA operator work — 2s-1 applies per s
  iterations vs s for standard CG (the classical CA-CG flop trade; halo
  exchanges ride each apply, so MOVEMENT collectives scale with applies
  while REDUCTIONS drop to 1/s per iteration — the trace-level counter
  the tests and the perfgate pin).
* the monomial basis conditions like kappa(A)^s: small s (2-4) only,
  and f32 parity vs standard CG sits inside the repo's standing fused-
  engine envelope (2e-5 * scale), not at bitwise.
* breakdown (a non-SPD Gram projection, pdot <= 0, or a non-finite
  norm) FREEZES the state at the last good outer boundary and raises
  the `breakdown` flag in info; the drivers re-run the one-reduction
  recurrence and record `s_step_fallback_reason` — graceful, never
  silent, never NaN.
"""

from __future__ import annotations

from typing import Callable

import numpy as np


def shift_matrix(s: int) -> np.ndarray:
    """(2s+1, 2s+1) monomial-basis shift B with A (V c) = V (B c) for
    every coefficient vector the s inner iterations produce: columns
    0..s-1 shift the P-chain up one power, columns s+1..2s-1 the
    R-chain. The top powers (P_s, R_{s-1}) have zero columns — the
    recurrences never apply A to a vector carrying weight there (p_j
    spans only P_0..P_j, R_0..R_{j-1} for j < s, by induction on the CG
    update)."""
    m = 2 * s + 1
    B = np.zeros((m, m))
    for i in range(s):
        B[i + 1, i] = 1.0
    for i in range(s - 1):
        B[s + 1 + i + 1, s + 1 + i] = 1.0
    return B


def local_gram(V):
    """Default (single-chip) Gram matrix of the (2s+1, ...) basis stack:
    one reduction pass. The sharded twin is dist.halo.owned_gram (masked
    partials + ONE psum)."""
    import jax.numpy as jnp

    Vf = V.reshape(V.shape[0], -1)
    return Vf @ Vf.T


def sstep_cg_solve(
    apply_A: Callable,
    b,
    x0,
    max_iter: int,
    s: int,
    gram: Callable | None = None,
    dot: Callable | None = None,
    capture: bool = False,
):
    """Solve A x = b with the s-step recurrence; returns `(x, info)` with
    info = {"breakdown": bool scalar, "iters": completed iterations
    [, "rnorm_history": (max_iter + 1,) when capture]} — the drivers
    check `breakdown` once, after the solve, and fall back.

    Benchmark semantics (rtol = 0): exactly `max_iter` iterations unless
    breakdown freezes the state earlier; max_iter need not divide by s —
    the last outer step freezes its excess inner iterations with the
    repo's standing keep discipline. `dot` (default la.vector
    inner_product; sharded: owned-dof psum dot) computes the two
    out-of-loop reductions (<r0,r0> and the init residual); `gram` the
    in-loop stacked one."""
    import jax
    import jax.numpy as jnp

    from .vector import inner_product

    if s < 1:
        raise ValueError("s-step CG needs s >= 1")
    if gram is None:
        gram = local_gram
    if dot is None:
        dot = inner_product

    m = 2 * s + 1
    B = jnp.asarray(shift_matrix(s), b.dtype)
    e_p = jnp.zeros((m,), b.dtype).at[0].set(1.0)
    e_r = jnp.zeros((m,), b.dtype).at[s + 1].set(1.0)
    zero = jnp.zeros((), b.dtype)

    y0 = apply_A(x0)
    r0 = b - y0
    rnorm0 = dot(r0, r0)
    nouter = -(-max_iter // s)

    def body(k, state):
        x, r, p, rnorm, iters, done, bad, hist = state
        # --- basis: 2s-1 applies, NO reductions
        Vs = [p]
        for _ in range(s):
            Vs.append(apply_A(Vs[-1]))
        Rs = [r]
        for _ in range(s - 1):
            Rs.append(apply_A(Rs[-1]))
        V = jnp.stack(Vs + Rs)
        # --- the outer step's ONE stacked reduction
        G = gram(V)

        # --- s inner iterations: scalar recurrences against G
        pc, rc, xc = e_p, e_r, jnp.zeros((m,), b.dtype)
        rn = rnorm
        bad1 = bad
        hist1 = hist
        for j in range(s):
            live = jnp.logical_and(
                jnp.logical_not(done),
                jnp.logical_not(bad1))
            live = jnp.logical_and(live, k * s + j < max_iter)
            wc = B @ pc
            Gw = G @ wc
            pdot = pc @ Gw
            ok = jnp.logical_and(pdot > zero, jnp.isfinite(pdot))
            alpha0 = jnp.where(ok, rn / jnp.where(ok, pdot, 1.0), zero)
            rc1 = rc - alpha0 * wc
            rn1 = rc1 @ (G @ rc1)
            ok_r = jnp.logical_and(jnp.isfinite(rn1), rn1 >= zero)
            upd = jnp.logical_and(live, jnp.logical_and(ok, ok_r))
            bad1 = jnp.logical_or(
                bad1, jnp.logical_and(live, jnp.logical_not(
                    jnp.logical_and(ok, ok_r))))
            alpha = jnp.where(upd, alpha0, zero)
            xc = xc + alpha * pc
            beta = jnp.where(upd, rn1 / rn, zero)
            rc = jnp.where(upd, rc - alpha * wc, rc)
            pc = jnp.where(upd, rc + beta * pc, pc)
            rn = jnp.where(upd, rn1, rn)
            if capture:
                # a frozen inner iteration repeats its held value (the
                # capture discipline); indices past max_iter on the last
                # partial outer step are dropped by the OOB-scatter rule
                hist1 = hist1.at[k * s + j + 1].set(rn)

        # --- reconstruct full vectors once per outer step
        comb = lambda c: jnp.tensordot(c, V, axes=(0, 0))  # noqa: E731
        hold = jnp.logical_or(done, bad1)
        keep = lambda new, old: jnp.where(hold, old, new)  # noqa: E731
        x1 = keep(x + comb(xc), x)
        r1 = keep(comb(rc), r)
        p1 = keep(comb(pc), p)
        rnorm1 = keep(rn, rnorm)
        iters1 = jnp.where(hold, iters,
                           jnp.minimum(iters + s, max_iter))
        done1 = jnp.logical_or(done, rnorm1 == zero)
        return (x1, r1, p1, rnorm1, iters1, done1, bad1, hist1)

    hist0 = (jnp.zeros((max_iter + 1,), b.dtype).at[0].set(rnorm0)
             if capture else jnp.zeros((0,), b.dtype))
    state = (x0, r0, r0, rnorm0, jnp.zeros((), jnp.int32),
             rnorm0 == zero, jnp.asarray(False), hist0)
    x, _, _, _, iters, _, bad, hist = jax.lax.fori_loop(
        0, nouter, body, state)
    info = {"breakdown": bad, "iters": iters}
    if capture:
        info["rnorm_history"] = hist
    return x, info


from ..engines.registry import GATE_REASONS as _GATE_REASONS

#: recorded reason when a breakdown routed an s-step run back to the
#: one-reduction recurrence (la.cg) — the graceful fallback contract
#: (text owned by the registry vocabulary, engines.registry)
SSTEP_FALLBACK_REASON = _GATE_REASONS["sstep-breakdown"]

#: recorded reason when --s-step is requested on a path without an
#: s-step form (fused engines, batched stacks, df, folded layout)
SSTEP_GATE_REASON = _GATE_REASONS["sstep-unsupported"]
