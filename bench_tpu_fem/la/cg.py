"""Unpreconditioned CG with fixed iteration count (benchmark semantics).

Mirrors `cg_solve` (/root/reference/src/cg.hpp:89-169) exactly: with
rtol = 0 the loop runs exactly `max_iter` iterations (README.md:163), two
inner products and three axpys per iteration, operator applied to the
search direction each step. The whole loop is one jitted XLA computation
(`lax.fori_loop`), so on TPU there are no per-iteration launch or host
synchronisation costs — the analogue of the reference's requirement of
>= 10M dofs/GPU to hide launch latency (README.md:160-163) largely
disappears.

`dot` is injectable so the distributed path can pass a psum-reducing inner
product while reusing this loop unchanged inside `shard_map`.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp

from .vector import inner_product


# ---------------------------------------------------------------------------
# Single-reduction (fused-psum) recurrence: the communication-overlap CG
# forms replace the iteration's TWO global reductions (<p, A p> for
# alpha, then <r1, r1> for beta) with ONE fused reduction of the trio
# (<p, y>, <r, y>, <y, y>) computed right after the operator apply —
# <r1, r1> follows algebraically from r1 = r - alpha y:
#
#     <r1, r1> = <r, r> - 2 alpha <r, y> + alpha^2 <y, y>
#
# so all three partials are known BEFORE alpha and can ride one stacked
# psum per iteration (the reference's two MPI_Allreduce calls per
# iteration, cg.hpp:120-141, halved). The recurrence reassociates the
# residual-norm computation, so it is gated as a distinct engine form
# with measured parity bounds against the two-reduction oracle (<= 1e-7
# rel f32, <= 1e-13 df-class over the benchmark iteration budgets).
# ---------------------------------------------------------------------------


def onered_scalars(rnorm, pdot, ry, yy):
    """(alpha, rnorm1, beta1) of the single-reduction recurrence from the
    fused dot trio. rnorm1 is clamped at zero: near the f32 residual
    floor the reassociated form can cancel below zero, and a zero rnorm1
    (beta1 = 0, i.e. a steepest-descent restart) is the graceful
    degradation — the two-reduction oracle hits its own floor there."""
    alpha = rnorm / pdot
    rnorm1 = jnp.maximum(
        rnorm - alpha * (2.0 * ry - alpha * yy),
        jnp.zeros((), rnorm.dtype),
    )
    return alpha, rnorm1, rnorm1 / rnorm


def onered_scalars_df(rnorm, pdot, ry, yy):
    """df twin of onered_scalars: the same fused-reduction recurrence in
    compensated (hi, lo) arithmetic. The clamp guards the hi channel
    only (a negative hi at the df floor is the same cancellation mode)."""
    from .df64 import DF, df_div, df_mul, df_sub

    alpha = df_div(rnorm, pdot)
    two_ry = DF(2.0 * ry.hi, 2.0 * ry.lo)  # exact: power-of-two scale
    corr = df_mul(alpha, df_sub(two_ry, df_mul(alpha, yy)))
    rnorm1 = df_sub(rnorm, corr)
    pos = rnorm1.hi > 0
    rnorm1 = DF(jnp.where(pos, rnorm1.hi, jnp.zeros((), rnorm1.hi.dtype)),
                jnp.where(pos, rnorm1.lo, jnp.zeros((), rnorm1.lo.dtype)))
    return alpha, rnorm1, df_div(rnorm1, rnorm)


def stacked_dot3(p: jnp.ndarray, y: jnp.ndarray,
                 r: jnp.ndarray) -> jnp.ndarray:
    """Single-chip fused dot trio [<p,y>, <r,y>, <y,y>] as one stacked
    (3,) reduction — the `dot3` contract of `cg_solve(..., dot3=)`. The
    distributed layer's owned-dof-masked psum twin is
    dist.halo.owned_dot3 (the fused engines instead stack the kernel's
    in-kernel <p,Ap> partial via dist.halo.psum_stack; the dot3 hooks
    serve the unfused/batched sharded paths, production-wired when the
    batched overlap form lands)."""
    return jnp.stack([inner_product(p, y), inner_product(r, y),
                      inner_product(y, y)])


def onered_floor(dtype) -> jnp.ndarray:
    """Squared-relative-residual freeze floor for the single-reduction
    recurrence (squared rel 1e-13 f32 ~ rel residual 3e-7; 1e-28
    f64-width) — the same discipline as ops.kron_df.cg_solve_df's
    df-floor freeze. Applied ONLY on dot3 paths: the default
    two-reduction loop self-stabilises and stays bit-frozen."""
    import numpy as _np

    val = 1e-13 if _np.dtype(dtype) == _np.float32 else 1e-28
    return jnp.asarray(val, dtype)


#: consecutive recurrence-residual growths that freeze a dot3 solve.
#: The single-reduction recurrence LOSES STABILITY once rounding breaks
#: conjugacy (measured on a 2197-dof kron problem: the f32 recurrence
#: bottoms at rel 3e-3 around iteration 20 then grows monotonically to
#: 8e3 by iteration 60; f64 bottoms at 1e-7 then climbs the same way —
#: the two-reduction loop self-stabilises at 4e-7 on the same budget).
#: True CG residual norms DO grow transiently (the early iterations of
#: the same curve alternate up/down), so a single growth must not
#: freeze; sustained growth is the divergence signature. Freezing at
#: the current iterate a few steps past the minimum is the graceful
#: endpoint — the steepest-descent-restart philosophy of
#: onered_scalars' clamp, extended to the slow-divergence mode.
ONERED_GROW_MAX = 4


def _sentinel_zero() -> dict:
    """Fresh device-scalar sentinel carry (see `cg_solve(sentinel=)`)."""
    i32 = jnp.int32
    return {"breakdown_restarts": jnp.zeros((), i32),
            "nonfinite": jnp.asarray(False),
            "stag_run": jnp.zeros((), i32),
            "stag_max": jnp.zeros((), i32)}


class SdcInject(NamedTuple):
    """Deterministic seeded bit-flip injection into the audited loop's
    operator output (ISSUE 14 — the CHAOS_SDC fault model, jit-safe):
    at iteration `iteration` one bit of one element of ``y = A p`` is
    XOR-flipped (`bit` None = the per-dtype finite-exponent default,
    `index` < 0 = the largest-magnitude element). The injector exists so
    detection RATES are measured, not assumed; `inject=None` paths are
    bitwise the uninjected loop."""

    iteration: int
    bit: int | None = None
    index: int = -1


class CGAudit(NamedTuple):
    """SDC audit configuration for `cg_solve(audit=)` (ISSUE 14).

    ``every=K`` arms the periodic TRUE-RESIDUAL audit: every K
    iterations the loop recomputes ``‖b − A x‖`` from scratch (one
    extra apply under `lax.cond`, so off-cadence iterations pay
    nothing) and compares it against the carried recurrence rnorm,
    normalised by ``‖r0‖``, against a drift envelope calibrated per
    precision (ops.abft.RESIDUAL_ENVELOPE). ``every=0`` disables it.

    ``w``/``aw`` arm the per-apply ABFT check: ``aw = A w`` precomputed
    once (ops.abft.checksum_vectors), then every audited apply compares
    ``⟨w, A p⟩`` against ``⟨aw, p⟩`` (the operator-symmetry identity),
    Cauchy–Schwarz-normalised, against ``abft_envelope``.

    Exceedance on either check is CORRUPTION — the `sdc` failure class,
    distinct from the non-finite `breakdown` class: these values are
    finite but inconsistent. Detection freezes the solve at the last
    audited-good iterate (the recovery layer rolls back to a durable
    checkpoint); the verdicts ride the loop carry as device scalars
    (the PR-10 capture discipline — no host sync on the hot path) and
    come back in the info dict: `sdc_detected`, `sdc_iter` (first
    detection, -1 = clean), `sdc_abft_checks`/`sdc_resid_checks`,
    `sdc_abft_max`/`sdc_drift_max`."""

    every: int = 8
    envelope: float | None = None
    w: object = None
    aw: object = None
    abft_envelope: float | None = None
    inject: object = None  # SdcInject | None


def cg_solve(
    apply_A: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    x0: jnp.ndarray,
    max_iter: int,
    rtol: float = 0.0,
    dot: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray] | None = None,
    dot3: Callable | None = None,
    sentinel: bool = False,
    capture: bool = False,
    precond: Callable | None = None,
    dotpair: Callable | None = None,
    audit: CGAudit | None = None,
):
    """Solve A x = b; returns x after `max_iter` iterations (rtol=0) or until
    ||r||/||r0|| < rtol. Early termination freezes the state rather than
    exiting the loop, keeping the iteration count static for XLA.

    With `dot3(p, y, r) -> (3,) [<p,y>, <r,y>, <y,y>]` given, the loop
    runs the single-reduction recurrence (see onered_scalars): one fused
    reduction per iteration instead of two — the distributed overlap
    form's psum-count contract. Reassociated; parity vs the default
    two-reduction loop is <= 1e-7 rel (f32) over benchmark budgets.

    With `sentinel=True` the loop carries the numerical-breakdown
    sentinels (ISSUE 9) and returns `(x, info)` where info holds device
    scalars: `breakdown_restarts` (iterations where <p, A p> <= 0 or
    non-finite — routed to the graceful steepest-descent restart: the
    step is skipped and the next direction is the bare residual),
    `nonfinite` (a non-finite residual norm appeared; the state FREEZES
    at the last finite iterate instead of propagating NaN into the
    answer), and `stag_max` (longest run of non-decreasing residual
    norms — a stall signature). All sentinels are jit-safe select
    arithmetic on the scalars the loop already computes: no host sync
    anywhere on the hot path, and on a healthy solve every selected
    value is bit-identical to the unguarded loop.

    With `capture=True` (ISSUE 10: convergence telemetry) the loop
    carries a PREALLOCATED `(max_iter + 1,)` device buffer of the
    squared residual norms — `rnorm_history[0] = <r0, r0>`,
    `rnorm_history[k]` the CARRIED rnorm after iteration k (a frozen
    iteration repeats its held value, so the history is exactly what the
    recurrence saw) — written in the fori_loop body with a dynamic
    index store: NO host sync anywhere on the hot path; the history is
    fetched once, after the solve, by whoever stamps it
    (obs.convergence). Returns `(x, info)` with
    `info["rnorm_history"]`. With `capture=False` (the default) this
    function is the pre-capture code path unchanged — the bitwise
    contract tests/test_convergence.py pins.

    With `precond=` (ISSUE 11) the loop runs PRECONDITIONED CG: the
    <r, z> recurrence with z = precond(r) ~= M^{-1} r (M fixed SPD —
    la.precond builds Jacobi / Chebyshev / p-MG appliers). The routing
    is a pure python branch to a SEPARATE body (`_pcg_solve`), so
    `precond=None` is the pre-PR solve BIT-FOR-BIT (pinned against a
    frozen replica, the PR-10 discipline); sentinel/capture/rtol/dot
    compose with precond, `dot3` does not (the fused-trio recurrence is
    an unpreconditioned-form identity). `dotpair(r, z) -> (<r,z>,
    <r,r>)` optionally fuses the two post-update reductions into one
    stacked pass (sharded: dist.halo.owned_pair_dot, ONE psum).

    With `audit=` (ISSUE 14: SDC defense) the loop runs the AUDITED
    recurrence (`_audited_cg_solve`, a separate body — `audit=None` is
    the pre-PR solve bit-for-bit, the same routing discipline):
    periodic true-residual recompute + optional per-apply ABFT check
    (see `CGAudit`), verdicts carried as device scalars, corruption
    freezing the solve at the last audited-good iterate. Returns
    `(x, info)`. Composes with sentinel/capture/rtol/dot; `dot3` and
    `precond` do not (the audit identities are identities of the
    unpreconditioned two-reduction form)."""
    if audit is not None:
        if dot3 is not None or precond is not None:
            raise ValueError(
                "audit= composes with sentinel/capture only: the ABFT "
                "and true-residual identities are identities of the "
                "unpreconditioned two-reduction recurrence")
        return _audited_cg_solve(apply_A, b, x0, max_iter, rtol=rtol,
                                 dot=dot, audit=audit, sentinel=sentinel,
                                 capture=capture)
    if precond is not None:
        if dot3 is not None:
            raise ValueError(
                "precond= and dot3= are mutually exclusive: the fused "
                "single-reduction trio is an identity of the "
                "UNpreconditioned recurrence")
        return _pcg_solve(apply_A, b, x0, max_iter, rtol=rtol, dot=dot,
                          precond=precond, dotpair=dotpair,
                          sentinel=sentinel, capture=capture)
    if dot is None:
        dot = inner_product

    y = apply_A(x0)
    r = b - y
    p = r
    rnorm0 = dot(p, r)

    def body(i, state):
        x, r, p, rnorm, done, info = state
        y = apply_A(p)
        if dot3 is None:
            pdot = dot(p, y)
            alpha = rnorm / pdot
            if sentinel:
                ok_p = jnp.logical_and(pdot > 0, jnp.isfinite(pdot))
                alpha = jnp.where(ok_p, alpha, jnp.zeros((), alpha.dtype))
            x1 = x + alpha * p
            r1 = r - alpha * y
            rnorm_new = dot(r1, r1)
            beta = rnorm_new / rnorm
            if sentinel:
                # steepest-descent restart: a skipped step's next
                # direction is the bare residual (beta = 0)
                beta = jnp.where(ok_p, beta, jnp.zeros((), beta.dtype))
        else:
            pdot, ry, yy = dot3(p, y, r)
            if sentinel:
                ok_p = jnp.logical_and(pdot > 0, jnp.isfinite(pdot))
            alpha, rnorm_new, beta = onered_scalars(rnorm, pdot, ry, yy)
            if sentinel:
                alpha = jnp.where(ok_p, alpha, jnp.zeros((), alpha.dtype))
                beta = jnp.where(ok_p, beta, jnp.zeros((), beta.dtype))
                # the recurrence's rnorm_new was computed from the
                # UN-zeroed alpha: on a skipped step the residual did not
                # move, so its norm did not either
                rnorm_new = jnp.where(ok_p, rnorm_new, rnorm)
            x1 = x + alpha * p
            r1 = r - alpha * y
        p1 = beta * p + r1
        new_done = jnp.logical_or(done, rnorm_new / rnorm0 < rtol * rtol)
        # exact-zero residual = converged EXACTLY (small problems under
        # long budgets underflow there): freeze — one more iteration
        # would synthesize NaN out of beta = 0/0 (ISSUE 9: never
        # silently emit NaN; same guard as cg_solve_batched, keeping
        # the lane-0-bitwise parity in the degenerate regime too).
        # Benchmark-size problems never reach exact zero, so the
        # standing bitwise contracts are untouched.
        new_done = jnp.logical_or(
            new_done, rnorm_new == jnp.zeros((), rnorm_new.dtype))
        if dot3 is not None:
            # single-reduction stability guards (see onered_floor /
            # ONERED_GROW_MAX): freeze at the dtype floor, and freeze
            # on SUSTAINED recurrence-residual growth — the divergence
            # signature of the reassociated recurrence once rounding
            # breaks conjugacy
            new_done = jnp.logical_or(
                new_done, rnorm_new <= onered_floor(rnorm_new.dtype)
                * rnorm0)
            info = dict(info)
            live = jnp.logical_not(done)
            grew = jnp.logical_and(live, rnorm_new > rnorm)
            run = jnp.where(grew, info["onered_grow_run"] + 1,
                            jnp.zeros((), jnp.int32))
            info["onered_grow_run"] = run
            new_done = jnp.logical_or(new_done,
                                      run >= jnp.int32(ONERED_GROW_MAX))
        if sentinel:
            bad_r = jnp.logical_not(jnp.isfinite(rnorm_new))
            live = jnp.logical_not(done)
            info = dict(info)
            info["breakdown_restarts"] = info["breakdown_restarts"] + (
                jnp.logical_and(live, jnp.logical_not(ok_p))
                .astype(jnp.int32))
            info["nonfinite"] = jnp.logical_or(
                info["nonfinite"], jnp.logical_and(live, bad_r))
            no_prog = jnp.logical_and(rnorm_new >= rnorm,
                                      jnp.logical_not(bad_r))
            stag = jnp.where(jnp.logical_and(live, no_prog),
                             info["stag_run"] + 1,
                             jnp.zeros((), jnp.int32))
            info["stag_run"] = stag
            info["stag_max"] = jnp.maximum(info["stag_max"], stag)
            # a poisoned iterate freezes the state at the last finite
            # one: the loop keeps running (static trip count) but every
            # subsequent update is discarded
            new_done = jnp.logical_or(new_done, bad_r)
            hold = jnp.logical_or(done, bad_r)
        else:
            hold = done
        keep = lambda new, old: jnp.where(hold, old, new)
        rnorm_keep = keep(rnorm_new, rnorm)
        if capture:
            # in-loop dynamic index store into the preallocated device
            # buffer — the jit-safe, no-host-sync capture discipline
            info = dict(info)
            info["rnorm_history"] = (
                info["rnorm_history"].at[i + 1].set(rnorm_keep))
        return (
            keep(x1, x),
            keep(r1, r),
            keep(p1, p),
            rnorm_keep,
            new_done,
            info,
        )

    info0 = _sentinel_zero() if sentinel else {}
    if capture:
        info0 = dict(info0)
        info0["rnorm_history"] = (
            jnp.zeros((max_iter + 1,), rnorm0.dtype).at[0].set(rnorm0))
    if dot3 is not None:
        info0 = dict(info0)
        info0["onered_grow_run"] = jnp.zeros((), jnp.int32)
    state = (x0, r, p, rnorm0, jnp.asarray(False), info0)
    x, _, _, _, _, info = jax.lax.fori_loop(0, max_iter, body, state)
    if sentinel or capture:
        return x, {k: v for k, v in info.items()
                   if k not in ("stag_run", "onered_grow_run")}
    return x


def _pcg_solve(apply_A, b, x0, max_iter, rtol, dot, precond, dotpair,
               sentinel, capture):
    """Preconditioned CG (the <r, z> recurrence; ISSUE 11). Separate
    body from `cg_solve` BY DESIGN: the unpreconditioned path must stay
    bit-frozen, and the PCG loop carries one extra vector (z) and one
    extra scalar (<r, z>) it has no business threading through.

    Same freeze/sentinel/capture discipline as `cg_solve`: early
    termination freezes rather than exits (static trip count), the
    capture buffer holds the carried <r, r> (the ladder folds RESIDUAL
    norms — preconditioned and bare histories stay comparable), and the
    sentinels guard <p, A p> <= 0 exactly as the bare loop does (an
    indefinite M^{-1} surfaces there too: alpha/beta zero, the next
    direction restarts from z)."""
    if dot is None:
        dot = inner_product
    if dotpair is None:
        def dotpair(r_, z_):
            return dot(r_, z_), dot(r_, r_)

    y = apply_A(x0)
    r = b - y
    z = precond(r)
    p = z
    rz0, rnorm0 = dotpair(r, z)

    def body(i, state):
        x, r, p, rz, rnorm, done, info = state
        y = apply_A(p)
        pdot = dot(p, y)
        alpha = rz / pdot
        if sentinel:
            ok_p = jnp.logical_and(pdot > 0, jnp.isfinite(pdot))
            alpha = jnp.where(ok_p, alpha, jnp.zeros((), alpha.dtype))
        x1 = x + alpha * p
        r1 = r - alpha * y
        z1 = precond(r1)
        rz_new, rnorm_new = dotpair(r1, z1)
        beta = rz_new / rz
        if sentinel:
            beta = jnp.where(ok_p, beta, jnp.zeros((), beta.dtype))
        p1 = beta * p + z1
        new_done = jnp.logical_or(done, rnorm_new / rnorm0 < rtol * rtol)
        # exact-zero residual = exact convergence: freeze (beta would
        # synthesize NaN from 0/0 next iteration — the cg_solve guard)
        new_done = jnp.logical_or(
            new_done, rnorm_new == jnp.zeros((), rnorm_new.dtype))
        if sentinel:
            bad_r = jnp.logical_not(jnp.isfinite(rnorm_new))
            live = jnp.logical_not(done)
            info = dict(info)
            info["breakdown_restarts"] = info["breakdown_restarts"] + (
                jnp.logical_and(live, jnp.logical_not(ok_p))
                .astype(jnp.int32))
            info["nonfinite"] = jnp.logical_or(
                info["nonfinite"], jnp.logical_and(live, bad_r))
            no_prog = jnp.logical_and(rnorm_new >= rnorm,
                                      jnp.logical_not(bad_r))
            stag = jnp.where(jnp.logical_and(live, no_prog),
                             info["stag_run"] + 1,
                             jnp.zeros((), jnp.int32))
            info["stag_run"] = stag
            info["stag_max"] = jnp.maximum(info["stag_max"], stag)
            new_done = jnp.logical_or(new_done, bad_r)
            hold = jnp.logical_or(done, bad_r)
        else:
            hold = done
        keep = lambda new, old: jnp.where(hold, old, new)  # noqa: E731
        rnorm_keep = keep(rnorm_new, rnorm)
        if capture:
            info = dict(info)
            info["rnorm_history"] = (
                info["rnorm_history"].at[i + 1].set(rnorm_keep))
        return (
            keep(x1, x),
            keep(r1, r),
            keep(p1, p),
            keep(rz_new, rz),
            rnorm_keep,
            new_done,
            info,
        )

    info0 = _sentinel_zero() if sentinel else {}
    if capture:
        info0 = dict(info0)
        info0["rnorm_history"] = (
            jnp.zeros((max_iter + 1,), rnorm0.dtype).at[0].set(rnorm0))
    state = (x0, r, p, rz0, rnorm0, jnp.asarray(False), info0)
    x, _, _, _, _, _, info = jax.lax.fori_loop(0, max_iter, body, state)
    if sentinel or capture:
        return x, {k: v for k, v in info.items() if k != "stag_run"}
    return x


def _audited_cg_solve(apply_A, b, x0, max_iter, rtol, dot, audit,
                      sentinel, capture):
    """SDC-audited CG (ISSUE 14). Separate body from `cg_solve` BY
    DESIGN (the `_pcg_solve` discipline): the unaudited path must stay
    bit-frozen, and the audit carries scalars (verdict flags, check
    counters, drift maxima) the plain loop has no business threading.

    The RECURRENCE is `cg_solve`'s plain loop verbatim — same ops, same
    order — so on a clean solve the returned x is bitwise the unaudited
    solve's (the audit computations are pure observers). Detection
    freezes the state exactly as the non-finite sentinel does: the
    detected iteration's updates are discarded, every later iteration
    holds, and the caller (driver checkpoint rollback / serve lane
    re-admit) owns recovery. The injection seam (`CGAudit.inject`) is
    the deterministic mercurial-core model: one seeded bit flip in the
    operator output, `inject=None` bitwise off."""
    from ..ops.abft import (
        abft_envelope,
        abft_residual,
        default_flip_bit,
        flip_bit,
        residual_envelope,
    )

    if dot is None:
        dot = inner_product
    dtype = b.dtype
    every = int(audit.every)
    env = jnp.asarray(audit.envelope if audit.envelope is not None
                      else residual_envelope(dtype), dtype)
    abft_on = audit.w is not None and audit.aw is not None
    if abft_on:
        aenv = jnp.asarray(
            audit.abft_envelope if audit.abft_envelope is not None
            else abft_envelope(dtype), dtype)
        ww = dot(audit.w, audit.w)
    inject = audit.inject
    if inject is not None:
        inj_bit = (inject.bit if inject.bit is not None
                   else default_flip_bit(dtype))

    y = apply_A(x0)
    r = b - y
    p = r
    rnorm0 = dot(p, r)
    sq0 = jnp.sqrt(rnorm0)
    zero = jnp.zeros((), dtype)

    def body(i, state):
        x, r, p, rnorm, done, info = state
        y = apply_A(p)
        if inject is not None:
            # the mercurial core: one finite bit flip at the scripted
            # iteration (computed unconditionally, selected by `where` —
            # the loop stays one fused body)
            y = jnp.where(i == jnp.int32(inject.iteration),
                          flip_bit(y, inject.index, inj_bit), y)
        info = dict(info)
        live = jnp.logical_not(done)
        detected = jnp.asarray(False)
        if abft_on:
            # per-apply ABFT: <w, A p> must equal <A w, p> (symmetry)
            # to rounding, normalised by the Cauchy-Schwarz scale (the
            # raw sums may cancel arbitrarily); ww hoisted out of the
            # loop
            aerr = abft_residual(audit.w, audit.aw, p, y, dot, ww=ww)
            info["sdc_abft_checks"] = (info["sdc_abft_checks"]
                                       + live.astype(jnp.int32))
            info["sdc_abft_max"] = jnp.maximum(
                info["sdc_abft_max"], jnp.where(live, aerr, zero))
            detected = jnp.logical_or(
                detected, jnp.logical_and(live, aerr > aenv))
        pdot = dot(p, y)
        alpha = rnorm / pdot
        if sentinel:
            ok_p = jnp.logical_and(pdot > 0, jnp.isfinite(pdot))
            alpha = jnp.where(ok_p, alpha, jnp.zeros((), alpha.dtype))
        x1 = x + alpha * p
        r1 = r - alpha * y
        rnorm_new = dot(r1, r1)
        beta = rnorm_new / rnorm
        if sentinel:
            beta = jnp.where(ok_p, beta, jnp.zeros((), beta.dtype))
        p1 = beta * p + r1
        if every > 0:
            # periodic true-residual audit: recompute ||b - A x|| from
            # scratch under lax.cond (off-cadence iterations pay no
            # extra apply) and compare against the carried rnorm — a
            # corruption of the carried state breaks this identity and
            # STAYS broken, so the cadence bounds detection latency,
            # not detection itself
            do_check = jnp.logical_and(
                live, (i + 1) % jnp.int32(every) == 0)

            def _check(_):
                rr = b - apply_A(x1)
                tr = dot(rr, rr)
                return jnp.abs(
                    jnp.sqrt(jnp.maximum(tr, zero))
                    - jnp.sqrt(jnp.maximum(rnorm_new, zero))) / sq0

            drift = jax.lax.cond(do_check, _check, lambda _: zero, None)
            info["sdc_resid_checks"] = (info["sdc_resid_checks"]
                                        + do_check.astype(jnp.int32))
            info["sdc_drift_max"] = jnp.maximum(info["sdc_drift_max"],
                                                drift)
            detected = jnp.logical_or(detected, drift > env)
        first = jnp.logical_and(detected,
                                jnp.logical_not(info["sdc_detected"]))
        info["sdc_iter"] = jnp.where(first, jnp.asarray(i, jnp.int32),
                                     info["sdc_iter"])
        info["sdc_detected"] = jnp.logical_or(info["sdc_detected"],
                                              detected)
        new_done = jnp.logical_or(done, rnorm_new / rnorm0 < rtol * rtol)
        new_done = jnp.logical_or(new_done, rnorm_new == zero)
        # corruption freezes the solve: the detected iteration's updates
        # are DISCARDED (the ABFT check fired on this iteration's own
        # corrupted apply — the held state is the last audited-good
        # iterate) and the loop runs out its static trip count frozen
        new_done = jnp.logical_or(new_done, detected)
        if sentinel:
            bad_r = jnp.logical_not(jnp.isfinite(rnorm_new))
            info["breakdown_restarts"] = info["breakdown_restarts"] + (
                jnp.logical_and(live, jnp.logical_not(ok_p))
                .astype(jnp.int32))
            info["nonfinite"] = jnp.logical_or(
                info["nonfinite"], jnp.logical_and(live, bad_r))
            no_prog = jnp.logical_and(rnorm_new >= rnorm,
                                      jnp.logical_not(bad_r))
            stag = jnp.where(jnp.logical_and(live, no_prog),
                             info["stag_run"] + 1,
                             jnp.zeros((), jnp.int32))
            info["stag_run"] = stag
            info["stag_max"] = jnp.maximum(info["stag_max"], stag)
            new_done = jnp.logical_or(new_done, bad_r)
            hold = jnp.logical_or(jnp.logical_or(done, bad_r), detected)
        else:
            hold = jnp.logical_or(done, detected)
        keep = lambda new, old: jnp.where(hold, old, new)  # noqa: E731
        rnorm_keep = keep(rnorm_new, rnorm)
        if capture:
            info["rnorm_history"] = (
                info["rnorm_history"].at[i + 1].set(rnorm_keep))
        return (
            keep(x1, x),
            keep(r1, r),
            keep(p1, p),
            rnorm_keep,
            new_done,
            info,
        )

    info0 = _sentinel_zero() if sentinel else {}
    if capture:
        info0 = dict(info0)
        info0["rnorm_history"] = (
            jnp.zeros((max_iter + 1,), rnorm0.dtype).at[0].set(rnorm0))
    i32 = jnp.int32
    info0 = dict(info0)
    info0.update(
        sdc_detected=jnp.asarray(False),
        sdc_iter=jnp.asarray(-1, i32),
        sdc_abft_checks=jnp.zeros((), i32),
        sdc_resid_checks=jnp.zeros((), i32),
        sdc_drift_max=zero,
        sdc_abft_max=zero,
    )
    state = (x0, r, p, rnorm0, jnp.asarray(False), info0)
    x, _, _, _, _, info = jax.lax.fori_loop(0, max_iter, body, state)
    return x, {k: v for k, v in info.items() if k != "stag_run"}


def batched_dot(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched inner product: <a_i, b_i> per RHS over a (nrhs, ...) stack.
    vmap of the scalar `inner_product` rather than a reshape+sum: the
    vmapped dot lowers to the SAME per-lane reduction as the unbatched
    one (measured bitwise-equal on CPU), so an nrhs=1 batched solve
    reproduces `cg_solve` exactly — the parity anchor the serving tests
    assert. A reshape+sum reduction tiles differently and drifts ~1e-6
    (f32) after a few dozen iterations."""
    return jax.vmap(inner_product)(a, b)


def _bcast(flag: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a per-RHS (nrhs,) flag against (nrhs, ...) state."""
    return flag.reshape((-1,) + (1,) * (like.ndim - 1))


def batched_dot3(P: jnp.ndarray, Y: jnp.ndarray,
                 R: jnp.ndarray) -> jnp.ndarray:
    """Batched fused dot trio: (3, nrhs) stack of per-lane [<p,y>, <r,y>,
    <y,y>] — the `dot3` contract of `cg_solve_batched(..., dot3=)`. One
    reduction pass; the distributed twin psums the whole (3, nrhs) block
    in one collective."""
    return jnp.stack([batched_dot(P, Y), batched_dot(R, Y),
                      batched_dot(Y, Y)])


def cg_solve_batched(
    apply_A: Callable[[jnp.ndarray], jnp.ndarray],
    B: jnp.ndarray,
    X0: jnp.ndarray,
    max_iter: int,
    rtol: float = 0.0,
    dot: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray] | None = None,
    batch_apply: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
    dot3: Callable | None = None,
    sentinel: bool = False,
    capture: bool = False,
    precond: Callable | None = None,
    dotpair: Callable | None = None,
):
    """Multi-RHS CG over a (nrhs, ...) stack: solve A x_i = b_i for every
    RHS in ONE static loop — the serving-layer batch primitive (each
    request contributes one RHS; launch/loop overhead amortises across
    the batch instead of across problem size).

    Same recurrence as `cg_solve`, vectorised across the leading axis:
    the operator is applied through `jax.vmap(apply_A)` (override with
    `batch_apply` when the operator has a natively-batched form, e.g. the
    sharded path, whose psum'd batched dot must also come in via `dot`),
    and both inner products reduce to (nrhs,) vectors in one pass.
    Convergence (rtol > 0) freezes each RHS independently — a converged
    lane's state stops updating while the loop itself stays a fixed-trip
    `fori_loop`, so the computation is one XLA executable for any mix of
    easy and hard right-hand sides.

    All-zero RHS lanes (the batching window's padding) start frozen:
    they return X0 untouched and their 0/0 alpha never contaminates the
    live lanes (`keep` discards the dead lanes' arithmetic every
    iteration).

    With `dot3(P, Y, R) -> (3, nrhs)` given, the loop runs the
    single-reduction recurrence (onered_scalars, vectorised per lane):
    ONE fused reduction carries all lanes' three dots per iteration —
    the batched analogue of the distributed overlap form's one-psum
    contract (same reassociation, same parity envelope).

    With `sentinel=True` the loop carries per-lane breakdown sentinels
    (the `cg_solve(sentinel=)` contract, vectorised) and returns
    `(X, info)` with (nrhs,) arrays: `breakdown_restarts`, `nonfinite`
    (that lane froze at its last finite iterate), `stag_max`. Lane
    sentinels are independent: one poisoned lane never perturbs — or
    stalls — its batch-mates.

    With `capture=True` the loop carries a `(max_iter + 1, nrhs)`
    preallocated residual-history buffer (per-lane squared norms, same
    discipline and return contract as `cg_solve(capture=True)` — no
    host sync on the hot path; `capture=False` is the pre-capture code
    path unchanged).

    With `precond=` (ISSUE 11) every lane runs the preconditioned
    <r, z> recurrence (`precond` maps the whole (nrhs, ...) residual
    stack — a Jacobi dinv broadcasts, an operator-based M^{-1} vmaps);
    routed to a separate body so `precond=None` stays the pre-PR code
    path bit-for-bit. `dotpair(R, Z) -> ((nrhs,) <r,z>, (nrhs,) <r,r>)`
    optionally fuses the two post-update reductions (sharded: one
    stacked psum)."""
    if precond is not None:
        if dot3 is not None:
            raise ValueError(
                "precond= and dot3= are mutually exclusive: the fused "
                "single-reduction trio is an identity of the "
                "UNpreconditioned recurrence")
        return _pcg_solve_batched(
            apply_A, B, X0, max_iter, rtol=rtol, dot=dot,
            batch_apply=batch_apply, precond=precond, dotpair=dotpair,
            sentinel=sentinel, capture=capture)
    if dot is None:
        dot = batched_dot
    if batch_apply is None:
        batch_apply = jax.vmap(apply_A)

    Y = batch_apply(X0)
    R = B - Y
    P = R
    rnorm0 = dot(P, R)
    # padding lanes (rnorm0 == 0) are born converged
    done0 = rnorm0 == jnp.zeros((), rnorm0.dtype)
    nrhs = rnorm0.shape[0]

    def body(i, state):
        X, R, P, rnorm, done, info = state
        Y = batch_apply(P)
        if dot3 is None:
            pdot = dot(P, Y)
            alpha = rnorm / pdot
        else:
            pdot, ry, yy = dot3(P, Y, R)
            alpha, rnorm_new, beta = onered_scalars(rnorm, pdot, ry, yy)
        if sentinel:
            ok_p = jnp.logical_and(pdot > 0, jnp.isfinite(pdot))
            alpha = jnp.where(ok_p, alpha, jnp.zeros((), alpha.dtype))
        X1 = X + _bcast(alpha, X) * P
        R1 = R - _bcast(alpha, R) * Y
        if dot3 is None:
            rnorm_new = dot(R1, R1)
            beta = rnorm_new / rnorm
        if sentinel:
            beta = jnp.where(ok_p, beta, jnp.zeros((), beta.dtype))
            if dot3 is not None:
                # the single-reduction rnorm_new used the UN-zeroed
                # alpha: a skipped lane's residual norm did not move
                rnorm_new = jnp.where(ok_p, rnorm_new, rnorm)
        P1 = _bcast(beta, P) * P + R1
        new_done = jnp.logical_or(done, rnorm_new / rnorm0 < rtol * rtol)
        # exact-zero residual = converged EXACTLY (small problems under
        # long budgets underflow there): freeze the lane — one more
        # iteration would synthesize NaN out of beta = 0/0 (ISSUE 9:
        # never silently emit NaN solutions). Benchmark-size problems
        # never reach exact zero, so the standing bitwise contracts are
        # untouched.
        new_done = jnp.logical_or(
            new_done, rnorm_new == jnp.zeros((), rnorm_new.dtype))
        if dot3 is not None:
            # per-lane single-reduction stability guards (see
            # onered_floor / ONERED_GROW_MAX)
            new_done = jnp.logical_or(
                new_done, rnorm_new <= onered_floor(rnorm_new.dtype)
                * rnorm0)
            info = dict(info)
            live = jnp.logical_not(done)
            grew = jnp.logical_and(live, rnorm_new > rnorm)
            run = jnp.where(grew, info["onered_grow_run"] + 1,
                            jnp.zeros((nrhs,), jnp.int32))
            info["onered_grow_run"] = run
            new_done = jnp.logical_or(new_done,
                                      run >= jnp.int32(ONERED_GROW_MAX))
        if sentinel:
            bad_r = jnp.logical_not(jnp.isfinite(rnorm_new))
            live = jnp.logical_not(done)
            info = dict(info)
            info["breakdown_restarts"] = info["breakdown_restarts"] + (
                jnp.logical_and(live, jnp.logical_not(ok_p))
                .astype(jnp.int32))
            info["nonfinite"] = jnp.logical_or(
                info["nonfinite"], jnp.logical_and(live, bad_r))
            no_prog = jnp.logical_and(rnorm_new >= rnorm,
                                      jnp.logical_not(bad_r))
            stag = jnp.where(jnp.logical_and(live, no_prog),
                             info["stag_run"] + 1,
                             jnp.zeros((), jnp.int32))
            info["stag_run"] = stag
            info["stag_max"] = jnp.maximum(info["stag_max"], stag)
            new_done = jnp.logical_or(new_done, bad_r)
            hold = jnp.logical_or(done, bad_r)
        else:
            hold = done

        def keep(new, old):
            return jnp.where(_bcast(hold, old), old, new)

        def keep1(new, old):
            return jnp.where(hold, old, new)

        rnorm_keep = keep1(rnorm_new, rnorm)
        if capture:
            info = dict(info)
            info["rnorm_history"] = (
                info["rnorm_history"].at[i + 1].set(rnorm_keep))
        return (
            keep(X1, X),
            keep(R1, R),
            keep(P1, P),
            rnorm_keep,
            new_done,
            info,
        )

    if sentinel:
        i32 = jnp.int32
        info0 = {"breakdown_restarts": jnp.zeros((nrhs,), i32),
                 "nonfinite": jnp.zeros((nrhs,), bool),
                 "stag_run": jnp.zeros((nrhs,), i32),
                 "stag_max": jnp.zeros((nrhs,), i32)}
    else:
        info0 = {}
    if capture:
        info0 = dict(info0)
        info0["rnorm_history"] = (
            jnp.zeros((max_iter + 1, nrhs), rnorm0.dtype).at[0].set(rnorm0))
    if dot3 is not None:
        info0 = dict(info0)
        info0["onered_grow_run"] = jnp.zeros((nrhs,), jnp.int32)
    state = (X0, R, P, rnorm0, done0, info0)
    X, _, _, _, _, info = jax.lax.fori_loop(0, max_iter, body, state)
    if sentinel or capture:
        return X, {k: v for k, v in info.items()
                   if k not in ("stag_run", "onered_grow_run")}
    return X


def _pcg_solve_batched(apply_A, B, X0, max_iter, rtol, dot, batch_apply,
                       precond, dotpair, sentinel, capture):
    """Batched preconditioned CG — `_pcg_solve` vectorised across the
    lane axis with `cg_solve_batched`'s frozen-lane discipline (padding
    lanes born frozen, per-lane freeze on convergence/exact zero, lane
    algebra independent)."""
    if dot is None:
        dot = batched_dot
    if batch_apply is None:
        batch_apply = jax.vmap(apply_A)
    if dotpair is None:
        def dotpair(R_, Z_):
            return dot(R_, Z_), dot(R_, R_)

    Y = batch_apply(X0)
    R = B - Y
    Z = precond(R)
    P = Z
    rz0, rnorm0 = dotpair(R, Z)
    done0 = rnorm0 == jnp.zeros((), rnorm0.dtype)
    nrhs = rnorm0.shape[0]

    def body(i, state):
        X, R, P, rz, rnorm, done, info = state
        Y = batch_apply(P)
        pdot = dot(P, Y)
        alpha = rz / pdot
        if sentinel:
            ok_p = jnp.logical_and(pdot > 0, jnp.isfinite(pdot))
            alpha = jnp.where(ok_p, alpha, jnp.zeros((), alpha.dtype))
        X1 = X + _bcast(alpha, X) * P
        R1 = R - _bcast(alpha, R) * Y
        Z1 = precond(R1)
        rz_new, rnorm_new = dotpair(R1, Z1)
        beta = rz_new / rz
        if sentinel:
            beta = jnp.where(ok_p, beta, jnp.zeros((), beta.dtype))
        P1 = _bcast(beta, P) * P + Z1
        new_done = jnp.logical_or(done, rnorm_new / rnorm0 < rtol * rtol)
        new_done = jnp.logical_or(
            new_done, rnorm_new == jnp.zeros((), rnorm_new.dtype))
        if sentinel:
            bad_r = jnp.logical_not(jnp.isfinite(rnorm_new))
            live = jnp.logical_not(done)
            info = dict(info)
            info["breakdown_restarts"] = info["breakdown_restarts"] + (
                jnp.logical_and(live, jnp.logical_not(ok_p))
                .astype(jnp.int32))
            info["nonfinite"] = jnp.logical_or(
                info["nonfinite"], jnp.logical_and(live, bad_r))
            no_prog = jnp.logical_and(rnorm_new >= rnorm,
                                      jnp.logical_not(bad_r))
            stag = jnp.where(jnp.logical_and(live, no_prog),
                             info["stag_run"] + 1,
                             jnp.zeros((nrhs,), jnp.int32))
            info["stag_run"] = stag
            info["stag_max"] = jnp.maximum(info["stag_max"], stag)
            new_done = jnp.logical_or(new_done, bad_r)
            hold = jnp.logical_or(done, bad_r)
        else:
            hold = done

        def keep(new, old):
            return jnp.where(_bcast(hold, old), old, new)

        def keep1(new, old):
            return jnp.where(hold, old, new)

        rnorm_keep = keep1(rnorm_new, rnorm)
        if capture:
            info = dict(info)
            info["rnorm_history"] = (
                info["rnorm_history"].at[i + 1].set(rnorm_keep))
        return (
            keep(X1, X),
            keep(R1, R),
            keep(P1, P),
            keep1(rz_new, rz),
            rnorm_keep,
            new_done,
            info,
        )

    if sentinel:
        i32 = jnp.int32
        info0 = {"breakdown_restarts": jnp.zeros((nrhs,), i32),
                 "nonfinite": jnp.zeros((nrhs,), bool),
                 "stag_run": jnp.zeros((nrhs,), i32),
                 "stag_max": jnp.zeros((nrhs,), i32)}
    else:
        info0 = {}
    if capture:
        info0 = dict(info0)
        info0["rnorm_history"] = (
            jnp.zeros((max_iter + 1, nrhs), rnorm0.dtype).at[0].set(rnorm0))
    state = (X0, R, P, rz0, rnorm0, done0, info0)
    X, _, _, _, _, _, info = jax.lax.fori_loop(0, max_iter, body, state)
    if sentinel or capture:
        return X, {k: v for k, v in info.items() if k != "stag_run"}
    return X


# ---------------------------------------------------------------------------
# Checkpointable batched CG: the continuous-batching primitive.
#
# `cg_solve_batched` above runs a whole batch to completion inside one
# fori_loop — the fixed-window serving shape. Continuous batching needs
# the SAME per-lane recurrence exposed at iteration boundaries, so the
# serving broker can admit a new RHS into a free lane and retire a
# finished lane while the other lanes keep iterating. The state below is
# that boundary: one pytree per batch, every field lane-major, every
# lane's algebra independent of every other lane's (the only shared
# computation, the batched operator apply, is lane-diagonal), so an
# admit/retire is a pure per-lane state edit and the frozen-lane `keep`
# discipline of `cg_solve_batched` carries over unchanged.
#
# The recurrence is the p-update-reassociated form the fused engines use
# (p = beta * p_prev + r at the START of the iteration — see
# `fused_cg_solve`): with the unfused composition engine
# (`unfused_batch_engine`) it is the same per-element operation order as
# `cg_solve_batched`, measured bitwise-equal per lane on CPU —
# `cg_solve_batched` stays the parity oracle. A fused engine (e.g.
# ops.kron_cg.kron_batched_engine) slots into the same step function and
# matches to f32 reassociation accuracy instead.
# ---------------------------------------------------------------------------


class BatchedCGState(NamedTuple):
    """One batched CG solve at an iteration boundary. Lane-major
    ((nrhs, ...) arrays / (nrhs,) scalars); `P` is the search direction
    the LAST iteration used (p_{k-1} of the reassociated recurrence),
    `beta` the coefficient the NEXT p-update will apply, `iters` the
    per-lane iteration count since that lane's admission (each lane runs
    exactly its own budget: benchmark rtol=0 semantics per request)."""

    X: jnp.ndarray
    R: jnp.ndarray
    P: jnp.ndarray
    beta: jnp.ndarray
    rnorm: jnp.ndarray
    rnorm0: jnp.ndarray
    done: jnp.ndarray
    iters: jnp.ndarray


def batched_cg_init(B: jnp.ndarray,
                    dot: Callable | None = None) -> BatchedCGState:
    """Fresh state for a padded RHS stack, x0 = 0 (the serving and
    benchmark semantics — `cg_solve_batched(apply, B, 0, ...)` computes
    apply(0) = 0 exactly, so skipping the initial apply is bitwise
    equivalent). All-zero lanes (padding) are born frozen, exactly as in
    `cg_solve_batched`."""
    if dot is None:
        dot = batched_dot
    nrhs = B.shape[0]
    rnorm0 = dot(B, B)
    return BatchedCGState(
        X=jnp.zeros_like(B),
        R=B,
        P=jnp.zeros_like(B),
        beta=jnp.zeros((nrhs,), B.dtype),
        rnorm=rnorm0,
        rnorm0=rnorm0,
        done=rnorm0 == jnp.zeros((), rnorm0.dtype),
        iters=jnp.zeros((nrhs,), jnp.int32),
    )


def unfused_batch_engine(batch_apply: Callable,
                         dot: Callable | None = None) -> Callable:
    """The unfused composition of the fused-engine contract
    `engine(R, P_prev, beta) -> (P, Y, <P, A P>)`: p-update, vmapped
    operator apply and alpha-dot as separate XLA passes. Driving
    `make_batched_cg_step` with this engine reproduces
    `cg_solve_batched` bitwise per lane (same ops, same order — the
    p-update just moved across the loop boundary)."""
    if dot is None:
        dot = batched_dot

    def engine(R, P_prev, beta):
        P = _bcast(beta, P_prev) * P_prev + R
        Y = batch_apply(P)
        return P, Y, dot(P, Y)

    return engine


def make_batched_cg_step(engine: Callable, nreps: int,
                         dot: Callable | None = None,
                         rtol: float = 0.0) -> Callable:
    """One iteration `state -> state` of the batched reassociated CG
    recurrence. Frozen-lane discipline identical to `cg_solve_batched`:
    a done lane's arithmetic is computed and discarded (`keep`), its
    state bit-frozen; a lane freezes when its own `iters` reaches
    `nreps` (each lane gets exactly its request's iteration budget,
    regardless of when it was admitted) or, with rtol > 0, when its
    residual converges. Dead/padding lanes (rnorm0 == 0) produce the
    same 0/0 arithmetic `cg_solve_batched` documents — discarded every
    iteration, never contaminating live lanes."""
    if dot is None:
        dot = batched_dot

    def step(state: BatchedCGState) -> BatchedCGState:
        X, R, P_prev, beta, rnorm, rnorm0, done, iters = state
        P, Y, pdot = engine(R, P_prev, beta)
        alpha = rnorm / pdot
        X1 = X + _bcast(alpha, X) * P
        R1 = R - _bcast(alpha, R) * Y
        rnorm1 = dot(R1, R1)
        beta1 = rnorm1 / rnorm
        iters1 = iters + 1
        new_done = jnp.logical_or(done, iters1 >= jnp.int32(nreps))
        if rtol > 0.0:
            new_done = jnp.logical_or(
                new_done, rnorm1 / rnorm0 < jnp.asarray(rtol * rtol,
                                                        rnorm1.dtype))
        # exact-zero residual = exact convergence: freeze the lane (one
        # more iteration would synthesize NaN from beta = 0/0) — same
        # guard as cg_solve_batched, so the bitwise parity contract
        # between the two holds in the degenerate regime too
        new_done = jnp.logical_or(
            new_done, rnorm1 == jnp.zeros((), rnorm1.dtype))

        def keep(new, old):
            return jnp.where(_bcast(done, old), old, new)

        def keep1(new, old):
            return jnp.where(done, old, new)

        return BatchedCGState(
            X=keep(X1, X),
            R=keep(R1, R),
            P=keep(P, P_prev),
            beta=keep1(beta1, beta),
            rnorm=keep1(rnorm1, rnorm),
            rnorm0=rnorm0,
            done=new_done,
            iters=jnp.where(done, iters, iters1),
        )

    return step


def batched_cg_run(state: BatchedCGState, step: Callable,
                   k: int) -> BatchedCGState:
    """Advance a batched solve by k iteration boundaries (one compiled
    fori_loop; frozen lanes stay frozen, so overshooting a lane's budget
    is harmless)."""
    return jax.lax.fori_loop(0, k, lambda _, s: step(s), state)


def batched_cg_admit(state: BatchedCGState, lane,
                     b: jnp.ndarray) -> BatchedCGState:
    """Admit a new RHS into one lane at an iteration boundary: the lane
    restarts exactly as a fresh `batched_cg_init` lane would (x0 = 0,
    its own rnorm0/iters), so its trajectory is indistinguishable from
    the same RHS solved in a fresh batch — the admit-parity property the
    serving tests assert. Every edit is lane-local; live lanes' state is
    untouched bit-for-bit."""
    rn = inner_product(b, b)
    zero = jnp.zeros_like(b)
    return BatchedCGState(
        X=state.X.at[lane].set(zero),
        R=state.R.at[lane].set(b),
        P=state.P.at[lane].set(zero),
        beta=state.beta.at[lane].set(jnp.zeros((), state.beta.dtype)),
        rnorm=state.rnorm.at[lane].set(rn),
        rnorm0=state.rnorm0.at[lane].set(rn),
        done=state.done.at[lane].set(rn == jnp.zeros((), rn.dtype)),
        iters=state.iters.at[lane].set(jnp.zeros((), jnp.int32)),
    )


def batched_cg_init_warm(B: jnp.ndarray, X0: jnp.ndarray,
                         batch_apply: Callable, rtol: float = 0.0,
                         dot: Callable | None = None) -> BatchedCGState:
    """Fresh state with per-lane warm starts (ISSUE 20, the heat
    workload): x0 = X0, r0 = B - A x0. `rnorm0` is the COLD target
    <B, B> — the rtol budget must measure convergence relative to the
    problem, not relative to the already-small warm residual, or a warm
    lane would be asked for the same relative reduction as a cold one
    and save nothing. With X0 = 0 this is bitwise `batched_cg_init`
    (A 0 = 0 exactly), so cold traffic through the warm path keeps the
    cold trajectory. A lane whose warm residual already meets the rtol
    budget is born frozen (zero iterations burned — the best case the
    savings counter measures)."""
    if dot is None:
        dot = batched_dot
    nrhs = B.shape[0]
    R = B - batch_apply(X0)
    rnorm0 = dot(B, B)
    rnorm = dot(R, R)
    zero = jnp.zeros((), rnorm.dtype)
    done = jnp.logical_or(rnorm0 == zero, rnorm == zero)
    if rtol > 0.0:
        done = jnp.logical_or(
            done, rnorm / rnorm0 < jnp.asarray(rtol * rtol, rnorm.dtype))
    return BatchedCGState(
        X=X0,
        R=R,
        P=jnp.zeros_like(B),
        beta=jnp.zeros((nrhs,), B.dtype),
        rnorm=rnorm,
        rnorm0=rnorm0,
        done=done,
        iters=jnp.zeros((nrhs,), jnp.int32),
    )


def batched_cg_admit_warm(state: BatchedCGState, lane, b: jnp.ndarray,
                          x0: jnp.ndarray, apply: Callable,
                          rtol: float = 0.0) -> BatchedCGState:
    """Admit one RHS with a warm start at an iteration boundary: the
    lane restarts from x0 with r = b - A x0 and the COLD rnorm0 = <b, b>
    (same convention as `batched_cg_init_warm`, so an admitted warm lane
    is indistinguishable from the same request warm-started in a fresh
    batch). With x0 = 0 this reproduces `batched_cg_admit` bitwise
    (plus the admit-time rtol freeze, which a zero warm start can only
    trip when b itself is zero). Every edit is lane-local."""
    r = b - apply(x0)
    rn0 = inner_product(b, b)
    rn = inner_product(r, r)
    zero = jnp.zeros((), rn.dtype)
    done = jnp.logical_or(rn0 == zero, rn == zero)
    if rtol > 0.0:
        done = jnp.logical_or(
            done, rn / rn0 < jnp.asarray(rtol * rtol, rn.dtype))
    zerov = jnp.zeros_like(b)
    return BatchedCGState(
        X=state.X.at[lane].set(x0),
        R=state.R.at[lane].set(r),
        P=state.P.at[lane].set(zerov),
        beta=state.beta.at[lane].set(jnp.zeros((), state.beta.dtype)),
        rnorm=state.rnorm.at[lane].set(rn),
        rnorm0=state.rnorm0.at[lane].set(rn0),
        done=state.done.at[lane].set(done),
        iters=state.iters.at[lane].set(jnp.zeros((), jnp.int32)),
    )


def batched_cg_retire(state: BatchedCGState, lane) -> BatchedCGState:
    """Retire one lane at an iteration boundary: zero its state and mark
    it born-frozen (rnorm0 = 0, the padding-lane convention), freeing
    the lane for a future admit. Lane-local, so live lanes are
    unperturbed bit-for-bit."""
    zero = jnp.zeros_like(state.X[0])
    zs = jnp.zeros((), state.rnorm.dtype)
    return BatchedCGState(
        X=state.X.at[lane].set(zero),
        R=state.R.at[lane].set(zero),
        P=state.P.at[lane].set(zero),
        beta=state.beta.at[lane].set(jnp.zeros((), state.beta.dtype)),
        rnorm=state.rnorm.at[lane].set(zs),
        rnorm0=state.rnorm0.at[lane].set(zs),
        done=state.done.at[lane].set(True),
        iters=state.iters.at[lane].set(jnp.zeros((), jnp.int32)),
    )


def fused_cg_solve_batched(engine: Callable, B: jnp.ndarray, nreps: int,
                           dot: Callable | None = None) -> jnp.ndarray:
    """Whole-batch driver over the checkpointable machinery: init + nreps
    steps, returning X — the batched analogue of `fused_cg_solve`
    (benchmark semantics: x0 = 0, rtol = 0, exactly nreps iterations per
    live lane; padding lanes born frozen). With `unfused_batch_engine`
    this equals `cg_solve_batched` bitwise per lane; with a fused engine
    it matches to f32 reassociation accuracy (<= 1e-7, the serving
    parity contract)."""
    state = batched_cg_init(B, dot=dot)
    step = make_batched_cg_step(engine, nreps, dot=dot)
    return batched_cg_run(state, step, nreps).X


# ---------------------------------------------------------------------------
# df (double-float) batched checkpointable recurrence — closing the PR 6
# remainder: df32 requests could not ride continuous batching because the
# vmapped `cg_solve_df` recurrence was ONE whole-solve executable with no
# iteration boundary. This is the same lane-major state machine as
# `BatchedCGState`, carried in compensated (hi, lo) arithmetic: per-lane
# algebra stays lane-local (the batched operator apply is lane-diagonal,
# every scalar is a per-lane DF pair), so admit/retire remain pure
# per-lane state edits and the frozen-lane `keep` discipline transfers
# unchanged. The recurrence is the p-update-reassociated form (p = beta *
# p_prev + r at the START of the iteration): the identical df op sequence
# as `ops.kron_df.cg_solve_df` moved across the loop boundary, so the
# vmapped whole-solve df executable stays the parity oracle (df-class
# <= 1e-13, the standing serve convention). The df residual-floor freeze
# (rnorm.hi <= 1e-24 * rnorm0.hi, rel residual ~1e-12 — see
# cg_solve_df's docstring) is carried PER LANE next to each lane's own
# iteration budget.
# ---------------------------------------------------------------------------

#: the df64 recurrence's per-lane squared-residual freeze floor
#: (hi-channel, relative): rel residual ~1e-12, cg_solve_df's constant.
DF_BATCH_FLOOR = 1e-24


class BatchedCGStateDF(NamedTuple):
    """One batched df CG solve at an iteration boundary: DF pytrees for
    the lane-major vectors ((nrhs, ...) hi/lo pairs) and per-lane DF
    scalar pairs ((nrhs,)) for the recurrence scalars. `rnorm0_hi` keeps
    only the hi channel — it exists for the floor freeze and the
    born-frozen padding convention (rnorm0 == 0), neither of which needs
    the lo channel."""

    X: object  # DF (nrhs, ...)
    R: object  # DF
    P: object  # DF
    beta: object  # DF (nrhs,)
    rnorm: object  # DF (nrhs,)
    rnorm0_hi: jnp.ndarray  # (nrhs,) f32
    done: jnp.ndarray
    iters: jnp.ndarray


def batched_dot_df(A, B):
    """Per-lane <a, b> as DF (nrhs,) scalars: vmapped `df_dot`, so each
    lane runs the exact compensated reduction order of the scalar df
    solve — the parity contract's foundation."""
    from .df64 import df_dot

    return jax.vmap(df_dot)(A, B)


def batched_cg_init_df(B) -> BatchedCGStateDF:
    """Fresh df state for a padded DF RHS stack (x0 = 0; all-zero lanes
    born frozen, the padding convention of `batched_cg_init`)."""
    from .df64 import DF, df_zeros_like

    rnorm0 = batched_dot_df(B, B)
    nrhs = B.hi.shape[0]
    zscal = DF(jnp.zeros((nrhs,), jnp.float32),
               jnp.zeros((nrhs,), jnp.float32))
    return BatchedCGStateDF(
        X=df_zeros_like(B),
        R=B,
        P=df_zeros_like(B),
        beta=zscal,
        rnorm=rnorm0,
        rnorm0_hi=rnorm0.hi,
        done=rnorm0.hi == jnp.zeros((), rnorm0.hi.dtype),
        iters=jnp.zeros((nrhs,), jnp.int32),
    )


def make_batched_cg_step_df(batch_apply: Callable, nreps: int) -> Callable:
    """One iteration `state -> state` of the batched df recurrence.
    `batch_apply` is the lane-major DF operator apply (e.g.
    `jax.vmap(op.apply)` over a KronLaplacianDF). Frozen-lane discipline
    as in `make_batched_cg_step`; a lane freezes on its own iteration
    budget OR on the df residual floor (the cg_solve_df freeze guard,
    per lane)."""
    from .df64 import df_add, df_div, df_sub

    def step(state: BatchedCGStateDF) -> BatchedCGStateDF:
        X, R, P_prev, beta, rnorm, rnorm0_hi, done, iters = state
        P = df_add(_df_scale_lanes(P_prev, beta), R)
        Y = batch_apply(P)
        pdot = batched_dot_df(P, Y)
        alpha = df_div(rnorm, pdot)
        X1 = df_add(X, _df_scale_lanes(P, alpha))
        R1 = df_sub(R, _df_scale_lanes(Y, alpha))
        rnorm1 = batched_dot_df(R1, R1)
        beta1 = df_div(rnorm1, rnorm)
        iters1 = iters + 1
        floor = jnp.float32(DF_BATCH_FLOOR)
        new_done = jnp.logical_or(done, iters1 >= jnp.int32(nreps))
        new_done = jnp.logical_or(new_done, rnorm1.hi <= floor * rnorm0_hi)

        def keep(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(_bcast(done, o), o, n), new, old)

        def keep1(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(done, o, n), new, old)

        return BatchedCGStateDF(
            X=keep(X1, X),
            R=keep(R1, R),
            P=keep(P, P_prev),
            beta=keep1(beta1, beta),
            rnorm=keep1(rnorm1, rnorm),
            rnorm0_hi=rnorm0_hi,
            done=new_done,
            iters=jnp.where(done, iters, iters1),
        )

    return step


def _df_scale_lanes(A, s):
    """Lane-major DF array times per-lane DF scalars: the batched
    spelling of `df_scale(a, scalar)` — same elementwise df ops, the
    scalar pair broadcast across each lane."""
    from .df64 import DF, df_mul

    shape = (-1,) + (1,) * (A.hi.ndim - 1)
    return df_mul(A, DF(jnp.broadcast_to(s.hi.reshape(shape), A.hi.shape),
                        jnp.broadcast_to(s.lo.reshape(shape),
                                         A.hi.shape)))


def batched_cg_admit_df(state: BatchedCGStateDF, lane,
                        b) -> BatchedCGStateDF:
    """Admit a DF RHS into one lane at an iteration boundary — the lane
    restarts exactly as a fresh `batched_cg_init_df` lane (x0 = 0, its
    own rnorm0/iters); every edit is lane-local."""
    from .df64 import DF, df_dot

    rn = df_dot(b, b)
    zero_hi = jnp.zeros_like(b.hi)
    zs = jnp.zeros((), jnp.float32)

    def set_vec(A, new_hi, new_lo):
        return DF(A.hi.at[lane].set(new_hi), A.lo.at[lane].set(new_lo))

    def set_scal(s, new):
        return DF(s.hi.at[lane].set(new.hi), s.lo.at[lane].set(new.lo))

    return BatchedCGStateDF(
        X=set_vec(state.X, zero_hi, zero_hi),
        R=set_vec(state.R, b.hi, b.lo),
        P=set_vec(state.P, zero_hi, zero_hi),
        beta=set_scal(state.beta, DF(zs, zs)),
        rnorm=set_scal(state.rnorm, rn),
        rnorm0_hi=state.rnorm0_hi.at[lane].set(rn.hi),
        done=state.done.at[lane].set(rn.hi == zs),
        iters=state.iters.at[lane].set(jnp.zeros((), jnp.int32)),
    )


def batched_cg_retire_df(state: BatchedCGStateDF, lane) -> BatchedCGStateDF:
    """Retire one lane: zero its df state and mark it born-frozen
    (rnorm0 = 0, the padding convention), freeing the lane for a future
    admit. Lane-local; live lanes bit-untouched."""
    from .df64 import DF

    zero_hi = jnp.zeros_like(state.X.hi[0])
    zs = jnp.zeros((), jnp.float32)

    def set_vec(A):
        return DF(A.hi.at[lane].set(zero_hi), A.lo.at[lane].set(zero_hi))

    def set_scal(s):
        return DF(s.hi.at[lane].set(zs), s.lo.at[lane].set(zs))

    return BatchedCGStateDF(
        X=set_vec(state.X),
        R=set_vec(state.R),
        P=set_vec(state.P),
        beta=set_scal(state.beta),
        rnorm=set_scal(state.rnorm),
        rnorm0_hi=state.rnorm0_hi.at[lane].set(zs),
        done=state.done.at[lane].set(True),
        iters=state.iters.at[lane].set(jnp.zeros((), jnp.int32)),
    )


def fused_cg_solve(
    engine: Callable,
    b: jnp.ndarray,
    nreps: int,
    update: Callable | None = None,
    inner: Callable | None = None,
) -> jnp.ndarray:
    """Shared driver loop for the fused-engine CG paths (ops.folded_cg and
    ops.kron_cg): `engine(r, p_prev, beta) -> (p, y, <p, A p>)` performs
    the p-update, operator apply and alpha-dot in one fused pass; the
    remaining algebra runs as one XLA elementwise+reduce pass per
    iteration, or through `update(x, p, r, y, alpha) -> (x1, r1,
    <r1, r1>)` when given (ops.kron_cg routes very large problems through
    a chunked pallas update pass this way).

    Benchmark semantics only (x0 = 0, rtol = 0, exactly `nreps`
    iterations — reference cg.hpp:88-91); the recurrence is the reference
    loop with the p-update reassociated to the start of the next
    iteration (p1 = r1 + beta*p0), identical per-element operation
    order. `inner` overrides the inner product (the distributed engine
    passes an owned-dof-masked psum dot)."""
    dot = inner_product if inner is None else inner
    x0 = jnp.zeros_like(b)
    rnorm0 = dot(b, b)

    def body(_, state):
        x, r, p_prev, beta, rnorm = state
        p, y, pdot = engine(r, p_prev, beta)
        alpha = rnorm / pdot
        if update is None:
            x1 = x + alpha * p
            r1 = r - alpha * y
            rnorm1 = dot(r1, r1)
        else:
            x1, r1, rnorm1 = update(x, p, r, y, alpha)
        beta1 = rnorm1 / rnorm
        return (x1, r1, p, beta1, rnorm1)

    state = (x0, b, jnp.zeros_like(b), jnp.zeros((), b.dtype), rnorm0)
    x, *_ = jax.lax.fori_loop(0, nreps, body, state)
    return x
