"""Unpreconditioned CG with fixed iteration count (benchmark semantics).

Mirrors `cg_solve` (/root/reference/src/cg.hpp:89-169) exactly: with
rtol = 0 the loop runs exactly `max_iter` iterations (README.md:163), two
inner products and three axpys per iteration, operator applied to the
search direction each step. The whole loop is one jitted XLA computation
(`lax.fori_loop`), so on TPU there are no per-iteration launch or host
synchronisation costs — the analogue of the reference's requirement of
>= 10M dofs/GPU to hide launch latency (README.md:160-163) largely
disappears.

`dot` is injectable so the distributed path can pass a psum-reducing inner
product while reusing this loop unchanged inside `shard_map`.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from .vector import inner_product


def cg_solve(
    apply_A: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    x0: jnp.ndarray,
    max_iter: int,
    rtol: float = 0.0,
    dot: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray] | None = None,
) -> jnp.ndarray:
    """Solve A x = b; returns x after `max_iter` iterations (rtol=0) or until
    ||r||/||r0|| < rtol. Early termination freezes the state rather than
    exiting the loop, keeping the iteration count static for XLA."""
    if dot is None:
        dot = inner_product

    y = apply_A(x0)
    r = b - y
    p = r
    rnorm0 = dot(p, r)

    def body(_, state):
        x, r, p, rnorm, done = state
        y = apply_A(p)
        alpha = rnorm / dot(p, y)
        x1 = x + alpha * p
        r1 = r - alpha * y
        rnorm_new = dot(r1, r1)
        beta = rnorm_new / rnorm
        p1 = beta * p + r1
        new_done = jnp.logical_or(done, rnorm_new / rnorm0 < rtol * rtol)
        keep = lambda new, old: jnp.where(done, old, new)
        return (
            keep(x1, x),
            keep(r1, r),
            keep(p1, p),
            keep(rnorm_new, rnorm),
            new_done,
        )

    state = (x0, r, p, rnorm0, jnp.asarray(False))
    x, *_ = jax.lax.fori_loop(0, max_iter, body, state)
    return x


def fused_cg_solve(
    engine: Callable,
    b: jnp.ndarray,
    nreps: int,
    update: Callable | None = None,
    inner: Callable | None = None,
) -> jnp.ndarray:
    """Shared driver loop for the fused-engine CG paths (ops.folded_cg and
    ops.kron_cg): `engine(r, p_prev, beta) -> (p, y, <p, A p>)` performs
    the p-update, operator apply and alpha-dot in one fused pass; the
    remaining algebra runs as one XLA elementwise+reduce pass per
    iteration, or through `update(x, p, r, y, alpha) -> (x1, r1,
    <r1, r1>)` when given (ops.kron_cg routes very large problems through
    a chunked pallas update pass this way).

    Benchmark semantics only (x0 = 0, rtol = 0, exactly `nreps`
    iterations — reference cg.hpp:88-91); the recurrence is the reference
    loop with the p-update reassociated to the start of the next
    iteration (p1 = r1 + beta*p0), identical per-element operation
    order. `inner` overrides the inner product (the distributed engine
    passes an owned-dof-masked psum dot)."""
    dot = inner_product if inner is None else inner
    x0 = jnp.zeros_like(b)
    rnorm0 = dot(b, b)

    def body(_, state):
        x, r, p_prev, beta, rnorm = state
        p, y, pdot = engine(r, p_prev, beta)
        alpha = rnorm / pdot
        if update is None:
            x1 = x + alpha * p
            r1 = r - alpha * y
            rnorm1 = dot(r1, r1)
        else:
            x1, r1, rnorm1 = update(x, p, r, y, alpha)
        beta1 = rnorm1 / rnorm
        return (x1, r1, p, beta1, rnorm1)

    state = (x0, b, jnp.zeros_like(b), jnp.zeros((), b.dtype), rnorm0)
    x, *_ = jax.lax.fori_loop(0, nreps, body, state)
    return x
