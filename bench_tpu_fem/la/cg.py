"""Unpreconditioned CG with fixed iteration count (benchmark semantics).

Mirrors `cg_solve` (/root/reference/src/cg.hpp:89-169) exactly: with
rtol = 0 the loop runs exactly `max_iter` iterations (README.md:163), two
inner products and three axpys per iteration, operator applied to the
search direction each step. The whole loop is one jitted XLA computation
(`lax.fori_loop`), so on TPU there are no per-iteration launch or host
synchronisation costs — the analogue of the reference's requirement of
>= 10M dofs/GPU to hide launch latency (README.md:160-163) largely
disappears.

`dot` is injectable so the distributed path can pass a psum-reducing inner
product while reusing this loop unchanged inside `shard_map`.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from .vector import inner_product


def cg_solve(
    apply_A: Callable[[jnp.ndarray], jnp.ndarray],
    b: jnp.ndarray,
    x0: jnp.ndarray,
    max_iter: int,
    rtol: float = 0.0,
    dot: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray] | None = None,
) -> jnp.ndarray:
    """Solve A x = b; returns x after `max_iter` iterations (rtol=0) or until
    ||r||/||r0|| < rtol. Early termination freezes the state rather than
    exiting the loop, keeping the iteration count static for XLA."""
    if dot is None:
        dot = inner_product

    y = apply_A(x0)
    r = b - y
    p = r
    rnorm0 = dot(p, r)

    def body(_, state):
        x, r, p, rnorm, done = state
        y = apply_A(p)
        alpha = rnorm / dot(p, y)
        x1 = x + alpha * p
        r1 = r - alpha * y
        rnorm_new = dot(r1, r1)
        beta = rnorm_new / rnorm
        p1 = beta * p + r1
        new_done = jnp.logical_or(done, rnorm_new / rnorm0 < rtol * rtol)
        keep = lambda new, old: jnp.where(done, old, new)
        return (
            keep(x1, x),
            keep(r1, r),
            keep(p1, p),
            keep(rnorm_new, rnorm),
            new_done,
        )

    state = (x0, r, p, rnorm0, jnp.asarray(False))
    x, *_ = jax.lax.fori_loop(0, max_iter, body, state)
    return x


def batched_dot(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Batched inner product: <a_i, b_i> per RHS over a (nrhs, ...) stack.
    vmap of the scalar `inner_product` rather than a reshape+sum: the
    vmapped dot lowers to the SAME per-lane reduction as the unbatched
    one (measured bitwise-equal on CPU), so an nrhs=1 batched solve
    reproduces `cg_solve` exactly — the parity anchor the serving tests
    assert. A reshape+sum reduction tiles differently and drifts ~1e-6
    (f32) after a few dozen iterations."""
    return jax.vmap(inner_product)(a, b)


def _bcast(flag: jnp.ndarray, like: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a per-RHS (nrhs,) flag against (nrhs, ...) state."""
    return flag.reshape((-1,) + (1,) * (like.ndim - 1))


def cg_solve_batched(
    apply_A: Callable[[jnp.ndarray], jnp.ndarray],
    B: jnp.ndarray,
    X0: jnp.ndarray,
    max_iter: int,
    rtol: float = 0.0,
    dot: Callable[[jnp.ndarray, jnp.ndarray], jnp.ndarray] | None = None,
    batch_apply: Callable[[jnp.ndarray], jnp.ndarray] | None = None,
) -> jnp.ndarray:
    """Multi-RHS CG over a (nrhs, ...) stack: solve A x_i = b_i for every
    RHS in ONE static loop — the serving-layer batch primitive (each
    request contributes one RHS; launch/loop overhead amortises across
    the batch instead of across problem size).

    Same recurrence as `cg_solve`, vectorised across the leading axis:
    the operator is applied through `jax.vmap(apply_A)` (override with
    `batch_apply` when the operator has a natively-batched form, e.g. the
    sharded path, whose psum'd batched dot must also come in via `dot`),
    and both inner products reduce to (nrhs,) vectors in one pass.
    Convergence (rtol > 0) freezes each RHS independently — a converged
    lane's state stops updating while the loop itself stays a fixed-trip
    `fori_loop`, so the computation is one XLA executable for any mix of
    easy and hard right-hand sides.

    All-zero RHS lanes (the batching window's padding) start frozen:
    they return X0 untouched and their 0/0 alpha never contaminates the
    live lanes (`keep` discards the dead lanes' arithmetic every
    iteration)."""
    if dot is None:
        dot = batched_dot
    if batch_apply is None:
        batch_apply = jax.vmap(apply_A)

    Y = batch_apply(X0)
    R = B - Y
    P = R
    rnorm0 = dot(P, R)
    # padding lanes (rnorm0 == 0) are born converged
    done0 = rnorm0 == jnp.zeros((), rnorm0.dtype)

    def body(_, state):
        X, R, P, rnorm, done = state
        Y = batch_apply(P)
        alpha = rnorm / dot(P, Y)
        X1 = X + _bcast(alpha, X) * P
        R1 = R - _bcast(alpha, R) * Y
        rnorm_new = dot(R1, R1)
        beta = rnorm_new / rnorm
        P1 = _bcast(beta, P) * P + R1
        new_done = jnp.logical_or(done, rnorm_new / rnorm0 < rtol * rtol)

        def keep(new, old):
            return jnp.where(_bcast(done, old), old, new)

        return (
            keep(X1, X),
            keep(R1, R),
            keep(P1, P),
            keep(rnorm_new, rnorm),
            new_done,
        )

    state = (X0, R, P, rnorm0, done0)
    X, *_ = jax.lax.fori_loop(0, max_iter, body, state)
    return X


def fused_cg_solve(
    engine: Callable,
    b: jnp.ndarray,
    nreps: int,
    update: Callable | None = None,
    inner: Callable | None = None,
) -> jnp.ndarray:
    """Shared driver loop for the fused-engine CG paths (ops.folded_cg and
    ops.kron_cg): `engine(r, p_prev, beta) -> (p, y, <p, A p>)` performs
    the p-update, operator apply and alpha-dot in one fused pass; the
    remaining algebra runs as one XLA elementwise+reduce pass per
    iteration, or through `update(x, p, r, y, alpha) -> (x1, r1,
    <r1, r1>)` when given (ops.kron_cg routes very large problems through
    a chunked pallas update pass this way).

    Benchmark semantics only (x0 = 0, rtol = 0, exactly `nreps`
    iterations — reference cg.hpp:88-91); the recurrence is the reference
    loop with the p-update reassociated to the start of the next
    iteration (p1 = r1 + beta*p0), identical per-element operation
    order. `inner` overrides the inner product (the distributed engine
    passes an owned-dof-masked psum dot)."""
    dot = inner_product if inner is None else inner
    x0 = jnp.zeros_like(b)
    rnorm0 = dot(b, b)

    def body(_, state):
        x, r, p_prev, beta, rnorm = state
        p, y, pdot = engine(r, p_prev, beta)
        alpha = rnorm / pdot
        if update is None:
            x1 = x + alpha * p
            r1 = r - alpha * y
            rnorm1 = dot(r1, r1)
        else:
            x1, r1, rnorm1 = update(x, p, r, y, alpha)
        beta1 = rnorm1 / rnorm
        return (x1, r1, p, beta1, rnorm1)

    state = (x0, b, jnp.zeros_like(b), jnp.zeros((), b.dtype), rnorm0)
    x, *_ = jax.lax.fori_loop(0, nreps, body, state)
    return x
