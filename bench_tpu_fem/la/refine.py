"""Mixed-precision iterative refinement / flexible PCG (ISSUE 17).

The speed ladder's driver: run the HOT LOOP — every CG operator apply —
on the bf16-stream / f32-accumulate operator (ops.bf16.Bf16Operator, HBM
bytes halved), and recover f64-class answers with a cheap high-precision
outer correction loop:

    r_k = b - A_hi x_k          (one hi-precision apply per OUTER)
    d_k ~ A_lo^{-1} r_k         (inner_iters of [P]CG on the bf16 op)
    x_{k+1} = x_k + d_k         (hi-precision axpy)

Classic iterative refinement with an approximate inner solver: each
outer contracts the error by roughly the inner solve's relative
accuracy (bf16 mantissa ~ 2-3 decimal digits with a few Jacobi-PCG
digits on top), so rel 1e-10 arrives in a handful of outers while the
per-iteration bandwidth bill stays at bf16 width. bf16 keeps f32's
exponent range, so no loss scaling: a 1e-10 residual is still a normal
bf16 number and the inner solve sees it at full (mantissa-limited)
fidelity.

Composes with la.precond Jacobi as FLEXIBLE PCG: the inner solve takes
a diag-inverse and runs preconditioned CG on the bf16 operator (the
preconditioner is f32 outer-loop state, not a streamed operand), so the
creative endpoint — bf16 bandwidth, Jacobi iteration counts, f64-class
answers — is one config.

Evidence contract: `RefineResult.stamp()` carries the inner/outer
iteration split, the rel-residual history, and `time_to_rtol_s` — the
end-to-end adjudicator for cheaper-but-weaker iterations (a precision
that halves bytes but doubles iterations must still win THIS number).
All numbers are cpu-measured until the harness `bf16` agenda stage
re-runs them on hardware. `refine=None` paths touch nothing here: this
module is additive, and la.cg's solve bodies are byte-identical to
pre-PR (the frozen-replica pin).
"""

from __future__ import annotations

import time
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .cg import cg_solve
from .vector import inner_product


class RefineResult(NamedTuple):
    """One refinement solve: the answer plus the evidence split the
    driver stamps (outer/inner iterations, rel history, time-to-rtol)."""

    x: jnp.ndarray
    outer_iters: int
    inner_iters: int            # inner CG budget per outer
    inner_iters_total: int      # outer_iters * inner_iters (all bf16)
    rel_history: tuple          # ||r_k|| / ||b|| per outer check
    achieved_rel: float
    converged: bool
    preconditioned: bool
    wall_s: float
    time_to_rtol_s: float | None

    def stamp(self) -> dict:
        """The `refine` evidence stamp (record extra["refine"])."""
        return {
            "outer_iters": self.outer_iters,
            "inner_iters": self.inner_iters,
            "inner_iters_total": self.inner_iters_total,
            "rel_history": [float(f"{v:.3e}") for v in self.rel_history],
            "achieved_rel": float(self.achieved_rel),
            "converged": bool(self.converged),
            "preconditioned": bool(self.preconditioned),
            "wall_s": round(float(self.wall_s), 6),
            "time_to_rtol_s": (round(float(self.time_to_rtol_s), 6)
                               if self.time_to_rtol_s is not None
                               else None),
        }


@jax.jit
def _residual(op_hi, x, b):
    """(r, <r,r>) in the hi-precision operator's dtype — the one
    non-bf16 apply per outer iteration."""
    r = b - op_hi.apply(x)
    return r, inner_product(r, r)


@partial(jax.jit, static_argnames=("inner_iters",))
def _correct(op_lo, r32, inner_iters):
    return cg_solve(op_lo.apply, r32, jnp.zeros_like(r32), inner_iters)


@partial(jax.jit, static_argnames=("inner_iters",))
def _correct_pc(op_lo, r32, dinv, inner_iters):
    return cg_solve(op_lo.apply, r32, jnp.zeros_like(r32), inner_iters,
                    precond=lambda z: dinv * z)


@jax.jit
def _axpy(x, d):
    return x + jnp.asarray(d, x.dtype)


def refine_solve(
    op_hi,
    op_lo,
    b: jnp.ndarray,
    *,
    rtol: float = 1e-10,
    max_outer: int = 60,
    inner_iters: int = 16,
    dinv: jnp.ndarray | None = None,
) -> RefineResult:
    """Solve A x = b to `rtol` relative residual with ALL hot-loop
    applies on `op_lo` (the bf16-stream operator) and one `op_hi` apply
    per outer for the residual correction.

    `op_hi` sets the answer class: an f64-leaf operator (CPU x64 / TPU
    with x64) gives f64-class outer arithmetic; f32 gives f32-floor
    answers. `dinv` (la.precond Jacobi diag-inverse, f32) arms the
    flexible-PCG inner solve. The loop is host-driven — each step is one
    compiled call, reused across outers — and the per-outer host sync is
    the rel-residual check itself, so the evidence timing is honest."""
    hi_dtype = b.dtype
    bnorm2 = float(_norm2(b))
    bnorm = bnorm2 ** 0.5 if bnorm2 > 0.0 else 1.0
    x = jnp.zeros_like(b)
    pre = dinv is not None
    hist: list = []
    t0 = time.perf_counter()
    time_to_rtol = None
    converged = False
    outer = 0
    for outer in range(max_outer):
        r, rn2 = _residual(op_hi, x, b)
        rel = float(rn2) ** 0.5 / bnorm
        hist.append(rel)
        if rel <= rtol:
            if time_to_rtol is None:
                time_to_rtol = time.perf_counter() - t0
            converged = True
            break
        r32 = jnp.asarray(r, jnp.float32)
        if pre:
            d = _correct_pc(op_lo, r32, dinv, inner_iters)
        else:
            d = _correct(op_lo, r32, inner_iters)
        x = _axpy(x, d)
    wall = time.perf_counter() - t0
    n_out = outer if converged else max_outer
    return RefineResult(
        x=x,
        outer_iters=n_out,
        inner_iters=int(inner_iters),
        inner_iters_total=n_out * int(inner_iters),
        rel_history=tuple(hist),
        achieved_rel=float(hist[-1]) if hist else float("inf"),
        converged=converged,
        preconditioned=pre,
        wall_s=wall,
        time_to_rtol_s=time_to_rtol,
    )


@jax.jit
def _norm2(b):
    return inner_product(b, b)
