"""p-multigrid V-cycle preconditioner across the degree-1..7 family.

The repo already tabulates every degree of the tensor-product Lagrange
family (elements.lagrange / elements.tables); p-multigrid coarsens in
POLYNOMIAL DEGREE on the same cell mesh, so the grid transfer operators
are tiny 1D interpolation matrices — the degree-p_c basis evaluated at
the degree-p_f nodes (`lagrange_eval`), assembled per cell into a global
(N_f, N_c) 1D matrix per axis — that slot straight into the kron
machinery: a 3D prolongation is three per-axis tensordots, exactly like
`ops.kron.banded_apply` but with a rectangular matrix.

Cycle shape (all jit-safe; the level loop unrolls at trace time):

    z  = S r                   # pre-smooth: Chebyshev, zero initial guess
    rc = notbc_c * R (r - A z) # restrict residual, zero Dirichlet rows
    zc = V-cycle(rc)           # recurse; coarsest level: Chebyshev solve
    z += P zc                  # prolongate the coarse correction
    z += S (r - A z)           # post-smooth (same S => symmetric cycle)

with R = P^T (the transpose restriction of a Galerkin-style symmetric
cycle — SPD when the smoother is, and the Chebyshev smoother is a fixed
positive polynomial in D^{-1}A) and per-level operators ASSEMBLED at
their own degree (non-Galerkin but spectrally equivalent: the standard
p-MG construction — the coarse operator is just the same PDE at lower
p, which this codebase builds natively at O(N) cost). Homogeneous
Dirichlet survives the transfers exactly: coarse and fine boundary
nodes coincide geometrically (GLL node sets include the endpoints), so
prolongating a correction that vanishes on the coarse boundary vanishes
on the fine boundary.

Constraints (gated with recorded reasons by the drivers): GLL node sets
(gl_warped/gauss nodes exclude the endpoints, breaking the boundary
argument above), grid-layout operators (kron / xla — the folded layout
has no per-axis tensor structure to transfer through), degree >= 2.

Scalability caveat, measured honestly: p-coarsening never coarsens the
MESH, so the degree-1 bottom level keeps the fine h and its
conditioning still grows like 1/h^2 — the fixed Chebyshev coarse
polynomial that suffices at test scale (iteration counts cut ~3x at
~10k dofs) weakens as the mesh refines (at 200k dofs the V-cycle no
longer beats Jacobi). An h-robust coarse solver (h-multigrid or a
direct coarse solve) is the recorded remainder; `time_to_rtol_s`
adjudicates per problem either way.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable

import numpy as np

from .precond import (
    CHEB_LMIN_FRACTION,
    PrecondBundle,
    estimate_lmax,
    make_chebyshev,
)

#: Chebyshev smoothing steps per pre/post smooth
PMG_SMOOTH_STEPS = 2
#: Chebyshev steps of the coarsest-level solve (a polynomial "solve":
#: fixed and SPD, so the whole cycle stays a fixed linear operator —
#: fixed-iteration CG there would make the cycle nonlinear)
PMG_COARSE_STEPS = 8


def degree_chain(degree: int) -> list[int]:
    """Coarsening schedule: halve the degree down to 1 (7 -> 3 -> 1,
    6 -> 3 -> 1, 3 -> 1, 2 -> 1) — the conventional p-MG ladder."""
    chain = [degree]
    while chain[-1] > 1:
        chain.append(max(1, chain[-1] // 2))
    return chain


def prolongation_1d(nodes_f: np.ndarray, nodes_c: np.ndarray,
                    ncells: int) -> np.ndarray:
    """Global 1D prolongation (N_f, N_c): per cell, the coarse Lagrange
    basis tabulated at the fine nodes (lagrange_eval — both node sets on
    [0, 1]); cells overlap at shared endpoint dofs where the rows from
    both neighbours agree exactly (L_j(0)/L_j(1) are Kronecker deltas on
    endpoint-including node sets), so plain assignment assembles it."""
    from ..elements.lagrange import lagrange_eval

    E = lagrange_eval(nodes_c, nodes_f)  # (nd_f, nd_c)
    Pf, Pc = len(nodes_f) - 1, len(nodes_c) - 1
    Nf, Nc = ncells * Pf + 1, ncells * Pc + 1
    M = np.zeros((Nf, Nc))
    for c in range(ncells):
        M[c * Pf: c * Pf + Pf + 1, c * Pc: c * Pc + Pc + 1] = E
    return M


def restriction_interp_1d(nodes_f: np.ndarray, nodes_c: np.ndarray,
                          ncells: int) -> np.ndarray:
    """Interpolation restriction (N_c, N_f): the FINE basis tabulated at
    the coarse nodes. Not used inside the (transpose-restriction)
    V-cycle — it exists for the exactness check the tests pin:
    restriction_interp @ prolongation == identity on the coarse space
    (interpolating a degree-p_c polynomial up and sampling it back is
    lossless)."""
    return prolongation_1d(nodes_c, nodes_f, ncells)


def tensor3_apply(v, A0, A1, A2):
    """Apply three per-axis matrices to a 3D grid array: out[a,b,c] =
    sum_{ijk} A0[a,i] A1[b,j] A2[c,k] v[i,j,k] — the rectangular
    analogue of the kron operator's banded per-axis contractions."""
    import jax.numpy as jnp

    v = jnp.tensordot(A0, v, axes=(1, 0))
    v = jnp.moveaxis(jnp.tensordot(A1, v, axes=(1, 1)), 0, 1)
    v = jnp.moveaxis(jnp.tensordot(A2, v, axes=(1, 2)), 0, 2)
    return v


@dataclass
class PMGLevel:
    """One multigrid level: its operator apply, Jacobi inverse diagonal,
    smoother interval, and (except on the coarsest level) the per-axis
    prolongation matrices FROM the next-coarser level onto this one."""

    degree: int
    apply_A: Callable
    dinv: object
    lmax: float
    P1d: tuple | None  # 3x (N_this, N_coarser) or None on the coarsest
    notbc: object  # (NX, NY, NZ) float interior mask at this level


def _level_notbc(n, degree, dtype):
    import jax.numpy as jnp

    from ..mesh.dofmap import boundary_dof_marker

    bc = boundary_dof_marker(n, degree)
    return jnp.asarray(~bc, dtype)


def build_pmg_levels(mesh, degree: int, qmode: int, kappa: float, dtype,
                     backend: str, tables_for=None) -> list[PMGLevel]:
    """Assemble the level hierarchy on one box mesh: per degree in the
    chain, the native operator at that degree (kron on uniform meshes,
    xla einsum on general geometry — both grid-layout), its matrix-free
    Jacobi diagonal, a power-method smoother interval, and the 1D
    prolongation matrices up from the next level. GLL node sets only
    (see module docstring)."""
    import jax.numpy as jnp

    from ..elements.tables import build_operator_tables
    from ..ops.laplacian import build_laplacian
    from .precond import jacobi_dinv_general, jacobi_dinv_uniform

    if degree < 2:
        raise ValueError("p-multigrid needs degree >= 2 (no coarser "
                         "level exists below degree 1)")
    chain = degree_chain(degree)
    if tables_for is None:
        tables_for = {}
    levels: list[PMGLevel] = []
    for li, p in enumerate(chain):
        t = tables_for.get(p) or build_operator_tables(p, qmode, "gll")
        op = build_laplacian(mesh, p, qmode, "gll", kappa=kappa,
                             dtype=dtype, tables=t, backend=backend)
        if backend == "kron":
            dinv = jacobi_dinv_uniform(t, mesh.n, kappa, dtype)
        else:
            dinv = jacobi_dinv_general(op.G, t.phi0, t.dphi1, op.bc_mask,
                                       kappa, mesh.n, p)
        lmax = estimate_lmax(op.apply, dinv, dinv.shape, dtype)
        P1d = None
        if li > 0:
            pf, pc = chain[li - 1], p
            tf = tables_for.get(pf) or build_operator_tables(pf, qmode,
                                                             "gll")
            P1d = tuple(
                jnp.asarray(
                    prolongation_1d(np.asarray(tf.nodes1d),
                                    np.asarray(t.nodes1d), na), dtype)
                for na in mesh.n)
            # attach to the FINER level (the transfer lives between the
            # pair; the finer level owns its way down)
            levels[-1].P1d = P1d
        levels.append(PMGLevel(
            degree=p, apply_A=op.apply, dinv=dinv, lmax=lmax, P1d=None,
            notbc=_level_notbc(mesh.n, p, dtype)))
    return levels


def make_vcycle(levels: list[PMGLevel],
                smooth_steps: int = PMG_SMOOTH_STEPS,
                coarse_steps: int = PMG_COARSE_STEPS) -> Callable:
    """The symmetric V-cycle apply `z = M^{-1} r` (jit-safe; levels
    unroll at trace time). Chebyshev pre/post smoothing at every level,
    Chebyshev coarse solve at the bottom; restriction is the transpose
    of the per-axis prolongation with Dirichlet rows re-zeroed."""
    smoothers = []
    for li, lev in enumerate(levels):
        steps = coarse_steps if li == len(levels) - 1 else smooth_steps
        smoothers.append(make_chebyshev(
            lev.apply_A, lev.dinv, lev.lmax,
            lev.lmax / CHEB_LMIN_FRACTION, steps))

    def cycle(li: int, r):
        lev = levels[li]
        if li == len(levels) - 1:
            return smoothers[li](r)
        z = smoothers[li](r)
        res = r - lev.apply_A(z)
        Px, Py, Pz = lev.P1d
        rc = levels[li + 1].notbc * tensor3_apply(res, Px.T, Py.T, Pz.T)
        zc = cycle(li + 1, rc)
        z = z + tensor3_apply(zc, Px, Py, Pz)
        return z + smoothers[li](r - lev.apply_A(z))

    return lambda r: cycle(0, r)


def vcycle_applies_per_iter(degree: int,
                            smooth_steps: int = PMG_SMOOTH_STEPS,
                            coarse_steps: int = PMG_COARSE_STEPS) -> int:
    """Operator applies one V-cycle costs, counted at the FINE level's
    price in the roofline stamp (coarser applies are cheaper; this is
    the honest upper bound the cost model uses): per non-coarse level 2
    smooths of `smooth_steps` Chebyshev applies each (steps - 1 applies
    per smooth, +1 residual each) plus 2 residual applies; the coarse
    level one `coarse_steps` smooth."""
    nlev = len(degree_chain(degree))
    per_smooth = smooth_steps - 1
    return (nlev - 1) * (2 * per_smooth + 2) + (coarse_steps - 1)


def build_pmg_bundle(mesh, degree: int, qmode: int, kappa: float, dtype,
                     backend: str) -> PrecondBundle:
    """Driver-facing factory: levels + V-cycle in one PrecondBundle with
    the setup wall and apply-cost stamps."""
    t0 = time.monotonic()
    levels = build_pmg_levels(mesh, degree, qmode, kappa, dtype, backend)
    apply = make_vcycle(levels)
    setup_s = time.monotonic() - t0
    from .precond import POWER_ITERS

    return PrecondBundle(
        kind="pmg", apply=apply, setup_s=setup_s,
        setup_applies=POWER_ITERS * len(levels),
        applies_per_iter=vcycle_applies_per_iter(degree),
        params={"levels": degree_chain(degree),
                "smooth_steps": PMG_SMOOTH_STEPS,
                "coarse_steps": PMG_COARSE_STEPS,
                "lmax": [round(lv.lmax, 6) for lv in levels]},
        state={"levels": levels})
