"""Vector math (device BLAS-1): the jnp counterparts of the reference's
thrust + MPI_Allreduce vector layer (/root/reference/src/vector.hpp:159-292):
inner product, L2/Linf norms, axpy, scale, copy-free pointwise ops, fill.

Single-chip versions; the distributed layer wraps the reductions with
`lax.psum` / `lax.pmax` over the device mesh (the ICI replacement for
MPI_Allreduce SUM / MAX, vector.hpp:173,211). The CG loop (la.cg) and the
benchmark drivers consume these — the dof layout (grid or folded) never
matters because every operation is elementwise or a full reduction.
"""

from __future__ import annotations

import jax.numpy as jnp


def inner_product(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """<a, b> (reference inner_product, vector.hpp:159-176)."""
    return jnp.vdot(a, b)


def norm(a: jnp.ndarray) -> jnp.ndarray:
    """L2 norm (reference norm(..., l2), vector.hpp:196-209)."""
    return jnp.sqrt(jnp.vdot(a, a))


def norm_linf(a: jnp.ndarray) -> jnp.ndarray:
    """Linf norm (reference norm(..., linf) with MPI_MAX,
    vector.hpp:210-218)."""
    return jnp.max(jnp.abs(a))


def axpy(y: jnp.ndarray, alpha, x: jnp.ndarray) -> jnp.ndarray:
    """y + alpha * x (reference axpy, vector.hpp:228-240; functional — JAX
    arrays are immutable, the caller rebinds)."""
    return y + alpha * x


def scale(a: jnp.ndarray, alpha) -> jnp.ndarray:
    """alpha * a (reference scale, vector.hpp:242-252)."""
    return alpha * a


def pointwise_mult(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Elementwise a * b (reference pointwise_mult, vector.hpp:254-277)."""
    return a * b


def set_value(a: jnp.ndarray, value) -> jnp.ndarray:
    """Fill with a constant (reference set_value, vector.hpp:279-292)."""
    return jnp.full_like(a, value)


def inner_product_compensated(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """<a, b> with Neumaier (compensated) accumulation in the working
    precision: a running sum + error term per lane over a lax.scan, then a
    tree reduction across lanes. For f32 this recovers most of the
    accuracy a wider accumulator would give without any f64 emulation —
    the 'compensated dot' option the precision policy evaluates (the
    reference accumulates per-rank dots in its scalar type T and
    MPI_Allreduces, vector.hpp:159-176; an f32 reference build rounds the
    same way our plain inner_product does).

    Cost: a scan of length N / lane-count — an accuracy tool, not the
    benchmark hot path (CG keeps inner_product)."""
    import jax

    p = a * b
    if p.ndim > 1:
        flat = p.reshape(-1, p.shape[-1])
    else:
        # Pad 1-D inputs up to a multiple of 128 lanes so the scan length is
        # N/128, not N (zeros are exact no-ops for the accumulation).
        lanes = min(p.size, 128) or 1
        pad = (-p.size) % lanes
        flat = jnp.pad(p, (0, pad)).reshape(-1, lanes)

    def body(carry, x):
        s, c = carry
        t = s + x
        c = c + jnp.where(jnp.abs(s) >= jnp.abs(x),
                          (s - t) + x, (x - t) + s)
        return (t, c), None

    zero = jnp.zeros(flat.shape[-1], dtype=flat.dtype)
    (s, c), _ = jax.lax.scan(body, (zero, zero), flat)
    return jnp.sum(s + c)
