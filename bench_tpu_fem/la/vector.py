"""Vector reductions. Single-chip versions; the distributed layer wraps these
with `lax.psum` over the device mesh (the ICI replacement for MPI_Allreduce,
/root/reference/src/vector.hpp:173, cg.hpp:76)."""

from __future__ import annotations

import jax.numpy as jnp


def inner_product(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.vdot(a, b)


def norm(a: jnp.ndarray) -> jnp.ndarray:
    """L2 norm (the reference reports dolfinx::la::norm l2, e.g.
    laplacian_solver.cpp:130-131)."""
    return jnp.sqrt(jnp.vdot(a, a))
