"""Checkpointable CG state: the iteration-boundary form of `la.cg`.

`cg_solve` / `cg_solve_df` run a whole solve inside ONE `fori_loop`
executable — the benchmark shape, but also the shape a preemption kills
at iteration 0: nothing inside the loop is observable, so a killed
process restarts from scratch. This module re-exposes the SAME loop
bodies at iteration boundaries (the continuous-batching move of
`BatchedCGState`, applied to the scalar and df solves) so a solve can be
advanced `k` iterations at a time, its carry fetched to the host,
snapshotted crash-safely (`harness.checkpoint.CheckpointStore`) and
restored into a fresh process.

Parity contract (the restore proof, pinned by tests/test_checkpoint.py):

* the step body is `cg_solve`'s body **verbatim** (same ops, same order
  — not the p-update-reassociated fused recurrence), so a sequence of
  chunked `fori_loop`s over it is bitwise-identical to the single-loop
  solve, and a save/restore round-trip through host numpy (exact: array
  bits move, nothing is recomputed) keeps the continuation bitwise too;
* the df twin mirrors `ops.kron_df.cg_solve_df` the same way (including
  its residual-floor freeze), so checkpointed df solves are bitwise the
  uninterrupted ones;
* overshoot is free: a lane frozen at `max_iter` (or by rtol) keeps its
  state bit-for-bit through any number of extra step calls, so chunk
  sizes need not divide the iteration budget.

Fused whole-solve engines (ops.kron_cg / ops.folded_cg) bake `nreps`
into one executable and expose no boundary — the drivers gate them off
with a recorded reason when checkpointing is requested
(`checkpoint_gate_reason`); the fused *batched* serving path checkpoints
through `BatchedCGState`, whose per-executable envelope is the standing
serve parity contract.

Serialization is generic pytree <-> host-numpy (`state_to_host` /
`state_from_host`): it covers `CGCkptState`, `DFCGCkptState` and
`la.cg.BatchedCGState` (and any future NamedTuple state) without
per-type code; shapes and dtypes are validated on restore so a snapshot
from a different problem can never be silently loaded.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .vector import inner_product


class CGCkptState(NamedTuple):
    """One f32/f64 CG solve at an iteration boundary: exactly
    `cg_solve`'s loop carry plus the boundary bookkeeping (`rnorm0` for
    the rtol test, `iters` so overshot chunks freeze instead of running
    past the budget)."""

    x: jnp.ndarray
    r: jnp.ndarray
    p: jnp.ndarray
    rnorm: jnp.ndarray
    rnorm0: jnp.ndarray
    done: jnp.ndarray
    iters: jnp.ndarray


def cg_ckpt_init(apply_A: Callable, b: jnp.ndarray,
                 x0: jnp.ndarray | None = None,
                 dot: Callable | None = None) -> CGCkptState:
    """`cg_solve`'s preamble, verbatim (y = A x0; r = b - y; p = r)."""
    if dot is None:
        dot = inner_product
    if x0 is None:
        x0 = jnp.zeros_like(b)
    y = apply_A(x0)
    r = b - y
    p = r
    rnorm0 = dot(p, r)
    return CGCkptState(x=x0, r=r, p=p, rnorm=rnorm0, rnorm0=rnorm0,
                       done=jnp.asarray(False),
                       iters=jnp.zeros((), jnp.int32))


def make_cg_ckpt_step(apply_A: Callable, max_iter: int,
                      rtol: float = 0.0,
                      dot: Callable | None = None) -> Callable:
    """One iteration `state -> state`, `cg_solve`'s body verbatim. While
    `iters < max_iter` the select predicate equals `cg_solve`'s `done`,
    so every kept value is bit-identical; past the budget the state
    freezes (overshoot-safe chunking)."""
    if dot is None:
        dot = inner_product

    def step(state: CGCkptState) -> CGCkptState:
        x, r, p, rnorm, rnorm0, done, iters = state
        y = apply_A(p)
        alpha = rnorm / dot(p, y)
        x1 = x + alpha * p
        r1 = r - alpha * y
        rnorm_new = dot(r1, r1)
        beta = rnorm_new / rnorm
        p1 = beta * p + r1
        new_done = jnp.logical_or(done, rnorm_new / rnorm0 < rtol * rtol)
        # cg_solve's exact-zero-residual freeze, mirrored VERBATIM (the
        # bitwise contract): exact convergence must not synthesize NaN
        # out of beta = 0/0 on the next iteration
        new_done = jnp.logical_or(
            new_done, rnorm_new == jnp.zeros((), rnorm_new.dtype))
        hold = jnp.logical_or(done, iters >= jnp.int32(max_iter))
        keep = lambda new, old: jnp.where(hold, old, new)  # noqa: E731
        return CGCkptState(
            x=keep(x1, x),
            r=keep(r1, r),
            p=keep(p1, p),
            rnorm=keep(rnorm_new, rnorm),
            rnorm0=rnorm0,
            done=jnp.where(hold, done, new_done),
            iters=jnp.where(hold, iters, iters + 1),
        )

    return step


def cg_ckpt_run(state, step: Callable, k: int):
    """Advance a checkpointable solve by k iteration boundaries in one
    compiled `fori_loop` (frozen state is held bit-for-bit, so k need
    not divide the remaining budget)."""
    return jax.lax.fori_loop(0, k, lambda _, s: step(s), state)


def true_residual_sq(apply_A: Callable, b, x, dot: Callable | None = None):
    """The SDC audit's ground truth (ISSUE 14): ``‖b − A x‖²``
    recomputed from scratch. At an iteration boundary this must agree
    with the carried ``state.rnorm`` to rounding — a silent corruption
    of the checkpointable carry (a bit-flipped x, r or p) breaks the
    identity and stays broken, which is what the driver's
    boundary-audited checkpointed loop (bench.driver) compares against
    the per-precision envelope before trusting a snapshot enough to
    save it."""
    if dot is None:
        dot = inner_product
    r = b - apply_A(x)
    return dot(r, r)


# ---------------------------------------------------------------------------
# df twin: ops.kron_df.cg_solve_df at iteration boundaries.
# ---------------------------------------------------------------------------


class DFCGCkptState(NamedTuple):
    """df (double-float) CG solve at an iteration boundary — the carry
    of `ops.kron_df.cg_solve_df` (DF vectors/scalars) plus `rnorm0_hi`
    (its closed-over floor reference) and the boundary bookkeeping."""

    x: object  # DF
    r: object  # DF
    p: object  # DF
    rnorm: object  # DF
    rnorm0_hi: jnp.ndarray
    done: jnp.ndarray
    iters: jnp.ndarray


def df_cg_ckpt_init(b) -> DFCGCkptState:
    """`cg_solve_df`'s preamble verbatim: x0 = 0, r = p = b."""
    from .df64 import df_dot, df_zeros_like

    rnorm0 = df_dot(b, b)
    return DFCGCkptState(x=df_zeros_like(b), r=b, p=b, rnorm=rnorm0,
                         rnorm0_hi=rnorm0.hi, done=jnp.asarray(False),
                         iters=jnp.zeros((), jnp.int32))


def make_df_cg_ckpt_step(apply_A: Callable, max_iter: int) -> Callable:
    """One df iteration `state -> state`: `cg_solve_df`'s body verbatim
    — including its residual-floor freeze (rnorm.hi <= 1e-24 * rnorm0.hi)
    — with the overshoot freeze added on top."""
    from .df64 import df_add, df_axpy, df_div, df_dot, df_scale, df_sub

    floor = jnp.float32(1e-24)

    def step(state: DFCGCkptState) -> DFCGCkptState:
        x, r, p, rnorm, rnorm0_hi, done, iters = state
        y = apply_A(p)
        alpha = df_div(rnorm, df_dot(p, y))
        x1 = df_axpy(x, alpha, p)
        r1 = df_sub(r, df_scale(y, alpha))
        rnorm1 = df_dot(r1, r1)
        beta = df_div(rnorm1, rnorm)
        p1 = df_add(df_scale(p, beta), r1)
        done1 = jnp.logical_or(done, rnorm1.hi <= floor * rnorm0_hi)
        hold = jnp.logical_or(done, iters >= jnp.int32(max_iter))

        def keep(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(hold, o, n), new, old)

        return DFCGCkptState(
            x=keep(x1, x), r=keep(r1, r), p=keep(p1, p),
            rnorm=keep(rnorm1, rnorm), rnorm0_hi=rnorm0_hi,
            done=jnp.where(hold, done, done1),
            iters=jnp.where(hold, iters, iters + 1),
        )

    return step


# ---------------------------------------------------------------------------
# Host <-> device serialization (generic over pytree states).
# ---------------------------------------------------------------------------


def state_to_host(state) -> dict[str, np.ndarray]:
    """Flatten a CG state pytree to host numpy arrays keyed by leaf
    index (`leaf_000`, ...). The flatten order is the pytree's — stable
    for a given state type, which `state_from_host` re-derives from its
    template, so no names need to survive in the snapshot."""
    leaves = jax.tree_util.tree_leaves(state)
    return {f"leaf_{i:03d}": np.asarray(leaf)
            for i, leaf in enumerate(leaves)}


def state_from_host(template, arrays: dict[str, np.ndarray]):
    """Rebuild a state of `template`'s type/treedef from `state_to_host`
    output. `template` may hold concrete arrays or
    `jax.ShapeDtypeStruct`s (e.g. from `jax.eval_shape` over the init
    function). Shape/dtype mismatches raise — a snapshot from a
    different problem or precision must never load silently."""
    leaves, treedef = jax.tree_util.tree_flatten(template)
    if len(arrays) != len(leaves):
        raise ValueError(
            f"snapshot has {len(arrays)} leaves, state needs {len(leaves)}")
    out = []
    for i, ref in enumerate(leaves):
        a = arrays[f"leaf_{i:03d}"]
        ref_shape = tuple(ref.shape)
        ref_dtype = np.dtype(ref.dtype)
        if tuple(a.shape) != ref_shape or np.dtype(a.dtype) != ref_dtype:
            raise ValueError(
                f"snapshot leaf {i} is {a.dtype}{a.shape}, state needs "
                f"{ref_dtype}{ref_shape}")
        out.append(jnp.asarray(a))
    return jax.tree_util.tree_unflatten(treedef, out)
