"""Double-float ("df64") arithmetic: f64-class precision from f32 pairs.

TPUs have no f64 hardware; XLA emulates f64 op-by-op and the measured CG
throughput is ~100x below f32 (BENCH artifacts). This module provides the
classical error-free-transformation alternative: a value is an unevaluated
sum hi + lo of two f32 (|lo| <= ulp(hi)/2), giving ~48 significant bits —
enough to track the reference's f64 CG residual behaviour to ~1e-12
(their floor: laplacian_solver.cpp:130-148 norms) at a few tens of f32
flops per op instead of XLA's per-op software emulation.

Algorithms: Knuth two_sum (6 flops, no branches), Dekker split/two_prod
(no FMA assumed — TPU VPU exposes none through XLA), and the standard
double-float add/mul with one renormalisation. All functions are
elementwise on (hi, lo) pairs of equal-shape f32 arrays and jit/vmap
compatible (pure jnp).

References (public domain algorithms): T.J. Dekker, "A floating-point
technique for extending the available precision" (1971); D.E. Knuth,
TAOCP vol. 2. The pair layout mirrors standard double-double libraries
(e.g. QD); no code is derived from them.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Dekker splitter for f32: 2^12 + 1 (24-bit mantissa -> 12 + 12 bits).
_SPLIT = np.float32(4097.0)


def _launder(x):
    """Value-identical but opaque to floating-point pattern rewrites.
    Required for correctness: when the error-free transformations below
    fuse with their producers, the compiler rewrites patterns like
    `a - (a + b)` as real arithmetic, which zeroes the computed rounding
    error and silently degrades every df64 result to ~f32 accuracy
    (measured on XLA:CPU whole-graph compilation; per-op execution is
    unaffected, and no public XLA flag disables it — tests/test_df64.py
    pins the jitted behaviour).

    IMPORTANT: the launder is defense-in-depth, NOT a guarantee. On
    XLA:CPU both known spellings are stripped before late
    simplification (verified in HLO dumps of `after_optimizations`:
    f32->i32->f32 bitcast pairs are folded to the identity, and
    opt-barriers are expanded away), after which fused graphs can still
    rewrite compensation patterns — the banded df contractions of
    ops.kron_cg_df measured a ~1e-8 relative loss from exactly this.
    The guaranteed defense is STRUCTURAL: every term is renormalised
    (two_sum) before it enters an accumulation two_sum — the one form
    measured to survive whole-graph optimisation (see
    ops.kron_cg_df._acc2 and df_sum's docstring). The barrier spelling
    is kept because it is free at run time and may still block earlier
    pipeline phases (and other backends' pipelines) from fusing across
    it."""
    (out,) = jax.lax.optimization_barrier((x,))
    return out


class DF(NamedTuple):
    """Unevaluated sum hi + lo; both f32 arrays of equal shape."""

    hi: jnp.ndarray
    lo: jnp.ndarray


def two_sum(a, b):
    """Error-free a + b: returns (s, err) with s + err == a + b exactly.
    The laundered copies are best-effort rewrite protection (see
    _launder: XLA:CPU strips them, so they are NOT sufficient on their
    own). The load-bearing rule is the CALLER's: renormalise each term
    (a two_sum of the product pair itself is fine) BEFORE accumulating
    it into a running sum — accumulating raw product values measurably
    loses the carries inside larger fused graphs (~1e-8 relative in the
    banded df contractions of ops.kron_cg_df) regardless of laundering,
    while the renorm-first form holds ~1e-15 (see
    ops.kron_cg_df._acc2)."""
    s = a + b
    so = _launder(s)
    bb = _launder(so - a)
    err = (a - (so - bb)) + (b - bb)
    return s, err


def _split(a):
    c = _launder(_SPLIT * a)
    hi = c - (c - a)
    return hi, a - hi


def two_prod(a, b):
    """Error-free a * b (Dekker, no FMA): (p, err), p + err == a*b."""
    p = a * b
    ah, al = _split(a)
    bh, bl = _split(b)
    err = ((ah * bh - p) + ah * bl + al * bh) + al * bl
    return p, err


def _renorm(hi, lo):
    # Full two_sum, not the classic quick-renorm s = hi + lo;
    # lo' = (hi - s) + lo: whole-graph compilation rewrites the quick form
    # to real arithmetic (lo' -> 0) even with a laundered s, silently
    # degrading every df64 product to f32 accuracy. The laundered two_sum
    # is the only renormalisation measured to survive fusion (see
    # _launder; pinned by tests/test_df64.py).
    return DF(*two_sum(hi, lo))


def df_from_f64(a: np.ndarray) -> DF:
    """Host-side split of an f64 array into an (hi, lo) f32 pair."""
    hi = np.asarray(a, np.float32)
    lo = np.asarray(a - np.asarray(hi, np.float64), np.float32)
    return DF(jnp.asarray(hi), jnp.asarray(lo))


def df_to_f64(a: DF) -> np.ndarray:
    return np.asarray(a.hi, np.float64) + np.asarray(a.lo, np.float64)


def df_zeros_like(a: DF) -> DF:
    return DF(jnp.zeros_like(a.hi), jnp.zeros_like(a.lo))


def df_add(a: DF, b: DF) -> DF:
    s, e = two_sum(a.hi, b.hi)
    t, f = two_sum(a.lo, b.lo)
    e = e + t
    s, e = _renorm(s, e)
    e = e + f
    return _renorm(s, e)


def df_neg(a: DF) -> DF:
    return DF(-a.hi, -a.lo)


def df_sub(a: DF, b: DF) -> DF:
    return df_add(a, df_neg(b))


def _prod_terms(a: DF, b: DF):
    """Raw product pair (p, e) with p + e ~= a*b to df accuracy: error-free
    hi product plus the first-order cross terms folded into the error
    channel. The one shared implementation of the mixed df product (df_mul,
    df_dot, and the operator kernels build on it) so the fusion-hazard
    defenses (see _launder) live in exactly one place."""
    p, e = two_prod(a.hi, b.hi)
    return p, e + (a.hi * b.lo + a.lo * b.hi)


def df_mul(a: DF, b: DF) -> DF:
    return _renorm(*_prod_terms(a, b))


def df_div(a: DF, b: DF) -> DF:
    """One Newton refinement of the f32 quotient — ~full df precision."""
    q1 = a.hi / b.hi
    r = df_sub(a, df_mul(DF(q1, jnp.zeros_like(q1)), b))
    q2 = (r.hi + r.lo) / b.hi
    s, e = two_sum(q1, q2)
    return DF(s, e)


def df_sum(a: DF):
    """Full reduction to a scalar DF: a binary tree of full df_add steps
    (log2 N levels of elementwise halving).

    Deliberately NOT the cheaper raw two_sum + lo-carry fold: that
    pattern is destroyed by XLA:CPU's fusion-time simplifications when
    the intermediates are dead (measured: the compensation vanishes and
    the dot degrades to ~f32-pairwise accuracy; the effect disappears if
    the intermediates are returned as outputs). The fully renormalising
    df_add chain survives whole-graph optimisation on every backend
    tested and costs only ~3x the flops of the fragile fold — noise next
    to the apply."""
    x = DF(a.hi.ravel(), a.lo.ravel())
    while x.hi.shape[0] > 1:
        n = x.hi.shape[0]
        m = n // 2
        s = df_add(DF(x.hi[:m], x.lo[:m]), DF(x.hi[m : 2 * m],
                                              x.lo[m : 2 * m]))
        if n % 2:
            s = DF(jnp.concatenate([s.hi, x.hi[-1:]]),
                   jnp.concatenate([s.lo, x.lo[-1:]]))
        x = s
    return DF(x.hi[0], x.lo[0])


def df_dot(a: DF, b: DF):
    """<a, b> as a scalar DF (error-free products, compensated sum)."""
    return df_sum(DF(*_prod_terms(a, b)))


def df_scale(a: DF, s: DF) -> DF:
    """a * scalar-DF s (broadcasts)."""
    return df_mul(a, DF(jnp.broadcast_to(s.hi, a.hi.shape),
                        jnp.broadcast_to(s.lo, a.hi.shape)))


def df_axpy(y: DF, alpha: DF, x: DF) -> DF:
    """y + alpha * x."""
    return df_add(y, df_scale(x, alpha))
