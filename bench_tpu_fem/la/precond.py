"""Matrix-free preconditioners for the CG stack (ROADMAP item 3).

The reference benchmark (PAPER.md L5, cg.hpp) runs *unpreconditioned* CG,
so at scale iteration count — not GDoF/s — dominates wall-clock. Since
PR 10 every CG record stamps `time_to_rtol_s` next to `gdof_per_second`,
making preconditioning directly measurable: a preconditioner wins iff it
reduces iterations-to-rtol by more than its per-iteration cost multiplier.

Three matrix-free preconditioners, all reusing existing machinery:

* **Jacobi** — the operator diagonal WITHOUT the matrix. Two routes,
  cross-checked against the assembled-CSR diagonal
  (fem.assemble.csr_diag_inv, the `--mat_comp` oracle seam):
  - uniform (kron) meshes: diag of a Kronecker sum is the Kronecker sum
    of 1D diagonals — three O(N^(1/3)) host vectors, outer-broadcast on
    device (`jacobi_dinv_uniform`);
  - general geometry: the sum-factorised basis-SQUARED contraction over
    the weighted geometry tensor G (`jacobi_dinv_general`) — the same
    separable structure as the operator apply, with per-axis squared
    (phi^2, dphi^2) and mixed (phi*dphi) 1D tables, folded per cell
    into the dof grid by the existing ops.laplacian.fold_cells scatter.
  Dirichlet rows carry a unit diagonal (assemble_csr semantics), so the
  inverse is finite everywhere.

* **Chebyshev** — a fixed-degree polynomial in the Jacobi-scaled
  operator D^{-1}A, applied with `CHEB_STEPS` extra operator applies per
  PCG iteration (any engine form of the apply composes — it is just a
  callable). The eigenvalue interval comes from a few power-method
  applies (`estimate_lmax`, deterministic seed) with the standard
  smoothing convention lmin = lmax / CHEB_LMIN_FRACTION. Fixed step
  count => a FIXED SPD linear operator, so plain (non-flexible) PCG
  stays valid.

* **p-multigrid** — la.pmg: V-cycle across the degree family with
  Chebyshev smoothing and a bottom-level Chebyshev coarse solve,
  exposed through the same bundle contract.

Evidence discipline: every constructed preconditioner returns a
`PrecondBundle` carrying its setup wall, setup operator-apply count and
parameters — the driver stamps these (`precond` block) so a PCG record
always answers "what did the preconditioner cost to build and what does
it cost per iteration" (obs.roofline.precond_cost).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

#: Chebyshev polynomial degree (extra operator applies per PCG iteration)
CHEB_STEPS = 3
#: assumed lower eigenvalue bound as a fraction of the estimated upper
#: bound — the standard smoothing-interval convention (hypre/MFEM use
#: lmax/30-ish; the polynomial stays positive below lmin, so a true
#: lambda_min under the assumed one costs efficiency, never SPD-ness)
CHEB_LMIN_FRACTION = 30.0
#: safety factor on the power-method estimate (an UNDER-estimated lmax
#: makes the Chebyshev polynomial change sign inside the spectrum)
LMAX_SAFETY = 1.05
#: power-method applies for the eigenvalue bound estimate
POWER_ITERS = 10


@dataclass
class PrecondBundle:
    """One constructed preconditioner: `apply(r) -> z ~= M^{-1} r` plus
    the evidence the driver stamps. `state` is the pytree of device
    arrays the apply closes over (dinv, pmg levels) — kept visible so
    drivers can pass it as an executable ARGUMENT instead of baking
    O(N) arrays into the HLO as constants."""

    kind: str
    apply: Callable
    setup_s: float = 0.0
    setup_applies: int = 0
    applies_per_iter: int = 0
    params: dict = field(default_factory=dict)
    state: dict = field(default_factory=dict)

    def stamp(self) -> dict:
        """The `precond` evidence block (bench records / journal)."""
        return {
            "kind": self.kind,
            "setup_s": round(float(self.setup_s), 6),
            "setup_applies": int(self.setup_applies),
            "applies_per_iter": int(self.applies_per_iter),
            **{k: v for k, v in self.params.items()},
        }


# ---------------------------------------------------------------------------
# Jacobi: the matrix-free operator diagonal.
# ---------------------------------------------------------------------------


def kron_diag_1d(t, n: tuple[int, int, int], with_bc: bool = True):
    """Per-axis 1D diagonals ([dK_x, dK_y, dK_z], [dM_x, dM_y, dM_z],
    masks) of the assembled (and, with `with_bc`, column-masked) 1D
    matrices — O(N^(1/3)) host work, f64. diag(A (x) B) = diag(A) (x)
    diag(B), so these three pairs ARE the uniform operator's diagonal."""
    from ..ops.kron import axis_matrices_1d

    Ks, Ms, masks = axis_matrices_1d(t, n, with_bc=with_bc)
    dK = [np.ascontiguousarray(np.diagonal(K)) for K in Ks]
    dM = [np.ascontiguousarray(np.diagonal(M)) for M in Ms]
    return dK, dM, masks


def jacobi_dinv_uniform(t, n: tuple[int, int, int], kappa: float, dtype):
    """(NX, NY, NZ) inverse diagonal of the uniform (kron) operator,
    computed ON DEVICE from the three 1D diagonal pairs (no O(N) host
    array — the kron path's sizing rationale). Dirichlet dofs read 1.0
    (their effective row is the identity pass-through)."""
    import jax
    import jax.numpy as jnp

    dK, dM, masks = kron_diag_1d(t, n)
    dKj = [jnp.asarray(d, dtype) for d in dK]
    dMj = [jnp.asarray(d, dtype) for d in dM]
    mj = [jnp.asarray(m, dtype) for m in masks]

    def build():
        d = kappa * (
            dKj[0][:, None, None] * dMj[1][None, :, None] * dMj[2][None, None, :]
            + dMj[0][:, None, None] * dKj[1][None, :, None] * dMj[2][None, None, :]
            + dMj[0][:, None, None] * dMj[1][None, :, None] * dKj[2][None, None, :]
        )
        notbc = mj[0][:, None, None] * mj[1][None, :, None] * mj[2][None, None, :]
        one = jnp.ones((), d.dtype)
        return jnp.where(notbc > 0, one / jnp.where(notbc > 0, d, one), one)

    return jax.jit(build)()


def jacobi_dinv_general(G, phi0, dphi1, bc_mask, kappa,
                        n: tuple[int, int, int], degree: int):
    """(NX, NY, NZ) inverse diagonal of the general-geometry operator via
    the sum-factorised basis-squared contraction: d_e[i] = kappa *
    sum_q sum_ab G[c, ab, q] D_a[q, i] D_b[q, i] separates per axis into
    squared (phi^2 / dphi^2) and mixed (phi*dphi) 1D tables (the
    off-diagonal G components appear twice by symmetry), one einsum per
    packed component, folded into the dof grid by the SAME overlap-add
    scatter the operator apply uses (ops.laplacian.fold_cells) — an
    independent path from the assembled-matrix diagonal, which the
    oracle tests cross-check at machine precision. Runs wherever G
    lives (device jnp or host np via jnp.asarray); `G` is the PLAIN
    (ncells, 6, nq, nq, nq) layout (the pallas blocked layout is not
    accepted — callers on that path gate with a recorded reason)."""
    import jax.numpy as jnp

    from ..ops.laplacian import fold_cells

    grid = kappa * fold_cells(jacobi_diag_cells(G, phi0, dphi1), n, degree)
    one = jnp.ones((), grid.dtype)
    bc = jnp.asarray(bc_mask)
    return jnp.where(bc, one, one / jnp.where(bc, one, grid))


def jacobi_diag_cells(G, phi0, dphi1):
    """(ncells, nd, nd, nd) per-cell diagonal contributions — the
    basis-squared contraction shared by the single-chip and sharded
    (seam-folded) diagonal assemblies."""
    import jax.numpy as jnp

    G = jnp.asarray(G)
    phi = jnp.asarray(phi0, G.dtype)  # (nq, nd)
    dphi = jnp.asarray(dphi1, G.dtype) @ phi  # collocation chain, as the apply
    P2, D2, PD = phi * phi, dphi * dphi, phi * dphi

    def term(ab, Ax, Ay, Az, w):
        return w * jnp.einsum("cxyz,xi,yj,zk->cijk", G[:, ab], Ax, Ay, Az)

    return (
        term(0, D2, P2, P2, 1.0) + term(3, P2, D2, P2, 1.0)
        + term(5, P2, P2, D2, 1.0) + term(1, PD, PD, P2, 2.0)
        + term(2, PD, P2, PD, 2.0) + term(4, P2, PD, PD, 2.0)
    )


def jacobi_dinv_dist_local(G_local, phi0, dphi1, bc_local, kappa,
                           n_local: tuple[int, int, int], degree: int):
    """Sharded inverse diagonal, called INSIDE shard_map on one shard's
    block: local per-cell contributions folded into the local grid, seam
    partials completed by the existing ghost-plane collectives (partial
    sums on ghost planes accumulate to their owners via
    reverse_scatter_add, then owners refresh the ghosts via halo_refresh
    so shared planes read identically on every shard)."""
    import jax.numpy as jnp

    from ..dist.halo import halo_refresh, reverse_scatter_add
    from ..ops.laplacian import fold_cells

    grid = kappa * fold_cells(jacobi_diag_cells(G_local, phi0, dphi1),
                              n_local, degree)
    grid = halo_refresh(reverse_scatter_add(grid))
    one = jnp.ones((), grid.dtype)
    return jnp.where(bc_local, one, one / jnp.where(bc_local, one, grid))


def jacobi_dinv_uniform_host(t, n: tuple[int, int, int], kappa: float,
                             np_dtype) -> np.ndarray:
    """Host (numpy) twin of jacobi_dinv_uniform for the sharded kron
    driver, which slices the global grid into overlapping local blocks
    (dist.operator.shard_grid_blocks) before device_put — O(N) host
    memory, acceptable at the CPU-proof and precond-stage scales (the
    flagship-capacity runs are unpreconditioned)."""
    dK, dM, masks = kron_diag_1d(t, n)
    d = kappa * (
        dK[0][:, None, None] * dM[1][None, :, None] * dM[2][None, None, :]
        + dM[0][:, None, None] * dK[1][None, :, None] * dM[2][None, None, :]
        + dM[0][:, None, None] * dM[1][None, :, None] * dK[2][None, None, :]
    )
    notbc = (masks[0][:, None, None] * masks[1][None, :, None]
             * masks[2][None, None, :]) > 0
    return np.where(notbc, 1.0 / np.where(notbc, d, 1.0),
                    1.0).astype(np_dtype)


def op_jacobi_dinv(op):
    """Matrix-free inverse diagonal straight from an operator's own
    state (duck-typed — no driver re-plumbing of tables/meshes):

    * kron (`Kd`/`Md` banded diagonals): the centre band IS the 1D main
      diagonal, so diag(A) is three outer products — O(N) device work;
    * xla Laplacian (plain-layout `G`): the basis-squared contraction
      (`jacobi_dinv_general`);
    * anything else (folded layout, pallas blocked G): None — the
      caller gates with a recorded reason.
    """
    import jax.numpy as jnp

    if hasattr(op, "Kd") and hasattr(op, "notbc1d"):
        P = (op.Kd[0].shape[0] - 1) // 2
        dK = [kd[P] for kd in op.Kd]
        dM = [md[P] for md in op.Md]
        d = op.kappa * (
            dK[0][:, None, None] * dM[1][None, :, None] * dM[2][None, None, :]
            + dM[0][:, None, None] * dK[1][None, :, None] * dM[2][None, None, :]
            + dM[0][:, None, None] * dM[1][None, :, None] * dK[2][None, None, :]
        )
        mx, my, mz = op.notbc1d
        notbc = (mx[:, None, None] * my[None, :, None]
                 * mz[None, None, :]) > 0
        one = jnp.ones((), d.dtype)
        return jnp.where(notbc, one / jnp.where(notbc, d, one), one)
    if getattr(op, "backend", "") == "xla" and hasattr(op, "G"):
        return jacobi_dinv_general(op.G, op.phi0, op.dphi1, op.bc_mask,
                                   op.kappa, op.n, op.degree)
    return None


def make_jacobi(dinv) -> Callable:
    """z = D^{-1} r — one elementwise stream, no extra operator applies."""
    return lambda r: dinv * r


def make_jacobi_df(dinv) -> Callable:
    """df twin: both channels scaled by the f32 inverse diagonal. The
    scaling is an APPROXIMATE df product (no compensation terms) — a
    preconditioner's own rounding only reshapes M, never the answer, so
    the cheap elementwise form is the right one."""
    from .df64 import DF

    return lambda r: DF(dinv * r.hi, dinv * r.lo)


# ---------------------------------------------------------------------------
# Chebyshev: fixed polynomial in the Jacobi-scaled operator.
# ---------------------------------------------------------------------------


def make_chebyshev(apply_A: Callable, dinv, lmax: float,
                   lmin: float | None = None,
                   steps: int = CHEB_STEPS) -> Callable:
    """z = q(D^{-1} A) D^{-1} r with q the degree-`steps` Chebyshev
    polynomial minimising the error on [lmin, lmax] — the classical
    semi-iteration recurrence, unrolled at trace time (steps is small
    and static). Fixed steps => a fixed SPD operator (q > 0 on (0,
    lmax]), so plain PCG needs no flexible variant. Costs `steps - 1`
    extra operator applies per PCG iteration plus `steps` diagonal
    streams; the caller stamps that via PrecondBundle."""
    if lmin is None:
        lmin = lmax / CHEB_LMIN_FRACTION
    theta = 0.5 * (lmax + lmin)
    delta = 0.5 * (lmax - lmin)
    sigma = theta / delta

    def apply(r):
        rhat = dinv * r
        rho = 1.0 / sigma
        d = rhat / theta
        z = d
        for _ in range(steps - 1):
            res = rhat - dinv * apply_A(z)
            rho1 = 1.0 / (2.0 * sigma - rho)
            d = (rho1 * rho) * d + (2.0 * rho1 / delta) * res
            z = z + d
            rho = rho1
        return z

    return apply


def estimate_lmax(apply_A: Callable, dinv, shape, dtype,
                  iters: int = POWER_ITERS, seed: int = 0,
                  norm_fn: Callable | None = None) -> float:
    """Upper eigenvalue bound of D^{-1} A by `iters` power-method
    applies from a fixed-seed start (deterministic — the same problem
    always estimates the same interval), inflated by LMAX_SAFETY.
    `norm_fn` overrides the 2-norm for sharded callers (owned-dof psum
    dot under shard_map); the host loop is setup-phase work, counted
    into the bundle's setup_applies by the caller."""
    import jax
    import jax.numpy as jnp

    if norm_fn is None:
        def norm_fn(v):
            return jnp.sqrt(jnp.sum(v * v))

    step = jax.jit(lambda v: apply_A(dinv * v))
    nrm = jax.jit(norm_fn)
    rng = np.random.RandomState(seed)
    v = jnp.asarray(rng.rand(*shape), dtype)
    lmax = 1.0
    for _ in range(iters):
        w = step(v)
        wn = float(nrm(w))
        vn = float(nrm(v))
        if not (np.isfinite(wn) and wn > 0.0 and vn > 0.0):
            break
        lmax = wn / vn
        v = w / wn
    return float(lmax) * LMAX_SAFETY


# ---------------------------------------------------------------------------
# Bundle factories (the driver-facing seam).
# ---------------------------------------------------------------------------


def build_jacobi_bundle(dinv, *, setup_s: float,
                        extra_params: dict | None = None) -> PrecondBundle:
    return PrecondBundle(
        kind="jacobi", apply=make_jacobi(dinv), setup_s=setup_s,
        setup_applies=0, applies_per_iter=0,
        params=dict(extra_params or {}), state={"dinv": dinv})


def build_chebyshev_bundle(apply_A: Callable, dinv, shape, dtype, *,
                           steps: int = CHEB_STEPS,
                           setup_s_diag: float = 0.0) -> PrecondBundle:
    """Jacobi diagonal + power-method interval + Chebyshev apply in one
    bundle. `setup_s_diag` is the already-paid diagonal-assembly wall so
    the stamped setup cost covers the WHOLE construction."""
    t0 = time.monotonic()
    lmax = estimate_lmax(apply_A, dinv, shape, dtype)
    lmin = lmax / CHEB_LMIN_FRACTION
    setup_s = (time.monotonic() - t0) + setup_s_diag
    return PrecondBundle(
        kind="chebyshev",
        apply=make_chebyshev(apply_A, dinv, lmax, lmin, steps),
        setup_s=setup_s, setup_applies=POWER_ITERS,
        applies_per_iter=steps - 1,
        params={"steps": steps, "lmax": round(lmax, 6),
                "lmin": round(lmin, 8)},
        state={"dinv": dinv})


from ..engines.registry import GATE_REASONS as _GATE_REASONS

#: the recorded reason a driver stamps when a requested preconditioner
#: cannot run on a path (folded layouts, fused engines, action runs) —
#: classified `unsupported` by the harness taxonomy, never silent
#: (texts owned by the registry vocabulary, engines.registry)
PRECOND_GATE_REASONS = {
    "engine": _GATE_REASONS["precond-engine"],
    "action": _GATE_REASONS["precond-action"],
    "folded": _GATE_REASONS["precond-folded"],
    "checkpoint": _GATE_REASONS["precond-checkpoint"],
}
