"""Vector math and the CG solver (layers L3/L5).

Replaces the reference's thrust BLAS-1 + `MPI_Allreduce` dots
(/root/reference/src/vector.hpp:159-292, cg.hpp:21-79) with jnp reductions,
and `cg_solve` (cg.hpp:89-169) with a single jitted `lax.fori_loop` — the
whole CG iteration (halo exchange, operator, two dots, three axpys) is one
XLA computation with no host round-trips.
"""

from .cg import (
    BatchedCGState,
    batched_cg_admit,
    batched_cg_init,
    batched_cg_retire,
    batched_cg_run,
    cg_solve,
    cg_solve_batched,
    fused_cg_solve_batched,
    make_batched_cg_step,
    unfused_batch_engine,
)
from .vector import (
    axpy,
    inner_product,
    inner_product_compensated,
    norm,
    norm_linf,
    pointwise_mult,
    scale,
    set_value,
)

__all__ = [
    "BatchedCGState",
    "axpy",
    "batched_cg_admit",
    "batched_cg_init",
    "batched_cg_retire",
    "batched_cg_run",
    "cg_solve",
    "cg_solve_batched",
    "fused_cg_solve_batched",
    "make_batched_cg_step",
    "unfused_batch_engine",
    "inner_product",
    "inner_product_compensated",
    "norm",
    "norm_linf",
    "pointwise_mult",
    "scale",
    "set_value",
]
