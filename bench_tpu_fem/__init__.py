"""bench-tpu-fem: a TPU-native matrix-free high-order FEM benchmark framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
ukri-bench/benchmark-dolfinx (reference: /root/reference/src): the Poisson
equation -div(kappa grad u) = f on a hexahedral mesh of the unit cube,
discretised with degree 1-7 tensor-product Lagrange elements, applied
matrix-free with sum factorisation, timed as bare operator action or
unpreconditioned CG, reporting GDoF/s, with an assembled-CSR oracle
(`--mat_comp`) as the correctness check.

Layer map (mirrors SURVEY.md section 1):
  elements/  L0 1D quadrature + Lagrange tabulation     (ref: basix usage, laplacian.hpp:123-212)
  mesh/      L1 structured box mesh + tensor dofmap     (ref: mesh.cpp)
  fem/       L2 assembled oracle: CSR, RHS, geometry    (ref: csr.hpp, forms.cpp, geometry_cpu.hpp)
  ops/       L4 matrix-free operator (jnp + Pallas)     (ref: laplacian_gpu.hpp, laplacian.hpp)
  la/        L3/L5 vector math + CG                     (ref: vector.hpp, cg.hpp)
  dist/      SPMD domain decomposition over a TPU mesh  (ref: MPI scatter in vector.hpp, mesh.cpp:26-114)
  bench/     L6 benchmark driver + JSON reporting       (ref: laplacian_solver.cpp, main.cpp)
  cli.py     L7 command line interface                  (ref: main.cpp:144-183)
"""

__version__ = "0.1.0"

# Older-jax API shims (jax.shard_map / lax.pcast names; no-op on current
# jax) — must run before any dist module touches the attributes.
from .utils.jax_compat import apply_compat_shims as _apply_compat_shims

_apply_compat_shims()
del _apply_compat_shims
