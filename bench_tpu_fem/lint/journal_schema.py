"""BF-JRNL: the journal event-schema registry.

Every journaled event in the tree flows through `Journal.append` (the
harness transport) or `Metrics._journal`/`FleetMetrics._journal` (the
serve wrappers around it). This module statically extracts every such
call site's event name + field set and checks them against the committed
`LINT_JOURNAL_SCHEMA.json` registry:

  BF-JRNL001  a site emits an event or field the registry has never
              seen (run `python -m bench_tpu_fem.lint --emit-schema`
              to register it — evolution is additive)
  BF-JRNL002  a site DROPPED a field the registry lists as required
              for its event (two sites emitting the same event with
              incompatible field sets surface as one of them dropping
              the other's required fields)
  BF-JRNL003  the registry carries an event no site emits any more —
              removals are schema edits, never silent code deletions
              (additive-only evolution; full-tree scans only)
  BF-JRNL004  a site the extractor cannot resolve statically (dynamic
              event name, non-literal record) — the coverage self-check
              that makes "the schema covers every site" a theorem
              rather than a hope

Per-site field classification: the record literal's constant keys are
GUARANTEED; later `rec["k"] = ...` stores before the emit are OPTIONAL
(they are almost always conditional — controller stamps, retry hints);
`**spread`/`rec.update(...)` marks the site OPEN (extra fields allowed,
e.g. `serve_phase`'s free-form per-phase payload). The journal envelope
(`v`/`seq`/`ts` stamped by `Journal.append`, `device` by the metrics
wrappers) is registered once, not per event.

The registry file itself evolves through `merge_schema`: new events and
new fields land additively; an event losing a required field or
vanishing outright is REFUSED at generation time so the committed file
can only ever grow (the tuning-DB durability discipline applies on
write: tmp + fsync + os.replace + directory fsync).
"""

from __future__ import annotations

import ast
import json
import os

from .engine import (
    Finding,
    LintContext,
    Source,
    allow_on,
    resolve_dict_arg,
    rule,
)

SCHEMA_VERSION = 1
SCHEMA_BASENAME = "LINT_JOURNAL_SCHEMA.json"
#: fields the transport/wrappers stamp on every record
ENVELOPE_FIELDS = ("v", "seq", "ts", "device")

#: receivers whose .append IS journalling (vs list.append everywhere)
_JOURNAL_RECEIVERS = ("journal", "_journal", "jrnl")
#: the transport itself (stamps the envelope; not an event site)
_TRANSPORT_SUFFIX = os.path.join("harness", "journal.py")


class Site:
    __slots__ = ("event", "guaranteed", "optional", "open", "src", "line")

    def __init__(self, event, guaranteed, optional, open_, src, line):
        self.event = event
        self.guaranteed = frozenset(guaranteed)
        self.optional = frozenset(optional)
        self.open = open_
        self.src = src
        self.line = line


def _is_journal_call(call: ast.Call) -> bool:
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return False
    if fn.attr == "_journal":
        return True
    if fn.attr != "append":
        return False
    recv = fn.value
    if isinstance(recv, ast.Name):
        return recv.id in _JOURNAL_RECEIVERS
    if isinstance(recv, ast.Attribute):
        return recv.attr in _JOURNAL_RECEIVERS
    return False


def _enclosing_functions(tree: ast.Module):
    """(scope_node, call) for every journal call; scope is the tightest
    enclosing def (or the module) — the region variable-assigned
    records are resolved in."""
    out = []

    def walk(node, scope):
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_scope = child
            if isinstance(child, ast.Call) and _is_journal_call(child):
                out.append((child_scope, child))
            walk(child, child_scope)

    walk(tree, tree)
    return out


def _is_forwarder(scope, call: ast.Call) -> bool:
    """`def _journal(self, rec): self.journal.append(rec)` — a wrapper
    forwarding its caller's record to the transport. The real schema
    sites are its callers (matched through the `_journal` attr), so the
    forwarder itself is transport, not an event site."""
    if not isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    if not (call.args and isinstance(call.args[0], ast.Name)):
        return False
    params = {a.arg for a in scope.args.posonlyargs + scope.args.args}
    return call.args[0].id in params


def extract_sites(ctx: LintContext) -> tuple[list[Site], list[Finding]]:
    sites: list[Site] = []
    unresolved: list[Finding] = []
    for src in ctx.sources:
        if src.file.endswith(_TRANSPORT_SUFFIX) and "::" not in src.path:
            continue
        for scope, call in _enclosing_functions(src.tree):
            if call.args and isinstance(call.args[0], ast.Call) and \
                    isinstance(call.args[0].func, ast.Name) and \
                    call.args[0].func.id == "error_record":
                continue  # the taxonomy validator owns that shape
            d, extra, open_ = resolve_dict_arg(scope, call)
            if d is None and _is_forwarder(scope, call):
                continue
            if d is None:
                if allow_on(src, call, "BF-JRNL004"):
                    continue
                unresolved.append(Finding(
                    "BF-JRNL004", "error", src.path, src.real_line(call),
                    "journal emit site not statically resolvable (the "
                    "schema registry cannot cover it); emit a literal "
                    "record or annotate `# lint: allow(BF-JRNL004)` "
                    "with a reason",
                    key=f"BF-JRNL004:{src.path}:"
                        f"{getattr(scope, 'name', '<module>')}"))
                continue
            event = None
            guaranteed = []
            for k, v in zip(d.keys, d.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    guaranteed.append(k.value)
                    if k.value == "event" and \
                            isinstance(v, ast.Constant) and \
                            isinstance(v.value, str):
                        event = v.value
            if "event" not in guaranteed:
                continue  # not an event record (stage bookkeeping etc.)
            if event is None:
                if allow_on(src, call, "BF-JRNL004"):
                    continue
                unresolved.append(Finding(
                    "BF-JRNL004", "error", src.path, src.real_line(call),
                    "journal event name is not a string literal — the "
                    "registry cannot cover a dynamic event",
                    key=f"BF-JRNL004:{src.path}:"
                        f"{getattr(scope, 'name', '<module>')}"))
                continue
            sites.append(Site(event, set(guaranteed) - {"event"},
                              set(extra), open_, src, call.lineno))
    return sites, unresolved


def build_schema(sites: list[Site]) -> dict:
    """Fold sites into the registry shape: per event, required = fields
    every site guarantees, optional = everything else any site may
    stamp, open = any site sprays dynamic fields."""
    events: dict[str, dict] = {}
    for s in sites:
        ev = events.setdefault(s.event, {"required": None,
                                         "optional": set(), "open": False})
        req = set(s.guaranteed)
        ev["required"] = req if ev["required"] is None \
            else ev["required"] & req
        ev["optional"] |= s.guaranteed | s.optional
        ev["open"] = ev["open"] or s.open
    out = {}
    for name, ev in sorted(events.items()):
        req = sorted(ev["required"] or ())
        opt = sorted(ev["optional"] - set(req))
        entry = {"required": req, "optional": opt}
        if ev["open"]:
            entry["open"] = True
        out[name] = entry
    return {"version": SCHEMA_VERSION,
            "envelope": list(ENVELOPE_FIELDS),
            "events": out}


def load_schema(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict) or "events" not in data:
        return None
    return data


def save_schema(path: str, schema: dict) -> None:
    """Tuning-DB durability discipline: tmp + fsync + atomic replace +
    directory fsync, so a torn write can never half-update the
    committed registry."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(schema, fh, indent=1, sort_keys=True)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    dfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                  os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def merge_schema(old: dict | None, new: dict) -> tuple[dict, list[str]]:
    """Additive-only evolution. Returns (merged, refusals): events and
    fields may be ADDED; an event present in `old` but absent from
    `new`, or a required field the new tree no longer guarantees, is a
    refusal — the generation step fails rather than silently shrinking
    the registry."""
    if not old:
        return new, []
    refusals: list[str] = []
    merged_events: dict[str, dict] = {}
    old_events = old.get("events", {})
    new_events = new.get("events", {})
    for name in sorted(set(old_events) | set(new_events)):
        o, n = old_events.get(name), new_events.get(name)
        if n is None:
            refusals.append(
                f"event '{name}' is registered but the tree no longer "
                "emits it (removal is a hand edit, not a regeneration)")
            merged_events[name] = o
            continue
        if o is None:
            merged_events[name] = n
            continue
        lost = sorted(set(o.get("required", ())) - set(n["required"]))
        if lost:
            refusals.append(
                f"event '{name}' dropped required field(s) "
                f"{', '.join(lost)} — journal consumers replay old "
                "rounds; required fields only grow")
        req = sorted(set(o.get("required", ())) | set())
        opt = sorted((set(o.get("optional", ())) | set(n["required"])
                      | set(n["optional"])) - set(req))
        entry = {"required": req, "optional": opt}
        if o.get("open") or n.get("open"):
            entry["open"] = True
        merged_events[name] = entry
    return {"version": SCHEMA_VERSION,
            "envelope": list(ENVELOPE_FIELDS),
            "events": merged_events}, refusals


def _site_findings(site: Site, schema: dict) -> list[Finding]:
    src: Source = site.src
    events = schema.get("events", {})
    entry = events.get(site.event)
    where = f"{src.path}:{site.line}"
    node_like = type("N", (), {"lineno": site.line})
    if allow_on(src, node_like, "BF-JRNL001") or \
            allow_on(src, node_like, "BF-JRNL002"):
        return []
    if entry is None:
        return [Finding(
            "BF-JRNL001", "error", src.path, src.real_line(site.line),
            f"event '{site.event}' is not in the committed "
            f"{SCHEMA_BASENAME}; run `python -m bench_tpu_fem.lint "
            "--emit-schema` to register it",
            key=f"BF-JRNL001:{src.path}:{site.event}")]
    out: list[Finding] = []
    missing = sorted(set(entry.get("required", ())) - site.guaranteed)
    if missing:
        out.append(Finding(
            "BF-JRNL002", "error", src.path, src.real_line(site.line),
            f"event '{site.event}' emitted without required field(s) "
            f"{', '.join(missing)} (registered required: "
            f"{', '.join(entry.get('required', ()))}) at {where}",
            key=f"BF-JRNL002:{src.path}:{site.event}:"
                + ",".join(missing)))
    known = set(entry.get("required", ())) | set(entry.get("optional", ())) \
        | set(ENVELOPE_FIELDS)
    unknown = sorted((site.guaranteed | site.optional) - known)
    if unknown:
        out.append(Finding(
            "BF-JRNL001", "error", src.path, src.real_line(site.line),
            f"event '{site.event}' emits unregistered field(s) "
            f"{', '.join(unknown)}; regenerate the schema "
            "(additive) with --emit-schema",
            key=f"BF-JRNL001:{src.path}:{site.event}:"
                + ",".join(unknown)))
    return out


@rule({
    "BF-JRNL001": "journal event/field not registered in "
                  "LINT_JOURNAL_SCHEMA.json",
    "BF-JRNL002": "journal site drops a field its event registers as "
                  "required",
    "BF-JRNL003": "registered journal event no longer emitted anywhere "
                  "(additive-only schema)",
    "BF-JRNL004": "journal emit site not statically resolvable "
                  "(schema-coverage self-check)",
})
def check_journal_schema(ctx: LintContext):
    sites, findings = extract_sites(ctx)
    schema_path = ctx.schema_path or os.path.join(ctx.root, SCHEMA_BASENAME)
    schema = load_schema(schema_path)
    if schema is None:
        if sites:
            findings.append(Finding(
                "BF-JRNL001", "error", SCHEMA_BASENAME, 1,
                f"committed schema registry missing/unreadable at "
                f"{schema_path} but the tree journals "
                f"{len(sites)} event sites; generate it with "
                "--emit-schema",
                key="BF-JRNL001:schema-missing"))
        return findings
    for site in sites:
        findings.extend(_site_findings(site, schema))
    if ctx.full_scan:
        emitted = {s.event for s in sites}
        for name in sorted(set(schema.get("events", {})) - emitted):
            findings.append(Finding(
                "BF-JRNL003", "error", SCHEMA_BASENAME, 1,
                f"event '{name}' is registered but no site emits it — "
                "either restore the emitter or hand-edit the registry "
                "in the same change that retires its consumers",
                key=f"BF-JRNL003:{name}"))
    return findings
