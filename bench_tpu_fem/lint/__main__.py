"""CLI: the benchfem-lint gate.

    python -m bench_tpu_fem.lint                          # full tree
    python -m bench_tpu_fem.lint --baseline LINT_BASELINE.json
    python -m bench_tpu_fem.lint --json report.json
    python -m bench_tpu_fem.lint path/to/file.py          # scoped scan
    python -m bench_tpu_fem.lint --emit-schema            # (re)register

Exit 0 = no findings beyond the committed baseline; 1 otherwise — every
rc-1 line names rule id + file:line, the perfgate discipline. Scoped
scans (explicit paths) skip the whole-tree cross-checks (BF-JRNL003
orphans, BF-CNTR both directions) that only mean something over the
full package.

--emit-schema regenerates LINT_JOURNAL_SCHEMA.json from the tree,
merging ADDITIVELY into the committed file: new events/fields land,
removals are refused with rc 1 (hand-edit the registry in the change
that retires the consumers, or fix the emitter).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (
    RULE_CATALOG,
    apply_baseline,
    build_schema,
    extract_sites,
    load_baseline,
    load_context,
    merge_schema,
    run_lint,
    save_schema,
)
from .engine import repo_root
from .journal_schema import SCHEMA_BASENAME


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m bench_tpu_fem.lint")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: the package + "
                         "scripts/perfgate.py; explicit paths disable "
                         "the whole-tree cross-checks)")
    ap.add_argument("--baseline", default="", metavar="PATH",
                    help="LINT_BASELINE.json — findings matching a "
                         "committed entry are suppressed")
    ap.add_argument("--json", default="", metavar="PATH",
                    help="write the machine-readable report here "
                         "('-' = stdout)")
    ap.add_argument("--schema", default="", metavar="PATH",
                    help=f"journal schema registry (default: "
                         f"<repo>/{SCHEMA_BASENAME})")
    ap.add_argument("--emit-schema", action="store_true",
                    help="regenerate the journal schema registry "
                         "(additive merge; refuses removals)")
    ap.add_argument("--root", default="", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    root = args.root or repo_root()
    schema_path = args.schema or os.path.join(root, SCHEMA_BASENAME)

    if args.emit_schema:
        return _emit_schema(args.paths or None, root, schema_path)

    findings = run_lint(args.paths or None, root=root,
                        schema_path=schema_path)
    suppressed, stale = [], []
    if args.baseline:
        bl = load_baseline(args.baseline)
        findings, suppressed, stale = apply_baseline(findings, bl)

    if args.json:
        report = {
            "lint_version": 1,
            "findings": [f.as_dict() for f in findings],
            "suppressed": [f.as_dict() for f in suppressed],
            "stale_baseline_keys": stale,
            "rules": dict(sorted(RULE_CATALOG.items())),
        }
        text = json.dumps(report, indent=1, sort_keys=True)
        if args.json == "-":
            print(text)
        else:
            with open(args.json, "w", encoding="utf-8") as fh:
                fh.write(text + "\n")

    for f in findings:
        print(f.render())
    if suppressed:
        print(f"benchfem-lint: {len(suppressed)} finding(s) suppressed "
              f"by baseline {args.baseline}")
    for key in stale:
        print(f"benchfem-lint: stale baseline entry (fixed — remove "
              f"it): {key}")
    errors = [f for f in findings if f.severity == "error"]
    warnings = [f for f in findings if f.severity != "error"]
    print(f"benchfem-lint: {len(errors)} error(s), "
          f"{len(warnings)} warning(s)"
          + (" beyond baseline" if args.baseline else ""))
    return 1 if findings else 0


def _emit_schema(paths, root: str, schema_path: str) -> int:
    from .journal_schema import load_schema

    ctx, findings = load_context(paths, root=root, schema_path=schema_path)
    sites, unresolved = extract_sites(ctx)
    for f in findings + unresolved:
        print(f.render())
    if unresolved:
        print("benchfem-lint: refusing to emit a schema over "
              "unresolvable sites")
        return 1
    fresh = build_schema(sites)
    merged, refusals = merge_schema(load_schema(schema_path), fresh)
    for r in refusals:
        print(f"benchfem-lint: schema refusal: {r}")
    if refusals:
        return 1
    save_schema(schema_path, merged)
    n_ev = len(merged.get("events", {}))
    print(f"benchfem-lint: {schema_path}: {n_ev} events over "
          f"{len(sites)} sites")
    return 0


if __name__ == "__main__":
    sys.exit(main())
