"""BF-RACE: guarded-by race rules.

BF-RACE001 — per-class guarded-attribute analysis. For every class the
scan sees, the rule (a) collects its lock-like fields (`threading.Lock`
/ `RLock` / `Condition`, assigned in a method or declared as a dataclass
`field(default_factory=threading.Lock)`), (b) infers the guarded set of
each lock as the attributes WRITTEN at least once inside a
`with self.<lock>:` body anywhere in the class (read-only config that
merely appears under a lock is not state the lock protects), plus any
attribute annotated `# guarded-by: <lock>`, then (c) flags reads and
writes of guarded attributes outside the lock in methods reachable from
a thread entry point. Entry points are `threading.Thread(target=...)`
sites anywhere in the scan (worker/balancer loops, disposable solve
threads, closures handed to Thread) plus functions annotated
`# lint: thread-entry` (HTTP handler surface, cache-builder callbacks —
call paths a static graph cannot see). Reachability propagates through
`self.method()` calls, bare same-module calls, and one level of typed
attribute calls (`self.metrics.batch()` follows into `Metrics.batch`
when `__init__` assigned `self.metrics = Metrics(...)`).

Construction is exempt: `__init__`/`__post_init__` and methods whose
only intra-class callers are exempt methods run before the object is
published to other threads.

One level of cross-object checking rides the same type inference: a
read/write of `self.<attr>.<field>` where `<attr>`'s inferred class
guards `<field>` is flagged unless the access sits inside
`with self.<attr>.<lock>:` — the shape of the fleet-reads-FleetMetrics
counters bug this rule was built to catch.

BF-RACE002 — module-scope fan-out: a module-global mutated inside a
function that a module-level `threading.Thread(target=...)` site starts,
without holding a module-level lock. This is the agenda stage-code
shape (`SERVE_SMOKE`'s 64-thread `fire` loop appending to a shared
list), which the engine lints through the embedded-source extractor.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .engine import (
    Finding,
    LintContext,
    Source,
    allow_on,
    dotted_name,
    guarded_by_annotation,
    rule,
    thread_entry_annotation,
)

LOCK_FACTORIES = ("Lock", "RLock", "Condition")
#: method names that mutate their receiver in place
MUTATORS = frozenset((
    "append", "extend", "add", "insert", "remove", "discard", "pop",
    "popleft", "appendleft", "clear", "update", "setdefault",
    "heappush", "heapreplace", "sort",
))
EXEMPT_METHODS = frozenset((
    "__init__", "__post_init__", "__repr__", "__str__", "__del__",
))


def _is_lock_factory(call: ast.AST) -> bool:
    if not isinstance(call, ast.Call):
        return False
    name = dotted_name(call.func)
    return name.split(".")[-1] in LOCK_FACTORIES and (
        "." not in name or name.startswith("threading."))


@dataclass
class Access:
    attr: str  # "x" for self.x, "metrics.x" for self.metrics.x
    line: int
    write: bool
    held: frozenset  # lock path strings held at the access
    fn: ast.AST  # enclosing function node


@dataclass
class ClassInfo:
    name: str
    src: Source
    node: ast.ClassDef
    locks: set[str] = field(default_factory=set)
    methods: dict[str, ast.AST] = field(default_factory=dict)
    attr_types: dict[str, str] = field(default_factory=dict)
    annotated: dict[str, str] = field(default_factory=dict)
    accesses: list[Access] = field(default_factory=list)
    # intra-class call sites: (callee name, locks held, caller fn node)
    calls: list[tuple] = field(default_factory=list)
    # attr -> lock name it was written under at least once
    written_under: dict[str, str] = field(default_factory=dict)

    def guard_of(self, attr: str) -> str | None:
        return self.annotated.get(attr) or self.written_under.get(attr)


def _self_attr(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _self_attr2(node: ast.AST) -> str | None:
    """'metrics.x' for self.metrics.x, else None."""
    if isinstance(node, ast.Attribute):
        base = _self_attr(node.value)
        if base is not None:
            return f"{base}.{node.attr}"
    return None


class _MethodWalker(ast.NodeVisitor):
    """Records self-attribute accesses with the lock set held at each,
    resetting the held set inside nested defs (a closure body runs on
    whatever thread calls it, not under the locks of its definition
    site)."""

    def __init__(self, info: ClassInfo, fn: ast.AST):
        self.info = info
        self.fn = fn
        self.held: tuple[str, ...] = ()
        self._writes: set[int] = set()  # id() of nodes in store context

    # -- lock scopes -----------------------------------------------------
    def visit_With(self, node: ast.With):
        added = []
        for item in node.items:
            path = dotted_name(item.context_expr)
            if path.startswith("self."):
                added.append(path)
        for expr in (i.context_expr for i in node.items):
            self.visit(expr)
        self.held = self.held + tuple(added)
        for stmt in node.body:
            self.visit(stmt)
        if added:
            self.held = self.held[:len(self.held) - len(added)]

    # -- nested functions: fresh lock context, same recorder -------------
    def visit_FunctionDef(self, node: ast.FunctionDef):
        if node is self.fn:
            self.generic_visit(node)
            return
        _MethodWalker(self.info, node).visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        outer = self.held
        self.held = ()
        self.generic_visit(node)
        self.held = outer

    # -- access classification -------------------------------------------
    def _record(self, node: ast.Attribute, write: bool):
        attr = _self_attr(node) or _self_attr2(node)
        if attr is None:
            return
        self.info.accesses.append(Access(
            attr=attr, line=node.lineno, write=write,
            held=frozenset(self.held), fn=self.fn))

    def visit_Assign(self, node: ast.Assign):
        for tgt in node.targets:
            self._mark_store(tgt)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        self._mark_store(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign):
        if node.target is not None:
            self._mark_store(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for tgt in node.targets:
            self._mark_store(tgt)
        self.generic_visit(node)

    def _mark_store(self, tgt: ast.AST):
        if isinstance(tgt, ast.Attribute):
            self._writes.add(id(tgt))
        elif isinstance(tgt, ast.Subscript) and \
                isinstance(tgt.value, ast.Attribute):
            self._writes.add(id(tgt.value))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._mark_store(el)

    def visit_Call(self, node: ast.Call):
        # self.attr.append(...) is a WRITE of self.attr; self.m(...) is
        # a call edge (not an attribute access of m)
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in MUTATORS and isinstance(fn.value, ast.Attribute):
                self._record(fn.value, write=True)
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    self.visit(arg)
                return
            if _self_attr(fn) is not None or _self_attr2(fn) is not None:
                # method call: skip the func chain, visit args only.
                # self.m() call sites also feed caller-held-lock
                # propagation (the called-under-lock helper pattern)
                if _self_attr(fn) is not None:
                    self.info.calls.append(
                        (fn.attr, frozenset(self.held), self.fn))
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    self.visit(arg)
                return
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        attr2 = _self_attr2(node)
        if attr2 is not None:
            self._record(node, write=id(node) in self._writes)
            return  # don't double-record the inner self.attr read
        if _self_attr(node) is not None:
            self._record(node, write=id(node) in self._writes)
            return
        self.generic_visit(node)


def _collect_class(src: Source, node: ast.ClassDef,
                   class_names: set[str]) -> ClassInfo:
    info = ClassInfo(name=node.name, src=src, node=node)
    is_dataclass = any("dataclass" in dotted_name(d) or
                       (isinstance(d, ast.Call) and
                        "dataclass" in dotted_name(d.func))
                       for d in node.decorator_list)
    for stmt in node.body:
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name):
            name = stmt.target.id
            ann = ast.unparse(stmt.annotation) if stmt.annotation else ""
            val = stmt.value
            if is_dataclass and isinstance(val, ast.Call) and \
                    dotted_name(val.func).endswith("field"):
                for kw in val.keywords:
                    if kw.arg == "default_factory" and \
                            dotted_name(kw.value).split(".")[-1] \
                            in LOCK_FACTORIES:
                        info.locks.add(name)
            if any(lk in ann for lk in LOCK_FACTORIES):
                info.locks.add(name)
            g = guarded_by_annotation(src, stmt.lineno)
            if g:
                info.annotated[name] = g
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            info.methods[stmt.name] = stmt
    # lock fields + attribute types + guarded-by comments in methods
    for meth in info.methods.values():
        for sub in ast.walk(meth):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                attr = _self_attr(sub.targets[0])
                if attr is None:
                    continue
                if _is_lock_factory(sub.value):
                    info.locks.add(attr)
                g = guarded_by_annotation(src, sub.lineno)
                if g:
                    info.annotated[attr] = g
                for cand in _constructor_classes(sub.value):
                    if cand in class_names:
                        info.attr_types[attr] = cand
    return info


def _constructor_classes(value: ast.AST):
    """Class names a `self.x = ...` value may construct: direct calls
    plus `arg or ClassName(...)` fallbacks."""
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, ast.Call):
            name = dotted_name(v.func).split(".")[-1]
            if name and name[0].isupper():
                yield name
        elif isinstance(v, ast.BoolOp):
            stack.extend(v.values)
        elif isinstance(v, ast.IfExp):
            stack.extend((v.body, v.orelse))


def _infer_guards(info: ClassInfo):
    for acc in info.accesses:
        if "." in acc.attr or not acc.write:
            continue
        for lock_path in acc.held:
            lock = lock_path[len("self."):]
            if lock in info.locks and acc.attr not in info.locks:
                info.written_under.setdefault(acc.attr, lock)


# -------------------------------------------------------------------------
# Thread-entry reachability over a package-wide call graph.

def _fn_index(ctx: LintContext):
    """(source, class_name|None, fn_node) for every def in the scan,
    plus name indexes for edge resolution."""
    fns = []
    by_class: dict[tuple[str, str], ast.AST] = {}
    module_fns: dict[tuple[str, str], ast.AST] = {}
    for src in ctx.sources:
        in_class: set[int] = set()
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                for stmt in node.body:
                    if isinstance(stmt,
                                  (ast.FunctionDef, ast.AsyncFunctionDef)):
                        by_class[(node.name, stmt.name)] = stmt
                        # closures inherit the enclosing class — their
                        # bodies reference the method's `self`
                        for sub in ast.walk(stmt):
                            if isinstance(sub, (ast.FunctionDef,
                                                ast.AsyncFunctionDef)):
                                fns.append((src, node.name, sub))
                                in_class.add(id(sub))
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                module_fns[(src.path, node.name)] = node
        # nested defs (closures) outside classes get their own nodes
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and id(node) not in in_class:
                fns.append((src, None, node))
    # dedupe by node identity
    seen, out = set(), []
    for rec in fns:
        if id(rec[2]) not in seen:
            seen.add(id(rec[2]))
            out.append(rec)
    return out, by_class, module_fns


def _thread_targets(src: Source, fn_node: ast.AST, cls: str | None,
                    local_defs: dict[str, ast.AST]):
    """Entry designators found inside one function: ('method', cls, name)
    or ('node', def_node)."""
    for node in ast.walk(fn_node):
        if not (isinstance(node, ast.Call)
                and dotted_name(node.func).split(".")[-1] == "Thread"):
            continue
        for kw in node.keywords:
            if kw.arg != "target":
                continue
            tattr = _self_attr(kw.value)
            if tattr is not None and cls is not None:
                yield ("method", cls, tattr)
            elif isinstance(kw.value, ast.Name):
                if kw.value.id in local_defs:
                    yield ("node", local_defs[kw.value.id])
                else:
                    yield ("modfn", src.path, kw.value.id)


def _reachable_fns(ctx: LintContext, classes: list[ClassInfo]
                   ) -> tuple[set[int], set[int]]:
    fns, by_class, module_fns = _fn_index(ctx)
    attr_types = {(c.name): c.attr_types for c in classes}
    class_of_fn = {id(f): c for (s, c, f) in fns}
    src_of_fn = {id(f): s for (s, c, f) in fns}

    def local_defs(fn_node):
        return {sub.name: sub for sub in ast.walk(fn_node)
                if sub is not fn_node and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef))}

    # --- seed entries ---
    entries: list[ast.AST] = []
    for src, cls, fn in fns:
        if thread_entry_annotation(src, fn):
            entries.append(fn)
        for tgt in _thread_targets(src, fn, cls, local_defs(fn)):
            if tgt[0] == "method" and (tgt[1], tgt[2]) in by_class:
                entries.append(by_class[(tgt[1], tgt[2])])
            elif tgt[0] == "node":
                entries.append(tgt[1])
            elif tgt[0] == "modfn" and (tgt[1], tgt[2]) in module_fns:
                entries.append(module_fns[(tgt[1], tgt[2])])
    # module-level Thread(...) sites (embedded stage code)
    for src in ctx.sources:
        mdefs = {n.name: n for n in src.tree.body
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        fn_ids = {id(sub) for fn in mdefs.values() for sub in ast.walk(fn)}
        for node in ast.walk(src.tree):
            if id(node) in fn_ids:
                continue
            if isinstance(node, ast.Call) and \
                    dotted_name(node.func).split(".")[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target" and \
                            isinstance(kw.value, ast.Name) and \
                            kw.value.id in mdefs:
                        entries.append(mdefs[kw.value.id])

    # --- edges ---
    def edges(fn_node):
        cls = class_of_fn.get(id(fn_node))
        src = src_of_fn.get(id(fn_node))
        ldefs = local_defs(fn_node)
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            a1 = _self_attr(f)
            if a1 is not None and cls is not None:
                tgt = by_class.get((cls, a1))
                if tgt is not None:
                    yield tgt
                continue
            a2 = _self_attr2(f)
            if a2 is not None and cls is not None:
                base, meth = a2.split(".", 1)
                typ = attr_types.get(cls, {}).get(base)
                if typ is not None:
                    tgt = by_class.get((typ, meth))
                    if tgt is not None:
                        yield tgt
                continue
            if isinstance(f, ast.Name):
                if f.id in ldefs:
                    yield ldefs[f.id]
                elif src is not None and (src.path, f.id) in module_fns:
                    yield module_fns[(src.path, f.id)]
                else:
                    # cross-module bare call: match by name (an
                    # over-approximation — it can only widen the set of
                    # methods the rule checks)
                    for (path, name), tgt in module_fns.items():
                        if name == f.id:
                            yield tgt

    reachable: set[int] = set()
    work = list(entries)
    while work:
        fn = work.pop()
        if id(fn) in reachable:
            continue
        reachable.add(id(fn))
        for tgt in edges(fn):
            if id(tgt) not in reachable:
                work.append(tgt)
    return reachable, {id(e) for e in entries}


def _caller_held(info: ClassInfo, method_of_fn: dict[int, str],
                 entry_ids: set[int]) -> dict[str, frozenset]:
    """Locks provably held at EVERY intra-class call site of a method —
    the called-under-lock helper pattern (`Broker._gather` holds `_cv`
    around `_take_compatible`, which touches `_queue` with no `with` of
    its own). A method that is itself a thread entry (Thread target or
    `# lint: thread-entry`) never inherits: it has an unlocked caller
    the static graph can't see. Fixpoint so helpers of helpers inherit
    transitively; the sets only grow, so it converges."""
    held: dict[str, frozenset] = {}
    for _ in range(len(info.methods) + 1):
        changed = False
        sites: dict[str, list[frozenset]] = {}
        for callee, h, fn in info.calls:
            if callee not in info.methods:
                continue
            caller = method_of_fn.get(id(fn))
            eff = h | held.get(caller, frozenset())
            sites.setdefault(callee, []).append(eff)
        for m, hs in sites.items():
            if id(info.methods[m]) in entry_ids:
                continue
            common = frozenset.intersection(*hs)
            if common != held.get(m, frozenset()):
                held[m] = common
                changed = True
        if not changed:
            break
    return held


def _construction_only(info: ClassInfo) -> set[str]:
    """Methods only ever called (intra-class) from exempt methods —
    the `__init__ -> _load -> _count_corrupt` chains run before the
    object escapes to other threads."""
    callers: dict[str, set[str]] = {m: set() for m in info.methods}
    for mname, meth in info.methods.items():
        for node in ast.walk(meth):
            if isinstance(node, ast.Call):
                callee = _self_attr(node.func)
                if callee in callers:
                    callers[callee].add(mname)
    exempt = set(EXEMPT_METHODS)
    changed = True
    while changed:
        changed = False
        for m, cs in callers.items():
            if m in exempt or not cs:
                continue
            if all(c in exempt for c in cs):
                exempt.add(m)
                changed = True
    return exempt - EXEMPT_METHODS | {m for m in info.methods
                                      if m in EXEMPT_METHODS}


@rule({
    "BF-RACE001": "guarded attribute accessed outside its lock on a "
                  "thread-reachable path",
    "BF-RACE002": "module-global mutated in a threading.Thread target "
                  "without a module-level lock",
})
def check_races(ctx: LintContext):
    classes: list[ClassInfo] = []
    class_names: set[str] = set()
    for src in ctx.sources:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                class_names.add(node.name)
    for src in ctx.sources:
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                classes.append(_collect_class(src, node, class_names))
    for info in classes:
        for meth in info.methods.values():
            _MethodWalker(info, meth).visit(meth)
        _infer_guards(info)
    guards_by_class = {c.name: c for c in classes}
    reachable, entry_ids = _reachable_fns(ctx, classes)

    findings: list[Finding] = []
    for info in classes:
        if not info.locks and not info.annotated:
            continue
        exempt = _construction_only(info)
        method_of_fn = {}
        for mname, meth in info.methods.items():
            for sub in ast.walk(meth):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    method_of_fn[id(sub)] = mname
        caller_held = _caller_held(info, method_of_fn, entry_ids)
        for acc in info.accesses:
            mname = method_of_fn.get(id(acc.fn))
            if mname in exempt:
                continue
            if id(acc.fn) not in reachable:
                continue
            # only the method body proper inherits caller-held locks —
            # a closure defined inside it may run on any thread
            inherited = caller_held.get(mname, frozenset()) \
                if acc.fn is info.methods.get(mname) else frozenset()
            eff_held = acc.held | inherited
            if "." in acc.attr:
                base, attr2 = acc.attr.split(".", 1)
                typ = info.attr_types.get(base)
                other = guards_by_class.get(typ) if typ else None
                lock = other.guard_of(attr2) if other else None
                if lock is None or attr2 in (other.locks if other else ()):
                    continue
                need = f"self.{base}.{lock}"
                if any(h == need for h in eff_held):
                    continue
                node_like = type("N", (), {"lineno": acc.line})
                if allow_on(info.src, node_like, "BF-RACE001"):
                    continue
                findings.append(Finding(
                    "BF-RACE001", "error", info.src.path,
                    info.src.real_line(acc.line),
                    f"{typ}.{attr2} is guarded by {typ}.{lock} but "
                    f"{'written' if acc.write else 'read'} via "
                    f"self.{base} without holding it "
                    f"(in {info.name}.{mname}); take the lock or go "
                    f"through a locked accessor",
                    key=f"BF-RACE001:{info.src.path}:"
                        f"{info.name}.{mname}:{typ}.{attr2}"))
                continue
            lock = info.guard_of(acc.attr)
            if lock is None or acc.attr in info.locks:
                continue
            if any(h == f"self.{lock}" for h in eff_held):
                continue
            node_like = type("N", (), {"lineno": acc.line})
            if allow_on(info.src, node_like, "BF-RACE001"):
                continue
            findings.append(Finding(
                "BF-RACE001", "error", info.src.path,
                info.src.real_line(acc.line),
                f"{info.name}.{acc.attr} is guarded by "
                f"{info.name}.{lock} but "
                f"{'written' if acc.write else 'read'} without holding "
                f"it in {info.name}.{mname} (thread-reachable)",
                key=f"BF-RACE001:{info.src.path}:"
                    f"{info.name}.{mname}:{acc.attr}"))

    findings.extend(_check_module_globals(ctx))
    return findings


def _check_module_globals(ctx: LintContext):
    findings = []
    for src in ctx.sources:
        gnames: set[str] = set()
        glocks: set[str] = set()
        mdefs: dict[str, ast.AST] = {}
        for node in src.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mdefs[node.name] = node
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        gnames.add(tgt.id)
                        if _is_lock_factory(node.value):
                            glocks.add(tgt.id)
        if not mdefs:
            continue
        fn_ids = {id(sub) for fn in mdefs.values() for sub in ast.walk(fn)}
        targets: set[str] = set()
        for node in ast.walk(src.tree):
            if id(node) in fn_ids:
                continue
            if isinstance(node, ast.Call) and \
                    dotted_name(node.func).split(".")[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target" and \
                            isinstance(kw.value, ast.Name) and \
                            kw.value.id in mdefs:
                        targets.add(kw.value.id)
        for tname in sorted(targets):
            findings.extend(_scan_thread_target(
                src, tname, mdefs[tname], gnames, glocks))
    return findings


def _scan_thread_target(src: Source, tname: str, fn: ast.AST,
                        gnames: set[str], glocks: set[str]):
    held_locks: list[str] = []
    findings = []

    def visit(node):
        if isinstance(node, ast.With):
            names = [dotted_name(i.context_expr) for i in node.items]
            locks = [n for n in names if n in glocks]
            held_locks.extend(locks)
            for stmt in node.body:
                visit(stmt)
            for _ in locks:
                held_locks.pop()
            return
        mutated = None
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATORS and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id in gnames:
            mutated = node.func.value.id
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id in gnames:
                    mutated = tgt.value.id
        if mutated is not None and not held_locks and \
                not allow_on(src, node, "BF-RACE002"):
            findings.append(Finding(
                "BF-RACE002", "error", src.path, src.real_line(node),
                f"thread target {tname}() mutates module-global "
                f"'{mutated}' without a lock "
                f"({len(glocks) or 'no'} module-level lock(s) "
                f"declared); wrap the mutation in `with <lock>:`",
                key=f"BF-RACE002:{src.path}:{tname}:{mutated}"))
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in fn.body:
        visit(stmt)
    return findings
