"""LINT_BASELINE.json: the zero-NEW-findings gate.

The baseline is the adoption ramp: findings whose stable `key` matches
a committed entry are suppressed (each entry carries a `why` note — a
baseline without prose is just a mute button), anything else fails the
gate. Keys deliberately omit line numbers so unrelated edits do not
churn the file.

Durability discipline matches the tuning DB: tmp file + flush + fsync +
`os.replace` + directory fsync on save; a torn/corrupt file DEGRADES to
an empty baseline (every finding shows as new — fail-closed) plus a
BF-BASE001 warning naming the corruption, never a crash.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from .engine import Finding

BASELINE_VERSION = 1
BASELINE_BASENAME = "LINT_BASELINE.json"


@dataclass
class Baseline:
    path: str
    entries: list[dict] = field(default_factory=list)
    corrupt: str = ""  # non-empty: why the load degraded

    @property
    def keys(self) -> set[str]:
        return {e.get("key", "") for e in self.entries}


def load_baseline(path: str) -> Baseline:
    if not os.path.exists(path):
        return Baseline(path=path)
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        if not isinstance(data, dict) or \
                not isinstance(data.get("entries"), list):
            raise ValueError("not a baseline object")
        entries = []
        for e in data["entries"]:
            if not isinstance(e, dict) or not e.get("key"):
                raise ValueError(f"malformed entry: {e!r}")
            if not e.get("why"):
                raise ValueError(
                    f"baseline entry {e.get('key')!r} has no 'why' — "
                    "a waiver without prose is a mute button")
            entries.append(e)
        return Baseline(path=path, entries=entries)
    except (OSError, ValueError) as exc:
        return Baseline(path=path, corrupt=str(exc))


def save_baseline(baseline: Baseline) -> None:
    data = {"version": BASELINE_VERSION,
            "entries": sorted(baseline.entries,
                              key=lambda e: e.get("key", ""))}
    tmp = baseline.path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, baseline.path)
    dfd = os.open(os.path.dirname(os.path.abspath(baseline.path)) or ".",
                  os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def apply_baseline(findings: list[Finding], baseline: Baseline
                   ) -> tuple[list[Finding], list[Finding], list[str]]:
    """(new, suppressed, stale_keys). Stale keys — entries matching no
    current finding — are reported so the baseline shrinks as fixes
    land (they never fail the gate: a stale waiver is progress)."""
    new: list[Finding] = []
    suppressed: list[Finding] = []
    keys = baseline.keys
    if baseline.corrupt:
        new = list(findings)
        new.append(Finding(
            "BF-BASE001", "warning", os.path.basename(baseline.path), 1,
            f"baseline unreadable ({baseline.corrupt}); degraded to "
            "empty — every finding gates as new until the file is "
            "restored",
            key="BF-BASE001:corrupt"))
        return new, [], []
    hit: set[str] = set()
    for f in findings:
        if f.key in keys:
            suppressed.append(f)
            hit.add(f.key)
        else:
            new.append(f)
    stale = sorted(keys - hit)
    return new, suppressed, stale
