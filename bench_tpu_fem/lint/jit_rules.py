"""BF-JIT001: host-side constructs inside jit-compiled functions.

A function decorated with `@jax.jit` (or `@partial(jax.jit, ...)`, or
wrapped via `f2 = jax.jit(f)`) traces ONCE; host clock reads, `.item()`
materialization and Python branches on traced arguments either freeze a
stale value into the executable or abort tracing on hardware after the
CPU tests passed — the "interpret mode accepted it" failure class, one
layer up from the Mosaic checks `bench_tpu_fem.analysis` runs.

Flagged inside a jitted function (and its nested helpers):
  * host clock calls: time.time / time.monotonic / time.perf_counter /
    time.process_time — a traced clock read is a constant;
  * `.item()` / `float(tracer)`-style host materialization (`.item()`
    only: float()/int() casts on scalars are legal on concrete values
    and the tracer aborts loudly on them anyway);
  * `if`/`while` tests on a BARE parameter compared to a numeric
    constant — the classic tracer branch. Parameters named by
    `static_argnames`/`static_argnums` are exempt (they are Python
    values at trace time), as are `is None` sentinel checks.

The convergence capture (`obs/convergence.py`) is the reason the rule
exists: its in-loop residual capture had to be rebuilt jit-safe, and
nothing but review memory kept host clocks out of the hot loops since.
"""

from __future__ import annotations

import ast

from .engine import Finding, LintContext, allow_on, dotted_name, rule

_CLOCK_CALLS = ("time.time", "time.monotonic", "time.perf_counter",
                "time.process_time")


def _jit_decorated(node) -> tuple[bool, set[str], set[int]]:
    """(is_jitted, static_argnames, static_argnums) from decorators."""
    static_names: set[str] = set()
    static_nums: set[int] = set()
    jitted = False
    for dec in node.decorator_list:
        name = dotted_name(dec)
        if name.split(".")[-1] == "jit":
            jitted = True
            continue
        if isinstance(dec, ast.Call):
            fname = dotted_name(dec.func).split(".")[-1]
            inner = dec.args and dotted_name(dec.args[0]).split(".")[-1]
            if fname == "jit" or (fname == "partial" and inner == "jit"):
                jitted = True
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        for leaf in ast.walk(kw.value):
                            if isinstance(leaf, ast.Constant) and \
                                    isinstance(leaf.value, str):
                                static_names.add(leaf.value)
                    elif kw.arg == "static_argnums":
                        for leaf in ast.walk(kw.value):
                            if isinstance(leaf, ast.Constant) and \
                                    isinstance(leaf.value, int):
                                static_nums.add(leaf.value)
    return jitted, static_names, static_nums


def _wrapped_defs(tree: ast.Module) -> set[str]:
    """Names of functions passed through jax.jit(f) somewhere."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                dotted_name(node.func).split(".")[-1] == "jit" and \
                node.args and isinstance(node.args[0], ast.Name):
            out.add(node.args[0].id)
    return out


def _tracer_params(node, static_names: set[str],
                   static_nums: set[int]) -> set[str]:
    params = [a.arg for a in node.args.posonlyargs + node.args.args]
    out = set()
    for i, p in enumerate(params):
        if p in ("self", "cls") or p in static_names or i in static_nums:
            continue
        out.add(p)
    return out


@rule({
    "BF-JIT001": "host clock / .item() / tracer branch inside a "
                 "jit-compiled function",
})
def check_jit(ctx: LintContext):
    findings: list[Finding] = []
    for src in ctx.sources:
        wrapped = _wrapped_defs(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            jitted, snames, snums = _jit_decorated(node)
            if not jitted and node.name not in wrapped:
                continue
            tracers = _tracer_params(node, snames, snums)
            findings.extend(_scan_jitted(src, node, tracers))
    return findings


def _scan_jitted(src, fn, tracers: set[str]):
    findings = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            bad = None
            if name in _CLOCK_CALLS:
                bad = (f"host clock {name}() traces to a constant — "
                       "capture timestamps outside the jitted region")
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                bad = (".item() forces a host sync inside the traced "
                       "region — keep reductions on-device and "
                       "materialize after the jit boundary")
            if bad and not allow_on(src, node, "BF-JIT001"):
                findings.append(Finding(
                    "BF-JIT001", "error", src.path, src.real_line(node),
                    f"in jitted `{fn.name}`: {bad}",
                    key=f"BF-JIT001:{src.path}:{fn.name}:"
                        f"{name or 'item'}"))
        elif isinstance(node, (ast.If, ast.While)):
            pname = _tracer_branch(node.test, tracers)
            if pname and not allow_on(src, node, "BF-JIT001"):
                findings.append(Finding(
                    "BF-JIT001", "error", src.path, src.real_line(node),
                    f"in jitted `{fn.name}`: Python branch on traced "
                    f"argument '{pname}' — use lax.cond/lax.select, or "
                    "mark the argument static",
                    key=f"BF-JIT001:{src.path}:{fn.name}:if-{pname}"))
    return findings


def _tracer_branch(test: ast.AST, tracers: set[str]) -> str | None:
    """`if x:` / `if x > 0:` on a bare tracer parameter; `is None`
    sentinel checks are host-legal and skipped."""
    if isinstance(test, ast.Name) and test.id in tracers:
        return test.id
    if isinstance(test, ast.Compare) and \
            isinstance(test.left, ast.Name) and \
            test.left.id in tracers and len(test.ops) == 1:
        if isinstance(test.ops[0], (ast.Is, ast.IsNot)):
            return None
        cmp = test.comparators[0]
        if isinstance(cmp, ast.Constant) and \
                isinstance(cmp.value, (int, float)):
            return test.left.id
    return None
