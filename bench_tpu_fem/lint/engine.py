"""Core of benchfem-lint: source loading, comment directives, findings.

The engine walks Python sources (plus the agenda's EMBEDDED stage-code
string constants — `harness/agenda.py` ships thread fan-outs inside
triple-quoted module constants executed via `_py` stages, and those must
not dodge the race rules), parses each into an AST, extracts the comment
map (tokenize-accurate, so string literals containing '#' cannot fake a
directive), and hands a `LintContext` to every registered rule.

Comment directives (the annotation syntax the README documents):

  # guarded-by: _lock          attribute is protected by self._lock
                               (attach to the assignment / field line)
  # lint: thread-entry         this function runs on a worker thread
                               even though no threading.Thread(target=..)
                               site names it statically (HTTP handlers,
                               cache-builder callbacks)
  # lint: allow(BF-RACE001)    suppress one rule on this line, in place
                               (prefer a LINT_BASELINE.json entry with a
                               `why` when the waiver needs prose)

Findings carry a stable `key` (rule + path + semantic anchor, no line
number) so LINT_BASELINE.json entries survive unrelated line drift.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

LINT_VERSION = 1

#: rule id -> one-line description (the README's rule catalog renders
#: from this; --json embeds it so reports are self-describing). Seeded
#: with the rules the engine/baseline layers emit themselves; checker
#: modules add theirs at registration.
RULE_CATALOG: dict[str, str] = {
    "BF-META001": "source file failed to parse (nothing below can be "
                  "checked)",
    "BF-BASE001": "baseline file unreadable — degraded to empty "
                  "(fail-closed)",
}

_CHECKERS: list = []


def rule(rule_ids: dict[str, str]):
    """Register a checker function emitting the given rule ids."""

    def deco(fn):
        RULE_CATALOG.update(rule_ids)
        _CHECKERS.append(fn)
        return fn

    return deco


def checkers() -> list:
    return list(_CHECKERS)


@dataclass
class Finding:
    rule: str
    severity: str  # "error" | "warning"
    path: str  # repo-relative display path (may carry ::EMBEDDED)
    line: int  # 1-based line in the REAL file
    message: str
    key: str = ""  # stable baseline identity (no line numbers)

    def __post_init__(self):
        if not self.key:
            self.key = f"{self.rule}:{self.path}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line,
                "message": self.message, "key": self.key}

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} "
                f"{self.severity}: {self.message}")


@dataclass
class Source:
    """One parsed compilation unit: a real .py file or an embedded
    stage-code string hoisted out of one."""

    path: str  # display path ("pkg/mod.py" or "pkg/mod.py::NAME")
    file: str  # the real file on disk
    text: str
    tree: ast.Module
    line_offset: int = 0  # embedded: AST line N is file line N+offset
    comments: dict[int, str] = field(default_factory=dict)

    def real_line(self, node_or_line) -> int:
        n = getattr(node_or_line, "lineno", node_or_line)
        return int(n) + self.line_offset

    def comment(self, lineno: int) -> str:
        """Comment text on this AST line (source-local numbering)."""
        return self.comments.get(lineno, "")


_ALLOW_RE = re.compile(r"lint:\s*allow\(([A-Z0-9_,\- ]+)\)")
_GUARDED_RE = re.compile(r"guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
_ENTRY_RE = re.compile(r"lint:\s*thread-entry")


def allow_on(src: Source, node, rule_id: str) -> bool:
    """True when the node's line (or the line above it) carries a
    `# lint: allow(RULE)` waiver for this rule."""
    for ln in (node.lineno, node.lineno - 1):
        m = _ALLOW_RE.search(src.comment(ln))
        if m and rule_id in {s.strip() for s in m.group(1).split(",")}:
            return True
    return False


def guarded_by_annotation(src: Source, lineno: int) -> str | None:
    m = _GUARDED_RE.search(src.comment(lineno))
    return m.group(1) if m else None


def thread_entry_annotation(src: Source, node) -> bool:
    for ln in (node.lineno, node.lineno - 1):
        if _ENTRY_RE.search(src.comment(ln)):
            return True
    return False


def _comment_map(text: str) -> dict[int, str]:
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(text).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        pass
    return out


def _embedded_sources(path: str, file: str, tree: ast.Module) -> list[Source]:
    """Module-level UPPERCASE string constants that parse as Python with
    at least one import — the agenda's `_py` stage sources. Linted as
    virtual files `<path>::<NAME>` with line numbers mapped back."""
    out = []
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        name = node.targets[0].id
        if name != name.upper():
            continue
        val = node.value
        if not (isinstance(val, ast.Constant) and isinstance(val.value, str)
                and "import" in val.value):
            continue
        try:
            subtree = ast.parse(val.value)
        except SyntaxError:
            continue  # f-string template / shell text, not stage code
        if not any(isinstance(n, (ast.Import, ast.ImportFrom))
                   for n in ast.walk(subtree)):
            continue
        out.append(Source(path=f"{path}::{name}", file=file,
                          text=val.value, tree=subtree,
                          line_offset=val.lineno - 1,
                          comments=_comment_map(val.value)))
    return out


def _iter_py_files(path: str):
    if os.path.isfile(path):
        yield path
        return
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs
                         if d not in ("__pycache__", ".git"))
        for fn in sorted(files):
            if fn.endswith(".py"):
                yield os.path.join(root, fn)


def repo_root() -> str:
    """The tree the default scan covers: the repo checkout holding the
    package (parent of bench_tpu_fem/)."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def default_paths(root: str) -> list[str]:
    """The full-tree scan: the package (minus this linter's own sources
    and the analysis fixture corpus, both of which stage deliberate
    violations) plus the perfgate collector (the counter-emission side
    of the BF-CNTR cross-check)."""
    out = [os.path.join(root, "bench_tpu_fem")]
    pg = os.path.join(root, "scripts", "perfgate.py")
    if os.path.exists(pg):
        out.append(pg)
    return out


_DEFAULT_EXCLUDE = (os.path.join("bench_tpu_fem", "lint") + os.sep,
                    os.path.join("bench_tpu_fem", "analysis", "fixtures.py"))


@dataclass
class LintContext:
    sources: list[Source]
    root: str
    full_scan: bool  # default paths -> whole-tree cross-checks armed
    schema_path: str = ""

    def source_by_suffix(self, suffix: str) -> Source | None:
        for src in self.sources:
            if src.path.endswith(suffix):
                return src
        return None


def load_context(paths: list[str] | None, root: str | None = None,
                 schema_path: str = "") -> tuple[LintContext, list[Finding]]:
    root = root or repo_root()
    full = not paths
    scan = [os.path.abspath(p) for p in (paths or default_paths(root))]
    sources: list[Source] = []
    findings: list[Finding] = []
    for path in scan:
        for file in _iter_py_files(path):
            rel = os.path.relpath(file, root)
            if full and any(rel.startswith(ex) or rel == ex
                            for ex in _DEFAULT_EXCLUDE):
                continue
            try:
                with open(file, encoding="utf-8") as fh:
                    text = fh.read()
                tree = ast.parse(text)
            except (OSError, SyntaxError) as exc:
                findings.append(Finding(
                    "BF-META001", "error", rel,
                    getattr(exc, "lineno", 1) or 1,
                    f"source failed to parse: {exc}",
                    key=f"BF-META001:{rel}"))
                continue
            src = Source(path=rel, file=file, text=text, tree=tree,
                         comments=_comment_map(text))
            sources.append(src)
            sources.extend(_embedded_sources(rel, file, tree))
    ctx = LintContext(sources=sources, root=root, full_scan=full,
                      schema_path=schema_path)
    return ctx, findings


# -------------------------------------------------------------------------
# Shared AST helpers used by more than one rule module.

def dotted_name(node) -> str:
    """'threading.Lock' for Attribute/Name chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def const_str_keys(d: ast.Dict) -> list[str]:
    return [k.value for k in d.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)]


def resolve_dict_arg(fn_node, call: ast.Call):
    """Resolve a call's first argument to (ast.Dict, extra_keys, open_).

    Handles the project's two journaling shapes: a literal dict argument,
    and `rec = {...}; rec["k"] = v; ...; emit(rec)` where later subscript
    stores contribute OPTIONAL fields. Returns (None, [], False) when
    the argument cannot be resolved statically.
    """
    if not call.args:
        return None, [], False
    arg = call.args[0]
    if isinstance(arg, ast.Dict):
        return arg, [], any(k is None for k in arg.keys)
    if not isinstance(arg, ast.Name):
        return None, [], False
    target, extra, open_ = None, [], False
    for node in ast.walk(fn_node):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == arg.id
                and node.lineno < call.lineno):
            if isinstance(node.value, ast.Dict):
                target = node.value
                open_ = any(k is None for k in node.value.keys)
            else:
                target, open_ = None, True
        elif (isinstance(node, ast.Assign)
                and isinstance(node.targets[0], ast.Subscript)
                and isinstance(node.targets[0].value, ast.Name)
                and node.targets[0].value.id == arg.id
                and node.lineno < call.lineno):
            sl = node.targets[0].slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                extra.append(sl.value)
            else:
                open_ = True
    # rec.update(...) makes the field set dynamic
    for node in ast.walk(fn_node):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("update", "setdefault")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == arg.id
                and node.lineno < call.lineno):
            if node.func.attr == "update":
                open_ = True
            elif node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                extra.append(node.args[0].value)
    return target, extra, open_
