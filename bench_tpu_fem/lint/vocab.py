"""BF-VOCAB / BF-CNTR / BF-EVID: vocabulary and evidence hygiene.

BF-VOCAB001 — free-text gate-reason literals. Gate/fallback reasons are
a registered vocabulary (`engines/registry.py:GATE_REASONS`, rendered
through `gate_reason(slug, **fmt)`), so downstream reports can group by
reason and the analysis tests can pin the set. A plain string literal
assigned into a `*_gate_reason` / `s_step_fallback_reason` /
`f64_df32_fallback_reason` slot bypasses the registry. This generalizes
(and replaces) the AST sweep that lived in tests/test_engine_registry.py
— package-wide, same key patterns.

BF-CNTR001/002 — the perfgate counter cross-check, both directions.
The gating tables in `obs/regress.py` (LOWER_IS_BETTER_COUNTERS,
HIGHER_IS_BETTER_COUNTERS, CONTRACT_FLAGS, MEASURED_ONLY_COUNTERS) and
the counters `scripts/perfgate.py` actually collects must agree:
  * BF-CNTR001: a table references a counter no module emits (the gate
    can never fire — dead discipline);
  * BF-CNTR002: perfgate collects a counter no table gates and no
    registered exemption covers (`ADVISORY_COUNTERS` in obs/regress.py,
    the label keys `comparable_labels` consumes, the specially-gated
    `collectives_per_iter`/`iters_to_*` families) — an ungated counter
    silently drifts, which is exactly what ROADMAP item 7 forbids.
Both directions run only on full-tree scans (they need both files).

BF-EVID001/002 — evidence provenance labels. Every numeric evidence
stamp carries a cpu-measured / design-estimate / hardware label
(`engines/autotune.py:LABELS`, extended by the obs conventions
cpu-host / cpu-interpret / hardware-armed). BF-EVID001 flags a
label/evidence/measured value outside the registered stems;
BF-EVID002 flags a stamp-shaped dict (carries a `score` — the
autotuner's evidence shape) with no label at all.
"""

from __future__ import annotations

import ast

from .engine import (
    Finding,
    LintContext,
    allow_on,
    rule,
)

# ---- BF-VOCAB001 ---------------------------------------------------------

REASON_KEY_SUFFIXES = ("_gate_reason",)
REASON_KEYS_EXACT = ("s_step_fallback_reason", "f64_df32_fallback_reason")
#: the vocabulary's own home may of course assign literals
_VOCAB_EXEMPT_SUFFIX = "engines/registry.py"


def is_reason_key(key: str) -> bool:
    if key in REASON_KEYS_EXACT:
        return True
    return key.endswith(REASON_KEY_SUFFIXES) and key != "engine_fallback_reason"


# ---- BF-EVID -------------------------------------------------------------

#: registered provenance stems; composite labels extend a stem with a
#: parenthesized qualifier ("cpu-measured (time-to-rtol ...)")
LABEL_STEMS = ("cpu-measured", "design-estimate", "hardware",
               "cpu-host", "cpu-interpret", "analytic-design-estimate")
_LABEL_KEYS = ("label", "evidence", "measured")


def _label_ok(text: str) -> bool:
    return any(text == stem or text.startswith(stem + " ")
               or text.startswith(stem + "-armed")
               or text.startswith(stem + " (")
               for stem in LABEL_STEMS)


def _label_leaves(value: ast.AST):
    """String-constant leaves of a label expression (IfExp branches,
    BoolOp fallbacks). Dynamic parts yield nothing — runtime contracts
    (autotune put()'s LABELS check) own those."""
    stack = [value]
    while stack:
        v = stack.pop()
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            yield v
        elif isinstance(v, ast.IfExp):
            stack.extend((v.body, v.orelse))
        elif isinstance(v, ast.BoolOp):
            stack.extend(v.values)


@rule({
    "BF-VOCAB001": "free-text gate-reason literal outside "
                   "engines/registry.py:GATE_REASONS",
    "BF-EVID001": "provenance label outside the registered "
                  "cpu-measured/design-estimate/hardware vocabulary",
    "BF-EVID002": "evidence stamp (score-bearing dict) without a "
                  "provenance label",
})
def check_vocab(ctx: LintContext):
    findings: list[Finding] = []
    for src in ctx.sources:
        exempt_vocab = src.path.replace("\\", "/").endswith(
            _VOCAB_EXEMPT_SUFFIX)
        for node in ast.walk(src.tree):
            # -- reason literals: res.extra["x_gate_reason"] = "text"
            if isinstance(node, ast.Assign) and not exempt_vocab:
                for tgt in node.targets:
                    if not (isinstance(tgt, ast.Subscript)
                            and isinstance(tgt.slice, ast.Constant)
                            and isinstance(tgt.slice.value, str)
                            and is_reason_key(tgt.slice.value)):
                        continue
                    if isinstance(node.value, ast.Constant) and \
                            isinstance(node.value.value, str) and \
                            not allow_on(src, node, "BF-VOCAB001"):
                        findings.append(Finding(
                            "BF-VOCAB001", "error", src.path,
                            src.real_line(node),
                            f"free-text reason literal assigned to "
                            f"'{tgt.slice.value}'; register a slug in "
                            "GATE_REASONS and render it with "
                            "gate_reason(...)",
                            key=f"BF-VOCAB001:{src.path}:"
                                f"{tgt.slice.value}"))
            # -- dict-literal reason fields + evidence labels/stamps
            if isinstance(node, ast.Dict):
                keys = {}
                for k, v in zip(node.keys, node.values):
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        keys[k.value] = v
                for key, v in keys.items():
                    if key in _LABEL_KEYS:
                        for leaf in _label_leaves(v):
                            if not _label_ok(leaf.value) and \
                                    not allow_on(src, leaf, "BF-EVID001"):
                                findings.append(Finding(
                                    "BF-EVID001", "error", src.path,
                                    src.real_line(leaf),
                                    f"'{key}' value "
                                    f"{leaf.value!r} is outside the "
                                    "registered provenance stems "
                                    f"({', '.join(LABEL_STEMS)})",
                                    key=f"BF-EVID001:{src.path}:"
                                        f"{leaf.value}"))
                has_spread = len(node.keys) != len(keys)
                if "score" in keys and not has_spread and \
                        not any(k in keys for k in _LABEL_KEYS) and \
                        not allow_on(src, node, "BF-EVID002"):
                    # a **spread may carry the label — skip those
                    findings.append(Finding(
                        "BF-EVID002", "error", src.path,
                        src.real_line(node),
                        "score-bearing evidence stamp has no "
                        "label/evidence field — numbers carry their "
                        "provenance (cpu-measured / design-estimate / "
                        "hardware)",
                        key=f"BF-EVID002:{src.path}:"
                            + ",".join(sorted(keys))))
    findings.extend(_check_counters(ctx))
    return findings


# ---- BF-CNTR -------------------------------------------------------------

_TABLE_NAMES = ("LOWER_IS_BETTER_COUNTERS", "HIGHER_IS_BETTER_COUNTERS",
                "CONTRACT_FLAGS", "MEASURED_ONLY_COUNTERS")
_ADVISORY_NAME = "ADVISORY_COUNTERS"
#: gated by dedicated gate_counters logic rather than the tables
_SPECIALLY_GATED = ("collectives_per_iter",)
#: configuration-identity labels comparable_labels() consumes
_LABEL_COUNTERS = ("precond_label", "s_step_label",
                   "heat_warm_start_label")


def _tuple_of_strs(node: ast.AST) -> list[str] | None:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
        return out
    return None


def _check_counters(ctx: LintContext):
    regress = ctx.source_by_suffix("obs/regress.py") or \
        ctx.source_by_suffix("obs\\regress.py")
    perfgate = ctx.source_by_suffix("perfgate.py")
    if not ctx.full_scan or regress is None or perfgate is None:
        return []
    tables: dict[str, list[str]] = {}
    advisory: list[str] = []
    for node in regress.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            vals = _tuple_of_strs(node.value)
            if vals is None:
                continue
            if name in _TABLE_NAMES:
                tables[name] = vals
            elif name == _ADVISORY_NAME:
                advisory = vals
    gated = {c for vals in tables.values() for c in vals}
    counters_keys: list[str] = []
    counters_line = 1
    for node in ast.walk(perfgate.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "counters" and \
                isinstance(node.value, ast.Dict):
            counters_line = node.lineno
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    counters_keys.append(k.value)
    # every string constant anywhere else in the scan = emission evidence
    emitted: set[str] = set(counters_keys)
    for src in ctx.sources:
        if src is regress:
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                emitted.add(node.value)
    findings = []
    for tname, vals in sorted(tables.items()):
        for counter in vals:
            if counter not in emitted:
                findings.append(Finding(
                    "BF-CNTR001", "error", regress.path, 1,
                    f"{tname} gates '{counter}' but no module emits "
                    "it — the gate can never fire; drop the row or "
                    "restore the emitter",
                    key=f"BF-CNTR001:{counter}"))
    ungated_ok = gated | set(advisory) | set(_SPECIALLY_GATED) \
        | set(_LABEL_COUNTERS)
    for counter in counters_keys:
        if counter in ungated_ok or counter.startswith("iters_to_"):
            continue
        findings.append(Finding(
            "BF-CNTR002", "error", perfgate.path, counters_line,
            f"perfgate collects '{counter}' but no obs/regress.py "
            "table gates it and ADVISORY_COUNTERS does not exempt it "
            "— stamp, label, gate (ROADMAP item 7)",
            key=f"BF-CNTR002:{counter}"))
    return findings
