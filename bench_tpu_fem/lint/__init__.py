"""benchfem-lint: the project-native static contract analyzer.

Eighteen PRs of discipline — registered gate-reason vocabulary,
additive-only journal schemas, evidence labels, lock-guarded serve
state — enforced by a pluggable AST engine instead of scattered one-off
tests and reviewer memory:

  BF-RACE001/002   guarded-by race rules (lock inference + thread-entry
                   reachability; module-global fan-outs)
  BF-JRNL001..004  journal event-schema registry vs
                   LINT_JOURNAL_SCHEMA.json (additive-only)
  BF-VOCAB001      free-text gate-reason literals
  BF-CNTR001/002   regress gating tables vs perfgate-emitted counters
  BF-EVID001/002   provenance labels on evidence stamps
  BF-JIT001        host constructs inside jit-compiled functions
  BF-META001       unparsable source
  BF-BASE001       corrupt baseline (degraded, fail-closed)

    python -m bench_tpu_fem.lint [--json] [--baseline LINT_BASELINE.json]
                                 [--emit-schema] [paths...]

Library entry: `run_lint(paths)` returns sorted findings;
`python -m bench_tpu_fem.lint` is the CI gate (exit 1 on any finding
not matched by the committed baseline).
"""

from __future__ import annotations

from . import jit_rules, journal_schema, races, vocab  # noqa: F401 (register)
from .baseline import (  # noqa: F401
    Baseline,
    apply_baseline,
    load_baseline,
    save_baseline,
)
from .engine import (  # noqa: F401
    LINT_VERSION,
    RULE_CATALOG,
    Finding,
    LintContext,
    checkers,
    load_context,
)
from .journal_schema import (  # noqa: F401
    build_schema,
    extract_sites,
    load_schema,
    merge_schema,
    save_schema,
)


def run_lint(paths: list[str] | None = None, root: str | None = None,
             schema_path: str = "") -> list[Finding]:
    """Run every registered rule over `paths` (default: the package +
    scripts/perfgate.py). Returns findings sorted by path/line/rule."""
    ctx, findings = load_context(paths, root=root, schema_path=schema_path)
    for checker in checkers():
        findings.extend(checker(ctx))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    return findings
