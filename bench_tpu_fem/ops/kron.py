"""Kronecker (tensor-product) stiffness apply: the uniform-mesh fast path.

On the *unperturbed* box mesh every cell shares one axis-aligned diagonal
Jacobian, so the assembled global stiffness matrix factorises exactly:

    A = kappa * (K_x (x) M_y (x) M_z  +  M_x (x) K_y (x) M_z
                 +  M_x (x) M_y (x) K_z)

where K_a / M_a are 1D assembled stiffness / mass matrices on axis `a`
(banded, bandwidth P). This is a structural property of tensor-product
Lagrange elements with separable quadrature — the same quadrature rule and
basis tables as the general path, so the factorisation is exact to machine
precision (tested against the assembled-CSR oracle).

The apply then needs **no geometry tensor at all**: seven banded 1D
contractions over the plain (NX, NY, NZ) dof grid,

    y = kappa * ( M_x (M_y (K_z u) + K_y (M_z u)) + K_x (M_y (M_z u)) )

each a fused stencil pass (pad + 2P+1 shifted slices * per-row coefficient,
which XLA fuses into one elementwise kernel). Per CG iteration this streams
~7 vectors instead of the general path's 6*nq^3-per-cell geometry tensor
(~111 B/dof at degree 3) — the reference precomputes and streams G per cell
(/root/reference/src/geometry_gpu.hpp:26-133) because a GPU has bandwidth to
spare; on TPU the bandwidth *is* the roofline, so exploiting the Kronecker
structure is the idiomatic move (cf. constant-Jacobian fast paths in MFEM /
deal.II). Perturbed meshes take the general folded/Pallas path instead.

Dirichlet handling (reference semantics, laplacian_gpu.hpp:163-169): the
input mask is separable — 1 - bc = m_x (x) m_y (x) m_z with m_a zero at the
two endpoints — so it folds into the 1D matrices as A_a' = A_a diag(m_a)
(free at apply time); the output pass-through is one fused blend
y = notbc * y + bc * x.

1D matrix construction mirrors the reference element setup
(/root/reference/src/laplacian.hpp:123-212): dofs at GLL-warped Lagrange
nodes, quadrature per qmode/rule, derivative through the collocation element
(dphi1 @ phi0), i.e. exactly the 1D factors of the 3D sum-factorised chain.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..elements.tables import OperatorTables, build_operator_tables
from ..mesh.box import BoxMesh


def cell_matrices_1d(t: OperatorTables) -> tuple[np.ndarray, np.ndarray]:
    """Reference-cell 1D stiffness and mass matrices (nd, nd), f64, on the
    unit interval (no mesh scaling): K_c[i,j] = sum_q w_q phi_i'(x_q)
    phi_j'(x_q), M_c[i,j] = sum_q w_q phi_i(x_q) phi_j(x_q), with the
    derivative evaluated through the collocation element exactly as the 3D
    chain does (dphi1 @ phi0)."""
    phi0 = np.asarray(t.phi0, np.float64)  # (nq, nd)
    dphi = np.asarray(t.dphi1, np.float64) @ phi0  # (nq, nd)
    w = np.asarray(t.wts1d, np.float64)
    Kc = (dphi.T * w) @ dphi
    Mc = (phi0.T * w) @ phi0
    return Kc, Mc


def assemble_1d(cellmat: np.ndarray, ncells: int) -> np.ndarray:
    """Assemble the (N, N) banded 1D matrix from `ncells` overlapping cell
    blocks (N = ncells*P + 1; neighbouring cells share one endpoint dof)."""
    nd = cellmat.shape[0]
    P = nd - 1
    N = ncells * P + 1
    A = np.zeros((N, N))
    for c in range(ncells):
        A[c * P : c * P + nd, c * P : c * P + nd] += cellmat
    return A


def banded_diags(A1: np.ndarray, P: int) -> np.ndarray:
    """(N, N) banded matrix -> (2P+1, N) diagonal storage: out[P+d, i] =
    A1[i, i+d] (zero where i+d is out of range). The zeros at out-of-range
    rows are what make the shifted-slice stencil exact at the boundary."""
    N = A1.shape[0]
    out = np.zeros((2 * P + 1, N))
    for d in range(-P, P + 1):
        if d >= 0:
            out[P + d, : N - d] = np.diagonal(A1, d)
        else:
            out[P + d, -d:] = np.diagonal(A1, d)
    return out


def axis_matrices_1d(
    t: OperatorTables, n: tuple[int, int, int], with_bc: bool = True
) -> tuple[list[np.ndarray], list[np.ndarray], list[np.ndarray]]:
    """Assembled per-axis 1D matrices [K_x, K_y, K_z], [M_x, M_y, M_z] (f64)
    for the uniform mesh with n cells per axis, scaled by the cell widths
    h_a = 1/n_a (K ~ 1/h, M ~ h), plus the per-axis interior masks [m_x,
    m_y, m_z]. With `with_bc`, the separable Dirichlet input mask is folded
    in on the right: A_a' = A_a diag(m_a). The returned masks are the single
    source of the 1D Dirichlet convention (shared with the output blend)."""
    Kc, Mc = cell_matrices_1d(t)
    Ks, Ms, masks = [], [], []
    for na in n:
        h = 1.0 / na
        K1 = assemble_1d(Kc, na) / h
        M1 = assemble_1d(Mc, na) * h
        m = np.ones(K1.shape[0])
        m[0] = m[-1] = 0.0
        if with_bc:
            K1 = K1 * m[None, :]
            M1 = M1 * m[None, :]
        Ks.append(K1)
        Ms.append(M1)
        masks.append(m)
    return Ks, Ms, masks


def kron_matrix(t: OperatorTables, n: tuple[int, int, int], kappa: float) -> np.ndarray:
    """Dense global matrix via explicit Kronecker products (tests only; no
    Dirichlet folding). Must equal the assembled-CSR oracle exactly."""
    K, M, _ = axis_matrices_1d(t, n, with_bc=False)
    return kappa * (
        np.kron(np.kron(K[0], M[1]), M[2])
        + np.kron(np.kron(M[0], K[1]), M[2])
        + np.kron(np.kron(M[0], M[1]), K[2])
    )


def banded_apply(u: jnp.ndarray, diags: jnp.ndarray, axis: int) -> jnp.ndarray:
    """One banded 1D contraction along `axis` of the 3D grid `u`:
    y[..., i, ...] = sum_d diags[P+d, i] * u[..., i+d, ...]. Implemented as
    one pad plus 2P+1 shifted static slices with per-row coefficients — XLA
    fuses the whole sum into a single elementwise pass."""
    nb = diags.shape[0]
    P = (nb - 1) // 2
    N = u.shape[axis]
    pads = [(0, 0)] * u.ndim
    pads[axis] = (P, P)
    up = jnp.pad(u, pads)
    bshape = [1] * u.ndim
    bshape[axis] = N
    acc = None
    for di in range(nb):
        start = [0] * u.ndim
        start[axis] = di
        lim = list(up.shape)
        lim[axis] = di + N
        term = diags[di].reshape(bshape) * jax.lax.slice(up, start, lim)
        acc = term if acc is None else acc + term
    return acc


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["Kd", "Md", "notbc1d", "kappa"],
    meta_fields=["n", "degree", "impl"],
)
@dataclass(frozen=True)
class KronLaplacian:
    """Uniform-mesh Laplacian as an exact Kronecker sum (pytree operator,
    same `apply` contract as ops.laplacian.Laplacian: dof-grid vectors in,
    Dirichlet rows pass through).

    impl: 'auto' (Pallas banded kernels for f32 on TPU, XLA otherwise),
    'xla', or 'pallas' (tests force interpret mode on CPU)."""

    Kd: tuple  # 3x (2P+1, N_a) banded diagonals of K_a diag(m_a)
    Md: tuple  # 3x (2P+1, N_a) banded diagonals of M_a diag(m_a)
    notbc1d: tuple  # 3x (N_a,) float 1D interior masks (notbc = outer product)
    kappa: jnp.ndarray
    n: tuple[int, int, int]
    degree: int
    impl: str = "auto"

    def apply(self, x_grid: jnp.ndarray) -> jnp.ndarray:
        """y = A @ x on the (NX, NY, NZ) dof grid."""
        impl = self.impl
        if impl == "auto":
            impl = (
                "pallas"
                if (
                    jax.default_backend() == "tpu"
                    and x_grid.dtype == jnp.float32
                )
                else "xla"
            )
        if impl == "pallas":
            from .kron_pallas import kron_apply_pallas

            return kron_apply_pallas(
                x_grid, self.Kd, self.Md, self.notbc1d, self.kappa,
                self.degree,
            )
        Kx, Ky, Kz = self.Kd
        Mx, My, Mz = self.Md
        aKz = banded_apply(x_grid, Kz, 2)
        aMz = banded_apply(x_grid, Mz, 2)
        t12 = banded_apply(aKz, My, 1) + banded_apply(aMz, Ky, 1)
        tyz = banded_apply(aMz, My, 1)
        y = self.kappa * (banded_apply(t12, Mx, 0) + banded_apply(tyz, Kx, 0))
        mx, my, mz = self.notbc1d
        notbc = mx[:, None, None] * my[None, :, None] * mz[None, None, :]
        return notbc * y + (1.0 - notbc) * x_grid


def dof_coords_1d(ncells: int, nodes1d: np.ndarray) -> np.ndarray:
    """(N,) dof coordinates along one axis of the unit cube: cell c spans
    [c/n, (c+1)/n] with element nodes `nodes1d` (shared endpoints dedup'd)."""
    nodes = np.asarray(nodes1d, np.float64)
    nd = len(nodes)
    P = nd - 1
    x = np.zeros(ncells * P + 1)
    for c in range(ncells):
        x[c * P : c * P + nd] = (c + nodes) / ncells
    return x


def rhs_factors_1d(
    t: OperatorTables, n: tuple[int, int, int]
) -> list[np.ndarray]:
    """The three 1D factors of the RHS b = M3d f_h with Dirichlet rows
    zeroed, built with O(N^(1/3)) host work: on the uniform mesh the mass
    matrix is separable (M_x (x) M_y (x) M_z) *and* the benchmark source is
    separable (1000 exp(-((x-.5)^2+(y-.5)^2)/0.02) = 1000 g(x) g(y) * 1), so

        b = 1000 * (m_x o M_x g_x) (x) (m_y o M_y g_y) (x) (m_z o M_z 1)

    — three tiny host-side 1D mass applies; the caller outer-products them
    on device (device_rhs_uniform single-chip, dist.kron.make_kron_rhs_fn
    per shard). Replaces the O(N) host assembly path (fem.assemble.
    assemble_rhs, mirroring /root/reference/src/laplacian_solver.cpp:100-105)
    for uniform-mesh runs, where host memory would otherwise cap the problem
    size far below HBM capacity. Exactness vs the host path is tested."""
    from ..fem.source import default_source

    _, Ms, masks = axis_matrices_1d(t, n, with_bc=False)
    coords = [dof_coords_1d(na, t.nodes1d) for na in n]
    # 1D factors of the benchmark source, derived from the *actual* source
    # function so the two paths cannot drift: f(x,y,z) is evaluated along
    # each axis with the other coordinates pinned at the bump centre, and
    # the peak value divided out of all but the first factor.
    centre = np.array([0.5, 0.5, 0.5])
    peak = float(default_source(centre))

    def axis_factor(axis, c):
        pts = np.tile(centre, (len(c), 1))
        pts[:, axis] = c
        return np.asarray(default_source(pts), np.float64)

    g = [axis_factor(a, coords[a]) for a in range(3)]
    g[1] /= peak
    g[2] /= peak
    # Separability self-check: the benchmark source must factor as
    # g0(x)*g1(y)*g2(z)/peak^2; catches any future non-separable edit to
    # fem.source.default_source before it silently changes the problem.
    rng = np.random.RandomState(0)
    probe = rng.rand(8, 3)
    f_probe = np.asarray(default_source(probe), np.float64)
    f_fact = (
        axis_factor(0, probe[:, 0])
        * axis_factor(1, probe[:, 1])
        * axis_factor(2, probe[:, 2])
        / peak**2
    )
    if not np.allclose(f_probe, f_fact, rtol=1e-12):
        raise ValueError(
            "benchmark source is not separable; device_rhs_uniform cannot "
            "be used (update ops.kron or use the host assembly path)"
        )
    return [(M1 @ ga) * m for M1, ga, m in zip(Ms, g, masks)]


def device_rhs_uniform(
    t: OperatorTables, n: tuple[int, int, int], dtype
) -> jnp.ndarray:
    """Single-chip device RHS: outer product of the separable 1D factors
    (see rhs_factors_1d)."""
    fx, fy, fz = (jnp.asarray(f, dtype=dtype) for f in rhs_factors_1d(t, n))
    return fx[:, None, None] * fy[None, :, None] * fz[None, None, :]


def build_kron_laplacian(
    mesh: BoxMesh,
    degree: int,
    qmode: int,
    rule: str = "gll",
    kappa: float = 2.0,
    dtype=jnp.float64,
    tables: OperatorTables | None = None,
) -> KronLaplacian:
    """Build the Kronecker operator for a *uniform* box mesh. All 1D factors
    are assembled host-side in f64 and cast once; total operator state is
    O(N) — there is no geometry tensor."""
    if not mesh.is_uniform:
        from ..engines.registry import GATE_REASONS

        raise ValueError(GATE_REASONS["kron-perturbed"])
    t = tables or build_operator_tables(degree, qmode, rule)
    Ks, Ms, masks = axis_matrices_1d(t, mesh.n)
    P = degree
    Kd = tuple(jnp.asarray(banded_diags(K1, P), dtype=dtype) for K1 in Ks)
    Md = tuple(jnp.asarray(banded_diags(M1, P), dtype=dtype) for M1 in Ms)
    notbc = [jnp.asarray(m, dtype=dtype) for m in masks]
    return KronLaplacian(
        Kd=Kd,
        Md=Md,
        notbc1d=tuple(notbc),
        kappa=jnp.asarray(kappa, dtype=dtype),
        n=mesh.n,
        degree=degree,
    )
