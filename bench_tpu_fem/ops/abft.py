"""Algorithm-based fault tolerance (ABFT) for the matrix-free apply
(ISSUE 14): checksum vectors, drift envelopes and the bit-flip model —
the detection vocabulary every SDC seam shares.

Silent data corruption ("mercurial cores", Hochschild et al., HotOS
2021) returns FINITE-but-wrong values: none of the existing defenses
see it — the breakdown sentinels catch non-finite values, the CRC
machinery catches torn bytes, but a bit-flipped apply that stays finite
sails through CG unchecked. A matrix-free iterative solver has two free
invariants that close the hole (Huang & Abraham, 1984):

* **Operator linearity / symmetry** — for any checksum vector ``w``,
  ``⟨w, A p⟩ == ⟨A^T w, p⟩`` exactly in real arithmetic, and ``A^T w =
  A w`` for the symmetric Laplacian, so ONE precomputed apply
  (``aw = A w``) turns every subsequent audited apply into one extra
  dot: compute ``⟨w, y⟩`` next to the recurrence's own dots and compare
  against ``⟨aw, p⟩``. A corruption of any output element by ``δ``
  shifts ``⟨w, y⟩`` by ``w_i·δ`` while ``⟨aw, p⟩`` is untouched.
* **The CG true-residual identity** — the recurrence's carried
  ``rnorm`` tracks ``‖b − A x‖²`` to rounding; a corruption of the
  carried state (x, r, p) breaks the identity and stays broken, so a
  periodic recompute of the true residual catches what the per-apply
  check cannot (a flip BETWEEN applies).

Both comparisons are scale-normalised and judged against a drift
envelope calibrated per precision (below): rounding drift is bounded by
``O(eps·sqrt(n))`` relative to the Cauchy–Schwarz scale of the
operands, so the envelopes sit orders of magnitude above clean-solve
drift (zero false positives on the fixed-seed perfgate solves) and
orders of magnitude below any corruption that could perturb the
answer's leading digits.

The exceedance class is ``sdc`` (harness.classify) — distinct from the
non-finite ``breakdown`` class by construction: these checks fire on
finite-but-inconsistent values.
"""

from __future__ import annotations

import jax.numpy as jnp

# --------------------------------------------------------------------------
# Drift envelopes, calibrated per precision.
#
# True-residual audit: |sqrt(true) - sqrt(carried)| / sqrt(rnorm0).
# Clean-solve drift measured on the fixed-seed perfgate problems:
# O(1e-6) f32 (eps 6e-8 times a ~benchmark-budget iteration count),
# O(1e-14) f64, O(1e-13) for the df carried hi channel. The envelopes
# keep >= 2 orders of headroom above clean drift on each side.
#
# bf16 (ISSUE 17): the bf16-stream / f32-accumulate recurrence STALLS
# at its 8-bit-mantissa floor, so carried-vs-true drift on a CLEAN
# solve is O(1e-2..1e-1) (measured 2.7e-2 at 13^3 dofs, 6.4e-2 at
# 19^3, 1.1e-1 at 25^3 on the fixed-seed calibration problems) — a
# bf16 run audited against the f32 tier (1e-3) FALSE-POSITIVES on the
# first clean audit, forcing audits off and letting real flips sail
# through: the threat tests/test_bf16.py pins. The bf16 tier sits
# >= 50x above the measured clean floor and adjudicates GROSS carry
# corruption only (a 2^±8 carry flip lands O(1e2)); per-APPLY flip
# detection at bf16 is the ABFT check's job (below), whose clean floor
# is orders smaller.
RESIDUAL_ENVELOPE = {
    "f32": 1e-3,
    "f64": 1e-9,
    "df32": 1e-8,
    "bf16": 5.0,
}

# Per-apply ABFT check: |<w, y> - <aw, p>| / (||w||·||y||). The error of
# either dot is bounded by O(eps·sqrt(n)) of the Cauchy-Schwarz scale
# (the sums themselves may cancel arbitrarily — the interior rows of a
# Laplacian applied to the ones vector cancel to ~0 — which is why the
# comparison must NOT normalise by |<aw, p>| itself).
# bf16 per-apply clean floor: the Cauchy–Schwarz-normalised mismatch
# averages the per-element bf16 rounding across the dof count, measured
# 6.2e-5 (13^3), 3.3e-5 (19^3), 9.0e-6 (25^3) on the fixed-seed
# calibration problems; an early-iteration exponent-bit flip of the
# apply output lands 4.7e-3 at the 13^3 calibration size (the ones-
# checksum dilutes a single-element hit ~1/sqrt(n), so the per-apply
# check discriminates at small-to-moderate n; beyond that, carry
# corruption falls to the gross-drift tier above and the hardware
# agenda stage re-calibrates). 3.5e-3 keeps >= 50x headroom over the
# 13^3 clean floor while sitting under the measured flip signal.
ABFT_ENVELOPE = {
    "f32": 1e-4,
    "f64": 1e-10,
    "bf16": 3.5e-3,
}


def residual_envelope(dtype) -> float:
    """True-residual drift envelope for a jnp/np dtype."""
    dt = jnp.dtype(dtype)
    if dt == jnp.dtype(jnp.bfloat16):
        return RESIDUAL_ENVELOPE["bf16"]
    return (RESIDUAL_ENVELOPE["f32"]
            if dt == jnp.dtype(jnp.float32)
            else RESIDUAL_ENVELOPE["f64"])


def abft_envelope(dtype) -> float:
    """Per-apply ABFT envelope for a jnp/np dtype."""
    dt = jnp.dtype(dtype)
    if dt == jnp.dtype(jnp.bfloat16):
        return ABFT_ENVELOPE["bf16"]
    return (ABFT_ENVELOPE["f32"]
            if dt == jnp.dtype(jnp.float32)
            else ABFT_ENVELOPE["f64"])


def checksum_vectors(apply_A, like):
    """The ABFT checksum pair ``(w, aw)`` for a symmetric matrix-free
    operator: ``w`` the ones vector (every output element weighs into
    the check equally) and ``aw = A w`` computed ONCE up front — the
    precomputed ``A^T w`` of the classic row-checksum scheme, by
    symmetry. One setup apply buys an audit on every subsequent apply."""
    w = jnp.ones_like(like)
    return w, apply_A(w)


def abft_residual(w, aw, p, y, dot, ww=None) -> jnp.ndarray:
    """Scale-normalised ABFT residual of one audited apply ``y = A p``:
    ``|<w, y> - <aw, p>| / (||w||·||y|| + tiny)``. jit-safe device
    scalar — the audited CG loop carries its max, no host sync. Pass a
    precomputed ``ww = <w, w>`` to hoist the loop-invariant reduction
    out of the loop body (la.cg does)."""
    wy = dot(w, y)
    awp = dot(aw, p)
    if ww is None:
        ww = dot(w, w)
    scale = jnp.sqrt(ww * dot(y, y))
    tiny = jnp.asarray(jnp.finfo(scale.dtype).tiny, scale.dtype)
    return jnp.abs(wy - awp) / (scale + tiny)


# --------------------------------------------------------------------------
# The bit-flip fault model (shared with harness.faults — the injector
# must corrupt exactly the way the detector is judged against).


def _uint_dtype(dtype):
    size = jnp.dtype(dtype).itemsize
    if size == 2:
        return jnp.uint16
    return jnp.uint32 if size == 4 else jnp.uint64


#: default flipped bit: exponent bit 3 of the f32 layout (bit 26) — a
#: 2^±8 scale change, large enough that any audited check sees it and
#: FINITE for every value the solves produce (an exponent-MSB flip
#: would overflow to inf and be caught by the breakdown sentinel
#: instead — the point of SDC is that the value stays finite).
DEFAULT_FLIP_BIT = 26
#: the f64 twin (exponent bit 3 of the f64 layout: 2^±8 as well)
DEFAULT_FLIP_BIT_F64 = 55
#: the bf16 twin (exponent bit 3 of the bf16 layout — bf16 shares f32's
#: 8-bit exponent at bits 14..7, so exponent bit 3 is bit 10: the same
#: finite 2^±8 scale change as the f32/f64 defaults)
DEFAULT_FLIP_BIT_BF16 = 10


def default_flip_bit(dtype) -> int:
    size = jnp.dtype(dtype).itemsize
    if size == 2:
        return DEFAULT_FLIP_BIT_BF16
    return DEFAULT_FLIP_BIT if size == 4 else DEFAULT_FLIP_BIT_F64


def flip_bit(y: jnp.ndarray, index, bit: int) -> jnp.ndarray:
    """XOR one bit of one element of a device array (jit-safe): the
    mercurial-core fault model. ``index`` indexes the FLATTENED array
    and may be traced; ``index < 0`` flips the element of largest
    magnitude (guaranteed above any scale-normalised envelope)."""
    import jax

    flat = y.reshape(-1)
    udt = _uint_dtype(flat.dtype)
    idx = jnp.where(jnp.asarray(index) < 0,
                    jnp.argmax(jnp.abs(flat)).astype(jnp.int32),
                    jnp.asarray(index, jnp.int32))
    word = jax.lax.bitcast_convert_type(flat[idx], udt)
    flipped = jax.lax.bitcast_convert_type(
        word ^ jnp.asarray(1, udt) << jnp.asarray(bit, udt), flat.dtype)
    return flat.at[idx].set(flipped).reshape(y.shape)
