"""Pallas TPU kernel for the per-cell sum-factorised stiffness apply.

TPU re-design of `stiffness_operator_gpu` (/root/reference/src/
laplacian_gpu.hpp:91-426). The GPU kernel maps one thread block per cell with
Q^3 threads and shared-memory scratch; on TPU a single cell's (P+1)^3 working
set is microscopic next to the 8x128 vector lanes, so instead:

- 8*NL cells fill the full sublane x lane vreg cross-section, with the
  tensor-product indices (i, j, k) on leading, vreg-*indexed* axes — so
  slicing any contraction axis is register naming, never a sublane/lane
  shuffle;
- every sum-factorisation stage is an unrolled chain of broadcast-FMAs
  against compile-time basis-table immediates — pure VPU work at 100% vector
  occupancy (the 2-9-wide contractions would waste 96%+ of MXU tiles);
- all operands are laid out *block-major* in HBM ((nb, ..., 8, NL), one
  contiguous chunk per grid step), so the dominant traffic — the geometry
  tensor G at 6 * Q^3 values/cell — streams at full DMA bandwidth. The
  measured kernel runs at the HBM roofline (compute fully hidden behind the
  G stream).

The kernel computes gathered-cell -> per-cell-contribution; the structured
gather/fold (dofmap application) stays outside in XLA (see ops.laplacian).
float64 is not supported by Mosaic — callers fall back to the XLA einsum path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis.budgets import (
    PALLAS_CORNER_BUDGET_BYTES as _VMEM_BUDGET_CORNER_BYTES,
    PALLAS_STREAM_BUDGET_BYTES as _VMEM_BUDGET_BYTES,
    PALLAS_STREAMED_BUDGET_BYTES as _STREAMED_SCOPED_BUDGET_BYTES,
    PALLAS_STREAMED_SCOPED_KIB as STREAMED_SCOPED_KIB,
)

SUBLANES = 8  # cells fill the full sublane x lane vreg cross-section


def stream_cell_bytes(nd: int, nq: int, itemsize: int = 4) -> int:
    """Modelled per-cell VMEM of the G-streaming window kernel:
    double-buffered u/y (2*nd^3 each), double-buffered G (12*nq^3) and
    the live contraction intermediates (~7*nq^3). The ONE formula behind
    pick_lanes — the analysis rule engine (analysis.rules.R2)
    cross-checks it against captured spec footprints."""
    return (4 * nd**3 + 19 * nq**3) * itemsize


def pick_lanes(nd: int, nq: int, itemsize: int = 4) -> int:
    """Lanes-per-block so one block's VMEM working set (stream_cell_bytes
    per cell, times the 8 x lanes cells per block) fits the budget.
    128 lanes (1024 cells) through degree ~4, shrinking for the big
    high-degree working sets."""
    per_cell = stream_cell_bytes(nd, nq, itemsize)
    for nl in (128, 64, 32, 16):
        if per_cell * SUBLANES * nl <= _VMEM_BUDGET_BYTES:
            return nl
    return 8


# Corner mode swaps the 12*nq^3 double-buffered G stream for 2*25
# corner/mask values plus the in-kernel G as a ~6*nq^3 live value — a
# smaller VMEM footprint, so some configurations (degree 4, qmode 1) keep
# full 128-lane blocks that G streaming cannot. Its budget
# (analysis.budgets.PALLAS_CORNER_BUDGET_BYTES) is separate and
# deliberately tighter than the hardware ~16.5 MB: the corner kernels'
# live-value estimate carries more model risk than the streaming one.


def corner_cell_bytes(nd: int, nq: int, itemsize: int = 4) -> int:
    """Modelled per-cell VMEM of the corner-mode kernel: double-buffered
    u/y (4*nd^3), live G + contraction intermediates (~13*nq^3),
    double-buffered corners+mask (~50)."""
    return (4 * nd**3 + 13 * nq**3 + 50) * itemsize


def corner_lanes_ok(nd: int, nq: int, itemsize: int = 4) -> bool:
    """True when the corner-mode kernel fits full 128-lane blocks."""
    per_cell = corner_cell_bytes(nd, nq, itemsize)
    return per_cell * SUBLANES * 128 <= _VMEM_BUDGET_CORNER_BYTES


def block_count(C: int, nl: int) -> int:
    return -(-C // (SUBLANES * nl))


def blocked_G(G: jnp.ndarray, nl: int) -> jnp.ndarray:
    """Re-lay the geometry tensor (C, 6, nq, nq, nq) -> block-major
    (nb, 6, nq, nq, nq, 8, nl), once at operator build time. Each grid step
    then streams one fully *contiguous* 3D-dense chunk of G — the dominant
    HBM traffic of the apply (6*nq^3 values/cell) at full DMA bandwidth,
    where a strided cells-last layout measures ~6x slower."""
    C = G.shape[0]
    nb = block_count(C, nl)
    Cb = nb * SUBLANES * nl
    g = jnp.moveaxis(G, 0, -1)  # (6, nq, nq, nq, C)
    g = jnp.pad(g, [(0, 0)] * 4 + [(0, Cb - C)])
    g = g.reshape(*g.shape[:-1], SUBLANES, nb, nl)
    return jnp.moveaxis(g, -2, 0)  # (nb, 6, nq, nq, nq, 8, nl)


def _stage(mat: np.ndarray, arr, axis: int):
    """Contract the *compile-time* matrix `mat` (m, n) against tensor axis
    `axis` of `arr`, laid out (n0, n1, n2, 8, NL) — cells split over the
    sublane x lane axes, tensor-product indices on vreg-indexed leading axes.

    mat[q, i] are Python-float immediates, so each output slab is an unrolled
    chain of broadcast-FMAs over full (8, NL) vregs — pure VPU work at 100%
    occupancy, and slicing any tensor axis is vreg selection (free, no
    sublane shuffles). These contraction dims are 2-9 wide; an MXU matmul
    here would pad them to 128x128 tiles (25x+ wasted cycles), which is why
    the tables are baked in rather than passed as runtime operands."""
    m, n = mat.shape
    idx = [slice(None)] * arr.ndim

    def take(i):
        idx[axis] = i
        return arr[tuple(idx)]

    slabs = []
    for q in range(m):
        acc = float(mat[q, 0]) * take(0)
        for i in range(1, n):
            c = float(mat[q, i])
            if c != 0.0:
                acc = acc + c * take(i)
        slabs.append(acc)
    return jnp.stack(slabs, axis=axis)


def _packed_G_from_cols(cols, mask, wts1d: np.ndarray, pre_w: float,
                        n_wdiag_axes: int):
    """Shared numerically-sensitive tail of the corner geometry: Jacobian
    columns -> adjugate rows (cross products) -> detJ -> scale =
    pre_w * mask / detJ with a diagonal quadrature-weight _stage per
    remaining tensor axis -> the 6 packed upper-triangle components of
    w * detJ^-1 * (adj J)(adj J)^T. Exists exactly once so the cube
    (corner_window_G) and plane-streamed (_corner_plane_G) forms can
    never diverge — the packing order here is what sumfact_window_apply
    consumes. Ghost cells must carry an invertible placeholder Jacobian
    (unit cube, ops.folded.ghost_corner_arrays) so the division stays
    finite; their mask zeroes the result."""

    def cross(u, v):
        return (
            u[1] * v[2] - u[2] * v[1],
            u[2] * v[0] - u[0] * v[2],
            u[0] * v[1] - u[1] * v[0],
        )

    # adjugate rows K[a] = cross of the other two Jacobian columns
    K = (cross(cols[1], cols[2]), cross(cols[2], cols[0]),
         cross(cols[0], cols[1]))
    detJ = (cols[0][0] * K[0][0] + cols[0][1] * K[0][1]
            + cols[0][2] * K[0][2])
    # per-axis diagonal weight stages: scalar immediates, Mosaic-friendly
    scale = (pre_w * mask) / detJ if pre_w != 1.0 else mask / detJ
    wdiag = np.diag(np.asarray(wts1d, np.float64))
    for ax in range(n_wdiag_axes):
        scale = _stage(wdiag, scale, ax)
    pairs = ((0, 0), (0, 1), (0, 2), (1, 1), (1, 2), (2, 2))
    return tuple(
        (K[a][0] * K[b][0] + K[a][1] * K[b][1] + K[a][2] * K[b][2]) * scale
        for a, b in pairs
    )


def corner_window_G(corners, mask, pts1d: np.ndarray, wts1d: np.ndarray):
    """In-kernel geometry: trilinear Jacobian -> packed G, from the 8 cell
    corners. The streamed-geometry replacement for a precomputed G tensor:
    6*nq^3 values/cell of HBM traffic become 24 (plus ~30*nq^3 VPU FLOPs/cell,
    which the folded kernel has headroom for — it is HBM-bound).

    Same math as `geometry_computation_gpu` (/root/reference/src/
    geometry_gpu.hpp:26-133) and ops.geometry.geometry_factors_jax, restated
    as compile-time-table stages on the (8, NL) cell cross-section:

      corners (3, 2, 2, 2, 8, NL)  [component, corner offsets a/b/c, cells]
      mask    (8, NL)              1 for real cells, 0 for ghost/pad cells
      -> G tuple of 6 arrays (nq, nq, nq, 8, NL): w*detJ^-1*(adj J)(adj J)^T
         upper triangle, masked to zero on ghost cells.

    pts1d/wts1d are numpy compile-time quadrature tables; N/D (trilinear
    shape values/derivatives at the points) become FMA immediates via _stage.
    Ghost cells must carry an invertible placeholder Jacobian (unit cube,
    see ops.folded.ghost_corner_arrays) so the division stays finite.
    """
    pts = np.asarray(pts1d, np.float64)
    nq = len(pts)
    N = np.stack([1.0 - pts, pts], axis=1)  # (nq, 2)
    D = np.broadcast_to(np.array([-1.0, 1.0]), (nq, 2))
    cols = []  # cols[a][i] = d x_i / d xi_a at the nq^3 points
    for a in range(3):
        T = [N, N, N]
        T[a] = D
        col = []
        for i in range(3):
            c = corners[i]  # (2, 2, 2, 8, NL)
            c = _stage(T[2], c, 2)
            c = _stage(T[1], c, 1)
            c = _stage(T[0], c, 0)
            col.append(c)  # (nq, nq, nq, 8, NL)
        cols.append(col)

    return _packed_G_from_cols(cols, mask, wts1d, 1.0, 3)


def _corner_plane_G(corners, mask, pts1d: np.ndarray, wts1d: np.ndarray,
                    a: int):
    """One qx-plane of `corner_window_G`: the 6 packed-G components at the
    nq^2 quadrature points of x-plane `a`, as (nq, nq, 8, NL) arrays. The
    x-direction shape/derivative tables collapse to their row `a` (scalar
    immediates), so the per-plane Jacobian costs the same total FLOPs as
    the full-cube form when summed over planes — but only O(nq^2) values
    are ever live, which is what lets degree 5 qmode 1 keep full
    128-lane blocks (see sumfact_window_apply_corner_streamed; degree 6
    qmode 1 misses the budget by ~10%% even streamed —
    corner_streamed_lanes_ok)."""
    pts = np.asarray(pts1d, np.float64)
    N = np.stack([1.0 - pts, pts], axis=1)  # (nq, 2)
    D = np.broadcast_to(np.array([-1.0, 1.0]), (len(pts), 2))
    cols = []
    for d3 in range(3):
        T = [N, N, N]
        T[d3] = D
        col = []
        for i in range(3):
            c = corners[i]  # (2, 2, 2, 8, NL)
            ca = float(T[0][a, 0]) * c[0] + float(T[0][a, 1]) * c[1]
            ca = _stage(T[1], ca, 0)
            ca = _stage(T[2], ca, 1)
            col.append(ca)  # (nq, nq, 8, NL)
        cols.append(col)

    return _packed_G_from_cols(cols, mask, wts1d, float(wts1d[a]), 2)


def sumfact_window_apply_corner_streamed(u, corners, mask, kappa,
                                         phi0: np.ndarray,
                                         dphi1: np.ndarray,
                                         pts1d: np.ndarray,
                                         wts1d: np.ndarray,
                                         is_identity: bool):
    """Corner-mode contraction chain restructured as a sweep over the nq
    qx-planes, algebraically identical to
    `sumfact_window_apply(u, corner_window_G(...), ...)` but with O(nq^2)
    live geometry instead of the 6*nq^3 G cube:

      u_yz  = phi0_y phi0_z u                      (nd, nq, nq) live
      per plane a: G_a from the corners; collocation values
      u_a = phi0_x[a] u_yz and derivatives (du0 via the fused
      dphi1@phi0 x-table, du1/du2 in-plane); flux planes f0/f1/f2;
      z_a = dphi1_y^T f1 + dphi1_z^T f2;
      y_acc[id] += (dphi1@phi0)[a, id] f0 + phi0[a, id] z_a
      finally y = phi0_y^T phi0_z^T y_acc          (nd, nq, nq) live

    The per-cell live set drops from ~13*nq^3 (cube corner mode) to
    ~2*nd*nq^2 + nd^3 + O(nq^2), which keeps full 128-lane folded blocks
    at degree 5 qmode 1 where the cube form (and G streaming) cannot
    (pick_lanes/corner_lanes_ok; degree 6+ still exceeds the corner VMEM
    budget and falls back to the XLA path). Same FLOP count to leading order; the
    folded kernel is HBM-bound so the sweep's extra x-table FMAs are
    hidden. Numerically: the quadrature-point sums are reassociated
    (plane-major instead of stage-major), so results match the cube form
    to f32 rounding, not bitwise — the oracle tests bound the difference."""
    nq = len(pts1d)
    if is_identity:
        u_yz = u
        dphi_x = np.asarray(dphi1, np.float64)
        phi_x = np.eye(nq)
    else:
        u_yz = _stage(phi0, _stage(phi0, u, 2), 1)  # (nd, nq, nq, 8, NL)
        dphi_x = np.asarray(dphi1, np.float64) @ np.asarray(phi0, np.float64)
        phi_x = np.asarray(phi0, np.float64)
    nd = u_yz.shape[0]

    y_acc = None
    for a in range(nq):
        G = _corner_plane_G(corners, mask, pts1d, wts1d, a)
        # collocation values and x-derivative at plane a (reads all nd
        # u_yz planes — FMA chains against compile-time rows)
        ua = None
        du0 = None
        for i in range(nd):
            cv, cd = float(phi_x[a, i]), float(dphi_x[a, i])
            if cv != 0.0:
                ua = cv * u_yz[i] if ua is None else ua + cv * u_yz[i]
            if cd != 0.0:
                du0 = cd * u_yz[i] if du0 is None else du0 + cd * u_yz[i]
        du1 = _stage(dphi1, ua, 0)
        du2 = _stage(dphi1, ua, 1)
        f0 = kappa * (G[0] * du0 + G[1] * du1 + G[2] * du2)
        f1 = kappa * (G[1] * du0 + G[3] * du1 + G[4] * du2)
        f2 = kappa * (G[2] * du0 + G[4] * du1 + G[5] * du2)
        z_a = _stage(dphi1.T, f1, 0) + _stage(dphi1.T, f2, 1)
        # scatter plane a into the (nd, nq, nq) x-reduced accumulator
        contribs = []
        for i in range(nd):
            cv, cd = float(phi_x[a, i]), float(dphi_x[a, i])
            term = None
            if cd != 0.0:
                term = cd * f0
            if cv != 0.0:
                term = cv * z_a if term is None else term + cv * z_a
            if term is None:
                term = jnp.zeros_like(f0)
            contribs.append(term)
        plane_acc = jnp.stack(contribs, axis=0)
        y_acc = plane_acc if y_acc is None else y_acc + plane_acc

    if is_identity:
        return y_acc
    return _stage(phi0.T, _stage(phi0.T, y_acc, 2), 1)


# The plane-streamed kernels run above the DEFAULT ~16 MB scoped-VMEM
# limit (Mosaic's stack allocator lands ~1.4-1.7x the live-value model:
# degree 5 measured 19.3 MB, degree 6 23.2 MB on v5e) — they compile
# only with a raised per-compile xla_tpu_scoped_vmem_limit_kib (see
# utils.compilation; hardware-checked at degree 5: 3.82 GDoF/s at 12.5M
# dofs, MEASURE_r04.log E probe). The request is per-path because a
# blanket raise costs unaffected kernels pipeline headroom. The raised
# request and the derated admission budget both live in
# analysis.budgets (imported at the top of this module).


def streamed_cell_bytes(nd: int, nq: int, itemsize: int = 4) -> int:
    """Modelled per-cell VMEM of the plane-streamed corner kernel:
    double-buffered u/y pipeline as 4*nd^3 (the same model
    corner_cell_bytes uses for the identical streams — the two models
    must not disagree about shared terms), window (nd^3), the two
    x-reduced accumulators (2*nd*nq^2, plus one transient stack), and
    ~16 nq^2 live plane temporaries at the Jacobian/flux peaks."""
    return (5 * nd**3 + 3 * nd * nq**2 + 16 * nq**2 + 50) * itemsize


def corner_streamed_lanes_ok(nd: int, nq: int, itemsize: int = 4) -> bool:
    """True when the plane-streamed corner kernel fits full 128-lane
    folded blocks under the RAISED scoped-VMEM limit (STREAMED_SCOPED_KIB
    — every streamed config needs it; the degree-5 kernel already
    measures 19.3 MB against the 16 MB default limit): degree 5 (model
    11.5 MB) and degree 6 (16.9 MB) pass, degree 7 (24 MB -> ~41 MB
    actual) does not."""
    per_cell = streamed_cell_bytes(nd, nq, itemsize)
    return per_cell * SUBLANES * 128 <= _STREAMED_SCOPED_BUDGET_BYTES


def corner_apply(u, corners, mask, kappa, phi0: np.ndarray,
                 dphi1: np.ndarray, pts1d: np.ndarray, wts1d: np.ndarray,
                 is_identity: bool):
    """Corner-mode cell apply with the cube/streamed choice made ONCE,
    statically, from (nd, nq): the full-G-cube form while it fits VMEM
    (fewer reassociations, marginally fewer FMAs), else the plane-streamed
    form that keeps full 128-lane blocks at degree 5 qmode 1. All corner
    call sites (plain folded kernel, folded CG engine) must route through
    here so the policy cannot diverge between paths."""
    nd, nq = u.shape[0], len(pts1d)
    itemsize = jnp.dtype(u.dtype).itemsize
    if corner_lanes_ok(nd, nq, itemsize):
        G = corner_window_G(corners, mask, pts1d, wts1d)
        return sumfact_window_apply(u, G, kappa, phi0, dphi1, is_identity)
    return sumfact_window_apply_corner_streamed(
        u, corners, mask, kappa, phi0, dphi1, pts1d, wts1d, is_identity
    )


def sumfact_window_apply(u, G, kappa, phi0: np.ndarray, dphi1: np.ndarray,
                         is_identity: bool):
    """The per-cell contraction chain on one VMEM-resident cell block:
    window cube u (nd, nd, nd, 8, NL) x geometry G (6, nq, nq, nq, 8, NL)
    -> contribution cube (nd, nd, nd, 8, NL). Shared by the cells-layout and
    folded-layout kernels — it is the numerically sensitive core
    (laplacian_gpu.hpp:174-421) and must exist exactly once."""
    if not is_identity:
        u = _stage(phi0, u, 0)
        u = _stage(phi0, u, 1)
        u = _stage(phi0, u, 2)

    du0 = _stage(dphi1, u, 0)
    du1 = _stage(dphi1, u, 1)
    du2 = _stage(dphi1, u, 2)

    f0 = kappa * (G[0] * du0 + G[1] * du1 + G[2] * du2)
    f1 = kappa * (G[1] * du0 + G[3] * du1 + G[4] * du2)
    f2 = kappa * (G[2] * du0 + G[4] * du1 + G[5] * du2)

    y = _stage(dphi1.T, f0, 0) + _stage(dphi1.T, f1, 1) + _stage(dphi1.T, f2, 2)

    if not is_identity:
        y = _stage(phi0.T, y, 0)
        y = _stage(phi0.T, y, 1)
        y = _stage(phi0.T, y, 2)
    return y


def _make_kernel(nd: int, nq: int, is_identity: bool,
                 phi0: np.ndarray, dphi1: np.ndarray):
    """Kernel body for one cell block; phi0/dphi1 are numpy compile-time
    tables (fixed per operator configuration, like the reference's
    template-specialised kernels)."""

    def kernel(u_ref, g_ref, kappa_ref, out_ref):
        out_ref[0] = sumfact_window_apply(
            u_ref[0], g_ref[0], kappa_ref[0, 0], phi0, dphi1, is_identity
        )

    return kernel


_warned_interpret = False


def _use_interpret() -> bool:
    """Interpret mode when not on a TPU backend (tests on CPU). Warns once:
    interpret mode is a numerics tool, orders of magnitude slower than the
    XLA path — never a benchmark configuration."""
    global _warned_interpret
    if jax.default_backend() != "tpu":
        if not _warned_interpret:
            import warnings

            warnings.warn(
                "Pallas backend on a non-TPU host runs in interpret mode "
                "(testing only, very slow); use backend='xla' for CPU runs"
            )
            _warned_interpret = True
        return True
    return False


def block_cells_lanes(u_lanes: jnp.ndarray, nl: int) -> jnp.ndarray:
    """(nd, nd, nd, C) cells-last -> block-major (nb, nd, nd, nd, 8, nl),
    padding the cell count to a whole number of blocks. Must use the same
    cell <-> (block, sublane, lane) mapping as blocked_G."""
    nd = u_lanes.shape[0]
    C = u_lanes.shape[-1]
    nb = block_count(C, nl)
    Cb = nb * SUBLANES * nl
    u = jnp.pad(u_lanes, [(0, 0)] * 3 + [(0, Cb - C)])
    u = u.reshape(nd, nd, nd, SUBLANES, nb, nl)
    return jnp.moveaxis(u, -2, 0)


def unblock_cells_lanes(u_blocked: jnp.ndarray, C: int) -> jnp.ndarray:
    """Inverse of block_cells_lanes: (nb, nd, nd, nd, 8, nl) -> (nd, nd, nd, C)."""
    nb, nd = u_blocked.shape[0], u_blocked.shape[1]
    u = jnp.moveaxis(u_blocked, 0, -2)
    return u.reshape(nd, nd, nd, nb * SUBLANES * u_blocked.shape[-1])[..., :C]


def pallas_cell_apply_blocked(
    u_blocked: jnp.ndarray,  # (nb, nd, nd, nd, 8, nl) block-major cells
    G: jnp.ndarray,  # (nb, 6, nq, nq, nq, 8, nl) block-major (see blocked_G)
    kappa: jnp.ndarray,  # scalar
    phi0: np.ndarray,  # (nq, nd) compile-time table
    dphi1: np.ndarray,  # (nq, nq) compile-time table
    is_identity: bool,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """The hot-path entry: block-major in, block-major out. Each grid step
    DMAs one contiguous u block and one contiguous G chunk into VMEM and
    writes one contiguous y block — all HBM traffic is dense streaming."""
    nq, nd = phi0.shape
    nb, nl = u_blocked.shape[0], u_blocked.shape[-1]
    dtype = u_blocked.dtype

    kernel = _make_kernel(
        nd, nq, is_identity, np.asarray(phi0, np.float64),
        np.asarray(dphi1, np.float64),
    )
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec(
                (1, nd, nd, nd, SUBLANES, nl), lambda i: (i, 0, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 6, nq, nq, nq, SUBLANES, nl),
                lambda i: (i, 0, 0, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, nd, nd, nd, SUBLANES, nl), lambda i: (i, 0, 0, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(u_blocked.shape, dtype),
        interpret=_use_interpret() if interpret is None else interpret,
    )(u_blocked, G, kappa.reshape(1, 1).astype(dtype))


def pallas_cell_apply(
    u_cells: jnp.ndarray,  # (C, nd, nd, nd)
    G: jnp.ndarray,  # (C, 6, nq, nq, nq)
    phi0,  # (nq, nd) concrete array
    dphi1,  # (nq, nq) concrete array
    kappa: jnp.ndarray,  # scalar
    nd: int,
    nq: int,
    is_identity: bool,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Cells-first convenience wrapper (tests, API parity with the XLA path):
    re-lays operands block-major around pallas_cell_apply_blocked. phi0/dphi1
    must be concrete (numpy or non-traced) — they become compile-time
    constants of the kernel."""
    C = u_cells.shape[0]
    nl = pick_lanes(nd, nq, np.dtype(u_cells.dtype).itemsize)
    u = block_cells_lanes(jnp.moveaxis(u_cells, 0, -1), nl)
    g = blocked_G(G.astype(u_cells.dtype), nl)
    out = pallas_cell_apply_blocked(
        u, g, kappa, np.asarray(phi0, np.float64), np.asarray(dphi1, np.float64),
        is_identity, interpret=interpret,
    )
    return jnp.moveaxis(unblock_cells_lanes(out, C), -1, 0)
