"""Pallas TPU kernel for the per-cell sum-factorised stiffness apply.

TPU re-design of `stiffness_operator_gpu` (/root/reference/src/
laplacian_gpu.hpp:91-426). The GPU kernel maps one thread block per cell with
Q^3 threads and shared-memory scratch; on TPU a single cell's (P+1)^3 working
set is microscopic next to the 8x128 vector lanes, so instead:

- cells are batched along the 128-wide *lane* axis (`NC` cells per grid
  step), with the tensor-product index occupying the sublane axis;
- every sum-factorisation stage is then one (small x small) @ (small x
  big-batch) matmul streaming over the lane dimension — MXU work with all
  intermediates held in VMEM (the analogue of the GPU kernel's shared-memory
  scratch, but for hundreds of cells at once);
- the geometry tensor G is streamed HBM -> VMEM once per block, which is the
  dominant memory traffic (6 * Q^3 values/cell), exactly as in the reference.

The kernel computes gathered-cell -> per-cell-contribution; the structured
gather/fold (dofmap application) stays outside in XLA (see ops.laplacian).
float64 is not supported by Mosaic — callers fall back to the XLA einsum path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_CELLS = 512
_VMEM_BUDGET_BYTES = 10 * 1024 * 1024  # leave headroom in the ~16 MB VMEM


def pick_block_cells(nd: int, nq: int, itemsize: int = 4) -> int:
    """Largest 128-multiple cell-batch whose per-block VMEM working set
    (G: 6*nq^3, intermediates: ~8*nq^3, u/y: 2*nd^3 values per cell) fits
    the budget, capped at DEFAULT_BLOCK_CELLS."""
    per_cell = (6 * nq**3 + 8 * nq**3 + 2 * nd**3) * itemsize
    nc = (_VMEM_BUDGET_BYTES // per_cell) // 128 * 128
    return int(max(128, min(DEFAULT_BLOCK_CELLS, nc)))


def cells_last_G(G: jnp.ndarray) -> jnp.ndarray:
    """Re-lay the geometry tensor (C, 6, nq, nq, nq) -> (6, nq, nq, nq, C)
    once at operator build time, so the per-iteration apply streams it
    without a transposing copy (G is the dominant HBM traffic)."""
    return jnp.moveaxis(G, 0, -1)


def _stage(mat: jnp.ndarray, arr: jnp.ndarray, axis: int, nd3: tuple[int, int, int], nc: int):
    """Contract `mat` (m, n) against tensor axis `axis` of `arr`, which is
    laid out (n0, n1, n2, NC) with cells last. Returns the new array with
    that axis replaced by m. The contraction is expressed as a single 2D
    matmul (m, n) @ (n, rest*NC) after rotating `axis` to the front."""
    n0, n1, n2 = nd3
    if axis == 0:
        a2 = arr.reshape(n0, n1 * n2 * nc)
        out = jnp.dot(mat, a2, preferred_element_type=arr.dtype)
        return out.reshape(mat.shape[0], n1, n2, nc)
    if axis == 1:
        a = jnp.moveaxis(arr, 1, 0).reshape(n1, n0 * n2 * nc)
        out = jnp.dot(mat, a, preferred_element_type=arr.dtype)
        return jnp.moveaxis(out.reshape(mat.shape[0], n0, n2, nc), 0, 1)
    a = jnp.moveaxis(arr, 2, 0).reshape(n2, n0 * n1 * nc)
    out = jnp.dot(mat, a, preferred_element_type=arr.dtype)
    return jnp.moveaxis(out.reshape(mat.shape[0], n0, n1, nc), 0, 2)


def _make_kernel(nd: int, nq: int, nc: int, is_identity: bool):
    def kernel(u_ref, g_ref, phi0_ref, dphi1_ref, kappa_ref, out_ref):
        u = u_ref[...]  # (nd, nd, nd, NC)
        phi0 = phi0_ref[...]
        dphi1 = dphi1_ref[...]
        kappa = kappa_ref[0, 0]

        if not is_identity:
            u = _stage(phi0, u, 0, (nd, nd, nd), nc)
            u = _stage(phi0, u, 1, (nq, nd, nd), nc)
            u = _stage(phi0, u, 2, (nq, nq, nd), nc)

        q3 = (nq, nq, nq)
        du0 = _stage(dphi1, u, 0, q3, nc)
        du1 = _stage(dphi1, u, 1, q3, nc)
        du2 = _stage(dphi1, u, 2, q3, nc)

        G = g_ref[...]  # (6, nq, nq, nq, NC)
        f0 = kappa * (G[0] * du0 + G[1] * du1 + G[2] * du2)
        f1 = kappa * (G[1] * du0 + G[3] * du1 + G[4] * du2)
        f2 = kappa * (G[2] * du0 + G[4] * du1 + G[5] * du2)

        dphi1_t = dphi1.T
        y = _stage(dphi1_t, f0, 0, q3, nc)
        y = y + _stage(dphi1_t, f1, 1, q3, nc)
        y = y + _stage(dphi1_t, f2, 2, q3, nc)

        if not is_identity:
            phi0_t = phi0.T
            y = _stage(phi0_t, y, 0, (nq, nq, nq), nc)
            y = _stage(phi0_t, y, 1, (nd, nq, nq), nc)
            y = _stage(phi0_t, y, 2, (nd, nd, nq), nc)

        out_ref[...] = y

    return kernel


_warned_interpret = False


def _use_interpret() -> bool:
    """Interpret mode when not on a TPU backend (tests on CPU). Warns once:
    interpret mode is a numerics tool, orders of magnitude slower than the
    XLA path — never a benchmark configuration."""
    global _warned_interpret
    if jax.default_backend() != "tpu":
        if not _warned_interpret:
            import warnings

            warnings.warn(
                "Pallas backend on a non-TPU host runs in interpret mode "
                "(testing only, very slow); use backend='xla' for CPU runs"
            )
            _warned_interpret = True
        return True
    return False


@partial(
    jax.jit,
    static_argnames=(
        "nd", "nq", "is_identity", "g_cells_last", "block_cells", "interpret"
    ),
)
def pallas_cell_apply(
    u_cells: jnp.ndarray,  # (C, nd, nd, nd)
    G: jnp.ndarray,  # (C, 6, nq, nq, nq) or cells-last (6, nq, nq, nq, C)
    phi0: jnp.ndarray,  # (nq, nd)
    dphi1: jnp.ndarray,  # (nq, nq)
    kappa: jnp.ndarray,  # scalar
    nd: int,
    nq: int,
    is_identity: bool,
    g_cells_last: bool = False,
    block_cells: int | None = None,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Drop-in replacement for ops.laplacian._sumfact_cell_apply backed by the
    Pallas kernel. Pads the cell count to a block multiple, transposes to the
    cells-last layout, and grids over cell blocks. Pass G pre-transposed
    (g_cells_last=True, see cells_last_G) to keep the per-apply hot path free
    of layout copies."""
    C = u_cells.shape[0]
    dtype = u_cells.dtype
    if block_cells is None:
        block_cells = pick_block_cells(nd, nq, np.dtype(dtype).itemsize)
    nc = min(block_cells, max(128, 1 << (C - 1).bit_length()))
    nblocks = pl.cdiv(C, nc)
    Cp = nblocks * nc

    u = jnp.moveaxis(u_cells, 0, -1)  # (nd, nd, nd, C)
    g = G if g_cells_last else jnp.moveaxis(G, 0, -1)  # (6, nq, nq, nq, C)
    if Cp != C:
        u = jnp.pad(u, [(0, 0)] * 3 + [(0, Cp - C)])
        g = jnp.pad(g, [(0, 0)] * 4 + [(0, Cp - C)])

    kernel = _make_kernel(nd, nq, nc, is_identity)
    out = pl.pallas_call(
        kernel,
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec(
                (nd, nd, nd, nc), lambda i: (0, 0, 0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (6, nq, nq, nq, nc),
                lambda i: (0, 0, 0, 0, i),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (nd, nd, nd, nc), lambda i: (0, 0, 0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((nd, nd, nd, Cp), dtype),
        interpret=_use_interpret() if interpret is None else interpret,
    )(u, g, phi0.astype(dtype), dphi1.astype(dtype), kappa.reshape(1, 1).astype(dtype))

    out = jnp.moveaxis(out, -1, 0)[:C]
    return out
