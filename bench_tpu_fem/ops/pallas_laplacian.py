"""Pallas TPU kernel for the per-cell sum-factorised stiffness apply.

TPU re-design of `stiffness_operator_gpu` (/root/reference/src/
laplacian_gpu.hpp:91-426). The GPU kernel maps one thread block per cell with
Q^3 threads and shared-memory scratch; on TPU a single cell's (P+1)^3 working
set is microscopic next to the 8x128 vector lanes, so instead:

- 8*NL cells fill the full sublane x lane vreg cross-section, with the
  tensor-product indices (i, j, k) on leading, vreg-*indexed* axes — so
  slicing any contraction axis is register naming, never a sublane/lane
  shuffle;
- every sum-factorisation stage is an unrolled chain of broadcast-FMAs
  against compile-time basis-table immediates — pure VPU work at 100% vector
  occupancy (the 2-9-wide contractions would waste 96%+ of MXU tiles);
- all operands are laid out *block-major* in HBM ((nb, ..., 8, NL), one
  contiguous chunk per grid step), so the dominant traffic — the geometry
  tensor G at 6 * Q^3 values/cell — streams at full DMA bandwidth. The
  measured kernel runs at the HBM roofline (compute fully hidden behind the
  G stream).

The kernel computes gathered-cell -> per-cell-contribution; the structured
gather/fold (dofmap application) stays outside in XLA (see ops.laplacian).
float64 is not supported by Mosaic — callers fall back to the XLA einsum path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

SUBLANES = 8  # cells fill the full sublane x lane vreg cross-section
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024  # leave headroom in the ~16 MB VMEM


def pick_lanes(nd: int, nq: int, itemsize: int = 4) -> int:
    """Lanes-per-block so one block's VMEM working set fits the budget:
    double-buffered u/y (2*nd^3 each), double-buffered G (12*nq^3) and the
    live contraction intermediates (~7*nq^3), all per cell, times the
    8 x lanes cells per block. 128 lanes (1024 cells) through degree ~4,
    shrinking for the big high-degree working sets."""
    per_cell = (4 * nd**3 + 19 * nq**3) * itemsize
    for nl in (128, 64, 32, 16):
        if per_cell * SUBLANES * nl <= _VMEM_BUDGET_BYTES:
            return nl
    return 8


# Corner mode swaps the 12*nq^3 double-buffered G stream for 2*25
# corner/mask values plus the in-kernel G as a ~6*nq^3 live value — a
# smaller VMEM footprint, so some configurations (degree 4, qmode 1) keep
# full 128-lane blocks that G streaming cannot. Its budget is separate and
# deliberately tighter than the hardware ~16.5 MB: the corner kernels'
# live-value estimate carries more model risk than the streaming one.
_VMEM_BUDGET_CORNER_BYTES = 14 * 1024 * 1024


def corner_lanes_ok(nd: int, nq: int, itemsize: int = 4) -> bool:
    """True when the corner-mode kernel fits full 128-lane blocks:
    double-buffered u/y (4*nd^3), live G + contraction intermediates
    (~13*nq^3), double-buffered corners+mask (~50)."""
    per_cell = (4 * nd**3 + 13 * nq**3 + 50) * itemsize
    return per_cell * SUBLANES * 128 <= _VMEM_BUDGET_CORNER_BYTES


def block_count(C: int, nl: int) -> int:
    return -(-C // (SUBLANES * nl))


def blocked_G(G: jnp.ndarray, nl: int) -> jnp.ndarray:
    """Re-lay the geometry tensor (C, 6, nq, nq, nq) -> block-major
    (nb, 6, nq, nq, nq, 8, nl), once at operator build time. Each grid step
    then streams one fully *contiguous* 3D-dense chunk of G — the dominant
    HBM traffic of the apply (6*nq^3 values/cell) at full DMA bandwidth,
    where a strided cells-last layout measures ~6x slower."""
    C = G.shape[0]
    nb = block_count(C, nl)
    Cb = nb * SUBLANES * nl
    g = jnp.moveaxis(G, 0, -1)  # (6, nq, nq, nq, C)
    g = jnp.pad(g, [(0, 0)] * 4 + [(0, Cb - C)])
    g = g.reshape(*g.shape[:-1], SUBLANES, nb, nl)
    return jnp.moveaxis(g, -2, 0)  # (nb, 6, nq, nq, nq, 8, nl)


def _stage(mat: np.ndarray, arr, axis: int):
    """Contract the *compile-time* matrix `mat` (m, n) against tensor axis
    `axis` of `arr`, laid out (n0, n1, n2, 8, NL) — cells split over the
    sublane x lane axes, tensor-product indices on vreg-indexed leading axes.

    mat[q, i] are Python-float immediates, so each output slab is an unrolled
    chain of broadcast-FMAs over full (8, NL) vregs — pure VPU work at 100%
    occupancy, and slicing any tensor axis is vreg selection (free, no
    sublane shuffles). These contraction dims are 2-9 wide; an MXU matmul
    here would pad them to 128x128 tiles (25x+ wasted cycles), which is why
    the tables are baked in rather than passed as runtime operands."""
    m, n = mat.shape
    idx = [slice(None)] * arr.ndim

    def take(i):
        idx[axis] = i
        return arr[tuple(idx)]

    slabs = []
    for q in range(m):
        acc = float(mat[q, 0]) * take(0)
        for i in range(1, n):
            c = float(mat[q, i])
            if c != 0.0:
                acc = acc + c * take(i)
        slabs.append(acc)
    return jnp.stack(slabs, axis=axis)


def corner_window_G(corners, mask, pts1d: np.ndarray, wts1d: np.ndarray):
    """In-kernel geometry: trilinear Jacobian -> packed G, from the 8 cell
    corners. The streamed-geometry replacement for a precomputed G tensor:
    6*nq^3 values/cell of HBM traffic become 24 (plus ~30*nq^3 VPU FLOPs/cell,
    which the folded kernel has headroom for — it is HBM-bound).

    Same math as `geometry_computation_gpu` (/root/reference/src/
    geometry_gpu.hpp:26-133) and ops.geometry.geometry_factors_jax, restated
    as compile-time-table stages on the (8, NL) cell cross-section:

      corners (3, 2, 2, 2, 8, NL)  [component, corner offsets a/b/c, cells]
      mask    (8, NL)              1 for real cells, 0 for ghost/pad cells
      -> G tuple of 6 arrays (nq, nq, nq, 8, NL): w*detJ^-1*(adj J)(adj J)^T
         upper triangle, masked to zero on ghost cells.

    pts1d/wts1d are numpy compile-time quadrature tables; N/D (trilinear
    shape values/derivatives at the points) become FMA immediates via _stage.
    Ghost cells must carry an invertible placeholder Jacobian (unit cube,
    see ops.folded.ghost_corner_arrays) so the division stays finite.
    """
    pts = np.asarray(pts1d, np.float64)
    nq = len(pts)
    N = np.stack([1.0 - pts, pts], axis=1)  # (nq, 2)
    D = np.broadcast_to(np.array([-1.0, 1.0]), (nq, 2))
    cols = []  # cols[a][i] = d x_i / d xi_a at the nq^3 points
    for a in range(3):
        T = [N, N, N]
        T[a] = D
        col = []
        for i in range(3):
            c = corners[i]  # (2, 2, 2, 8, NL)
            c = _stage(T[2], c, 2)
            c = _stage(T[1], c, 1)
            c = _stage(T[0], c, 0)
            col.append(c)  # (nq, nq, nq, 8, NL)
        cols.append(col)

    def cross(u, v):
        return (
            u[1] * v[2] - u[2] * v[1],
            u[2] * v[0] - u[0] * v[2],
            u[0] * v[1] - u[1] * v[0],
        )

    # adjugate rows K[a] = cross of the other two Jacobian columns
    K = (cross(cols[1], cols[2]), cross(cols[2], cols[0]),
         cross(cols[0], cols[1]))
    detJ = (cols[0][0] * K[0][0] + cols[0][1] * K[0][1]
            + cols[0][2] * K[0][2])
    # scale = mask * w3 / detJ; w3 = w⊗w⊗w applied as three diagonal stages
    # (per-plane scalar immediates — Mosaic-friendly, no constant arrays).
    scale = mask / detJ
    wdiag = np.diag(np.asarray(wts1d, np.float64))
    for ax in range(3):
        scale = _stage(wdiag, scale, ax)
    pairs = ((0, 0), (0, 1), (0, 2), (1, 1), (1, 2), (2, 2))
    return tuple(
        (K[a][0] * K[b][0] + K[a][1] * K[b][1] + K[a][2] * K[b][2]) * scale
        for a, b in pairs
    )


def sumfact_window_apply(u, G, kappa, phi0: np.ndarray, dphi1: np.ndarray,
                         is_identity: bool):
    """The per-cell contraction chain on one VMEM-resident cell block:
    window cube u (nd, nd, nd, 8, NL) x geometry G (6, nq, nq, nq, 8, NL)
    -> contribution cube (nd, nd, nd, 8, NL). Shared by the cells-layout and
    folded-layout kernels — it is the numerically sensitive core
    (laplacian_gpu.hpp:174-421) and must exist exactly once."""
    if not is_identity:
        u = _stage(phi0, u, 0)
        u = _stage(phi0, u, 1)
        u = _stage(phi0, u, 2)

    du0 = _stage(dphi1, u, 0)
    du1 = _stage(dphi1, u, 1)
    du2 = _stage(dphi1, u, 2)

    f0 = kappa * (G[0] * du0 + G[1] * du1 + G[2] * du2)
    f1 = kappa * (G[1] * du0 + G[3] * du1 + G[4] * du2)
    f2 = kappa * (G[2] * du0 + G[4] * du1 + G[5] * du2)

    y = _stage(dphi1.T, f0, 0) + _stage(dphi1.T, f1, 1) + _stage(dphi1.T, f2, 2)

    if not is_identity:
        y = _stage(phi0.T, y, 0)
        y = _stage(phi0.T, y, 1)
        y = _stage(phi0.T, y, 2)
    return y


def _make_kernel(nd: int, nq: int, is_identity: bool,
                 phi0: np.ndarray, dphi1: np.ndarray):
    """Kernel body for one cell block; phi0/dphi1 are numpy compile-time
    tables (fixed per operator configuration, like the reference's
    template-specialised kernels)."""

    def kernel(u_ref, g_ref, kappa_ref, out_ref):
        out_ref[0] = sumfact_window_apply(
            u_ref[0], g_ref[0], kappa_ref[0, 0], phi0, dphi1, is_identity
        )

    return kernel


_warned_interpret = False


def _use_interpret() -> bool:
    """Interpret mode when not on a TPU backend (tests on CPU). Warns once:
    interpret mode is a numerics tool, orders of magnitude slower than the
    XLA path — never a benchmark configuration."""
    global _warned_interpret
    if jax.default_backend() != "tpu":
        if not _warned_interpret:
            import warnings

            warnings.warn(
                "Pallas backend on a non-TPU host runs in interpret mode "
                "(testing only, very slow); use backend='xla' for CPU runs"
            )
            _warned_interpret = True
        return True
    return False


def block_cells_lanes(u_lanes: jnp.ndarray, nl: int) -> jnp.ndarray:
    """(nd, nd, nd, C) cells-last -> block-major (nb, nd, nd, nd, 8, nl),
    padding the cell count to a whole number of blocks. Must use the same
    cell <-> (block, sublane, lane) mapping as blocked_G."""
    nd = u_lanes.shape[0]
    C = u_lanes.shape[-1]
    nb = block_count(C, nl)
    Cb = nb * SUBLANES * nl
    u = jnp.pad(u_lanes, [(0, 0)] * 3 + [(0, Cb - C)])
    u = u.reshape(nd, nd, nd, SUBLANES, nb, nl)
    return jnp.moveaxis(u, -2, 0)


def unblock_cells_lanes(u_blocked: jnp.ndarray, C: int) -> jnp.ndarray:
    """Inverse of block_cells_lanes: (nb, nd, nd, nd, 8, nl) -> (nd, nd, nd, C)."""
    nb, nd = u_blocked.shape[0], u_blocked.shape[1]
    u = jnp.moveaxis(u_blocked, 0, -2)
    return u.reshape(nd, nd, nd, nb * SUBLANES * u_blocked.shape[-1])[..., :C]


def pallas_cell_apply_blocked(
    u_blocked: jnp.ndarray,  # (nb, nd, nd, nd, 8, nl) block-major cells
    G: jnp.ndarray,  # (nb, 6, nq, nq, nq, 8, nl) block-major (see blocked_G)
    kappa: jnp.ndarray,  # scalar
    phi0: np.ndarray,  # (nq, nd) compile-time table
    dphi1: np.ndarray,  # (nq, nq) compile-time table
    is_identity: bool,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """The hot-path entry: block-major in, block-major out. Each grid step
    DMAs one contiguous u block and one contiguous G chunk into VMEM and
    writes one contiguous y block — all HBM traffic is dense streaming."""
    nq, nd = phi0.shape
    nb, nl = u_blocked.shape[0], u_blocked.shape[-1]
    dtype = u_blocked.dtype

    kernel = _make_kernel(
        nd, nq, is_identity, np.asarray(phi0, np.float64),
        np.asarray(dphi1, np.float64),
    )
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec(
                (1, nd, nd, nd, SUBLANES, nl), lambda i: (i, 0, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 6, nq, nq, nq, SUBLANES, nl),
                lambda i: (i, 0, 0, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, nd, nd, nd, SUBLANES, nl), lambda i: (i, 0, 0, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct(u_blocked.shape, dtype),
        interpret=_use_interpret() if interpret is None else interpret,
    )(u_blocked, G, kappa.reshape(1, 1).astype(dtype))


def pallas_cell_apply(
    u_cells: jnp.ndarray,  # (C, nd, nd, nd)
    G: jnp.ndarray,  # (C, 6, nq, nq, nq)
    phi0,  # (nq, nd) concrete array
    dphi1,  # (nq, nq) concrete array
    kappa: jnp.ndarray,  # scalar
    nd: int,
    nq: int,
    is_identity: bool,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """Cells-first convenience wrapper (tests, API parity with the XLA path):
    re-lays operands block-major around pallas_cell_apply_blocked. phi0/dphi1
    must be concrete (numpy or non-traced) — they become compile-time
    constants of the kernel."""
    C = u_cells.shape[0]
    nl = pick_lanes(nd, nq, np.dtype(u_cells.dtype).itemsize)
    u = block_cells_lanes(jnp.moveaxis(u_cells, 0, -1), nl)
    g = blocked_G(G.astype(u_cells.dtype), nl)
    out = pallas_cell_apply_blocked(
        u, g, kappa, np.asarray(phi0, np.float64), np.asarray(dphi1, np.float64),
        is_identity, interpret=interpret,
    )
    return jnp.moveaxis(unblock_cells_lanes(out, C), -1, 0)
