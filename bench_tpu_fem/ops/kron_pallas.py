"""Pallas TPU kernels for the Kronecker (uniform-mesh) banded apply.

The XLA formulation of ops.kron.banded_apply (pad + 2P+1 shifted slices)
leaves ~2x on the table and its fusion choices vary by shape; these three
kernels make the seven banded 1D contractions deterministic and stream each
operand exactly once:

- Z kernel  : u -> (K_z u, M_z u)                 shifts along lanes
- Y kernel  : (aK, aM) -> (M_y aK + K_y aM, M_y aM)   shifts along sublanes
- X kernel  : (t12, tyz, x) -> kappa (M_x t12 + K_x tyz), blended with the
              Dirichlet pass-through (y = notbc * y + bc * x)  [epilogue]

Shifts stay inside each tile: every kernel's tile spans the *full* extent of
its contraction axis (the other two axes are gridded), so no halo exchange
between grid steps is ever needed. Out-of-range rows are killed by the zero
boundary rows of the banded-diagonal storage (ops.kron.banded_diags), not by
bounds logic. Per CG iteration the apply streams ~7 vectors total; the
per-cell geometry stream of the general path (and of the reference,
/root/reference/src/laplacian_gpu.hpp:91-426) is absent entirely.

All tensor-product structure mirrors the reference operator semantics
(laplacian.hpp:281-403); the Kronecker factorisation itself is tested exact
against the assembled oracle in tests/test_kron.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_laplacian import _use_interpret


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


def _shifted(xp: jnp.ndarray, di: int, n: int, axis: int) -> jnp.ndarray:
    """Slice window [di, di+n) along `axis` of the (pre-padded) tile."""
    idx = [slice(None)] * xp.ndim
    idx[axis] = slice(di, di + n)
    return xp[tuple(idx)]


def _make_z_kernel(P: int, NZ: int):
    """(TR, NZ) row-block -> (K_z u, M_z u); shifts along the lane axis."""

    def kern(x_ref, ck_ref, cm_ref, aK_ref, aM_ref):
        x = x_ref[...]
        xp = jnp.pad(x, ((0, 0), (P, P)))
        accK = accM = None
        for di in range(2 * P + 1):
            s = _shifted(xp, di, NZ, 1)
            k = ck_ref[di][None, :] * s
            m = cm_ref[di][None, :] * s
            accK = k if accK is None else accK + k
            accM = m if accM is None else accM + m
        aK_ref[...] = accK
        aM_ref[...] = accM

    return kern


def _make_y_kernel(P: int, NY: int):
    """(NY, CZ) slab -> (M_y aK + K_y aM, M_y aM); shifts along sublanes."""

    def kern(aK_ref, aM_ref, ck_ref, cm_ref, t12_ref, tyz_ref):
        aK = aK_ref[0]
        aM = aM_ref[0]
        aKp = jnp.pad(aK, ((P, P), (0, 0)))
        aMp = jnp.pad(aM, ((P, P), (0, 0)))
        t12 = tyz = None
        for di in range(2 * P + 1):
            sK = _shifted(aKp, di, NY, 0)
            sM = _shifted(aMp, di, NY, 0)
            cK = ck_ref[di][:, None]
            cM = cm_ref[di][:, None]
            a = cM * sK + cK * sM
            b = cM * sM
            t12 = a if t12 is None else t12 + a
            tyz = b if tyz is None else tyz + b
        t12_ref[0] = t12
        tyz_ref[0] = tyz

    return kern


def _make_x_kernel(P: int, NX: int):
    """(NX, CL) slab -> kappa (M_x t12 + K_x tyz) with the Dirichlet blend
    (kappa is folded into the coefficient operands at call time)."""

    def kern(t12_ref, tyz_ref, x_ref, cm_ref, ck_ref, mx_ref, nbc_ref, y_ref):
        t12p = jnp.pad(t12_ref[...], ((P, P), (0, 0)))
        tyzp = jnp.pad(tyz_ref[...], ((P, P), (0, 0)))
        acc = None
        for di in range(2 * P + 1):
            a = cm_ref[di][:, None] * _shifted(t12p, di, NX, 0) \
                + ck_ref[di][:, None] * _shifted(tyzp, di, NX, 0)
            acc = a if acc is None else acc + a
        nb = mx_ref[...] * nbc_ref[...]  # (NX, 1) * (1, CL) outer broadcast
        y_ref[...] = nb * acc + (1.0 - nb) * x_ref[...]

    return kern


def _vspec(bs, ix):
    return pl.BlockSpec(bs, ix, memory_space=pltpu.VMEM)


def z_stage_pallas(x, Kzd, Mzd, P, interpret, row_block=256):
    """(NX, NY, NZ) -> (K_z x, M_z x), both (NX, NY, NZ). Coefficient arrays
    are (2P+1, NZ) banded diagonals (any slice of a global banded matrix —
    the distributed path passes per-shard slices)."""
    NX, NY, NZ = x.shape
    dtype = x.dtype
    R = NX * NY
    TR = min(row_block, R)
    x2 = x.reshape(R, NZ)
    aK, aM = pl.pallas_call(
        _make_z_kernel(P, NZ),
        grid=(_cdiv(R, TR),),
        in_specs=[
            _vspec((TR, NZ), lambda i: (i, 0)),
            _vspec((2 * P + 1, NZ), lambda i: (0, 0)),
            _vspec((2 * P + 1, NZ), lambda i: (0, 0)),
        ],
        out_specs=[
            _vspec((TR, NZ), lambda i: (i, 0)),
            _vspec((TR, NZ), lambda i: (i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((R, NZ), dtype)] * 2,
        interpret=interpret,
    )(x2, Kzd.astype(dtype), Mzd.astype(dtype))
    return aK.reshape(NX, NY, NZ), aM.reshape(NX, NY, NZ)


def y_stage_pallas(aK3, aM3, Kyd, Myd, P, interpret, lane_block=512):
    """(aK, aM) -> (t12 = M_y aK + K_y aM, tyz = M_y aM)."""
    NX, NY, NZ = aK3.shape
    dtype = aK3.dtype
    CZ = min(lane_block, NZ)
    return pl.pallas_call(
        _make_y_kernel(P, NY),
        grid=(NX, _cdiv(NZ, CZ)),
        in_specs=[
            _vspec((1, NY, CZ), lambda i, j: (i, 0, j)),
            _vspec((1, NY, CZ), lambda i, j: (i, 0, j)),
            _vspec((2 * P + 1, NY), lambda i, j: (0, 0)),
            _vspec((2 * P + 1, NY), lambda i, j: (0, 0)),
        ],
        out_specs=[
            _vspec((1, NY, CZ), lambda i, j: (i, 0, j)),
            _vspec((1, NY, CZ), lambda i, j: (i, 0, j)),
        ],
        out_shape=[jax.ShapeDtypeStruct((NX, NY, NZ), dtype)] * 2,
        interpret=interpret,
    )(aK3, aM3, Kyd.astype(dtype), Myd.astype(dtype))


def x_stage_pallas(t12, tyz, x, cMx, cKx, mx, nbc_yz, P, interpret,
                   lane_block=512):
    """(t12, tyz, x) -> blended y = nb * (cMx t12 + cKx tyz) + (1 - nb) x.
    kappa is pre-folded into cMx/cKx by the caller; nb = mx (outer) nbc_yz."""
    NX, NY, NZ = x.shape
    dtype = x.dtype
    RZ = NY * NZ
    CL = min(lane_block, RZ)
    y2 = pl.pallas_call(
        _make_x_kernel(P, NX),
        grid=(_cdiv(RZ, CL),),
        in_specs=[
            _vspec((NX, CL), lambda i: (0, i)),
            _vspec((NX, CL), lambda i: (0, i)),
            _vspec((NX, CL), lambda i: (0, i)),
            _vspec((2 * P + 1, NX), lambda i: (0, 0)),
            _vspec((2 * P + 1, NX), lambda i: (0, 0)),
            _vspec((NX, 1), lambda i: (0, 0)),
            _vspec((1, CL), lambda i: (0, i)),
        ],
        out_specs=_vspec((NX, CL), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((NX, RZ), dtype),
        interpret=interpret,
    )(
        t12.reshape(NX, RZ),
        tyz.reshape(NX, RZ),
        x.reshape(NX, RZ),
        cMx.astype(dtype),
        cKx.astype(dtype),
        mx[:, None].astype(dtype),
        nbc_yz.astype(dtype),
    )
    return y2.reshape(NX, NY, NZ)


def kron_apply_pallas(
    x: jnp.ndarray,  # (NX, NY, NZ) dof grid
    Kd: tuple,  # 3x (2P+1, N_a) banded diagonals (bc-folded)
    Md: tuple,
    notbc1d: tuple,  # 3x (N_a,)
    kappa: jnp.ndarray,
    degree: int,
    interpret: bool | None = None,
    row_block: int = 256,
    lane_block: int = 512,
) -> jnp.ndarray:
    """Full uniform-mesh operator apply as three Pallas kernels."""
    P = degree
    interp = _use_interpret() if interpret is None else interpret

    # kappa folds into the x-axis coefficients (the final stage).
    cMx = kappa * Md[0]
    cKx = kappa * Kd[0]
    mx, my, mz = notbc1d
    NY, NZ = x.shape[1], x.shape[2]
    nbc_yz = (my[:, None] * mz[None, :]).reshape(1, NY * NZ)

    aK, aM = z_stage_pallas(x, Kd[2], Md[2], P, interp, row_block)
    t12, tyz = y_stage_pallas(aK, aM, Kd[1], Md[1], P, interp, lane_block)
    return x_stage_pallas(
        t12, tyz, x, cMx, cKx, mx, nbc_yz, P, interp, lane_block
    )
