"""The matrix-free sum-factorised Laplacian apply (the hot path).

TPU-first re-design of `stiffness_operator_gpu`
(/root/reference/src/laplacian_gpu.hpp:91-426) and its host dispatcher
`MatFreeLaplacianGPU::apply` (laplacian.hpp:281-403):

- The dof vector is a 3D *grid* array (NX, NY, NZ) — the tensor-product
  dofmap of the box mesh is implicit in the layout, so "gather via dofmap"
  becomes three per-axis `take`s and "atomicAdd scatter" becomes three
  per-axis overlap-add folds (deterministic, XLA-friendly, no atomics).
- Each sum-factorisation stage (interpolation phi0, collocation derivative
  dphi1, transpose stages) is a single batched matmul over *all* cells at
  once — these are the MXU ops. Degree/qmode are static (compile-time)
  parameters, replacing the reference's template dispatch if-chain
  (laplacian.hpp:361-398).
- Dirichlet semantics match laplacian_gpu.hpp:163-169,423-425: constrained
  dofs contribute zero on input, and output rows pass the input through
  (y[bc] = x[bc]).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..elements.tables import OperatorTables, build_operator_tables
from ..mesh.box import BoxMesh
from ..mesh.dofmap import boundary_dof_marker
from .geometry import geometry_factors_jax


def gather_cells(x_grid: jnp.ndarray, n: tuple[int, int, int], degree: int) -> jnp.ndarray:
    """(NX, NY, NZ) grid -> (ncells, nd, nd, nd) per-cell dof values.

    Cells are ordered (cx, cy, cz) row-major, matching
    bench_tpu_fem.mesh.dofmap.cell_dofmap.
    """
    P = degree
    nd = P + 1
    nx, ny, nz = n
    ix = (np.arange(nx)[:, None] * P + np.arange(nd)[None, :]).astype(np.int32)
    iy = (np.arange(ny)[:, None] * P + np.arange(nd)[None, :]).astype(np.int32)
    iz = (np.arange(nz)[:, None] * P + np.arange(nd)[None, :]).astype(np.int32)
    u = jnp.take(x_grid, jnp.asarray(ix), axis=0)  # (nx, nd, NY, NZ)
    u = jnp.take(u, jnp.asarray(iy), axis=2)  # (nx, nd, ny, nd, NZ)
    u = jnp.take(u, jnp.asarray(iz), axis=4)  # (nx, nd, ny, nd, nz, nd)
    u = u.transpose(0, 2, 4, 1, 3, 5)
    return u.reshape(nx * ny * nz, nd, nd, nd)


def _fold_last(a: jnp.ndarray, P: int) -> jnp.ndarray:
    """Overlap-add along the trailing (nc, nd) axis pair: (..., nc, nd) ->
    (..., nc*P + 1), where entry (c, i) lands at position c*P + i."""
    *lead, nc, nd = a.shape
    assert nd == P + 1
    main = a[..., :, :P].reshape(*lead, nc * P)
    out = jnp.concatenate([main, jnp.zeros((*lead, 1), dtype=a.dtype)], axis=-1)
    idx = (np.arange(nc, dtype=np.int32) + 1) * P
    return out.at[..., idx].add(a[..., :, P])


def fold_cells(
    cells: jnp.ndarray, n: tuple[int, int, int], degree: int
) -> jnp.ndarray:
    """(ncells, nd, nd, nd) per-cell contributions -> (NX, NY, NZ) grid via
    per-axis overlap-add (the structured replacement for atomicAdd scatter,
    laplacian_gpu.hpp:425)."""
    nx, ny, nz = n
    nd = degree + 1
    a = cells.reshape(nx, ny, nz, nd, nd, nd).transpose(0, 3, 1, 4, 2, 5)
    a = _fold_last(a, degree)  # (nx, nd, ny, nd, NZ')
    a = jnp.moveaxis(a, -1, 0)  # (NZ, nx, nd, ny, nd)
    a = _fold_last(a, degree)  # (NZ, nx, nd, NY)
    a = jnp.moveaxis(a, -1, 0)  # (NY, NZ, nx, nd)
    a = _fold_last(a, degree)  # (NY, NZ, NX)
    return a.transpose(2, 0, 1)


def cell_apply(
    u_cells: jnp.ndarray,
    G: jnp.ndarray,
    phi0: jnp.ndarray,
    dphi1: jnp.ndarray,
    kappa,
    is_identity: bool,
    backend: str = "xla",
    g_cells_last: bool = False,
) -> jnp.ndarray:
    """Per-cell stiffness apply, dispatching to the XLA einsum chain or the
    Pallas TPU kernel (ops.pallas_laplacian). Operators built with
    backend='pallas' store G cells-last (g_cells_last=True)."""
    if backend == "pallas":
        from .pallas_laplacian import pallas_cell_apply

        return pallas_cell_apply(
            u_cells,
            G,
            phi0,
            dphi1,
            jnp.asarray(kappa),
            nd=u_cells.shape[-1],
            nq=phi0.shape[0],
            is_identity=is_identity,
            g_cells_last=g_cells_last,
        )
    if backend != "xla":
        raise ValueError(f"unknown operator backend '{backend}'")
    if g_cells_last:
        G = jnp.moveaxis(G, -1, 0)
    return _sumfact_cell_apply(u_cells, G, phi0, dphi1, kappa, is_identity)


def _sumfact_cell_apply(
    u: jnp.ndarray,
    G: jnp.ndarray,
    phi0: jnp.ndarray,
    dphi1: jnp.ndarray,
    kappa,
    is_identity: bool,
) -> jnp.ndarray:
    """Per-cell kernel on gathered dofs: (C, nd, nd, nd) -> (C, nd, nd, nd).

    The contraction chain of laplacian_gpu.hpp:174-421 (interpolate ->
    collocation gradient -> geometry scaling -> transpose gradient ->
    back-interpolate) as batched einsums.
    """
    if not is_identity:
        u = jnp.einsum("qi,eijk->eqjk", phi0, u)
        u = jnp.einsum("rj,eqjk->eqrk", phi0, u)
        u = jnp.einsum("sk,eqrk->eqrs", phi0, u)
    du0 = jnp.einsum("xi,eijk->exjk", dphi1, u)
    du1 = jnp.einsum("yj,eijk->eiyk", dphi1, u)
    du2 = jnp.einsum("zk,eijk->eijz", dphi1, u)
    G0, G1, G2, G3, G4, G5 = (G[:, c] for c in range(6))
    f0 = kappa * (G0 * du0 + G1 * du1 + G2 * du2)
    f1 = kappa * (G1 * du0 + G3 * du1 + G4 * du2)
    f2 = kappa * (G2 * du0 + G4 * du1 + G5 * du2)
    y = (
        jnp.einsum("qi,eqjk->eijk", dphi1, f0)
        + jnp.einsum("qj,eiqk->eijk", dphi1, f1)
        + jnp.einsum("qk,eijq->eijk", dphi1, f2)
    )
    if not is_identity:
        y = jnp.einsum("qi,eqjk->eijk", phi0, y)
        y = jnp.einsum("qj,eiqk->eijk", phi0, y)
        y = jnp.einsum("qk,eijq->eijk", phi0, y)
    return y


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["G", "phi0", "dphi1", "bc_mask", "kappa"],
    meta_fields=["n", "degree", "is_identity", "backend"],
)
@dataclass(frozen=True)
class Laplacian:
    """Matrix-free Laplacian operator state (a pytree; `n`, `degree`,
    `is_identity` and `backend` are static so `apply` specialises per
    configuration, like the reference's template dispatch).

    backend: "xla" (batched einsums, any dtype) or "pallas" (TPU kernel,
    f32/bf16; see ops.pallas_laplacian)."""

    G: jnp.ndarray  # (ncells, 6, nq, nq, nq) weighted geometry tensor
    phi0: jnp.ndarray  # (nq, nd) interpolation matrix
    dphi1: jnp.ndarray  # (nq, nq) collocation derivative
    bc_mask: jnp.ndarray  # (NX, NY, NZ) bool Dirichlet marker
    kappa: jnp.ndarray  # scalar (or (ncells,1,1,1)) coefficient
    n: tuple[int, int, int]
    degree: int
    is_identity: bool
    backend: str = "xla"

    def apply(self, x_grid: jnp.ndarray) -> jnp.ndarray:
        """y = A @ x on the dof grid, with Dirichlet pass-through rows."""
        xm = jnp.where(self.bc_mask, 0, x_grid)
        u = gather_cells(xm, self.n, self.degree)
        y = cell_apply(
            u, self.G, self.phi0, self.dphi1, self.kappa, self.is_identity,
            backend=self.backend, g_cells_last=self.backend == "pallas",
        )
        y_grid = fold_cells(y, self.n, self.degree)
        return jnp.where(self.bc_mask, x_grid, y_grid)


def build_laplacian(
    mesh: BoxMesh,
    degree: int,
    qmode: int,
    rule: str = "gll",
    kappa: float = 2.0,
    dtype=jnp.float64,
    tables: OperatorTables | None = None,
    backend: str = "xla",
) -> Laplacian:
    """Assemble operator state from a mesh: tables host-side (f64), geometry
    tensor on device (mirrors MatFreeLaplacianGPU's constructor,
    laplacian.hpp:102-227)."""
    t = tables or build_operator_tables(degree, qmode, rule)
    corners = jnp.asarray(mesh.cell_corners.reshape(-1, 2, 2, 2, 3), dtype=dtype)
    G, _ = geometry_factors_jax(corners, t.pts1d, t.wts1d)
    if backend == "pallas":
        from .pallas_laplacian import cells_last_G

        G = cells_last_G(G)
    bc = jnp.asarray(boundary_dof_marker(mesh.n, degree))
    return Laplacian(
        G=G,
        phi0=jnp.asarray(t.phi0, dtype=dtype),
        dphi1=jnp.asarray(t.dphi1, dtype=dtype),
        bc_mask=bc,
        kappa=jnp.asarray(kappa, dtype=dtype),
        n=mesh.n,
        degree=degree,
        is_identity=t.is_identity,
        backend=backend,
    )
