"""The matrix-free sum-factorised Laplacian apply (the hot path).

TPU-first re-design of `stiffness_operator_gpu`
(/root/reference/src/laplacian_gpu.hpp:91-426) and its host dispatcher
`MatFreeLaplacianGPU::apply` (laplacian.hpp:281-403):

- The dof vector is a 3D *grid* array (NX, NY, NZ) — the tensor-product
  dofmap of the box mesh is implicit in the layout, so "gather via dofmap"
  becomes three per-axis `take`s and "atomicAdd scatter" becomes three
  per-axis overlap-add folds (deterministic, XLA-friendly, no atomics).
- Each sum-factorisation stage (interpolation phi0, collocation derivative
  dphi1, transpose stages) is a single batched matmul over *all* cells at
  once — these are the MXU ops. Degree/qmode are static (compile-time)
  parameters, replacing the reference's template dispatch if-chain
  (laplacian.hpp:361-398).
- Dirichlet semantics match laplacian_gpu.hpp:163-169,423-425: constrained
  dofs contribute zero on input, and output rows pass the input through
  (y[bc] = x[bc]).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..elements.tables import OperatorTables, build_operator_tables
from ..mesh.box import BoxMesh
from ..mesh.dofmap import boundary_dof_marker
from .geometry import geometry_factors_jax


def _window_axis0(x: jnp.ndarray, nc: int, P: int) -> jnp.ndarray:
    """(nc*P + 1, ...) -> (nc, P+1, ...) overlapping cell windows along axis
    0: window c holds entries c*P .. c*P+P. Pure reshape + strided slice +
    concat — no XLA gather (dynamic indexing is slow on TPU; the structured
    box makes the dofmap a static stencil)."""
    main = x[: nc * P].reshape(nc, P, *x.shape[1:])
    last = x[P :: P][:, None]
    return jnp.concatenate([main, last], axis=1)


def gather_cells(x_grid: jnp.ndarray, n: tuple[int, int, int], degree: int) -> jnp.ndarray:
    """(NX, NY, NZ) grid -> (ncells, nd, nd, nd) per-cell dof values.

    Cells are ordered (cx, cy, cz) row-major, matching
    bench_tpu_fem.mesh.dofmap.cell_dofmap.
    """
    P = degree
    nx, ny, nz = n
    u = _windows_6d(x_grid, n, degree)
    u = u.transpose(0, 2, 4, 1, 3, 5)
    return u.reshape(nx * ny * nz, P + 1, P + 1, P + 1)


def _windows_6d(x_grid: jnp.ndarray, n: tuple[int, int, int], degree: int) -> jnp.ndarray:
    """(NX, NY, NZ) grid -> (nx, nd, ny, nd, nz, nd) overlapping cell windows."""
    P = degree
    nx, ny, nz = n
    u = _window_axis0(x_grid, nx, P)  # (nx, nd, NY, NZ)
    u = jnp.moveaxis(_window_axis0(jnp.moveaxis(u, 2, 0), ny, P), (0, 1), (2, 3))
    u = jnp.moveaxis(_window_axis0(jnp.moveaxis(u, 4, 0), nz, P), (0, 1), (4, 5))
    return u


def gather_cells_lanes(
    x_grid: jnp.ndarray, n: tuple[int, int, int], degree: int
) -> jnp.ndarray:
    """(NX, NY, NZ) grid -> (nd, nd, nd, ncells) with cells on the trailing
    (lane) axis — the layout the Pallas kernel consumes directly."""
    nx, ny, nz = n
    nd = degree + 1
    u = _windows_6d(x_grid, n, degree)
    u = u.transpose(1, 3, 5, 0, 2, 4)
    return u.reshape(nd, nd, nd, nx * ny * nz)


def _fold_last(a: jnp.ndarray, P: int) -> jnp.ndarray:
    """Overlap-add along the trailing (nc, nd) axis pair: (..., nc, nd) ->
    (..., nc*P + 1), where entry (c, i) lands at position c*P + i.

    Entry (c, P) coincides with entry (c+1, 0); shift the i=P slab one cell
    right and add it to the i=0 slab — static slices and one concat, no XLA
    scatter (the inverse of the _window_axis0 stencil)."""
    *lead, nc, nd = a.shape
    assert nd == P + 1
    seam = a[..., :, P]  # (..., nc): right-face value of each cell
    first = a[..., :, :P]
    carried = first.at[..., 1:, 0].add(seam[..., :-1]) if nc > 1 else first
    main = carried.reshape(*lead, nc * P)
    return jnp.concatenate([main, seam[..., -1:]], axis=-1)


def _fold_6d(a: jnp.ndarray, degree: int) -> jnp.ndarray:
    """(nx, nd, ny, nd, nz, nd) windows -> (NX, NY, NZ) grid overlap-add."""
    a = _fold_last(a, degree)  # (nx, nd, ny, nd, NZ)
    a = jnp.moveaxis(a, -1, 0)  # (NZ, nx, nd, ny, nd)
    a = _fold_last(a, degree)  # (NZ, nx, nd, NY)
    a = jnp.moveaxis(a, -1, 0)  # (NY, NZ, nx, nd)
    a = _fold_last(a, degree)  # (NY, NZ, NX)
    return a.transpose(2, 0, 1)


def fold_cells(
    cells: jnp.ndarray, n: tuple[int, int, int], degree: int
) -> jnp.ndarray:
    """(ncells, nd, nd, nd) per-cell contributions -> (NX, NY, NZ) grid via
    per-axis overlap-add (the structured replacement for atomicAdd scatter,
    laplacian_gpu.hpp:425)."""
    nx, ny, nz = n
    nd = degree + 1
    a = cells.reshape(nx, ny, nz, nd, nd, nd).transpose(0, 3, 1, 4, 2, 5)
    return _fold_6d(a, degree)


def fold_cells_lanes(
    cells: jnp.ndarray, n: tuple[int, int, int], degree: int
) -> jnp.ndarray:
    """(nd, nd, nd, ncells) cells-last contributions -> (NX, NY, NZ) grid
    (inverse layout of gather_cells_lanes)."""
    nx, ny, nz = n
    nd = degree + 1
    a = cells.reshape(nd, nd, nd, nx, ny, nz).transpose(3, 0, 4, 1, 5, 2)
    return _fold_6d(a, degree)


def cell_apply(
    u_cells: jnp.ndarray,
    G: jnp.ndarray,
    phi0: jnp.ndarray,
    dphi1: jnp.ndarray,
    kappa,
    is_identity: bool,
    backend: str = "xla",
) -> jnp.ndarray:
    """Per-cell stiffness apply, dispatching to the XLA einsum chain or the
    Pallas TPU kernel (ops.pallas_laplacian). For the pallas backend
    phi0/dphi1 must be concrete (they become kernel compile-time constants);
    the jitted hot path goes through Laplacian.apply, which carries them as
    static metadata."""
    if backend == "pallas":
        from .pallas_laplacian import pallas_cell_apply

        return pallas_cell_apply(
            u_cells,
            G,
            phi0,
            dphi1,
            jnp.asarray(kappa),
            nd=u_cells.shape[-1],
            nq=np.shape(phi0)[0],
            is_identity=is_identity,
        )
    if backend != "xla":
        raise ValueError(f"unknown operator backend '{backend}'")
    return _sumfact_cell_apply(u_cells, G, phi0, dphi1, kappa, is_identity)


def _sumfact_cell_apply(
    u: jnp.ndarray,
    G: jnp.ndarray,
    phi0: jnp.ndarray,
    dphi1: jnp.ndarray,
    kappa,
    is_identity: bool,
) -> jnp.ndarray:
    """Per-cell kernel on gathered dofs: (C, nd, nd, nd) -> (C, nd, nd, nd).

    The contraction chain of laplacian_gpu.hpp:174-421 (interpolate ->
    collocation gradient -> geometry scaling -> transpose gradient ->
    back-interpolate) as batched einsums. precision=HIGHEST: TPU matmuls
    default to bf16 passes, which costs ~3 decimal digits — fatal to the
    mat_comp oracle contract (the Pallas backend is exact-f32 VPU work and
    needs no such override).
    """
    hi = jax.lax.Precision.HIGHEST
    if not is_identity:
        u = jnp.einsum("qi,eijk->eqjk", phi0, u, precision=hi)
        u = jnp.einsum("rj,eqjk->eqrk", phi0, u, precision=hi)
        u = jnp.einsum("sk,eqrk->eqrs", phi0, u, precision=hi)
    du0 = jnp.einsum("xi,eijk->exjk", dphi1, u, precision=hi)
    du1 = jnp.einsum("yj,eijk->eiyk", dphi1, u, precision=hi)
    du2 = jnp.einsum("zk,eijk->eijz", dphi1, u, precision=hi)
    G0, G1, G2, G3, G4, G5 = (G[:, c] for c in range(6))
    f0 = kappa * (G0 * du0 + G1 * du1 + G2 * du2)
    f1 = kappa * (G1 * du0 + G3 * du1 + G4 * du2)
    f2 = kappa * (G2 * du0 + G4 * du1 + G5 * du2)
    y = (
        jnp.einsum("qi,eqjk->eijk", dphi1, f0, precision=hi)
        + jnp.einsum("qj,eiqk->eijk", dphi1, f1, precision=hi)
        + jnp.einsum("qk,eijq->eijk", dphi1, f2, precision=hi)
    )
    if not is_identity:
        y = jnp.einsum("qi,eqjk->eijk", phi0, y, precision=hi)
        y = jnp.einsum("qj,eiqk->eijk", phi0, y, precision=hi)
        y = jnp.einsum("qk,eijq->eijk", phi0, y, precision=hi)
    return y


def pallas_grid_apply(
    xm: jnp.ndarray,
    n: tuple[int, int, int],
    degree: int,
    G: jnp.ndarray,
    kappa,
    phi0_c: tuple,
    dphi1_c: tuple,
    is_identity: bool,
) -> jnp.ndarray:
    """Masked dof grid -> operator contribution grid via the Pallas kernel:
    the blocked-layout handshake (gather -> block -> kernel -> unblock ->
    fold) shared by the single-device and distributed operators."""
    from .pallas_laplacian import (
        block_cells_lanes,
        pallas_cell_apply_blocked,
        unblock_cells_lanes,
    )

    C = int(np.prod(n))
    nl = G.shape[-1]
    u = block_cells_lanes(gather_cells_lanes(xm, n, degree), nl)
    y = pallas_cell_apply_blocked(
        u, G, kappa,
        np.asarray(phi0_c, np.float64),
        np.asarray(dphi1_c, np.float64),
        is_identity,
    )
    return fold_cells_lanes(unblock_cells_lanes(y, C), n, degree)


def freeze_table(a: np.ndarray) -> tuple:
    """numpy table -> hashable nested tuple (for pytree meta fields)."""
    return tuple(tuple(float(v) for v in row) for row in np.asarray(a, np.float64))


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["G", "phi0", "dphi1", "bc_mask", "kappa"],
    meta_fields=["n", "degree", "is_identity", "backend", "phi0_c", "dphi1_c"],
)
@dataclass(frozen=True)
class Laplacian:
    """Matrix-free Laplacian operator state (a pytree; `n`, `degree`,
    `is_identity` and `backend` are static so `apply` specialises per
    configuration, like the reference's template dispatch).

    backend: "xla" (batched einsums, any dtype) or "pallas" (TPU kernel,
    f32/bf16; see ops.pallas_laplacian). The pallas path needs the basis
    tables as *compile-time constants* (they are baked into the kernel as
    immediates), so they are carried twice: as arrays (phi0/dphi1, the XLA
    operands) and as hashable tuples (phi0_c/dphi1_c, static metadata)."""

    G: jnp.ndarray  # (ncells, 6, nq, nq, nq); block-major (see blocked_G) for pallas
    phi0: jnp.ndarray  # (nq, nd) interpolation matrix
    dphi1: jnp.ndarray  # (nq, nq) collocation derivative
    bc_mask: jnp.ndarray  # (NX, NY, NZ) bool Dirichlet marker
    kappa: jnp.ndarray  # scalar (or (ncells,1,1,1)) coefficient
    n: tuple[int, int, int]
    degree: int
    is_identity: bool
    backend: str = "xla"
    phi0_c: tuple | None = None
    dphi1_c: tuple | None = None

    def apply(self, x_grid: jnp.ndarray) -> jnp.ndarray:
        """y = A @ x on the dof grid, with Dirichlet pass-through rows."""
        xm = jnp.where(self.bc_mask, 0, x_grid)
        if self.backend == "pallas":
            y_grid = pallas_grid_apply(
                xm, self.n, self.degree, self.G, self.kappa,
                self.phi0_c, self.dphi1_c, self.is_identity,
            )
        else:
            u = gather_cells(xm, self.n, self.degree)
            y = cell_apply(
                u, self.G, self.phi0, self.dphi1, self.kappa, self.is_identity,
                backend=self.backend,
            )
            y_grid = fold_cells(y, self.n, self.degree)
        return jnp.where(self.bc_mask, x_grid, y_grid)


def build_laplacian(
    mesh: BoxMesh,
    degree: int,
    qmode: int,
    rule: str = "gll",
    kappa: float = 2.0,
    dtype=jnp.float64,
    tables: OperatorTables | None = None,
    backend: str = "xla",
) -> Laplacian:
    """Assemble operator state from a mesh: tables host-side (f64), geometry
    tensor on device (mirrors MatFreeLaplacianGPU's constructor,
    laplacian.hpp:102-227)."""
    if backend == "kron":
        from .kron import build_kron_laplacian

        return build_kron_laplacian(
            mesh, degree, qmode, rule, kappa=kappa, dtype=dtype, tables=tables
        )
    t = tables or build_operator_tables(degree, qmode, rule)
    corners = jnp.asarray(mesh.cell_corners.reshape(-1, 2, 2, 2, 3), dtype=dtype)
    G, _ = geometry_factors_jax(corners, t.pts1d, t.wts1d)
    if backend == "pallas":
        from .pallas_laplacian import blocked_G, pick_lanes

        G = blocked_G(G, pick_lanes(degree + 1, t.nq, np.dtype(dtype).itemsize))
    bc = jnp.asarray(boundary_dof_marker(mesh.n, degree))
    return Laplacian(
        G=G,
        phi0=jnp.asarray(t.phi0, dtype=dtype),
        dphi1=jnp.asarray(t.dphi1, dtype=dtype),
        bc_mask=bc,
        kappa=jnp.asarray(kappa, dtype=dtype),
        n=mesh.n,
        degree=degree,
        is_identity=t.is_identity,
        backend=backend,
        phi0_c=freeze_table(t.phi0),
        dphi1_c=freeze_table(t.dphi1),
    )
