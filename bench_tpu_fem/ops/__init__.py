"""Matrix-free sum-factorised Laplacian operator (layer L4), TPU-native.

Re-designs the reference's CUDA/HIP kernels (`stiffness_operator_gpu`,
/root/reference/src/laplacian_gpu.hpp:91-426; `geometry_computation_gpu`,
geometry_gpu.hpp:26-133) as batched tensor contractions over all cells at
once: where the GPU version launches one thread block per cell with shared-
memory scratch and an atomicAdd scatter, the TPU version expresses each
sum-factorisation stage as one large (nq x nd) x (cells * nd^2) matmul that
XLA tiles onto the MXU, and replaces scatter-add entirely with a structured
per-axis "fold" (the tensor-product dofmap on a box mesh makes cell->dof
overlap a regular stencil; cf. SURVEY.md section 7 "Scatter-add").
"""

from .geometry import geometry_factors_jax
from .laplacian import Laplacian, build_laplacian, gather_cells, fold_cells

__all__ = [
    "geometry_factors_jax",
    "Laplacian",
    "build_laplacian",
    "gather_cells",
    "fold_cells",
]
