"""Fully-fused CG engine on folded vectors: the TPU benchmark hot path.

The reference CG iteration (/root/reference/src/cg.hpp:121-167) is one
operator apply + two allreduce dots + three axpys. Run naively through XLA
on folded vectors, the vector algebra and the operator's window gathers
each re-stream the dof vector several times; measured, the glue costs more
HBM time than the stiffness kernel itself. This module restates the whole
iteration as ONE pallas kernel plus one fused XLA update pass:

Kernel A (`_cg_apply_call`) — one pass over the mesh per iteration:
  - DELAY-RING INPUT: the grid runs nb + D steps. At step t the kernel
    DMAs input block t (ONE view of the vector — not one view per shift
    offset) and stores it in a VMEM ring of KI = D + 1 blocks. The output
    for block i = t - D is computed from ring slices: every shifted cell
    window (+x/+y/+z neighbour slabs at flat shifts s) reads ring blocks
    i + s//B and i + s//B + 1, which are guaranteed present because
    D = max(s)//B + 1. Static sub-block shifts are register lane/sublane
    rotates (ops.folded._shift_window_pair).
  - p-UPDATE FUSED: on the input stage it forms p = beta*p_prev + r in
    registers and writes it back out, so the CG direction update costs no
    separate pass.
  - SEAM RINGS: cell contributions that overlap +neighbour cells accumulate
    across sequential grid steps in VMEM rings (see ops.folded fused
    kernel) — the structured replacement for the reference's atomicAdd
    scatter (laplacian_gpu.hpp:425).
  - DOT FUSED: per-block partials of <p, y> are reduced in-register and
    written as an (nb, 8, nl) array; XLA sums the ~MB-sized partials. One
    scalar reduction's traffic instead of re-reading two 50 MB vectors.
  - Dirichlet rows pass through p (zero) via a bc mask computed IN-KERNEL
    from the structured-box closed form (no 4 B/dof mask stream;
    laplacian_gpu.hpp:163-169 semantics; p is zero on bc rows by the CG
    invariant since the RHS has homogeneous bc rows).

The remaining vector algebra (x1 = x + alpha p; r1 = r - alpha y;
<r1, r1>) runs as plain XLA ops: on the block-major (nb, P^3, B) layout XLA
streams one fused elementwise+reduce pass at near-HBM bandwidth, measured
faster than a hand-written pallas equivalent.

The CG recurrence is reassociated so the p-update happens at the START of
the next iteration (p_1 = r_1 + beta * p_0), which is algebraically the
reference loop with the same operation order per element. rtol semantics:
benchmark mode only (rtol = 0, exactly nreps iterations — cg.hpp:88-91).

float32 only (Mosaic has no f64); the driver routes f64 to the XLA path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..la.cg import fused_cg_solve
from .kron_cg import pallas_update_for
from .pallas_laplacian import (
    SUBLANES,
    _use_interpret,
    corner_apply,
    sumfact_window_apply,
)
from .folded import (
    _SHIFT_CLASSES,
    FoldedLaplacian,
    FoldedLayout,
    _class_shifts,
    _r8,
    _rb,
    _assemble_window,
    _seam_accumulate,
    _seam_ring_shapes,
    _shift_window_pair,
)

# Input-ring depth above which the delay-ring VMEM footprint is not worth
# it (KI * P^3 * 8 * nl * 4 bytes); callers fall back to the multi-view
# apply path. KI grows with the +x flat shift: ~ (ny * nz) / (8 * nl).
MAX_RING_BLOCKS = 24


def ring_depth(layout: FoldedLayout) -> int:
    """KI = D + 1 where D = max shift in blocks + 1."""
    B = layout.block
    qmax = max(s // B for s in _class_shifts(layout).values())
    return qmax + 2


def _make_cg_apply_kernel(P: int, nl: int, B: int, nb: int, KI: int, K: int,
                          is_identity: bool,
                          phi0: np.ndarray, dphi1: np.ndarray,
                          qr: dict[str, tuple[int, int]],
                          n_cells: tuple[int, int, int],
                          update_p: bool, geom_tables=None,
                          stream_masks: bool = False):
    """`stream_masks` is the HALO (distributed) form of the kernel
    (dist.folded_cg): the closed-form Dirichlet mask assumes local block
    coordinates are global, which is false on a shard, so the per-shard bc
    mask streams as a (1, P^3, B) block instead; and a second streamed 0/1
    weight block (the owned-dof mask) multiplies the <p, y> partials so
    duplicated seam slots and ghost columns count zero BEFORE the psum —
    every dof exactly once globally. The delay ring, seam rings, p-update
    and emit schedule are identical to the single-chip form."""
    corner_mode = geom_tables is not None
    D = KI - 1
    nx, ny, nz = n_cells
    npy, npz = ny + 1, nz + 1
    plane = {
        "x": lambda a: a[0], "y": lambda a: a[:, 0], "z": lambda a: a[:, :, 0],
        "xy": lambda a: a[0, 0], "xz": lambda a: a[0, :, 0],
        "yz": lambda a: a[:, 0, 0], "xyz": lambda a: a[0, 0, 0],
    }

    def kernel(*refs):
        if update_p:
            r_ref, pprev_ref = refs[:2]
            ni = 2
        else:
            (x_ref,) = refs[:1]
            ni = 1
        ngeom = 2 if corner_mode else 1
        geom_refs = refs[ni:ni + ngeom]
        scal_ref = refs[ni + ngeom]  # SMEM (1, 2): [beta, kappa]
        base = ni + 1 + ngeom
        bc_ref = w_ref = None
        if stream_masks:
            bc_ref, w_ref = refs[base:base + 2]
            base += 2
        if update_p:
            p_out_ref, y_out_ref, dot_ref = refs[base:base + 3]
            no = 3
        else:
            y_out_ref, dot_ref = refs[base:base + 2]
            no = 2
        inring = refs[base + no]
        rings = {k: refs[base + no + 1 + ci]
                 for ci, k in enumerate(_SHIFT_CLASSES)}

        t = pl.program_id(0)

        @pl.when(t == 0)
        def _zero_rings():
            for k in _SHIFT_CLASSES:
                rings[k][...] = jnp.zeros_like(rings[k])

        # ---- input stage: ingest block t (clamped at the tail) ----
        @pl.when(t < np.int32(nb))
        def _ingest():
            if update_p:
                pb = (scal_ref[0, 0] * _r8(pprev_ref[0], nl)
                      + _r8(r_ref[0], nl))
                p_out_ref[0] = _rb(pb)
            else:
                pb = _r8(x_ref[0], nl)
            inring[jax.lax.rem(t, np.int32(KI))] = pb.reshape(
                P, P, P, SUBLANES, nl
            )

        # ---- output stage: compute block i = t - D ----
        @pl.when(t >= np.int32(D))
        def _emit():
            i = t - np.int32(D)

            def rblk(d):
                return inring[jax.lax.rem(i + np.int32(d), np.int32(KI))]

            u0 = rblk(0)
            win = {
                k: _shift_window_pair(
                    plane[k](rblk(qr[k][0])), plane[k](rblk(qr[k][0] + 1)),
                    qr[k][1], nl,
                )
                for k in _SHIFT_CLASSES
            }
            u = _assemble_window(
                u0, win["x"], win["y"], win["z"],
                win["xy"], win["xz"], win["yz"], win["xyz"],
            )
            if corner_mode:
                y = corner_apply(u, geom_refs[0][0], geom_refs[1][0],
                                 scal_ref[0, 1], phi0, dphi1,
                                 *geom_tables, is_identity)
            else:
                y = sumfact_window_apply(u, geom_refs[0][0],
                                         scal_ref[0, 1], phi0, dphi1,
                                         is_identity)
            m = _seam_accumulate(rings, y, i, K, qr, B, nl, P)
            if stream_masks:
                # HALO form: per-shard bc mask streamed (the closed form
                # below needs global coordinates), applied as the same
                # multiplicative blend as folded_cell_apply_fused; the
                # dot partials are weighted by the streamed owned mask so
                # ghost/duplicated-seam slots count zero before the psum.
                bcb = _r8(bc_ref[0], nl).reshape(P, P, P, SUBLANES, nl)
                m = m + bcb * (u0 - m)
                wb = _r8(w_ref[0], nl).reshape(P, P, P, SUBLANES, nl)
                prod = u0 * m * wb
            else:
                # Dirichlet pass-through with the bc mask computed
                # IN-KERNEL from the structured-box closed form (no
                # 4 B/dof mask stream): grid coord X = cx*P + ilocal is
                # on the boundary iff ilocal == 0 and cx in {0, nx} (the
                # global X = nx*P plane lives in the ghost column's
                # ilocal = 0 slots) — and likewise per axis. Sequential
                # per-axis selects compose the union.
                cat = jnp.concatenate
                sub_i = jax.lax.broadcasted_iota(
                    jnp.int32, (SUBLANES, nl), 0)
                lane_i = jax.lax.broadcasted_iota(
                    jnp.int32, (SUBLANES, nl), 1)
                c = i * np.int32(B) + sub_i * np.int32(nl) + lane_i
                cx = jax.lax.div(c, np.int32(npy * npz))
                rem = c - cx * np.int32(npy * npz)
                cy = jax.lax.div(rem, np.int32(npz))
                cz = rem - cy * np.int32(npz)
                mx = jnp.logical_or(cx == 0, cx == np.int32(nx))
                my = jnp.logical_or(cy == 0, cy == np.int32(ny))
                mz = jnp.logical_or(cz == 0, cz == np.int32(nz))

                def bsel(mask, lead_shape):
                    return jax.lax.broadcast(mask, lead_shape)

                m = cat([jax.lax.select(bsel(mx, (P, P)), u0[0],
                                        m[0])[None], m[1:]], axis=0)
                m = cat([jax.lax.select(bsel(my, (P, P)), u0[:, 0],
                                        m[:, 0])[:, None], m[:, 1:]],
                        axis=1)
                m = cat([jax.lax.select(bsel(mz, (P, P)), u0[:, :, 0],
                                        m[:, :, 0])[:, :, None],
                         m[:, :, 1:]], axis=2)
                prod = u0 * m
            y_out_ref[0] = _rb(m).reshape(P * P * P, B)
            # <p, y> partial for this block, reduced over the 27 window rows
            dot_ref[...] = jnp.sum(
                prod.reshape(P * P * P, SUBLANES, nl), axis=0
            )[None]

    return kernel


def _cg_apply_call(
    layout: FoldedLayout,
    geom,
    kappa,
    phi0: np.ndarray,
    dphi1: np.ndarray,
    is_identity: bool,
    geom_tables,
    update_p: bool,
    interpret: bool | None,
    *vectors,
    masks=None,
):
    """update_p: vectors = (r, p_prev, beta) -> (p, y, dot_partials).
    else:       vectors = (x,)              -> (y, dot_partials) where the
    dot partials are of <x, y> (used for <p, A p> style reductions).
    kappa rides in SMEM next to beta — no scaled copy of G is ever made.

    `masks = (bc, w)` selects the HALO form (dist.folded_cg): two extra
    streamed (nb, P^3, B) blocks — the per-shard Dirichlet mask replacing
    the closed-form in-kernel one, and the owned-dof dot weight (see
    _make_cg_apply_kernel)."""
    P = layout.degree
    nl, B, nb = layout.nl, layout.block, layout.nblocks
    nq = phi0.shape[0]
    qr = {k: divmod(s, B) for k, s in _class_shifts(layout).items()}
    K = max(q for q, _ in qr.values()) + 2
    KI = ring_depth(layout)
    D = KI - 1
    nsteps = nb + D
    dtype = vectors[0].dtype
    P3 = P * P * P

    def clamp_in(i):
        return (jax.lax.min(i, np.int32(nb - 1)), 0, 0)

    def clamp_out(i):
        return (jax.lax.max(i - np.int32(D), np.int32(0)), 0, 0)

    in_specs = []
    operands = []
    if update_p:
        r, p_prev, beta = vectors
        in_specs += [
            pl.BlockSpec((1, P3, B), clamp_in, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, P3, B), clamp_in, memory_space=pltpu.VMEM),
        ]
        operands += [r, p_prev]
    else:
        (x,) = vectors
        beta = jnp.zeros((), dtype)
        in_specs.append(pl.BlockSpec((1, P3, B), clamp_in,
                                     memory_space=pltpu.VMEM))
        operands.append(x)
    if geom_tables is None:
        in_specs.append(pl.BlockSpec(
            (1, 6, nq, nq, nq, SUBLANES, nl),
            lambda i: (jax.lax.max(i - np.int32(D), np.int32(0)),
                       0, 0, 0, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ))
        operands.append(geom)
    else:
        corners_b, mask_b = geom
        in_specs += [
            pl.BlockSpec(
                (1, 3, 2, 2, 2, SUBLANES, nl),
                lambda i: (jax.lax.max(i - np.int32(D), np.int32(0)),
                           0, 0, 0, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, SUBLANES, nl),
                lambda i: (jax.lax.max(i - np.int32(D), np.int32(0)), 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ]
        operands += [corners_b, mask_b]
    in_specs.append(pl.BlockSpec((1, 2), lambda i: (0, 0),
                                 memory_space=pltpu.SMEM))
    operands.append(
        jnp.stack([beta.astype(dtype),
                   jnp.asarray(kappa, dtype)]).reshape(1, 2)
    )
    if masks is not None:
        # halo form: bc + owned-weight blocks, consumed at the emit stage
        # for output block i = t - D
        for mk in masks:
            in_specs.append(pl.BlockSpec((1, P3, B), clamp_out,
                                         memory_space=pltpu.VMEM))
            operands.append(mk.astype(dtype))

    out_specs = []
    out_shapes = []
    if update_p:
        out_specs.append(pl.BlockSpec((1, P3, B), clamp_in,
                                      memory_space=pltpu.VMEM))
        out_shapes.append(jax.ShapeDtypeStruct((nb, P3, B), dtype))
    out_specs.append(pl.BlockSpec((1, P3, B), clamp_out,
                                  memory_space=pltpu.VMEM))
    out_shapes.append(jax.ShapeDtypeStruct((nb, P3, B), dtype))
    out_specs.append(pl.BlockSpec(
        (1, SUBLANES, nl),
        lambda i: (jax.lax.max(i - np.int32(D), np.int32(0)), 0, 0),
        memory_space=pltpu.VMEM,
    ))
    out_shapes.append(jax.ShapeDtypeStruct((nb, SUBLANES, nl), dtype))

    ring_shapes = _seam_ring_shapes(P, K, nl)
    kernel = _make_cg_apply_kernel(
        P, nl, B, nb, KI, K, is_identity,
        np.asarray(phi0, np.float64), np.asarray(dphi1, np.float64),
        qr, layout.n, update_p, geom_tables=geom_tables,
        stream_masks=masks is not None,
    )
    return pl.pallas_call(
        kernel,
        grid=(nsteps,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=(
            [pltpu.VMEM((KI, P, P, P, SUBLANES, nl), dtype)]
            + [pltpu.VMEM(ring_shapes[k], dtype) for k in _SHIFT_CLASSES]
        ),
        interpret=_use_interpret() if interpret is None else interpret,
    )(*operands)


def supports_cg_engine(op: FoldedLaplacian) -> bool:
    """The delay-ring engine needs the input ring to fit VMEM."""
    return ring_depth(op.layout) <= MAX_RING_BLOCKS


def _op_geom_for_engine(op: FoldedLaplacian):
    """Geometry operands for the engine kernel (kappa streams via SMEM)."""
    if op.G is not None:
        return op.G, None
    return (op.corners, op.cmask), (
        np.asarray(op.pts_c), np.asarray(op.wts_c)
    )


def folded_cg_solve(
    op: FoldedLaplacian,
    b: jnp.ndarray,
    nreps: int,
    interpret: bool | None = None,
    pallas_update: bool | None = None,
) -> jnp.ndarray:
    """Benchmark CG (x0 = 0, rtol = 0, exactly nreps iterations) with the
    fused two-kernel iteration. Matches la.cg.cg_solve(op.apply_cg, b, 0,
    nreps) to f32 reassociation accuracy.

    `pallas_update` (default: by size) routes the x/r update through the
    chunked pallas pass shared with the kron engine
    (ops.kron_cg.cg_update_pallas): the XLA TPU backend fails compilation
    of whole-vector fusions around ~130M dofs, and corner-mode geometry
    scales perturbed problems well past that. The (nb, P^3, B) folded
    layout rides the pass as a 3D grid directly — full B-lane trailing
    blocks, sublane-aligned row chunks; the folded structural zero slots
    contribute zeros to <r1, r1> exactly as in the fused XLA pass."""
    from .kron_cg import PALLAS_UPDATE_MIN_DOFS, cg_update_pallas

    layout = op.layout
    geom, geom_tables = _op_geom_for_engine(op)
    phi0 = np.asarray(op.phi0_c, np.float64)
    dphi1 = np.asarray(op.dphi1_c, np.float64)

    apply_cg = partial(
        _cg_apply_call, layout, geom, op.kappa, phi0, dphi1,
        op.is_identity, geom_tables,
    )

    def engine(r, p_prev, beta):
        p, y, pdot = apply_cg(True, interpret, r, p_prev, beta)
        # the kernel emits per-block partials; XLA sums the ~MB array
        return p, y, jnp.sum(pdot)

    update = pallas_update_for(b, pallas_update, interpret)
    return fused_cg_solve(engine, b, nreps, update=update)


def folded_apply_ring(
    op: FoldedLaplacian, x: jnp.ndarray, interpret: bool | None = None
) -> jnp.ndarray:
    """Single delay-ring apply (y = A x with Dirichlet pass-through,
    x zero on bc rows — see FoldedLaplacian.apply_cg). Also returns only y,
    discarding the fused <x, y> partials."""
    geom, geom_tables = _op_geom_for_engine(op)
    y, _ = _cg_apply_call(
        op.layout, geom, op.kappa,
        np.asarray(op.phi0_c, np.float64), np.asarray(op.dphi1_c, np.float64),
        op.is_identity, geom_tables, False, interpret, x,
    )
    return y
