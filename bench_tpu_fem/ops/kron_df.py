"""Double-float (df64) Kronecker-path operator and CG: the TPU-native
answer to `--float 64` on the uniform mesh.

The reference benchmarks f64 natively (GH200 has f64 units); a TPU does
not, and XLA's op-by-op f64 emulation measures ~100x below f32 here
(BENCH artifacts, 'Precision policy' in the README). This module runs the
same banded Kronecker apply and CG recurrence in double-float arithmetic
(la.df64: f32 pairs, ~48-bit mantissa): a few tens of f32 VPU flops per
term instead of per-op software emulation, with CG residual behaviour in
the reference's f64 class (~1e-12 floors vs f32's ~1e-3 —
F32_ACCURACY artifacts; ref norms laplacian_solver.cpp:130-148).

Semantics mirror ops.kron exactly: bc-folded banded 1D factors, separable
Dirichlet blend, fixed-iteration rtol=0 CG (cg.hpp:88-91). Everything is
pure jnp on (hi, lo) pairs — XLA fuses the error-free transformations
into the same elementwise passes as the f32 path, so the expected cost is
the ~20x flop multiplier, not the ~100x emulation penalty.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ..elements.tables import OperatorTables, build_operator_tables
from ..la.df64 import (
    DF,
    _prod_terms,
    _renorm,
    df_add,
    df_axpy,
    df_div,
    df_dot,
    df_from_f64,
    df_scale,
    df_sub,
    df_zeros_like,
)
from ..mesh.box import BoxMesh
from .kron import axis_matrices_1d, banded_diags, cell_matrices_1d  # noqa: F401


def banded_apply_df(u: DF, diags: DF, axis: int) -> DF:
    """df64 twin of ops.kron.banded_apply: one pad + 2P+1 shifted slices
    with per-row DF coefficients, accumulated in df arithmetic."""
    nb = diags.hi.shape[0]
    P = (nb - 1) // 2
    N = u.hi.shape[axis]
    pads = [(0, 0)] * u.hi.ndim
    pads[axis] = (P, P)
    uhp = jnp.pad(u.hi, pads)
    ulp = jnp.pad(u.lo, pads)
    bshape = [1] * u.hi.ndim
    bshape[axis] = N
    acc = None
    for di in range(nb):
        start = [0] * u.hi.ndim
        start[axis] = di
        lim = list(uhp.shape)
        lim[axis] = di + N
        sh = jax.lax.slice(uhp, start, lim)
        sl = jax.lax.slice(ulp, start, lim)
        c = DF(diags.hi[di].reshape(bshape), diags.lo[di].reshape(bshape))
        term = _renorm(*_prod_terms(c, DF(sh, sl)))
        acc = term if acc is None else df_add(acc, term)
    return acc


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["Kd", "Md", "notbc"],
    meta_fields=["n", "degree"],
)
@dataclass(frozen=True)
class KronLaplacianDF:
    """df64 uniform-mesh Laplacian (pytree operator; kappa folded into the
    1D factors host-side in f64, unlike the f32 twin, so no scalar df mul
    is needed per apply)."""

    Kd: tuple  # 3x DF of (2P+1, N_a) banded diagonals (bc-folded, kappa'd)
    Md: tuple  # 3x DF (the x/y factors carry kappa once: see builder)
    notbc: DF  # (NX, NY, NZ) 0/1 interior mask (exact in f32: hi only)
    n: tuple[int, int, int]
    degree: int

    def apply(self, x: DF) -> DF:
        aK = banded_apply_df(x, self.Kd[2], 2)
        aM = banded_apply_df(x, self.Md[2], 2)
        t12 = df_add(
            banded_apply_df(aK, self.Md[1], 1),
            banded_apply_df(aM, self.Kd[1], 1),
        )
        tyz = banded_apply_df(aM, self.Md[1], 1)
        y = df_add(
            banded_apply_df(t12, self.Md[0], 0),
            banded_apply_df(tyz, self.Kd[0], 0),
        )
        nb = self.notbc
        y_in = DF(nb.hi * y.hi, nb.hi * y.lo)  # mask is exactly 0/1
        x_bc = DF((1.0 - nb.hi) * x.hi, (1.0 - nb.hi) * x.lo)
        return df_add(y_in, x_bc)


def build_kron_laplacian_df(
    mesh: BoxMesh,
    degree: int,
    qmode: int,
    rule: str = "gll",
    kappa: float = 2.0,
    tables: OperatorTables | None = None,
) -> KronLaplacianDF:
    """All 1D factors assembled host-side in f64, kappa folded into the
    x-axis factors (any single axis works: A = kappa * sum of Kronecker
    terms, and every term has exactly one x factor), then split hi/lo."""
    if not mesh.is_uniform:
        raise ValueError("df64 kron requires an unperturbed box mesh")
    t = tables or build_operator_tables(degree, qmode, rule)
    Ks, Ms, masks = axis_matrices_1d(t, mesh.n)
    P = degree
    Kd, Md = [], []
    for a, (K1, M1) in enumerate(zip(Ks, Ms)):
        scale = kappa if a == 0 else 1.0
        Kd.append(df_from_f64(banded_diags(K1 * scale, P)))
        Md.append(df_from_f64(banded_diags(M1 * scale, P)))
    nb = (
        masks[0][:, None, None] * masks[1][None, :, None]
        * masks[2][None, None, :]
    )
    return KronLaplacianDF(
        Kd=tuple(Kd),
        Md=tuple(Md),
        notbc=df_from_f64(nb),
        n=mesh.n,
        degree=degree,
    )


def cg_solve_df(op: KronLaplacianDF, b: DF, max_iter: int,
                capture: bool = False, precond=None):
    """Fixed-iteration CG in df arithmetic (x0 = 0, rtol = 0 — reference
    cg.hpp:89-169 semantics), scalars (alpha, beta, rnorm) carried as DF.

    Freeze guard: on small problems a fixed iteration budget can push the
    recurrence past the df64 residual floor (rel ~1e-12), where the
    direction updates turn into noise amplification (beta > 1 sustained)
    — unlike native f64, whose deeper floor self-stabilises within any
    realistic budget. Once the recurrence residual drops below the floor
    (rnorm <= 1e-24 * rnorm0, i.e. rel residual ~1e-12), the state
    freezes, mirroring la.cg.cg_solve's rtol freeze. Benchmark-size runs
    never converge that far and are unaffected.

    With `capture=True` (ISSUE 10) the loop carries a preallocated
    `(max_iter + 1,)` f32 buffer of the carried squared residual norms'
    HI channels (the lo channel is ~1e-7 relative — irrelevant to an
    iterations-to-rtol ladder that stops at 1e-8 of the NORM, i.e. 1e-16
    of the square) and returns `(x, {"rnorm_history": ...})` — the
    `la.cg.cg_solve(capture=True)` contract. `capture=False` (default)
    is the pre-capture code path unchanged.

    With `precond=` (ISSUE 11: a DF -> DF callable, e.g. a Jacobi
    diagonal scaling of both channels) the loop is routed to the df
    <r, z> twin `_pcg_solve_df` — a separate body, so `precond=None`
    stays this pre-PR code path bit-for-bit (the la.cg discipline)."""
    if precond is not None:
        return _pcg_solve_df(op, b, max_iter, precond, capture=capture)
    floor = jnp.float32(1e-24)

    def body(i, state):
        if capture:
            x, r, p, rnorm, done, hist = state
        else:
            x, r, p, rnorm, done = state
        y = op.apply(p)
        alpha = df_div(rnorm, df_dot(p, y))
        x1 = df_axpy(x, alpha, p)
        r1 = df_sub(r, df_scale(y, alpha))
        rnorm1 = df_dot(r1, r1)
        beta = df_div(rnorm1, rnorm)
        p1 = df_add(df_scale(p, beta), r1)
        done1 = jnp.logical_or(done, rnorm1.hi <= floor * rnorm0_hi)

        def keep(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(done, o, n), new, old
            )

        rnorm_keep = keep(rnorm1, rnorm)
        out = (keep(x1, x), keep(r1, r), keep(p1, p), rnorm_keep, done1)
        if capture:
            out = out + (hist.at[i + 1].set(rnorm_keep.hi),)
        return out

    x0 = df_zeros_like(b)
    rnorm0 = df_dot(b, b)
    rnorm0_hi = rnorm0.hi
    state = (x0, b, b, rnorm0, jnp.asarray(False))
    if capture:
        state = state + (
            jnp.zeros((max_iter + 1,), jnp.float32).at[0].set(rnorm0.hi),)
        x, _, _, _, _, hist = jax.lax.fori_loop(0, max_iter, body, state)
        return x, {"rnorm_history": hist}
    x, *_ = jax.lax.fori_loop(0, max_iter, body, state)
    return x


def _pcg_solve_df(op: KronLaplacianDF, b: DF, max_iter: int, precond,
                  capture: bool = False):
    """Preconditioned CG in df arithmetic (the <r, z> recurrence of
    la.cg._pcg_solve with DF scalars): z = precond(r), alpha = <r,z> /
    <p,Ap>, beta = <r1,z1> / <r,z>. Carries BOTH <r,z> (the recurrence)
    and <r,r> (the residual-floor freeze + capture buffer — the ladder
    folds residual norms, so preconditioned and bare df histories stay
    comparable). Same df floor freeze as `cg_solve_df`."""
    floor = jnp.float32(1e-24)

    def body(i, state):
        if capture:
            x, r, p, rz, rnorm, done, hist = state
        else:
            x, r, p, rz, rnorm, done = state
        y = op.apply(p)
        alpha = df_div(rz, df_dot(p, y))
        x1 = df_axpy(x, alpha, p)
        r1 = df_sub(r, df_scale(y, alpha))
        z1 = precond(r1)
        rz1 = df_dot(r1, z1)
        rnorm1 = df_dot(r1, r1)
        beta = df_div(rz1, rz)
        p1 = df_add(df_scale(p, beta), z1)
        done1 = jnp.logical_or(done, rnorm1.hi <= floor * rnorm0_hi)

        def keep(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(done, o, n), new, old
            )

        rnorm_keep = keep(rnorm1, rnorm)
        out = (keep(x1, x), keep(r1, r), keep(p1, p), keep(rz1, rz),
               rnorm_keep, done1)
        if capture:
            out = out + (hist.at[i + 1].set(rnorm_keep.hi),)
        return out

    x0 = df_zeros_like(b)
    z0 = precond(b)
    rz0 = df_dot(b, z0)
    rnorm0 = df_dot(b, b)
    rnorm0_hi = rnorm0.hi
    state = (x0, b, z0, rz0, rnorm0, jnp.asarray(False))
    if capture:
        state = state + (
            jnp.zeros((max_iter + 1,), jnp.float32).at[0].set(rnorm0.hi),)
        x, _, _, _, _, _, hist = jax.lax.fori_loop(0, max_iter, body,
                                                   state)
        return x, {"rnorm_history": hist}
    x, *_ = jax.lax.fori_loop(0, max_iter, body, state)
    return x


def action_df(op: KronLaplacianDF, u: DF, nreps: int) -> DF:
    """nreps operator applications of the same input (benchmark action
    semantics, laplacian_solver.cpp:119-127), loop-fenced like the f32
    driver."""

    def rep(_, y):
        uu, _ = jax.lax.optimization_barrier((u, y))
        return op.apply(uu)

    return jax.lax.fori_loop(0, nreps, rep, df_zeros_like(u))


def device_rhs_uniform_df(t: OperatorTables, n) -> DF:
    """Separable device RHS: the three O(N^(1/3)) 1D factors are split
    hi/lo on the host and outer-multiplied ON DEVICE in df arithmetic —
    no O(N) host array, preserving the kron path's RHS scaling rationale
    (ops.kron.rhs_factors_1d docstring)."""
    from .kron import rhs_factors_1d

    fx, fy, fz = (df_from_f64(f) for f in rhs_factors_1d(t, n))

    def outer():
        fxg = DF(fx.hi[:, None, None], fx.lo[:, None, None])
        fyg = DF(fy.hi[None, :, None], fy.lo[None, :, None])
        fzg = DF(fz.hi[None, None, :], fz.lo[None, None, :])
        nx, ny, nz = fx.hi.shape[0], fy.hi.shape[0], fz.hi.shape[0]

        def bc(a):
            return DF(jnp.broadcast_to(a.hi, (nx, ny, nz)),
                      jnp.broadcast_to(a.lo, (nx, ny, nz)))

        xy = _renorm(*_prod_terms(bc(fxg), bc(fyg)))
        return _renorm(*_prod_terms(xy, bc(fzg)))

    return jax.jit(outer)()
