"""Geometry precompute on device (jnp): batched over all cells.

TPU-native equivalent of `geometry_computation_gpu`
(/root/reference/src/geometry_gpu.hpp:26-133): one einsum per Jacobian
column instead of one thread block per cell. Returns the same packed
6-component tensor G and w*detJ as the numpy oracle
(bench_tpu_fem.fem.geometry), against which it is tested.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def geometry_factors_jax(
    corners: jnp.ndarray, pts1d: np.ndarray, wts1d: np.ndarray, dtype=None,
    compute_G: bool = True,
) -> tuple[jnp.ndarray | None, jnp.ndarray]:
    """corners: (ncells, 2, 2, 2, 3) -> (G (ncells,6,nq,nq,nq), wdetJ).
    compute_G=False skips the stiffness tensor (returns (None, wdetJ)) —
    the mass/RHS path needs only w*detJ.

    Computation is carried out in the dtype of `corners` (float64 host mesh
    data should be cast by the caller for f32 runs *after* this computes, or
    passed as f32 directly to trade precision for speed; the benchmark driver
    computes in f64-on-host precision only for the oracle path).
    """
    import jax

    corners = jnp.asarray(corners, dtype=dtype)
    rdtype = corners.dtype
    pts = np.asarray(pts1d)
    N = jnp.asarray(np.stack([1.0 - pts, pts], axis=1), dtype=rdtype)  # (nq, 2)
    D = jnp.asarray(np.broadcast_to([-1.0, 1.0], (len(pts), 2)), dtype=rdtype)
    tab = {0: (D, N, N), 1: (N, D, N), 2: (N, N, D)}
    cols = [
        # precision: TPU matmuls default to bf16 passes; the geometry tensor
        # feeds every operator apply, so compute it at full width (one-time,
        # build-time cost).
        jnp.einsum(
            "eabci,xa,yb,zc->exyzi", corners, *tab[a],
            precision=jax.lax.Precision.HIGHEST,
        )
        for a in range(3)
    ]  # J columns: dx/dxi_a at (nq,nq,nq) points
    K0 = jnp.cross(cols[1], cols[2])
    detJ = jnp.einsum("...i,...i->...", cols[0], K0)
    w = np.asarray(wts1d)
    w3 = jnp.asarray(
        w[:, None, None] * w[None, :, None] * w[None, None, :], dtype=rdtype
    )
    if not compute_G:
        return None, w3[None] * detJ
    K = [
        K0,
        jnp.cross(cols[2], cols[0]),
        jnp.cross(cols[0], cols[1]),
    ]  # adjugate rows
    scale = w3[None] / detJ
    pairs = [(0, 0), (0, 1), (0, 2), (1, 1), (1, 2), (2, 2)]
    G = jnp.stack(
        [jnp.einsum("...i,...i->...", K[a], K[b]) * scale for a, b in pairs], axis=1
    )
    return G, w3[None] * detJ
