"""Fused delay-ring CG engine in double-float (df32) arithmetic: the
f64-class twin of ops.kron_cg.

The unfused df path (ops.kron_df) runs the banded Kronecker apply and the
CG algebra as separate XLA passes over (hi, lo) f32 pairs; like the f32
path before its engine, its iteration time is its HBM stream count
(~46 dof-vector streams: every df pass doubles the f32 path's traffic —
stream counts here are DESIGN ARITHMETIC from the pass structure; the
f32 engine's counts were validated on hardware, the df ones have not
been).
This module fuses one whole CG iteration into ONE pallas kernel plus one
XLA update pass, exactly mirroring ops.kron_cg's delay-ring design — the
same grid over x-planes, in-register z/y contractions, in-kernel p-update,
Dirichlet blend and <p, A p> — with every plane carried as an (hi, lo)
pair and every contraction term computed with error-free transformations
(la.df64's Dekker/Knuth algorithms, which are pure jnp and lower inside
Mosaic kernels as ordinary vector ops).

Differences from the f32 engine, driven by df cost shapes:

- X-STAGE SCATTERS AT INGEST: the f32 engine gathers 2P+1 ring planes per
  emit; in df each error-free product needs the Dekker split of its plane
  operand, so gathering would either re-split every ring plane per emit
  (~56 extra flops/dof) or store 4 channels per ring plane (2x the VMEM).
  Instead, when plane t's (t12, tyz) are formed — their splits in hand —
  their contribution is immediately accumulated into the 2P+1 pending
  output planes (compensated: two_sum on the value channel, carries into
  the error channel). The rings become ONE accumulator pair of 2P+1
  slots, and the one-kernel ring VMEM is ~1.3x the f32 engine's rather
  than 4x (DESIGN ESTIMATE from the live-value model — the df kernel
  has not been Mosaic-compiled or measured on hardware yet).
- COEFFICIENT SPLITS PRECOMPUTED: banded coefficients are constants, so
  their Dekker splits ship with the operand stacks (4 channels: hi, lo,
  hi_split_high, hi_split_low); only the data planes are split in-kernel,
  once per contraction stage.
- COMPENSATED PLANE REDUCTION: <p, A p> partials tree-reduce in-kernel
  with two_sum halving (a plain f32 sum over ~1e7 products would cost
  ~1e-4 relative accuracy — the whole point of df is ~1e-12), then
  accumulate across planes in a (value, error) scalar pair.

Accuracy: each banded term is an error-free product of the hi channels
plus first-order cross terms; accumulation is two_sum-compensated with
error channels renormalised per stage. Dropped terms are O(2^-45)
relative, comfortably inside the df32 target (~1e-12 residual floors,
matching the unfused path and the reference's f64 behaviour,
/root/reference/src/laplacian_solver.cpp:130-148).

Reference parity: cg.hpp:89-169 recurrence (rtol = 0, exactly nreps
iterations) with the p-update reassociated into the next iteration's
kernel, as in ops.folded_cg / ops.kron_cg; dispatch parity
main.cpp:277-288 (this is the `--float 64 --f64_impl df32` fast path).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..la.df64 import (
    DF,
    _split,
    df_axpy,
    df_div,
    df_dot,
    df_scale,
    df_sub,
    df_zeros_like,
    two_sum,
)
from ..analysis import budgets as _B
from .kron_df import KronLaplacianDF
from .pallas_laplacian import _use_interpret


def _grid_shape(op: KronLaplacianDF) -> tuple[int, int, int]:
    return tuple(int(na) * op.degree + 1 for na in op.n)


def _lane_pad(n: int) -> int:
    return -(-n // 128) * 128


def engine_vmem_bytes_df(grid_shape: tuple[int, int, int],
                         degree: int) -> int:
    """Estimated kernel VMEM: accumulator pair of 2P+1 (NY, NZpad) f32
    planes x2 channels + p ring (P+1) x2 + 8 pipeline-buffered in/out
    planes (x2 double buffering) + ~8 ephemeral df intermediates."""
    _, NY, NZ = grid_shape
    plane = NY * _lane_pad(NZ) * 4
    return (2 * (2 * degree + 1) + 2 * (degree + 1) + 8 * 2 + 8) * plane


# df-specific one-kernel tier ceilings — DESIGN ESTIMATES pending the
# dflarge hardware calibration. The f32 ladder's ceilings
# (ops.kron_cg.VMEM_BUDGET / ONE_KERNEL_SCOPED_MAX*) are
# hardware-calibrated for the f32 kernel's allocation pattern; the df
# kernel allocates differently (paired accumulator/ring channels,
# 4-channel coefficient stacks, deeper live df temporaries per stage),
# so its Mosaic stack-to-estimate ratio has NOT been measured. Until it
# is, the df ladder derives each ceiling from the scoped limit it runs
# under (16 / 64 / 96 MiB) divided by the WORST measured
# model->Mosaic allocator ratio anywhere in this repo: 1.7x, from the
# plane-streamed corner kernels (ops.pallas_laplacian). A too-tight line
# costs a (recorded) raised-limit request or chunked form; a too-loose
# one costs a recorded Mosaic-reject retry — the driver survives both,
# but the estimates must not masquerade as f32's measured ones
# (round-5 verdict, weak #3).
# (constants consolidated in analysis.budgets with every other VMEM
# budget; the module-attribute aliases remain the probes' patch points)
DF_VMEM_BUDGET = _B.DF_VMEM_BUDGET  # 16 MiB default scoped limit / 1.7
DF_ONE_KERNEL_SCOPED_MAX = _B.DF_ONE_KERNEL_SCOPED_MAX  # 64 MiB tier
DF_ONE_KERNEL_SCOPED_MAX2 = _B.DF_ONE_KERNEL_SCOPED_MAX2  # 96 MiB / 1.7


def engine_plan_df(grid_shape: tuple[int, int, int],
                   degree: int) -> tuple[str, int | None]:
    """(form, scoped_vmem_kib) for the df engine: 'one' within the
    df-specific one-kernel tiers above (requesting the same per-compile
    scoped-VMEM limits as the f32 ladder — those are hardware properties,
    not kernel estimates), else 'chunked' (the y-chunked two-kernel form
    — every VMEM object O(CY * NZ), no size ceiling)."""
    from .kron_cg import ONE_KERNEL_SCOPED_KIB, ONE_KERNEL_SCOPED_KIB2

    v = engine_vmem_bytes_df(grid_shape, degree)
    if v <= DF_VMEM_BUDGET:
        return "one", None
    if v <= DF_ONE_KERNEL_SCOPED_MAX:
        return "one", ONE_KERNEL_SCOPED_KIB
    if v <= DF_ONE_KERNEL_SCOPED_MAX2:
        return "one", ONE_KERNEL_SCOPED_KIB2
    return "chunked", None


# ---------------------------------------------------------------------------
# In-kernel df building blocks (plain-array (value, error) pairs; DF
# NamedTuples are avoided inside the kernel to keep ref plumbing flat).
# ---------------------------------------------------------------------------


def _eft_term(chi, clo, chh, chl, s, slo, sh, sl):
    """One banded term c * x in df: error-free product of the hi channels
    (Dekker, both splits precomputed/shared) plus first-order cross
    terms. Returns (t, e) with t + e ~= c*x to df accuracy. Zero
    coefficient columns (banded_diags boundary) give t = e = 0 exactly,
    preserving the stencil's edge behaviour."""
    t = chi * s
    e = ((chh * sh - t) + (chh * sl + chl * sh)) + chl * sl
    return t, e + (chi * slo + clo * s)


def _acc2(acc, t, e):
    """Compensated accumulation: the term is RENORMALISED first (feeding
    a raw product straight into the accumulation two_sum is a measured
    XLA:CPU rewrite hazard — the fused graph loses the carries and the
    contraction degrades to ~1e-8 relative; with the renorm the whole
    chain holds ~4e-15, and neither bitcast nor optimization_barrier
    laundering prevents the rewrite, both being stripped before late
    simplification), then two_sum on the value channel with the carry
    folded into the error channel by plain adds (the error channel is
    O(2^-24) of the value, so its own rounding is O(2^-48))."""
    th, tl = two_sum(t, e)
    if acc is None:
        return th, tl
    s, c = two_sum(acc[0], th)
    return s, acc[1] + (tl + c)


def _renorm2(p, e):
    return two_sum(p, e)


def _z_contract_df(hi, lo, cK, cM, P: int, NZ: int):
    """Banded z (lane-shift) contractions of the df plane by the Kz and
    Mz 4-channel stacks: ((aK, aKe), (aM, aMe)), renormalised."""
    hh, hl = _split(hi)

    def pad(a):
        return jnp.pad(a, ((0, 0), (P, P)))

    Phi, Plo, Phh, Phl = pad(hi), pad(lo), pad(hh), pad(hl)
    accK = accM = None
    for d in range(2 * P + 1):
        s = Phi[:, d:d + NZ]
        slo = Plo[:, d:d + NZ]
        sh = Phh[:, d:d + NZ]
        sl = Phl[:, d:d + NZ]
        for c4, which in ((cK, "K"), (cM, "M")):
            t, e = _eft_term(
                c4[0, d][None, :], c4[1, d][None, :],
                c4[2, d][None, :], c4[3, d][None, :],
                s, slo, sh, sl,
            )
            if which == "K":
                accK = _acc2(accK, t, e)
            else:
                accM = _acc2(accM, t, e)
    return _renorm2(*accK), _renorm2(*accM)


def _y_window_contract_df(ops_k, ops_m, cK_rows, cM_rows, nb: int,
                          rows: int, offset: int = 0):
    """Windowed banded y (sublane-shift) contraction core on 4-channel
    pre-extended operands (rows [offset - P, offset + rows + P) relative
    to the output): t12 = M_y aK + K_y aM in ONE compensated pair,
    tyz = M_y aM. `cK_rows`/`cM_rows` are per-output-row coefficient
    channels as callables ch, d -> (rows,) column vectors. Shared by the
    one-kernel (full plane) and chunked forms."""
    acc12 = accyz = None
    for d in range(nb):
        sK = [a[offset + d:offset + d + rows, :] for a in ops_k]
        sM = [a[offset + d:offset + d + rows, :] for a in ops_m]
        cm = [cM_rows(ch, d)[:, None] for ch in range(4)]
        ck = [cK_rows(ch, d)[:, None] for ch in range(4)]
        # t12 += M_y[d] * aK[shift]
        t, e = _eft_term(*cm, *sK)
        acc12 = _acc2(acc12, t, e)
        # t12 += K_y[d] * aM[shift]
        t, e = _eft_term(*ck, *sM)
        acc12 = _acc2(acc12, t, e)
        # tyz += M_y[d] * aM[shift]
        t, e = _eft_term(*cm, *sM)
        accyz = _acc2(accyz, t, e)
    return _renorm2(*acc12), _renorm2(*accyz)


def _split4(pair):
    """(hi, lo) -> 4-channel [hi, lo, split_high(hi), split_low(hi)]."""
    h, lo = pair
    hh, hl = _split(h)
    return [h, lo, hh, hl]


def _y_contract_df(aK, aM, cKy, cMy, P: int, NY: int):
    """Full-plane banded y contractions (one-kernel form): inputs are
    renormalised (hi, lo) pairs; splits computed once, zero-padded by P
    rows each side (boundary exactness via the banded zero columns)."""

    def pad(a):
        return jnp.pad(a, ((P, P), (0, 0)))

    ops_k = [pad(a) for a in _split4(aK)]
    ops_m = [pad(a) for a in _split4(aM)]
    return _y_window_contract_df(
        ops_k, ops_m,
        lambda ch, d: cKy[ch, d], lambda ch, d: cMy[ch, d],
        2 * P + 1, NY,
    )


def _plane_dot_df(ph, plo, yh, ylo, NY: int, NZ: int):
    """Compensated <p, y> over one (NY, NZ) plane: error-free elementwise
    products, then two_sum tree reduction over zero-padded power-of-two
    axes. Returns ((1, 1), (1, 1)) value/error arrays."""
    phh, phl = _split(ph)
    yhh, yhl = _split(yh)
    t = ph * yh
    e = ((phh * yhh - t) + (phh * yhl + phl * yhh)) + phl * yhl
    e = e + (ph * ylo + plo * yh)
    # renormalise before the tree: raw products feeding two_sum is the
    # XLA rewrite hazard (_acc2 docstring)
    t, e = two_sum(t, e)

    def p2(n):
        m = 1
        while m < n:
            m *= 2
        return m

    padr, padc = p2(NY) - NY, p2(NZ) - NZ
    t = jnp.pad(t, ((0, padr), (0, padc)))
    e = jnp.pad(e, ((0, padr), (0, padc)))
    for axis in (0, 1):
        while t.shape[axis] > 1:
            m = t.shape[axis] // 2
            if axis == 0:
                ta, tb = t[:m, :], t[m:, :]
                ea, eb = e[:m, :], e[m:, :]
            else:
                ta, tb = t[:, :m], t[:, m:]
                ea, eb = e[:, :m], e[:, m:]
            t, c = two_sum(ta, tb)
            e = (ea + eb) + c
    return t, e


# ---------------------------------------------------------------------------
# The kernel
# ---------------------------------------------------------------------------


def _make_kron_cg_df_kernel(P: int, NX: int, NY: int, NZ: int,
                            update_p: bool, halo: int = 0,
                            ext2d: bool = False):
    """One-kernel delay-ring df CG iteration: grid of NX + P steps. Step
    t < NX ingests plane t (df p-update fused), contracts z and y in
    registers, and scatter-accumulates the x-band contribution into the
    2P+1 pending output accumulator slots; step t >= P emits output
    plane i = t - P (renormalise, Dirichlet blend, compensated dot) and
    recycles its slot.

    `halo = P` is the distributed form (dist.kron_cg_df): NX is the
    shard's local plane count, the input slab carries P exchanged halo
    planes per side, ingest sweeps all NX + 2P extended planes, the
    scatter targets local outputs i = (t - halo) + d, and emit runs at
    lag P + halo (output i's last contribution arrives at extended step
    i + halo + P) — every output row globally exact, no boundary
    epilogue, grid exactly NX + 2*halo steps. The per-plane
    [interior-in-x, dot-ownership] pair streams via SMEM (aux_ref), as
    in the f32 halo form (ops.kron_cg).

    `ext2d` (3D-sharded meshes, with halo = P — the df twin of the f32
    ext2d form, ops.kron_cg): the input planes are halo-extended in y/z
    as well ((NY+2P, NZ+2P), NY/NZ the LOCAL cross-section); the df z/y
    contractions run on the extended cross-section with per-shard
    global-indexed 4-channel coefficient slices — exact on the local
    window, garbage in the (unconsumed) halo fringe — and the local
    (NY, NZ) window of (p, t12, tyz) is sliced before the ring stores
    and the accumulator scatter. The Dirichlet interior test and the
    cross-section dot-ownership weights come from two streamed (NY, NZ)
    mask planes (mask2d, w2d): the closed-form iota test and the
    per-plane scalar weight only know global axes. The 0/1 w2d weight
    multiplies the p channels BEFORE the compensated plane dot —
    exact, so the compensation survives the dedup."""
    KI = 2 * P + 1  # accumulator ring: exactly the live x-band window
    KP = P + 1  # p ring: read back once at lag P
    nb = 2 * P + 1
    lag = P + halo
    n_in = NX + 2 * halo
    nsteps = n_in if halo else NX + P
    E = 2 * P if ext2d else 0
    NYe, NZe = NY + E, NZ + E

    def kernel(*refs):
        if update_p:
            rh_ref, rl_ref, pph_ref, ppl_ref = refs[:4]
            ni = 4
        else:
            xh_ref, xl_ref = refs[:2]
            ni = 2
        ckz_ref, cmz_ref, cky_ref, cmy_ref = refs[ni:ni + 4]
        ni += 4
        # nb single-row SMEM views of the x coefficient rows: view j holds
        # the row of output plane i = (t - halo) + (j - P) (a stride-1
        # sliding window is not expressible as one blocked spec, so the
        # window is nb static-offset views of the same array — the folded
        # kernels' multi-view pattern)
        cx_refs = refs[ni:ni + nb]
        ni += nb
        aux_ref = mask2d_ref = w2d_ref = None
        if halo:
            aux_ref = refs[ni]
            ni += 1
            if ext2d:
                mask2d_ref, w2d_ref = refs[ni:ni + 2]
                ni += 2
        beta_ref = refs[ni]
        base = ni + 1
        if update_p:
            (ph_out, pl_out, yh_out, yl_out, dot_ref) = refs[base:base + 5]
            no = 5
        else:
            yh_out, yl_out, dot_ref = refs[base:base + 3]
            no = 3
        (acc_p, acc_e, ring_ph, ring_pl, dacc_p, dacc_e) = \
            refs[base + no:base + no + 6]

        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            # Zero accumulators and rings: freshly allocated VMEM can hold
            # NaN bit patterns, and the first P emits read ring slots that
            # 0-coefficient products never overwrote.
            acc_p[...] = jnp.zeros_like(acc_p)
            acc_e[...] = jnp.zeros_like(acc_e)
            ring_ph[...] = jnp.zeros_like(ring_ph)
            ring_pl[...] = jnp.zeros_like(ring_pl)
            dacc_p[...] = jnp.zeros_like(dacc_p)
            dacc_e[...] = jnp.zeros_like(dacc_e)

        # ---- ingest plane t ----
        @pl.when(t < np.int32(n_in))
        def _ingest():
            if update_p:
                # p = beta * p_prev + r in df (beta splits ride in SMEM)
                bh = beta_ref[0, 0]
                bl = beta_ref[0, 1]
                bhh = beta_ref[0, 2]
                bhl = beta_ref[0, 3]
                pph = pph_ref[0]
                ppl = ppl_ref[0]
                ph_h, ph_l = _split(pph)
                tb = bh * pph
                eb = (((bhh * ph_h - tb) + (bhh * ph_l + bhl * ph_h))
                      + bhl * ph_l) + (bh * ppl + bl * pph)
                tbh, tbl = two_sum(tb, eb)  # renorm-first (_acc2 docstring)
                s, c = two_sum(tbh, rh_ref[0])
                p2h, p2l = _renorm2(s, (tbl + c) + rl_ref[0])
            else:
                p2h = xh_ref[0]
                p2l = xl_ref[0]
            if ext2d:
                # p-update runs on the FULL extended plane (the halo
                # fringe feeds the contractions); ring/p_out carry the
                # local window only
                p2h_loc = p2h[P:P + NY, P:P + NZ]
                p2l_loc = p2l[P:P + NY, P:P + NZ]
            else:
                p2h_loc, p2l_loc = p2h, p2l
            if update_p:
                if halo:
                    # p is owned for the NX local planes only; halo
                    # planes feed the rings but are the neighbours' to
                    # store
                    @pl.when(jnp.logical_and(t >= np.int32(halo),
                                             t < np.int32(NX + halo)))
                    def _store_p():
                        ph_out[0] = p2h_loc
                        pl_out[0] = p2l_loc
                else:
                    ph_out[0] = p2h_loc
                    pl_out[0] = p2l_loc
            # ungated extended-index ring store (the f32 halo kernel's
            # scheme): emit for local output i reads the plane ingested
            # at extended step i + halo — P intervening stores fill the
            # other KP-1 slots, so no collision in either form
            ring_ph[jax.lax.rem(t, np.int32(KP))] = p2h_loc
            ring_pl[jax.lax.rem(t, np.int32(KP))] = p2l_loc

            aK, aM = _z_contract_df(p2h, p2l, ckz_ref, cmz_ref, P, NZe)
            t12, tyz = _y_contract_df(aK, aM, cky_ref, cmy_ref, P, NYe)
            t12h, t12l = t12
            tyzh, tyzl = tyz
            if ext2d:
                # exact on the local window (the per-shard coefficient
                # slices are global-indexed there); the halo fringe
                # rows/cols are garbage and sliced away before the
                # accumulator scatter
                t12h = t12h[P:P + NY, P:P + NZ]
                t12l = t12l[P:P + NY, P:P + NZ]
                tyzh = tyzh[P:P + NY, P:P + NZ]
                tyzl = tyzl[P:P + NY, P:P + NZ]
            t12hh, t12hl = _split(t12h)
            tyzhh, tyzhl = _split(tyzh)

            # x-band scatter: contribution of source plane t to output
            # i = (t - halo) + d uses band entry P - d of output i's
            # coefficient row (y[i] = sum_db c[db, i] * t12[i + db - P]).
            for d in range(-P, P + 1):
                i_out = t - np.int32(halo) + np.int32(d)

                @pl.when(jnp.logical_and(i_out >= 0,
                                         i_out < np.int32(NX)))
                def _scatter(i_out=i_out, d=d):
                    cx_ref = cx_refs[d + P]  # view pinned to this i_out
                    db = P - d
                    # cx channel groups of 2nb: [hi | lo | hih | hil],
                    # M at +db, K at +nb+db within each group
                    cm = [cx_ref[0, 0, g * 2 * nb + db]
                          for g in range(4)]
                    ck = [cx_ref[0, 0, g * 2 * nb + nb + db]
                          for g in range(4)]
                    tM, eM = _eft_term(*cm, t12h, t12l, t12hh, t12hl)
                    tK, eK = _eft_term(*ck, tyzh, tyzl, tyzhh, tyzhl)
                    # renorm-first per term (_acc2 docstring), then one
                    # compensated read-modify-write of the slot
                    tMh, tMl = two_sum(tM, eM)
                    tKh, tKl = two_sum(tK, eK)
                    slot = jax.lax.rem(i_out, np.int32(KI))
                    s1, c1 = two_sum(acc_p[slot], tMh)
                    s2, c2 = two_sum(s1, tKh)
                    acc_p[slot] = s2
                    acc_e[slot] = (acc_e[slot]
                                   + ((tMl + c1) + (tKl + c2)))

        # ---- emit plane i = t - (P + halo) ----
        @pl.when(t >= np.int32(lag))
        def _emit():
            i = t - np.int32(lag)
            slot = jax.lax.rem(i, np.int32(KI))
            yh, yl = _renorm2(acc_p[slot], acc_e[slot])
            # local output i was ingested at extended step i + halo
            pslot = jax.lax.rem(i + np.int32(halo), np.int32(KP))
            p_ih = ring_ph[pslot]
            p_il = ring_pl[pslot]
            # interior-in-x from the streamed aux row in the halo form
            # (the local plane index is not the global one)
            mi = (aux_ref[0, 0, 0] > 0.5 if halo
                  else jnp.logical_and(i > 0, i < np.int32(NX - 1)))
            if ext2d:
                # streamed cross-section interior mask: local row/col
                # indices are not global ones on a 3D-sharded mesh
                inter2d = mask2d_ref[...] > 0.5
            else:
                gy = jax.lax.broadcasted_iota(jnp.int32, (NY, NZ), 0)
                gz = jax.lax.broadcasted_iota(jnp.int32, (NY, NZ), 1)
                inter2d = jnp.logical_and(
                    jnp.logical_and(gy > 0, gy < np.int32(NY - 1)),
                    jnp.logical_and(gz > 0, gz < np.int32(NZ - 1)),
                )
            inter = jnp.logical_and(mi, inter2d)
            yh = jax.lax.select(inter, yh, p_ih)
            yl = jax.lax.select(inter, yl, p_il)
            yh_out[0] = yh
            yl_out[0] = yl
            # recycle the slot for output i + KI (first touched at step
            # i + KI - P (+halo) > t, strictly after this zeroing)
            acc_p[slot] = jnp.zeros_like(yh)
            acc_e[slot] = jnp.zeros_like(yh)
            if ext2d:
                # cross-section seam dedup: the exact 0/1 w2d weight
                # multiplies the p channels before the compensated dot
                pdh = p_ih * w2d_ref[...]
                pdl = p_il * w2d_ref[...]
            else:
                pdh, pdl = p_ih, p_il
            dp, de = _plane_dot_df(pdh, pdl, yh, yl, NY, NZ)
            if halo:
                # dot-ownership weight: 0 on duplicated seam planes so
                # <p, A p> counts every dof once globally
                w = aux_ref[0, 0, 1]
                dp = dp * w
                de = de * w
            s, c = two_sum(dacc_p[...], dp)
            dacc_p[...] = s
            dacc_e[...] = dacc_e[...] + (de + c)

        @pl.when(t == np.int32(nsteps - 1))
        def _finish():
            dh, dl = _renorm2(dacc_p[...], dacc_e[...])
            dot_ref[...] = jnp.concatenate([dh, dl], axis=1)

    return kernel


# ---------------------------------------------------------------------------
# Host-side call plumbing
# ---------------------------------------------------------------------------


def _coeff_stack4(c: DF) -> jnp.ndarray:
    """(4, nb, N) channel stack [hi, lo, hi_split_high, hi_split_low] of
    a DF banded-diagonal array (computed inside jit, hoisted out of the
    CG loop by the callers)."""
    hh, hl = _split(c.hi)
    return jnp.stack([c.hi, c.lo, hh, hl])


def _cx_rows_df(op: KronLaplacianDF, NX: int) -> jnp.ndarray:
    """(NX, 1, 8nb) per-output-plane x coefficient rows: 4 channel groups
    (hi, lo, hih, hil), each [M-row(nb) | K-row(nb)]; kappa is already
    folded into the axis-0 DF factors by build_kron_laplacian_df."""
    m, k = op.Md[0], op.Kd[0]
    mhh, mhl = _split(m.hi)
    khh, khl = _split(k.hi)
    groups = [(m.hi, k.hi), (m.lo, k.lo), (mhh, khh), (mhl, khl)]
    return jnp.concatenate(
        [jnp.concatenate([a.T, b.T], axis=1) for a, b in groups], axis=1
    )[:, None, :]


def _kron_cg_df_call(op: KronLaplacianDF, coeffs, update_p: bool,
                     interpret, *vectors, cx=None, aux=None,
                     mask2d=None, w2d=None):
    """update_p: vectors = (r: DF, p_prev: DF, beta4: (1,4)) ->
    (p: DF, y: DF, <p, A p>: scalar DF).
    else: vectors = (x: DF) -> (y: DF, <x, A x>: scalar DF).

    With `cx`/`aux` given (the distributed form, dist.kron_cg_df),
    vectors are halo-extended (NX + 2P, NY, NZ) DF slabs, `cx` carries
    the per-shard 8nb-channel x-coefficient rows, `aux` the per-plane
    [interior-in-x, dot-ownership] pairs; outputs stay (NX, NY, NZ).

    With `mask2d`/`w2d` also given (the ext2d 3D-sharded form), vectors
    are halo-extended in every axis ((NX+2P, NY+2P, NZ+2P) DF slabs),
    `coeffs` carries the per-shard extended 4-channel (ckz, cmz, cky,
    cmy) banded slices, `mask2d` the (NY, NZ) cross-section
    Dirichlet-interior mask and `w2d` the cross-section dot-ownership
    weights; outputs stay (NX, NY, NZ)."""
    P = op.degree
    halo = 0 if cx is None else P
    ext2d = mask2d is not None
    E = 2 * P if ext2d else 0
    if halo == 0:
        NX, NY, NZ = _grid_shape(op)
    else:
        NXe, NYe_in, NZe_in = (int(d) for d in vectors[0].hi.shape)
        NX = NXe - 2 * P
        NY, NZ = NYe_in - E, NZe_in - E
    NYe, NZe = NY + E, NZ + E
    nb = 2 * P + 1
    ckz, cmz, cky, cmy, cx_rows = coeffs
    if cx is not None:
        cx_rows = cx
    dtype = jnp.float32
    lag = P + halo
    n_in = NX + 2 * halo
    nsteps = n_in if halo else NX + P

    def clamp_in(t):
        return (jax.lax.min(t, np.int32(n_in - 1)), 0, 0)

    def clamp_out(t):
        return (jax.lax.clamp(np.int32(0), t - np.int32(lag),
                              np.int32(NX - 1)), 0, 0)

    plane_spec_in = pl.BlockSpec((1, NYe, NZe), clamp_in,
                                 memory_space=pltpu.VMEM)
    plane_spec_out = pl.BlockSpec((1, NY, NZ), clamp_out,
                                  memory_space=pltpu.VMEM)

    in_specs = []
    operands = []
    if update_p:
        r, p_prev, beta4 = vectors
        in_specs += [plane_spec_in] * 4
        operands += [r.hi, r.lo, p_prev.hi, p_prev.lo]
    else:
        (x,) = vectors
        beta4 = jnp.zeros((1, 4), dtype)
        in_specs += [plane_spec_in] * 2
        operands += [x.hi, x.lo]
    for c, n_ax in ((ckz, NZe), (cmz, NZe), (cky, NYe), (cmy, NYe)):
        in_specs.append(pl.BlockSpec((4, nb, n_ax), lambda t: (0, 0, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(c)
    for j in range(nb):
        def cx_map(t, j=j):
            # view j: the row of output i = (t - halo) + (j - P),
            # clamped; writes to out-of-range i are gated in-kernel
            return (jax.lax.clamp(np.int32(0),
                                  t + np.int32(j - P - halo),
                                  np.int32(NX - 1)), 0, 0)

        in_specs.append(pl.BlockSpec((1, 1, 8 * nb), cx_map,
                                     memory_space=pltpu.SMEM))
        operands.append(cx_rows)
    if halo:
        in_specs.append(pl.BlockSpec((1, 1, 2), clamp_out,
                                     memory_space=pltpu.SMEM))
        operands.append(aux)
        if ext2d:
            for plane in (mask2d, w2d):
                in_specs.append(pl.BlockSpec((NY, NZ), lambda t: (0, 0),
                                             memory_space=pltpu.VMEM))
                operands.append(plane.astype(dtype))
    in_specs.append(pl.BlockSpec((1, 4), lambda t: (0, 0),
                                 memory_space=pltpu.SMEM))
    operands.append(beta4)

    out_specs = []
    out_shapes = []
    if update_p:
        def clamp_p_out(t):
            return (jax.lax.clamp(np.int32(0), t - np.int32(halo),
                                  np.int32(NX - 1)), 0, 0)

        out_specs += [pl.BlockSpec((1, NY, NZ), clamp_p_out,
                                   memory_space=pltpu.VMEM)] * 2
        out_shapes += [jax.ShapeDtypeStruct((NX, NY, NZ), dtype)] * 2
    out_specs += [plane_spec_out] * 2
    out_shapes += [jax.ShapeDtypeStruct((NX, NY, NZ), dtype)] * 2
    out_specs.append(pl.BlockSpec((1, 2), lambda t: (0, 0),
                                  memory_space=pltpu.VMEM))
    out_shapes.append(jax.ShapeDtypeStruct((1, 2), dtype))

    kernel = _make_kron_cg_df_kernel(P, NX, NY, NZ, update_p, halo=halo,
                                     ext2d=ext2d)
    out = pl.pallas_call(
        kernel,
        grid=(nsteps,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((nb, NY, NZ), dtype),  # acc_p
            pltpu.VMEM((nb, NY, NZ), dtype),  # acc_e
            pltpu.VMEM((P + 1, NY, NZ), dtype),  # ring_p hi
            pltpu.VMEM((P + 1, NY, NZ), dtype),  # ring_p lo
            pltpu.VMEM((1, 1), dtype),
            pltpu.VMEM((1, 1), dtype),
        ],
        interpret=_use_interpret() if interpret is None else interpret,
    )(*operands)
    if update_p:
        ph, plo, yh, yl, dot = out
        return (DF(ph, plo), DF(yh, yl), DF(dot[0, 0], dot[0, 1]))
    yh, yl, dot = out
    return DF(yh, yl), DF(dot[0, 0], dot[0, 1])


# ---------------------------------------------------------------------------
# Two-kernel (y-chunked) form: no VMEM size ceiling. Mirrors the f32
# chunked form (ops.kron_cg): kernel ZY streams df (t12, tyz) chunk pairs
# to HBM; kernel X runs the scatter-at-ingest x-band accumulation per
# y-chunk row. Every VMEM object is O(CY * NZ), so 300M-dof df problems
# compile where the one-kernel ring cannot fit a scoped-VMEM tier.
# ---------------------------------------------------------------------------


def _pick_cy_df(NY: int, P: int) -> int:
    from .kron_cg import _pick_cy

    return _pick_cy(NY, P)


def _make_zy_chunk_df_kernel(P: int, NX: int, NY: int, NZ: int, CY: int,
                             NYB: int, update_p: bool):
    """Chunked form, kernel ZY: grid (NX, NYB+1). Ingest chunk yj of
    plane xi (df p-update fused, virtual-pad rows masked), z-contract in
    df, push (value, error) pairs into 3-slot rings; emit chunk yj-1's
    y-contraction from the ring-concatenated window."""
    nb = 2 * P + 1

    def kernel(*refs):
        if update_p:
            rh_ref, rl_ref, pph_ref, ppl_ref = refs[:4]
            ni = 4
        else:
            xh_ref, xl_ref = refs[:2]
            ni = 2
        ckz_ref, cmz_ref, cky_ref, cmy_ref, beta_ref = refs[ni:ni + 5]
        base = ni + 5
        if update_p:
            (ph_out, pl_out, t12h_ref, t12l_ref, tyzh_ref, tyzl_ref) = \
                refs[base:base + 6]
            no = 6
        else:
            t12h_ref, t12l_ref, tyzh_ref, tyzl_ref = refs[base:base + 4]
            no = 4
        (rKp, rKe, rMp, rMe) = refs[base + no:base + no + 4]

        xi = pl.program_id(0)
        yj = pl.program_id(1)

        @pl.when(jnp.logical_and(xi == 0, yj == 0))
        def _init():
            rKp[...] = jnp.zeros_like(rKp)
            rKe[...] = jnp.zeros_like(rKe)
            rMp[...] = jnp.zeros_like(rMp)
            rMe[...] = jnp.zeros_like(rMe)

        @pl.when(yj < np.int32(NYB))
        def _ingest():
            if update_p:
                bh = beta_ref[0, 0]
                bl = beta_ref[0, 1]
                bhh = beta_ref[0, 2]
                bhl = beta_ref[0, 3]
                pph = pph_ref[0]
                ppl = ppl_ref[0]
                ph_h, ph_l = _split(pph)
                tb = bh * pph
                eb = (((bhh * ph_h - tb) + (bhh * ph_l + bhl * ph_h))
                      + bhl * ph_l) + (bh * ppl + bl * pph)
                tbh, tbl = two_sum(tb, eb)  # renorm-first (_acc2)
                s, c = two_sum(tbh, rh_ref[0])
                p2h, p2l = _renorm2(s, (tbl + c) + rl_ref[0])
            else:
                p2h = xh_ref[0]
                p2l = xl_ref[0]
            # Mask virtual-pad rows of the last chunk: their garbage
            # would ride the ring into valid output rows as 0 * NaN.
            gy = (yj * np.int32(CY)
                  + jax.lax.broadcasted_iota(jnp.int32, (CY, NZ), 0))
            valid = gy < np.int32(NY)
            p2h = jax.lax.select(valid, p2h, jnp.zeros_like(p2h))
            p2l = jax.lax.select(valid, p2l, jnp.zeros_like(p2l))
            if update_p:
                ph_out[0] = p2h
                pl_out[0] = p2l
            aK, aM = _z_contract_df(p2h, p2l, ckz_ref, cmz_ref, P, NZ)
            slot = jax.lax.rem(yj, np.int32(3))
            rKp[slot], rKe[slot] = aK
            rMp[slot], rMe[slot] = aM

        @pl.when(yj >= 1)
        def _emit():
            j = yj - 1

            def rd(ring, d):
                return ring[jax.lax.rem(j + np.int32(d + 3), np.int32(3))]

            def buf(rp, re):
                h = jnp.concatenate([rd(rp, -1), rd(rp, 0), rd(rp, 1)],
                                    axis=0)
                lo = jnp.concatenate([rd(re, -1), rd(re, 0), rd(re, 1)],
                                     axis=0)
                return _split4((h, lo))

            ops_k = buf(rKp, rKe)
            ops_m = buf(rMp, rMe)
            # rows [(j-1)CY, (j+2)CY): the chunk's rows start at offset
            # CY - P relative to its -P halo
            t12, tyz = _y_window_contract_df(
                ops_k, ops_m,
                lambda ch, d: cky_ref[0, ch, d],
                lambda ch, d: cmy_ref[0, ch, d],
                nb, CY, offset=CY - P,
            )
            t12h_ref[0], t12l_ref[0] = t12
            tyzh_ref[0], tyzl_ref[0] = tyz

    return kernel


def _make_x_chunk_df_kernel(P: int, NX: int, NY: int, NZ: int, CY: int):
    """Chunked form, kernel X: grid (NYB, NX+P), xi fastest — the
    scatter-at-ingest x-band accumulation and compensated dot of the
    one-kernel form, per y-chunk row."""
    nb = 2 * P + 1
    KI = nb
    KP = P + 1

    def kernel(*refs):
        (t12h_ref, t12l_ref, tyzh_ref, tyzl_ref, ph_ref, pl_ref) = refs[:6]
        cx_refs = refs[6:6 + nb]
        yh_out, yl_out, dot_ref = refs[6 + nb:6 + nb + 3]
        (acc_p, acc_e, ring_ph, ring_pl, dacc_p, dacc_e) = \
            refs[6 + nb + 3:6 + nb + 9]

        yj = pl.program_id(0)
        xi = pl.program_id(1)

        @pl.when(xi == 0)
        def _init():
            acc_p[...] = jnp.zeros_like(acc_p)
            acc_e[...] = jnp.zeros_like(acc_e)
            ring_ph[...] = jnp.zeros_like(ring_ph)
            ring_pl[...] = jnp.zeros_like(ring_pl)
            dacc_p[...] = jnp.zeros_like(dacc_p)
            dacc_e[...] = jnp.zeros_like(dacc_e)

        @pl.when(xi < np.int32(NX))
        def _ingest():
            t12h = t12h_ref[0]
            t12l = t12l_ref[0]
            tyzh = tyzh_ref[0]
            tyzl = tyzl_ref[0]
            t12hh, t12hl = _split(t12h)
            tyzhh, tyzhl = _split(tyzh)
            ring_ph[jax.lax.rem(xi, np.int32(KP))] = ph_ref[0]
            ring_pl[jax.lax.rem(xi, np.int32(KP))] = pl_ref[0]
            for d in range(-P, P + 1):
                i_out = xi + np.int32(d)

                @pl.when(jnp.logical_and(i_out >= 0,
                                         i_out < np.int32(NX)))
                def _scatter(i_out=i_out, d=d):
                    cx_ref = cx_refs[d + P]
                    db = P - d
                    cm = [cx_ref[0, 0, g * 2 * nb + db] for g in range(4)]
                    ck = [cx_ref[0, 0, g * 2 * nb + nb + db]
                          for g in range(4)]
                    tM, eM = _eft_term(*cm, t12h, t12l, t12hh, t12hl)
                    tK, eK = _eft_term(*ck, tyzh, tyzl, tyzhh, tyzhl)
                    tMh, tMl = two_sum(tM, eM)
                    tKh, tKl = two_sum(tK, eK)
                    slot = jax.lax.rem(i_out, np.int32(KI))
                    s1, c1 = two_sum(acc_p[slot], tMh)
                    s2, c2 = two_sum(s1, tKh)
                    acc_p[slot] = s2
                    acc_e[slot] = (acc_e[slot]
                                   + ((tMl + c1) + (tKl + c2)))

        @pl.when(xi >= np.int32(P))
        def _emit():
            i = xi - np.int32(P)
            slot = jax.lax.rem(i, np.int32(KI))
            yh, yl = _renorm2(acc_p[slot], acc_e[slot])
            pslot = jax.lax.rem(i, np.int32(KP))
            gy = (yj * np.int32(CY)
                  + jax.lax.broadcasted_iota(jnp.int32, (CY, NZ), 0))
            gz = jax.lax.broadcasted_iota(jnp.int32, (CY, NZ), 1)
            # Mask virtual-pad rows of the last chunk out of p: the p
            # stream's partial edge block reads garbage there (the
            # action form streams the raw input; the CG form reads back
            # rows the ZY writeback dropped), and 0 * garbage is NaN.
            valid = gy < np.int32(NY)
            p_ih = jax.lax.select(valid, ring_ph[pslot],
                                  jnp.zeros_like(ring_ph[pslot]))
            p_il = jax.lax.select(valid, ring_pl[pslot],
                                  jnp.zeros_like(ring_pl[pslot]))
            inter = jnp.logical_and(
                jnp.logical_and(i > 0, i < np.int32(NX - 1)),
                jnp.logical_and(
                    jnp.logical_and(gy > 0, gy < np.int32(NY - 1)),
                    jnp.logical_and(gz > 0, gz < np.int32(NZ - 1)),
                ),
            )
            yh = jax.lax.select(inter, yh, p_ih)
            yl = jax.lax.select(inter, yl, p_il)
            yh_out[0] = yh
            yl_out[0] = yl
            acc_p[slot] = jnp.zeros_like(yh)
            acc_e[slot] = jnp.zeros_like(yh)
            # pad rows also masked out of y for the dot (the acc garbage
            # rides them; the writeback drops them from the output)
            ydh = jax.lax.select(valid, yh, jnp.zeros_like(yh))
            ydl = jax.lax.select(valid, yl, jnp.zeros_like(yl))
            dp, de = _plane_dot_df(p_ih, p_il, ydh, ydl, CY, NZ)
            s, c = two_sum(dacc_p[...], dp)
            dacc_p[...] = s
            dacc_e[...] = dacc_e[...] + (de + c)

        @pl.when(xi == np.int32(NX + P - 1))
        def _finish():
            dh, dl = _renorm2(dacc_p[...], dacc_e[...])
            dot_ref[...] = jnp.concatenate([dh, dl], axis=1)[None]

    return kernel


def _kron_cg_df_call_chunked(op: KronLaplacianDF, coeffs, update_p: bool,
                             interpret, *vectors):
    """Two-kernel (y-chunked) form of _kron_cg_df_call — same contract,
    no VMEM size ceiling (every buffer is one (CY, NZ) chunk pair)."""
    P = op.degree
    NX, NY, NZ = _grid_shape(op)
    nb = 2 * P + 1
    CY = _pick_cy_df(NY, P)
    NYB = -(-NY // CY)
    dtype = jnp.float32
    interp = _use_interpret() if interpret is None else interpret
    ckz, cmz, cky, cmy, cx_rows = coeffs

    # chunk-major y coefficients (NYB, 4, nb, CY), zero-padded rows (the
    # zero columns keep garbage source rows out of valid outputs)
    pad_y = NYB * CY - NY

    def chunk_major(c4):
        c = jnp.pad(c4, ((0, 0), (0, 0), (0, pad_y)))
        return c.reshape(4, nb, NYB, CY).transpose(2, 0, 1, 3)

    cky_c = chunk_major(cky)
    cmy_c = chunk_major(cmy)

    def in_map(xi, yj):
        return (xi, jax.lax.min(yj, np.int32(NYB - 1)), 0)

    def out_map_emit(xi, yj):
        return (xi, jax.lax.max(yj - 1, np.int32(0)), 0)

    in_specs = []
    operands = []
    if update_p:
        r, p_prev, beta4 = vectors
        in_specs += [pl.BlockSpec((1, CY, NZ), in_map,
                                  memory_space=pltpu.VMEM)] * 4
        operands += [r.hi, r.lo, p_prev.hi, p_prev.lo]
    else:
        (x,) = vectors
        beta4 = jnp.zeros((1, 4), dtype)
        in_specs += [pl.BlockSpec((1, CY, NZ), in_map,
                                  memory_space=pltpu.VMEM)] * 2
        operands += [x.hi, x.lo]
    for c in (ckz, cmz):
        in_specs.append(pl.BlockSpec((4, nb, NZ), lambda xi, yj: (0, 0, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(c)
    for c in (cky_c, cmy_c):
        in_specs.append(pl.BlockSpec(
            (1, 4, nb, CY),
            lambda xi, yj: (jax.lax.max(yj - 1, np.int32(0)), 0, 0, 0),
            memory_space=pltpu.VMEM,
        ))
        operands.append(c)
    in_specs.append(pl.BlockSpec((1, 4), lambda xi, yj: (0, 0),
                                 memory_space=pltpu.SMEM))
    operands.append(beta4)

    out_specs = []
    out_shapes = []
    if update_p:
        out_specs += [pl.BlockSpec((1, CY, NZ), in_map,
                                   memory_space=pltpu.VMEM)] * 2
        out_shapes += [jax.ShapeDtypeStruct((NX, NY, NZ), dtype)] * 2
    out_specs += [pl.BlockSpec((1, CY, NZ), out_map_emit,
                               memory_space=pltpu.VMEM)] * 4
    out_shapes += [jax.ShapeDtypeStruct((NX, NY, NZ), dtype)] * 4

    zy = pl.pallas_call(
        _make_zy_chunk_df_kernel(P, NX, NY, NZ, CY, NYB, update_p),
        grid=(NX, NYB + 1),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[pltpu.VMEM((3, CY, NZ), dtype)] * 4,
        interpret=interp,
    )(*operands)
    if update_p:
        ph, plo, t12h, t12l, tyzh, tyzl = zy
        p = DF(ph, plo)
    else:
        t12h, t12l, tyzh, tyzl = zy
        p = vectors[0]

    def x_in_map(yj, xi):
        return (jax.lax.min(xi, np.int32(NX - 1)), yj, 0)

    def x_lag_map(yj, xi):
        return (jax.lax.clamp(np.int32(0), xi - np.int32(P),
                              np.int32(NX - 1)), yj, 0)

    x_in_specs = [pl.BlockSpec((1, CY, NZ), x_in_map,
                               memory_space=pltpu.VMEM)] * 4
    x_in_specs += [pl.BlockSpec((1, CY, NZ), x_in_map,
                                memory_space=pltpu.VMEM)] * 2
    x_operands = [t12h, t12l, tyzh, tyzl, p.hi, p.lo]
    for j in range(nb):
        def cx_map(yj, xi, j=j):
            return (jax.lax.clamp(np.int32(0), xi + np.int32(j - P),
                                  np.int32(NX - 1)), 0, 0)

        x_in_specs.append(pl.BlockSpec((1, 1, 8 * nb), cx_map,
                                       memory_space=pltpu.SMEM))
        x_operands.append(cx_rows)

    yh, yl, dot = pl.pallas_call(
        _make_x_chunk_df_kernel(P, NX, NY, NZ, CY),
        grid=(NYB, NX + P),
        in_specs=x_in_specs,
        out_specs=[
            pl.BlockSpec((1, CY, NZ), x_lag_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, CY, NZ), x_lag_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 2), lambda yj, xi: (yj, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((NX, NY, NZ), dtype),
            jax.ShapeDtypeStruct((NX, NY, NZ), dtype),
            jax.ShapeDtypeStruct((NYB, 1, 2), dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((nb, CY, NZ), dtype),
            pltpu.VMEM((nb, CY, NZ), dtype),
            pltpu.VMEM((P + 1, CY, NZ), dtype),
            pltpu.VMEM((P + 1, CY, NZ), dtype),
            pltpu.VMEM((1, 1), dtype),
            pltpu.VMEM((1, 1), dtype),
        ],
        interpret=interp,
    )(*x_operands)
    # per-chunk dot partials: fold the (value, error) rows with the
    # renorm-first discipline (plain summing the hi channel would cost
    # the compensation; NYB is tiny so this is scalar work)
    from ..la.df64 import df_add

    acc = DF(dot[0, 0, 0], dot[0, 0, 1])
    for j in range(1, int(dot.shape[0])):
        acc = df_add(acc, DF(dot[j, 0, 0], dot[j, 0, 1]))
    y = DF(yh, yl)
    if update_p:
        return p, y, acc
    return y, acc


def _make_update_df_kernel(NX: int, NY: int, NZ: int, CY: int):
    """df x/r update + <r, r> partials as one chunked pallas pass (the
    XLA whole-vector df fusion hits the TPU backend's compile wall even
    earlier than f32's ~130M dofs; every buffer here is one (CY, NZ)
    chunk pair)."""

    def kernel(xh_ref, xl_ref, ph_ref, pl_ref, rh_ref, rl_ref,
               yh_ref, yl_ref, al_ref, x1h_ref, x1l_ref, r1h_ref,
               r1l_ref, rr_ref, racc_p, racc_e):
        xi = pl.program_id(0)
        yj = pl.program_id(1)

        @pl.when(jnp.logical_and(xi == 0, yj == 0))
        def _init():
            racc_p[...] = jnp.zeros_like(racc_p)
            racc_e[...] = jnp.zeros_like(racc_e)

        ah = al_ref[0, 0]
        alo = al_ref[0, 1]
        ahh = al_ref[0, 2]
        ahl = al_ref[0, 3]

        def axpy(vh, vl, wh, wl, sign):
            # v + sign * alpha * w in df (alpha splits in SMEM)
            wh_h, wh_l = _split(wh)
            t = ah * wh
            e = (((ahh * wh_h - t) + (ahh * wh_l + ahl * wh_h))
                 + ahl * wh_l) + (ah * wl + alo * wh)
            th, tl = two_sum(t, e)  # renorm-first (_acc2 docstring)
            if sign < 0:
                th, tl = -th, -tl
            s, c = two_sum(vh, th)
            return _renorm2(s, (tl + c) + vl)

        x1h, x1l = axpy(xh_ref[0], xl_ref[0], ph_ref[0], pl_ref[0], +1)
        x1h_ref[0] = x1h
        x1l_ref[0] = x1l
        r1h, r1l = axpy(rh_ref[0], rl_ref[0], yh_ref[0], yl_ref[0], -1)
        # mask virtual-pad rows of the last y-chunk out of the reduction
        gy = (yj * np.int32(CY)
              + jax.lax.broadcasted_iota(jnp.int32, (CY, NZ), 0))
        valid = gy < np.int32(NY)
        r1h = jax.lax.select(valid, r1h, jnp.zeros_like(r1h))
        r1l = jax.lax.select(valid, r1l, jnp.zeros_like(r1l))
        r1h_ref[0] = r1h
        r1l_ref[0] = r1l
        dp, de = _plane_dot_df(r1h, r1l, r1h, r1l, CY, NZ)
        s, c = two_sum(racc_p[...], dp)
        racc_p[...] = s
        racc_e[...] = racc_e[...] + (de + c)

        @pl.when(jnp.logical_and(xi == np.int32(NX - 1),
                                 yj == np.int32(-(-NY // CY) - 1)))
        def _finish():
            dh, dl = _renorm2(racc_p[...], racc_e[...])
            rr_ref[...] = jnp.concatenate([dh, dl], axis=1)

    return kernel


def cg_update_df_pallas(x: DF, p: DF, r: DF, y: DF, alpha: DF,
                        interpret: bool | None = None):
    """(x + alpha p, r - alpha y, <r1, r1>) in df via the chunked pallas
    pass; alpha rides as a 4-channel SMEM row."""
    NX, NY, NZ = x.hi.shape
    dtype = jnp.float32
    CY = _pick_cy_df(NY, 1)
    NYB = -(-NY // CY)
    spec = pl.BlockSpec((1, CY, NZ), lambda xi, yj: (xi, yj, 0),
                        memory_space=pltpu.VMEM)
    a4 = _beta4(alpha)
    x1h, x1l, r1h, r1l, rr = pl.pallas_call(
        _make_update_df_kernel(NX, NY, NZ, CY),
        grid=(NX, NYB),
        in_specs=[spec] * 8 + [pl.BlockSpec((1, 4), lambda xi, yj: (0, 0),
                                            memory_space=pltpu.SMEM)],
        out_specs=[spec] * 4 + [pl.BlockSpec(
            (1, 2), lambda xi, yj: (0, 0), memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((NX, NY, NZ), dtype)] * 4
        + [jax.ShapeDtypeStruct((1, 2), dtype)],
        scratch_shapes=[pltpu.VMEM((1, 1), dtype)] * 2,
        interpret=_use_interpret() if interpret is None else interpret,
    )(x.hi, x.lo, p.hi, p.lo, r.hi, r.lo, y.hi, y.lo, a4)
    return DF(x1h, x1l), DF(r1h, r1l), DF(rr[0, 0], rr[0, 1])


def _engine_coeffs(op: KronLaplacianDF):
    """The kernel's coefficient operands, built once per jitted call
    (outside the CG loop): z/y 4-channel stacks + the x SMEM rows."""
    NX, _, _ = _grid_shape(op)
    return (
        _coeff_stack4(op.Kd[2]),
        _coeff_stack4(op.Md[2]),
        _coeff_stack4(op.Kd[1]),
        _coeff_stack4(op.Md[1]),
        _cx_rows_df(op, NX),
    )


def _beta4(beta: DF) -> jnp.ndarray:
    """(1, 4) SMEM row [hi, lo, split_high(hi), split_low(hi)]."""
    bh = beta.hi.astype(jnp.float32)
    bhh, bhl = _split(bh)
    return jnp.stack(
        [bh, beta.lo.astype(jnp.float32), bhh, bhl]
    ).reshape(1, 4)


def fused_cg_solve_df(engine, b: DF, nreps: int, update=None,
                      inner=None, done0=None) -> DF:
    """Shared df driver loop, mirroring la.cg.fused_cg_solve: the engine
    performs p-update/apply/alpha-dot in one kernel; x/r updates and
    <r, r> run as XLA df passes, or through `update(x, p, r, y, alpha)
    -> (x1, r1, <r1, r1>)` (the chunked pallas df pass for very large
    problems). `inner` overrides the inner product (the distributed
    engine passes an owned-dof-masked compensated psum dot). Includes
    ops.kron_df.cg_solve_df's df-floor freeze so small fixed-budget
    problems don't amplify noise past the df64 residual floor."""
    dot = df_dot if inner is None else inner
    floor = jnp.float32(1e-24)
    x0 = df_zeros_like(b)
    rnorm0 = dot(b, b)
    rnorm0_hi = rnorm0.hi

    def body(_, state):
        x, r, p_prev, beta, rnorm, done = state
        p, y, pdot = engine(r, p_prev, _beta4(beta))
        alpha = df_div(rnorm, pdot)
        if update is None:
            x1 = df_axpy(x, alpha, p)
            r1 = df_sub(r, df_scale(y, alpha))
            rnorm1 = dot(r1, r1)
        else:
            x1, r1, rnorm1 = update(x, p, r, y, alpha)
        beta1 = df_div(rnorm1, rnorm)
        done1 = jnp.logical_or(done, rnorm1.hi <= floor * rnorm0_hi)

        def keep(new, old):
            return jax.tree_util.tree_map(
                lambda n, o: jnp.where(done, o, n), new, old
            )

        return (keep(x1, x), keep(r1, r), keep(p, p_prev),
                keep(beta1, beta), keep(rnorm1, rnorm), done1)

    zero = DF(jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
    state = (x0, b, df_zeros_like(b), zero, rnorm0,
             jnp.asarray(False) if done0 is None else done0)
    x, *_ = jax.lax.fori_loop(0, nreps, body, state)
    return x


def _df_call_for(op, force_chunked: bool):
    """The engine call matching engine_plan_df's form pick (or the
    driver's chunked retry)."""
    form = engine_plan_df(_grid_shape(op), op.degree)[0]
    if force_chunked or form == "chunked":
        return _kron_cg_df_call_chunked
    return _kron_cg_df_call


def kron_cg_df_solve(op: KronLaplacianDF, b: DF, nreps: int,
                     interpret: bool | None = None,
                     pallas_update: bool | None = None,
                     force_chunked: bool = False) -> DF:
    """Benchmark CG with the fused df iteration. Matches
    ops.kron_df.cg_solve_df to df reassociation accuracy (~1e-12
    relative). `pallas_update` (default: by size, same policy constant
    as the f32 engine) routes the x/r update through the chunked pallas
    df pass; `force_chunked` overrides the auto form pick (the driver's
    Mosaic-rejection retry)."""
    from .kron_cg import PALLAS_UPDATE_MIN_DOFS

    coeffs = _engine_coeffs(op)
    call = _df_call_for(op, force_chunked)

    def engine(r, p_prev, beta4):
        return call(op, coeffs, True, interpret, r, p_prev, beta4)

    use_pallas_update = (b.hi.size >= PALLAS_UPDATE_MIN_DOFS
                         if pallas_update is None else pallas_update)
    update = None
    if use_pallas_update:
        def update(x, p, r, y, alpha):
            return cg_update_df_pallas(x, p, r, y, alpha, interpret)

    return fused_cg_solve_df(engine, b, nreps, update=update)


def kron_apply_ring_df(op: KronLaplacianDF, x: DF,
                       interpret: bool | None = None,
                       force_chunked: bool = False) -> DF:
    """Single fused apply y = A x (Dirichlet pass-through), discarding
    the fused dot. Used by the df action benchmark."""
    coeffs = _engine_coeffs(op)
    y, _ = _df_call_for(op, force_chunked)(op, coeffs, False, interpret, x)
    return y


def action_ring_df(op: KronLaplacianDF, u: DF, nreps: int,
                   interpret: bool | None = None,
                   force_chunked: bool = False) -> DF:
    """nreps fused applies of the same input (benchmark action
    semantics, laplacian_solver.cpp:119-127), loop-fenced like the
    unfused twin (ops.kron_df.action_df)."""
    coeffs = _engine_coeffs(op)
    call = _df_call_for(op, force_chunked)

    def rep(_, y):
        uu, _ = jax.lax.optimization_barrier((u, y))
        out, _ = call(op, coeffs, False, interpret, uu)
        return out

    return jax.lax.fori_loop(0, nreps, rep, df_zeros_like(u))
