"""Device-side RHS assembly for general (perturbed) geometry on folded
vectors: b = M f_h with f_h the nodal interpolant of the Gaussian-bump
source, Dirichlet rows zeroed.

The reference assembles its RHS on the CPU (`assemble_vector(b, L)` +
`bc.set`, /root/reference/src/laplacian_solver.cpp:100-105); our host twin
is fem.assemble.assemble_rhs. That path materialises O(global dofs) host
arrays, which caps the perturbed-mesh problem size by host RAM/wall-time
rather than HBM. This module assembles the same b entirely on device from
the cell corners:

  per cell: dof-node coords = trilinear(corners, nodes1d)  ->  f at nodes
            -> interpolate to quadrature (phi0 per axis)   ->  * w*detJ
            -> project back (phi0^T per axis)              ->  seam-fold

matching assemble_rhs's quadrature exactly (same f-interpolation, same
w*detJ), so the two agree to dtype precision (tested). The per-shard
distributed builder reuses this inside shard_map with each shard's own
corner slice — no global dof-sized arrays anywhere.

Memory: one-shot einsum intermediates are O(ncells * nq^3); fine through
~100M dofs on a 16 GB chip. (The uniform-mesh capacity path is
ops.kron.device_rhs_uniform, which is O(N^1/3).)
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..elements.tables import OperatorTables
from .folded import FoldedLayout, xla_seam_fold
from .geometry import geometry_factors_jax


def source_bump(coords: jnp.ndarray) -> jnp.ndarray:
    """The benchmark source f = 1000 exp(-((x-.5)^2+(y-.5)^2)/0.02)
    (main.cpp:81-92) as jnp (fem.source.default_source is the numpy twin)."""
    dx = (coords[..., 0] - 0.5) ** 2
    dy = (coords[..., 1] - 0.5) ** 2
    return 1000.0 * jnp.exp(-(dx + dy) / 0.02)


def device_rhs_folded(
    corners_cs: jnp.ndarray,  # (Lv, 2, 2, 2, 3) c-space cell corners
    mask_cs: jnp.ndarray,  # (Lv,) 1 real / 0 ghost+pad
    bcf: jnp.ndarray,  # (nb, P^3, B) 0/1 Dirichlet mask (folded)
    layout: FoldedLayout,
    t: OperatorTables,
    dtype=jnp.float32,
) -> jnp.ndarray:
    """Traced: the folded RHS vector (nb, P^3, B). Ghost/pad cells carry a
    zero mask so their contributions vanish; shared-face node values agree
    between neighbouring cells (trilinear restricted to a face depends only
    on that face's corners), so per-cell evaluation matches a global
    interpolant."""
    P = layout.degree
    nd = t.nd
    nodes = np.asarray(t.nodes1d)
    Nn = np.stack([1.0 - nodes, nodes], axis=1)  # (nd, 2) trilinear at nodes
    c = jnp.asarray(corners_cs, dtype)
    Nj = jnp.asarray(Nn, dtype)
    # dof-node coordinates per cell: (Lv, nd, nd, nd, 3)
    coords = jnp.einsum("cabgi,xa,yb,zg->cxyzi", c, Nj, Nj, Nj)
    fd = source_bump(coords)  # (Lv, nd, nd, nd)
    phi = jnp.asarray(t.phi0, dtype)
    # f_h at quadrature points
    fq = jnp.einsum("cxyz,qx,ry,sz->cqrs", fd, phi, phi, phi)
    _, wdetJ = geometry_factors_jax(c, t.pts1d, t.wts1d, compute_G=False)
    tq = fq * wdetJ.reshape(fq.shape) * jnp.asarray(mask_cs, dtype)[
        :, None, None, None
    ]
    # project back to the nd^3 cell nodes
    be = jnp.einsum("cqrs,qi,rj,sk->cijk", tq, phi, phi, phi)
    # per-cell contribution cube -> folded vector with seam overlap-add
    cube = jnp.moveaxis(be, 0, -1)  # (nd, nd, nd, Lv)
    outs = (
        cube[:P, :P, :P], cube[P, :P, :P], cube[:P, P, :P], cube[:P, :P, P],
        cube[P, P, :P], cube[P, :P, P], cube[:P, P, P], cube[P, P, P],
    )
    b = xla_seam_fold(outs, layout)
    return b * (1 - bcf)
