"""Folded vector layout: the TPU-native dof storage for the hot path.

The grid layout (NX, NY, NZ) forces every operator apply through two large
strided transposes (gather to per-cell layout, overlap-add back) that XLA
executes far below DMA speed. This module instead stores a dof vector the
way the kernel consumes it:

    X[i, j, k, c]   i, j, k in [0, P)   c = (cx*npy + cy)*npz + cz

where (cx, cy, cz) ranges over the real cells *plus one ghost column per
axis* (np_a = n_a + 1). Grid point (cx*P+i, ...) maps bijectively: the final
boundary plane of each axis lives in the ghost column's i=0 slot; the
remaining ghost slots are structural zeros. The payoffs:

- a cell's (P+1)^3 window is its own (P,P,P) block plus 7 slabs at
  *constant* flat-c shifts (+Sz=1, +Sy=npz, +Sx=npy*npz and their sums) —
  so "gather" is 7 contiguous-slice reads, and "scatter-add" (the
  reference's atomicAdd, laplacian_gpu.hpp:425) is 7 shifted adds;
- ghost cells get zero geometry rows, so they mask themselves: no bounds
  logic anywhere in the kernel;
- CG vector algebra runs unchanged on the flat arrays (structural zeros are
  preserved by every linear operation).

The kernel (standard pallas_call, fully pipelined BlockSpecs) processes
B = 8*NL cells per grid step: window slabs are DMA'd as (..., B) lane-major
blocks, relaid in-register to the (..., 8, NL) vreg cross-section of
ops.pallas_laplacian, contracted with the compile-time basis tables, and
written back as one main block plus 7 seam outputs.

Cites: stiffness_operator_gpu /root/reference/src/laplacian_gpu.hpp:91-426
(the per-cell math), MatFreeLaplacianGPU::apply laplacian.hpp:281-403
(operator protocol, Dirichlet pass-through laplacian_gpu.hpp:163-169).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..elements.tables import OperatorTables, build_operator_tables
from ..mesh.box import BoxMesh
from ..mesh.dofmap import boundary_dof_marker
from .pallas_laplacian import (
    SUBLANES,
    _use_interpret,
    corner_apply,
    pick_lanes,
    sumfact_window_apply,
)


# ---------------------------------------------------------------------------
# Layout geometry
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FoldedLayout:
    """Shape bookkeeping for the folded layout of one box mesh."""

    n: tuple[int, int, int]  # real cells per axis
    degree: int
    nl: int  # lanes per kernel block

    @property
    def np3(self) -> tuple[int, int, int]:
        return (self.n[0] + 1, self.n[1] + 1, self.n[2] + 1)

    @property
    def shifts(self) -> tuple[int, int, int]:
        """Flat-c shift to the +x/+y/+z neighbour cell."""
        npx, npy, npz = self.np3
        return (npy * npz, npz, 1)

    @property
    def cg(self) -> int:
        npx, npy, npz = self.np3
        return npx * npy * npz

    @property
    def block(self) -> int:
        return SUBLANES * self.nl

    @property
    def nblocks(self) -> int:
        """Rounded up to a multiple of 8 so streaming kernels (CG vector
        update) can process 8 contiguous blocks per grid step without tail
        masking — the pad blocks are structural zeros end to end (zero
        geometry mask, zero vectors), so dots and updates are unaffected."""
        nb = -(-self.cg // self.block)
        return -(-nb // 8) * 8

    @property
    def lv(self) -> int:
        """Padded flat-c vector length (whole number of kernel blocks)."""
        return self.nblocks * self.block

    @property
    def vec_shape(self) -> tuple[int, int, int]:
        """Folded vectors are stored block-major 3D as (nblocks, P^3, B).

        Two hardware constraints picked this layout (both measured):
        - XLA tiles the trailing two dims (8, 128); with the tensor index P
          on the second-minor axis an elementwise pass runs at P/8 sublane
          utilisation — CG glue cost ~3x the kernel. (P^3, B) trailing
          gives 27/32 utilisation at P=3.
        - DMA wants the kernel's per-grid-step operand contiguous: a
          (P^3, B) block gathered from a (P^3, Lv) array is P^3 scattered
          4 kB rows and streams at ~140 GB/s; block-major it is one
          contiguous ~108 kB chunk at full bandwidth.

        The kernel reshapes blocks to (P, P, P, 8, nl) in-register
        (leading-axis split, free)."""
        P = self.degree
        return (self.nblocks, P * P * P, self.block)

    @property
    def vec4_shape(self) -> tuple[int, int, int, int]:
        P = self.degree
        return (P, P, P, self.lv)


def make_layout(n: tuple[int, int, int], degree: int, nq: int,
                itemsize: int = 4, nl: int | None = None) -> FoldedLayout:
    """nl override exists for tests (small nl forces multi-block grids on
    meshes that fit interpret mode)."""
    return FoldedLayout(n=tuple(n), degree=degree,
                        nl=nl or pick_lanes(degree + 1, nq, itemsize))


def _grid_to_cell_indices(layout: FoldedLayout):
    """Per grid point: (i, j, k, c) indices into the folded vector."""
    P = layout.degree
    nx, ny, nz = layout.n
    npx, npy, npz = layout.np3
    X = np.arange(nx * P + 1)
    Y = np.arange(ny * P + 1)
    Z = np.arange(nz * P + 1)
    cx, i = X // P, X % P
    cy, j = Y // P, Y % P
    cz, k = Z // P, Z % P
    c = (
        (cx[:, None, None] * npy + cy[None, :, None]) * npz
        + cz[None, None, :]
    )
    ii = np.broadcast_to(i[:, None, None], c.shape)
    jj = np.broadcast_to(j[None, :, None], c.shape)
    kk = np.broadcast_to(k[None, None, :], c.shape)
    return ii, jj, kk, c


def fold_vector(grid: np.ndarray, layout: FoldedLayout) -> np.ndarray:
    """(NX, NY, NZ) grid -> folded (nb, P^3, B); structural slots zero."""
    ii, jj, kk, c = _grid_to_cell_indices(layout)
    out = np.zeros(layout.vec4_shape, dtype=grid.dtype)
    out[ii, jj, kk, c] = grid
    P3 = layout.degree ** 3
    return np.ascontiguousarray(
        out.reshape(P3, layout.nblocks, layout.block).transpose(1, 0, 2)
    )


def unfold_vector(folded: np.ndarray, layout: FoldedLayout) -> np.ndarray:
    """Folded (nb, P^3, B) -> (NX, NY, NZ) grid (inverse of fold_vector)."""
    ii, jj, kk, c = _grid_to_cell_indices(layout)
    flat = np.asarray(folded).transpose(1, 0, 2).reshape(layout.vec4_shape)
    return flat[ii, jj, kk, c]


def real_cell_flat_indices(layout: FoldedLayout) -> np.ndarray:
    """Flat-c index of each real cell, in (cx, cy, cz) row-major order —
    the cell order of mesh.cell_corners and the geometry tensor."""
    nx, ny, nz = layout.n
    npx, npy, npz = layout.np3
    cx, cy, cz = np.meshgrid(
        np.arange(nx), np.arange(ny), np.arange(nz), indexing="ij"
    )
    return ((cx * npy + cy) * npz + cz).ravel()


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------

def _r8(a: jnp.ndarray, nl: int) -> jnp.ndarray:
    """(..., B) lane-major -> (..., 8, nl) vreg cross-section (in-register
    relayout; cheap next to the contraction work)."""
    return a.reshape(*a.shape[:-1], SUBLANES, nl)


def _rb(a: jnp.ndarray) -> jnp.ndarray:
    """Inverse of _r8."""
    return a.reshape(*a.shape[:-2], a.shape[-2] * a.shape[-1])


def _assemble_window(c000, cx, cy, cz, cxy, cxz, cyz, cxyz):
    """Build the (nd, nd, nd, 8, nl) cell window cube from the 8 shift-class
    slabs (each already in vreg layout). Pure concatenation on vreg-indexed
    axes — register naming, no data movement."""
    A = jnp.concatenate([c000, cz[:, :, None]], axis=2)  # (P, P, nd, ...)
    By = jnp.concatenate([cy, cyz[:, None]], axis=1)  # (P, nd, ...)
    A = jnp.concatenate([A, By[:, None]], axis=1)  # (P, nd, nd, ...)
    Bx = jnp.concatenate([cx, cxz[:, None]], axis=1)  # (P, nd, ...)
    Cx = jnp.concatenate([cxy, cxyz[None]], axis=0)  # (nd, ...)
    Bx = jnp.concatenate([Bx, Cx[None]], axis=0)  # (nd, nd, ...)
    return jnp.concatenate([A, Bx[None]], axis=0)  # (nd, nd, nd, ...)


def _make_folded_kernel(P: int, nl: int, is_identity: bool,
                        phi0: np.ndarray, dphi1: np.ndarray,
                        geom_tables: tuple[np.ndarray, np.ndarray] | None = None):
    """Kernel body. geom_tables=None: geometry streamed as a precomputed
    blocked-G operand. geom_tables=(pts1d, wts1d): geometry computed
    in-kernel from streamed cell corners (corner mode — ~24 floats/cell of
    HBM traffic instead of 6*nq^3; see pallas_laplacian.corner_window_G)."""

    def write_outs(y, y_ref, yx_ref, yy_ref, yz_ref, yxy_ref, yxz_ref,
                   yyz_ref, yxyz_ref):
        y_ref[...] = _rb(y[:P, :P, :P])
        yx_ref[...] = _rb(y[P, :P, :P])
        yy_ref[...] = _rb(y[:P, P, :P])
        yz_ref[...] = _rb(y[:P, :P, P])
        yxy_ref[...] = _rb(y[P, P, :P])
        yxz_ref[...] = _rb(y[P, :P, P])
        yyz_ref[...] = _rb(y[:P, P, P])
        yxyz_ref[...] = _rb(y[P, P, P])

    if geom_tables is None:
        def kernel(u000_ref, ux_ref, uy_ref, uz_ref, uxy_ref, uxz_ref,
                   uyz_ref, uxyz_ref, g_ref, kappa_ref, *out_refs):
            r8 = lambda r: _r8(r[...], nl)  # noqa: E731
            u = _assemble_window(
                r8(u000_ref), r8(ux_ref), r8(uy_ref), r8(uz_ref),
                r8(uxy_ref), r8(uxz_ref), r8(uyz_ref), r8(uxyz_ref),
            )
            y = sumfact_window_apply(
                u, g_ref[0], kappa_ref[0, 0], phi0, dphi1, is_identity
            )
            write_outs(y, *out_refs)
    else:
        pts1d, wts1d = geom_tables

        def kernel(u000_ref, ux_ref, uy_ref, uz_ref, uxy_ref, uxz_ref,
                   uyz_ref, uxyz_ref, c_ref, m_ref, kappa_ref, *out_refs):
            r8 = lambda r: _r8(r[...], nl)  # noqa: E731
            u = _assemble_window(
                r8(u000_ref), r8(ux_ref), r8(uy_ref), r8(uz_ref),
                r8(uxy_ref), r8(uxz_ref), r8(uyz_ref), r8(uxyz_ref),
            )
            y = corner_apply(
                u, c_ref[0], m_ref[0], kappa_ref[0, 0], phi0, dphi1,
                pts1d, wts1d, is_identity
            )
            write_outs(y, *out_refs)

    return kernel


def window_slabs(xm: jnp.ndarray, layout: FoldedLayout) -> tuple:
    """(nb, P^3, B) folded vector -> the v1 window-slab set: the flat-c 4D
    main view (P, P, P, Lv) plus the 7 shifted slab classes (pad + static
    slices; a traced transpose). Shared by the f32 v1 pipeline and the df
    pipeline (ops.folded_df), which runs it once per (hi, lo) channel."""
    P = layout.degree
    Lv = layout.lv
    Sx, Sy, Sz = layout.shifts
    S7 = Sx + Sy + Sz
    xm = jnp.transpose(xm, (1, 0, 2)).reshape(layout.vec4_shape)
    xp = jnp.pad(xm, [(0, 0)] * 3 + [(0, S7)])
    ux = jax.lax.slice(xp[0], (0, 0, Sx), (P, P, Sx + Lv))
    uy = jax.lax.slice(xp[:, 0], (0, 0, Sy), (P, P, Sy + Lv))
    uz = jax.lax.slice(xp[:, :, 0], (0, 0, Sz), (P, P, Sz + Lv))
    uxy = jax.lax.slice(xp[0, 0], (0, Sx + Sy), (P, Sx + Sy + Lv))
    uxz = jax.lax.slice(xp[0, :, 0], (0, Sx + Sz), (P, Sx + Sz + Lv))
    uyz = jax.lax.slice(xp[:, 0, 0], (0, Sy + Sz), (P, Sy + Sz + Lv))
    uxyz = jax.lax.slice(xp[0, 0, 0], (S7,), (S7 + Lv,))
    return (xm, ux, uy, uz, uxy, uxz, uyz, uxyz)


def window_slab_specs(layout: FoldedLayout) -> list:
    """BlockSpecs matching window_slabs' operand order (one (... , B) block
    per grid step), shared with the df pipeline."""
    P = layout.degree
    B = layout.block
    spec = lambda *lead: pl.BlockSpec(  # noqa: E731
        (*lead, B), lambda i, _n=len(lead): (0,) * _n + (i,),
        memory_space=pltpu.VMEM,
    )
    return [
        spec(P, P, P), spec(P, P), spec(P, P), spec(P, P),
        spec(P), spec(P), spec(P), spec(),
    ]


def folded_cell_apply(
    xm: jnp.ndarray,  # (nb, P^3, B) masked folded vector
    geom,  # blocked G (nblocks, 6, nq,nq,nq, 8, nl) | (corners_b, mask_b)
    kappa: jnp.ndarray,
    layout: FoldedLayout,
    phi0: np.ndarray,
    dphi1: np.ndarray,
    is_identity: bool,
    interpret: bool | None = None,
    geom_tables: tuple[np.ndarray, np.ndarray] | None = None,
) -> jnp.ndarray:
    """One operator contribution pass: returns the un-bc'd result vector.

    Geometry comes in one of two forms:
    - precomputed: `geom` is the blocked G tensor (geom_tables None);
    - corner mode: `geom` is `(corners_b, mask_b)` (see blocked_corners) and
      `geom_tables=(pts1d, wts1d)` — G is computed in-kernel per cell.
    """
    P = layout.degree
    nq = phi0.shape[0]
    nl, B, nb, Lv = layout.nl, layout.block, layout.nblocks, layout.lv
    dtype = xm.dtype

    xm, ux, uy, uz, uxy, uxz, uyz, uxyz = window_slabs(xm, layout)

    wspecs = window_slab_specs(layout)
    kernel = _make_folded_kernel(
        P, nl, is_identity,
        np.asarray(phi0, np.float64), np.asarray(dphi1, np.float64),
        geom_tables=geom_tables,
    )
    if geom_tables is None:
        geom_ops = (geom,)
        geom_specs = [
            pl.BlockSpec(
                (1, 6, nq, nq, nq, SUBLANES, nl),
                lambda i: (i, 0, 0, 0, 0, 0, 0), memory_space=pltpu.VMEM,
            ),
        ]
    else:
        corners_b, mask_b = geom
        geom_ops = (corners_b, mask_b)
        geom_specs = [
            pl.BlockSpec(
                (1, 3, 2, 2, 2, SUBLANES, nl),
                lambda i: (i, 0, 0, 0, 0, 0, 0), memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, SUBLANES, nl), lambda i: (i, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ]
    outs = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            *wspecs,
            *geom_specs,
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=list(wspecs),
        out_shape=[
            jax.ShapeDtypeStruct((P, P, P, Lv), dtype),
            jax.ShapeDtypeStruct((P, P, Lv), dtype),
            jax.ShapeDtypeStruct((P, P, Lv), dtype),
            jax.ShapeDtypeStruct((P, P, Lv), dtype),
            jax.ShapeDtypeStruct((P, Lv), dtype),
            jax.ShapeDtypeStruct((P, Lv), dtype),
            jax.ShapeDtypeStruct((P, Lv), dtype),
            jax.ShapeDtypeStruct((Lv,), dtype),
        ],
        interpret=_use_interpret() if interpret is None else interpret,
    )(xm, ux, uy, uz, uxy, uxz, uyz, uxyz, *geom_ops,
      kappa.reshape(1, 1).astype(dtype))

    return xla_seam_fold(outs, layout)


def xla_seam_fold(outs, layout: FoldedLayout) -> jnp.ndarray:
    """XLA-side seam accumulation: the 8 per-cell contribution classes
    (Y (P,P,P,Lv), faces Yx/Yy/Yz, edges Yxy/Yxz/Yyz, corner Yxyz — cells
    last, flat c) overlap-added into one folded (nb, P^3, B) vector.

    The i/j/k = P faces of each cell window coincide with the i/j/k = 0
    slots of the +x/+y/+z neighbour (the structured replacement for
    atomicAdd scatter). Everything is expressed as zero-pads + adds — XLA
    fuses those into one elementwise pass, where the equivalent
    .at[...].add chain costs a full-array copy per seam. Shared by the v1
    reference apply and the device-side RHS assembly (ops.folded_rhs)."""
    P = layout.degree
    Lv, nb, B = layout.lv, layout.nblocks, layout.block
    Sx, Sy, Sz = layout.shifts
    Y, Yx, Yy, Yz, Yxy, Yxz, Yyz, Yxyz = outs

    def shift(a, S):
        """a[..., c] -> contribution at c + S (front zero-pad)."""
        return jnp.pad(a[..., : Lv - S], [(0, 0)] * (a.ndim - 1) + [(S, 0)])

    def lift(a, axis):
        """Insert a size-P axis holding `a` at index 0, zeros elsewhere."""
        pads = [(0, 0)] * (a.ndim + 1)
        pads[axis] = (0, P - 1)
        return jnp.pad(jnp.expand_dims(a, axis), pads)

    # Fold edge/corner contributions into the face slabs first (small
    # arrays), then the three faces into the main block in one fused add.
    Yx = Yx + lift(shift(Yxy, Sy), 0) + lift(shift(Yxz, Sz), 1) \
        + lift(lift(shift(Yxyz, Sy + Sz), 0), 1)
    Yy = Yy + lift(shift(Yyz, Sz), 1)
    out = (
        Y
        + lift(shift(Yx, Sx), 0)
        + lift(shift(Yy, Sy), 1)
        + lift(shift(Yz, Sz), 2)
    )
    return jnp.transpose(
        out.reshape(P * P * P, nb, B), (1, 0, 2)
    )


# ---------------------------------------------------------------------------
# Fused kernel: window gather + apply + seam overlap-add in ONE pallas_call
# ---------------------------------------------------------------------------
#
# The v1 pipeline above (XLA pad/slice -> kernel -> XLA seam pass) measures
# ~2x the kernel's own time: materialising the 7 shifted window slabs alone
# costs as much as the whole contraction chain. The fused kernel eliminates
# every XLA glue pass:
#
# - inputs: the SAME (P^3, Lv) folded vector is passed once per *distinct*
#   block offset q = s // B needed by the 7 shift classes (typically 4-5
#   views), each as a full (P^3, B) block at grid index i + q. In-kernel,
#   each view reshapes (leading-axis split, free) to (P, P, P, 8, nl); the
#   class's window plane is a vreg-indexed slice of that, and the sub-block
#   shift (r = s mod B) is applied IN REGISTERS: a static sublane slice of
#   the concatenated view pair plus a static lane rotate
#   (_shift_window_pair). No shifted copy of x ever exists in HBM;
# - outputs: ONE (P^3, B) block. Seam partials (the 7 cell-window faces/
#   edges/corner that overlap the +x/+y/+z neighbour cells) are kept in VMEM
#   ring buffers across the sequential TPU grid; block i folds in the
#   partials emitted by blocks i - s//B - 1 and i - s//B, which are exactly
#   the blocks whose +s windows overlap it. The reference's atomicAdd
#   scatter (laplacian_gpu.hpp:425) thus becomes a register-shift + add in
#   the consumer's grid step;
# - the Dirichlet pass-through is an in-register select against a streamed
#   0/1 mask block (see folded_cell_apply_fused docstring).


def _shift_window_pair(v0, v1, r: int, nl: int):
    """Extract the flat window [r, r + B) from the concatenation of two
    consecutive (lead..., 8, nl) vreg blocks (flat index = sub*nl + lane).
    r is compile-time static, 0 <= r <= B."""
    if r == 0:
        return v0
    buf = jnp.concatenate([v0, v1], axis=-2)  # (lead..., 16, nl)
    sr, lr = divmod(r, nl)
    A = buf[..., sr:sr + SUBLANES, :]
    if lr == 0:
        return A
    Bv = buf[..., sr + 1:sr + 1 + SUBLANES, :]
    # np.int32: under jax_enable_x64 a Python int traces as int64 and the
    # Mosaic verifier rejects the rotate ('tpu.dynamic_rotate' wants i32).
    Ar = pltpu.roll(A, np.int32(nl - lr), axis=A.ndim - 1)
    Br = pltpu.roll(Bv, np.int32(nl - lr), axis=Bv.ndim - 1)
    lane = jax.lax.broadcasted_iota(jnp.int32, A.shape, A.ndim - 1)
    # raw lax.select (not jnp.where): jnp wrappers trace to closed_call,
    # which the Mosaic kernel-lowering path rejects
    return jax.lax.select(lane < nl - lr, Ar, Br)


# (class key, leading window axes of the slab) in fixed order
_SHIFT_CLASSES = ("x", "y", "z", "xy", "xz", "yz", "xyz")


def _class_shifts(layout: FoldedLayout) -> dict[str, int]:
    Sx, Sy, Sz = layout.shifts
    return {"x": Sx, "y": Sy, "z": Sz, "xy": Sx + Sy, "xz": Sx + Sz,
            "yz": Sy + Sz, "xyz": Sx + Sy + Sz}


def _seam_ring_shapes(P: int, K: int, nl: int) -> dict[str, tuple]:
    """VMEM scratch shapes for the per-class seam partial rings."""
    return {
        "x": (K, P, P, SUBLANES, nl), "y": (K, P, P, SUBLANES, nl),
        "z": (K, P, P, SUBLANES, nl), "xy": (K, P, SUBLANES, nl),
        "xz": (K, P, SUBLANES, nl), "yz": (K, P, SUBLANES, nl),
        "xyz": (K, SUBLANES, nl),
    }


def _seam_accumulate(rings, y, i, K: int, qr, B: int, nl: int, P: int):
    """The in-kernel seam overlap-add, shared by every fused kernel (it is
    the trickiest modular arithmetic in the module and must exist once):

    1. publish block i's seam partials (the 7 cell-window faces/edges/corner
       that overlap +x/+y/+z neighbour cells) into the VMEM rings;
    2. fold in the partials emitted by blocks i - q - 1 and i - q per shift
       class (exactly the blocks whose +s windows overlap [i*B, (i+1)*B)),
       composing edges/corner into the +x/+y faces first and the faces into
       the main block last — the same order as the v1 XLA seam pass.

    Returns the finished (P, P, P, 8, nl) main block."""
    part = {
        "x": y[P, :P, :P], "y": y[:P, P, :P], "z": y[:P, :P, P],
        "xy": y[P, P, :P], "xz": y[P, :P, P], "yz": y[:P, P, P],
        "xyz": y[P, P, P],
    }
    islot = jax.lax.rem(i, np.int32(K))
    for k in _SHIFT_CLASSES:
        rings[k][islot] = part[k]

    def ring_window(k):
        q, r = qr[k]
        # operands are non-negative, so lax.rem == mod (and, unlike the
        # % operator, lowers without a closed_call)
        j1 = jax.lax.rem(i + np.int32(K - q - 1), np.int32(K))
        j0 = jax.lax.rem(i + np.int32(K - q), np.int32(K))
        return _shift_window_pair(rings[k][j1], rings[k][j0], B - r, nl)

    a_x, a_y, a_z = ring_window("x"), ring_window("y"), ring_window("z")
    a_xy, a_xz = ring_window("xy"), ring_window("xz")
    a_yz, a_xyz = ring_window("yz"), ring_window("xyz")
    cat = jnp.concatenate
    a_xy = cat([(a_xy[0] + a_xyz)[None], a_xy[1:]], axis=0)
    a_x = cat([(a_x[0] + a_xy)[None], a_x[1:]], axis=0)
    a_x = cat([(a_x[:, 0] + a_xz)[:, None], a_x[:, 1:]], axis=1)
    a_y = cat([(a_y[:, 0] + a_yz)[:, None], a_y[:, 1:]], axis=1)
    m = y[:P, :P, :P]
    m = cat([(m[0] + a_x)[None], m[1:]], axis=0)
    m = cat([(m[:, 0] + a_y)[:, None], m[:, 1:]], axis=1)
    m = cat([(m[:, :, 0] + a_z)[:, :, None], m[:, :, 1:]], axis=2)
    return m


def _make_folded_fused_kernel(P: int, nl: int, B: int, K: int,
                              is_identity: bool,
                              phi0: np.ndarray, dphi1: np.ndarray,
                              qr: dict[str, tuple[int, int]],
                              offsets: tuple[int, ...],
                              geom_tables=None):
    corner_mode = geom_tables is not None
    # Per shift class: which window-plane of the (P, P, P, 8, nl) view cube
    # holds the slab (a vreg-indexed slice — free register naming).
    plane = {
        "x": lambda a: a[0], "y": lambda a: a[:, 0], "z": lambda a: a[:, :, 0],
        "xy": lambda a: a[0, 0], "xz": lambda a: a[0, :, 0],
        "yz": lambda a: a[:, 0, 0], "xyz": lambda a: a[0, 0, 0],
    }

    def kernel(*refs):
        nv = len(offsets)
        views = {off: refs[vi] for vi, off in enumerate(offsets)}
        bc_ref = refs[nv]
        ngeom = 2 if corner_mode else 1
        geom_refs = refs[nv + 1:nv + 1 + ngeom]
        kappa_ref = refs[nv + 1 + ngeom]
        out_ref = refs[nv + 2 + ngeom]
        rings = {k: refs[nv + 3 + ngeom + ci]
                 for ci, k in enumerate(_SHIFT_CLASSES)}

        i = pl.program_id(0)

        @pl.when(i == 0)
        def _zero_rings():
            for k in _SHIFT_CLASSES:
                rings[k][...] = jnp.zeros_like(rings[k])

        # each view block (1, P^3, B) -> (P, P, P, 8, nl): leading-axis
        # split plus the native (B,) -> (8, nl) lane relayout
        v4 = {off: _r8(ref[0], nl).reshape(P, P, P, SUBLANES, nl)
              for off, ref in views.items()}
        u0 = v4[0]
        win = {
            k: _shift_window_pair(
                plane[k](v4[qr[k][0]]), plane[k](v4[qr[k][0] + 1]),
                qr[k][1], nl,
            )
            for k in _SHIFT_CLASSES
        }
        u = _assemble_window(
            u0, win["x"], win["y"], win["z"],
            win["xy"], win["xz"], win["yz"], win["xyz"],
        )
        if corner_mode:
            y = corner_apply(u, geom_refs[0][0], geom_refs[1][0],
                             kappa_ref[0, 0], phi0, dphi1, *geom_tables,
                             is_identity)
        else:
            y = sumfact_window_apply(u, geom_refs[0][0], kappa_ref[0, 0],
                                     phi0, dphi1, is_identity)
        m = _seam_accumulate(rings, y, i, K, qr, B, nl, P)
        # Dirichlet pass-through in-register (reference
        # laplacian_gpu.hpp:163-169): bc is a streamed 0/1 mask in the
        # vector dtype; select m -> own input on bc rows. Doing this here
        # (instead of a jnp.where around the pallas_call) saves two full
        # elementwise HBM passes per apply.
        bcb = _r8(bc_ref[0], nl).reshape(P, P, P, SUBLANES, nl)
        m = m + bcb * (u0 - m)
        out_ref[0] = _rb(m).reshape(P * P * P, B)

    return kernel


def folded_cell_apply_fused(
    xm: jnp.ndarray,  # (nb, P^3, B) folded vector
    bcf: jnp.ndarray,  # (nb, P^3, B) 0/1 Dirichlet mask, vector dtype
    geom,  # blocked G | (corners_b, mask_b)
    kappa: jnp.ndarray,
    layout: FoldedLayout,
    phi0: np.ndarray,
    dphi1: np.ndarray,
    is_identity: bool,
    interpret: bool | None = None,
    geom_tables: tuple[np.ndarray, np.ndarray] | None = None,
) -> jnp.ndarray:
    """Fused single-pass operator apply (see module comment above).

    Computes the cell-contribution sum of folded_cell_apply AND the
    Dirichlet row pass-through in one kernel: output rows with bcf == 1
    carry the *input* value of xm. Full operator semantics (y_bc = x_bc,
    interior contributions exclude bc dofs) additionally require xm to be
    zero on bc rows — which CG vectors satisfy by construction when the RHS
    has homogeneous bc rows; general callers pre-mask (see
    FoldedLaplacian.apply)."""
    P = layout.degree
    nq = phi0.shape[0]
    nl, B, nb = layout.nl, layout.block, layout.nblocks
    dtype = xm.dtype
    shifts = _class_shifts(layout)
    qr = {k: divmod(s, B) for k, s in shifts.items()}
    K = max(q for q, _ in qr.values()) + 2
    # distinct block offsets whose (P^3, B) views the kernel needs: each
    # class reads from offsets q and q + 1 (0 is the main block itself)
    offsets = tuple(sorted(
        {0} | {q for q, _ in qr.values()} | {q + 1 for q, _ in qr.values()}
    ))

    def clampmap(q):
        # np.int32 literals: under x64 a Python int would promote to int64,
        # which lax.min rejects against the int32 grid index
        return lambda i: (
            jax.lax.min(i + np.int32(q), np.int32(nb - 1)), 0, 0
        )

    # One full-block view of xm per distinct offset (clamped; data read past
    # the real array only ever feeds ghost/pad-cell windows whose geometry
    # mask is zero).
    in_specs = [
        pl.BlockSpec((1, P * P * P, B), clampmap(q), memory_space=pltpu.VMEM)
        for q in offsets
    ]
    operands = [xm for _ in offsets]
    # streamed Dirichlet mask, own block only
    in_specs.append(pl.BlockSpec((1, P * P * P, B), lambda i: (i, 0, 0),
                                 memory_space=pltpu.VMEM))
    operands.append(bcf)

    if geom_tables is None:
        operands.append(geom)
        in_specs.append(pl.BlockSpec(
            (1, 6, nq, nq, nq, SUBLANES, nl),
            lambda i: (i, 0, 0, 0, 0, 0, 0), memory_space=pltpu.VMEM,
        ))
    else:
        corners_b, mask_b = geom
        operands += [corners_b, mask_b]
        in_specs += [
            pl.BlockSpec((1, 3, 2, 2, 2, SUBLANES, nl),
                         lambda i: (i, 0, 0, 0, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, SUBLANES, nl), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ]
    operands.append(kappa.reshape(1, 1).astype(dtype))
    in_specs.append(pl.BlockSpec((1, 1), lambda i: (0, 0),
                                 memory_space=pltpu.SMEM))

    ring_shapes = _seam_ring_shapes(P, K, nl)
    kernel = _make_folded_fused_kernel(
        P, nl, B, K, is_identity,
        np.asarray(phi0, np.float64), np.asarray(dphi1, np.float64),
        qr, offsets, geom_tables=geom_tables,
    )
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, P * P * P, B), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(xm.shape, dtype),
        scratch_shapes=[pltpu.VMEM(ring_shapes[k], dtype)
                        for k in _SHIFT_CLASSES],
        interpret=_use_interpret() if interpret is None else interpret,
    )(*operands)


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------

@partial(
    jax.tree_util.register_dataclass,
    data_fields=["G", "corners", "cmask", "bc_mask", "kappa"],
    meta_fields=["n", "degree", "nl", "is_identity", "phi0_c", "dphi1_c",
                 "pts_c", "wts_c"],
)
@dataclass(frozen=True)
class FoldedLaplacian:
    """Matrix-free Laplacian on folded vectors (the TPU fast path).

    Geometry is carried either precomputed (G set, corners/cmask None) or as
    blocked cell corners (corner mode: G None) that the kernel turns into G
    on the fly — the default, since the kernel is HBM-bound and corners are
    ~30x less traffic than G at Q3."""

    G: jnp.ndarray | None  # (nblocks, 6, nq, nq, nq, 8, nl) or None
    corners: jnp.ndarray | None  # (nblocks, 3, 2, 2, 2, 8, nl) or None
    cmask: jnp.ndarray | None  # (nblocks, 8, nl) or None
    bc_mask: jnp.ndarray  # (nb, P^3, B) 0/1 Dirichlet marker, vector dtype
    kappa: jnp.ndarray
    n: tuple[int, int, int]
    degree: int
    nl: int
    is_identity: bool
    phi0_c: tuple = ()
    dphi1_c: tuple = ()
    pts_c: tuple = ()
    wts_c: tuple = ()

    @property
    def layout(self) -> FoldedLayout:
        return FoldedLayout(n=self.n, degree=self.degree, nl=self.nl)

    @property
    def geom(self):
        if self.G is not None:
            return self.G
        return (self.corners, self.cmask)

    @property
    def geom_tables(self) -> tuple[np.ndarray, np.ndarray] | None:
        if self.G is not None:
            return None
        return (np.asarray(self.pts_c), np.asarray(self.wts_c))

    def _fused(self, x: jnp.ndarray) -> jnp.ndarray:
        return folded_cell_apply_fused(
            x, self.bc_mask, self.geom, self.kappa, self.layout,
            np.asarray(self.phi0_c, np.float64),
            np.asarray(self.dphi1_c, np.float64),
            self.is_identity,
            geom_tables=self.geom_tables,
        )

    def apply(self, x: jnp.ndarray) -> jnp.ndarray:
        """y = A @ x on folded vectors, Dirichlet rows pass through
        (general x: bc rows of x are excluded from interior contributions
        by pre-masking, then restored by the in-kernel pass-through +
        final correction)."""
        bc = self.bc_mask
        xm = x * (1 - bc)
        y = self._fused(xm)
        # kernel pass-through carried xm's bc rows (zeros); restore x's
        return y + bc * x

    def apply_cg(self, x: jnp.ndarray) -> jnp.ndarray:
        """Fast-path apply for CG iterations: assumes x is zero on Dirichlet
        rows (true for every CG vector when the RHS has homogeneous bc rows
        — r, p, x all inherit it). Skips both elementwise masking passes;
        the in-kernel pass-through keeps bc rows at zero."""
        return self._fused(x)


def auto_geom(layout: FoldedLayout, nq: int, dtype) -> str:
    """geom='auto' policy, shared by the single-chip and distributed
    builders: precomputed G is the faster apply (the corner path trades
    ~2x FLOPs for ~30x less geometry traffic, and the kernel is compute-
    bound when G streams from HBM at full bandwidth) — but G costs 6*nq^3
    values/cell of HBM. Use it while it fits comfortably (<= 6 GB for the
    local layout), else corner mode, which scales to the same problem
    sizes as the uniform fast path."""
    g_bytes = layout.lv * 6 * nq ** 3 * np.dtype(dtype).itemsize
    return "g" if g_bytes <= 6e9 else "corner"


def check_tpu_lane_support(layout: FoldedLayout, degree: int,
                           qmode: int) -> None:
    """Ops-layer guard (the kron/perturbed guard's sibling), shared by the
    single-chip and distributed builders: when the per-cell VMEM working
    set forces pick_lanes below a full 128-lane block (degree 5 qmode 1
    and up; degree 4 qmode 1 in G-streaming mode), the kernels' narrow
    (..., 8, nl<128) relayout is unsupported by Mosaic and the compile
    dies with an opaque shape-cast error. resolve_backend's auto mode
    routes these to 'xla'; this catches explicit --backend pallas
    requests, including explicitly-passed small nl. (CPU interpret-mode
    tests run all degrees — the backend check excludes them.)"""
    import jax

    if layout.nl < 128 and jax.default_backend() == "tpu":
        raise ValueError(
            f"the folded Pallas path needs full 128-lane blocks on TPU; "
            f"degree {degree} qmode {qmode} would need nl={layout.nl} — "
            f"use the xla backend for this configuration"
        )


def pallas_plan(degree: int, nq: int, itemsize: int = 4):
    """(supported, forced_geom, scoped_vmem_kib) for the TPU folded
    Pallas path: full 128-lane blocks with G streaming when it fits;
    corner mode's smaller VMEM footprint rescues degree 4 qmode 1; its
    plane-streamed form (pallas_laplacian.
    sumfact_window_apply_corner_streamed — O(nq^2) live geometry)
    extends that to degrees 5-6 qmode 1 under a raised per-compile
    scoped-VMEM limit (scoped_vmem_kib, passed to compile_lowered — the
    streamed kernels measure 19-23 MB against the 16 MB default);
    otherwise unsupported (the driver routes to 'xla'). Single policy
    shared by resolve_backend and the builders (via
    resolve_pallas_geom)."""
    from .pallas_laplacian import (
        STREAMED_SCOPED_KIB,
        corner_lanes_ok,
        corner_streamed_lanes_ok,
        pick_lanes,
    )

    if pick_lanes(degree + 1, nq, itemsize) == 128:
        return True, None, None
    if corner_lanes_ok(degree + 1, nq, itemsize):
        return True, "corner", None
    if corner_streamed_lanes_ok(degree + 1, nq, itemsize):
        return True, "corner", STREAMED_SCOPED_KIB
    return False, None, None


def pallas_geom_constraint(degree: int, nq: int, itemsize: int = 4):
    """(supported, forced_geom) — pallas_plan minus the compile option
    (kept for callers that only route/build)."""
    supported, forced, _ = pallas_plan(degree, nq, itemsize)
    return supported, forced


def resolve_pallas_geom(degree: int, nq: int, itemsize: int,
                        geom: str, nl: int | None):
    """Apply the forced-corner lane policy to a builder's (geom, nl)
    request — the one place the override lives, shared by the single-chip
    and distributed builders. Deliberately platform-agnostic: CPU
    interpret-mode builds take the same geom/nl the TPU compile would, so
    the test suite exercises exactly the kernels TPU runs (an explicit
    geom='g' request keeps the G-mode lane pick and hits the TPU lane
    guard instead)."""
    if nl is None and geom != "g":
        _, forced = pallas_geom_constraint(degree, nq, itemsize)
        if forced is not None:
            return forced, 128
    return geom, nl


_BUILD_CHUNK_BLOCKS = 64  # cells per geometry-build chunk = 64 * block


def ghost_corner_arrays(
    layout: FoldedLayout, cell_corners: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side c-space geometry inputs: (corners_cs (Lv, 2,2,2,3),
    mask_cs (Lv,)). Ghost/pad cells get unit-cube corners — an invertible
    Jacobian, so the geometry math stays finite — and a zero mask that then
    zeroes their G rows (the self-masking that replaces all ghost bounds
    logic). The single source of the ghost-cell convention, shared by the
    single-device and distributed builders."""
    unit = np.zeros((2, 2, 2, 3))
    g = np.arange(2, dtype=np.float64)
    unit[..., 0], unit[..., 1], unit[..., 2] = (
        g[:, None, None], g[None, :, None], g[None, None, :],
    )
    corners_cs = np.broadcast_to(unit, (layout.lv, 2, 2, 2, 3)).copy()
    mask_cs = np.zeros(layout.lv)
    idx = real_cell_flat_indices(layout)
    corners_cs[idx] = cell_corners.reshape(-1, 2, 2, 2, 3)
    mask_cs[idx] = 1.0
    return corners_cs, mask_cs


def blocked_corners(
    corners_cs: np.ndarray, mask_cs: np.ndarray, layout: FoldedLayout
) -> tuple[np.ndarray, np.ndarray]:
    """c-space corner/mask arrays (from ghost_corner_arrays) -> the blocked
    kernel operands of corner mode:

      (Lv, 2, 2, 2, 3), (Lv,) -> (nb, 3, 2, 2, 2, 8, nl), (nb, 8, nl)

    using the same flat-c <-> (block, sublane, lane) mapping as blocked_G
    (c = b*B + s*nl + l, see _r8)."""
    nb, nl = layout.nblocks, layout.nl
    c = corners_cs.reshape(nb, SUBLANES, nl, 2, 2, 2, 3)
    c = c.transpose(0, 6, 3, 4, 5, 1, 2)
    m = mask_cs.reshape(nb, SUBLANES, nl)
    return np.ascontiguousarray(c), m


def chunk_blocked_G(corners, mask, layout: FoldedLayout, t: OperatorTables,
                    nbc: int) -> jnp.ndarray:
    """Traced: geometry for one chunk of nbc blocks, in blocked layout
    (nbc, 6, nq, nq, nq, 8, nl). Shared by both builders so the blocking
    transform exists exactly once."""
    from .geometry import geometry_factors_jax

    nq = t.nq
    Gc, _ = geometry_factors_jax(corners, t.pts1d, t.wts1d)
    Gc = Gc * mask[:, None, None, None, None]
    Gc = Gc.reshape(nbc, SUBLANES, layout.nl, 6, nq, nq, nq)
    return Gc.transpose(0, 3, 4, 5, 6, 1, 2)


def blocked_G_traced(corners_cs, mask_cs, layout: FoldedLayout,
                     t: OperatorTables) -> jnp.ndarray:
    """Traced chunked build (for use inside an enclosing jit/shard_map):
    the dynamic-update-slice chain forces sequential chunk evaluation, so
    XLA's liveness analysis reuses the chunk temporaries instead of holding
    ~3x final-G live at once."""
    nq = t.nq
    nb, B = layout.nblocks, layout.block
    ch = min(_BUILD_CHUNK_BLOCKS, nb)
    acc = jnp.zeros(
        (nb, 6, nq, nq, nq, SUBLANES, layout.nl), dtype=corners_cs.dtype
    )
    for b0 in range(0, nb, ch):
        nbc = min(ch, nb - b0)
        c0, c1 = b0 * B, (b0 + nbc) * B
        Gc = chunk_blocked_G(corners_cs[c0:c1], mask_cs[c0:c1], layout, t, nbc)
        acc = jax.lax.dynamic_update_slice(acc, Gc, (b0, 0, 0, 0, 0, 0, 0))
    return acc


def _build_G_chunked(corners_cs: np.ndarray, mask_cs: np.ndarray,
                     layout: FoldedLayout, t: OperatorTables, dtype) -> jnp.ndarray:
    """Device-side geometry build in chunks with a donated accumulator, so
    peak HBM is final-G + one chunk (a monolithic build needs ~3x final-G,
    which is the capacity limit at benchmark sizes)."""
    nb, B = layout.nblocks, layout.block
    ch = min(_BUILD_CHUNK_BLOCKS, nb)

    @partial(jax.jit, donate_argnums=0, static_argnames="nbc")
    def fill(acc, corners, mask, start, nbc):
        Gc = chunk_blocked_G(corners, mask, layout, t, nbc)
        return jax.lax.dynamic_update_slice(
            acc, Gc, (start, 0, 0, 0, 0, 0, 0)
        )

    nq = t.nq
    acc = jnp.zeros((nb, 6, nq, nq, nq, SUBLANES, layout.nl), dtype=dtype)
    for b0 in range(0, nb, ch):
        nbc = min(ch, nb - b0)
        c0, c1 = b0 * B, (b0 + nbc) * B
        acc = fill(
            acc,
            jnp.asarray(corners_cs[c0:c1], dtype=dtype),
            jnp.asarray(mask_cs[c0:c1], dtype=dtype),
            b0,
            nbc=nbc,
        )
    return acc


def build_folded_laplacian(
    mesh: BoxMesh,
    degree: int,
    qmode: int,
    rule: str = "gll",
    kappa: float = 2.0,
    dtype=jnp.float32,
    tables: OperatorTables | None = None,
    nl: int | None = None,
    geom: str = "auto",
) -> FoldedLaplacian:
    """Build the folded-layout operator.

    geom='g' precomputes the geometry tensor on device in chunks (fastest
    apply while G fits HBM); geom='corner' ships the blocked cell corners
    (24 floats/cell) and computes G in-kernel — ~30x less HBM capacity, so
    perturbed-geometry problems scale to the same sizes as the uniform fast
    path; geom='auto' (default) picks by G's footprint. Ghost/pad cells get
    unit-cube corners so the Jacobian stays invertible, then a zero mask."""
    from .laplacian import freeze_table

    if geom not in ("auto", "corner", "g"):
        raise ValueError(f"unknown geom mode {geom!r}")
    import jax

    t = tables or build_operator_tables(degree, qmode, rule)
    itemsize = np.dtype(dtype).itemsize
    geom, nl = resolve_pallas_geom(degree, t.nq, itemsize, geom, nl)
    layout = make_layout(mesh.n, degree, t.nq, itemsize, nl=nl)
    check_tpu_lane_support(layout, degree, qmode)
    if geom == "auto":
        geom = auto_geom(layout, t.nq, dtype)
    corners_cs, mask_cs = ghost_corner_arrays(layout, mesh.cell_corners)
    G = corners_b = cmask_b = None
    if geom == "corner":
        cb, mb = blocked_corners(corners_cs, mask_cs, layout)
        corners_b = jnp.asarray(cb, dtype=dtype)
        cmask_b = jnp.asarray(mb, dtype=dtype)
    else:
        G = _build_G_chunked(corners_cs, mask_cs, layout, t, dtype)
    bc = fold_vector(
        np.asarray(boundary_dof_marker(mesh.n, degree), np.float64), layout
    )
    return FoldedLaplacian(
        G=G,
        corners=corners_b,
        cmask=cmask_b,
        bc_mask=jnp.asarray(bc, dtype=dtype),
        kappa=jnp.asarray(kappa, dtype=dtype),
        n=mesh.n,
        degree=degree,
        nl=layout.nl,
        is_identity=t.is_identity,
        phi0_c=freeze_table(t.phi0),
        dphi1_c=freeze_table(t.dphi1),
        pts_c=tuple(float(v) for v in t.pts1d),
        wts_c=tuple(float(v) for v in t.wts1d),
    )
