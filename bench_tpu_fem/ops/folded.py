"""Folded vector layout: the TPU-native dof storage for the hot path.

The grid layout (NX, NY, NZ) forces every operator apply through two large
strided transposes (gather to per-cell layout, overlap-add back) that XLA
executes far below DMA speed. This module instead stores a dof vector the
way the kernel consumes it:

    X[i, j, k, c]   i, j, k in [0, P)   c = (cx*npy + cy)*npz + cz

where (cx, cy, cz) ranges over the real cells *plus one ghost column per
axis* (np_a = n_a + 1). Grid point (cx*P+i, ...) maps bijectively: the final
boundary plane of each axis lives in the ghost column's i=0 slot; the
remaining ghost slots are structural zeros. The payoffs:

- a cell's (P+1)^3 window is its own (P,P,P) block plus 7 slabs at
  *constant* flat-c shifts (+Sz=1, +Sy=npz, +Sx=npy*npz and their sums) —
  so "gather" is 7 contiguous-slice reads, and "scatter-add" (the
  reference's atomicAdd, laplacian_gpu.hpp:425) is 7 shifted adds;
- ghost cells get zero geometry rows, so they mask themselves: no bounds
  logic anywhere in the kernel;
- CG vector algebra runs unchanged on the flat arrays (structural zeros are
  preserved by every linear operation).

The kernel (standard pallas_call, fully pipelined BlockSpecs) processes
B = 8*NL cells per grid step: window slabs are DMA'd as (..., B) lane-major
blocks, relaid in-register to the (..., 8, NL) vreg cross-section of
ops.pallas_laplacian, contracted with the compile-time basis tables, and
written back as one main block plus 7 seam outputs.

Cites: stiffness_operator_gpu /root/reference/src/laplacian_gpu.hpp:91-426
(the per-cell math), MatFreeLaplacianGPU::apply laplacian.hpp:281-403
(operator protocol, Dirichlet pass-through laplacian_gpu.hpp:163-169).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..elements.tables import OperatorTables, build_operator_tables
from ..mesh.box import BoxMesh
from ..mesh.dofmap import boundary_dof_marker
from .pallas_laplacian import (
    SUBLANES,
    _use_interpret,
    pick_lanes,
    sumfact_window_apply,
)


# ---------------------------------------------------------------------------
# Layout geometry
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FoldedLayout:
    """Shape bookkeeping for the folded layout of one box mesh."""

    n: tuple[int, int, int]  # real cells per axis
    degree: int
    nl: int  # lanes per kernel block

    @property
    def np3(self) -> tuple[int, int, int]:
        return (self.n[0] + 1, self.n[1] + 1, self.n[2] + 1)

    @property
    def shifts(self) -> tuple[int, int, int]:
        """Flat-c shift to the +x/+y/+z neighbour cell."""
        npx, npy, npz = self.np3
        return (npy * npz, npz, 1)

    @property
    def cg(self) -> int:
        npx, npy, npz = self.np3
        return npx * npy * npz

    @property
    def block(self) -> int:
        return SUBLANES * self.nl

    @property
    def nblocks(self) -> int:
        return -(-self.cg // self.block)

    @property
    def lv(self) -> int:
        """Padded flat-c vector length (whole number of kernel blocks)."""
        return self.nblocks * self.block

    @property
    def vec_shape(self) -> tuple[int, int, int, int]:
        P = self.degree
        return (P, P, P, self.lv)


def make_layout(n: tuple[int, int, int], degree: int, nq: int,
                itemsize: int = 4, nl: int | None = None) -> FoldedLayout:
    """nl override exists for tests (small nl forces multi-block grids on
    meshes that fit interpret mode)."""
    return FoldedLayout(n=tuple(n), degree=degree,
                        nl=nl or pick_lanes(degree + 1, nq, itemsize))


def _grid_to_cell_indices(layout: FoldedLayout):
    """Per grid point: (i, j, k, c) indices into the folded vector."""
    P = layout.degree
    nx, ny, nz = layout.n
    npx, npy, npz = layout.np3
    X = np.arange(nx * P + 1)
    Y = np.arange(ny * P + 1)
    Z = np.arange(nz * P + 1)
    cx, i = X // P, X % P
    cy, j = Y // P, Y % P
    cz, k = Z // P, Z % P
    c = (
        (cx[:, None, None] * npy + cy[None, :, None]) * npz
        + cz[None, None, :]
    )
    ii = np.broadcast_to(i[:, None, None], c.shape)
    jj = np.broadcast_to(j[None, :, None], c.shape)
    kk = np.broadcast_to(k[None, None, :], c.shape)
    return ii, jj, kk, c


def fold_vector(grid: np.ndarray, layout: FoldedLayout) -> np.ndarray:
    """(NX, NY, NZ) grid -> folded (P, P, P, Lv); structural slots zero."""
    ii, jj, kk, c = _grid_to_cell_indices(layout)
    out = np.zeros(layout.vec_shape, dtype=grid.dtype)
    out[ii, jj, kk, c] = grid
    return out


def unfold_vector(folded: np.ndarray, layout: FoldedLayout) -> np.ndarray:
    """Folded (P, P, P, Lv) -> (NX, NY, NZ) grid (inverse of fold_vector)."""
    ii, jj, kk, c = _grid_to_cell_indices(layout)
    return np.asarray(folded)[ii, jj, kk, c]


def real_cell_flat_indices(layout: FoldedLayout) -> np.ndarray:
    """Flat-c index of each real cell, in (cx, cy, cz) row-major order —
    the cell order of mesh.cell_corners and the geometry tensor."""
    nx, ny, nz = layout.n
    npx, npy, npz = layout.np3
    cx, cy, cz = np.meshgrid(
        np.arange(nx), np.arange(ny), np.arange(nz), indexing="ij"
    )
    return ((cx * npy + cy) * npz + cz).ravel()


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------

def _r8(a: jnp.ndarray, nl: int) -> jnp.ndarray:
    """(..., B) lane-major -> (..., 8, nl) vreg cross-section (in-register
    relayout; cheap next to the contraction work)."""
    return a.reshape(*a.shape[:-1], SUBLANES, nl)


def _rb(a: jnp.ndarray) -> jnp.ndarray:
    """Inverse of _r8."""
    return a.reshape(*a.shape[:-2], a.shape[-2] * a.shape[-1])


def _assemble_window(c000, cx, cy, cz, cxy, cxz, cyz, cxyz):
    """Build the (nd, nd, nd, 8, nl) cell window cube from the 8 shift-class
    slabs (each already in vreg layout). Pure concatenation on vreg-indexed
    axes — register naming, no data movement."""
    A = jnp.concatenate([c000, cz[:, :, None]], axis=2)  # (P, P, nd, ...)
    By = jnp.concatenate([cy, cyz[:, None]], axis=1)  # (P, nd, ...)
    A = jnp.concatenate([A, By[:, None]], axis=1)  # (P, nd, nd, ...)
    Bx = jnp.concatenate([cx, cxz[:, None]], axis=1)  # (P, nd, ...)
    Cx = jnp.concatenate([cxy, cxyz[None]], axis=0)  # (nd, ...)
    Bx = jnp.concatenate([Bx, Cx[None]], axis=0)  # (nd, nd, ...)
    return jnp.concatenate([A, Bx[None]], axis=0)  # (nd, nd, nd, ...)


def _make_folded_kernel(P: int, nl: int, is_identity: bool,
                        phi0: np.ndarray, dphi1: np.ndarray):
    def kernel(u000_ref, ux_ref, uy_ref, uz_ref, uxy_ref, uxz_ref, uyz_ref,
               uxyz_ref, g_ref, kappa_ref,
               y_ref, yx_ref, yy_ref, yz_ref, yxy_ref, yxz_ref, yyz_ref,
               yxyz_ref):
        r8 = lambda r: _r8(r[...], nl)  # noqa: E731
        u = _assemble_window(
            r8(u000_ref), r8(ux_ref), r8(uy_ref), r8(uz_ref),
            r8(uxy_ref), r8(uxz_ref), r8(uyz_ref), r8(uxyz_ref),
        )
        y = sumfact_window_apply(
            u, g_ref[0], kappa_ref[0, 0], phi0, dphi1, is_identity
        )

        y_ref[...] = _rb(y[:P, :P, :P])
        yx_ref[...] = _rb(y[P, :P, :P])
        yy_ref[...] = _rb(y[:P, P, :P])
        yz_ref[...] = _rb(y[:P, :P, P])
        yxy_ref[...] = _rb(y[P, P, :P])
        yxz_ref[...] = _rb(y[P, :P, P])
        yyz_ref[...] = _rb(y[:P, P, P])
        yxyz_ref[...] = _rb(y[P, P, P])

    return kernel


def folded_cell_apply(
    xm: jnp.ndarray,  # (P, P, P, Lv) masked folded vector
    G: jnp.ndarray,  # (nblocks, 6, nq, nq, nq, 8, nl) c-space blocked
    kappa: jnp.ndarray,
    layout: FoldedLayout,
    phi0: np.ndarray,
    dphi1: np.ndarray,
    is_identity: bool,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """One operator contribution pass: returns the un-bc'd result vector."""
    P = layout.degree
    nq = phi0.shape[0]
    nl, B, nb, Lv = layout.nl, layout.block, layout.nblocks, layout.lv
    Sx, Sy, Sz = layout.shifts
    S7 = Sx + Sy + Sz
    dtype = xm.dtype

    xp = jnp.pad(xm, [(0, 0)] * 3 + [(0, S7)])
    ux = jax.lax.slice(xp[0], (0, 0, Sx), (P, P, Sx + Lv))
    uy = jax.lax.slice(xp[:, 0], (0, 0, Sy), (P, P, Sy + Lv))
    uz = jax.lax.slice(xp[:, :, 0], (0, 0, Sz), (P, P, Sz + Lv))
    uxy = jax.lax.slice(xp[0, 0], (0, Sx + Sy), (P, Sx + Sy + Lv))
    uxz = jax.lax.slice(xp[0, :, 0], (0, Sx + Sz), (P, Sx + Sz + Lv))
    uyz = jax.lax.slice(xp[:, 0, 0], (0, Sy + Sz), (P, Sy + Sz + Lv))
    uxyz = jax.lax.slice(xp[0, 0, 0], (S7,), (S7 + Lv,))

    spec = lambda *lead: pl.BlockSpec(  # noqa: E731
        (*lead, B), lambda i, _n=len(lead): (0,) * _n + (i,),
        memory_space=pltpu.VMEM,
    )
    kernel = _make_folded_kernel(
        P, nl, is_identity,
        np.asarray(phi0, np.float64), np.asarray(dphi1, np.float64),
    )
    outs = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            spec(P, P, P), spec(P, P), spec(P, P), spec(P, P),
            spec(P), spec(P), spec(P), spec(),
            pl.BlockSpec(
                (1, 6, nq, nq, nq, SUBLANES, nl),
                lambda i: (i, 0, 0, 0, 0, 0, 0), memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
        ],
        out_specs=[
            spec(P, P, P), spec(P, P), spec(P, P), spec(P, P),
            spec(P), spec(P), spec(P), spec(),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((P, P, P, Lv), dtype),
            jax.ShapeDtypeStruct((P, P, Lv), dtype),
            jax.ShapeDtypeStruct((P, P, Lv), dtype),
            jax.ShapeDtypeStruct((P, P, Lv), dtype),
            jax.ShapeDtypeStruct((P, Lv), dtype),
            jax.ShapeDtypeStruct((P, Lv), dtype),
            jax.ShapeDtypeStruct((P, Lv), dtype),
            jax.ShapeDtypeStruct((Lv,), dtype),
        ],
        interpret=_use_interpret() if interpret is None else interpret,
    )(xm, ux, uy, uz, uxy, uxz, uyz, uxyz, G,
      kappa.reshape(1, 1).astype(dtype))

    Y, Yx, Yy, Yz, Yxy, Yxz, Yyz, Yxyz = outs
    # Seam accumulation: the i/j/k = P faces of each cell window coincide
    # with the i/j/k = 0 slots of the +x/+y/+z neighbour (the structured
    # replacement for atomicAdd scatter). Everything is expressed as
    # zero-pads + adds — XLA fuses those into one elementwise pass, where
    # the equivalent .at[...].add chain costs a full-array copy per seam.

    def shift(a, S):
        """a[..., c] -> contribution at c + S (front zero-pad)."""
        return jnp.pad(a[..., : Lv - S], [(0, 0)] * (a.ndim - 1) + [(S, 0)])

    def lift(a, axis):
        """Insert a size-P axis holding `a` at index 0, zeros elsewhere."""
        pads = [(0, 0)] * (a.ndim + 1)
        pads[axis] = (0, P - 1)
        return jnp.pad(jnp.expand_dims(a, axis), pads)

    # Fold edge/corner contributions into the face slabs first (small
    # arrays), then the three faces into the main block in one fused add.
    Yx = Yx + lift(shift(Yxy, Sy), 0) + lift(shift(Yxz, Sz), 1) \
        + lift(lift(shift(Yxyz, Sy + Sz), 0), 1)
    Yy = Yy + lift(shift(Yyz, Sz), 1)
    return (
        Y
        + lift(shift(Yx, Sx), 0)
        + lift(shift(Yy, Sy), 1)
        + lift(shift(Yz, Sz), 2)
    )


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------

@partial(
    jax.tree_util.register_dataclass,
    data_fields=["G", "bc_mask", "kappa"],
    meta_fields=["n", "degree", "nl", "is_identity", "phi0_c", "dphi1_c"],
)
@dataclass(frozen=True)
class FoldedLaplacian:
    """Matrix-free Laplacian on folded vectors (the TPU fast path)."""

    G: jnp.ndarray  # (nblocks, 6, nq, nq, nq, 8, nl)
    bc_mask: jnp.ndarray  # (P, P, P, Lv) bool Dirichlet marker (folded)
    kappa: jnp.ndarray
    n: tuple[int, int, int]
    degree: int
    nl: int
    is_identity: bool
    phi0_c: tuple = ()
    dphi1_c: tuple = ()

    @property
    def layout(self) -> FoldedLayout:
        return FoldedLayout(n=self.n, degree=self.degree, nl=self.nl)

    def apply(self, x: jnp.ndarray) -> jnp.ndarray:
        """y = A @ x on folded vectors, Dirichlet rows pass through."""
        xm = jnp.where(self.bc_mask, 0, x)
        y = folded_cell_apply(
            xm, self.G, self.kappa, self.layout,
            np.asarray(self.phi0_c, np.float64),
            np.asarray(self.dphi1_c, np.float64),
            self.is_identity,
        )
        return jnp.where(self.bc_mask, x, y)


_BUILD_CHUNK_BLOCKS = 64  # cells per geometry-build chunk = 64 * block


def ghost_corner_arrays(
    layout: FoldedLayout, cell_corners: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Host-side c-space geometry inputs: (corners_cs (Lv, 2,2,2,3),
    mask_cs (Lv,)). Ghost/pad cells get unit-cube corners — an invertible
    Jacobian, so the geometry math stays finite — and a zero mask that then
    zeroes their G rows (the self-masking that replaces all ghost bounds
    logic). The single source of the ghost-cell convention, shared by the
    single-device and distributed builders."""
    unit = np.zeros((2, 2, 2, 3))
    g = np.arange(2, dtype=np.float64)
    unit[..., 0], unit[..., 1], unit[..., 2] = (
        g[:, None, None], g[None, :, None], g[None, None, :],
    )
    corners_cs = np.broadcast_to(unit, (layout.lv, 2, 2, 2, 3)).copy()
    mask_cs = np.zeros(layout.lv)
    idx = real_cell_flat_indices(layout)
    corners_cs[idx] = cell_corners.reshape(-1, 2, 2, 2, 3)
    mask_cs[idx] = 1.0
    return corners_cs, mask_cs


def chunk_blocked_G(corners, mask, layout: FoldedLayout, t: OperatorTables,
                    nbc: int) -> jnp.ndarray:
    """Traced: geometry for one chunk of nbc blocks, in blocked layout
    (nbc, 6, nq, nq, nq, 8, nl). Shared by both builders so the blocking
    transform exists exactly once."""
    from .geometry import geometry_factors_jax

    nq = t.nq
    Gc, _ = geometry_factors_jax(corners, t.pts1d, t.wts1d)
    Gc = Gc * mask[:, None, None, None, None]
    Gc = Gc.reshape(nbc, SUBLANES, layout.nl, 6, nq, nq, nq)
    return Gc.transpose(0, 3, 4, 5, 6, 1, 2)


def blocked_G_traced(corners_cs, mask_cs, layout: FoldedLayout,
                     t: OperatorTables) -> jnp.ndarray:
    """Traced chunked build (for use inside an enclosing jit/shard_map):
    the dynamic-update-slice chain forces sequential chunk evaluation, so
    XLA's liveness analysis reuses the chunk temporaries instead of holding
    ~3x final-G live at once."""
    nq = t.nq
    nb, B = layout.nblocks, layout.block
    ch = min(_BUILD_CHUNK_BLOCKS, nb)
    acc = jnp.zeros(
        (nb, 6, nq, nq, nq, SUBLANES, layout.nl), dtype=corners_cs.dtype
    )
    for b0 in range(0, nb, ch):
        nbc = min(ch, nb - b0)
        c0, c1 = b0 * B, (b0 + nbc) * B
        Gc = chunk_blocked_G(corners_cs[c0:c1], mask_cs[c0:c1], layout, t, nbc)
        acc = jax.lax.dynamic_update_slice(acc, Gc, (b0, 0, 0, 0, 0, 0, 0))
    return acc


def _build_G_chunked(corners_cs: np.ndarray, mask_cs: np.ndarray,
                     layout: FoldedLayout, t: OperatorTables, dtype) -> jnp.ndarray:
    """Device-side geometry build in chunks with a donated accumulator, so
    peak HBM is final-G + one chunk (a monolithic build needs ~3x final-G,
    which is the capacity limit at benchmark sizes)."""
    nb, B = layout.nblocks, layout.block
    ch = min(_BUILD_CHUNK_BLOCKS, nb)

    @partial(jax.jit, donate_argnums=0, static_argnames="nbc")
    def fill(acc, corners, mask, start, nbc):
        Gc = chunk_blocked_G(corners, mask, layout, t, nbc)
        return jax.lax.dynamic_update_slice(
            acc, Gc, (start, 0, 0, 0, 0, 0, 0)
        )

    nq = t.nq
    acc = jnp.zeros((nb, 6, nq, nq, nq, SUBLANES, layout.nl), dtype=dtype)
    for b0 in range(0, nb, ch):
        nbc = min(ch, nb - b0)
        c0, c1 = b0 * B, (b0 + nbc) * B
        acc = fill(
            acc,
            jnp.asarray(corners_cs[c0:c1], dtype=dtype),
            jnp.asarray(mask_cs[c0:c1], dtype=dtype),
            b0,
            nbc=nbc,
        )
    return acc


def build_folded_laplacian(
    mesh: BoxMesh,
    degree: int,
    qmode: int,
    rule: str = "gll",
    kappa: float = 2.0,
    dtype=jnp.float32,
    tables: OperatorTables | None = None,
    nl: int | None = None,
) -> FoldedLaplacian:
    """Build the folded-layout operator (geometry computed on device, in
    chunks over c-space; ghost/pad cells get unit-cube corners so the
    Jacobian stays invertible, then a zero mask)."""
    from .laplacian import freeze_table

    t = tables or build_operator_tables(degree, qmode, rule)
    layout = make_layout(mesh.n, degree, t.nq, np.dtype(dtype).itemsize, nl=nl)
    corners_cs, mask_cs = ghost_corner_arrays(layout, mesh.cell_corners)
    G = _build_G_chunked(corners_cs, mask_cs, layout, t, dtype)
    bc = fold_vector(
        np.asarray(boundary_dof_marker(mesh.n, degree)), layout
    )
    return FoldedLaplacian(
        G=G,
        bc_mask=jnp.asarray(bc),
        kappa=jnp.asarray(kappa, dtype=dtype),
        n=mesh.n,
        degree=degree,
        nl=layout.nl,
        is_identity=t.is_identity,
        phi0_c=freeze_table(t.phi0),
        dphi1_c=freeze_table(t.dphi1),
    )
