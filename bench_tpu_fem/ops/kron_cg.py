"""Fused CG engine for the Kronecker (uniform-mesh) fast path.

The 3-stage kron apply (ops.kron_pallas) plus XLA CG algebra streams ~23
dof-vectors of HBM per iteration: the z/y/x kernels round-trip two full
intermediates (aK/aM between z and y in registers, but t12/tyz through HBM)
and the vector algebra re-reads everything it touches. Measured on a v5e
chip the whole iteration is HBM-bound at ~400 GB/s effective, so streams
are the iteration time. This module restates the iteration the way
ops.folded_cg does for the general path — ONE pallas kernel per iteration
plus one fused XLA update pass:

Kernel (`_kron_cg_call`) — grid over the NX dof planes, sequential:
  - p-UPDATE FUSED: step t ingests r and p_prev planes and forms
    p = beta*p_prev + r in registers (beta rides in SMEM), writing p out —
    the CG direction update costs no separate pass.
  - Z+Y IN REGISTERS: the banded z (lane-shift) and y (sublane-shift)
    contractions for the ingested plane run back-to-back in-kernel; the
    t12/tyz intermediates never touch HBM.
  - X VIA DELAY RING: t12/tyz planes land in VMEM rings of KI = 2P + 2
    slots (the x contraction for output plane i = t - P reads ring rows
    i - P .. i + P); the p plane is read back exactly once at lag P, so
    its ring needs only P + 1 slots. Per-output-row banded coefficients
    streamed as (1, 2P+1) SMEM blocks. Out-of-range rows are killed by the
    zero boundary columns of the banded-diagonal storage
    (ops.kron.banded_diags), as in every kron kernel.
  - DIRICHLET IN-KERNEL: the pass-through blend y = nb*y + (1-nb)*p uses
    masks computed from plane/sublane/lane indices in closed form (the
    uniform box's boundary dofs are exactly the extreme grid planes) — no
    mask stream. Matches laplacian_gpu.hpp:163-169 semantics
    (/root/reference/src/, documentation of intent).
  - DOT FUSED: <p, A p> accumulates in a VMEM scalar across grid steps and
    is emitted once — no re-read of two full vectors for the alpha dot.

The remaining algebra (x += alpha p; r -= alpha y; <r, r>) is one fused
XLA elementwise+reduce pass. Total ~11 dof-vector streams per iteration
instead of ~23.

Same reassociation as ops.folded_cg: the p-update moves to the start of
the next iteration (p1 = r1 + beta*p0), algebraically the reference CG
loop (/root/reference/src/cg.hpp:121-167) with identical per-element
operation order. float32 only (Mosaic has no f64); rtol = 0 benchmark
semantics (exactly nreps iterations, cg.hpp:88-91).

VMEM: the one-kernel form holds 2 rings x KI + one ring x (P+1) full
(NY, NZ_padded) planes. engine_plan escalates through hardware-checked
scoped-VMEM tiers (default limit, then raised 64/96 MiB per-compile
requests — see the tier constants below), carrying the one-kernel form
through 300M dofs at degree 3; beyond ~62 MiB of estimated ring a
two-kernel form takes over, chunking
the y axis so every VMEM object is a (CY, NZ) chunk:

  Kernel ZY (`_zy_chunk_call`): grid (NX, NYB+1). Step (xi, yj) ingests
  y-chunk yj of plane xi (p-update fused), z-contracts it, and pushes
  aK/aM chunks into a 3-slot ring; the y contraction for chunk yj-1 reads
  the concatenated ring (the +-P sublane halo lives in the neighbouring
  chunks). t12/tyz go to HBM once.

  Kernel X (`_x_chunk_call`): grid (NYB, NX+P), xi fastest. The x
  contraction, Dirichlet blend and <p, A p> partials run exactly as in the
  one-kernel form but per y-chunk, with t12/tyz/p streamed in once.

Streams/iteration: one-kernel ~11, two-kernel ~15 (t12/tyz round-trip),
vs ~23 unfused — and the two-kernel form has no size ceiling: every
buffer is O(CY * NZ). `supports_kron_cg_engine` is thus dtype-only; the
internal dispatch picks the form by VMEM estimate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis import budgets as _B
from ..la.cg import fused_cg_solve
from .pallas_laplacian import _use_interpret

# VMEM budget (bytes) under which the one-kernel ring compiles at the
# DEFAULT scoped-VMEM limit (Mosaic's stack limit is 16.0 MB on v5e and
# its allocator lands up to ~1.35x this estimate: the degree-3 12.8 MiB
# estimate is rejected while the degree-6 12.35 MiB one compiles — so
# 11 MiB is the hardware-validated safe line). Estimates between
# VMEM_BUDGET and ONE_KERNEL_SCOPED_MAX still take the one-kernel form,
# but with a raised per-compile scoped limit (engine_plan below). The
# constant lives with every other VMEM budget in analysis.budgets; the
# module-attribute alias is the patch point probes use.
VMEM_BUDGET = _B.KRON_VMEM_BUDGET


def _lane_pad(n: int) -> int:
    return -(-n // 128) * 128


def engine_vmem_bytes(grid_shape: tuple[int, int, int], degree: int) -> int:
    """Estimated kernel VMEM footprint: 2 rings of KI = 2P+2 (NY, NZpad)
    f32 planes (the t12/tyz x-windows) + the P+1-slot p ring (read once
    at lag P) + 4 pipeline-buffered in/out planes (x2 for double
    buffering) + 2 in-register intermediates."""
    _, NY, NZ = grid_shape
    plane = NY * _lane_pad(NZ) * 4
    KI = 2 * degree + 2
    return (2 * KI + degree + 1 + 4 * 2 + 2) * plane


def supports_kron_cg_engine(grid_shape, degree: int, dtype) -> bool:
    """f32 only (Mosaic has no f64). Size no longer gates: the internal
    dispatch switches to the y-chunked two-kernel form when the one-kernel
    ring would blow the VMEM budget."""
    return dtype == jnp.float32


def _pick_cy(NY: int, P: int) -> int:
    """y-chunk rows for the two-kernel form: sublane-aligned, >= P (the
    3-slot ring needs each chunk to cover the +-P halo)."""
    cy = min(-(-NY // 8) * 8, 64)
    return max(cy, -(-P // 8) * 8)


def _z_contract(p2, ckz, cmz, P: int, NZ: int):
    """Banded z (lane-shift) contraction: (K_z p, M_z p) for one slab.
    Coefficient refs hold (2P+1, NZ) banded diagonals; the explicit zero
    pad plus the zero boundary rows of the banded storage make edges
    exact. Shared by both engine forms."""
    pp = jnp.pad(p2, ((0, 0), (P, P)))
    aK = aM = None
    for d in range(2 * P + 1):
        s = pp[:, d:d + NZ]
        k = ckz[d][None, :] * s
        m = cmz[d][None, :] * s
        aK = k if aK is None else aK + k
        aM = m if aM is None else aM + m
    return aK, aM


def _y_contract(aKp, aMp, cky, cmy, rows: int, offset: int = 0):
    """Banded y (sublane-shift) contraction producing `rows` output rows
    from pre-extended operands (aKp/aMp hold rows [offset-P, offset+rows+P)
    relative to the output): (t12, tyz) = (M_y aK + K_y aM, M_y aM).
    Shared by both engine forms (the chunked form passes ring-concatenated
    operands with offset > 0)."""
    t12 = tyz = None
    nb = cky.shape[0]
    for d in range(nb):
        sK = aKp[offset + d:offset + d + rows, :]
        sM = aMp[offset + d:offset + d + rows, :]
        a = cmy[d][:, None] * sK + cky[d][:, None] * sM
        b = cmy[d][:, None] * sM
        t12 = a if t12 is None else t12 + a
        tyz = b if tyz is None else tyz + b
    return t12, tyz


def _zy_contract(p2, ckz, cmz, cky, cmy, P: int, NY: int, NZ: int):
    """Full-plane z then y contractions (one-kernel form)."""
    aK, aM = _z_contract(p2, ckz, cmz, P, NZ)
    aKp = jnp.pad(aK, ((P, P), (0, 0)))
    aMp = jnp.pad(aM, ((P, P), (0, 0)))
    return _y_contract(aKp, aMp, cky, cmy, NY)


def _x_emit_blend(ring_t12, ring_tyz, cx_ref, i, p_i, gy, gz, P: int,
                  KI: int, NX: int, NY: int, NZ: int, mi=None,
                  inter2d=None):
    """Banded x contraction from the delay ring + closed-form Dirichlet
    blend: shared by both engine forms and the distributed engine (gy/gz
    carry the caller's global row/lane indices; virtual-pad rows arrive
    with p_i = 0 and inter = False, so they emit 0). cx_ref row:
    [M-coeffs | K-coeffs], kappa folded in. `mi` overrides the
    interior-in-x indicator when the caller's plane index `i` is not the
    global plane index (the distributed engine streams it per plane);
    `inter2d` overrides the closed-form y/z interior test when local
    row/col indices are not global (the 3D-sharded engine streams the
    cross-section interior mask as a plane)."""
    acc = None
    for d in range(2 * P + 1):
        # source plane i + d - P; + 2*KI keeps lax.rem's argument
        # non-negative for the first planes
        slot = jax.lax.rem(i + np.int32(d - P + 2 * KI), np.int32(KI))
        term = (cx_ref[0, 0, d] * ring_t12[slot]
                + cx_ref[0, 0, 2 * P + 1 + d] * ring_tyz[slot])
        acc = term if acc is None else acc + term
    # Closed-form Dirichlet mask: boundary dofs are exactly the extreme
    # planes of the structured dof grid, per axis.
    if mi is None:
        mi = jnp.logical_and(i > 0, i < np.int32(NX - 1))
    if inter2d is None:
        inter2d = jnp.logical_and(
            jnp.logical_and(gy > 0, gy < np.int32(NY - 1)),
            jnp.logical_and(gz > 0, gz < np.int32(NZ - 1)),
        )
    inter = jnp.logical_and(mi, inter2d)
    # raw lax.select (not jnp.where): jnp wrappers trace to closed_call,
    # which the Mosaic kernel-lowering path rejects
    return jax.lax.select(inter, acc, p_i)


def _make_kron_cg_kernel(P: int, NX: int, NY: int, NZ: int, KI: int,
                         update_p: bool, halo: int = 0,
                         ext2d: bool = False):
    """One-kernel delay-ring CG iteration. `halo = 0` is the single-chip
    form over the full NX-plane grid. `halo = P` is the distributed form
    (dist.kron_cg): NX is the shard's local plane count, the input slab is
    extended by P exchanged halo planes per side, ingest sweeps the
    NX + 2P extended planes and emit covers exactly the NX local planes —
    every output row globally exact, no boundary epilogue. In that form
    the per-plane [interior-in-x, dot-ownership] pair streams via SMEM
    (aux_ref) since the local plane index is not the global one, and the
    emit lag is fully absorbed by the trailing halo planes (extra steps
    would clamp-revisit the final output block and overwrite it with
    halo-plane garbage), so the grid is exactly NX + 2*halo steps when
    halo > 0 and NX + P when halo == 0.

    `ext2d` (3D-sharded meshes, with halo = P): the input planes are
    halo-extended in y/z as well ((NY+2P, NZ+2P), where NY/NZ are the
    LOCAL cross-section); the z/y contractions run on the extended
    cross-section with per-shard global-indexed coefficient slices —
    exact on the local window, garbage in the (unconsumed) halo fringe —
    and the local (NY, NZ) window is sliced before the rings. The
    Dirichlet interior test and the dot ownership weights come from two
    streamed (NY, NZ) mask planes (mask2d, w2d): the closed-form iota
    test and the per-plane scalar weight only know global axes."""
    D = P  # output delay in grid steps
    n_in = NX + 2 * halo  # ingest sweep length
    nsteps = n_in if halo else NX + D
    E = 2 * P if ext2d else 0
    NYe, NZe = NY + E, NZ + E

    def kernel(*refs):
        if update_p:
            r_ref, pprev_ref = refs[:2]
            ni = 2
        else:
            (x_ref,) = refs[:1]
            ni = 1
        ckz_ref, cmz_ref, cky_ref, cmy_ref, cx_ref = refs[ni:ni + 5]
        ni += 5
        aux_ref = mask2d_ref = w2d_ref = None
        if halo:
            aux_ref = refs[ni]
            ni += 1
            if ext2d:
                mask2d_ref, w2d_ref = refs[ni:ni + 2]
                ni += 2
        scal_ref = refs[ni]
        base = ni + 1
        if update_p:
            p_out_ref, y_out_ref, dot_ref = refs[base:base + 3]
            no = 3
        else:
            y_out_ref, dot_ref = refs[base:base + 2]
            no = 2
        ring_t12, ring_tyz, ring_p, dacc = refs[base + no:base + no + 4]

        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            # Zero the rings: out-of-range x-plane reads are killed by the
            # zero coefficient columns, but 0 * garbage must stay finite —
            # freshly allocated VMEM can hold NaN bit patterns.
            ring_t12[...] = jnp.zeros_like(ring_t12)
            ring_tyz[...] = jnp.zeros_like(ring_tyz)
            ring_p[...] = jnp.zeros_like(ring_p)
            dacc[...] = jnp.zeros_like(dacc)

        KP = np.int32(P + 1)  # p ring: single-plane read at lag D = P

        # ---- ingest plane t: p-update, z+y contractions, ring publish ----
        @pl.when(t < np.int32(n_in))
        def _ingest():
            if update_p:
                p2f = scal_ref[0, 0] * pprev_ref[0] + r_ref[0]
                p2 = p2f[P:P + NY, P:P + NZ] if ext2d else p2f
                if halo:
                    # p is owned for the NX local planes only; the halo
                    # planes feed the rings but are the neighbours' to
                    # store.
                    @pl.when(jnp.logical_and(t >= np.int32(halo),
                                             t < np.int32(NX + halo)))
                    def _store_p():
                        p_out_ref[0] = p2
                else:
                    p_out_ref[0] = p2
            else:
                p2f = x_ref[0]
                p2 = p2f[P:P + NY, P:P + NZ] if ext2d else p2f
            slot = jax.lax.rem(t, np.int32(KI))
            t12, tyz = _zy_contract(
                p2f, ckz_ref, cmz_ref, cky_ref, cmy_ref, P, NYe, NZe
            )
            if ext2d:
                # exact on the local window (the per-shard coefficient
                # slices are global-indexed there); the halo fringe rows/
                # cols are garbage and sliced away before the rings
                t12 = t12[P:P + NY, P:P + NZ]
                tyz = tyz[P:P + NY, P:P + NZ]
            # p is read back exactly once, at emit lag D = P, so its ring
            # needs only P + 1 slots (the t12/tyz rings need the full
            # 2P + 1 x-window, hence KI = 2P + 2 with the write slot)
            ring_p[jax.lax.rem(t, KP)] = p2
            ring_t12[slot] = t12
            ring_tyz[slot] = tyz

        # ---- emit plane i = t - P: x contraction + blend + dot ----
        @pl.when(t >= np.int32(D + halo))
        def _emit():
            i = t - np.int32(D)
            p_i = ring_p[jax.lax.rem(i, KP)]
            gy = jax.lax.broadcasted_iota(jnp.int32, (NY, NZ), 0)
            gz = jax.lax.broadcasted_iota(jnp.int32, (NY, NZ), 1)
            mi = aux_ref[0, 0, 0] > 0.5 if halo else None
            inter2d = mask2d_ref[...] > 0.5 if ext2d else None
            y2 = _x_emit_blend(ring_t12, ring_tyz, cx_ref, i, p_i, gy, gz,
                               P, KI, NX, NY, NZ, mi=mi, inter2d=inter2d)
            y_out_ref[0] = y2
            # aux col 1 (dist form): dot-ownership weight, 0 on duplicated
            # seam planes so <p, A p> counts every dof once globally. In
            # the ext2d form the cross-section seams are deduplicated by
            # the w2d weight plane as well.
            w = aux_ref[0, 0, 1] if halo else None
            prod = p_i * y2
            if ext2d:
                prod = prod * w2d_ref[...]
            term = jnp.sum(prod)
            # rank-2 (1,1) stores: Mosaic rejects scalar stores to VMEM
            dacc[...] = dacc[...] + (w * term if halo else term)

        @pl.when(t == np.int32(nsteps - 1))
        def _finish():
            dot_ref[...] = dacc[...]

    return kernel


def _make_zy_chunk_kernel(P: int, NX: int, NY: int, NZ: int, CY: int,
                          NYB: int, update_p: bool):
    """Two-kernel form, kernel ZY: grid (NX, NYB+1)."""

    def kernel(*refs):
        if update_p:
            r_ref, pprev_ref = refs[:2]
            ni = 2
        else:
            (x_ref,) = refs[:1]
            ni = 1
        ckz_ref, cmz_ref, cky_ref, cmy_ref, scal_ref = refs[ni:ni + 5]
        base = ni + 5
        if update_p:
            p_out_ref, t12_ref, tyz_ref = refs[base:base + 3]
            no = 3
        else:
            t12_ref, tyz_ref = refs[base:base + 2]
            no = 2
        ring_aK, ring_aM = refs[base + no:base + no + 2]

        xi = pl.program_id(0)
        yj = pl.program_id(1)

        @pl.when(jnp.logical_and(xi == 0, yj == 0))
        def _init():
            # NaN insurance for the first stripe's halo reads (later
            # stripes find finite data from the previous plane; the zero
            # boundary coefficient columns kill it either way).
            ring_aK[...] = jnp.zeros_like(ring_aK)
            ring_aM[...] = jnp.zeros_like(ring_aM)

        @pl.when(yj < np.int32(NYB))
        def _ingest():
            if update_p:
                p2 = scal_ref[0, 0] * pprev_ref[0] + r_ref[0]
            else:
                p2 = x_ref[0]
            # Mask virtual-pad rows of the last chunk: their garbage would
            # otherwise ride the ring into valid output rows as 0 * NaN.
            gy = (yj * np.int32(CY)
                  + jax.lax.broadcasted_iota(jnp.int32, (CY, NZ), 0))
            p2 = jax.lax.select(gy < np.int32(NY), p2, jnp.zeros_like(p2))
            if update_p:
                p_out_ref[0] = p2
            aK, aM = _z_contract(p2, ckz_ref, cmz_ref, P, NZ)
            slot = jax.lax.rem(yj, np.int32(3))
            ring_aK[slot] = aK
            ring_aM[slot] = aM

        @pl.when(yj >= 1)
        def _emit():
            j = yj - 1

            def rd(ring, d):
                return ring[jax.lax.rem(j + np.int32(d + 3), np.int32(3))]

            bufK = jnp.concatenate(
                [rd(ring_aK, -1), rd(ring_aK, 0), rd(ring_aK, 1)], axis=0
            )
            bufM = jnp.concatenate(
                [rd(ring_aM, -1), rd(ring_aM, 0), rd(ring_aM, 1)], axis=0
            )
            # rows [(j-1)CY, (j+2)CY): the chunk's rows start at offset
            # CY - P relative to its -P halo
            t12, tyz = _y_contract(bufK, bufM, cky_ref[0], cmy_ref[0],
                                   CY, offset=CY - P)
            t12_ref[0] = t12
            tyz_ref[0] = tyz

    return kernel


def _make_x_chunk_kernel(P: int, NX: int, NY: int, NZ: int, CY: int,
                         KI: int):
    """Two-kernel form, kernel X: grid (NYB, NX+P), xi fastest."""
    D = P

    def kernel(t12_ref, tyz_ref, p_ref, cx_ref, y_out_ref, dot_ref,
               ring_t12, ring_tyz, dacc):
        yj = pl.program_id(0)
        xi = pl.program_id(1)

        @pl.when(xi == 0)
        def _init():
            ring_t12[...] = jnp.zeros_like(ring_t12)
            ring_tyz[...] = jnp.zeros_like(ring_tyz)
            dacc[...] = jnp.zeros_like(dacc)

        @pl.when(xi < np.int32(NX))
        def _ingest():
            slot = jax.lax.rem(xi, np.int32(KI))
            ring_t12[slot] = t12_ref[0]
            ring_tyz[slot] = tyz_ref[0]

        @pl.when(xi >= np.int32(D))
        def _emit():
            i = xi - np.int32(D)
            gy = (yj * np.int32(CY)
                  + jax.lax.broadcasted_iota(jnp.int32, (CY, NZ), 0))
            gz = jax.lax.broadcasted_iota(jnp.int32, (CY, NZ), 1)
            p_i = jax.lax.select(gy < np.int32(NY), p_ref[0],
                                 jnp.zeros_like(p_ref[0]))
            # virtual-pad rows: inter is False there and p_i is 0, so y2
            # is 0 and the dot term contributes nothing
            y2 = _x_emit_blend(ring_t12, ring_tyz, cx_ref, i, p_i, gy, gz,
                               P, KI, NX, NY, NZ)
            y_out_ref[0] = y2
            # rank-2 (1,1) stores: Mosaic rejects scalar stores to VMEM
            dacc[...] = dacc[...] + jnp.sum(p_i * y2)

        @pl.when(xi == np.int32(NX + D - 1))
        def _finish():
            dot_ref[...] = dacc[...].reshape(1, 1, 1)

    return kernel


def _cx_rows(op, dtype):
    """Per-output-plane x coefficients [M-row | K-row], kappa folded in,
    as an (NX, 1, 2(2P+1)) array streamed one row per emit step via SMEM.
    The singleton middle axis makes each block's last-two dims equal the
    array's — Mosaic requires (8,128)-divisible or full-dim blocks in the
    trailing two axes, and a (1, 2nb) block over an (NX, 2nb) array
    violates that (sublane dim 1 vs NX). jnp throughout: op is a traced
    pytree argument inside jit. Shared by both engine forms."""
    return jnp.concatenate(
        [(op.kappa * op.Md[0]).T, (op.kappa * op.Kd[0]).T], axis=1
    ).astype(dtype)[:, None, :]


def _kron_cg_call_chunked(op, update_p: bool, interpret, *vectors):
    """Two-kernel (y-chunked) form of _kron_cg_call — same contract, no
    VMEM size ceiling."""
    P = op.degree
    NX, NY, NZ = (int(a.shape[0]) for a in op.notbc1d)
    KI = 2 * P + 2
    D = P
    CY = _pick_cy(NY, P)
    NYB = -(-NY // CY)
    dtype = vectors[0].dtype
    nb = 2 * P + 1
    interp = _use_interpret() if interpret is None else interpret

    cx_rows = _cx_rows(op, dtype)
    # y coefficients, zero-padded to the chunk grid (the zero columns keep
    # garbage source rows out of valid outputs, as in banded_diags), laid
    # out chunk-major (NYB, nb, CY) so each grid step's block covers the
    # full trailing (nb, CY) axes — Mosaic rejects partial trailing-dim
    # blocks that aren't (8,128)-divisible (a (nb, CY) block over
    # (nb, NYB*CY) is such a block).
    pad_y = NYB * CY - NY
    cky = jnp.pad(op.Kd[1].astype(dtype), ((0, 0), (0, pad_y)))
    cmy = jnp.pad(op.Md[1].astype(dtype), ((0, 0), (0, pad_y)))
    cky = cky.reshape(nb, NYB, CY).transpose(1, 0, 2)
    cmy = cmy.reshape(nb, NYB, CY).transpose(1, 0, 2)

    def in_map(xi, yj):
        return (xi, jax.lax.min(yj, np.int32(NYB - 1)), 0)

    def out_map_emit(xi, yj):
        return (xi, jax.lax.max(yj - 1, np.int32(0)), 0)

    in_specs = []
    operands = []
    if update_p:
        r, p_prev, beta = vectors
        in_specs += [
            pl.BlockSpec((1, CY, NZ), in_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, CY, NZ), in_map, memory_space=pltpu.VMEM),
        ]
        operands += [r, p_prev]
    else:
        (x,) = vectors
        beta = jnp.zeros((), dtype)
        in_specs.append(
            pl.BlockSpec((1, CY, NZ), in_map, memory_space=pltpu.VMEM)
        )
        operands.append(x)
    for coeff in (op.Kd[2], op.Md[2]):
        in_specs.append(pl.BlockSpec((nb, NZ), lambda xi, yj: (0, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(coeff.astype(dtype))
    for coeff in (cky, cmy):
        in_specs.append(pl.BlockSpec(
            (1, nb, CY),
            lambda xi, yj: (jax.lax.max(yj - 1, np.int32(0)), 0, 0),
            memory_space=pltpu.VMEM,
        ))
        operands.append(coeff)
    in_specs.append(pl.BlockSpec((1, 1), lambda xi, yj: (0, 0),
                                 memory_space=pltpu.SMEM))
    operands.append(beta.astype(dtype).reshape(1, 1))

    out_specs = []
    out_shapes = []
    if update_p:
        out_specs.append(pl.BlockSpec((1, CY, NZ), in_map,
                                      memory_space=pltpu.VMEM))
        out_shapes.append(jax.ShapeDtypeStruct((NX, NY, NZ), dtype))
    out_specs += [
        pl.BlockSpec((1, CY, NZ), out_map_emit, memory_space=pltpu.VMEM),
        pl.BlockSpec((1, CY, NZ), out_map_emit, memory_space=pltpu.VMEM),
    ]
    out_shapes += [jax.ShapeDtypeStruct((NX, NY, NZ), dtype)] * 2

    zy = pl.pallas_call(
        _make_zy_chunk_kernel(P, NX, NY, NZ, CY, NYB, update_p),
        grid=(NX, NYB + 1),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((3, CY, NZ), dtype),
            pltpu.VMEM((3, CY, NZ), dtype),
        ],
        interpret=interp,
    )(*operands)
    if update_p:
        p, t12, tyz = zy
    else:
        t12, tyz = zy
        p = vectors[0]

    def x_in_map(yj, xi):
        return (jax.lax.min(xi, np.int32(NX - 1)), yj, 0)

    def x_lag_map(yj, xi):
        return (jax.lax.clamp(np.int32(0), xi - np.int32(D),
                              np.int32(NX - 1)), yj, 0)

    def cx_map(yj, xi):
        return (jax.lax.clamp(np.int32(0), xi - np.int32(D),
                              np.int32(NX - 1)), 0, 0)

    y, dot = pl.pallas_call(
        _make_x_chunk_kernel(P, NX, NY, NZ, CY, KI),
        grid=(NYB, NX + D),
        in_specs=[
            pl.BlockSpec((1, CY, NZ), x_in_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, CY, NZ), x_in_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, CY, NZ), x_lag_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 2 * nb), cx_map, memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, CY, NZ), x_lag_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 1), lambda yj, xi: (yj, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((NX, NY, NZ), dtype),
            jax.ShapeDtypeStruct((NYB, 1, 1), dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((KI, CY, NZ), dtype),
            pltpu.VMEM((KI, CY, NZ), dtype),
            pltpu.VMEM((1, 1), dtype),
        ],
        interpret=interp,
    )(t12, tyz, p, cx_rows)
    dot_total = jnp.sum(dot)
    if update_p:
        return p, y, dot_total
    return y, dot_total


# The one-kernel form above the default-scoped-limit budget: PJRT
# forwards a raised xla_tpu_scoped_vmem_limit_kib per compile (see
# utils.compilation), and the one-kernel form measured consistently
# faster than the chunked form on v5e once admitted (MEASURE_r04.log):
# tier 2 (64 MiB limit) Q3@25M 6.90 vs ~5.3, Q3@100M 7.74 vs 6.32;
# tier 3 (96 MiB limit, estimates to ~59 MiB) Q3@200M 6.63 vs 5.68,
# Q3@300M 6.71 vs 5.74, Q6@64M 5.36 vs 5.00 GDoF/s (interactive
# probes; the scripted matrix re-measures read 6.53/6.48/5.32 —
# BASELINE_MATRIX_r04.json). Above
# ONE_KERNEL_SCOPED_MAX2 the ring no longer fits even 96 MiB of the
# 128 MiB physical VMEM (Mosaic's stack runs ~1.3-1.4x the estimate)
# and the chunked form takes over. The raised limit is requested ONLY
# where needed: a blanket raise costs the flagship ~12% (9.26 -> 8.13,
# A probe) by stealing pipeline-buffer headroom.
ONE_KERNEL_SCOPED_MAX = _B.KRON_ONE_KERNEL_SCOPED_MAX
ONE_KERNEL_SCOPED_KIB = _B.KRON_ONE_KERNEL_SCOPED_KIB
ONE_KERNEL_SCOPED_MAX2 = _B.KRON_ONE_KERNEL_SCOPED_MAX2
ONE_KERNEL_SCOPED_KIB2 = _B.KRON_ONE_KERNEL_SCOPED_KIB2


def engine_plan(
    grid_shape: tuple[int, int, int], degree: int
) -> tuple[str, int | None]:
    """(form, scoped_vmem_kib) the auto dispatch picks for a single-chip
    grid: 'one' (delay-ring one-kernel) under the default-scoped-limit
    budget; 'one' with a raised per-compile scoped-VMEM request through
    the two hardware-checked tiers; else 'chunked'. The driver passes
    the kib to compile_lowered; _kron_cg_call derives the form from the
    same plan, so the two cannot disagree."""
    v = engine_vmem_bytes(grid_shape, degree)
    if v <= VMEM_BUDGET:
        return "one", None
    if v <= ONE_KERNEL_SCOPED_MAX:
        return "one", ONE_KERNEL_SCOPED_KIB
    if v <= ONE_KERNEL_SCOPED_MAX2:
        return "one", ONE_KERNEL_SCOPED_KIB2
    return "chunked", None


def engine_form(grid_shape: tuple[int, int, int], degree: int) -> str:
    """Form component of engine_plan (the driver's compile-failure
    fallback retries the chunked form exactly when this says 'one')."""
    return engine_plan(grid_shape, degree)[0]


def _kron_cg_call(op, update_p: bool, interpret, *vectors,
                  cx=None, aux=None, force_chunked: bool = False,
                  coeffs=None, mask2d=None, w2d=None):
    """update_p: vectors = (r, p_prev, beta) -> (p, y, <p, A p>).
    else:       vectors = (x,)              -> (y, <x, A x>).

    With `cx`/`aux` given (the distributed form, dist.kron_cg), vectors
    are halo-extended (NX + 2P, NY, NZ) local slabs, `cx` carries the
    per-shard x-coefficient rows, `aux` the per-plane
    [interior-in-x, dot-ownership] pairs; outputs stay (NX, NY, NZ).

    With `mask2d`/`w2d`/`coeffs` also given (the 3D-sharded form),
    vectors are halo-extended in every axis ((NX+2P, NY+2P, NZ+2P)
    local slabs), `coeffs` carries the per-shard extended (ckz, cmz,
    cky, cmy) banded slices, `mask2d` the (NY, NZ) cross-section
    Dirichlet-interior mask and `w2d` the cross-section dot-ownership
    weights; outputs stay (NX, NY, NZ)."""
    P = op.degree
    halo = 0 if cx is None else P
    ext2d = mask2d is not None
    if halo == 0:
        NX, NY, NZ = (int(a.shape[0]) for a in op.notbc1d)
        if force_chunked or engine_form((NX, NY, NZ), P) == "chunked":
            return _kron_cg_call_chunked(op, update_p, interpret, *vectors)
    else:
        # distributed form (dist.kron_cg): vectors are halo-extended local
        # slabs; the caller gates VMEM and provides per-shard cx/aux rows.
        NXe, NYe_in, NZe_in = (int(d) for d in vectors[0].shape)
        NX = NXe - 2 * P
        E = 2 * P if ext2d else 0
        NY, NZ = NYe_in - E, NZe_in - E
    E = 2 * P if ext2d else 0
    NYe, NZe = NY + E, NZ + E
    KI = 2 * P + 2
    D = P
    n_in = NX + 2 * halo
    nsteps = n_in if halo else NX + D
    dtype = vectors[0].dtype

    cx_rows = _cx_rows(op, dtype) if cx is None else cx

    def clamp_in(t):
        return (jax.lax.min(t, np.int32(n_in - 1)), 0, 0)

    def clamp_out(t):
        return (jax.lax.clamp(np.int32(0), t - np.int32(D + halo),
                              np.int32(NX - 1)), 0, 0)

    def clamp_p_out(t):
        return (jax.lax.clamp(np.int32(0), t - np.int32(halo),
                              np.int32(NX - 1)), 0, 0)

    nb = 2 * P + 1
    in_specs = []
    operands = []
    if update_p:
        r, p_prev, beta = vectors
        in_specs += [
            pl.BlockSpec((1, NYe, NZe), clamp_in, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, NYe, NZe), clamp_in, memory_space=pltpu.VMEM),
        ]
        operands += [r, p_prev]
    else:
        (x,) = vectors
        beta = jnp.zeros((), dtype)
        in_specs.append(
            pl.BlockSpec((1, NYe, NZe), clamp_in, memory_space=pltpu.VMEM)
        )
        operands.append(x)
    coeff_ops = (coeffs if ext2d else
                 (op.Kd[2], op.Md[2], op.Kd[1], op.Md[1]))
    for coeff, n_ax in zip(coeff_ops, (NZe, NZe, NYe, NYe)):
        in_specs.append(pl.BlockSpec((nb, n_ax), lambda t: (0, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(coeff.astype(dtype))
    in_specs.append(pl.BlockSpec((1, 1, 2 * nb), clamp_out,
                                 memory_space=pltpu.SMEM))
    operands.append(cx_rows)
    if halo:
        in_specs.append(pl.BlockSpec((1, 1, 2), clamp_out,
                                     memory_space=pltpu.SMEM))
        operands.append(aux)
        if ext2d:
            for plane in (mask2d, w2d):
                in_specs.append(pl.BlockSpec(
                    (NY, NZ), lambda t: (0, 0),
                    memory_space=pltpu.VMEM))
                operands.append(plane.astype(dtype))
    in_specs.append(pl.BlockSpec((1, 1), lambda t: (0, 0),
                                 memory_space=pltpu.SMEM))
    operands.append(beta.astype(dtype).reshape(1, 1))

    out_specs = []
    out_shapes = []
    if update_p:
        out_specs.append(pl.BlockSpec((1, NY, NZ), clamp_p_out,
                                      memory_space=pltpu.VMEM))
        out_shapes.append(jax.ShapeDtypeStruct((NX, NY, NZ), dtype))
    out_specs.append(pl.BlockSpec((1, NY, NZ), clamp_out,
                                  memory_space=pltpu.VMEM))
    out_shapes.append(jax.ShapeDtypeStruct((NX, NY, NZ), dtype))
    out_specs.append(pl.BlockSpec((1, 1), lambda t: (0, 0),
                                  memory_space=pltpu.VMEM))
    out_shapes.append(jax.ShapeDtypeStruct((1, 1), dtype))

    kernel = _make_kron_cg_kernel(P, NX, NY, NZ, KI, update_p, halo=halo,
                                  ext2d=ext2d)
    out = pl.pallas_call(
        kernel,
        grid=(nsteps,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=[
            pltpu.VMEM((KI, NY, NZ), dtype),
            pltpu.VMEM((KI, NY, NZ), dtype),
            pltpu.VMEM((P + 1, NY, NZ), dtype),  # p: single-plane lag read
            pltpu.VMEM((1, 1), dtype),
        ],
        interpret=_use_interpret() if interpret is None else interpret,
    )(*operands)
    if update_p:
        p, y, dot = out
        return p, y, dot[0, 0]
    y, dot = out
    return y, dot[0, 0]


def _make_update_kernel(NX: int, NY: int, NZ: int, CY: int):
    """x/r update + <r, r> partials as one pallas pass: the same 6 streams
    as the fused XLA pass, but immune to the XLA TPU backend's compile
    failure on very large whole-vector fusions (VMEM stack allocation at
    ~130M+ dofs), since every buffer here is one (CY, NZ) chunk."""

    def kernel(x_ref, p_ref, r_ref, y_ref, al_ref, x1_ref, r1_ref,
               rr_ref, racc):
        xi = pl.program_id(0)
        yj = pl.program_id(1)

        @pl.when(jnp.logical_and(xi == 0, yj == 0))
        def _init():
            racc[...] = jnp.zeros_like(racc)

        a = al_ref[0, 0]
        x1_ref[0] = x_ref[0] + a * p_ref[0]
        r1 = r_ref[0] - a * y_ref[0]
        r1_ref[0] = r1
        # mask virtual-pad rows of the last y-chunk out of the reduction
        gy = (yj * np.int32(CY)
              + jax.lax.broadcasted_iota(jnp.int32, (CY, NZ), 0))
        r1m = jax.lax.select(gy < np.int32(NY), r1, jnp.zeros_like(r1))
        # rank-2 (1,1) stores: Mosaic rejects scalar stores to VMEM
        racc[...] = racc[...] + jnp.sum(r1m * r1m)

        @pl.when(jnp.logical_and(xi == np.int32(NX - 1),
                                 yj == np.int32(-(-NY // CY) - 1)))
        def _finish():
            rr_ref[...] = racc[...]

    return kernel


def cg_update_pallas(x, p, r, y, alpha, interpret: bool | None = None):
    """(x + alpha p, r - alpha y, <r1, r1>) via the chunked pallas pass."""
    NX, NY, NZ = x.shape
    dtype = x.dtype
    CY = _pick_cy(NY, 1)
    NYB = -(-NY // CY)
    spec = pl.BlockSpec((1, CY, NZ), lambda xi, yj: (xi, yj, 0),
                        memory_space=pltpu.VMEM)
    x1, r1, rr = pl.pallas_call(
        _make_update_kernel(NX, NY, NZ, CY),
        grid=(NX, NYB),
        in_specs=[spec, spec, spec, spec,
                  pl.BlockSpec((1, 1), lambda xi, yj: (0, 0),
                               memory_space=pltpu.SMEM)],
        out_specs=[spec, spec,
                   pl.BlockSpec((1, 1), lambda xi, yj: (0, 0),
                                memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((NX, NY, NZ), dtype)] * 2
        + [jax.ShapeDtypeStruct((1, 1), dtype)],
        scratch_shapes=[pltpu.VMEM((1, 1), dtype)],
        interpret=_use_interpret() if interpret is None else interpret,
    )(x, p, r, y, alpha.astype(dtype).reshape(1, 1))
    return x1, r1, rr[0, 0]


# Above this many dofs the fused XLA update pass is replaced by the
# chunked pallas one: XLA's TPU backend fails compilation of whole-vector
# fusions around ~130M dofs ("allocating on stack for f32[667,670,670]").
PALLAS_UPDATE_MIN_DOFS = 100_000_000


def pallas_update_for(b, pallas_update, interpret):
    """Shared x/r-update routing for the fused CG solvers (kron, folded):
    the chunked pallas pass above PALLAS_UPDATE_MIN_DOFS (XLA's TPU
    backend fails whole-vector fusions ~130M dofs), else None (the fused
    XLA pass). One helper so the gating policy cannot diverge between
    engines."""
    use = (b.size >= PALLAS_UPDATE_MIN_DOFS if pallas_update is None
           else pallas_update)
    if not use:
        return None
    return (lambda x, p, r, y, alpha:
            cg_update_pallas(x, p, r, y, alpha, interpret))


def kron_cg_solve(op, b: jnp.ndarray, nreps: int,
                  interpret: bool | None = None,
                  pallas_update: bool | None = None,
                  force_chunked: bool = False) -> jnp.ndarray:
    """Benchmark CG with the fused one-kernel iteration (shared driver
    loop: la.cg.fused_cg_solve). Matches la.cg.cg_solve(op.apply, b, 0,
    nreps) to f32 reassociation accuracy. `pallas_update` (default: by
    size) routes the x/r update through cg_update_pallas. `force_chunked`
    overrides the auto form pick (the driver's Mosaic-rejection retry)."""

    def engine(r, p_prev, beta):
        return _kron_cg_call(op, True, interpret, r, p_prev, beta,
                             force_chunked=force_chunked)

    update = pallas_update_for(b, pallas_update, interpret)
    return fused_cg_solve(engine, b, nreps, update=update)


def kron_apply_ring(op, x: jnp.ndarray,
                    interpret: bool | None = None,
                    force_chunked: bool = False) -> jnp.ndarray:
    """Single delay-ring apply y = A x (with Dirichlet pass-through),
    discarding the fused <x, A x> partial. Used by the action benchmark
    when the engine is available."""
    y, _ = _kron_cg_call(op, False, interpret, x,
                         force_chunked=force_chunked)
    return y


# ---------------------------------------------------------------------------
# Batch-aware (nrhs-native) fused engine: the serving-layer kernel form.
#
# The vmapped fallback batches the GRID — each lane re-streams the banded
# coefficient tables and runs its own delay ring as a separate kernel
# sweep. This form makes nrhs a VMEM-resident minor axis of ONE sweep
# instead: every lane's rings live in VMEM simultaneously (per-lane ring
# buffers, so all indexing is exactly the proven single-RHS pattern), the
# z/y/x banded coefficient blocks and the per-plane SMEM cx rows are
# fetched ONCE per grid step and shared by all lanes, and the per-lane
# <p, A p> partials accumulate in per-lane (1, 1) VMEM scalars emitted
# together at the last step. Input/output blocks carry the whole lane
# stack for one x-plane ((nrhs, 1, NY, NZ) over lane-major (nrhs, NX,
# NY, NZ) arrays — trailing two dims full, so Mosaic tiling is the same
# as the single-RHS form's).
#
# VMEM scales ~ nrhs x the single-RHS ring estimate, so the bucket is a
# plan input: `engine_plan_batched` walks the same hardware-checked
# scoped-VMEM tiers as `engine_plan` and falls back to "unfused"
# (recorded by the caller) when the stacked rings outgrow the top tier.
# Evidence label: the batched form's tier admissions are DESIGN ESTIMATES
# derived from the single-RHS measured ceilings (same allocator ratio
# assumed per lane); no hardware numbers yet (tunnel wedged since r04) —
# the harness `fusedbatch` stage is armed to convert them.
# ---------------------------------------------------------------------------


def engine_vmem_bytes_batched(grid_shape: tuple[int, int, int],
                              degree: int, nrhs: int) -> int:
    """Estimated batched-kernel VMEM footprint: nrhs independent lane
    rings (each the single-RHS model) — the coefficient blocks shared
    across lanes are small and already over-bounded by the per-lane
    model's slack."""
    return int(nrhs) * engine_vmem_bytes(grid_shape, degree)


def engine_plan_batched(
    grid_shape: tuple[int, int, int], degree: int, nrhs: int
) -> tuple[str, int | None]:
    """(form, scoped_vmem_kib) for a batched single-chip solve at this
    lane count: 'one_batched' (the nrhs-native delay ring) through the
    same default/raised scoped-VMEM tiers as `engine_plan`, else
    'unfused' (vmapped fallback; the caller records the reason). nrhs = 1
    degenerates to the single-RHS ring footprint. There is no chunked
    batched form yet — planned, gated here."""
    if nrhs < 1:
        raise ValueError(f"nrhs must be >= 1, got {nrhs}")
    v = engine_vmem_bytes_batched(grid_shape, degree, nrhs)
    if v <= VMEM_BUDGET:
        return "one_batched", None
    if v <= ONE_KERNEL_SCOPED_MAX:
        return "one_batched", ONE_KERNEL_SCOPED_KIB
    if v <= ONE_KERNEL_SCOPED_MAX2:
        return "one_batched", ONE_KERNEL_SCOPED_KIB2
    return "unfused", None


def supports_kron_cg_engine_batched(grid_shape, degree: int, dtype,
                                    nrhs: int) -> bool:
    """f32 only (Mosaic has no f64) AND the stacked rings must fit a
    scoped-VMEM tier — unlike the single-RHS engine there is no chunked
    escape hatch yet, so the plan gates availability."""
    return (dtype == jnp.float32
            and engine_plan_batched(grid_shape, degree, nrhs)[0]
            != "unfused")


def _make_kron_cg_kernel_batched(P: int, NX: int, NY: int, NZ: int,
                                 KI: int, nrhs: int):
    """nrhs-native one-kernel delay-ring CG iteration (single-chip,
    update_p form only — the serving/batched-benchmark path). Per-lane
    ring scratch keeps every store/read the exact single-RHS pattern;
    the static python loop over lanes unrolls at trace time (nrhs is a
    bucket constant, <= 16)."""
    D = P
    nsteps = NX + D

    def kernel(*refs):
        (r_ref, pprev_ref, ckz_ref, cmz_ref, cky_ref, cmy_ref, cx_ref,
         scal_ref, p_out_ref, y_out_ref) = refs[:10]
        dot_refs = refs[10:10 + nrhs]
        scr = refs[10 + nrhs:]
        lanes = [scr[4 * l:4 * l + 4] for l in range(nrhs)]

        t = pl.program_id(0)

        @pl.when(t == 0)
        def _init():
            # Zero every lane's rings: 0 * garbage must stay finite (see
            # the single-RHS kernel's _init).
            for ring_t12, ring_tyz, ring_p, dacc in lanes:
                ring_t12[...] = jnp.zeros_like(ring_t12)
                ring_tyz[...] = jnp.zeros_like(ring_tyz)
                ring_p[...] = jnp.zeros_like(ring_p)
                dacc[...] = jnp.zeros_like(dacc)

        KP = np.int32(P + 1)

        @pl.when(t < np.int32(NX))
        def _ingest():
            slot = jax.lax.rem(t, np.int32(KI))
            pslot = jax.lax.rem(t, KP)
            for l in range(nrhs):
                ring_t12, ring_tyz, ring_p, _ = lanes[l]
                # per-lane beta rides in the shared SMEM row
                p2 = scal_ref[0, l] * pprev_ref[l, 0] + r_ref[l, 0]
                p_out_ref[l, 0] = p2
                t12, tyz = _zy_contract(p2, ckz_ref, cmz_ref, cky_ref,
                                        cmy_ref, P, NY, NZ)
                ring_p[pslot] = p2
                ring_t12[slot] = t12
                ring_tyz[slot] = tyz

        @pl.when(t >= np.int32(D))
        def _emit():
            i = t - np.int32(D)
            gy = jax.lax.broadcasted_iota(jnp.int32, (NY, NZ), 0)
            gz = jax.lax.broadcasted_iota(jnp.int32, (NY, NZ), 1)
            for l in range(nrhs):
                ring_t12, ring_tyz, ring_p, dacc = lanes[l]
                p_i = ring_p[jax.lax.rem(i, KP)]
                y2 = _x_emit_blend(ring_t12, ring_tyz, cx_ref, i, p_i,
                                   gy, gz, P, KI, NX, NY, NZ)
                y_out_ref[l, 0] = y2
                # rank-2 (1,1) stores: Mosaic rejects scalar VMEM stores
                dacc[...] = dacc[...] + jnp.sum(p_i * y2)

        @pl.when(t == np.int32(nsteps - 1))
        def _finish():
            for l in range(nrhs):
                dot_refs[l][...] = lanes[l][3][...]

    return kernel


def _kron_cg_call_batched(op, interpret, R, P_prev, beta):
    """Batched fused iteration: lane-major (nrhs, NX, NY, NZ) slabs in,
    (P, Y, pdots) out with pdots a (nrhs,) vector — the
    `la.cg.make_batched_cg_step` engine contract. Single-chip uniform
    geometry, f32, update_p form only (the plan gates everything
    else)."""
    P_ = op.degree
    NX, NY, NZ = (int(a.shape[0]) for a in op.notbc1d)
    nrhs = int(R.shape[0])
    KI = 2 * P_ + 2
    D = P_
    nsteps = NX + D
    dtype = R.dtype
    nb = 2 * P_ + 1
    cx_rows = _cx_rows(op, dtype)

    def clamp_in(t):
        return (0, jax.lax.min(t, np.int32(NX - 1)), 0, 0)

    def clamp_p_out(t):
        return (0, jax.lax.clamp(np.int32(0), t, np.int32(NX - 1)), 0, 0)

    def clamp_out(t):
        return (0, jax.lax.clamp(np.int32(0), t - np.int32(D),
                                 np.int32(NX - 1)), 0, 0)

    def cx_map(t):
        return (jax.lax.clamp(np.int32(0), t - np.int32(D),
                              np.int32(NX - 1)), 0, 0)

    lane_block = (nrhs, 1, NY, NZ)
    in_specs = [
        pl.BlockSpec(lane_block, clamp_in, memory_space=pltpu.VMEM),
        pl.BlockSpec(lane_block, clamp_in, memory_space=pltpu.VMEM),
    ]
    operands = [R, P_prev]
    for coeff, n_ax in zip((op.Kd[2], op.Md[2], op.Kd[1], op.Md[1]),
                           (NZ, NZ, NY, NY)):
        in_specs.append(pl.BlockSpec((nb, n_ax), lambda t: (0, 0),
                                     memory_space=pltpu.VMEM))
        operands.append(coeff.astype(dtype))
    in_specs.append(pl.BlockSpec((1, 1, 2 * nb), cx_map,
                                 memory_space=pltpu.SMEM))
    operands.append(cx_rows)
    in_specs.append(pl.BlockSpec((1, nrhs), lambda t: (0, 0),
                                 memory_space=pltpu.SMEM))
    operands.append(beta.astype(dtype).reshape(1, nrhs))

    out_specs = [
        pl.BlockSpec(lane_block, clamp_p_out, memory_space=pltpu.VMEM),
        pl.BlockSpec(lane_block, clamp_out, memory_space=pltpu.VMEM),
    ]
    out_shapes = [jax.ShapeDtypeStruct((nrhs, NX, NY, NZ), dtype)] * 2
    for _ in range(nrhs):
        out_specs.append(pl.BlockSpec((1, 1), lambda t: (0, 0),
                                      memory_space=pltpu.VMEM))
        out_shapes.append(jax.ShapeDtypeStruct((1, 1), dtype))

    scratch = []
    for _ in range(nrhs):
        scratch += [
            pltpu.VMEM((KI, NY, NZ), dtype),
            pltpu.VMEM((KI, NY, NZ), dtype),
            pltpu.VMEM((P_ + 1, NY, NZ), dtype),
            pltpu.VMEM((1, 1), dtype),
        ]

    out = pl.pallas_call(
        _make_kron_cg_kernel_batched(P_, NX, NY, NZ, KI, nrhs),
        grid=(nsteps,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=scratch,
        interpret=_use_interpret() if interpret is None else interpret,
    )(*operands)
    p, y = out[0], out[1]
    pdots = jnp.concatenate([d.reshape(1) for d in out[2:]], axis=0)
    return p, y, pdots


def kron_batched_engine(op, interpret: bool | None = None):
    """The fused batched iteration as a `la.cg.make_batched_cg_step`
    engine: engine(R, P_prev, beta) -> (P, Y, <P, A P> per lane)."""

    def engine(R, P_prev, beta):
        return _kron_cg_call_batched(op, interpret, R, P_prev, beta)

    return engine


def kron_cg_solve_batched(op, B: jnp.ndarray, nreps: int,
                          interpret: bool | None = None) -> jnp.ndarray:
    """Batched benchmark CG with the nrhs-native fused iteration
    (la.cg.fused_cg_solve_batched over kron_batched_engine). Matches
    `la.cg.cg_solve_batched(op.apply, B, 0, nreps)` per lane to f32
    reassociation accuracy (<= 1e-7 — the serving parity contract);
    padding (all-zero) lanes return zeros, exactly as the oracle's."""
    from ..la.cg import fused_cg_solve_batched

    return fused_cg_solve_batched(kron_batched_engine(op, interpret),
                                  B, nreps)
