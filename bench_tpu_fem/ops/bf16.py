"""bf16-stream / f32-accumulate operator wrapper (ISSUE 17).

The roofline stamps place the sum-factorised apply firmly HBM-bound, so
halving streamed bytes is the most direct GDoF/s lever left: store the
operator's streamed operands (banded factor diagonals for the kron fast
path, the geometry tensor G for the perturbed einsum path) as bfloat16
and let every contraction accumulate in f32. bf16 keeps f32's 8-bit
exponent — only mantissa is sacrificed — so no loss-scaling is needed:
residuals at 1e-10 still round to normal bf16 numbers, which is exactly
why the iterative-refinement outer loop (la.refine) can run its hot-loop
applies at bf16 bandwidth and still hand back f64-class answers.

Mechanically `Bf16Operator` wraps ANY existing operator pytree
(ops.kron.KronLaplacian uniform fast path, ops.laplacian.Laplacian
einsum path for perturbed geometry): construction rounds every floating
leaf to bfloat16 — the HBM-resident copy IS bf16, so the streamed-byte
claim is structural, not a compiler hope — and `apply` upcasts operands
and input to the f32 accumulator dtype around the wrapped apply. On TPU,
XLA fuses the widening converts into the contractions so HBM traffic
stays at bf16 width; on CPU the same graph is the bit-exact oracle for
what the chip computes. The bandwidth halving itself is labelled
design-estimate until the harness `bf16` agenda stage measures it on
hardware (obs.roofline carries the byte model).

VMEM planning: bf16 tiles on TPU are (16, 128) sublane x lane (f32 is
(8, 128)) — see analysis/fixtures.py fixture_r1_bf16 — so every window
estimate here is quantised UP to the 4 KiB bf16 tile quantum before the
tier ladder runs. There is no fused bf16 Mosaic ring yet: the plan
always routes the unfused composition (engines.registry gates the fused
form with a registered reason), but the quantised window numbers are
what the autotuner sweeps and what the hardware stage will check.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

#: bf16 Mosaic tile (sublane, lane) — double the f32 sublane count, so a
#: bf16 tile is the same 4 KiB footprint as an f32 (8, 128) tile but
#: holds twice the elements (the packing that halves streamed bytes).
BF16_TILE = (16, 128)

#: bytes per bf16 tile: 16 * 128 * 2 = 4 KiB — the VMEM window quantum
#: every bf16 plan rounds up to.
BF16_TILE_BYTES = BF16_TILE[0] * BF16_TILE[1] * 2


def quantize_to_bf16_tile(nbytes: int) -> int:
    """Round a VMEM window estimate UP to the bf16 (16, 128) tile
    quantum (Mosaic allocates whole tiles; a 1-byte overhang costs a
    full 4 KiB tile)."""
    q = BF16_TILE_BYTES
    return max(q, -(-int(nbytes) // q) * q)


def engine_vmem_bytes_bf16(grid_shape, degree: int) -> int:
    """Design-estimate VMEM footprint of a (future) fused bf16 kron
    ring: the f32 ring's vector windows at half width, re-quantised to
    the bf16 tile. Labelled design-estimate until the hardware `bf16`
    agenda stage checks it on chip."""
    from .kron_cg import engine_vmem_bytes

    return quantize_to_bf16_tile(engine_vmem_bytes(grid_shape, degree) // 2)


def engine_plan_bf16(grid_shape, degree: int) -> tuple[str, int | None]:
    """(form, scoped_vmem_kib) for a bf16 single-chip solve — the
    registry's plan contract (ops.kron_cg.engine_plan). No fused bf16
    Mosaic ring exists yet, so the achieved form is always the unfused
    streamed composition; the quantised window estimate still rides the
    plan so the autotuner's candidate ladder and the hardware stage
    agree on the tile-quantised footprint."""
    del grid_shape, degree  # footprint via engine_vmem_bytes_bf16
    return "unfused", None


def _to_bf16_leaf(a):
    if isinstance(a, (jnp.ndarray, np.ndarray)) and jnp.issubdtype(
            jnp.asarray(a).dtype, jnp.floating):
        return jnp.asarray(a, jnp.bfloat16)
    return a


def _widen_leaf(a, dtype):
    if isinstance(a, (jnp.ndarray, np.ndarray)) and jnp.issubdtype(
            jnp.asarray(a).dtype, jnp.floating):
        return jnp.asarray(a, dtype)
    return a


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["inner"],
    meta_fields=["accum"],
)
@dataclass(frozen=True)
class Bf16Operator:
    """bf16-stream / f32-accumulate wrapper around an operator pytree.

    `inner` is the wrapped operator with every floating leaf already
    rounded to bfloat16 (the device-resident state — what HBM streams).
    `apply` rounds the input to bf16 (the streamed width of the vector),
    widens operands + input to the `accum` dtype, and runs the wrapped
    apply — contractions accumulate at f32, the standard mixed-precision
    contract. Dirichlet rows pass the bf16-rounded input through (the
    wrapped operator's own blend), consistent with "every streamed value
    is bf16-width"."""

    inner: object
    accum: str = "float32"

    def apply(self, x_grid: jnp.ndarray) -> jnp.ndarray:
        acc = jnp.dtype(self.accum)
        xb = jnp.asarray(x_grid, jnp.bfloat16)
        hi = jax.tree_util.tree_map(lambda a: _widen_leaf(a, acc),
                                    self.inner)
        return hi.apply(jnp.asarray(xb, acc))


def to_bf16(op) -> Bf16Operator:
    """Wrap an operator pytree (KronLaplacian / Laplacian / ...) as a
    bf16-stream operator: every floating leaf rounds to bfloat16 ONCE at
    construction (integer/bool leaves — bc masks — pass through), so the
    wrapped state genuinely lives at half width."""
    inner = jax.tree_util.tree_map(_to_bf16_leaf, op)
    return Bf16Operator(inner=inner)


def bf16_dinv(op) -> jnp.ndarray | None:
    """Jacobi diag-inverse for a bf16-wrapped operator, computed from
    the WIDENED operand state (f32): the preconditioner is outer-loop
    state, not a streamed hot-loop operand, so it keeps f32 accuracy —
    the la.precond composition the refinement driver feeds cg_solve."""
    from ..la.precond import op_jacobi_dinv

    wide = jax.tree_util.tree_map(
        lambda a: _widen_leaf(a, jnp.dtype("float32")), op.inner)
    return op_jacobi_dinv(wide)
