"""Double-float folded pipeline: f64-class CG on general (perturbed)
geometry.

The reference runs its f64 matrix-free operator on *arbitrary* geometry
(laplacian_gpu.hpp:91-426 is templated on T=double with no uniformity
assumption); this repo's df32 fast path was kron-uniform-only, so
perturbed `--float 64` fell back to XLA's op-by-op f64 emulation
(~0.014x of the reference baseline, BASELINE_MATRIX_r04.json). This
module closes that cell: the UNFUSED folded/corner pipeline of
ops.folded restated on (hi, lo) double-float channels —

- the window gather/seam structure is ops.folded's v1 pipeline run once
  per channel (pad/slice slabs -> one pallas kernel -> XLA seam fold);
  the data movement (transpose, pad, slice, concat) is exact per channel,
  so only the arithmetic needed df treatment;
- the per-cell sum-factorised contraction chain runs with error-free
  products against 4-channel compile-time basis-table immediates and the
  renorm-first compensated accumulation pinned by ops.kron_cg_df._acc2
  (every term renormalised by a two_sum before it enters the running
  sum — the one form measured to survive whole-graph optimisation);
- geometry is df end to end: precomputed mode streams the host-f64 G
  split into (hi, lo) blocked pairs; corner mode ships df corner pairs
  (2 x 24 floats/cell) and runs the full Jacobian -> adjugate -> detJ ->
  division chain in df arithmetic in-kernel (la.df64 primitives — a
  f32-rounded geometry would cap the whole pipeline at ~1e-7 relative,
  defeating the ~1e-12 target);
- the seam overlap-add and CG vector algebra run as XLA df passes
  (df_add/df_dot: channel-wise adds would drop the two_sum carries).

Deliberately UNFUSED (the v1 composition, not a delay-ring engine): the
df working set roughly doubles every VMEM-resident value and the corner
geometry chain adds deep df temporaries, so the fused forms' VMEM
budgets do not carry over; the unfused pipeline is the capacity- and
accuracy-correct first form (README 'Precision policy' named exactly
this design), with the fused df folded engine as follow-up work once
`folded_df_plan`'s DESIGN-ESTIMATE VMEM model is hardware-calibrated
(scripts/measure_all.py pertdf stage).

Reference parity: f64 dispatch main.cpp:277-288, per-cell math
laplacian_gpu.hpp:91-426, CG recurrence cg.hpp:89-169 (rtol = 0,
fixed iteration count), residual floors laplacian_solver.cpp:130-148.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..analysis import budgets as _B
from ..elements.tables import OperatorTables, build_operator_tables
from ..la.df64 import (
    DF,
    _prod_terms,
    _split,
    df_add,
    df_axpy,
    df_div,
    df_dot,
    df_scale,
    df_sub,
    df_zeros_like,
)
from ..mesh.box import BoxMesh
from ..mesh.dofmap import boundary_dof_marker
from .folded import (
    FoldedLayout,
    _assemble_window,
    _r8,
    _rb,
    blocked_corners,
    check_tpu_lane_support,
    fold_vector,
    ghost_corner_arrays,
    make_layout,
    window_slab_specs,
    window_slabs,
)
from .kron_cg_df import _acc2, _eft_term, _renorm2
from .laplacian import freeze_table
from .pallas_laplacian import SUBLANES, _use_interpret


# ---------------------------------------------------------------------------
# df building blocks
# ---------------------------------------------------------------------------


def _table4(mat: np.ndarray) -> tuple:
    """Host 4-channel split of a compile-time f64 table: per entry
    [hi, lo, split_high(hi), split_low(hi)], the df twin of the float
    immediates ops.pallas_laplacian._stage bakes into kernels. The Dekker
    split is computed in numpy f32 (error-free, so it reproduces
    la.df64._split bit-for-bit); values are returned as f64 arrays so
    float() emits them exactly."""
    m64 = np.asarray(mat, np.float64)
    mhi = np.asarray(m64, np.float32)
    mlo = np.asarray(m64 - np.asarray(mhi, np.float64), np.float32)
    c = np.float32(4097.0) * mhi
    mhh = c - (c - mhi)
    mhl = mhi - mhh
    return tuple(np.asarray(a, np.float64) for a in (mhi, mlo, mhh, mhl))


def _stage_df(tab4: tuple, u: DF, axis: int) -> DF:
    """Contract a compile-time table (4-channel split, see _table4)
    against tensor axis `axis` of the df pair `u`: error-free products of
    scalar immediates against the data channels (_eft_term) with the
    renorm-first compensated accumulation of ops.kron_cg_df (_acc2). The
    Dekker split of u's hi channel is computed once and sliced per term;
    zero coefficients are skipped, preserving structural zeros exactly
    (the df twin of ops.pallas_laplacian._stage)."""
    mhi, mlo, mhh, mhl = tab4
    m, n = mhi.shape
    hh, hl = _split(u.hi)

    def take(a, i):
        idx = [slice(None)] * a.ndim
        idx[axis] = i
        return a[tuple(idx)]

    out_h, out_l = [], []
    for q in range(m):
        acc = None
        for i in range(n):
            if mhi[q, i] == 0.0 and mlo[q, i] == 0.0:
                continue
            t, e = _eft_term(
                float(mhi[q, i]), float(mlo[q, i]),
                float(mhh[q, i]), float(mhl[q, i]),
                take(u.hi, i), take(u.lo, i), take(hh, i), take(hl, i),
            )
            acc = _acc2(acc, t, e)
        if acc is None:
            z = jnp.zeros_like(take(u.hi, 0))
            out_h.append(z)
            out_l.append(z)
        else:
            rh, rl = _renorm2(*acc)
            out_h.append(rh)
            out_l.append(rl)
    return DF(jnp.stack(out_h, axis=axis), jnp.stack(out_l, axis=axis))


def _mul_df(a: DF, b: DF) -> DF:
    """Renormalised df product of two runtime pairs (splits in place)."""
    return DF(*_renorm2(*_prod_terms(a, b)))


def _sum_df(*terms: DF) -> DF:
    """Compensated sum of renormalised df terms (renorm-first, _acc2)."""
    acc = None
    for t in terms:
        acc = _acc2(acc, t.hi, t.lo)
    return DF(*_renorm2(*acc))


def _dot3_df(u, v) -> DF:
    """Compensated 3-term df dot (Jacobian-column x adjugate-row)."""
    acc = _acc2(None, *_prod_terms(u[0], v[0]))
    acc = _acc2(acc, *_prod_terms(u[1], v[1]))
    acc = _acc2(acc, *_prod_terms(u[2], v[2]))
    return DF(*_renorm2(*acc))


def sumfact_window_apply_df(u: DF, G, phi0_t4, dphi1_t4, phi0T_t4,
                            dphi1T_t4, is_identity: bool) -> DF:
    """df twin of ops.pallas_laplacian.sumfact_window_apply: window cube
    (nd, nd, nd, 8, NL) df pair x 6-component df geometry tuple ->
    contribution cube df pair. kappa is folded into the geometry by the
    builders (the df analogue of ops.kron_df folding kappa into the 1D
    factors host-side: no runtime df scalar product per apply). Tables
    arrive pre-split (_table4) so the kernel maker pays the host split
    once."""
    if not is_identity:
        u = _stage_df(phi0_t4, u, 0)
        u = _stage_df(phi0_t4, u, 1)
        u = _stage_df(phi0_t4, u, 2)
    du0 = _stage_df(dphi1_t4, u, 0)
    du1 = _stage_df(dphi1_t4, u, 1)
    du2 = _stage_df(dphi1_t4, u, 2)

    def flux(a, b, c):
        acc = _acc2(None, *_prod_terms(G[a], du0))
        acc = _acc2(acc, *_prod_terms(G[b], du1))
        acc = _acc2(acc, *_prod_terms(G[c], du2))
        return DF(*_renorm2(*acc))

    f0 = flux(0, 1, 2)
    f1 = flux(1, 3, 4)
    f2 = flux(2, 4, 5)
    y = _sum_df(
        _stage_df(dphi1T_t4, f0, 0),
        _stage_df(dphi1T_t4, f1, 1),
        _stage_df(dphi1T_t4, f2, 2),
    )
    if not is_identity:
        y = _stage_df(phi0T_t4, y, 0)
        y = _stage_df(phi0T_t4, y, 1)
        y = _stage_df(phi0T_t4, y, 2)
    return y


def corner_window_G_df(corners: DF, mask, pts1d: np.ndarray,
                       wts1d: np.ndarray, kappa: float):
    """df twin of ops.pallas_laplacian.corner_window_G: trilinear df
    Jacobian (compile-time shape tables, df corner pairs) -> adjugate
    rows (df cross products) -> detJ -> scale = kappa * mask / detJ (df
    Newton division, la.df64.df_div) with diagonal quadrature-weight
    stages -> the 6 packed G components as df pairs. kappa is a
    compile-time constant folded into the scale numerator (exact: mask
    is 0/1). Ghost cells carry the unit-cube placeholder Jacobian
    (detJ = 1 exactly, also in df) and a zero mask that zeroes their G
    rows exactly — the same self-masking convention as the f32 kernels
    (ops.folded.ghost_corner_arrays)."""
    pts = np.asarray(pts1d, np.float64)
    nq = len(pts)
    N4 = _table4(np.stack([1.0 - pts, pts], axis=1))
    D4 = _table4(np.broadcast_to(np.array([-1.0, 1.0]), (nq, 2)))
    cols = []
    for a in range(3):
        T = [N4, N4, N4]
        T[a] = D4
        col = []
        for i in range(3):
            c = DF(corners.hi[i], corners.lo[i])  # (2, 2, 2, 8, NL)
            c = _stage_df(T[2], c, 2)
            c = _stage_df(T[1], c, 1)
            c = _stage_df(T[0], c, 0)
            col.append(c)  # (nq, nq, nq, 8, NL)
        cols.append(col)

    def cross(u, v):
        return (
            df_sub(_mul_df(u[1], v[2]), _mul_df(u[2], v[1])),
            df_sub(_mul_df(u[2], v[0]), _mul_df(u[0], v[2])),
            df_sub(_mul_df(u[0], v[1]), _mul_df(u[1], v[0])),
        )

    K = (cross(cols[1], cols[2]), cross(cols[2], cols[0]),
         cross(cols[0], cols[1]))
    detJ = _dot3_df(cols[0], K[0])
    khi = float(np.float32(kappa))
    klo = float(np.float64(kappa) - np.float64(np.float32(kappa)))
    # kappa * mask is exact per channel (mask is 0/1)
    scale = df_div(DF(khi * mask, klo * mask), detJ)
    w4 = _table4(np.diag(np.asarray(wts1d, np.float64)))
    for ax in range(3):
        scale = _stage_df(w4, scale, ax)
    pairs = ((0, 0), (0, 1), (0, 2), (1, 1), (1, 2), (2, 2))
    return tuple(_mul_df(_dot3_df(K[a], K[b]), scale) for a, b in pairs)


# ---------------------------------------------------------------------------
# Kernel + v1 pipeline
# ---------------------------------------------------------------------------


def _make_folded_df_kernel(P: int, nl: int, is_identity: bool,
                           phi0: np.ndarray, dphi1: np.ndarray,
                           geom_tables, kappa: float):
    """Kernel body: 16 window slab refs (8 classes x hi then lo), df
    geometry refs, 16 contribution outputs. Mirrors
    ops.folded._make_folded_kernel with the arithmetic in df."""
    t_phi0 = _table4(phi0)
    t_dphi1 = _table4(dphi1)
    t_phi0T = _table4(np.asarray(phi0, np.float64).T)
    t_dphi1T = _table4(np.asarray(dphi1, np.float64).T)
    corner_mode = geom_tables is not None

    def write_outs(y, refs):
        y_ref, yx_ref, yy_ref, yz_ref, yxy_ref, yxz_ref, yyz_ref, \
            yxyz_ref = refs
        y_ref[...] = _rb(y[:P, :P, :P])
        yx_ref[...] = _rb(y[P, :P, :P])
        yy_ref[...] = _rb(y[:P, P, :P])
        yz_ref[...] = _rb(y[:P, :P, P])
        yxy_ref[...] = _rb(y[P, P, :P])
        yxz_ref[...] = _rb(y[P, :P, P])
        yyz_ref[...] = _rb(y[:P, P, P])
        yxyz_ref[...] = _rb(y[P, P, P])

    def kernel(*refs):
        r8 = lambda r: _r8(r[...], nl)  # noqa: E731
        uh = _assemble_window(*(r8(refs[i]) for i in range(8)))
        ul = _assemble_window(*(r8(refs[8 + i]) for i in range(8)))
        if corner_mode:
            ch_ref, cl_ref, m_ref = refs[16:19]
            G = corner_window_G_df(
                DF(ch_ref[0], cl_ref[0]), m_ref[0], *geom_tables, kappa
            )
            base = 19
        else:
            gh_ref, gl_ref = refs[16:18]
            G = tuple(DF(gh_ref[0, c], gl_ref[0, c]) for c in range(6))
            base = 18
        y = sumfact_window_apply_df(
            DF(uh, ul), G, t_phi0, t_dphi1, t_phi0T, t_dphi1T, is_identity
        )
        write_outs(y.hi, refs[base:base + 8])
        write_outs(y.lo, refs[base + 8:base + 16])

    return kernel


def xla_seam_fold_df(outs_h, outs_l, layout: FoldedLayout) -> DF:
    """df twin of ops.folded.xla_seam_fold: identical shift/lift zero-pad
    structure (exact per channel), with every overlap addition a df_add —
    channel-wise adds would drop the two_sum carries (an O(2^-24)
    relative loss, exactly what df exists to avoid)."""
    P = layout.degree
    Lv, nb, B = layout.lv, layout.nblocks, layout.block
    Sx, Sy, Sz = layout.shifts

    def shift(a, S):
        return jnp.pad(a[..., : Lv - S], [(0, 0)] * (a.ndim - 1) + [(S, 0)])

    def lift(a, axis):
        pads = [(0, 0)] * (a.ndim + 1)
        pads[axis] = (0, P - 1)
        return jnp.pad(jnp.expand_dims(a, axis), pads)

    def sl(d: DF, S: int, *axes) -> DF:
        h, lo = shift(d.hi, S), shift(d.lo, S)
        for ax in axes:
            h, lo = lift(h, ax), lift(lo, ax)
        return DF(h, lo)

    Y, Yx, Yy, Yz, Yxy, Yxz, Yyz, Yxyz = (
        DF(h, lo) for h, lo in zip(outs_h, outs_l)
    )
    Yx = df_add(
        df_add(Yx, sl(Yxy, Sy, 0)),
        df_add(sl(Yxz, Sz, 1), sl(Yxyz, Sy + Sz, 0, 1)),
    )
    Yy = df_add(Yy, sl(Yyz, Sz, 1))
    out = df_add(
        df_add(Y, sl(Yx, Sx, 0)),
        df_add(sl(Yy, Sy, 1), sl(Yz, Sz, 2)),
    )

    def fold_back(a):
        return jnp.transpose(a.reshape(P * P * P, nb, B), (1, 0, 2))

    return DF(fold_back(out.hi), fold_back(out.lo))


def folded_cell_apply_df(
    x: DF,  # (nb, P^3, B) masked folded df pair
    geom,  # (Gh, Gl) blocked df G | (corners_h, corners_l, mask_b)
    layout: FoldedLayout,
    phi0: np.ndarray,
    dphi1: np.ndarray,
    is_identity: bool,
    kappa: float,
    interpret: bool | None = None,
    geom_tables: tuple[np.ndarray, np.ndarray] | None = None,
) -> DF:
    """One unfused df operator contribution pass (the v1 pipeline of
    ops.folded.folded_cell_apply on df channels): XLA slab prep per
    channel -> ONE pallas kernel over 16 window operands + df geometry ->
    XLA df seam fold. Returns the un-bc'd folded DF result."""
    P = layout.degree
    nq = np.shape(phi0)[0]
    nl, nb, Lv = layout.nl, layout.nblocks, layout.lv
    dtype = x.hi.dtype

    wspecs = window_slab_specs(layout)
    in_specs = wspecs + list(wspecs)
    operands = [*window_slabs(x.hi, layout), *window_slabs(x.lo, layout)]
    if geom_tables is None:
        Gh, Gl = geom
        gspec = pl.BlockSpec(
            (1, 6, nq, nq, nq, SUBLANES, nl),
            lambda i: (i, 0, 0, 0, 0, 0, 0), memory_space=pltpu.VMEM,
        )
        in_specs += [gspec, gspec]
        operands += [Gh, Gl]
    else:
        ch, cl, mask_b = geom
        cspec = pl.BlockSpec(
            (1, 3, 2, 2, 2, SUBLANES, nl),
            lambda i: (i, 0, 0, 0, 0, 0, 0), memory_space=pltpu.VMEM,
        )
        mspec = pl.BlockSpec((1, SUBLANES, nl), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM)
        in_specs += [cspec, cspec, mspec]
        operands += [ch, cl, mask_b]

    out_shapes = [
        jax.ShapeDtypeStruct((P, P, P, Lv), dtype),
        jax.ShapeDtypeStruct((P, P, Lv), dtype),
        jax.ShapeDtypeStruct((P, P, Lv), dtype),
        jax.ShapeDtypeStruct((P, P, Lv), dtype),
        jax.ShapeDtypeStruct((P, Lv), dtype),
        jax.ShapeDtypeStruct((P, Lv), dtype),
        jax.ShapeDtypeStruct((P, Lv), dtype),
        jax.ShapeDtypeStruct((Lv,), dtype),
    ]
    kernel = _make_folded_df_kernel(
        P, nl, is_identity,
        np.asarray(phi0, np.float64), np.asarray(dphi1, np.float64),
        geom_tables, kappa,
    )
    outs = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=in_specs,
        out_specs=list(wspecs) + list(wspecs),
        out_shape=out_shapes + list(out_shapes),
        interpret=_use_interpret() if interpret is None else interpret,
    )(*operands)
    return xla_seam_fold_df(outs[:8], outs[8:], layout)


# ---------------------------------------------------------------------------
# VMEM plan (DESIGN ESTIMATES — no folded-df kernel has been Mosaic-
# compiled yet; the pertdf stage of scripts/measure_all.py is armed to
# calibrate these the moment the tunnel lives)
# ---------------------------------------------------------------------------

# Per-compile scoped-VMEM request for every folded-df compile on TPU: the
# df working set roughly doubles the f32 kernels', which already sit near
# the 16 MiB default limit at full 128-lane blocks.
FOLDED_DF_SCOPED_KIB = _B.FOLDED_DF_SCOPED_KIB
# Live-value model budget under the raised 64 MiB limit, derated by the
# WORST measured model->Mosaic allocator ratio in this repo (1.7x, the
# plane-streamed corner kernels — ops.pallas_laplacian; derivation with
# every other budget in analysis.budgets). The folded
# kernels require full 128-lane blocks on TPU (narrower relayouts are
# Mosaic-unsupported), so a config either fits at nl=128 or routes to the
# recorded XLA-emulation fallback.
_FOLDED_DF_BUDGET_BYTES = _B.FOLDED_DF_BUDGET_BYTES


def _df_cell_bytes(nd: int, nq: int, geom: str) -> int:
    """Modelled per-cell VMEM of the df window kernel: double-buffered
    u/y at 2 channels (8*nd^3), live geometry + contraction intermediates
    with their Dekker splits (~44*nq^3 G-streaming / ~34*nq^3 + corner
    pairs in corner mode, where G is a live value but the df Jacobian
    chain holds deep temporaries)."""
    if geom == "g":
        return (8 * nd**3 + 44 * nq**3) * 4
    return (8 * nd**3 + 34 * nq**3 + 120) * 4


def folded_df_plan(degree: int, nq: int):
    """(supported, forced_geom, scoped_vmem_kib) for the TPU folded df
    path: G-streaming while its modelled footprint fits the derated
    raised-limit budget, corner mode (smaller streams, bigger compute)
    as the rescue, else unsupported — the drivers route unsupported
    configs to XLA f64 emulation WITH THE REASON RECORDED (never
    silently). Single policy shared by the single-chip and distributed
    builders and the bench drivers."""
    nd = degree + 1
    lanes = SUBLANES * 128
    if _df_cell_bytes(nd, nq, "g") * lanes <= _FOLDED_DF_BUDGET_BYTES:
        return True, None, FOLDED_DF_SCOPED_KIB
    if _df_cell_bytes(nd, nq, "corner") * lanes <= _FOLDED_DF_BUDGET_BYTES:
        return True, "corner", FOLDED_DF_SCOPED_KIB
    return False, None, None


def auto_geom_df(layout: FoldedLayout, nq: int) -> str:
    """geom='auto' policy for the df operator: precomputed df G is the
    faster apply but streams TWO blocked G channels — use it while both
    fit the same comfort budget as the f32 policy (ops.folded.auto_geom),
    else corner mode (2 x 24 floats/cell)."""
    g_bytes = 2 * layout.lv * 6 * nq ** 3 * 4
    return "g" if g_bytes <= 6e9 else "corner"


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------


def host_blocked_G_df(corners_cs: np.ndarray, mask_cs: np.ndarray,
                      layout: FoldedLayout, t: OperatorTables,
                      kappa: float) -> tuple[np.ndarray, np.ndarray]:
    """Host-side f64 geometry for the df folded path: the oracle-precision
    G (fem.geometry.geometry_factors) masked and kappa-folded in f64,
    split into (hi, lo) f32 channels and re-laid block-major per channel
    (the host twin of ops.folded.chunk_blocked_G's transform). O(Lv *
    6 * nq^3) f64 host memory — corner mode is the capacity mode."""
    from ..fem.geometry import geometry_factors

    nq = t.nq
    G, _ = geometry_factors(
        corners_cs.reshape(-1, 2, 2, 2, 3), t.pts1d, t.wts1d
    )
    G = G * (kappa * mask_cs)[:, None, None, None, None]
    Gh = np.asarray(G, np.float32)
    Gl = np.asarray(G - np.asarray(Gh, np.float64), np.float32)

    def block(a):
        a = a.reshape(layout.nblocks, SUBLANES, layout.nl, 6, nq, nq, nq)
        return np.ascontiguousarray(a.transpose(0, 3, 4, 5, 6, 1, 2))

    return block(Gh), block(Gl)


def split_corner_arrays_df(corners_cs: np.ndarray, mask_cs: np.ndarray,
                           layout: FoldedLayout):
    """f64 c-space corner/mask arrays (ghost_corner_arrays) -> blocked df
    corner-mode operands: ((nb, 3, 2,2,2, 8, nl) hi, same lo, (nb, 8, nl)
    mask), all f32. Shared by the single-chip and distributed builders."""
    ch = np.asarray(corners_cs, np.float32)
    cl = np.asarray(corners_cs - np.asarray(ch, np.float64), np.float32)
    cb_h, mb = blocked_corners(ch, mask_cs, layout)
    cb_l, _ = blocked_corners(cl, mask_cs, layout)
    return (np.asarray(cb_h, np.float32), np.asarray(cb_l, np.float32),
            np.asarray(mb, np.float32))


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["Gh", "Gl", "ch", "cl", "cmask", "bc_mask"],
    meta_fields=["n", "degree", "nl", "is_identity", "kappa",
                 "phi0_c", "dphi1_c", "pts_c", "wts_c"],
)
@dataclass(frozen=True)
class FoldedLaplacianDF:
    """Matrix-free df64 Laplacian on folded df vectors (general
    geometry). Geometry is carried as a blocked (hi, lo) G pair (Gh/Gl
    set) or as blocked df corner pairs with the mask (corner mode —
    the capacity default at scale). kappa is compile-time metadata:
    folded into G host-side (g mode) or into the in-kernel geometry
    scale (corner mode)."""

    Gh: jnp.ndarray | None
    Gl: jnp.ndarray | None
    ch: jnp.ndarray | None
    cl: jnp.ndarray | None
    cmask: jnp.ndarray | None
    bc_mask: jnp.ndarray  # (nb, P^3, B) 0/1 Dirichlet marker, f32
    n: tuple[int, int, int]
    degree: int
    nl: int
    is_identity: bool
    kappa: float
    phi0_c: tuple = ()
    dphi1_c: tuple = ()
    pts_c: tuple = ()
    wts_c: tuple = ()

    @property
    def layout(self) -> FoldedLayout:
        return FoldedLayout(n=self.n, degree=self.degree, nl=self.nl)

    @property
    def geom(self):
        if self.Gh is not None:
            return (self.Gh, self.Gl)
        return (self.ch, self.cl, self.cmask)

    @property
    def geom_tables(self):
        if self.Gh is not None:
            return None
        return (np.asarray(self.pts_c), np.asarray(self.wts_c))

    def contrib(self, xm: DF, interpret: bool | None = None) -> DF:
        """Un-bc'd contribution pass on a pre-masked df vector."""
        return folded_cell_apply_df(
            xm, self.geom, self.layout,
            np.asarray(self.phi0_c, np.float64),
            np.asarray(self.dphi1_c, np.float64),
            self.is_identity, self.kappa, interpret=interpret,
            geom_tables=self.geom_tables,
        )

    def apply(self, x: DF) -> DF:
        """y = A @ x with Dirichlet pass-through rows. All masking is
        multiplication by exact 0/1 channels with disjoint-support sums
        (never y + bc*(x - y), whose subtraction rounds)."""
        bc = self.bc_mask
        nbm = 1.0 - bc
        y = self.contrib(DF(x.hi * nbm, x.lo * nbm))
        return DF(y.hi * nbm + bc * x.hi, y.lo * nbm + bc * x.lo)


def build_folded_laplacian_df(
    mesh: BoxMesh,
    degree: int,
    qmode: int,
    rule: str = "gll",
    kappa: float = 2.0,
    tables: OperatorTables | None = None,
    nl: int | None = None,
    geom: str = "auto",
) -> FoldedLaplacianDF:
    """Build the folded df operator: geometry in f64 on the host, split
    into (hi, lo) channels (precomputed G or corner pairs), Dirichlet
    marker folded once. Ghost/pad cells keep the unit-cube placeholder
    corners (invertible Jacobian, zero mask) of the f32 path."""
    if geom not in ("auto", "corner", "g"):
        raise ValueError(f"unknown geom mode {geom!r}")
    t = tables or build_operator_tables(degree, qmode, rule)
    if nl is None and geom != "g":
        forced = folded_df_plan(degree, t.nq)[1]
        if forced is not None:
            geom = forced
    layout = make_layout(mesh.n, degree, t.nq, 4, nl=nl)
    check_tpu_lane_support(layout, degree, qmode)
    if geom == "auto":
        geom = auto_geom_df(layout, t.nq)
    corners_cs, mask_cs = ghost_corner_arrays(layout, mesh.cell_corners)
    Gh = Gl = ch = cl = cm = None
    if geom == "corner":
        cb_h, cb_l, mb = split_corner_arrays_df(corners_cs, mask_cs, layout)
        ch, cl = jnp.asarray(cb_h), jnp.asarray(cb_l)
        cm = jnp.asarray(mb)
    else:
        gh, gl = host_blocked_G_df(corners_cs, mask_cs, layout, t, kappa)
        Gh, Gl = jnp.asarray(gh), jnp.asarray(gl)
    bc = fold_vector(
        np.asarray(boundary_dof_marker(mesh.n, degree), np.float64), layout
    )
    return FoldedLaplacianDF(
        Gh=Gh, Gl=Gl, ch=ch, cl=cl, cmask=cm,
        bc_mask=jnp.asarray(bc, jnp.float32),
        n=mesh.n,
        degree=degree,
        nl=layout.nl,
        is_identity=t.is_identity,
        kappa=float(kappa),
        phi0_c=freeze_table(t.phi0),
        dphi1_c=freeze_table(t.dphi1),
        pts_c=tuple(float(v) for v in t.pts1d),
        wts_c=tuple(float(v) for v in t.wts1d),
    )


# ---------------------------------------------------------------------------
# CG / action (benchmark semantics)
# ---------------------------------------------------------------------------


def folded_cg_solve_df(op: FoldedLaplacianDF, b: DF, nreps: int) -> DF:
    """Fixed-iteration df CG on folded df vectors (x0 = 0, rtol = 0 —
    reference cg.hpp:89-169 semantics), the ops.kron_df.cg_solve_df
    recurrence (including the past-the-df-floor freeze guard) on the
    folded operator. Structural/pad slots are zero in every vector, so
    the compensated dots count real dofs only."""
    floor = jnp.float32(1e-24)
    rnorm0 = df_dot(b, b)
    rnorm0_hi = rnorm0.hi

    def body(_, state):
        x, r, p, rnorm, done = state
        y = op.apply(p)
        alpha = df_div(rnorm, df_dot(p, y))
        x1 = df_axpy(x, alpha, p)
        r1 = df_sub(r, df_scale(y, alpha))
        rnorm1 = df_dot(r1, r1)
        beta = df_div(rnorm1, rnorm)
        p1 = df_add(df_scale(p, beta), r1)
        done1 = jnp.logical_or(done, rnorm1.hi <= floor * rnorm0_hi)

        def keep(new, old):
            return jax.tree_util.tree_map(
                lambda nw, o: jnp.where(done, o, nw), new, old
            )

        return (keep(x1, x), keep(r1, r), keep(p1, p),
                keep(rnorm1, rnorm), done1)

    state = (df_zeros_like(b), b, b, rnorm0, jnp.asarray(False))
    x, *_ = jax.lax.fori_loop(0, nreps, body, state)
    return x


def folded_action_df(op: FoldedLaplacianDF, u: DF, nreps: int) -> DF:
    """nreps df operator applications of the same input (benchmark action
    semantics, laplacian_solver.cpp:119-127), loop-fenced like every
    other action driver so the invariant apply cannot be hoisted."""

    def rep(_, y):
        uu, _ = jax.lax.optimization_barrier((u, y))
        return op.apply(uu)

    return jax.lax.fori_loop(0, nreps, rep, df_zeros_like(u))
