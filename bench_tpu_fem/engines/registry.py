"""The declarative engine registry (ISSUE 16): ONE table for the engine
matrix the drivers grew implicitly — form x precision x geometry x
sharding x nrhs policy, each row carrying its capability predicate, its
VMEM plan ref, its analysis-config refs and the gate reasons its routing
can stamp — plus the full gate-reason vocabulary those routings record.

Everything here is derived FROM by the rest of the system:

  bench/driver.py    backend resolution (`resolve_backend`), engine
                     enables (`engine_available`), every stamped gate
                     reason (`GATE_REASONS` / `gate_reason`), and the
                     exec-cache key (`make_cache_key` via
                     `EngineSpec.cache_key`)
  dist/driver.py     same, for the sharded forms (the overlap resolvers
                     in dist.kron/folded/kron_df pull their reasons here)
  serve/engine.py    `planned_engine_form` + `spec_cache_key` =
                     `planned_form` + `EngineSpec.cache_key`
  serve/cache.py +   both key constructions route through ONE helper,
  serve/artifacts.py so precond/s-step/conv/tuning variants can never
                     alias (tests/test_engine_registry.py pins it)
  analysis/configs.py the shipped-config matrix is `analysis_plan()`
                     rendered into drive closures

The module is import-LEAF by design: stdlib only at module scope; every
reference into jax-heavy modules (plans, serve.cache) is a lazy import
inside the function that needs it, so the registry can sit below
`la/`, `ops/`, `dist/`, `serve/` and `analysis/` without cycles.

Gate-reason discipline: a reason stamped into results/journals MUST be a
registered constant (or a registered template instantiation) — a typo'd
free-text reason can never silently evade the resolvers again
(`is_registered_reason`; tests enforce it for every stamped reason).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

# ---------------------------------------------------------------------------
# Gate-reason vocabulary — every reason any routing layer stamps.
#
# Texts are the EXACT strings the drivers recorded before the registry
# existed (bitwise-stable journals and baselines). Entries with {field}
# placeholders are templates: instantiate via gate_reason(slug, **fmt);
# is_registered_reason matches instantiations structurally.
# ---------------------------------------------------------------------------

GATE_REASONS: dict[str, str] = {
    # -- engine-vs-feature gates (single-chip bench driver) -----------------
    "batched-unfused": (
        "batched multi-RHS (nrhs>1): fused batching is unsupported on this "
        "path (no batched engine form); running the unfused vmapped apply"),
    "checkpoint-engine": (
        "durable checkpointing (checkpoint_every > 0): the fused whole-solve "
        "engine exposes no iteration boundary; running the unfused "
        "checkpointable loop (la.checkpoint)"),
    "convergence-engine": (
        "convergence capture (convergence=True): the fused whole-solve "
        "engine exposes no per-iteration residual to buffer; running the "
        "unfused capture-able loop (la.cg capture=True)"),
    "checkpoint-batched": (
        "batched (nrhs>1) bench paths run whole-batch executables with no "
        "iteration boundary; snapshots disabled for this run"),
    "convergence-checkpoint": (
        "convergence capture is not wired through the checkpointable "
        "chunked loop; capture disabled for this checkpointed run"),
    "convergence-action": (
        "convergence capture applies to CG solves only (action runs carry "
        "no residual); capture disabled"),
    # -- preconditioning gates ----------------------------------------------
    "precond-engine": (
        "preconditioned CG (precond != none): the fused whole-solve engine "
        "bakes the unpreconditioned recurrence; running the unfused "
        "preconditioned loop"),
    "precond-action": (
        "preconditioning applies to CG solves only (action runs have no "
        "residual equation); precond disabled"),
    "precond-folded": (
        "preconditioning is unsupported on the folded (pallas) vector "
        "layout; precond disabled for this run"),
    "precond-checkpoint": (
        "durable checkpointing (checkpoint_every > 0) does not carry the "
        "preconditioned recurrence; precond disabled for this checkpointed "
        "run"),
    "precond-pmg-family": (
        "p-multigrid needs the GLL node family (endpoint nodes carry the "
        "Dirichlet transfer) and a grid-layout operator; precond disabled "
        "for this run"),
    "precond-pmg-degree": (
        "p-multigrid needs degree >= 2 (no coarser level below degree 1); "
        "precond disabled"),
    "precond-batched": (
        "batched (nrhs>1) paths support jacobi preconditioning only "
        "({precond} has no batched cost model); precond disabled"),
    "precond-df": (
        "df (double-float) paths support jacobi preconditioning only "
        "({precond} has no df form); precond disabled for this run"),
    "precond-batched-df": (
        "batched df32 (vmapped whole-solve) has no wired preconditioner; "
        "precond disabled for this run"),
    "precond-pmg-sharded": (
        "sharded p-multigrid transfers are not wired (single-chip only "
        "today); precond disabled for this run"),
    "precond-batched-sharded": (
        "batched sharded CG has no wired preconditioner; precond disabled "
        "for this run"),
    # -- s-step gates --------------------------------------------------------
    "sstep-unsupported": (
        "s-step CG is unsupported on this path (no communication-avoiding "
        "form); running the standard recurrence"),
    "sstep-breakdown": (
        "s-step CG breakdown (ill-conditioned monomial Gram projection or "
        "non-SPD step): re-ran the one-reduction recurrence"),
    "sstep-action": (
        "s-step applies to CG solves only; running the standard action "
        "loop"),
    "sstep-checkpoint": (
        "s-step is not wired through the checkpointable chunked loop; "
        "running the standard recurrence"),
    "sstep-precond": (
        "s-step with preconditioning has no communication-avoiding PCG "
        "form; running the preconditioned recurrence"),
    "sstep-engine": (
        "s-step rides the unfused loop; the fused whole-solve engine bakes "
        "the standard recurrence"),
    "sstep-engine-sharded": (
        "s-step rides the unfused sharded loop; the fused engine bakes the "
        "standard recurrence"),
    "sstep-df": (
        "s-step has no df (double-float) form; running the standard df "
        "recurrence"),
    "sstep-batched-df": (
        "batched df32 has no s-step form; running the standard recurrence"),
    "sstep-batched-sharded": (
        "batched sharded CG has no s-step form; running the fused-dot3 "
        "single-reduction recurrence"),
    "sstep-folded-sharded": (
        "sharded folded (pallas) backend has no s-step form; running the "
        "standard recurrence"),
    "sstep-folded-df": (
        "folded-df pipeline has no s-step form; running the standard "
        "recurrence"),
    # -- SDC audit gates -----------------------------------------------------
    "sdc-no-checkpoint": (
        "the SDC boundary audit rides the iteration-boundary checkpointed "
        "CG loop; set --checkpoint-every > 0 (and --cg) to arm it"),
    "sdc-df": (
        "the SDC boundary audit is not wired through the df (double-float) "
        "checkpointed loop; df32 detection runs in the serve layer's "
        "retire-time audit"),
    "sdc-folded-df": (
        "folded-df pipeline has no checkpointable boundary for the SDC "
        "audit to ride; audit disabled for this run"),
    # -- df (double-float) pipeline gates -----------------------------------
    "checkpoint-folded-df": (
        "folded-df pipeline has no checkpointable loop form; snapshots "
        "disabled for this run"),
    "convergence-folded-df": (
        "folded-df pipeline has no capture-able loop form; convergence "
        "capture disabled for this run"),
    "convergence-batched-df": (
        "batched df32 (vmapped whole-solve) has no wired capture form; "
        "convergence capture disabled for this run"),
    "df-backend-folded": (
        "perturbed f64_impl='df32' runs the folded pallas-df path; "
        "--backend {backend} is not supported with it"),
    "df-backend-kron": (
        "f64_impl='df32' runs the kron path on uniform meshes; --backend "
        "{backend} is not supported with it"),
    "df-batched-folded": (
        "batched multi-RHS (nrhs>1) is unsupported on the folded df "
        "pipeline; XLA-emulated batched fallback"),
    "df-plan-unsupported": (
        "folded-df plan: degree {degree} qmode {qmode} exceeds the df VMEM "
        "model (no 128-lane folded df kernel)"),
    "df-compile-failed": "folded-df compile failed: {error}",
    # -- sharded (dist driver) gates ----------------------------------------
    "kron-perturbed": (
        "kron backend requires an unperturbed (uniform) box mesh; use the "
        "xla/pallas backends for perturbed geometry"),
    "convergence-batched-sharded": (
        "batched sharded CG has no wired capture form; convergence capture "
        "disabled for this run"),
    "convergence-batched-df-sharded": (
        "batched sharded df CG has no wired capture form; convergence "
        "capture disabled for this run"),
    "convergence-folded-sharded": (
        "sharded folded (pallas) backend has no capture-able unfused CG "
        "form; convergence capture disabled for this run"),
    "convergence-folded-df-sharded": (
        "sharded folded-df pipeline has no capture-able loop form; "
        "convergence capture disabled for this run"),
    "checkpoint-folded-sharded": (
        "sharded folded (pallas) backend has no checkpointable unfused "
        "form; snapshots disabled for this run"),
    "batched-sharded-action": (
        "batched multi-RHS (nrhs>1) sharded runs require --cg; batched "
        "sharded action is unsupported"),
    "batched-sharded-folded": (
        "batched multi-RHS sharded CG supports the kron and xla backends; "
        "the folded (pallas) sharded batch form is unsupported"),
    "batched-sharded-df-action": (
        "batched multi-RHS (nrhs>1) sharded df runs require --cg; batched "
        "sharded df action is unsupported"),
    # -- communication-overlap form gates (dist resolvers) ------------------
    "overlap-engine-kron": (
        "overlap form rides the fused engine; the engine is unavailable "
        "here (non-pallas impl or ring past every scoped-VMEM tier)"),
    "overlap-fusion-wall-kron": (
        "ext2d overlap keeps the whole-slab r update as one XLA pass; this "
        "shard is past the whole-vector fusion wall "
        "(PALLAS_UPDATE_MIN_DOFS)"),
    "overlap-engine-folded": (
        "overlap form rides the fused folded engine; the engine is "
        "unavailable here (per-shard input ring past MAX_RING_BLOCKS or "
        "non-f32)"),
    "overlap-plan-folded": "folded overlap plan gate",
    "overlap-engine-df": (
        "overlap form rides the fused df engine; the engine is unavailable "
        "here (non-TPU backend or ring past every scoped-VMEM tier)"),
    "overlap-fusion-wall-df": (
        "df overlap keeps the whole-slab df r update as one XLA pass; this "
        "shard is past the whole-vector fusion wall "
        "(PALLAS_UPDATE_MIN_DOFS)"),
    # -- serve capability gates (SolveSpec.validate) ------------------------
    "serve-precision": "precision {precision} unsupported {precisions}",
    "serve-df32-perturbed": (
        "df32 serving requires a uniform mesh (the kron df path); "
        "perturbed f64-class serving is unsupported here"),
    "serve-ndofs-cap": (
        "ndofs {ndofs} exceeds the serving cap {cap} (engine.MAX_NDOFS) "
        "— unsupported"),
    "serve-f64-x64": (
        "precision 'f64' needs jax_enable_x64 (the serve CLI enables it; "
        "in-process callers must)"),
    # -- bf16 / mixed-precision refinement gates (ISSUE 17) -----------------
    "bf16-fused": (
        "bf16 has no fused Mosaic ring yet (the bf16 agenda stage arms the "
        "hardware path); running the unfused bf16-stream / f32-accumulate "
        "composition"),
    "bf16-float-bits": (
        "bf16 precision streams the f32-assembled operator at bfloat16; "
        "--float {bits} is unsupported with it (use --float 32)"),
    "bf16-backend": (
        "bf16 streaming wraps the kron (uniform) and xla (perturbed) "
        "operators; --backend {backend} is not supported with it"),
    "bf16-sharded": (
        "bf16 precision is single-chip today (no sharded bf16-stream "
        "form); running the sharded f32 path with the reason recorded"),
    "checkpoint-bf16": (
        "durable checkpointing is not wired through the bf16-stream loop; "
        "snapshots disabled for this run"),
    "refine-action": (
        "iterative refinement applies to CG solves only (action runs solve "
        "nothing); refine disabled"),
    "refine-batched": (
        "batched multi-RHS (nrhs>1) has no iterative-refinement form; "
        "refine disabled for this run"),
    "convergence-refine": (
        "convergence capture rides the refinement outer loop's own "
        "rel-residual history; per-iteration inner capture disabled"),
    "precond-bf16": (
        "bf16 paths support jacobi preconditioning only ({precond} has "
        "no bf16 form); precond disabled for this run"),
    # -- tuning-database fallback reasons (engines.autotune) ----------------
    "tuning-disabled": (
        "tuning lookup disabled (no tuning database configured); registry "
        "defaults in effect"),
    "tuning-entry-missing": (
        "tuning database holds no entry for this key; registry defaults "
        "in effect"),
    "tuning-db-invalid": (
        "tuning database failed validation (magic/CRC/version/key "
        "equality); counted fallback, registry defaults in effect"),
    # -- overload brownout (ISSUE 18) ---------------------------------------
    "brownout-precision": (
        "brownout level {level}: sustained SLO burn stepped this request "
        "down the registry precision ladder ({from_p} -> {to_p}); the "
        "response carries degraded provenance until hysteresis clears"),
    # -- operator-zoo form gates (ISSUE 20) ---------------------------------
    "form-df": (
        "the {form} form has no double-float pipeline (df32 composes the "
        "kron/pallas poisson engines only); use --float 64 native or f32"),
    "form-sharded": (
        "the {form} form is single-chip today (no sharded form action); "
        "run with ndevices=1"),
    "form-batched": (
        "driver-side batched multi-RHS (nrhs>1) is poisson-only; the "
        "{form} form serves batched lanes through the serve layer instead"),
    "form-backend": (
        "the {form} form runs the general sum-factorised einsum action; "
        "--backend {backend} is not supported with it"),
    "form-checkpoint": (
        "durable checkpointing/SDC boundary audits are not wired through "
        "the {form} form's CG loop; snapshots disabled for this run"),
    "form-sstep": (
        "s-step CG is poisson-only (the Gram projection assumes the "
        "flagship SPD operator); running the standard recurrence for the "
        "{form} form"),
    "form-precond": (
        "preconditioning is not wired through the {form} form's CG loop; "
        "precond disabled for this run"),
    "helmholtz-precond": (
        "the helmholtz form is indefinite (stiffness - k^2 mass): the SPD "
        "preconditioned-CG contract does not hold, precond disabled and "
        "breakdown taxonomy armed"),
    "form-bf16": (
        "the {form} form has no bf16-stream/refinement ladder rung; use "
        "f32 or f64 precision"),
}

# Template slugs contain {field} placeholders; everything else is a
# verbatim constant.
_TEMPLATE_SLUGS = tuple(
    slug for slug, text in GATE_REASONS.items() if "{" in text)

_TEMPLATE_RES = {
    slug: re.compile(
        "^" + re.sub(r"\\\{[a-z_]+\\\}", "(.+?)",
                     re.escape(GATE_REASONS[slug])) + "$",
        re.DOTALL)
    for slug in _TEMPLATE_SLUGS
}


def gate_reason(slug: str, **fmt) -> str:
    """The registered reason text for `slug` — templates are instantiated
    with `fmt` (a missing field raises KeyError loudly: a half-formatted
    reason must never reach a journal)."""
    text = GATE_REASONS[slug]
    if "{" in text:
        return text.format(**fmt)
    return text


def is_registered_reason(text) -> str | None:
    """The slug whose constant (or template) produced `text`, else None.
    The journal/stamp hygiene test runs every recorded `*_gate_reason` /
    `*_fallback_reason` through this."""
    if not isinstance(text, str):
        return None
    for slug, canon in GATE_REASONS.items():
        if "{" not in canon and text == canon:
            return slug
    for slug in _TEMPLATE_SLUGS:
        if _TEMPLATE_RES[slug].match(text):
            return slug
    return None


# ---------------------------------------------------------------------------
# The engine-form vocabulary (bench.driver.record_engine's unified names)
# ---------------------------------------------------------------------------

ENGINE_FORM_NAMES = {
    "one": "one_kernel",
    "chunked": "chunked",
    "one_batched": "one_kernel_batched",
}

#: every achieved-form name any driver records
ALL_FORMS = ("one_kernel", "chunked", "one_kernel_batched", "halo",
             "ext2d", "halo_overlap", "ext2d_overlap", "unfused")

PRECISIONS = ("f32", "f64", "df32", "bf16")
GEOMETRIES = ("uniform", "perturbed")


# ---------------------------------------------------------------------------
# EngineSpec rows — the declarative matrix
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class EngineSpec:
    """One engine family: the forms it can achieve, the (precision,
    geometry, sharding, nrhs) slice it serves, the capability predicate
    and VMEM plan that admit it, the analysis configs that verify it,
    the gate-reason slugs its routing can stamp, and the tunable
    parameters the autotuner may override (with their registry
    defaults)."""

    name: str
    forms: tuple            # achieved forms, best-first
    precision: str          # "f32" | "df32" | "f64" | "any"
    geometry: str           # "uniform" | "perturbed" | "any"
    sharding: str           # "single" | "sharded" | "any"
    backend: str            # "kron" | "pallas" | "xla" | "any"
    nrhs: str               # "1" | "bucketed" | "any"
    enabler: str | None = None   # key into _ENABLERS (None: always on)
    plan: str | None = None      # key into _PLANS (VMEM/tile plan)
    analysis: tuple = ()         # analysis_plan row refs (see below)
    gate_slugs: tuple = ()       # reasons this family's routing stamps
    tunables: tuple = ()         # autotunable parameter names
    defaults: dict = field(default_factory=dict)  # tunable defaults
    notes: str = ""

    # -- capability ---------------------------------------------------------

    def available(self, **ctx) -> bool:
        """Run this row's capability predicate (lazy import — the
        predicates live next to their kernels). Rows without an enabler
        are unconditionally available (the unfused fallback)."""
        if self.enabler is None:
            return True
        return _ENABLERS[self.enabler](**ctx)

    def plan_fn(self):
        """The VMEM/tile plan callable for this row (lazy), or None."""
        if self.plan is None:
            return None
        return _PLANS[self.plan]()

    # -- the ONE cache-key helper (exec cache + artifact store) -------------

    @staticmethod
    def cache_key(*, degree: int, cell_shape, precision: str, geom: str,
                  engine_form: str, nrhs_bucket: int, device_mesh,
                  nreps: int = 0, form: str = "poisson"):
        """serve.cache.ExecutableKey construction — the single helper
        both the bench driver's exec-cache keys and the serve layer's
        cache/artifact keys derive from, so the two key spaces can never
        drift apart structurally (variants are distinguished INSIDE
        engine_form / nrhs_bucket / device_mesh, pinned by the collision
        test). `form` is the weak-form axis (ISSUE 20): executables for
        different registry forms must never alias."""
        from ..serve.cache import ExecutableKey

        return ExecutableKey(
            degree=int(degree),
            cell_shape=tuple(int(c) for c in cell_shape),
            precision=str(precision),
            geom=str(geom),
            engine_form=str(engine_form),
            nrhs_bucket=int(nrhs_bucket),
            device_mesh=tuple(device_mesh),
            nreps=int(nreps),
            form=str(form),
        )


def make_cache_key(**kw):
    """Module-level alias of EngineSpec.cache_key (same signature)."""
    return EngineSpec.cache_key(**kw)


def bench_engine_form(backend: str, form: str, kind: str, qmode: int,
                      use_gauss: bool) -> str:
    """The bench driver's packed engine_form key slot: backend, planned
    form, solve kind (cg/action + conv/precond/s-step markers),
    quadrature mode and rule — everything form-shaped that the flat
    ExecutableKey fields don't carry. One packing function so driver
    variants (precond/s-step/conv) can never alias (the collision
    test covers it)."""
    return (f"{backend}|{form}|{kind}|q{qmode}"
            f"|{'gauss' if use_gauss else 'gll'}")


# -- capability predicates (lazy, living next to their kernels) -------------

def _kron_engine_available(*, grid_shape, degree, dtype, **_):
    import jax

    from ..ops.kron_cg import supports_kron_cg_engine

    return (jax.default_backend() == "tpu"
            and supports_kron_cg_engine(grid_shape, degree, dtype))


def _kron_engine_batched_available(*, grid_shape, degree, nrhs, **_):
    from ..ops.kron_cg import engine_plan_batched

    return engine_plan_batched(grid_shape, degree, nrhs)[0] != "unfused"


def _folded_engine_available(*, op, **_):
    from ..ops.folded_cg import supports_cg_engine

    return supports_cg_engine(op)


def _folded_df_available(*, degree, nq, **_):
    from ..ops.folded_df import folded_df_plan

    return bool(folded_df_plan(degree, nq)[0])


def _dist_kron_engine_available(*, op, **_):
    from ..dist.kron import resolve_kron_engine

    return resolve_kron_engine(op)


def _dist_folded_engine_available(*, op, **_):
    from ..dist.folded import resolve_folded_engine

    return resolve_folded_engine(op)


def _dist_df_engine_available(*, op, **_):
    from ..dist.kron_df import resolve_df_engine

    return resolve_df_engine(op)


_ENABLERS = {
    "kron_engine": _kron_engine_available,
    "kron_engine_batched": _kron_engine_batched_available,
    "folded_engine": _folded_engine_available,
    "folded_df": _folded_df_available,
    "dist_kron_engine": _dist_kron_engine_available,
    "dist_folded_engine": _dist_folded_engine_available,
    "dist_df_engine": _dist_df_engine_available,
}


def _plans():
    # keys -> zero-arg lazy importers returning the plan callable
    return {
        "kron": lambda: _imp("..ops.kron_cg", "engine_plan"),
        "kron_batched": lambda: _imp("..ops.kron_cg", "engine_plan_batched"),
        "kron_df": lambda: _imp("..ops.kron_cg_df", "engine_plan_df"),
        "folded": lambda: _imp("..ops.folded", "pallas_plan"),
        "folded_df": lambda: _imp("..ops.folded_df", "folded_df_plan"),
        "dist_kron": lambda: _imp("..dist.kron_cg",
                                  "dist_kron_engine_plan"),
        "dist_kron_df": lambda: _imp("..dist.kron_cg_df",
                                     "dist_df_engine_plan"),
        "dist_folded": lambda: _imp("..dist.folded_cg",
                                    "dist_folded_engine_plan"),
        "bf16": lambda: _imp("..ops.bf16", "engine_plan_bf16"),
    }


def _imp(mod: str, attr: str):
    import importlib

    return getattr(importlib.import_module(mod, __package__), attr)


_PLANS = _plans()


# -- the rows ---------------------------------------------------------------

#: serve's continuous-batching iteration chunk (iterations per compiled
#: step call) — the registry default the autotuner may override per key
DEFAULT_ITER_CHUNK = 4

#: inner-CG budget per refinement outer iteration (la.refine) — the
#: registry default the autotuner may override per key: each outer
#: contracts the error by roughly the bf16 inner solve's accuracy, so
#: a larger budget buys fewer (hi-precision) outers at more (bf16)
#: inners — exactly the trade the sweep adjudicates by time_to_rtol
DEFAULT_REFINE_INNER_ITERS = 16

ENGINE_SPECS: tuple[EngineSpec, ...] = (
    EngineSpec(
        name="kron_fused",
        forms=("one_kernel", "chunked"),
        precision="f32", geometry="uniform", sharding="single",
        backend="kron", nrhs="1",
        enabler="kron_engine", plan="kron",
        analysis=(("kron_engine_d{d}", "kron_engine", "d:(1,3,4,6)",
                   {"chunked": False}),
                  ("kron_engine_d{d}_chunked", "kron_engine", "d:(3,4)",
                   {"chunked": True}),
                  ("kron_update_pass", "kron_update_pass", None, {}),
                  ("kron_3stage_d3", "kron_3stage", None, {})),
        gate_slugs=("checkpoint-engine", "convergence-engine",
                    "precond-engine", "sstep-engine", "sdc-no-checkpoint"),
        tunables=("iter_chunk", "window_kib"),
        defaults={"iter_chunk": DEFAULT_ITER_CHUNK, "window_kib": 0},
        notes="fused whole-solve delay-ring CG on the Kronecker fast path"),
    EngineSpec(
        name="kron_fused_batched",
        forms=("one_kernel_batched",),
        precision="f32", geometry="uniform", sharding="single",
        backend="kron", nrhs="bucketed",
        enabler="kron_engine_batched", plan="kron_batched",
        analysis=(("kron_batched_engine_d{d}_r{r}", "kron_batched_engine",
                   "dr:((1,4),(3,2),(3,4),(3,8),(3,16),(6,4))", {}),),
        gate_slugs=("batched-unfused", "checkpoint-batched",
                    "precond-batched", "convergence-engine"),
        tunables=("iter_chunk",),
        defaults={"iter_chunk": DEFAULT_ITER_CHUNK},
        notes="nrhs-native fused batched ring (serve's f32-uniform path)"),
    EngineSpec(
        name="kron_fused_df",
        forms=("one_kernel", "chunked"),
        precision="df32", geometry="uniform", sharding="single",
        backend="kron", nrhs="1",
        plan="kron_df",
        analysis=(("kron_df_engine_d{d}", "kron_df_engine", "d:(1,3,4,6)",
                   {"chunked": False}),
                  ("kron_df_engine_d{d}_chunked", "kron_df_engine",
                   "d:(3,4)", {"chunked": True}),
                  ("kron_df_update_pass", "kron_df_update_pass", None, {})),
        gate_slugs=("sdc-df", "sstep-df", "precond-df", "df-backend-kron",
                    "convergence-checkpoint"),
        notes="double-float fused CG on the uniform kron path"),
    EngineSpec(
        name="folded_fused",
        forms=("one_kernel",),
        precision="f32", geometry="perturbed", sharding="single",
        backend="pallas", nrhs="1",
        enabler="folded_engine", plan="folded",
        analysis=(("folded_engine_{g}_d{d}", "folded_engine",
                   "gd:(g,corner)x(1,3,4,6)", {}),
                  ("folded_apply_{g}_d{d}", "folded_apply",
                   "gd:(g,corner)x(1,3,4,6)", {})),
        gate_slugs=("precond-folded", "checkpoint-engine",
                    "convergence-engine", "sstep-engine"),
        notes="folded general-geometry Pallas kernels (G/corner modes)"),
    EngineSpec(
        name="folded_df",
        forms=("unfused",),
        precision="df32", geometry="perturbed", sharding="single",
        backend="pallas", nrhs="1",
        enabler="folded_df", plan="folded_df",
        analysis=(("folded_df_apply_{g}_d{d}", "folded_df_apply",
                   "gd:(g,corner)x(1,3,6)", {}),),
        gate_slugs=("checkpoint-folded-df", "convergence-folded-df",
                    "sdc-folded-df", "sstep-folded-df",
                    "df-backend-folded", "df-batched-folded",
                    "df-plan-unsupported", "df-compile-failed"),
        notes="perturbed double-float pipeline (deliberately unfused)"),
    EngineSpec(
        name="serve_batched",
        forms=("one_kernel_batched", "unfused"),
        precision="any", geometry="any", sharding="single",
        backend="any", nrhs="bucketed",
        plan="kron_batched",
        analysis=(("serve_batched_apply_corner_d{d}", "serve_batched_apply",
                   "d:(1,3,6)", {"g": "corner"}),
                  ("serve_batched_kron_3stage_d3",
                   "serve_batched_kron_3stage", None, {})),
        gate_slugs=("serve-precision", "serve-df32-perturbed",
                    "serve-ndofs-cap", "serve-f64-x64"),
        tunables=("iter_chunk",),
        defaults={"iter_chunk": DEFAULT_ITER_CHUNK},
        notes="serving layer's padded-bucket batched solver"),
    EngineSpec(
        name="dist_kron",
        forms=("halo", "ext2d", "halo_overlap", "ext2d_overlap"),
        precision="f32", geometry="uniform", sharding="sharded",
        backend="kron", nrhs="any",
        enabler="dist_kron_engine", plan="dist_kron",
        analysis=(("dist_kron_engine_d{d}", "dist_kron_engine", "d:(3,5)",
                   {"min_devices": 4}),
                  ("dist_kron_engine_ext2d", "dist_kron_engine_3d", None,
                   {"min_devices": 8}),
                  ("dist_kron_overlap_d3", "dist_kron_overlap", None,
                   {"args": (3, False), "min_devices": 4}),
                  ("dist_kron_overlap_ext2d", "dist_kron_overlap", None,
                   {"args": (3, True), "min_devices": 8})),
        gate_slugs=("kron-perturbed", "overlap-engine-kron",
                    "overlap-fusion-wall-kron", "sstep-engine-sharded",
                    "precond-pmg-sharded", "batched-sharded-action"),
        notes="distributed fused delay-ring engine (plane-halo / ext2d)"),
    EngineSpec(
        name="dist_kron_df",
        forms=("halo", "ext2d", "halo_overlap", "ext2d_overlap"),
        precision="df32", geometry="uniform", sharding="sharded",
        backend="kron", nrhs="any",
        enabler="dist_df_engine", plan="dist_kron_df",
        analysis=(("dist_kron_df_halo", "dist_kron_df", None,
                   {"args": ((4, 1, 1),), "min_devices": 4}),
                  ("dist_kron_df_ext2d", "dist_kron_df", None,
                   {"args": ((2, 2, 2),), "min_devices": 8}),
                  ("dist_kron_df_overlap_halo", "dist_kron_df_overlap",
                   None, {"args": ((4, 1, 1),), "min_devices": 4}),
                  ("dist_kron_df_overlap_ext2d", "dist_kron_df_overlap",
                   None, {"args": ((2, 2, 2),), "min_devices": 8})),
        gate_slugs=("overlap-engine-df", "overlap-fusion-wall-df",
                    "batched-sharded-df-action",
                    "convergence-batched-df-sharded"),
        notes="distributed double-float fused engine"),
    EngineSpec(
        name="dist_folded",
        forms=("halo", "halo_overlap"),
        precision="f32", geometry="perturbed", sharding="sharded",
        backend="pallas", nrhs="1",
        enabler="dist_folded_engine", plan="dist_folded",
        analysis=(("dist_folded_engine", "dist_folded_engine", None,
                   {"min_devices": 2}),
                  ("dist_folded_overlap", "dist_folded_overlap", None,
                   {"min_devices": 2})),
        gate_slugs=("overlap-engine-folded", "overlap-plan-folded",
                    "checkpoint-folded-sharded", "convergence-folded-sharded",
                    "sstep-folded-sharded", "batched-sharded-folded",
                    "convergence-folded-df-sharded"),
        notes="distributed folded general-geometry engine"),
    EngineSpec(
        name="kron_bf16",
        forms=("unfused",),
        precision="bf16", geometry="uniform", sharding="single",
        backend="kron", nrhs="1",
        plan="bf16",
        analysis=(("bf16_apply_d{d}", "bf16_apply", "d:(3,)", {}),),
        gate_slugs=("bf16-fused", "bf16-float-bits", "checkpoint-bf16",
                    "sstep-unsupported", "precond-bf16"),
        tunables=("iter_chunk", "window_kib"),
        defaults={"iter_chunk": DEFAULT_ITER_CHUNK, "window_kib": 0},
        notes="bf16-stream / f32-accumulate kron apply (half HBM bytes; "
              "16x128-tile VMEM quantum)"),
    EngineSpec(
        name="xla_bf16",
        forms=("unfused",),
        precision="bf16", geometry="perturbed", sharding="single",
        backend="xla", nrhs="1",
        plan="bf16",
        analysis=(("bf16_apply_perturbed_d{d}", "bf16_apply_perturbed",
                   "d:(3,)", {}),),
        gate_slugs=("bf16-fused", "bf16-backend", "bf16-float-bits",
                    "checkpoint-bf16", "sstep-unsupported", "precond-bf16"),
        notes="bf16-stream perturbed-geometry einsum apply (G streamed "
              "at bfloat16, f32 accumulate)"),
    EngineSpec(
        name="bf16_refine",
        forms=("unfused",),
        precision="bf16", geometry="any", sharding="single",
        backend="any", nrhs="1",
        plan="bf16",
        analysis=(("bf16_refine_d{d}", "bf16_refine", "d:(3,)", {}),),
        gate_slugs=("refine-action", "refine-batched", "convergence-refine",
                    "bf16-sharded", "bf16-float-bits", "precond-bf16"),
        tunables=("refine_inner_iters", "iter_chunk"),
        defaults={"refine_inner_iters": DEFAULT_REFINE_INNER_ITERS,
                  "iter_chunk": DEFAULT_ITER_CHUNK},
        notes="mixed-precision iterative refinement / flexible PCG: bf16 "
              "hot-loop applies, hi-precision outer correction to "
              "f64-class rtol (la.refine)"),
    EngineSpec(
        name="forms_xla",
        forms=("unfused",),
        precision="any", geometry="any", sharding="single",
        backend="xla", nrhs="1",
        gate_slugs=("form-df", "form-sharded", "form-batched",
                    "form-backend", "form-checkpoint", "form-sstep",
                    "form-precond", "helmholtz-precond", "form-bf16"),
        notes="operator-zoo weak forms (mass/helmholtz/varkappa/heat): the "
              "general sum-factorised form action (forms.operators); every "
              "unsupported form x engine combination stamps one of this "
              "row's slugs"),
    EngineSpec(
        name="xla_unfused",
        forms=("unfused",),
        precision="any", geometry="any", sharding="any",
        backend="any", nrhs="any",
        gate_slugs=("batched-unfused", "convergence-action", "sstep-action",
                    "precond-action", "sstep-unsupported", "sstep-breakdown",
                    "sstep-checkpoint", "sstep-precond",
                    "convergence-checkpoint", "precond-checkpoint",
                    "precond-pmg-family", "precond-pmg-degree",
                    "sdc-no-checkpoint", "checkpoint-batched",
                    "precond-batched-df", "convergence-batched-df",
                    "sstep-batched-df", "sstep-batched-sharded",
                    "precond-batched-sharded",
                    "convergence-batched-sharded"),
        notes="the universal unfused composition — every gate lands here"),
)

_BY_NAME = {s.name: s for s in ENGINE_SPECS}


def specs(**filters) -> list[EngineSpec]:
    """Registry rows matching every given field filter; "any" on a row
    matches every requested value (specs(precision="f32") includes the
    xla_unfused row)."""
    out = []
    for s in ENGINE_SPECS:
        ok = True
        for k, want in filters.items():
            have = getattr(s, k)
            if isinstance(have, str) and have == "any":
                continue
            if isinstance(have, tuple):
                if want not in have:
                    ok = False
                    break
            elif have != want:
                ok = False
                break
        if ok:
            out.append(s)
    return out


def spec(name: str) -> EngineSpec:
    return _BY_NAME[name]


def degradation_ladder(start: str = "f32") -> tuple:
    """The brownout precision ladder (ISSUE 18): rung 0 is the fleet's
    normal serving precision, each further rung a cheaper precision the
    fleet may step down to under sustained SLO burn. A rung exists ONLY
    because a registry row explicitly serves that precision — the fleet
    carries zero hand-wired capability branches; deregistering the bf16
    row removes the rung with no fleet change. Today: f32 -> bf16 (the
    bf16_refine row — half-bandwidth applies, refined answers)."""
    ladder = [start]
    if any(s.precision == "bf16" for s in ENGINE_SPECS):
        ladder.append("bf16")
    return tuple(ladder)


# ---------------------------------------------------------------------------
# Routing resolvers the drivers derive from
# ---------------------------------------------------------------------------

def resolve_backend(backend: str, float_bits: int, uniform: bool = False,
                    degree: int = 3, qmode: int = 1) -> str:
    """'auto' backend resolution (moved verbatim from bench.driver —
    both drivers now call this one function):

    - uniform (unperturbed) mesh -> 'kron': the exact Kronecker-sum fast
      path (ops.kron), any dtype — no geometry tensor, ~2x the folded
      kernel's CG rate;
    - perturbed mesh, f32 on TPU, if the folded kernels fit full 128-lane
      blocks (G streaming through degree 3 qmode 1; corner mode extends
      that to degree 4, and its plane-streamed form to degree 5 qmode 1 —
      ops.folded.pallas_geom_constraint) -> 'pallas' (the folded general
      kernel);
    - otherwise 'xla' (einsum path; Mosaic has no f64, CPU runs use einsum,
      interpret-mode Pallas is for tests).
    """
    import jax

    if backend != "auto":
        return backend
    if uniform:
        return "kron"
    if float_bits == 32 and jax.default_backend() == "tpu":
        from ..ops.folded import pallas_geom_constraint

        nq = degree + qmode + 1
        if pallas_geom_constraint(degree, nq, 4)[0]:
            return "pallas"
    return "xla"


def planned_engine_form(precision: str, geom: str, ndofs: int,
                        degree: int, bucket: int) -> str:
    """The engine form a serving compile will pick — a deterministic
    function of the spec slice, so it can be part of the cache key: the
    fused nrhs-native kron ring for f32 uniform specs whose bucket fits
    the per-bucket VMEM plan (ops.kron_cg.engine_plan_batched), else the
    unfused vmapped composition. Unified vocabulary
    (bench.driver.record_engine). serve.engine.planned_engine_form is a
    thin wrapper over this."""
    if precision == "f32" and geom == "uniform":
        from ..mesh.dofmap import dof_grid_shape
        from ..mesh.sizing import compute_mesh_size

        n = compute_mesh_size(ndofs, degree)
        grid = dof_grid_shape(n, degree)
        if _ENABLERS["kron_engine_batched"](
                grid_shape=grid, degree=degree, nrhs=bucket):
            return "one_kernel_batched"
    return "unfused"


def engine_available(name: str, **ctx) -> bool:
    """Capability probe for one registry row by name — the drivers'
    engine-enable decisions route through this (the predicate itself
    lives next to the kernel; the registry binds name -> predicate)."""
    return _BY_NAME[name].available(**ctx)


# ---------------------------------------------------------------------------
# The analysis-config derivation (analysis/configs.py renders this)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class AnalysisRef:
    """One shipped analysis config: name, the drive key
    (analysis.configs maps it to a trace-only drive function), its
    positional args, and the device floor."""

    name: str
    drive: str
    args: tuple = ()
    min_devices: int = 1


def analysis_plan() -> tuple[AnalysisRef, ...]:
    """The shipped-config matrix as declarative rows, in the exact
    order analysis.configs shipped before the registry existed (the
    parity test pins the rendered names against the frozen list)."""
    rows: list[AnalysisRef] = []
    add = rows.append
    # kron f32 engine: plan cross-check degrees {1, 3, 6} + the shipped
    # degree-4 case and the Mosaic-reject chunked retry forms.
    for d in (1, 3, 4, 6):
        add(AnalysisRef(f"kron_engine_d{d}", "kron_engine", (d, False)))
    for d in (3, 4):
        add(AnalysisRef(f"kron_engine_d{d}_chunked", "kron_engine",
                        (d, True)))
    add(AnalysisRef("kron_update_pass", "kron_update_pass"))
    add(AnalysisRef("kron_3stage_d3", "kron_3stage"))
    # folded f32: engine + fused apply, both geometry modes, degrees
    # {1, 3, 6} (+4, the forced-corner boundary case).
    for geom in ("g", "corner"):
        for d in (1, 3, 4, 6):
            add(AnalysisRef(f"folded_engine_{geom}_d{d}", "folded_engine",
                            (geom, d)))
            add(AnalysisRef(f"folded_apply_{geom}_d{d}", "folded_apply",
                            (geom, d)))
    # kron df engine, degrees {1, 3, 6} + degree-4 + chunked forms.
    for d in (1, 3, 4, 6):
        add(AnalysisRef(f"kron_df_engine_d{d}", "kron_df_engine",
                        (d, False)))
    for d in (3, 4):
        add(AnalysisRef(f"kron_df_engine_d{d}_chunked", "kron_df_engine",
                        (d, True)))
    add(AnalysisRef("kron_df_update_pass", "kron_df_update_pass"))
    # folded df apply, both geometry modes, degrees {1, 3, 6}.
    for geom in ("g", "corner"):
        for d in (1, 3, 6):
            add(AnalysisRef(f"folded_df_apply_{geom}_d{d}",
                            "folded_df_apply", (geom, d)))
    # serve-layer batched (vmapped) applies + the uniform kron twin.
    for d in (1, 3, 6):
        add(AnalysisRef(f"serve_batched_apply_corner_d{d}",
                        "serve_batched_apply", ("corner", d)))
    add(AnalysisRef("serve_batched_kron_3stage_d3",
                    "serve_batched_kron_3stage"))
    # the nrhs-native fused batched engine: the serve-bucket sweep at
    # degree 3 plus the degree plan-estimator cross-check at nrhs=4.
    for d, r in ((1, 4), (3, 2), (3, 4), (3, 8), (3, 16), (6, 4)):
        add(AnalysisRef(f"kron_batched_engine_d{d}_r{r}",
                        "kron_batched_engine", (d, r)))
    # distributed forms (8 virtual CPU devices).
    for d in (3, 5):
        add(AnalysisRef(f"dist_kron_engine_d{d}", "dist_kron_engine",
                        (d,), min_devices=4))
    add(AnalysisRef("dist_kron_engine_ext2d", "dist_kron_engine_3d",
                    min_devices=8))
    add(AnalysisRef("dist_kron_df_halo", "dist_kron_df", ((4, 1, 1),),
                    min_devices=4))
    add(AnalysisRef("dist_kron_df_ext2d", "dist_kron_df", ((2, 2, 2),),
                    min_devices=8))
    add(AnalysisRef("dist_folded_engine", "dist_folded_engine",
                    min_devices=2))
    # communication-overlapped engine forms: the full overlapped CG
    # loops traced end to end.
    add(AnalysisRef("dist_kron_overlap_d3", "dist_kron_overlap",
                    (3, False), min_devices=4))
    add(AnalysisRef("dist_kron_overlap_ext2d", "dist_kron_overlap",
                    (3, True), min_devices=8))
    add(AnalysisRef("dist_kron_df_overlap_halo", "dist_kron_df_overlap",
                    ((4, 1, 1),), min_devices=4))
    add(AnalysisRef("dist_kron_df_overlap_ext2d", "dist_kron_df_overlap",
                    ((2, 2, 2),), min_devices=8))
    add(AnalysisRef("dist_folded_overlap", "dist_folded_overlap",
                    min_devices=2))
    # bf16 mixed-precision rows (ISSUE 17): stream-parity applies on
    # both geometry paths + the refinement driver traced end to end.
    add(AnalysisRef("bf16_apply_d3", "bf16_apply", (3,)))
    add(AnalysisRef("bf16_apply_perturbed_d3", "bf16_apply_perturbed",
                    (3,)))
    add(AnalysisRef("bf16_refine_d3", "bf16_refine", (3,)))
    return tuple(rows)


# ---------------------------------------------------------------------------
# Registry rendering (the `python -m bench_tpu_fem.bench engines` CLI)
# ---------------------------------------------------------------------------

def render_registry(tuning_db=None) -> str:
    """Human-readable registry table: one block per row (slice, forms,
    capability/plan refs, gate vocabulary, tunables with tuned-vs-default
    values when a TuningDB is handed in)."""
    lines = []
    lines.append("engine registry — %d rows, %d gate reasons"
                 % (len(ENGINE_SPECS), len(GATE_REASONS)))
    lines.append("")
    for s in ENGINE_SPECS:
        lines.append(f"[{s.name}]")
        lines.append(f"  slice    : precision={s.precision} "
                     f"geometry={s.geometry} sharding={s.sharding} "
                     f"backend={s.backend} nrhs={s.nrhs}")
        lines.append(f"  forms    : {', '.join(s.forms)}")
        lines.append(f"  enabler  : {s.enabler or '(always)'}"
                     f"   plan: {s.plan or '(none)'}")
        if s.analysis:
            lines.append(f"  analysis : {len(s.analysis)} config group(s)")
        if s.gate_slugs:
            lines.append("  gates    : " + ", ".join(s.gate_slugs))
        if s.tunables:
            tuned = ""
            if tuning_db is not None:
                n = sum(1 for e in tuning_db.entries()
                        if e.get("engine") == s.name)
                tuned = f"  ({n} tuned entr{'y' if n == 1 else 'ies'})"
            defs = ", ".join(f"{k}={s.defaults.get(k, '?')}"
                             for k in s.tunables)
            lines.append(f"  tunables : {defs}{tuned}")
        if s.notes:
            lines.append(f"  notes    : {s.notes}")
        lines.append("")
    lines.append("gate-reason vocabulary:")
    for slug in sorted(GATE_REASONS):
        kind = "template" if "{" in GATE_REASONS[slug] else "constant"
        lines.append(f"  {slug:32s} [{kind}]")
    return "\n".join(lines)
