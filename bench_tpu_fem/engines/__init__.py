"""Declarative engine registry + on-chip autotuner (ISSUE 16).

`registry.py` is the single source of truth for the engine matrix the
drivers grew implicitly: one `EngineSpec` table (form x precision x
geometry x sharding x nrhs policy, each with its capability predicate,
VMEM plan ref and analysis-config refs) plus the full gate-reason
vocabulary — `bench/driver.py` routing, `dist/driver.py` routing,
`serve/engine.py` capability checks, the exec-cache/artifact key
construction and the `analysis/configs.py` list are all DERIVED from it.

`autotune.py` is the deterministic sweep harness on top: candidate
tile/window/iter-chunk/nreps parameters generated from the registry's
VMEM plans, filtered by the analysis byte budgets (CPU-provable),
persisted in a durable tuning database keyed exactly like the
executable cache, consumed by driver and serve builds with a recorded
`tuning` evidence stamp.
"""

from .registry import (
    ENGINE_SPECS,
    GATE_REASONS,
    EngineSpec,
    analysis_plan,
    bench_engine_form,
    gate_reason,
    is_registered_reason,
    make_cache_key,
    planned_engine_form,
    resolve_backend,
    specs,
)
from .autotune import (
    TuningDB,
    default_tuning_db,
    generate_candidates,
    run_sweep,
    tuning_lookup,
    tuning_stamp,
)

__all__ = [
    "ENGINE_SPECS",
    "GATE_REASONS",
    "EngineSpec",
    "TuningDB",
    "analysis_plan",
    "bench_engine_form",
    "default_tuning_db",
    "gate_reason",
    "generate_candidates",
    "is_registered_reason",
    "make_cache_key",
    "planned_engine_form",
    "resolve_backend",
    "run_sweep",
    "specs",
    "tuning_lookup",
    "tuning_stamp",
]
