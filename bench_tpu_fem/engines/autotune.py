"""On-chip autotuner with a persisted, trend-gated tuning database
(ISSUE 16): a deterministic sweep harness that, per (degree, engine,
precision, sharding) slice, explores tile/window/iter-chunk/nreps
candidates generated from the registry's VMEM plans, filters them
through the analysis byte/VMEM budgets (CPU-provable — no hardware
needed to PROVE a candidate fits), scores them, and persists winners in
a durable tuning database keyed EXACTLY like the executable cache
(`serve.cache.ExecutableKey`, sha-addressed via
`serve.artifacts.key_hash`).

Database file format — the `harness.checkpoint` / `serve.artifacts`
write-and-validate discipline applied to tuning state:

    <path>.tmp  <- MAGIC | payload_len | crc32 | JSON payload
    flush + fsync, os.replace -> <path>, fsync(directory)

The JSON payload is `{"version": 1, "entries": {key_hash: entry}}`;
every entry embeds its FULL key dict, and `lookup` re-validates
`key_hash(embedded key) == address` AND embedded key == requested key —
a renamed, collided or repointed entry is refused (counted
`collisions`), a torn or bit-flipped file reads as an empty DB (counted
`corrupt`), and both degrade to ONE counted fallback-to-defaults, never
a crash or a silently wrong tile plan.

Evidence contract: every winner carries a round-stamp plus a
cpu-measured / design-estimate / hardware label; consumers stamp a
`tuning` evidence block (source=db/default, the label, and a REGISTERED
fallback reason when defaults are in effect) that `obs/regress.py`
trend-tracks and the perfgate counters (`tuning_db_hits`,
`tuning_fallbacks`, label presence) gate.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib

from . import registry

MAGIC = b"BTFTUNE1"
_HEADER = struct.Struct(">QI")  # payload length, crc32
DB_VERSION = 1

#: environment knob the drivers/serve consult for the process-wide DB
DB_ENV = "BTF_TUNING_DB"

#: evidence labels a tuning entry may carry (the measurement-hygiene
#: vocabulary, ROADMAP item 7)
LABELS = ("cpu-measured", "design-estimate", "hardware")


def _key_dict(key) -> dict:
    from ..serve.artifacts import key_dict

    return key_dict(key)


def _key_hash(key) -> str:
    from ..serve.artifacts import key_hash

    return key_hash(key)


class TuningDB:
    """One durable tuning database file. Thread-safe; counters mirror
    the artifact store's evidence discipline: lookups / hits /
    fallbacks / corrupt / collisions / puts. A missing, torn, corrupt
    or version-mismatched file behaves as an empty DB (every lookup a
    counted fallback), never a crash."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self.lookups = 0
        self.hits = 0
        self.fallbacks = 0
        self.corrupt = 0
        self.collisions = 0
        self.puts = 0
        self._entries: dict[str, dict] = {}
        self._loaded_ok = self._load()

    # -- read ---------------------------------------------------------------

    def _load(self) -> bool:
        """Validate + load the DB file into memory. Magic, header
        length, CRC, JSON shape and version are all checked; any
        failure counts `corrupt` once and leaves the DB empty."""
        try:
            with open(self.path, "rb") as fh:
                if fh.read(len(MAGIC)) != MAGIC:
                    return self._count_corrupt()
                head = fh.read(_HEADER.size)
                if len(head) != _HEADER.size:
                    return self._count_corrupt()
                length, crc = _HEADER.unpack(head)
                payload = fh.read(length + 1)
        except FileNotFoundError:
            return True  # absent is a legitimate empty DB, not corrupt
        except OSError:
            return self._count_corrupt()
        if len(payload) != length or zlib.crc32(payload) != crc:
            return self._count_corrupt()
        try:
            doc = json.loads(payload.decode())
        except (ValueError, UnicodeDecodeError):
            return self._count_corrupt()
        if not isinstance(doc, dict) or doc.get("version") != DB_VERSION:
            return self._count_corrupt()
        entries = doc.get("entries")
        if not isinstance(entries, dict):
            return self._count_corrupt()
        self._entries = entries
        return True

    def _count_corrupt(self) -> bool:
        with self._lock:
            self.corrupt += 1
        self._entries = {}
        return False

    def lookup(self, key) -> dict | None:
        """The validated tuning entry for `key`, or None (counted
        fallback). The embedded key must equal the requested key — a
        hash-addressed entry holding a different key is a collision,
        refused and counted, exactly like the artifact store."""
        from ..serve.artifacts import key_from_dict

        with self._lock:
            self.lookups += 1
        entry = self._entries.get(_key_hash(key))
        if entry is None:
            with self._lock:
                self.fallbacks += 1
            return None
        try:
            embedded = key_from_dict(entry.get("key", {}))
        except (KeyError, TypeError, ValueError):
            with self._lock:
                self.corrupt += 1
                self.fallbacks += 1
            return None
        if embedded != key:
            with self._lock:
                self.collisions += 1
                self.fallbacks += 1
            return None
        with self._lock:
            self.hits += 1
        return entry

    def entries(self) -> list[dict]:
        """Every loaded entry (already validated at load time)."""
        return list(self._entries.values())

    # -- write --------------------------------------------------------------

    def put(self, key, params: dict, *, score: float, label: str,
            engine: str, round_stamp: str, source: str = "sweep",
            extra: dict | None = None) -> dict:
        """Record one winner under `key` and durably rewrite the DB
        (tmp + fsync + os.replace + directory fsync — the
        harness.checkpoint discipline). Labels outside the evidence
        vocabulary are refused loudly: an unlabelled winner would evade
        the perfgate label-presence counter."""
        if label not in LABELS:
            raise ValueError(
                f"tuning label {label!r} not in {LABELS} — every entry "
                "must carry a measurement-hygiene label")
        entry = {
            "key": _key_dict(key),
            "engine": engine,
            "params": dict(params),
            "score": float(score),
            "label": label,
            "round": round_stamp,
            "source": source,
        }
        if extra:
            entry.update(extra)
        with self._lock:
            self._entries[_key_hash(key)] = entry
            self.puts += 1
        self._write()
        return entry

    def _write(self) -> None:
        payload = json.dumps(
            {"version": DB_VERSION, "entries": self._entries},
            sort_keys=True).encode()
        d = os.path.dirname(self.path) or "."
        os.makedirs(d, exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(MAGIC)
            fh.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)
        try:
            fd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass  # some filesystems refuse directory fsync; best-effort

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "lookups": self.lookups,
                "hits": self.hits,
                "fallbacks": self.fallbacks,
                "corrupt": self.corrupt,
                "collisions": self.collisions,
                "puts": self.puts,
                "labels_ok": all(
                    e.get("label") in LABELS
                    for e in self._entries.values()),
            }


# Process-wide default DB, resolved from $BTF_TUNING_DB once per path —
# the drivers and the serve engine consult this; tests and perfgate point
# it at their own temp files via the env var.
_DEFAULT: TuningDB | None = None
_DEFAULT_LOCK = threading.Lock()


def default_tuning_db() -> TuningDB | None:
    """The env-configured process DB, or None when tuning is disabled
    (no $BTF_TUNING_DB). Re-resolved when the env var changes path, so
    a test/perfgate leg can swap databases mid-process."""
    global _DEFAULT
    path = os.environ.get(DB_ENV)
    if not path:
        return None
    with _DEFAULT_LOCK:
        if _DEFAULT is None or _DEFAULT.path != path:
            _DEFAULT = TuningDB(path)
        return _DEFAULT


def reset_default_db() -> None:
    """Drop the cached process DB (tests use this to force a re-read of
    a file they rewrote outside the API)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        _DEFAULT = None


# ---------------------------------------------------------------------------
# Candidate generation + the deterministic sweep
# ---------------------------------------------------------------------------

#: the scoped-VMEM request ladder candidates sweep over, in KiB
#: (0 = the Mosaic default tier — analysis.budgets.scoped_limit_bytes(None));
#: the non-zero rungs are the tiers the shipped plans actually request
WINDOW_TIERS_KIB = (0, 32768, 65536, 98304)

#: refinement inner-iteration budgets the bf16-refine sweep ranks
#: (la.refine inner CG length per outer; the registry default 16 sits
#: mid-ladder — the U-shaped cost model prefers it until hardware
#: timing says otherwise)
REFINE_INNER_LADDER = (8, 16, 24, 32)


def generate_candidates(*, degree: int, grid_shape, nrhs_bucket: int = 1,
                        nreps: int = 30, precision: str = "f32",
                        refine: bool = False) -> list[dict]:
    """Deterministic tile/window/iter-chunk/nreps candidate set for one
    (degree, grid) slice, generated from the registry's VMEM plan: the
    plan's achieved form seeds the form axis, the scoped-VMEM tier
    ladder (the same rungs the shipped plans request) is the window
    axis, and iteration chunks sweep the powers of two up to the solve
    length. bf16 slices (ISSUE 17) take their form from the bf16 plan
    and quantise every window rung to the (16, 128) bf16 tile quantum
    (4 KiB — ops.bf16), adding the engine's own VMEM-estimate rung so
    the ladder brackets the real footprint; `refine` crosses the set
    with the inner-iteration budgets of the refinement ladder. Pure and
    ordered — identical inputs always yield the identical candidate
    list (the perfgate autotune leg pins the sweep byte-for-byte)."""
    if precision == "bf16":
        from ..ops.bf16 import (
            engine_plan_bf16,
            engine_vmem_bytes_bf16,
            quantize_to_bf16_tile,
        )

        form, kib = engine_plan_bf16(tuple(grid_shape), degree)
        est_kib = quantize_to_bf16_tile(
            engine_vmem_bytes_bf16(tuple(grid_shape), degree)) // 1024
        windows = sorted({
            (quantize_to_bf16_tile(int(w) * 1024) // 1024) if w else 0
            for w in (int(kib or 0), est_kib, *WINDOW_TIERS_KIB)})
    else:
        from ..ops.kron_cg import engine_plan

        form, kib = engine_plan(tuple(grid_shape), degree)
        windows = sorted({int(kib or 0), *WINDOW_TIERS_KIB})
    chunks = [c for c in (1, 2, 4, 8) if c <= max(1, nreps)]
    inner_ladder = REFINE_INNER_LADDER if refine else (None,)
    out = []
    for w in windows:
        for c in chunks:
            for ri in inner_ladder:
                cand = {
                    "plan_form": form,
                    "window_kib": int(w),
                    "iter_chunk": int(c),
                    "nreps": int(nreps),
                }
                if ri is not None:
                    cand["refine_inner_iters"] = int(ri)
                out.append(cand)
    return out


def _candidate_cost(cand: dict, *, degree: int, grid_shape,
                    nrhs_bucket: int) -> float:
    """Deterministic design-estimate cost model, used to RANK admitted
    candidates on CPU (hardware runs replace it with measured wall
    time): iteration-boundary sync cost amortises with larger chunks
    while continuous-batching latency grows with them (U-shaped,
    minimised at the registry default), and a smaller admitted scoped
    tier beats a larger one (less VMEM pressure for the same fit)."""
    chunk = max(1, cand["iter_chunk"])
    boundary_cost = 1.0 / chunk
    batching_cost = chunk / 16.0
    tier_cost = cand["window_kib"] / (1024.0 * 1024.0)  # prefer small tiers
    cost = boundary_cost + batching_cost + tier_cost
    ri = cand.get("refine_inner_iters")
    if ri:
        # refinement inner budget (ISSUE 17): too few inners means more
        # hi-precision outers (each a full-width apply), too many wastes
        # bf16 iterations past the mantissa floor — U-shaped, minimised
        # at the registry default of 16 until hardware timing replaces
        # this estimate
        cost += 0.25 * (ri / 16.0 + 16.0 / max(1, ri))
    return cost


def _fits_budget(cand: dict, *, degree: int, grid_shape,
                 precision: str = "f32") -> bool:
    """CPU-provable admission filter: the engine's VMEM byte estimate
    must fit the candidate's scoped-VMEM tier (analysis.budgets — the
    same byte model rules.R2 cross-checks against captures). bf16 uses
    its own half-width, (16, 128)-tile-quantised estimate (ops.bf16)."""
    from ..analysis.budgets import scoped_limit_bytes

    limit = scoped_limit_bytes(cand["window_kib"] or None)
    if precision == "bf16":
        from ..ops.bf16 import engine_vmem_bytes_bf16

        return engine_vmem_bytes_bf16(tuple(grid_shape), degree) <= limit
    from ..ops.kron_cg import engine_vmem_bytes

    return engine_vmem_bytes(tuple(grid_shape), degree) <= limit


def run_sweep(db: TuningDB, *, degree: int, ndofs: int, precision: str,
              geom: str, nrhs_bucket: int = 1, nreps: int = 30,
              device_mesh=(1, 1, 1), round_stamp: str = "r06",
              time_candidates: bool = False, refine: bool = False) -> dict:
    """One deterministic autotune sweep for a (degree, engine,
    precision, sharding) slice: generate candidates from the registry
    plan, drop the ones the analysis budgets refuse (each drop
    recorded — no silent truncation), score the rest, persist the
    winner. On CPU the score is the design-estimate cost model (label
    `design-estimate`) unless `time_candidates` asks for interpret-mode
    timing (label `cpu-measured`); on TPU the label is `hardware`.
    Returns {key, winner, candidates, rejected, label}."""
    import time as _time

    import jax

    from ..mesh.dofmap import dof_grid_shape
    from ..mesh.sizing import compute_mesh_size

    n = compute_mesh_size(ndofs, degree)
    grid = dof_grid_shape(n, degree)
    form = registry.planned_engine_form(
        precision, geom, ndofs, degree, nrhs_bucket)
    if refine:
        # refinement keys get their own engine_form slot so a swept
        # refine_inner_iters can never leak into a plain bf16 build
        form = "refine"
    key = registry.make_cache_key(
        degree=degree, cell_shape=n, precision=precision, geom=geom,
        engine_form=form, nrhs_bucket=nrhs_bucket,
        device_mesh=device_mesh, nreps=nreps)

    cands = generate_candidates(degree=degree, grid_shape=grid,
                                nrhs_bucket=nrhs_bucket, nreps=nreps,
                                precision=precision, refine=refine)
    admitted, rejected = [], []
    for c in cands:
        (admitted if _fits_budget(c, degree=degree, grid_shape=grid,
                                  precision=precision)
         else rejected).append(c)
    if not admitted:
        # every candidate over budget: record the registry default as
        # the (design-estimate) winner rather than leaving the slice
        # silently untuned under a sweep that claims to have run
        admitted = [{"plan_form": form, "window_kib": 0,
                     "iter_chunk": registry.DEFAULT_ITER_CHUNK,
                     "nreps": nreps,
                     **({"refine_inner_iters":
                         registry.DEFAULT_REFINE_INNER_ITERS}
                        if refine else {})}]

    on_tpu = jax.default_backend() == "tpu"
    label = "hardware" if on_tpu else (
        "cpu-measured" if time_candidates else "design-estimate")
    scored = []
    for c in admitted:
        if on_tpu or time_candidates:
            # measured path: one tiny timed apply per candidate through
            # the existing harness timing discipline (compile excluded)
            t0 = _time.perf_counter()
            _probe_candidate(c, degree=degree, ndofs=ndofs,
                             precision=precision, geom=geom)
            score = _time.perf_counter() - t0
        else:
            score = _candidate_cost(c, degree=degree, grid_shape=grid,
                                    nrhs_bucket=nrhs_bucket)
        scored.append((score, c))
    best_score, winner = min(scored, key=lambda sc: sc[0])
    if precision == "bf16":
        engine_name = ("bf16_refine" if refine
                       else ("kron_bf16" if geom == "uniform"
                             else "xla_bf16"))
    else:
        engine_name = ("kron_fused_batched" if form == "one_kernel_batched"
                       else ("kron_fused" if geom == "uniform" else
                             "xla_unfused"))
    entry = db.put(key, winner, score=best_score, label=label,
                   engine=engine_name, round_stamp=round_stamp)
    return {"key": _key_dict(key), "winner": winner,
            "score": best_score, "label": label, "entry": entry,
            "candidates": len(admitted), "rejected": len(rejected)}


def _probe_candidate(cand: dict, *, degree: int, ndofs: int,
                     precision: str, geom: str) -> None:
    """One warm apply at the candidate's shape — the measured-path
    probe. Deliberately tiny (the sweep is a ranking, not a benchmark);
    the full timing path re-validates winners in the agenda stage."""
    import jax.numpy as jnp
    import numpy as np

    from ..mesh.dofmap import dof_grid_shape
    from ..mesh.sizing import compute_mesh_size
    from ..ops.kron import build_kron_laplacian
    from ..mesh.box import create_box_mesh

    n = compute_mesh_size(ndofs, degree)
    mesh = create_box_mesh(n, geom_perturb_fact=0.0)
    op = build_kron_laplacian(mesh, degree, qmode=1, dtype=jnp.float32)
    grid = dof_grid_shape(n, degree)
    x = jnp.asarray(np.linspace(0.0, 1.0, int(np.prod(grid)),
                                dtype=np.float32).reshape(grid))
    y = op.apply(x)
    y.block_until_ready()


# ---------------------------------------------------------------------------
# Build-time consumption (drivers + serve fleet)
# ---------------------------------------------------------------------------

def tuning_lookup(key, db: TuningDB | None = None
                  ) -> tuple[dict | None, dict]:
    """(entry-or-None, tuning evidence stamp) for one executable key.
    The stamp ALWAYS exists — source=db with the entry's label and
    round when tuned, source=default with a REGISTERED fallback reason
    otherwise — so the journal records why defaults ran, never silence.
    """
    if db is None:
        db = default_tuning_db()
    if db is None:
        return None, {
            "source": "default",
            "label": "design-estimate",
            "fallback_reason": registry.gate_reason("tuning-disabled"),
        }
    entry = db.lookup(key)
    if entry is None:
        slug = ("tuning-db-invalid"
                if (db.corrupt or db.collisions) else
                "tuning-entry-missing")
        return None, {
            "source": "default",
            "label": "design-estimate",
            "fallback_reason": registry.gate_reason(slug),
        }
    return entry, {
        "source": "db",
        "label": entry.get("label"),
        "round": entry.get("round"),
        "params": dict(entry.get("params", {})),
    }


def tuning_stamp(extra: dict, key, db: TuningDB | None = None) -> dict | None:
    """Look up tuned parameters for `key` and stamp the `tuning`
    evidence block into `extra` (the drivers' results.extra / the serve
    solver's batch extra). Returns the entry's params dict when tuned,
    else None (defaults in effect, reason recorded)."""
    entry, stamp = tuning_lookup(key, db)
    extra["tuning"] = stamp
    return dict(entry["params"]) if entry else None
