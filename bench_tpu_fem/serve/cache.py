"""AOT-executable cache: compiled solver reuse across requests and reps.

The one-shot drivers lower and compile a fresh XLA executable per
invocation (`bench/driver.py` — tens of seconds against millisecond
solves at serving sizes). The reference amortises that launch cost by
demanding >= 10M dofs per device (README.md:160-163); a serving layer
amortises it the other way — across requests — by keying compiled
executables on everything that shapes the lowered computation and
reusing them for every compatible request.

The key is deliberately NOT the request: two requests with different
right-hand sides (or different nrhs up to the same bucket — batches are
padded, see `nrhs_bucket`) hit the same executable, because the RHS is
an *argument* of the compiled function, never a constant baked into it
(the same pytree-argument discipline as the benchmark drivers).

Counters (hits / misses / evictions / compiles) are the serving
contract's evidence: the smoke test asserts zero recompiles on repeat
configs straight off them, and `/metrics` republishes them.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable

# Batch-size buckets the broker pads to: a handful of executables cover
# every batch size, and the padding lanes (zero RHS) start frozen inside
# cg_solve_batched, so a padded solve does the same per-lane work.
NRHS_BUCKETS = (1, 2, 4, 8, 16)


def nrhs_bucket(nrhs: int) -> int:
    """Smallest bucket >= nrhs (the largest bucket for anything beyond:
    the broker never builds batches past its own nrhs_max anyway)."""
    for b in NRHS_BUCKETS:
        if nrhs <= b:
            return b
    return NRHS_BUCKETS[-1]


@dataclass(frozen=True)
class ExecutableKey:
    """Everything that shapes the lowered solver computation — the
    ISSUE's cache-key contract: degree, the per-device (local) cell
    shape, precision (f32 / f64-emulated / df32), geometry class,
    engine form, the nrhs bucket the batch pads to, and the device
    mesh it was compiled for. Two requests agreeing on this key can
    share one executable; anything else must not."""

    degree: int
    cell_shape: tuple  # local (per-device) mesh cells, e.g. (8, 8, 8)
    precision: str  # "f32" | "f64" | "df32"
    geom: str  # "uniform" | "perturbed"
    engine_form: str  # unified vocabulary (bench.driver.record_engine)
    nrhs_bucket: int
    device_mesh: tuple  # dshape, (1, 1, 1) for single-chip
    nreps: int = 0  # CG iterations baked into the loop
    form: str = "poisson"  # weak-form axis (forms.registry, ISSUE 20)


@dataclass
class CacheEntry:
    key: ExecutableKey
    executable: object  # the compiled solver (serve.engine.CompiledSolver)
    compile_s: float = 0.0
    meta: dict = field(default_factory=dict)


class ExecutableCache:
    """Thread-safe LRU over `ExecutableKey` with hit/miss/evict/compile
    counters and a warmup API. `get_or_build` is the only way anything
    enters the cache, so `compiles` counts exactly the builder calls —
    "zero recompiles on repeat configs" is `compiles` staying flat while
    `hits` climbs."""

    def __init__(self, capacity: int = 32):
        self.capacity = capacity
        self._entries: OrderedDict[ExecutableKey, CacheEntry] = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.compiles = 0
        # AOT warm loads (ISSUE 13): entries installed from a serialized
        # peer artifact — a deserialization, NOT a compile, so the
        # "zero recompiles on a warm replica" contract stays a truthful
        # counter read (compiles counts builder calls only)
        self.warm_loads = 0

    def lookup(self, key: ExecutableKey) -> CacheEntry | None:
        """Counter-free peek (the broker uses it to prefer an
        already-compiled bucket over the minimal one)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
            return entry

    def holds(self, key: ExecutableKey) -> bool:
        """Counter-free, LRU-order-free IN-MEMORY peek. The fleet's
        affinity probe uses this instead of `lookup`: a routing probe
        must not refresh the key's recency in lanes the request is not
        even routed to (a probe-refreshed never-served entry would
        out-survive entries the lane actually serves at eviction
        time)."""
        with self._lock:
            return key in self._entries

    def provisioned(self, key: ExecutableKey) -> bool:
        """Can this cache produce `key` WITHOUT a compile? The plain
        cache answers from the in-memory LRU; ArtifactWarmCache
        (serve.artifacts) also answers yes for keys a peer published to
        the shared store (a warm load, not a compile). The broker's
        bucket preference consults this, so a cold replica prefers the
        bucket its peers already compiled."""
        return self.holds(key)

    def get(self, key: ExecutableKey) -> CacheEntry | None:
        """Counted lookup: a hit or a miss, no build (the driver's
        exec-cache path pairs this with `insert`)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self.hits += 1
                self._entries.move_to_end(key)
            else:
                self.misses += 1
            return entry

    def insert(self, key: ExecutableKey, executable,
               compile_s: float = 0.0, meta: dict | None = None
               ) -> CacheEntry:
        """Insert an already-built executable (counted as one compile —
        the build happened at the caller; the counters stay truthful)."""
        entry = CacheEntry(key, executable, compile_s=compile_s,
                           meta=meta or {})
        with self._lock:
            self.compiles += 1
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
        return entry

    def insert_warm(self, key: ExecutableKey, executable,
                    load_s: float = 0.0,
                    meta: dict | None = None) -> CacheEntry:
        """Insert an executable deserialized from a peer's AOT artifact:
        counted `warm_loads`, NEVER `compiles` — no builder ran, no XLA
        compile happened (serve.engine's artifact loader installs the
        serialized PJRT executables directly)."""
        meta = dict(meta or {})
        meta.setdefault("source", "artifact")
        entry = CacheEntry(key, executable, compile_s=load_s, meta=meta)
        with self._lock:
            self.warm_loads += 1
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
        return entry

    def get_or_build(self, key: ExecutableKey,
                     builder: Callable[[], object],
                     compile_s: float | None = None) -> CacheEntry:
        """Return the cached executable for `key`, or build, count and
        insert one. The builder runs OUTSIDE the lock (compiles take
        seconds; lookups must not queue behind them) — a racing
        duplicate build is possible and harmless: last-in wins, both
        builds are counted (the counters are evidence, not fiction)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self.hits += 1
                self._entries.move_to_end(key)
                return entry
            self.misses += 1
        import time

        t0 = time.perf_counter()
        executable = builder()
        wall = time.perf_counter() - t0 if compile_s is None else compile_s
        entry = CacheEntry(key, executable, compile_s=wall)
        with self._lock:
            self.compiles += 1
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
        return entry

    def warmup(self, keys_and_builders) -> list[CacheEntry]:
        """Prebuild executables for [(key, builder), ...] — the serving
        analogue of the benchmark's compile-outside-the-timed-region
        rule: requests arriving after warmup never pay a compile."""
        return [self.get_or_build(k, b) for k, b in keys_and_builders]

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "compiles": self.compiles,
                "warm_loads": self.warm_loads,
                "hit_rate": (
                    self.hits / (self.hits + self.misses)
                    if (self.hits + self.misses) else 0.0
                ),
            }

    def keys(self) -> list[ExecutableKey]:
        with self._lock:
            return list(self._entries)


# Process-wide default instance: bench.py routes its repeated
# side-metric configs through it (BenchConfig.exec_cache) so a retry
# ladder's unchanged configs stop recompiling; the serve broker builds
# its own instance per server unless handed this one.
_DEFAULT: ExecutableCache | None = None
_DEFAULT_LOCK = threading.Lock()


def default_cache() -> ExecutableCache:
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = ExecutableCache()
        return _DEFAULT
