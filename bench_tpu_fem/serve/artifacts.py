"""Shared AOT executable-artifact store (ISSUE 13): serialized compiled
solvers on disk, keyed EXACTLY like the in-memory cache
(`serve.cache.ExecutableKey`), so a fresh broker replica warms its LRU
from a peer's published artifacts instead of recompiling — the
compilation-cache half of the fleet story (AlpaServe-style placement
needs executables to be portable across replicas; `jax.export`-class
serialization is how production inference stacks ship them).

Write protocol — the `harness.checkpoint` fsync discipline, applied to
artifacts:

    <keyhash>.art.tmp  <- MAGIC | payload_len | crc32 | npz payload
    flush + fsync          (the bytes are durable)
    os.replace -> <keyhash>.art   (atomic: readers see old or new,
                                   never a torn file)
    fsync(directory)       (the rename itself is durable)

The npz payload carries ``__meta__`` (JSON: the full ExecutableKey, the
solver spec, engine form, format/jax/backend pins, and a sha256 over the
executable blobs — the CONTENT hash) plus one uint8 blob per serialized
checkpoint executable (`serve.engine.CompiledSolver.export_artifact`).
`get` validates magic + length + CRC + content hash + **key equality**
(the embedded key must equal the requested key — a renamed, collided or
repointed file is refused, counted `collisions`, never silently served),
and treats anything torn/corrupt/incompatible as a MISS: a damaged
artifact degrades to one recompile, never to a crash or a wrong
executable.

Trust boundary: artifact blobs deserialize through
`jax.experimental.serialize_executable` (pickle-carried). The CRC and
content hash protect INTEGRITY (torn writes, bit rot), not malice —
load artifacts only from operator-owned stores, the same trust class as
the checkpoint and journal files.

`ArtifactWarmCache` is the drop-in `ExecutableCache` that consults the
store between the LRU and the builder: hit -> LRU; miss -> artifact warm
load (`warm_loads`, ZERO compiles); still missing -> builder (counted
compile) and, with `publish=True`, the freshly built solver is published
back so peers warm from it — "warms from peers instead of recompiling"
is these counters staying truthful.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import struct
import threading
import time
import zlib

import numpy as np

from .cache import ExecutableCache, ExecutableKey

MAGIC = b"BTFARTE1"
_HEADER = struct.Struct(">QI")  # payload length, crc32


def key_dict(key: ExecutableKey) -> dict:
    """The canonical JSON form of an ExecutableKey (tuples as lists —
    the artifact meta's key field and the content-addressing input)."""
    return {
        "degree": key.degree,
        "cell_shape": list(key.cell_shape),
        "precision": key.precision,
        "geom": key.geom,
        "engine_form": key.engine_form,
        "nrhs_bucket": key.nrhs_bucket,
        "device_mesh": list(key.device_mesh),
        "nreps": key.nreps,
    }


def key_from_dict(d: dict) -> ExecutableKey:
    return ExecutableKey(
        degree=int(d["degree"]),
        cell_shape=tuple(int(c) for c in d["cell_shape"]),
        precision=str(d["precision"]),
        geom=str(d["geom"]),
        engine_form=str(d["engine_form"]),
        nrhs_bucket=int(d["nrhs_bucket"]),
        device_mesh=tuple(int(c) for c in d["device_mesh"]),
        nreps=int(d.get("nreps", 0)),
    )


def key_hash(key: ExecutableKey) -> str:
    """Content address of a key: sha256 over its canonical JSON."""
    blob = json.dumps(key_dict(key), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:24]


def _content_hash(fns: dict) -> str:
    h = hashlib.sha256()
    for name in sorted(fns):
        h.update(name.encode())
        h.update(fns[name])
    return h.hexdigest()


class ArtifactStore:
    """Directory of durable executable artifacts, one file per
    ExecutableKey. Thread-safe counters mirror the cache's evidence
    discipline: puts/gets/hits/misses/corrupt/collisions."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self.puts = 0
        self.gets = 0
        self.hits = 0
        self.misses = 0
        self.corrupt = 0
        self.collisions = 0

    # -- write -------------------------------------------------------------

    def put(self, key: ExecutableKey, artifact: dict) -> str:
        """Durably publish one `export_artifact` payload under `key`;
        returns the artifact path. Last-writer-wins (the payloads are
        deterministic per key up to timing metadata)."""
        meta = dict(artifact.get("meta") or {})
        fns = artifact.get("fns") or {}
        meta["key"] = key_dict(key)
        meta["content_sha256"] = _content_hash(fns)
        meta["published_ts"] = time.time()
        buf = io.BytesIO()
        blobs = {name: np.frombuffer(data, np.uint8)
                 for name, data in fns.items()}
        np.savez(buf, __meta__=np.frombuffer(
            json.dumps(meta, sort_keys=True).encode(), np.uint8), **blobs)
        payload = buf.getvalue()
        path = os.path.join(self.root, f"{key_hash(key)}.art")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(MAGIC)
            fh.write(_HEADER.pack(len(payload), zlib.crc32(payload)))
            fh.write(payload)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        self._fsync_dir()
        with self._lock:
            self.puts += 1
        return path

    def put_solver(self, key: ExecutableKey, solver) -> str:
        """Publish a live CompiledSolver (export + put)."""
        return self.put(key, solver.export_artifact())

    def _fsync_dir(self) -> None:
        try:
            fd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass  # some filesystems refuse directory fsync; best-effort

    # -- read --------------------------------------------------------------

    def contains(self, key: ExecutableKey) -> bool:
        """Cheap existence probe (no read, no validation — a torn file
        still answers True here and degrades to a counted miss + one
        compile at load time; the probe only steers bucket/affinity
        preferences, never correctness)."""
        return os.path.exists(
            os.path.join(self.root, f"{key_hash(key)}.art"))

    def get(self, key: ExecutableKey) -> dict | None:
        """One validated artifact payload ({"meta", "fns"}) or None —
        missing, torn, corrupt, content-hash-mismatched and
        KEY-MISMATCHED (collision/rename defense) all read as a miss,
        with the reason counted; a bad artifact can cost a recompile,
        never correctness."""
        with self._lock:
            self.gets += 1
        path = os.path.join(self.root, f"{key_hash(key)}.art")
        out = self._read(path)
        if out is None:
            with self._lock:
                self.misses += 1
            return None
        meta, fns = out
        if key_from_dict(meta.get("key", {})) != key:
            # the embedded key IS the identity — a file that hashed (or
            # was renamed) onto this address but holds a different key
            # must be refused, loudly counted
            with self._lock:
                self.collisions += 1
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
        return {"meta": meta, "fns": fns}

    def _read(self, path: str):
        try:
            with open(path, "rb") as fh:
                if fh.read(len(MAGIC)) != MAGIC:
                    self._count_corrupt()
                    return None
                head = fh.read(_HEADER.size)
                if len(head) != _HEADER.size:
                    self._count_corrupt()
                    return None
                length, crc = _HEADER.unpack(head)
                payload = fh.read(length)
            if len(payload) != length or zlib.crc32(payload) != crc:
                self._count_corrupt()
                return None
            with np.load(io.BytesIO(payload)) as z:
                meta = json.loads(bytes(z["__meta__"]).decode())
                fns = {k: bytes(z[k]) for k in z.files if k != "__meta__"}
        except FileNotFoundError:
            return None
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            self._count_corrupt()
            return None
        if meta.get("content_sha256") != _content_hash(fns):
            self._count_corrupt()
            return None
        return meta, fns

    def _count_corrupt(self) -> None:
        with self._lock:
            self.corrupt += 1

    def keys(self) -> list[ExecutableKey]:
        """Every loadable artifact's embedded key (corrupt files are
        skipped, already counted on read)."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for name in sorted(names):
            if not name.endswith(".art"):
                continue
            got = self._read(os.path.join(self.root, name))
            if got is not None:
                try:
                    out.append(key_from_dict(got[0].get("key", {})))
                except (KeyError, TypeError, ValueError):
                    self._count_corrupt()
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "puts": self.puts,
                "gets": self.gets,
                "hits": self.hits,
                "misses": self.misses,
                "corrupt": self.corrupt,
                "collisions": self.collisions,
            }


class ArtifactWarmCache(ExecutableCache):
    """ExecutableCache that warms misses from an ArtifactStore before
    falling back to the builder — the fleet lane's cache. Counter
    contract: an LRU hit counts `hits`; an artifact load counts
    `warm_loads` (the executable was deserialized, not compiled); only
    a real builder invocation counts `compiles`. With `publish=True` a
    built solver is published back to the store so PEER replicas warm
    from this lane's compile."""

    def __init__(self, store: ArtifactStore, *, capacity: int = 32,
                 publish: bool = True, loader=None):
        super().__init__(capacity=capacity)
        self.store = store
        self.publish = publish
        # loader(meta, fns) -> executable; default rebuilds the host
        # state from the artifact's own spec and installs the
        # serialized executables (serve.engine.build_solver(artifact=))
        self._loader = loader or _default_loader

    def provisioned(self, key) -> bool:
        return self.holds(key) or self.store.contains(key)

    def get_or_build(self, key, builder, compile_s=None):
        entry = self.lookup(key)
        if entry is not None:
            with self._lock:
                self.hits += 1
            return entry
        with self._lock:
            self.misses += 1
        art = self.store.get(key)
        if art is not None:
            t0 = time.perf_counter()
            try:
                executable = self._loader(art["meta"], art["fns"])
            except Exception:
                # incompatible/damaged artifact: degrade to one build —
                # the store already counted the miss class; never crash
                # the serving path on bad artifact bytes
                executable = None
            if executable is not None:
                return self.insert_warm(
                    key, executable,
                    load_s=time.perf_counter() - t0,
                    meta={"source": "artifact",
                          "published_ts": art["meta"].get(
                              "published_ts")})
        t0 = time.perf_counter()
        executable = builder()
        wall = time.perf_counter() - t0 if compile_s is None else compile_s
        entry = self.insert(key, executable, compile_s=wall)
        # insert() counted the compile; undo the double miss-count from
        # our early-miss bookkeeping is NOT needed (insert doesn't count
        # misses), but publish the build so peers warm from it
        if self.publish:
            try:
                self.store.put(key, executable.export_artifact())
            except Exception:
                pass  # publication is best-effort; serving never blocks
        return entry


def _default_loader(meta: dict, fns: dict):
    """Rebuild a CompiledSolver from an artifact: host-side setup from
    the embedded spec + the serialized executables. Raises
    ArtifactIncompatible on version/format mismatch (the caller's miss
    signal)."""
    from .engine import SolveSpec, build_solver

    spec = SolveSpec(**meta["spec"])
    return build_solver(spec, meta["bucket"],
                        artifact={"meta": meta, "fns": fns})
