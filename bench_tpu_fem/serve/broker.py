"""Admission-controlled request broker with continuous batching.

The serving core: requests enter a BOUNDED queue (admission control —
a full queue sheds the request immediately with a retriable signal
rather than letting latency grow without bound), a single batching
worker drains it, collecting requests with the SAME `SolveSpec` until
either `nrhs_max` lanes are gathered or the batching window expires,
pads the batch to the executable cache's nrhs bucket, and starts ONE
compiled batched solve for the group.

For solvers exposing the iteration-boundary checkpoint API
(f32/f64 — serve.engine.CompiledSolver.supports_continuous), the batch
then runs CONTINUOUSLY, the shape LLM inference servers use: at every
`iter_chunk` iteration boundary the worker retires lanes that finished
their budget (answering those requests immediately — a finished request
never waits for its batch-mates) and admits compatible queued requests
into the freed lanes mid-solve (`serve_admit` journal records with
midsolve=true; each admitted lane gets its full iteration budget). The
solve ends when no lane is live and no compatible request is queued —
so under sustained traffic one batch can serve many windows' worth of
requests with lane occupancy pinned near the bucket instead of sawing
down as lanes finish. Solvers without the checkpoint API (df32) keep
the fixed-window one-shot batch, reason recorded.

Fault semantics reuse the measurement harness's taxonomy
(`harness.classify`): every failed response carries a `failure_class`,
and the retriable set (transient / timeout / oom / tunnel_wedge) maps to
"shed with retry-after" while the deterministic set (mosaic_reject /
accuracy_fail / unsupported) maps to "don't retry" — retrying a
deterministic failure just burns queue capacity, the same policy the
stage runner applies.

The queue can never deadlock on a wedged solve: each batch executes on
its own disposable thread under a hard deadline; a batch that overruns
is answered (classified `timeout`, retriable) and ABANDONED — the
worker moves on to the next batch while the stuck thread, which Python
cannot kill, is left to finish into the void. This is the in-process
analogue of the harness runner's group-kill-and-continue.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..harness.classify import classify_exception
from ..obs.trace import Lifecycle, span
from .cache import NRHS_BUCKETS, ExecutableCache, nrhs_bucket
from .engine import SolveSpec, build_solver, spec_cache_key
from .metrics import Metrics

# Classes worth a client retry (capacity/infrastructure); everything
# else in the taxonomy is deterministic — same split the stage-retry
# policy uses.
RETRIABLE_CLASSES = frozenset(
    {"transient", "timeout", "oom", "tunnel_wedge"})


class QueueFull(Exception):
    """Admission control shed the request (bounded queue at capacity).
    Retriable by contract: the server maps it to 503 + Retry-After."""


@dataclass
class PendingRequest:
    """One admitted request: a responder claims it (`answered`, under
    the broker's response lock), fulfils `result` and sets `done`; the
    submitting thread waits on `done`. With continuous batching two
    threads can race to answer (the solve thread's retire loop vs the
    worker's timeout path), so the claim must be atomic — `done` alone
    is a check-then-act hole.

    ``lc`` carries the request's lifecycle marks
    (enqueue -> admit -> solve -> respond, obs.trace.Lifecycle): every
    latency the broker reports derives from these marks instead of
    ad-hoc time.monotonic() arithmetic, and the per-stage breakdown
    rides on the response/journal. ``enqueued`` is kept as an alias of
    the enqueue mark (existing readers)."""

    id: str
    spec: SolveSpec
    scale: float
    enqueued: float
    done: threading.Event = field(default_factory=threading.Event)
    result: dict | None = None
    answered: bool = False
    lc: Lifecycle = field(default_factory=Lifecycle)

    def __post_init__(self):
        self.lc.marks.setdefault("enqueue", self.enqueued)


def _spec_dict(spec: SolveSpec) -> dict:
    return {"degree": spec.degree, "ndofs": spec.ndofs,
            "nreps": spec.nreps, "precision": spec.precision,
            "geom_perturb_fact": spec.geom_perturb_fact}


class Broker:
    def __init__(self, cache: ExecutableCache | None = None,
                 metrics: Metrics | None = None, *,
                 queue_max: int = 128, nrhs_max: int = 8,
                 window_s: float = 0.025, solve_timeout_s: float = 120.0,
                 continuous: bool = True, builder=build_solver):
        self.cache = cache or ExecutableCache()
        self.metrics = metrics or Metrics()
        self.queue_max = queue_max
        self.nrhs_max = min(nrhs_max, NRHS_BUCKETS[-1])
        self.window_s = window_s
        self.solve_timeout_s = solve_timeout_s
        # continuous=False pins every solver to fixed-window one-shot
        # batches — the A/B baseline the occupancy acceptance compares
        # against (serve CLI --no-continuous).
        self.continuous = continuous
        self._builder = builder
        self._queue: deque[PendingRequest] = deque()
        self._cv = threading.Condition()
        # atomic response claim (see PendingRequest.answered): the solve
        # thread (continuous retires) and the worker thread (timeout/
        # failure paths) may race to answer the same request
        self._respond_lock = threading.Lock()
        self._stop = False
        self._ids = itertools.count(1)
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-broker")
        self._worker.start()

    # -- client side -------------------------------------------------------

    def submit(self, spec: SolveSpec, scale: float = 1.0,
               req_id: str | None = None) -> PendingRequest:
        """Admit one request or shed it (QueueFull). Never blocks on the
        solve — the caller waits on the returned PendingRequest."""
        rid = req_id or f"r{next(self._ids)}"
        with self._cv:
            depth = len(self._queue)
            if self._stop:
                raise QueueFull("broker is shut down")
            if depth >= self.queue_max:
                self.metrics.shed(rid, depth)
                raise QueueFull(
                    f"queue at capacity ({depth}/{self.queue_max})")
            pending = PendingRequest(rid, spec, float(scale), time.monotonic())
            self._queue.append(pending)
            self.metrics.request(rid, _spec_dict(spec), len(self._queue))
            self._cv.notify_all()
        return pending

    def wait(self, pending: PendingRequest,
             timeout_s: float | None = None) -> dict:
        """Block until the request is answered (or the wait times out —
        a retriable timeout response; the broker may still answer the
        underlying batch later, into the void)."""
        if pending.done.wait(timeout_s):
            return pending.result  # type: ignore[return-value]
        return {"ok": False, "id": pending.id,
                "error": f"response wait exceeded {timeout_s}s",
                "failure_class": "timeout", "retriable": True}

    def warmup(self, specs, bucket: int | None = None) -> list:
        """Prebuild executables for the given specs at `bucket`
        (default: the broker's own nrhs_max bucket, the one its batches
        pad to) — requests arriving after warmup never pay a compile."""
        b = bucket or nrhs_bucket(self.nrhs_max)
        return self.cache.warmup(
            [(spec_cache_key(s, b), (lambda s=s: self._builder(s, b)))
             for s in specs])

    def shutdown(self, timeout_s: float = 5.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._worker.join(timeout_s)
        # anything still queued is answered, not dropped
        with self._cv:
            leftovers = list(self._queue)
            self._queue.clear()
        for p in leftovers:
            self._respond(p, {"ok": False, "id": p.id,
                              "error": "broker shut down",
                              "failure_class": "transient",
                              "retriable": True})

    # -- worker side -------------------------------------------------------

    def _take_compatible(self, spec: SolveSpec, k: int) -> list:
        """Pull up to k same-spec requests out of the queue (FIFO among
        compatible; incompatible requests keep their positions)."""
        taken, kept = [], deque()
        while self._queue and len(taken) < k:
            p = self._queue.popleft()
            (taken if p.spec == spec else kept).append(p)
        kept.extend(self._queue)
        self._queue.clear()
        self._queue.extend(kept)
        return list(taken)

    def _loop(self) -> None:
        while True:
            batch = self._gather()
            if batch is None:
                return
            if not batch:
                continue
            try:
                self._execute(batch)
            except BaseException as exc:  # the queue must NEVER deadlock
                self._fail_batch(batch, exc)

    def _gather(self) -> list | None:
        """Block for the first request, then hold the batching window
        open: collect same-spec requests until nrhs_max or deadline.
        Returns None only on shutdown with an empty queue."""
        with self._cv:
            while not self._queue:
                if self._stop:
                    return None
                self._cv.wait(0.1)
            first = self._queue.popleft()
            batch = [first]
            deadline = time.monotonic() + self.window_s
            while len(batch) < self.nrhs_max:
                batch.extend(self._take_compatible(
                    first.spec, self.nrhs_max - len(batch)))
                if len(batch) >= self.nrhs_max:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop:
                    break
                self._cv.wait(remaining)
            self.metrics.set_queue_depth(len(self._queue))
        return batch

    def _pick_bucket(self, spec: SolveSpec, live: int) -> int:
        """Prefer the smallest ALREADY-COMPILED bucket that fits the
        batch (padding is cheap — dead lanes start frozen; a compile is
        seconds), else the minimal bucket for the batch size."""
        for b in NRHS_BUCKETS:
            if b >= live and self.cache.lookup(
                    spec_cache_key(spec, b)) is not None:
                return b
        return nrhs_bucket(live)

    def _poll_compatible(self, spec: SolveSpec, k: int) -> list:
        """Queue poll from the solve thread (continuous admissions):
        same-spec FIFO extraction under the lock."""
        with self._cv:
            taken = self._take_compatible(spec, k)
            self.metrics.set_queue_depth(len(self._queue))
        return taken

    def _execute(self, batch: list) -> None:
        spec = batch[0].spec
        live = len(batch)
        bucket = self._pick_bucket(spec, live)
        key = spec_cache_key(spec, bucket)
        cache_hit = self.cache.lookup(key) is not None
        for p in batch:
            p.lc.mark("admit")  # window-seeded members enter the batch
        # `members` grows with mid-solve admissions: the timeout/failure
        # paths below must answer every request the solve ever owned
        # (_respond skips the already-answered ones).
        members = list(batch)
        box: dict = {}
        # the admission horizon is anchored where the HARD deadline is
        # (batch-execution start, before any compile): a zombie solve
        # thread must stop admitting BEFORE the worker can abandon the
        # batch, or admitted requests would sit outside any deadline
        # cover
        admit_deadline = time.monotonic() + self.solve_timeout_s / 2

        def _run():
            try:
                with span("serve:solve", spec=_spec_dict(spec),
                          bucket=bucket, live=len(members)):
                    entry = self.cache.get_or_build(
                        key, lambda: self._builder(spec, bucket))
                    solver = entry.executable
                    for p in members:
                        p.lc.mark("solve")
                    if self.continuous and getattr(
                            solver, "supports_continuous", False):
                        box["summary"] = self._solve_continuous(
                            solver, spec, members, bucket, cache_hit,
                            admit_deadline)
                    else:
                        box["result"] = solver.solve(
                            [p.scale for p in members])
            except BaseException as exc:
                box["error"] = exc

        t = threading.Thread(target=_run, daemon=True,
                             name="serve-solve")
        t.start()
        t.join(self.solve_timeout_s)
        if t.is_alive():
            # hard deadline: answer + abandon (the harness's
            # kill-the-group, minus the kill Python threads lack).
            # Continuous members already retired were answered as they
            # finished; _respond skips them here.
            msg = (f"solve exceeded {self.solve_timeout_s}s "
                   f"(spec {_spec_dict(spec)}); batch abandoned")
            for p in members:
                self._respond(p, {
                    "ok": False, "id": p.id, "error": msg,
                    "failure_class": "timeout", "retriable": True})
            self.metrics.batch(_spec_dict(spec), len(members), bucket,
                               cache_hit, self.solve_timeout_s, 0.0)
            return
        if "error" in box:
            self._fail_batch(members, box["error"], bucket=bucket,
                             cache_hit=cache_hit)
            return
        if "summary" in box:
            # continuous: per-request responses went out at each retire;
            # here only the batch-level accounting lands
            s = box["summary"]
            self.metrics.batch(
                _spec_dict(spec), s["served"], bucket, cache_hit,
                s["wall_s"], s["gdof_per_second"],
                padded_lanes=s["padded_lanes"], midsolve=s["midsolve"],
                boundaries=s["boundaries"],
                live_lane_boundaries=s["live_lane_boundaries"],
                continuous=True)
            return
        res = box["result"]
        self.metrics.batch(_spec_dict(spec), live, res.nrhs_bucket,
                           cache_hit, res.wall_s, res.gdof_per_second)
        for lane, p in enumerate(batch):
            self._respond(p, {
                "ok": True, "id": p.id,
                "xnorm": res.xnorms[lane],
                "scale": p.scale,
                "spec": _spec_dict(spec),
                "nrhs_live": res.nrhs_live,
                "nrhs_bucket": res.nrhs_bucket,
                "ndofs_global": res.ndofs_global,
                "cg_engine_form": res.extra.get("cg_engine_form",
                                                "unfused"),
                "continuous": False,
                "cache": "hit" if cache_hit else "miss",
                "batch_wall_s": res.wall_s,
                "gdof_per_second": res.gdof_per_second,
            })

    def _solve_continuous(self, solver, spec: SolveSpec, members: list,
                          bucket: int, cache_hit: bool,
                          admit_deadline: float) -> dict:
        """Run one continuous batch on the solve thread: step the
        compiled solve `iter_chunk` iterations at a time; at every
        boundary retire finished lanes (responding immediately) and
        admit compatible queued requests into the freed lanes. Returns
        the batch-level accounting for metrics.batch.

        `admit_deadline` (half the solve timeout, anchored by the
        caller at batch-execution start so a slow compile eats into it
        rather than extending it) closes the admission horizon well
        before the worker's hard deadline: a sustained request stream
        cannot hold one batch past the abandon point, and an abandoned
        zombie thread can never keep pulling fresh requests into a
        batch nobody is watching — remaining lanes drain, the batch
        ends, the worker forms a fresh batch for whatever is queued."""
        t0 = time.monotonic()
        state = solver.cont_init([p.scale for p in members])
        lanes: list = [None] * bucket
        served = midsolve = boundaries = live_lane_boundaries = 0
        dead_lane_boundaries = 0
        boundary_iter = 0
        for lane, p in enumerate(members):
            lanes[lane] = p
            self.metrics.admit(p.id, lane, 0, False, lane + 1)

        def spec_d():
            return _spec_dict(spec)

        while any(p is not None for p in lanes):
            state = solver.cont_step(state)
            boundary_iter += solver.iter_chunk
            iters, done = solver.cont_poll(state)
            live = sum(1 for p in lanes if p is not None)
            boundaries += 1
            live_lane_boundaries += live
            dead_lane_boundaries += bucket - live
            now = time.monotonic()
            for lane, p in enumerate(lanes):
                if p is None or not bool(done[lane]):
                    continue
                state, xnorm = solver.cont_retire(state, lane)
                lanes[lane] = None
                live -= 1
                served += 1
                self.metrics.retire(p.id, lane, boundary_iter,
                                    int(iters[lane]), live)
                self._respond(p, {
                    "ok": True, "id": p.id,
                    "xnorm": xnorm,
                    "scale": p.scale,
                    "spec": spec_d(),
                    "nrhs_live": live,
                    "nrhs_bucket": bucket,
                    "ndofs_global": solver.ndofs_global,
                    "cg_engine_form": solver.engine_form,
                    "continuous": True,
                    "iters_run": int(iters[lane]),
                    "cache": "hit" if cache_hit else "miss",
                })
            free = [i for i, p in enumerate(lanes) if p is None]
            if free and now < admit_deadline:
                for p in self._poll_compatible(spec, len(free)):
                    lane = free.pop(0)
                    p.lc.mark("admit")
                    p.lc.mark("solve")  # admitted into an in-flight solve
                    state = solver.cont_admit(state, lane, p.scale)
                    lanes[lane] = p
                    members.append(p)
                    midsolve += 1
                    live += 1
                    self.metrics.admit(p.id, lane, boundary_iter, True,
                                       live)
        wall = time.monotonic() - t0
        # GDoF/s over the whole continuous batch: every served lane ran
        # its full budget (retired lanes are answered, not truncated)
        gdof = (solver.ndofs_global * spec.nreps * served
                / (1e9 * wall) if wall > 0 else 0.0)
        # padding waste in lane units: dead boundary-slots normalised by
        # boundaries (comparable with the one-shot bucket - live)
        padded = (round(dead_lane_boundaries / boundaries)
                  if boundaries else bucket - served)
        return {"served": served, "wall_s": wall,
                "gdof_per_second": gdof, "midsolve": midsolve,
                "boundaries": boundaries,
                "live_lane_boundaries": live_lane_boundaries,
                "padded_lanes": padded}

    def _fail_batch(self, batch: list, exc: BaseException, *,
                    bucket: int | None = None,
                    cache_hit: bool = False) -> None:
        cls = classify_exception(exc)
        retriable = cls in RETRIABLE_CLASSES
        spec = batch[0].spec
        self.metrics.batch(_spec_dict(spec), len(batch),
                           bucket or nrhs_bucket(len(batch)), cache_hit,
                           0.0, 0.0)
        for p in batch:
            self._respond(p, {
                "ok": False, "id": p.id,
                "error": f"{type(exc).__name__}: {exc}"[:500],
                "failure_class": cls, "retriable": retriable})

    def _respond(self, pending: PendingRequest, result: dict) -> None:
        # atomic claim: exactly ONE responder wins (metrics must count
        # each request once; the loser's payload is dropped)
        with self._respond_lock:
            if pending.answered:
                return
            pending.answered = True
            # the lifecycle marks ARE the latency accounting: total and
            # the per-stage breakdown ride on every response/journal line
            pending.lc.mark("respond")
            lifecycle = pending.lc.breakdown()
            result["latency_s"] = latency = lifecycle.get("total_s", 0.0)
            result["lifecycle_s"] = lifecycle
            pending.result = result
        self.metrics.response(
            pending.id, bool(result.get("ok")), latency,
            failure_class=result.get("failure_class"),
            retriable=result.get("retriable"),
            cache=result.get("cache"),
            lifecycle=lifecycle)
        pending.done.set()
