"""Admission-controlled request broker with a dynamic batching window.

The serving core: requests enter a BOUNDED queue (admission control —
a full queue sheds the request immediately with a retriable signal
rather than letting latency grow without bound), a single batching
worker drains it, collecting requests with the SAME `SolveSpec` until
either `nrhs_max` lanes are gathered or the batching window expires,
pads the batch to the executable cache's nrhs bucket, and runs ONE
compiled batched solve for the whole group.

Fault semantics reuse the measurement harness's taxonomy
(`harness.classify`): every failed response carries a `failure_class`,
and the retriable set (transient / timeout / oom / tunnel_wedge) maps to
"shed with retry-after" while the deterministic set (mosaic_reject /
accuracy_fail / unsupported) maps to "don't retry" — retrying a
deterministic failure just burns queue capacity, the same policy the
stage runner applies.

The queue can never deadlock on a wedged solve: each batch executes on
its own disposable thread under a hard deadline; a batch that overruns
is answered (classified `timeout`, retriable) and ABANDONED — the
worker moves on to the next batch while the stuck thread, which Python
cannot kill, is left to finish into the void. This is the in-process
analogue of the harness runner's group-kill-and-continue.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from ..harness.classify import classify_exception
from .cache import NRHS_BUCKETS, ExecutableCache, nrhs_bucket
from .engine import SolveSpec, build_solver, spec_cache_key
from .metrics import Metrics

# Classes worth a client retry (capacity/infrastructure); everything
# else in the taxonomy is deterministic — same split the stage-retry
# policy uses.
RETRIABLE_CLASSES = frozenset(
    {"transient", "timeout", "oom", "tunnel_wedge"})


class QueueFull(Exception):
    """Admission control shed the request (bounded queue at capacity).
    Retriable by contract: the server maps it to 503 + Retry-After."""


@dataclass
class PendingRequest:
    """One admitted request: the worker fulfils `result` and sets
    `done`; the submitting thread waits on it."""

    id: str
    spec: SolveSpec
    scale: float
    enqueued: float
    done: threading.Event = field(default_factory=threading.Event)
    result: dict | None = None


def _spec_dict(spec: SolveSpec) -> dict:
    return {"degree": spec.degree, "ndofs": spec.ndofs,
            "nreps": spec.nreps, "precision": spec.precision,
            "geom_perturb_fact": spec.geom_perturb_fact}


class Broker:
    def __init__(self, cache: ExecutableCache | None = None,
                 metrics: Metrics | None = None, *,
                 queue_max: int = 128, nrhs_max: int = 8,
                 window_s: float = 0.025, solve_timeout_s: float = 120.0,
                 builder=build_solver):
        self.cache = cache or ExecutableCache()
        self.metrics = metrics or Metrics()
        self.queue_max = queue_max
        self.nrhs_max = min(nrhs_max, NRHS_BUCKETS[-1])
        self.window_s = window_s
        self.solve_timeout_s = solve_timeout_s
        self._builder = builder
        self._queue: deque[PendingRequest] = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._ids = itertools.count(1)
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-broker")
        self._worker.start()

    # -- client side -------------------------------------------------------

    def submit(self, spec: SolveSpec, scale: float = 1.0,
               req_id: str | None = None) -> PendingRequest:
        """Admit one request or shed it (QueueFull). Never blocks on the
        solve — the caller waits on the returned PendingRequest."""
        rid = req_id or f"r{next(self._ids)}"
        with self._cv:
            depth = len(self._queue)
            if self._stop:
                raise QueueFull("broker is shut down")
            if depth >= self.queue_max:
                self.metrics.shed(rid, depth)
                raise QueueFull(
                    f"queue at capacity ({depth}/{self.queue_max})")
            pending = PendingRequest(rid, spec, float(scale), time.monotonic())
            self._queue.append(pending)
            self.metrics.request(rid, _spec_dict(spec), len(self._queue))
            self._cv.notify_all()
        return pending

    def wait(self, pending: PendingRequest,
             timeout_s: float | None = None) -> dict:
        """Block until the request is answered (or the wait times out —
        a retriable timeout response; the broker may still answer the
        underlying batch later, into the void)."""
        if pending.done.wait(timeout_s):
            return pending.result  # type: ignore[return-value]
        return {"ok": False, "id": pending.id,
                "error": f"response wait exceeded {timeout_s}s",
                "failure_class": "timeout", "retriable": True}

    def warmup(self, specs, bucket: int | None = None) -> list:
        """Prebuild executables for the given specs at `bucket`
        (default: the broker's own nrhs_max bucket, the one its batches
        pad to) — requests arriving after warmup never pay a compile."""
        b = bucket or nrhs_bucket(self.nrhs_max)
        return self.cache.warmup(
            [(spec_cache_key(s, b), (lambda s=s: self._builder(s, b)))
             for s in specs])

    def shutdown(self, timeout_s: float = 5.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._worker.join(timeout_s)
        # anything still queued is answered, not dropped
        with self._cv:
            leftovers = list(self._queue)
            self._queue.clear()
        for p in leftovers:
            self._respond(p, {"ok": False, "id": p.id,
                              "error": "broker shut down",
                              "failure_class": "transient",
                              "retriable": True})

    # -- worker side -------------------------------------------------------

    def _take_compatible(self, spec: SolveSpec, k: int) -> list:
        """Pull up to k same-spec requests out of the queue (FIFO among
        compatible; incompatible requests keep their positions)."""
        taken, kept = [], deque()
        while self._queue and len(taken) < k:
            p = self._queue.popleft()
            (taken if p.spec == spec else kept).append(p)
        kept.extend(self._queue)
        self._queue.clear()
        self._queue.extend(kept)
        return list(taken)

    def _loop(self) -> None:
        while True:
            batch = self._gather()
            if batch is None:
                return
            if not batch:
                continue
            try:
                self._execute(batch)
            except BaseException as exc:  # the queue must NEVER deadlock
                self._fail_batch(batch, exc)

    def _gather(self) -> list | None:
        """Block for the first request, then hold the batching window
        open: collect same-spec requests until nrhs_max or deadline.
        Returns None only on shutdown with an empty queue."""
        with self._cv:
            while not self._queue:
                if self._stop:
                    return None
                self._cv.wait(0.1)
            first = self._queue.popleft()
            batch = [first]
            deadline = time.monotonic() + self.window_s
            while len(batch) < self.nrhs_max:
                batch.extend(self._take_compatible(
                    first.spec, self.nrhs_max - len(batch)))
                if len(batch) >= self.nrhs_max:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop:
                    break
                self._cv.wait(remaining)
            self.metrics.set_queue_depth(len(self._queue))
        return batch

    def _pick_bucket(self, spec: SolveSpec, live: int) -> int:
        """Prefer the smallest ALREADY-COMPILED bucket that fits the
        batch (padding is cheap — dead lanes start frozen; a compile is
        seconds), else the minimal bucket for the batch size."""
        for b in NRHS_BUCKETS:
            if b >= live and self.cache.lookup(
                    spec_cache_key(spec, b)) is not None:
                return b
        return nrhs_bucket(live)

    def _execute(self, batch: list) -> None:
        spec = batch[0].spec
        live = len(batch)
        bucket = self._pick_bucket(spec, live)
        key = spec_cache_key(spec, bucket)
        cache_hit = self.cache.lookup(key) is not None
        scales = [p.scale for p in batch]
        box: dict = {}

        def _run():
            try:
                entry = self.cache.get_or_build(
                    key, lambda: self._builder(spec, bucket))
                box["result"] = entry.executable.solve(scales)
            except BaseException as exc:
                box["error"] = exc

        t = threading.Thread(target=_run, daemon=True,
                             name="serve-solve")
        t.start()
        t.join(self.solve_timeout_s)
        if t.is_alive():
            # hard deadline: answer + abandon (the harness's
            # kill-the-group, minus the kill Python threads lack)
            msg = (f"solve exceeded {self.solve_timeout_s}s "
                   f"(spec {_spec_dict(spec)}); batch abandoned")
            for p in batch:
                self._respond(p, {
                    "ok": False, "id": p.id, "error": msg,
                    "failure_class": "timeout", "retriable": True})
            self.metrics.batch(_spec_dict(spec), live, bucket, cache_hit,
                               self.solve_timeout_s, 0.0)
            return
        if "error" in box:
            self._fail_batch(batch, box["error"], bucket=bucket,
                             cache_hit=cache_hit)
            return
        res = box["result"]
        self.metrics.batch(_spec_dict(spec), live, res.nrhs_bucket,
                           cache_hit, res.wall_s, res.gdof_per_second)
        now = time.monotonic()
        for lane, p in enumerate(batch):
            self._respond(p, {
                "ok": True, "id": p.id,
                "xnorm": res.xnorms[lane],
                "scale": p.scale,
                "spec": _spec_dict(spec),
                "nrhs_live": res.nrhs_live,
                "nrhs_bucket": res.nrhs_bucket,
                "ndofs_global": res.ndofs_global,
                "cg_engine_form": "unfused",
                "cache": "hit" if cache_hit else "miss",
                "batch_wall_s": res.wall_s,
                "gdof_per_second": res.gdof_per_second,
                "latency_s": now - p.enqueued,
            })

    def _fail_batch(self, batch: list, exc: BaseException, *,
                    bucket: int | None = None,
                    cache_hit: bool = False) -> None:
        cls = classify_exception(exc)
        retriable = cls in RETRIABLE_CLASSES
        spec = batch[0].spec
        self.metrics.batch(_spec_dict(spec), len(batch),
                           bucket or nrhs_bucket(len(batch)), cache_hit,
                           0.0, 0.0)
        for p in batch:
            self._respond(p, {
                "ok": False, "id": p.id,
                "error": f"{type(exc).__name__}: {exc}"[:500],
                "failure_class": cls, "retriable": retriable})

    def _respond(self, pending: PendingRequest, result: dict) -> None:
        if pending.done.is_set():
            return
        pending.result = result
        latency = time.monotonic() - pending.enqueued
        self.metrics.response(
            pending.id, bool(result.get("ok")), latency,
            failure_class=result.get("failure_class"),
            retriable=result.get("retriable"))
        pending.done.set()
