"""Admission-controlled request broker with continuous batching.

The serving core: requests enter a BOUNDED queue (admission control —
a full queue sheds the request immediately with a retriable signal
rather than letting latency grow without bound), a single batching
worker drains it, collecting requests with the SAME `SolveSpec` until
either `nrhs_max` lanes are gathered or the batching window expires,
pads the batch to the executable cache's nrhs bucket, and starts ONE
compiled batched solve for the group.

For solvers exposing the iteration-boundary checkpoint API
(f32/f64 — serve.engine.CompiledSolver.supports_continuous), the batch
then runs CONTINUOUSLY, the shape LLM inference servers use: at every
`iter_chunk` iteration boundary the worker retires lanes that finished
their budget (answering those requests immediately — a finished request
never waits for its batch-mates) and admits compatible queued requests
into the freed lanes mid-solve (`serve_admit` journal records with
midsolve=true; each admitted lane gets its full iteration budget). The
solve ends when no lane is live and no compatible request is queued —
so under sustained traffic one batch can serve many windows' worth of
requests with lane occupancy pinned near the bucket instead of sawing
down as lanes finish. Solvers without the checkpoint API (df32) keep
the fixed-window one-shot batch, reason recorded.

Fault semantics reuse the measurement harness's taxonomy
(`harness.classify`): every failed response carries a `failure_class`,
and the retriable set (transient / timeout / oom / tunnel_wedge) maps to
"shed with retry-after" while the deterministic set (mosaic_reject /
accuracy_fail / unsupported) maps to "don't retry" — retrying a
deterministic failure just burns queue capacity, the same policy the
stage runner applies.

The queue can never deadlock on a wedged solve: each batch executes on
its own disposable thread under a hard deadline; a batch that overruns
is answered (classified `timeout`, retriable) and ABANDONED — the
worker moves on to the next batch while the stuck thread, which Python
cannot kill, is left to finish into the void. This is the in-process
analogue of the harness runner's group-kill-and-continue.
"""

from __future__ import annotations

import math
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field

# RETRIABLE_CLASSES (re-exported): classes worth a client retry
# (capacity/infrastructure, now including `preempted`); everything else
# in the taxonomy is deterministic — the ONE split, shared with the
# stage-retry policy and the chaos invariants (harness.classify owns it).
from ..harness.classify import RETRIABLE_CLASSES, classify_exception
from ..obs.reqtrace import ReqTrace
from ..obs.trace import Lifecycle, span
from .cache import NRHS_BUCKETS, ExecutableCache, nrhs_bucket
from .engine import SolveSpec, build_solver, spec_cache_key
from .metrics import Metrics, spec_latency_key


class QueueFull(Exception):
    """Admission control shed the request (bounded queue at capacity,
    or — ISSUE 18 — a predictive deadline refusal). Retriable by
    contract: the server maps it to 503 + Retry-After.

    ``failure_class`` distinguishes the capacity shed ("transient")
    from the deadline refusal ("deadline_exceeded"); ``retry_after_s``
    is the predicted-queue-time hint when the predictor had one."""

    def __init__(self, msg: str, *, failure_class: str = "transient",
                 retry_after_s: float | None = None):
        super().__init__(msg)
        self.failure_class = failure_class
        self.retry_after_s = retry_after_s


@dataclass
class PendingRequest:
    """One admitted request: a responder claims it (`answered`, under
    the broker's response lock), fulfils `result` and sets `done`; the
    submitting thread waits on `done`. With continuous batching two
    threads can race to answer (the solve thread's retire loop vs the
    worker's timeout path), so the claim must be atomic — `done` alone
    is a check-then-act hole.

    ``lc`` carries the request's lifecycle marks
    (enqueue -> admit -> solve -> respond, obs.trace.Lifecycle): every
    latency the broker reports derives from these marks instead of
    ad-hoc time.monotonic() arithmetic, and the per-stage breakdown
    rides on the response/journal. ``enqueued`` is kept as an alias of
    the enqueue mark (existing readers)."""

    id: str
    spec: SolveSpec
    scale: float
    enqueued: float
    # warm-start hint (ISSUE 20, heat workload): the lane starts from
    # x0 = warm_scale * xbase on solvers that support it; 0.0 (the
    # default) is bitwise the cold admit, so every pre-zoo request is
    # untouched
    warm_scale: float = 0.0
    done: threading.Event = field(default_factory=threading.Event)
    result: dict | None = None
    answered: bool = False
    # SDC adjudication state (ISSUE 14): how many corruption-detected
    # rollback re-runs this request has consumed. One is the budget —
    # a second detection is the deterministic verdict.
    sdc_retries: int = 0
    # Overload resilience (ISSUE 18): the ABSOLUTE monotonic deadline
    # (enqueue instant + spec.deadline_s; None = unbounded) every phase
    # boundary checks against; the hedge-pair state (the SAME object is
    # enqueued on a second lane — `hedged` marks it, `hedge_dst` the
    # lane the copy landed on, for win attribution); and the brownout
    # provenance stamp the responding lane merges into the result.
    deadline: float | None = None
    hedged: bool = False
    hedge_dst: str | None = None
    degraded: dict | None = None
    lc: Lifecycle = field(default_factory=Lifecycle)
    # request-scoped phase trace (ISSUE 15): populated ONLY when the
    # broker was built with reqtrace=True — None is the pre-PR path
    # (zero allocations, zero clock reads beyond the Lifecycle marks)
    rt: ReqTrace | None = None
    # claim lock: PER REQUEST, not broker-global — the exactly-once
    # contract only needs responders to the SAME request serialized;
    # a global lock would funnel every response in the broker through
    # one journal fsync at a time
    lock: threading.Lock = field(default_factory=threading.Lock)

    def __post_init__(self):
        self.lc.marks.setdefault("enqueue", self.enqueued)


def _spec_dict(spec: SolveSpec) -> dict:
    d = {"degree": spec.degree, "ndofs": spec.ndofs,
         "nreps": spec.nreps, "precision": spec.precision,
         "geom_perturb_fact": spec.geom_perturb_fact}
    if spec.form != "poisson":
        # additive: poisson journal records keep their pre-zoo bytes,
        # and SolveSpec(**spec_dict) replays via the field default
        d["form"] = spec.form
    return d


class Broker:
    def __init__(self, cache: ExecutableCache | None = None,
                 metrics: Metrics | None = None, *,
                 queue_max: int = 128, nrhs_max: int = 8,
                 window_s: float = 0.025, solve_timeout_s: float = 120.0,
                 continuous: bool = True, builder=build_solver,
                 retry_max: int = 1, retry_backoff_s: float = 0.05,
                 retry_jitter: float = 0.5, sleep=time.sleep, rng=None,
                 audit: bool = False, reqtrace: bool = False):
        self.cache = cache or ExecutableCache()
        self.metrics = metrics or Metrics()
        self.queue_max = queue_max
        self.nrhs_max = min(nrhs_max, NRHS_BUCKETS[-1])
        self.window_s = window_s
        self.solve_timeout_s = solve_timeout_s
        # continuous=False pins every solver to fixed-window one-shot
        # batches — the A/B baseline the occupancy acceptance compares
        # against (serve CLI --no-continuous).
        self.continuous = continuous
        self._builder = builder
        # Broker-internal bounded retry (ISSUE 9): a batch whose solve
        # fails with a RETRIABLE class is re-run up to `retry_max` times
        # with exponential backoff + jitter (jitter so a fleet of
        # brokers recovering from one shared transient doesn't
        # re-converge on the same instant) — transient faults stop being
        # the client's problem. Deterministic classes never retry.
        self.retry_max = max(int(retry_max), 0)
        self.retry_backoff_s = retry_backoff_s
        self.retry_jitter = retry_jitter
        # SDC retire-time audit (ISSUE 14): when armed, every live lane
        # is true-residual-audited BEFORE its retirement; an exceedance
        # rolls the lane back once (the re-run adjudicates transient vs
        # deterministic) then answers `failure_class: "sdc"`. Off (the
        # default) is the pre-PR retire path exactly — no extra
        # compiled calls anywhere.
        self.audit = bool(audit)
        # Request-scoped tracing (ISSUE 15): when armed, every request
        # carries a ReqTrace whose consecutive cuts partition its
        # lifetime into queue/compile/solve/audit/retry/respond — the
        # decomposition rides as ADDITIVE fields on the existing WAL
        # records (plus one serve_phase record per batch execution).
        # Off (the default) is the pre-PR code path: no trace object,
        # no new journal records, no extra fsyncs or host syncs.
        self.reqtrace = bool(reqtrace)
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._queue: deque[PendingRequest] = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._next_id = 1  # guarded by _cv (see submit/recover)
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-broker")
        self._worker.start()

    # -- client side -------------------------------------------------------

    def submit(self, spec: SolveSpec, scale: float = 1.0,
               req_id: str | None = None,
               degraded: dict | None = None,
               warm_scale: float = 0.0) -> PendingRequest:
        """Admit one request or shed it (QueueFull). Never blocks on the
        solve — the caller waits on the returned PendingRequest.
        ``degraded`` (ISSUE 18) is the fleet's brownout provenance
        stamp: attached BEFORE the request is visible to any responder,
        so every response under brownout carries it race-free.
        ``warm_scale`` (ISSUE 20) seeds warm-start-capable solvers with
        x0 = warm_scale * xbase; 0.0 is the cold path bitwise."""
        with self._cv:
            if req_id is None:
                # id minting under the queue lock: recover() bumps the
                # counter under the same lock, so a submission racing a
                # journal replay can never mint an id colliding with a
                # replayed request's original id
                rid = f"r{self._next_id}"
                self._next_id += 1
            else:
                rid = req_id
            depth = len(self._queue)
            if self._stop:
                raise QueueFull("broker is shut down")
            if depth >= self.queue_max:
                self.metrics.shed(rid, depth)
                raise QueueFull(
                    f"queue at capacity ({depth}/{self.queue_max})")
            if spec.deadline_s is not None:
                # predictive admission control (ISSUE 18): refuse to
                # seat a request whose predicted completion (queue wait
                # + p95 solve, folded from the live per-spec latency
                # windows) exceeds its whole budget — shed NOW, before
                # the WAL record, before any work, with the prediction
                # inputs journaled so the decision replays from the
                # serve_shed line alone. No prediction (cold windows) =
                # no predictive shed: never refuse on thin evidence.
                pred = self.metrics.predict_completion(_spec_dict(spec))
                if pred is not None:
                    queue_wait = (depth / max(self.nrhs_max, 1)) \
                        * pred["p50_s"]
                    predicted = queue_wait + pred["p95_s"]
                    if predicted > spec.deadline_s:
                        retry_after = round(max(queue_wait,
                                                pred["p50_s"]), 3)
                        controller = {
                            "decision": "predictive_shed",
                            "deadline_s": spec.deadline_s,
                            "queue_depth": depth,
                            "nrhs_max": self.nrhs_max,
                            "queue_wait_s": round(queue_wait, 6),
                            "predicted_s": round(predicted, 6),
                            "prediction": pred}
                        self.metrics.shed(
                            rid, depth,
                            failure_class="deadline_exceeded",
                            controller=controller,
                            retry_after_s=retry_after)
                        raise QueueFull(
                            f"predicted completion {predicted:.3f}s "
                            "exceeds the remaining deadline budget "
                            f"{spec.deadline_s:.3f}s",
                            failure_class="deadline_exceeded",
                            retry_after_s=retry_after)
            pending = PendingRequest(rid, spec, float(scale), time.monotonic(),
                                     warm_scale=float(warm_scale))
            if spec.deadline_s is not None:
                pending.deadline = pending.enqueued + spec.deadline_s
            if degraded is not None:
                pending.degraded = degraded
            if self.reqtrace:
                # the trace origin IS the enqueue instant, so the phase
                # sum and the journaled latency_s share one origin
                pending.rt = ReqTrace(rid, t0=pending.enqueued)
            self._queue.append(pending)
            # the write-ahead admitted-request record (ISSUE 9): journaled
            # (fsynced, Journal.append) BEFORE the client gets its future
            # back, carrying spec + scale so a crashed generation's
            # recovery can replay the request (serve.recovery)
            self.metrics.request(rid, _spec_dict(spec), len(self._queue),
                                 scale=float(scale),
                                 warm_scale=float(warm_scale) or None)
            self._cv.notify_all()
        return pending

    def wait(self, pending: PendingRequest,
             timeout_s: float | None = None) -> dict:
        """Block until the request is answered (or the wait times out —
        a retriable timeout response; the broker may still answer the
        underlying batch later, into the void)."""
        if pending.done.wait(timeout_s):
            return pending.result  # type: ignore[return-value]
        return {"ok": False, "id": pending.id,
                "error": f"response wait exceeded {timeout_s}s",
                "failure_class": "timeout", "retriable": True}

    def warmup(self, specs, bucket: int | None = None) -> list:
        """Prebuild executables for the given specs at `bucket`
        (default: the broker's own nrhs_max bucket, the one its batches
        pad to) — requests arriving after warmup never pay a compile."""
        b = bucket or nrhs_bucket(self.nrhs_max)
        return self.cache.warmup(
            [(spec_cache_key(s, b), (lambda s=s: self._builder(s, b)))
             for s in specs])

    def metrics_snapshot(self, memory: dict | None = None) -> dict:
        """The /metrics snapshot (counters + cache stats + optional
        memory telemetry) — the one entry point the HTTP front end
        calls, shared by shape with FleetDispatcher.metrics_snapshot."""
        return self.metrics.snapshot(cache_stats=self.cache.stats(),
                                     memory=memory)

    def shutdown(self, timeout_s: float = 5.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._worker.join(timeout_s)
        # anything still queued is answered, not dropped
        with self._cv:
            leftovers = list(self._queue)
            self._queue.clear()
        for p in leftovers:
            self._respond(p, {"ok": False, "id": p.id,
                              "error": "broker shut down",
                              "failure_class": "transient",
                              "retriable": True})

    # -- fleet side (ISSUE 13) ---------------------------------------------

    def pending_count(self) -> int:
        """Current queue depth (the fleet balancer's imbalance input)."""
        with self._cv:
            return len(self._queue)

    def peek_queued(self) -> list:
        """Snapshot of the queued requests, arrival order (the fleet's
        hedge scan reads wait times off it; the requests stay queued —
        a hedge is an ADDITIONAL enqueue elsewhere, never a move)."""
        with self._cv:
            return list(self._queue)

    def steal_requests(self, k: int) -> list:
        """Pop up to k requests off the queue TAIL, returned in ARRIVAL
        order (the oldest requests keep their place at their home lane,
        where they will be served soonest, and the stolen set re-enqueues
        FIFO at the destination — fairness survives the steal end to
        end). The fleet balancer moves them to a colder lane via
        adopt_pending. The requests' write-ahead records already exist —
        stealing is a pure queue move, invisible to the exactly-once
        ledger."""
        stolen: list[PendingRequest] = []
        with self._cv:
            while self._queue and len(stolen) < k:
                stolen.append(self._queue.pop())
            self.metrics.set_queue_depth(len(self._queue))
        stolen.reverse()  # popped newest-first; hand back arrival order
        return stolen

    def adopt_pending(self, reqs: list) -> None:
        """Enqueue already-admitted requests (stolen from a peer lane or
        replayed by a standby adoption): bypasses the queue_max cap (the
        requests were admitted once — a full queue must not convert an
        admitted request into a loss) and writes NO new serve_request
        record (the WAL line already exists)."""
        if not reqs:
            return
        with self._cv:
            self._queue.extend(reqs)
            self.metrics.set_queue_depth(len(self._queue))
            self._cv.notify_all()

    def _replay_request(self, req: dict) -> PendingRequest | None:
        """Re-admit ONE journaled outstanding request under its ORIGINAL
        id (the shared half of Broker.recover and the fleet's standby
        adoption). Returns the pending, or None when the record is too
        damaged to rebuild its spec — in which case the id is answered
        with a TERMINAL `unsupported` response so the exactly-once
        ledger closes instead of reading it as LOST forever."""
        try:
            spec = SolveSpec(**req["spec"])
            spec.validate()
        except Exception:
            self.metrics.response(req["id"], False, 0.0,
                                  failure_class="unsupported",
                                  retriable=False)
            return None
        pending = PendingRequest(req["id"], spec,
                                 float(req.get("scale", 1.0)),
                                 time.monotonic(),
                                 warm_scale=float(
                                     req.get("warm_scale", 0.0)))
        if self.reqtrace:
            pending.rt = ReqTrace(pending.id, t0=pending.enqueued)
            pending.rt.annotate(replayed=True)
        with self._cv:
            self._queue.append(pending)
            self._cv.notify_all()
        return pending

    # -- worker side -------------------------------------------------------

    def _take_compatible(self, spec: SolveSpec, k: int) -> list:
        """Pull up to k same-spec requests out of the queue (FIFO among
        compatible; incompatible requests keep their positions)."""
        taken, kept = [], deque()
        while self._queue and len(taken) < k:
            p = self._queue.popleft()
            (taken if p.spec == spec else kept).append(p)
        kept.extend(self._queue)
        self._queue.clear()
        self._queue.extend(kept)
        return list(taken)

    def _loop(self) -> None:
        while True:
            batch = self._gather()
            if batch is None:
                return
            if not batch:
                continue
            try:
                self._execute(batch)
            except BaseException as exc:  # the queue must NEVER deadlock
                self._fail_batch(batch, exc)

    def _gather(self) -> list | None:
        """Block for the first request, then hold the batching window
        open: collect same-spec requests until nrhs_max or deadline.
        Returns None only on shutdown with an empty queue."""
        with self._cv:
            while not self._queue:
                if self._stop:
                    return None
                self._cv.wait(0.1)
            first = self._queue.popleft()
            batch = [first]
            deadline = time.monotonic() + self.window_s
            while len(batch) < self.nrhs_max:
                batch.extend(self._take_compatible(
                    first.spec, self.nrhs_max - len(batch)))
                if len(batch) >= self.nrhs_max:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0 or self._stop:
                    break
                self._cv.wait(remaining)
            self.metrics.set_queue_depth(len(self._queue))
        return batch

    def _pick_bucket(self, spec: SolveSpec, live: int) -> int:
        """Prefer the smallest ALREADY-PROVISIONED bucket that fits the
        batch (padding is cheap — dead lanes start frozen; a compile is
        seconds), else the minimal bucket for the batch size.
        "Provisioned" includes peer-published AOT artifacts (ISSUE 13):
        a cold replica prefers the bucket it can warm-load with zero
        recompiles over the minimal one it would have to compile."""
        for b in NRHS_BUCKETS:
            if b >= live and self.cache.provisioned(
                    spec_cache_key(spec, b)):
                return b
        return nrhs_bucket(live)

    def _poll_compatible(self, spec: SolveSpec, k: int) -> list:
        """Queue poll from the solve thread (continuous admissions):
        same-spec FIFO extraction under the lock."""
        with self._cv:
            taken = self._take_compatible(spec, k)
            self.metrics.set_queue_depth(len(self._queue))
        return taken

    def _requeue_front(self, reqs: list) -> None:
        """Put polled-but-not-admitted requests back at the queue front
        (relative order kept): a crash between the queue pop and the
        admission park must strand nobody. Bypasses the queue_max cap —
        these requests were already admitted once."""
        with self._cv:
            for p in reversed(reqs):
                self._queue.appendleft(p)
            self.metrics.set_queue_depth(len(self._queue))
            self._cv.notify_all()

    def _screen_batch(self, batch: list, boundary: int = 0) -> list:
        """Deadline/hedge screening at a phase boundary (ISSUE 18):
        batch formation and every mid-solve admission poll. Hedge-pair
        losers (the other lane already won the claim CAS) are dropped
        with a serve_hedge_cancelled record; members whose budget is
        already gone — or whose predicted solve time exceeds what
        remains — are answered ``deadline_exceeded`` WITHOUT burning a
        solve lane, controller inputs journaled. Requests with no
        deadline and no hedge pass through untouched: the unarmed path
        is bitwise pre-PR."""
        kept = []
        now = time.monotonic()
        for p in batch:
            if p.hedged and p.answered:
                self.metrics.hedge_cancel(p.id, -1, boundary)
                continue
            if p.deadline is not None and not p.answered:
                remaining = p.deadline - now
                if remaining <= 0:
                    self._respond(p, {
                        "ok": False, "id": p.id,
                        "error": (f"request {p.id} is past its deadline "
                                  f"({-remaining:.3f}s over) at batch "
                                  "formation; answered without a solve"),
                        "failure_class": "deadline_exceeded",
                        "retriable": True,
                        "controller": {"decision": "expired_in_queue",
                                       "boundary": boundary,
                                       "over_s": round(-remaining, 6)}})
                    continue
                pred = self.metrics.predict_completion(
                    _spec_dict(p.spec))
                if pred is not None and pred["p95_s"] > remaining:
                    self._respond(p, {
                        "ok": False, "id": p.id,
                        "error": (f"predicted solve p95 "
                                  f"{pred['p95_s']:.3f}s exceeds the "
                                  f"remaining deadline budget "
                                  f"{remaining:.3f}s"),
                        "failure_class": "deadline_exceeded",
                        "retriable": True,
                        "controller": {"decision": "predicted_over_budget",
                                       "boundary": boundary,
                                       "remaining_s": round(remaining, 6),
                                       "prediction": pred}})
                    continue
            kept.append(p)
        return kept

    def _execute(self, batch: list) -> None:
        batch = self._screen_batch(batch)
        if not batch:
            return
        spec = batch[0].spec
        live = len(batch)
        bucket = self._pick_bucket(spec, live)
        key = spec_cache_key(spec, bucket)
        cache_hit = self.cache.lookup(key) is not None
        for p in batch:
            p.lc.mark("admit")  # window-seeded members enter the batch
            if p.rt is not None:
                p.rt.cut("queue")  # queue wait ends at batch formation
        # `members` grows with mid-solve admissions: the timeout/failure
        # paths below must answer every request the solve ever owned
        # (_respond skips the already-answered ones).
        members = list(batch)
        # resume box (ISSUE 9): _solve_continuous parks its latest
        # iteration-boundary checkpoint here (state + lane map +
        # accounting). A retriable worker-thread crash re-enters the
        # solve FROM that boundary instead of abandoning the batch or
        # restarting at iteration 0 (metrics.retry resumed=true).
        resume: dict = {}
        # the admission horizon is anchored where the HARD deadline is
        # (batch-execution start, before any compile): a zombie solve
        # thread must stop admitting BEFORE the worker can abandon the
        # batch, or admitted requests would sit outside any deadline
        # cover
        admit_deadline = time.monotonic() + self.solve_timeout_s / 2
        attempt = 0
        while True:
            box: dict = {}

            def _run():
                try:
                    with span("serve:solve", spec=_spec_dict(spec),
                              bucket=bucket, live=len(members)):
                        entry = self.cache.get_or_build(
                            key, lambda: self._builder(spec, bucket))
                        solver = entry.executable
                        if self.reqtrace:
                            # cache resolution settled: hit (already in
                            # memory) / artifact-warm (peer AOT load) /
                            # compile — the serve_phase record is the
                            # one phase boundary with no WAL record
                            source = (
                                "hit" if cache_hit else
                                "artifact-warm"
                                if getattr(solver, "warm_source",
                                           None) == "artifact"
                                else "compile")
                            for p in members:
                                if p.rt is not None and not p.answered:
                                    p.rt.annotate_default("cache_source",
                                                          source)
                            info = getattr(solver, "trace_info", None)
                            self.metrics.phase_event(
                                [p.id for p in members], "execute",
                                cache_source=source, bucket=bucket,
                                attempt=attempt,
                                **(info() if callable(info) else {}))
                        for p in members:
                            p.lc.mark("solve")
                            if p.rt is not None and not p.answered:
                                # compile/cache-resolution window ends:
                                # the executable is in hand
                                p.rt.cut("compile")
                        if self.continuous and getattr(
                                solver, "supports_continuous", False):
                            box["summary"] = self._solve_continuous(
                                solver, spec, members, bucket, cache_hit,
                                admit_deadline, resume)
                        else:
                            box["result"] = solver.solve(
                                [p.scale for p in members])
                except BaseException as exc:
                    box["error"] = exc

            t = threading.Thread(target=_run, daemon=True,
                                 name="serve-solve")
            t.start()
            t.join(self.solve_timeout_s)
            if t.is_alive():
                # hard deadline: answer + abandon (the harness's
                # kill-the-group, minus the kill Python threads lack).
                # Continuous members already retired were answered as
                # they finished; _respond skips them here. Never
                # retried: the zombie thread still owns the members and
                # the resume state — a resumed attempt would race it.
                msg = (f"solve exceeded {self.solve_timeout_s}s "
                       f"(spec {_spec_dict(spec)}); batch abandoned")
                for p in members:
                    if p.rt is not None and not p.answered:
                        # the abandoned wait was spent inside the solve
                        p.rt.cut("solve")
                    self._respond(p, {
                        "ok": False, "id": p.id, "error": msg,
                        "failure_class": "timeout", "retriable": True})
                self.metrics.batch(_spec_dict(spec), len(members), bucket,
                                   cache_hit, self.solve_timeout_s, 0.0)
                return
            if "error" in box:
                exc = box["error"]
                cls = classify_exception(exc)
                # broker-internal bounded retry (ISSUE 9): transient
                # faults stop being the client's problem. Deterministic
                # classes fail straight through — retrying them burns
                # queue capacity for the same answer. The solve thread
                # has EXITED here (unlike the timeout path), so a
                # resumed attempt races nobody. `sdc` gets the SAME
                # internal retry (the re-run IS the adjudication,
                # ISSUE 14) while staying outside RETRIABLE_CLASSES —
                # a batch that fails sdc AGAIN answers the client
                # retriable:false, matching the audit path's verdict.
                if (cls in RETRIABLE_CLASSES or cls == "sdc") \
                        and attempt < self.retry_max:
                    attempt += 1
                    wait = self.retry_backoff_s * (2 ** (attempt - 1))
                    wait *= 1.0 + self.retry_jitter * self._rng.random()
                    resumed = resume.get("state") is not None
                    if resumed:
                        # reconcile members against the parked lane map:
                        # a member that is unanswered and in no parked
                        # lane (the crash hit between its admission and
                        # its park) is invisible to the resumed solve —
                        # requeue it rather than lose it.
                        parked = {id(q) for q in resume["lanes"]
                                  if q is not None}
                        orphans = [q for q in members
                                   if not q.answered
                                   and id(q) not in parked]
                        if orphans:
                            self._requeue_front(orphans)
                            gone = {id(q) for q in orphans}
                            members = [q for q in members
                                       if id(q) not in gone]
                    with span("serve:retry", failure_class=cls,
                              attempt=attempt, resumed=resumed):
                        self.metrics.retry(_spec_dict(spec), cls, attempt,
                                           wait, resumed)
                    self._sleep(wait)
                    for p in members:
                        if p.rt is not None and not p.answered:
                            # the failed attempt + its backoff are the
                            # retry segment; the next attempt's cache
                            # re-resolution re-opens compile
                            p.rt.retries += 1
                            p.rt.event("retry", failure_class=cls,
                                       attempt=attempt, resumed=resumed)
                            p.rt.cut("retry")
                    continue
                self._fail_batch(members, exc, bucket=bucket,
                                 cache_hit=cache_hit)
                return
            break
        if "summary" in box:
            # continuous: per-request responses went out at each retire;
            # here only the batch-level accounting lands
            s = box["summary"]
            self.metrics.batch(
                _spec_dict(spec), s["served"], bucket, cache_hit,
                s["wall_s"], s["gdof_per_second"],
                padded_lanes=s["padded_lanes"], midsolve=s["midsolve"],
                boundaries=s["boundaries"],
                live_lane_boundaries=s["live_lane_boundaries"],
                continuous=True)
            return
        res = box["result"]
        self.metrics.batch(_spec_dict(spec), live, res.nrhs_bucket,
                           cache_hit, res.wall_s, res.gdof_per_second)
        for lane, p in enumerate(batch):
            if p.rt is not None and not p.answered:
                p.rt.cut("solve")
                p.rt.annotate(lane=lane, batch_mates=live - 1)
            if not math.isfinite(res.xnorms[lane]):
                # breakdown sentinel, one-shot path (incl. df32): same
                # contract as the continuous retire check above
                self._respond(p, {
                    "ok": False, "id": p.id,
                    "error": ("non-finite solution norm "
                              f"({res.xnorms[lane]!r}): CG breakdown"),
                    "failure_class": "breakdown", "retriable": False,
                    "spec": _spec_dict(spec), "continuous": False})
                continue
            self._respond(p, {
                "ok": True, "id": p.id,
                "xnorm": res.xnorms[lane],
                "scale": p.scale,
                "spec": _spec_dict(spec),
                "nrhs_live": res.nrhs_live,
                "nrhs_bucket": res.nrhs_bucket,
                "ndofs_global": res.ndofs_global,
                "cg_engine_form": res.extra.get("cg_engine_form",
                                                "unfused"),
                "continuous": False,
                "cache": "hit" if cache_hit else "miss",
                "batch_wall_s": res.wall_s,
                "gdof_per_second": res.gdof_per_second,
            })

    def _solve_continuous(self, solver, spec: SolveSpec, members: list,
                          bucket: int, cache_hit: bool,
                          admit_deadline: float,
                          resume: dict | None = None) -> dict:
        """Run one continuous batch on the solve thread: step the
        compiled solve `iter_chunk` iterations at a time; at every
        boundary retire finished lanes (responding immediately) and
        admit compatible queued requests into the freed lanes. Returns
        the batch-level accounting for metrics.batch.

        `admit_deadline` (half the solve timeout, anchored by the
        caller at batch-execution start so a slow compile eats into it
        rather than extending it) closes the admission horizon well
        before the worker's hard deadline: a sustained request stream
        cannot hold one batch past the abandon point, and an abandoned
        zombie thread can never keep pulling fresh requests into a
        batch nobody is watching — remaining lanes drain, the batch
        ends, the worker forms a fresh batch for whatever is queued.

        `resume` (ISSUE 9) is the caller-owned boundary checkpoint box:
        after every boundary's retire/admit processing the solve parks
        its state (immutable pytree), lane map and accounting there; a
        retrying `_execute` passes the same box back and the solve
        continues FROM that boundary — already-retired lanes stay
        retired (their requests were answered; `_respond` would skip a
        re-answer anyway), in-flight lanes keep their iterates."""
        resume = resume if resume is not None else {}
        if resume.get("state") is not None:
            # resumed attempt: continue the crashed attempt's batch at
            # its last parked boundary (no cont_init — the fault hook
            # already fired on the attempt that crashed)
            state = resume["state"]
            lanes = list(resume["lanes"])
            (served, midsolve, boundaries, live_lane_boundaries,
             dead_lane_boundaries, boundary_iter, wall_accum) = resume["acct"]
        else:
            if getattr(solver, "supports_warm", False):
                state = solver.cont_init(
                    [p.scale for p in members],
                    warm_scales=[p.warm_scale for p in members])
            else:
                state = solver.cont_init([p.scale for p in members])
            lanes = [None] * bucket
            served = midsolve = boundaries = live_lane_boundaries = 0
            dead_lane_boundaries = 0
            boundary_iter = 0
            wall_accum = 0.0
            for lane, p in enumerate(members):
                lanes[lane] = p
                self.metrics.admit(p.id, lane, 0, False, lane + 1)
            # park boundary 0 immediately: a crash BEFORE the first
            # in-loop park (a hook at boundary 0, the first chunk) must
            # retry down the resumed path — re-running cont_init would
            # journal every member's serve_admit record a second time
            # and double-count those lanes in journal replay
            resume["lanes"] = list(lanes)
            resume["acct"] = (served, midsolve, boundaries,
                              live_lane_boundaries, dead_lane_boundaries,
                              boundary_iter, wall_accum)
            resume["state"] = state
        t0 = time.monotonic()

        def spec_d():
            return _spec_dict(spec)

        def park():
            # park the boundary checkpoint: everything a resumed attempt
            # needs to continue from HERE instead of iteration 0. Called
            # after every journaled lane mutation (retire sweep, each
            # admission, end of boundary) so a retriable crash BETWEEN
            # mutations can neither re-journal a retired lane nor drop
            # an admitted one on resume.
            resume["lanes"] = list(lanes)
            resume["acct"] = (served, midsolve, boundaries,
                              live_lane_boundaries, dead_lane_boundaries,
                              boundary_iter,
                              wall_accum + (time.monotonic() - t0))
            resume["state"] = state

        from . import engine as _engine

        while any(p is not None for p in lanes):
            if _engine.BOUNDARY_HOOK is not None:
                _engine.BOUNDARY_HOOK(spec, boundary_iter)
            state = solver.cont_step(state)
            if _engine.SDC_HOOK is not None:
                # corruption seam (ISSUE 14): the hook may hand back a
                # bit-flipped state — finite, wrong, invisible to
                # everything except the retire-time audit below
                mutated = _engine.SDC_HOOK(spec, boundary_iter, state)
                if mutated is not None:
                    state = mutated
            boundary_iter += solver.iter_chunk
            iters, done = solver.cont_poll(state)
            live = sum(1 for p in lanes if p is not None)
            boundaries += 1
            live_lane_boundaries += live
            dead_lane_boundaries += bucket - live
            now = time.monotonic()
            for lane, p in enumerate(lanes):
                if p is None:
                    continue
                if p.hedged and p.answered:
                    # hedge-pair loser (ISSUE 18): the copy on the
                    # other lane won the claim CAS — cancelled at THIS
                    # boundary (the next one after the win), lane
                    # freed, no second response ever journaled
                    state, _ = solver.cont_retire(state, lane)
                    lanes[lane] = None
                    live -= 1
                    self.metrics.hedge_cancel(p.id, lane, boundary_iter)
                    park()
                    continue
                if not bool(done[lane]):
                    continue
                if p.rt is not None:
                    # the lane's solve occupancy ends at THIS boundary;
                    # occupancy metadata rides for the exemplar render
                    p.rt.cut("solve")
                    p.rt.annotate(lane=lane,
                                  iters_run=int(iters[lane]),
                                  batch_mates=live - 1)
                if self.audit and hasattr(solver, "audit_lane"):
                    try:
                        verdict = solver.audit_lane(state, lane, p.scale)
                    except Exception:
                        verdict = None  # the audit must never sink a solve
                    if p.rt is not None:
                        p.rt.cut("audit")  # retire-time audit window
                    if verdict is not None and not verdict["ok"]:
                        action = ("rollback" if p.sdc_retries < 1
                                  else "terminal")
                        self.metrics.sdc(p.id, lane, verdict["drift"],
                                         verdict["envelope"], action)
                        if action == "rollback":
                            # corruption-aware rollback (ISSUE 14): the
                            # lane's durable checkpoint is its
                            # write-ahead record — discard the corrupted
                            # iterates and re-run the lane from scratch;
                            # the re-run IS the transient-vs-
                            # deterministic adjudication. Lane-local:
                            # batch-mates never notice.
                            if p.rt is not None:
                                p.rt.event("sdc_rollback", lane=lane,
                                           drift=verdict["drift"])
                                # the re-run is a retry segment: solve
                                # time re-opens after this cut
                                p.rt.cut("retry")
                            p.sdc_retries += 1
                            state, _ = solver.cont_retire(state, lane)
                            if getattr(solver, "supports_warm", False):
                                state = solver.cont_admit(
                                    state, lane, p.scale,
                                    warm_scale=p.warm_scale)
                            else:
                                state = solver.cont_admit(state, lane,
                                                          p.scale)
                            park()
                            continue
                        # detected AGAIN on the re-run: deterministic
                        # fault — answer terminally, never retried (the
                        # fleet's quarantine watches these)
                        state, _ = solver.cont_retire(state, lane)
                        lanes[lane] = None
                        live -= 1
                        served += 1
                        self.metrics.retire(p.id, lane, boundary_iter,
                                            int(iters[lane]), live)
                        park()
                        self._respond(p, {
                            "ok": False, "id": p.id,
                            "error": (
                                "silent data corruption: true-residual "
                                f"audit drift {verdict['drift']:.3e} > "
                                f"envelope {verdict['envelope']:.1e} "
                                "again after rollback (deterministic)"),
                            "failure_class": "sdc", "retriable": False,
                            "spec": spec_d(), "continuous": True,
                            "sdc_drift": verdict["drift"],
                            "iters_run": int(iters[lane])})
                        continue
                state, xnorm = solver.cont_retire(state, lane)
                lanes[lane] = None
                live -= 1
                served += 1
                self.metrics.retire(p.id, lane, boundary_iter,
                                    int(iters[lane]), live)
                # per-retire park, between the journaled retire record
                # and the response: a retriable crash later in this
                # sweep must not re-retire this lane (duplicate
                # serve_retire) on resume; if the crash lands inside
                # _respond itself, the lane is parked retired-but-
                # unanswered and the retry-path reconcile requeues it
                park()
                if not math.isfinite(xnorm):
                    # breakdown sentinel (ISSUE 9): a poisoned lane
                    # (injected NaN, numerical breakdown) must never
                    # ship as ok:true — classified `breakdown`,
                    # deterministic (re-solving the same input
                    # reproduces it), lane-local (batch-mates retire
                    # normally: lane algebra is independent)
                    self._respond(p, {
                        "ok": False, "id": p.id,
                        "error": ("non-finite solution norm "
                                  f"({xnorm!r}): CG breakdown"),
                        "failure_class": "breakdown",
                        "retriable": False,
                        "spec": spec_d(), "continuous": True,
                        "iters_run": int(iters[lane])})
                    continue
                self._respond(p, {
                    "ok": True, "id": p.id,
                    "xnorm": xnorm,
                    "scale": p.scale,
                    "spec": spec_d(),
                    "nrhs_live": live,
                    "nrhs_bucket": bucket,
                    "ndofs_global": solver.ndofs_global,
                    "cg_engine_form": solver.engine_form,
                    "continuous": True,
                    "iters_run": int(iters[lane]),
                    "cache": "hit" if cache_hit else "miss",
                })
            # park the boundary step + accounting even when nothing
            # retired: a crash in the admission block must not replay
            # this boundary's cont_step on resume
            park()
            free = [i for i, p in enumerate(lanes) if p is None]
            if free and now < admit_deadline:
                polled = self._screen_batch(
                    self._poll_compatible(spec, len(free)),
                    boundary=boundary_iter)
                for j, p in enumerate(polled):
                    lane = free.pop(0)
                    p.lc.mark("admit")
                    p.lc.mark("solve")  # admitted into an in-flight solve
                    if p.rt is not None:
                        p.rt.cut("queue")
                        # the executable is already resolved: the
                        # compile window of a mid-solve admission is
                        # the admission itself (~0)
                        p.rt.cut("compile")
                        p.rt.annotate_default("cache_source", "hit")
                        p.rt.annotate(midsolve=True)
                    try:
                        if getattr(solver, "supports_warm", False):
                            state = solver.cont_admit(
                                state, lane, p.scale,
                                warm_scale=p.warm_scale)
                        else:
                            state = solver.cont_admit(state, lane,
                                                      p.scale)
                    except BaseException:
                        # p (and any requests polled after it) is out of
                        # the queue but in neither `members` nor a parked
                        # lane — invisible to every answer path. Back to
                        # the queue front: the resumed attempt (or a
                        # later batch) re-admits them.
                        self._requeue_front(polled[j:])
                        raise
                    lanes[lane] = p
                    members.append(p)
                    midsolve += 1
                    live += 1
                    self.metrics.admit(p.id, lane, boundary_iter, True,
                                       live)
                    park()  # per-admission: a crash on the NEXT admit
                    # must not lose (or re-journal) this one on resume
            park()
        wall = wall_accum + (time.monotonic() - t0)
        # GDoF/s over the whole continuous batch: every served lane ran
        # its full budget (retired lanes are answered, not truncated)
        gdof = (solver.ndofs_global * spec.nreps * served
                / (1e9 * wall) if wall > 0 else 0.0)
        # padding waste in lane units: dead boundary-slots normalised by
        # boundaries (comparable with the one-shot bucket - live)
        padded = (round(dead_lane_boundaries / boundaries)
                  if boundaries else bucket - served)
        return {"served": served, "wall_s": wall,
                "gdof_per_second": gdof, "midsolve": midsolve,
                "boundaries": boundaries,
                "live_lane_boundaries": live_lane_boundaries,
                "padded_lanes": padded}

    def _fail_batch(self, batch: list, exc: BaseException, *,
                    bucket: int | None = None,
                    cache_hit: bool = False) -> None:
        cls = classify_exception(exc)
        retriable = cls in RETRIABLE_CLASSES
        spec = batch[0].spec
        self.metrics.batch(_spec_dict(spec), len(batch),
                           bucket or nrhs_bucket(len(batch)), cache_hit,
                           0.0, 0.0)
        for p in batch:
            if p.rt is not None and not p.answered:
                p.rt.cut("solve")  # the failure landed inside the solve
            self._respond(p, {
                "ok": False, "id": p.id,
                "error": f"{type(exc).__name__}: {exc}"[:500],
                "failure_class": cls, "retriable": retriable})

    def _respond(self, pending: PendingRequest, result: dict) -> bool:
        """Answer one request exactly once; True = this call won the
        claim. The whole visibility sequence — claim, journal the
        serve_response record (fsynced inside Journal.append),
        done.set() — runs UNDER the request's claim lock: a racing late
        responder (a zombie solve thread retiring a lane the worker
        already failed via _fail_batch, or vice versa) can neither
        double-release the client nor journal a second serve_response
        for the SAME request. Different requests journal concurrently —
        each Journal.append is an atomic O_APPEND write, so per-request
        locking suffices and the broker isn't serialized through one
        fsync. The fsync-before-done.set() ordering is what makes
        recovery exactly-once (serve.recovery): a request whose client
        was released always has a durable response record, so a replay
        can never answer it a second time."""
        with pending.lock:
            if pending.answered:
                return False
            pending.answered = True
            # the lifecycle marks ARE the latency accounting: total and
            # the per-stage breakdown ride on every response/journal line
            t_resp = pending.lc.mark("respond")
            lifecycle = pending.lc.breakdown()
            result["latency_s"] = latency = lifecycle.get("total_s", 0.0)
            result["lifecycle_s"] = lifecycle
            # late-deadline detection (ISSUE 18): a REAL response going
            # out past the request's declared deadline — the counter
            # the whole overload subsystem exists to pin at zero. The
            # broker's own early refusals are deadline-classed and
            # deliberately excluded (they are the subsystem working).
            deadline_late = (
                pending.deadline is not None
                and t_resp > pending.deadline
                and result.get("failure_class") != "deadline_exceeded")
            if deadline_late:
                result["deadline_late"] = True
            if pending.degraded is not None:
                # brownout provenance (ISSUE 18): the answer was
                # computed on a stepped-down precision rung — stamped
                # on the response AND the journal record
                result["degraded"] = pending.degraded
            phase = exemplar = None
            if pending.rt is not None:
                # the final cut closes the partition at the SAME instant
                # the lifecycle stamps respond, so the phase sum and
                # latency_s share both endpoints (epsilon = rounding)
                if pending.degraded is not None:
                    pending.rt.annotate(degraded=pending.degraded)
                pending.rt.cut("respond", now=t_resp)
                phase = pending.rt.decomposition()
                result["phase_s"] = phase
                exemplar = pending.rt.export()
            pending.result = result
            self.metrics.response(
                pending.id, bool(result.get("ok")), latency,
                failure_class=result.get("failure_class"),
                retriable=result.get("retriable"),
                cache=result.get("cache"),
                lifecycle=lifecycle, phase_s=phase, trace=exemplar,
                spec_key=spec_latency_key(
                    _spec_dict(pending.spec),
                    result.get("nrhs_bucket", 0)),
                deadline_late=deadline_late,
                controller=result.get("controller"),
                degraded=pending.degraded)
            if pending.hedged and pending.hedge_dst is not None \
                    and self.metrics.device == pending.hedge_dst:
                # the SPECULATIVE copy answered first: the hedge
                # rescued this request. Attribution journaled AFTER the
                # response record — the ledger sees exactly one
                # response; this line is the win accounting.
                self.metrics.hedge_won(pending.id, pending.hedge_dst)
            pending.done.set()
        return True

    # -- crash recovery (ISSUE 9) ------------------------------------------

    def recover(self, journal) -> dict:
        """Replay a crashed generation's journal into THIS broker:
        re-admit every admitted-but-unresponded request
        (serve.recovery.fold_outstanding — requests whose write-ahead
        ``serve_request`` record has no complete ``serve_response``)
        under its ORIGINAL id, so the journal reads as one continuous
        incident across restarts and ``verify_exactly_once`` holds over
        all generations appended to one file. No new serve_request
        records are written (the WAL line already exists); the fresh-id
        counter resumes past every journaled numeric id so new
        admissions never collide with replayed ones.

        ``journal`` is a journal path, an iterable of records, or a
        prebuilt RecoveryPlan. Returns {"plan", "pending", "replayed",
        "skipped"}; the caller waits on ``pending`` (the original
        clients died with the crashed process — their responses land in
        the journal, which is the exactly-once contract's ledger)."""
        from .recovery import RecoveryPlan, fold_outstanding

        plan = (journal if isinstance(journal, RecoveryPlan)
                else fold_outstanding(journal))
        replayed: list[PendingRequest] = []
        skipped = 0
        with span("serve:recover", outstanding=len(plan.outstanding),
                  corrupt=plan.corrupt):
            if plan.max_numeric_id:
                # never move the counter backward, and take the queue
                # lock: ids minted by submissions that beat (or race)
                # the recovery stay unique vs replayed ids
                with self._cv:
                    self._next_id = max(self._next_id,
                                        plan.max_numeric_id + 1)
            for req in plan.outstanding:
                # _replay_request answers unrebuildable records with a
                # TERMINAL `unsupported` response (leaving them
                # unanswered would hold the exactly-once ledger open
                # forever) and bypasses admission control for the rest:
                # these requests were ALREADY admitted (their WAL
                # records prove it) — a full queue must not convert an
                # admitted request into a loss.
                pending = self._replay_request(req)
                if pending is None:
                    skipped += 1
                    continue
                replayed.append(pending)
            self.metrics.recovery(len(plan.outstanding), len(replayed),
                                  skipped, plan.corrupt)
        return {"plan": plan, "pending": replayed,
                "replayed": len(replayed), "skipped": skipped}
