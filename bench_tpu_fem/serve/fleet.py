"""Fleet dispatcher (ISSUE 13): multi-device serving — per-device
queues with spec-aware affinity routing, work stealing between devices,
SLO-burn-driven spill, and standby journal adoption.

One `DeviceLane` per (virtual or physical) device: its own `Broker`
(continuous batching, PR 6), its own executable cache — an
`ArtifactWarmCache` when a shared `ArtifactStore` is attached, so a lane
facing a spec it never compiled warms from a peer's published artifact
instead of recompiling — and its own `Metrics` stamped with the device
label, all journaling into ONE shared file (O_APPEND-atomic appends,
the chaos-proven multi-writer discipline), so the whole fleet incident
replays from one journal and `verify_exactly_once` holds fleet-wide.

Routing (the AlpaServe-shaped placement decision, CPU-provable):

  1. **Affinity**: a request goes to a device whose in-memory cache
     already holds its (spec, bucket) executable (any admissible
     bucket), shortest queue among those; no holder -> coldest queue
     (that lane becomes the spec's affinity home after one compile or
     artifact warm load).
  2. **Spill**: when the chosen lane's FAST-window SLO burn rate
     exceeds `spill_burn` (default 1.0 — burning error budget faster
     than the SLO allows), the request spills to the least-loaded lane
     whose burn is below the threshold (journaled `fleet_spill`): the
     PR 10 burn rate is a CONTROL SIGNAL here, not just an alert.
  3. **Stealing**: a balancer rebalances queue depths — when
     max - min >= `steal_threshold`, half the gap moves from the fat
     queue's TAIL to the thin lane (`fleet_steal` journaled; FIFO
     fairness survives — the oldest requests keep their home-lane
     positions). Stolen work warms from the artifact store on arrival.

Admission: the fleet only submits to a lane with queue room at decision
time; when every lane is full the request sheds fleet-level (journaled
``serve_shed`` with device "fleet", retriable) — and a racing fill that
makes the chosen lane shed anyway propagates that lane's own shed, so
the exactly-once ledger never records an admit after a shed.

Standby adoption (`adopt_journal`): broker replication over the PR 9
write-ahead journal. A standby fleet folds the dead primary's journal
(`serve.recovery.fold_outstanding` — exactly-once-proven against torn
tails), routes every admitted-but-unresponded request through the SAME
affinity logic under its ORIGINAL id (the id-space handoff: fresh ids
resume past every journaled id), and answers them; `fleet_adopt` is the
journal record. The chaos schedule SIGKILLs the primary mid-incident
and asserts `verify_exactly_once` over both generations.

Evidence labels: every fleet number here is CPU-measured on virtual
devices (`force_host_cpu_devices`); the `fleet` agenda stage re-runs
the loadgen smoke on real hardware and re-stamps.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from .broker import Broker, QueueFull, _spec_dict
from .cache import NRHS_BUCKETS, ExecutableCache, nrhs_bucket
from .engine import SolveSpec, build_solver, spec_cache_key
from .metrics import FleetMetrics, Metrics


@dataclass
class DeviceLane:
    """One device's serving stack: broker + cache + labelled metrics.
    ``quarantined`` (ISSUE 14) takes the lane out of the routing pool —
    a device whose windowed SDC-detection counter tripped serves no new
    traffic until a known-answer self-test readmits it."""

    index: int
    label: str
    broker: Broker
    cache: ExecutableCache
    metrics: Metrics
    device: object | None = None  # jax.Device when available
    quarantined: bool = False


def _jax_devices(n: int):
    """Up to n distinct jax devices (None-padded when the platform
    exposes fewer — lanes then share the default device, which keeps
    the routing/stealing logic CPU-provable on any host)."""
    try:
        import jax

        devs = list(jax.devices())
    except Exception:
        devs = []
    return [devs[i] if i < len(devs) else None for i in range(n)]


class FleetDispatcher:
    """Spec-aware multi-device dispatcher over per-lane brokers. The
    server front end drives it exactly like a Broker (`submit` / `wait`
    / `metrics_snapshot` / `shutdown`)."""

    def __init__(self, ndevices: int = 2, *,
                 journal_path: str | None = None,
                 artifacts=None,
                 queue_max: int = 128, nrhs_max: int = 8,
                 window_s: float = 0.025,
                 solve_timeout_s: float = 120.0,
                 continuous: bool = True,
                 slo_objective_s: float | None = None,
                 slo_target: float = 0.99,
                 steal_threshold: int = 4,
                 balance_interval_s: float = 0.02,
                 spill_burn: float = 1.0,
                 publish_artifacts: bool = True,
                 builder=build_solver,
                 audit: bool = False,
                 quarantine_threshold: int = 0,
                 quarantine_window_s: float = 60.0,
                 reqtrace: bool = False,
                 hedge: bool = False,
                 hedge_budget: float = 0.05,
                 hedge_delay_s: float | None = None,
                 brownout: bool = False,
                 brownout_burn: float = 1.0,
                 brownout_clear_burn: float = 0.5,
                 brownout_windows=None):
        if ndevices < 1:
            raise ValueError("ndevices must be >= 1")
        self.artifacts = artifacts
        self.steal_threshold = max(int(steal_threshold), 1)
        self.spill_burn = float(spill_burn)
        # SDC lane quarantine (ISSUE 14): with `audit` on, every lane
        # broker true-residual-audits retiring lanes; a lane whose
        # detections inside `quarantine_window_s` reach
        # `quarantine_threshold` is quarantined (0 = never). Quarantine
        # drains the lane's queue to healthy peers through the
        # steal/adopt machinery (exactly-once: pure queue moves) and
        # the lane rejoins only through a passing known-answer
        # self-test (`run_selftest`).
        self.audit = bool(audit)
        # Request-scoped tracing (ISSUE 15): lane brokers allocate a
        # ReqTrace per request, the dispatcher stamps the ROUTING CAUSE
        # (affinity-hit / cold-home / spill) on it, and the control
        # plane (steal / quarantine drain) marks moved requests with
        # instant events — the per-request "why was it slow" story.
        self.reqtrace = bool(reqtrace)
        self.quarantine_threshold = int(quarantine_threshold)
        self.quarantine_window_s = float(quarantine_window_s)
        # Overload resilience (ISSUE 18). Hedged dispatch: the balancer
        # re-enqueues the SAME PendingRequest of a request queued past
        # its per-spec hedge delay (live p95 fold, or the override) on
        # a second healthy lane under a bounded hedge budget — no new
        # WAL record, so the exactly-once ledger cannot see a duplicate
        # by construction; first retire wins the per-request claim CAS,
        # the loser cancels at its next boundary. Brownout: sustained
        # fast+slow SLO burn steps the fleet down the registry's
        # precision degradation ladder, with hysteresis
        # (clear < brownout_clear_burn on BOTH windows) on recovery.
        # Both default OFF: the unarmed fleet is bitwise pre-PR.
        self.hedge = bool(hedge)
        self.hedge_budget = float(hedge_budget)
        self.hedge_delay_s = hedge_delay_s
        self.brownout = bool(brownout)
        self.brownout_burn = float(brownout_burn)
        self.brownout_clear_burn = float(brownout_clear_burn)
        # burn-window override (seconds, label) tuples — injectable for
        # the state-machine tests; None = obs.regress.SLO_WINDOWS
        self.brownout_windows = brownout_windows
        self.slo_objective_s = slo_objective_s
        self.slo_target = float(slo_target)
        from ..engines.registry import degradation_ladder

        self._ladder = degradation_ladder()
        self._overload_lock = threading.Lock()
        self._brownout_level = 0
        self._brownout_engaged_at: float | None = None
        self._brownout_residency_s = 0.0
        self.nrhs_max = min(nrhs_max, NRHS_BUCKETS[-1])
        self.queue_max = queue_max
        self.fleet_metrics = FleetMetrics(journal_path)
        self._builder = builder
        self.lanes: list[DeviceLane] = []
        devices = _jax_devices(ndevices)
        for i in range(ndevices):
            label = f"dev{i}"
            if artifacts is not None:
                from .artifacts import ArtifactWarmCache

                cache = ArtifactWarmCache(
                    artifacts, publish=publish_artifacts,
                    loader=self._lane_loader(devices[i]))
            else:
                cache = ExecutableCache()
            metrics = Metrics(journal_path,
                              slo_objective_s=slo_objective_s,
                              slo_target=slo_target, device=label)
            broker = Broker(cache, metrics, queue_max=queue_max,
                            nrhs_max=nrhs_max, window_s=window_s,
                            solve_timeout_s=solve_timeout_s,
                            continuous=continuous,
                            builder=self._lane_builder(devices[i]),
                            audit=audit, reqtrace=reqtrace)
            self.lanes.append(DeviceLane(i, label, broker, cache,
                                         metrics, devices[i]))
        # ONE fleet-wide id space (the lanes share a journal, so ids
        # must never collide across lanes)
        self._id_lock = threading.Lock()
        self._next_id = 1
        self._stop = False
        self._balancer = None
        if balance_interval_s and balance_interval_s > 0:
            self.balance_interval_s = balance_interval_s
            self._balancer = threading.Thread(
                target=self._balance_loop, daemon=True,
                name="fleet-balancer")
            self._balancer.start()

    # -- per-lane device pinning -------------------------------------------

    def _lane_builder(self, device):
        def build(spec, bucket):
            if device is None:
                return self._builder(spec, bucket)
            import jax

            with jax.default_device(device):
                return self._builder(spec, bucket)

        return build

    def _lane_loader(self, device):
        from .artifacts import _default_loader

        def load(meta, fns):
            if device is None:
                return _default_loader(meta, fns)
            import jax

            with jax.default_device(device):
                return _default_loader(meta, fns)

        return load

    # -- routing -----------------------------------------------------------

    def _lane_holds(self, lane: DeviceLane, spec: SolveSpec) -> bool:
        """Does the lane's IN-MEMORY cache hold an executable any batch
        of this spec could run (any bucket up to the lane cap)? A
        recency-free peek (`cache.holds`): a routing probe must not
        refresh LRU order in lanes the request never reaches."""
        for b in NRHS_BUCKETS:
            if b > nrhs_bucket(self.nrhs_max):
                break
            if lane.cache.holds(spec_cache_key(spec, b)):
                return True
        return False

    def _mint_id(self, req_id: str | None) -> str:
        with self._id_lock:
            if req_id is not None:
                return req_id
            rid = f"r{self._next_id}"
            self._next_id += 1
            return rid

    def submit(self, spec: SolveSpec, scale: float = 1.0,
               req_id: str | None = None, warm_scale: float = 0.0):
        """Route one request: affinity -> burn-spill -> shortest queue.
        Raises QueueFull (fleet-level, journaled) when every lane is at
        capacity. Returns the lane broker's PendingRequest.
        ``warm_scale`` (ISSUE 20) rides through to the lane broker —
        0.0 is the cold path bitwise on every solver."""
        rid = self._mint_id(req_id)
        # brownout rewrite (ISSUE 18) BEFORE the affinity probe: under
        # an engaged brownout level the request runs on the stepped-down
        # registry rung, so affinity must see the precision it will
        # actually execute at
        degraded = None
        if self.brownout:
            degraded, spec = self._brownout_spec(spec)

        def depth(ln):
            return ln.broker.pending_count()

        # quarantined lanes are out of the routing pool entirely
        # (ISSUE 14): a corruption-tripped device serves no new traffic
        # until its self-test readmits it. Every lane quarantined =
        # fleet-level shed (retriable — the fleet is degraded, not gone)
        pool = [ln for ln in self.lanes if not ln.quarantined]
        if not pool:
            total = sum(depth(ln) for ln in self.lanes)
            hint, ctl = self._shed_hint(spec, total)
            self.fleet_metrics.shed(rid, total, retry_after_s=hint,
                                    controller=ctl)
            raise QueueFull(
                f"every lane quarantined ({len(self.lanes)} of "
                f"{len(self.lanes)}) — self-test readmission pending",
                retry_after_s=hint)
        affine = [ln for ln in pool if self._lane_holds(ln, spec)]
        candidates = affine or pool
        chosen = min(candidates, key=depth)
        # burn-spill retarget: only to a colder lane WITH ROOM — the
        # final placement must be settled BEFORE anything is journaled,
        # or the spill record and the route record could name different
        # lanes (a spill "to" a full lane would bounce right back to
        # the burning one while the evidence claimed otherwise)
        spill_from, burn = None, chosen.metrics.fast_burn_rate()
        if burn > self.spill_burn and len(pool) > 1:
            colder = [ln for ln in pool if ln is not chosen
                      and ln.metrics.fast_burn_rate() <= self.spill_burn
                      and depth(ln) < self.queue_max]
            if colder:
                spill_from, chosen = chosen, min(colder, key=depth)
        if depth(chosen) >= self.queue_max:
            # the chosen lane is full: fall over to ANY healthy lane
            # with room; none -> shed FLEET-level before any WAL record
            # exists, so the ledger never sees an admit racing a shed
            others = [ln for ln in pool
                      if depth(ln) < self.queue_max]
            if not others:
                total = sum(depth(ln) for ln in self.lanes)
                hint, ctl = self._shed_hint(spec, total)
                self.fleet_metrics.shed(rid, total, retry_after_s=hint,
                                        controller=ctl)
                raise QueueFull(
                    f"fleet at capacity ({len(self.lanes)} lanes x "
                    f"{self.queue_max})",
                    retry_after_s=hint)
            chosen = min(others, key=depth)
            spill_from = None  # the burn retarget did not decide this
        spill = spill_from is not None
        # the affinity flag records the DECISION, so it reads off the
        # affine set computed at decision time — a concurrent eviction
        # between the probe and here must not flip the journaled flag
        # (the perfgate pins the hit-rate as a hard counter)
        affinity = chosen in affine
        cause = ("spill" if spill
                 else "affinity-hit" if affinity else "cold-home")
        pending = chosen.broker.submit(spec, scale, req_id=rid,
                                       degraded=degraded,
                                       warm_scale=warm_scale)
        if pending.rt is not None:
            # annotate() takes the trace lock: the lane worker may
            # already be answering this request on another thread
            pending.rt.annotate(route={"device": chosen.label,
                                       "cause": cause})
        if spill:
            self.fleet_metrics.spill(rid, spill_from.label,
                                     chosen.label, burn)
        self.fleet_metrics.route(rid, chosen.label, affinity, spill,
                                 depth(chosen),
                                 cause=cause if self.reqtrace else None)
        return pending

    def wait(self, pending, timeout_s: float | None = None) -> dict:
        """Lane-agnostic (the pending carries its own event) — same
        contract as Broker.wait."""
        if pending.done.wait(timeout_s):
            return pending.result
        return {"ok": False, "id": pending.id,
                "error": f"response wait exceeded {timeout_s}s",
                "failure_class": "timeout", "retriable": True}

    # -- warmup / artifacts ------------------------------------------------

    def warmup(self, specs, bucket: int | None = None) -> list:
        """Prebuild each spec on its affinity home (round-robin over
        lanes). With an artifact store attached the builds publish, so
        every OTHER lane can later warm the same spec with zero
        compiles."""
        out = []
        for i, spec in enumerate(specs):
            lane = self.lanes[i % len(self.lanes)]
            out.extend(lane.broker.warmup([spec], bucket=bucket))
        return out

    # -- balancing ---------------------------------------------------------

    def _balance_loop(self) -> None:
        while not self._stop:
            time.sleep(self.balance_interval_s)
            try:
                self.quarantine_scan()
                self.rebalance_once()
                self.hedge_scan()
                self.brownout_scan()
            except Exception:
                # the balancer must never die mid-incident; a failed
                # pass retries on the next tick
                pass

    def rebalance_once(self) -> int:
        """One stealing pass: move half the depth gap from the fattest
        queue's tail to the thinnest HEALTHY lane when the gap reaches
        the threshold (a quarantined lane neither gives nor receives —
        its queue was already drained at the trip). Returns the number
        of requests moved."""
        healthy = [ln for ln in self.lanes if not ln.quarantined]
        if len(healthy) < 2:
            return 0
        depths = [(ln.broker.pending_count(), ln) for ln in healthy]
        fat_d, fat = max(depths, key=lambda t: t[0])
        thin_d, thin = min(depths, key=lambda t: t[0])
        if fat is thin or fat_d - thin_d < self.steal_threshold:
            return 0
        stolen = fat.broker.steal_requests((fat_d - thin_d) // 2)
        if not stolen:
            return 0
        for p in stolen:
            if getattr(p, "rt", None) is not None:
                # steal-moved is an anomaly tag (ISSUE 15): the moved
                # request's full trace is always kept in the exemplar
                # ring, and the timeline renders the move as an instant
                p.rt.event("steal_moved", src=fat.label, dst=thin.label)
        thin.broker.adopt_pending(stolen)
        self.fleet_metrics.steal(fat.label, thin.label, len(stolen),
                                 ids=[p.id for p in stolen]
                                 if self.reqtrace else None)
        return len(stolen)

    # -- overload resilience (ISSUE 18) ------------------------------------

    def _shed_hint(self, spec: SolveSpec, depth: int):
        """Predicted-queue-time retry hint for a fleet-level shed: the
        first lane with a live per-spec prediction supplies the fold.
        Returns (retry_after_s, controller_inputs) or (None, None) when
        no lane has evidence — a blind hint is worse than none."""
        sd = _spec_dict(spec)
        for ln in self.lanes:
            pred = ln.metrics.predict_completion(sd)
            if pred is not None:
                wait = (depth / max(len(self.lanes) * self.nrhs_max, 1)
                        ) * pred["p50_s"]
                hint = round(max(wait, pred["p50_s"]), 3)
                return hint, {"decision": "shed_retry_hint",
                              "queue_depth": depth,
                              "predicted_wait_s": round(wait, 6),
                              "prediction": pred}
        return None, None

    def _brownout_spec(self, spec: SolveSpec):
        """Apply the engaged brownout level to one arriving request:
        rewrite its precision to the current registry-ladder rung and
        return the provenance stamp every response under brownout
        carries. Requests not at the ladder's base precision (explicit
        f64/df32 clients) pass through untouched — the ladder degrades
        the DEFAULT serving tier, never a client's explicit ask for
        more precision."""
        with self._overload_lock:
            level = self._brownout_level
        if level <= 0 or spec.precision != self._ladder[0]:
            return None, spec
        from dataclasses import replace

        from ..engines.registry import gate_reason

        rung = self._ladder[min(level, len(self._ladder) - 1)]
        degraded = {"from": spec.precision, "to": rung, "level": level,
                    "reason": gate_reason("brownout-precision",
                                          level=level,
                                          from_p=spec.precision,
                                          to_p=rung)}
        return degraded, replace(spec, precision=rung)

    def hedge_scan(self, now: float | None = None) -> int:
        """One hedged-dispatch pass (run by the balancer, callable
        manually with an injected clock): enqueue a speculative copy of
        any request queued longer than its per-spec hedge delay (p95 of
        the live latency fold, or the ``hedge_delay_s`` override) on a
        different healthy lane. The copy IS the same PendingRequest
        object — no new WAL record, so the exactly-once ledger cannot
        see a duplicate by construction; first retire wins the claim
        CAS, the loser cancels at its next boundary. Bounded budget:
        at most ``hedge_budget`` of routed requests ever hedge (floor
        one, so a cold fleet can still prove the mechanism). Returns
        the number of hedges fired this pass."""
        if not self.hedge:
            return 0
        if now is None:
            now = time.monotonic()
        healthy = [ln for ln in self.lanes if not ln.quarantined]
        if len(healthy) < 2:
            return 0
        fired = 0
        for src in healthy:
            for p in src.broker.peek_queued():
                if p.hedged or p.answered:
                    continue
                wait = now - p.enqueued
                pred = src.metrics.predict_completion(_spec_dict(p.spec))
                if self.hedge_delay_s is not None:
                    delay, delay_source = self.hedge_delay_s, "override"
                elif pred is not None:
                    delay, delay_source = pred["p95_s"], "p95"
                else:
                    continue  # no delay evidence: never hedge blind
                if wait <= delay:
                    continue
                routed, hedges_fired = \
                    self.fleet_metrics.hedge_budget_state()
                allowed = max(1, int(self.hedge_budget * routed))
                if hedges_fired >= allowed:
                    return fired  # budget spent: end the whole pass
                others = [ln for ln in healthy if ln is not src
                          and ln.broker.pending_count() < self.queue_max]
                if not others:
                    return fired
                tgt = min(others,
                          key=lambda ln: ln.broker.pending_count())
                p.hedged = True
                p.hedge_dst = tgt.label
                inputs = {"wait_s": round(wait, 6),
                          "delay_s": round(delay, 6),
                          "delay_source": delay_source,
                          "budget": {
                              "allowed": allowed,
                              "fired": hedges_fired,
                              "routed": routed,
                              "fraction": self.hedge_budget}}
                if pred is not None:
                    inputs["prediction"] = pred
                if getattr(p, "rt", None) is not None:
                    p.rt.event("hedge_fired", src=src.label,
                               dst=tgt.label)
                tgt.broker.adopt_pending([p])
                self.fleet_metrics.hedge_fired(p.id, src.label,
                                               tgt.label, wait, inputs)
                fired += 1
        return fired

    def brownout_scan(self, now: float | None = None) -> str | None:
        """One brownout pass (run by the balancer, callable manually
        with an injected wall clock): pool every lane's SLO samples
        through the SAME obs.regress.burn_rates fold the /metrics slo
        block runs, then drive the ladder state machine — step DOWN one
        registry rung when BOTH fast and slow windows burn past
        ``brownout_burn``, step UP one rung only when BOTH fall below
        ``brownout_clear_burn`` (the hysteresis band between the two
        thresholds holds the level steady). Every transition journals
        its burn-rate inputs. Returns "step", "recover" or None."""
        if not self.brownout or self.slo_objective_s is None:
            return None
        samples: list = []
        for ln in self.lanes:
            samples.extend(ln.metrics.slo_samples())
        if not samples:
            return None
        from ..obs.regress import burn_rates

        kw = {}
        if self.brownout_windows is not None:
            kw["windows"] = self.brownout_windows
        rates = burn_rates(samples, objective_s=self.slo_objective_s,
                           target=self.slo_target,
                           now=time.time() if now is None else now,
                           **kw)
        fast = rates["fast_burn_rate"]
        slow = rates["slow_burn_rate"]
        inputs = {"fast_burn": round(fast, 4),
                  "slow_burn": round(slow, 4),
                  "engage_burn": self.brownout_burn,
                  "clear_burn": self.brownout_clear_burn,
                  "samples": len(samples),
                  "objective_s": self.slo_objective_s,
                  "target": self.slo_target}
        with self._overload_lock:
            level = self._brownout_level
            if (fast > self.brownout_burn and slow > self.brownout_burn
                    and level < len(self._ladder) - 1):
                self._brownout_level = level + 1
                if level == 0:
                    self._brownout_engaged_at = time.monotonic()
                self.fleet_metrics.brownout(
                    "step", level + 1, self._ladder[level],
                    self._ladder[level + 1], inputs)
                return "step"
            if (level > 0 and fast < self.brownout_clear_burn
                    and slow < self.brownout_clear_burn):
                self._brownout_level = level - 1
                if level == 1 and self._brownout_engaged_at is not None:
                    self._brownout_residency_s += (
                        time.monotonic() - self._brownout_engaged_at)
                    self._brownout_engaged_at = None
                self.fleet_metrics.brownout(
                    "recover", level - 1, self._ladder[level],
                    self._ladder[level - 1], inputs)
                return "recover"
        return None

    # -- SDC lane quarantine (ISSUE 14) ------------------------------------

    def quarantine_scan(self) -> int:
        """One quarantine pass (run by the balancer thread, callable
        manually): any healthy lane whose SDC detections inside the
        trailing window reach the threshold trips into quarantine.
        Returns the number of lanes tripped this pass."""
        if self.quarantine_threshold <= 0:
            return 0
        tripped = 0
        for ln in self.lanes:
            if ln.quarantined:
                continue
            n = ln.metrics.sdc_recent(self.quarantine_window_s)
            if n >= self.quarantine_threshold:
                self._quarantine(ln, n)
                tripped += 1
        return tripped

    def _quarantine(self, lane: DeviceLane, window_events: int) -> None:
        """Trip one lane: mark it out of the routing pool and drain its
        QUEUED requests to the least-loaded healthy lane through the
        existing steal/adopt machinery — the requests' write-ahead
        records already exist, so the drain is a pure queue move the
        exactly-once ledger never sees (zero lost, zero duplicates by
        construction). The batch already IN FLIGHT on the lane runs out
        normally (its members answer through the audit/rollback path).
        With no healthy peer the queue stays put — a degraded lane
        still beats a lost request — and the journal records drained=0."""
        lane.quarantined = True
        healthy = [ln for ln in self.lanes if not ln.quarantined]
        drained: list = []
        if healthy:
            drained = lane.broker.steal_requests(
                lane.broker.pending_count())
            if drained:
                tgt = min(healthy,
                          key=lambda ln: ln.broker.pending_count())
                for p in drained:
                    if getattr(p, "rt", None) is not None:
                        p.rt.event("quarantine_drained",
                                   src=lane.label, dst=tgt.label)
                tgt.broker.adopt_pending(drained)
        self.fleet_metrics.quarantine(lane.label, len(drained),
                                      window_events)

    def run_selftest(self, lane_index: int, spec: SolveSpec,
                     scale: float = 1.0, timeout_s: float = 120.0,
                     expect_xnorm: float | None = None,
                     rel_tol: float = 1e-5) -> dict:
        """Known-answer self-test of one (typically quarantined) lane:
        submit a canonical solve DIRECTLY to the lane's broker
        (bypassing routing — the test must run on the suspect device)
        under the fleet's audited retire path. Pass = the response is
        ok (the true-residual audit held end-to-end) and, when
        `expect_xnorm` is given, the solution norm matches the known
        answer. A passing test readmits the lane (`fleet_readmit`
        journaled); a failing one keeps it quarantined. The test
        request rides the normal WAL/response ledger, so the journal
        stays exactly-once over self-tests too."""
        lane = self.lanes[lane_index]
        rid = self._mint_id(None)
        pending = lane.broker.submit(spec, scale, req_id=rid)
        out = lane.broker.wait(pending, timeout_s)
        ok = bool(out.get("ok"))
        if ok and expect_xnorm is not None:
            got = out.get("xnorm", float("nan"))
            ok = abs(got - expect_xnorm) <= rel_tol * abs(expect_xnorm)
        self.fleet_metrics.selftest(lane.label, rid, ok)
        if ok and lane.quarantined:
            # readmission resets the lane's detection WINDOW (not its
            # monotone counters): without this the balancer's next
            # quarantine_scan re-trips the lane on the pre-quarantine
            # detections still inside the window, silently undoing the
            # readmit it just journaled
            lane.metrics.sdc_reset_window()
            lane.quarantined = False
            self.fleet_metrics.readmit(lane.label, rid)
        return {"ok": ok, "response": out,
                "quarantined": lane.quarantined}

    # -- standby adoption (broker replication) -----------------------------

    def adopt_journal(self, journal) -> dict:
        """Adopt a dead primary's write-ahead journal: fold the
        admitted-but-unresponded set (torn tails dropped by
        read_records' rule), resume the id space past every journaled
        id, and route each outstanding request through the normal
        affinity logic under its ORIGINAL id. Returns {"plan",
        "pending", "routed", "skipped"}; the exactly-once contract then
        holds over the WHOLE journal — both generations.

        Adoption before traffic is the standby PROTOCOL, not an
        optimisation: even with zero outstanding requests the id-space
        handoff is what keeps the standby's fresh ids from colliding
        with the dead generation's in the shared journal (a collision
        reads as a duplicate response in the exactly-once ledger —
        the perfgate fleet leg pins exactly this)."""
        from .recovery import RecoveryPlan, fold_outstanding

        plan = (journal if isinstance(journal, RecoveryPlan)
                else fold_outstanding(journal))
        if plan.max_numeric_id:
            with self._id_lock:
                self._next_id = max(self._next_id,
                                    plan.max_numeric_id + 1)
        pending = []
        skipped = 0
        for req in plan.outstanding:
            try:
                spec = SolveSpec(**req["spec"])
                spec.validate()
                pool = ([ln for ln in self.lanes if not ln.quarantined]
                        or self.lanes)
                affine = [ln for ln in pool
                          if self._lane_holds(ln, spec)]
                lane = min(affine or pool,
                           key=lambda ln: ln.broker.pending_count())
            except Exception:
                lane = self.lanes[0]  # terminal-answer path below
            p = lane.broker._replay_request(req)
            if p is None:
                skipped += 1
                continue
            pending.append(p)
        self.fleet_metrics.adopt(len(plan.outstanding), len(pending),
                                 skipped, plan.corrupt)
        return {"plan": plan, "pending": pending,
                "routed": len(pending), "skipped": skipped}

    # -- snapshot / shutdown -----------------------------------------------

    def metrics_snapshot(self, memory: dict | None = None) -> dict:
        """Fleet /metrics: aggregated totals (the Broker snapshot's
        vocabulary, so existing consumers keep working), a `fleet`
        block (routing/steal/spill counters + artifact-store stats) and
        a per-lane `lanes` list."""
        lane_snaps = []
        for ln in self.lanes:
            snap = ln.metrics.snapshot(cache_stats=ln.cache.stats())
            snap["device"] = ln.label
            snap["queue_depth"] = ln.broker.pending_count()
            lane_snaps.append(snap)
        sum_keys = ("requests_total", "shed_total", "completed",
                    "failed", "batches", "midsolve_admissions",
                    "padded_lanes_total", "broker_retries",
                    "batch_resumes", "recovery_runs",
                    "recovered_requests", "queue_depth",
                    "sdc_detected", "sdc_rollbacks", "sdc_terminal",
                    "deadline_exceeded_early", "deadline_exceeded_late",
                    "hedge_wins", "hedge_cancels")
        out: dict = {k: sum(s.get(k, 0) for s in lane_snaps)
                     for k in sum_keys}
        # fleet-level sheds (every lane full) count into the top-level
        # shed_total next to the lanes' own admission-control sheds —
        # the perfgate shed gate must see fleet-mode shedding too
        out["shed_total"] += self.fleet_metrics.sheds
        cache_keys = ("entries", "hits", "misses", "evictions",
                      "compiles", "warm_loads")
        out["cache"] = {k: sum(s["cache"].get(k, 0) for s in lane_snaps)
                        for k in cache_keys}
        hit = sum(s["cache"].get("hits", 0) for s in lane_snaps)
        miss = sum(s["cache"].get("misses", 0) for s in lane_snaps)
        out["cache"]["hit_rate"] = hit / (hit + miss) if hit + miss else 0.0
        breq = [(s["cache_hit_rate_requests"],
                 s["requests_total"]) for s in lane_snaps]
        tot = sum(n for _, n in breq)
        out["cache_hit_rate_requests"] = (
            sum(r * n for r, n in breq) / tot if tot else 0.0)
        lat = sorted(x for ln in self.lanes
                     for x in ln.metrics.latency_samples())
        from .metrics import _pct

        out["latency_p50_s"] = _pct(lat, 0.50)
        out["latency_p95_s"] = _pct(lat, 0.95)
        out["latency_p99_s"] = _pct(lat, 0.99)
        # per-(spec, bucket) split merged across lanes (ISSUE 15): the
        # same bounded keys, fleet-wide percentiles
        by_key: dict[str, list] = {}
        for ln in self.lanes:
            for k, v in ln.metrics.latency_key_samples().items():
                by_key.setdefault(k, []).extend(v)
        if by_key:
            out["latency_by_spec"] = {
                k: {"n": len(sv), "p50_s": _pct(sv, 0.50),
                    "p95_s": _pct(sv, 0.95), "p99_s": _pct(sv, 0.99)}
                for k, sv in sorted(
                    (k, sorted(v)) for k, v in by_key.items())}
        # fleet-wide request-trace fold (ISSUE 15): lanes' phase windows
        # merged through the SAME summarize_phases fold the journal
        # replay runs — the loadgen's phase-share table reads this block
        trace_samples = [s for ln in self.lanes
                         for s in ln.metrics.trace_samples()]
        if trace_samples:
            from ..obs.reqtrace import merge_exemplars, summarize_phases

            rq = summarize_phases(trace_samples)
            complete = sum(ln.metrics.trace_complete for ln in self.lanes)
            incomplete = sum(ln.metrics.trace_incomplete
                             for ln in self.lanes)
            judged = complete + incomplete
            rq["trace_complete"] = complete
            rq["trace_incomplete"] = incomplete
            rq["trace_complete_rate"] = (
                round(complete / judged, 6) if judged else None)
            anomalies: dict[str, int] = {}
            for ln in self.lanes:
                for tag, n in dict(ln.metrics.exemplars.counts).items():
                    anomalies[tag] = anomalies.get(tag, 0) + n
            rq["anomalies"] = anomalies
            rq["exemplars"] = merge_exemplars(
                [ln.metrics.exemplars.snapshot() for ln in self.lanes])
            out["reqtrace"] = rq
        fleet = self.fleet_metrics.snapshot()
        fleet["devices"] = len(self.lanes)
        # current quarantine state (a gauge, not a counter: the trip
        # history lives in quarantines/readmits above)
        fleet["quarantined_lanes"] = [ln.label for ln in self.lanes
                                      if ln.quarantined]
        fleet["quarantined"] = len(fleet["quarantined_lanes"])
        if self.brownout:
            # brownout residency (ISSUE 18): the current ladder level
            # (a gauge — the step/recover history is the counters
            # above) and the cumulative time spent engaged
            with self._overload_lock:
                level = self._brownout_level
                residency = self._brownout_residency_s
                if self._brownout_engaged_at is not None:
                    residency += (time.monotonic()
                                  - self._brownout_engaged_at)
            fleet["brownout"] = {
                "level": level,
                "precision": self._ladder[
                    min(level, len(self._ladder) - 1)],
                "ladder": list(self._ladder),
                "residency_s": round(residency, 3)}
        if self.artifacts is not None:
            fleet["artifacts"] = self.artifacts.stats()
        out["fleet"] = fleet
        out["lanes"] = [
            {"device": s["device"], "queue_depth": s["queue_depth"],
             "requests_total": s["requests_total"],
             "completed": s["completed"], "failed": s["failed"],
             "batches": s["batches"],
             "mean_live_lanes": s["mean_live_lanes"],
             "midsolve_admissions": s["midsolve_admissions"],
             "cache": s["cache"],
             **({"slo": s["slo"]} if "slo" in s else {})}
            for s in lane_snaps]
        if memory is not None:
            out["memory"] = memory
        return out

    def shutdown(self, timeout_s: float = 5.0) -> None:
        self._stop = True
        if self._balancer is not None:
            self._balancer.join(timeout_s)
        for ln in self.lanes:
            ln.broker.shutdown(timeout_s)
