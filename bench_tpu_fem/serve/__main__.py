"""`python -m bench_tpu_fem.serve`: run the localhost solver service.

Example (CPU):

    JAX_PLATFORMS=cpu python -m bench_tpu_fem.serve --port 8378 \
        --warmup 1,3 --ndofs 50000 --nreps 30 --journal SERVE_r06.jsonl

then:

    curl -s -X POST localhost:8378/solve -d \
      '{"degree": 3, "ndofs": 50000, "nreps": 30, "scale": 2.0}'
    curl -s localhost:8378/metrics
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bench_tpu_fem.serve",
        description="Solver-as-a-service: batched multi-RHS CG with an "
                    "AOT-executable cache behind an admission-controlled "
                    "broker")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8378,
                   help="0 = ephemeral (printed on startup)")
    p.add_argument("--queue-max", type=int, default=128,
                   help="admission-control bound: beyond this, requests "
                        "shed with a retriable 503")
    p.add_argument("--nrhs-max", type=int, default=8,
                   help="batching-window lane cap (pads to the bucket)")
    p.add_argument("--window-ms", type=float, default=25.0,
                   help="batching window: wait this long for compatible "
                        "requests before solving a partial batch")
    p.add_argument("--solve-timeout", type=float, default=120.0,
                   help="hard per-batch deadline; overruns answer "
                        "classified-timeout and are abandoned")
    p.add_argument("--no-continuous", action="store_true",
                   help="disable continuous batching (fixed-window "
                        "one-shot batches only) — the occupancy A/B "
                        "baseline")
    p.add_argument("--journal", default="",
                   help="metrics JSONL journal path (crash-safe, "
                        "harness.journal format)")
    p.add_argument("--slo-objective", type=float, default=2.0,
                   help="latency SLO objective (seconds): /metrics "
                        "exposes fast/slow-window error-budget burn "
                        "rates against it (JSON `slo` block + "
                        "benchfem_serve_slo_* Prometheus series). "
                        "0 disables SLO tracking.")
    p.add_argument("--slo-target", type=float, default=0.99,
                   help="SLO availability target (fraction of requests "
                        "inside the objective)")
    p.add_argument("--fleet", type=int, default=0,
                   help="run a FLEET of N device lanes (ISSUE 13): "
                        "per-device queues, spec-aware affinity "
                        "routing, work stealing, SLO-burn spill. 0 "
                        "(default) = single broker. On CPU the lanes "
                        "pin to N virtual devices.")
    p.add_argument("--artifacts", default="",
                   help="shared AOT executable-artifact store directory "
                        "(serve.artifacts): lanes publish compiled "
                        "executables and warm misses from peers with "
                        "zero recompiles")
    p.add_argument("--adopt-journal", default="",
                   help="standby adoption: fold this (dead primary's) "
                        "write-ahead journal at startup and answer "
                        "every admitted-but-unresponded request "
                        "exactly once under its original id")
    p.add_argument("--steal-threshold", type=int, default=4,
                   help="fleet: queue-depth gap that triggers a steal "
                        "pass (half the gap moves)")
    p.add_argument("--spill-burn", type=float, default=1.0,
                   help="fleet: fast-window SLO burn rate above which "
                        "arrivals spill to a colder device (needs "
                        "--slo-objective > 0)")
    p.add_argument("--balance-interval-ms", type=float, default=20.0,
                   help="fleet balancer tick; 0 disables stealing")
    p.add_argument("--audit", action="store_true",
                   help="SDC defense (ISSUE 14): true-residual-audit "
                        "every retiring lane; an exceedance rolls the "
                        "lane back once then answers failure_class sdc")
    p.add_argument("--quarantine-threshold", type=int, default=0,
                   help="fleet lane quarantine: detections inside the "
                        "window that trip a lane out of routing "
                        "(0 = never; requires --audit and --fleet)")
    p.add_argument("--quarantine-window", type=float, default=60.0,
                   help="quarantine trip window, seconds")
    p.add_argument("--hedge", action="store_true",
                   help="hedged dispatch (ISSUE 18, fleet only): a "
                        "request queued past its per-spec hedge delay "
                        "(live p95, or --hedge-delay-ms) is "
                        "speculatively re-enqueued on a second healthy "
                        "lane; first retire wins, the loser cancels at "
                        "its next boundary, the exactly-once ledger "
                        "never sees duplicates")
    p.add_argument("--hedge-budget", type=float, default=0.05,
                   help="hedged-dispatch budget: cap hedges at this "
                        "fraction of routed requests (load-shifted "
                        "duplicates stay bounded)")
    p.add_argument("--hedge-delay-ms", type=float, default=0.0,
                   help="fixed hedge delay override in ms; 0 (default) "
                        "= per-spec live p95 from the latency windows")
    p.add_argument("--brownout", action="store_true",
                   help="brownout degradation ladder (ISSUE 18, fleet "
                        "only): sustained fast+slow SLO burn steps "
                        "arrivals down the registry precision ladder "
                        "(f32 -> bf16); responses carry `degraded` "
                        "provenance, hysteresis steps back up when the "
                        "burn clears")
    p.add_argument("--brownout-burn", type=float, default=1.0,
                   help="brownout engage threshold: step down when BOTH "
                        "fast and slow burn rates exceed this")
    p.add_argument("--brownout-clear", type=float, default=0.5,
                   help="brownout hysteresis: step back up only when "
                        "both burn rates fall below this (must be < "
                        "--brownout-burn)")
    p.add_argument("--reqtrace", action="store_true",
                   help="request-scoped tracing (ISSUE 15): every "
                        "response carries a phase decomposition "
                        "(queue/compile/solve/audit/retry/respond "
                        "summing to latency_s), /metrics exposes "
                        "per-phase percentiles + the exemplar ring, "
                        "and the journal replays the same story "
                        "(python -m bench_tpu_fem.obs reqtrace). Off "
                        "(default): no traces, no serve_phase records, "
                        "no extra fsyncs — only the reqtrace-"
                        "independent per-(spec,bucket) latency split "
                        "remains.")
    p.add_argument("--warmup", default="",
                   help="comma-separated degrees to prebuild at startup "
                        "(with --ndofs/--nreps/--precision), e.g. '1,3,6'")
    p.add_argument("--ndofs", type=int, default=50_000,
                   help="warmup spec ndofs")
    p.add_argument("--nreps", type=int, default=30,
                   help="warmup spec CG iterations")
    p.add_argument("--precision", default="f32",
                   choices=["f32", "f64", "df32"],
                   help="warmup spec precision")
    args = p.parse_args(argv)

    # Hermetic CPU pinning, same contract as the CLI: a serving process
    # must never hang on a wedged TPU tunnel when the caller pinned CPU.
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from ..utils.hermetic import force_host_cpu_devices

        # fleet mode gets one virtual device per lane — the multi-device
        # dispatch is CPU-provable on the same virtual-device mechanism
        # the test suite uses
        force_host_cpu_devices(max(1, args.fleet))
    import jax

    # Serving accepts mixed precision in one process: x64 on, so
    # f64-emulated requests trace at full width (f32/df32 operators pin
    # their dtypes explicitly and are unaffected).
    jax.config.update("jax_enable_x64", True)

    from .broker import Broker
    from .cache import ExecutableCache
    from .engine import SolveSpec
    from .metrics import Metrics
    from .server import make_server

    store = None
    if args.artifacts:
        from .artifacts import ArtifactStore

        store = ArtifactStore(args.artifacts)
    if args.fleet:
        from .fleet import FleetDispatcher

        broker = FleetDispatcher(
            args.fleet, journal_path=args.journal or None,
            artifacts=store,
            queue_max=args.queue_max, nrhs_max=args.nrhs_max,
            window_s=args.window_ms / 1000.0,
            solve_timeout_s=args.solve_timeout,
            continuous=not args.no_continuous,
            slo_objective_s=args.slo_objective or None,
            slo_target=args.slo_target,
            steal_threshold=args.steal_threshold,
            balance_interval_s=args.balance_interval_ms / 1000.0,
            spill_burn=args.spill_burn,
            audit=args.audit,
            quarantine_threshold=args.quarantine_threshold,
            quarantine_window_s=args.quarantine_window,
            reqtrace=args.reqtrace,
            hedge=args.hedge,
            hedge_budget=args.hedge_budget,
            hedge_delay_s=(args.hedge_delay_ms / 1000.0
                           if args.hedge_delay_ms else None),
            brownout=args.brownout,
            brownout_burn=args.brownout_burn,
            brownout_clear_burn=args.brownout_clear,
        )
    else:
        metrics = Metrics(
            args.journal or None,
            slo_objective_s=args.slo_objective or None,
            slo_target=args.slo_target,
        )
        cache = ExecutableCache()
        if store is not None:
            from .artifacts import ArtifactWarmCache

            cache = ArtifactWarmCache(store)
        broker = Broker(
            cache, metrics,
            queue_max=args.queue_max, nrhs_max=args.nrhs_max,
            window_s=args.window_ms / 1000.0,
            solve_timeout_s=args.solve_timeout,
            continuous=not args.no_continuous,
            audit=args.audit,
            reqtrace=args.reqtrace,
        )
    if args.warmup:
        degrees = [int(d) for d in args.warmup.split(",") if d.strip()]
        specs = [SolveSpec(degree=d, ndofs=args.ndofs, nreps=args.nreps,
                           precision=args.precision) for d in degrees]
        print(f"warmup: compiling {len(specs)} executables "
              f"(degrees {degrees}, bucket {broker.nrhs_max})", flush=True)
        broker.warmup(specs)
        print("warmup done", flush=True)
    if args.adopt_journal:
        # standby adoption: answer the dead primary's outstanding
        # requests exactly once before taking fresh traffic
        rec = (broker.adopt_journal(args.adopt_journal)
               if args.fleet else broker.recover(args.adopt_journal))
        n = rec.get("routed", rec.get("replayed", 0))
        print(f"adopted journal {args.adopt_journal}: {n} outstanding "
              f"replayed, {rec['skipped']} skipped", flush=True)

    srv = make_server(broker, args.host, args.port)
    host, port = srv.server_address[:2]
    print(f"serving on http://{host}:{port} "
          f"(fleet={args.fleet or 'off'}, queue_max={args.queue_max}, "
          f"nrhs_max={broker.nrhs_max}, "
          f"window={args.window_ms}ms)", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.shutdown()
        broker.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
