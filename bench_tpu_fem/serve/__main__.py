"""`python -m bench_tpu_fem.serve`: run the localhost solver service.

Example (CPU):

    JAX_PLATFORMS=cpu python -m bench_tpu_fem.serve --port 8378 \
        --warmup 1,3 --ndofs 50000 --nreps 30 --journal SERVE_r06.jsonl

then:

    curl -s -X POST localhost:8378/solve -d \
      '{"degree": 3, "ndofs": 50000, "nreps": 30, "scale": 2.0}'
    curl -s localhost:8378/metrics
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m bench_tpu_fem.serve",
        description="Solver-as-a-service: batched multi-RHS CG with an "
                    "AOT-executable cache behind an admission-controlled "
                    "broker")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8378,
                   help="0 = ephemeral (printed on startup)")
    p.add_argument("--queue-max", type=int, default=128,
                   help="admission-control bound: beyond this, requests "
                        "shed with a retriable 503")
    p.add_argument("--nrhs-max", type=int, default=8,
                   help="batching-window lane cap (pads to the bucket)")
    p.add_argument("--window-ms", type=float, default=25.0,
                   help="batching window: wait this long for compatible "
                        "requests before solving a partial batch")
    p.add_argument("--solve-timeout", type=float, default=120.0,
                   help="hard per-batch deadline; overruns answer "
                        "classified-timeout and are abandoned")
    p.add_argument("--no-continuous", action="store_true",
                   help="disable continuous batching (fixed-window "
                        "one-shot batches only) — the occupancy A/B "
                        "baseline")
    p.add_argument("--journal", default="",
                   help="metrics JSONL journal path (crash-safe, "
                        "harness.journal format)")
    p.add_argument("--slo-objective", type=float, default=2.0,
                   help="latency SLO objective (seconds): /metrics "
                        "exposes fast/slow-window error-budget burn "
                        "rates against it (JSON `slo` block + "
                        "benchfem_serve_slo_* Prometheus series). "
                        "0 disables SLO tracking.")
    p.add_argument("--slo-target", type=float, default=0.99,
                   help="SLO availability target (fraction of requests "
                        "inside the objective)")
    p.add_argument("--warmup", default="",
                   help="comma-separated degrees to prebuild at startup "
                        "(with --ndofs/--nreps/--precision), e.g. '1,3,6'")
    p.add_argument("--ndofs", type=int, default=50_000,
                   help="warmup spec ndofs")
    p.add_argument("--nreps", type=int, default=30,
                   help="warmup spec CG iterations")
    p.add_argument("--precision", default="f32",
                   choices=["f32", "f64", "df32"],
                   help="warmup spec precision")
    args = p.parse_args(argv)

    # Hermetic CPU pinning, same contract as the CLI: a serving process
    # must never hang on a wedged TPU tunnel when the caller pinned CPU.
    import os

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        from ..utils.hermetic import force_host_cpu_devices

        force_host_cpu_devices(1)
    import jax

    # Serving accepts mixed precision in one process: x64 on, so
    # f64-emulated requests trace at full width (f32/df32 operators pin
    # their dtypes explicitly and are unaffected).
    jax.config.update("jax_enable_x64", True)

    from .broker import Broker
    from .cache import ExecutableCache
    from .engine import SolveSpec
    from .metrics import Metrics
    from .server import make_server

    metrics = Metrics(
        args.journal or None,
        slo_objective_s=args.slo_objective or None,
        slo_target=args.slo_target,
    )
    broker = Broker(
        ExecutableCache(), metrics,
        queue_max=args.queue_max, nrhs_max=args.nrhs_max,
        window_s=args.window_ms / 1000.0,
        solve_timeout_s=args.solve_timeout,
        continuous=not args.no_continuous,
    )
    if args.warmup:
        degrees = [int(d) for d in args.warmup.split(",") if d.strip()]
        specs = [SolveSpec(degree=d, ndofs=args.ndofs, nreps=args.nreps,
                           precision=args.precision) for d in degrees]
        print(f"warmup: compiling {len(specs)} executables "
              f"(degrees {degrees}, bucket {broker.nrhs_max})", flush=True)
        broker.warmup(specs)
        print(f"warmup done: {broker.cache.stats()}", flush=True)

    srv = make_server(broker, args.host, args.port)
    host, port = srv.server_address[:2]
    print(f"serving on http://{host}:{port} "
          f"(queue_max={args.queue_max}, nrhs_max={broker.nrhs_max}, "
          f"window={args.window_ms}ms)", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.shutdown()
        broker.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
