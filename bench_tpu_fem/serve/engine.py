"""Solver construction for the serving layer: spec -> compiled batched
executable with an iteration-boundary checkpoint API.

A `SolveSpec` is the request-compatibility class (degree, problem size,
iteration count, precision, geometry class): requests agreeing on it can
share one batch and one executable. `build_solver` assembles the
operator ONCE and AOT-compiles the checkpointable batched CG machinery
(`la.cg.BatchedCGState` + step/admit/retire) for one nrhs bucket:

  * f32 uniform specs whose bucket fits the per-bucket VMEM plan run
    the FUSED nrhs-native kron delay ring
    (ops.kron_cg.kron_batched_engine, `cg_engine_form:
    "one_kernel_batched"` — interpret mode off-TPU, the real kernel on
    chip);
  * every other f32/f64 spec runs the unfused vmapped composition
    (`la.cg.unfused_batch_engine`, bitwise the `cg_solve_batched`
    parity oracle per lane), recorded `"unfused"`;
  * df32 pairs run the batched df checkpoint recurrence
    (`la.cg.BatchedCGStateDF` — the PR 6 gate CLOSED: the df recurrence
    now has iteration boundaries, so df32 requests ride continuous
    batching like f32/f64; the vmapped whole-solve `cg_solve_df` stays
    the parity oracle at df-class <= 1e-13).

The checkpoint API (`cont_init` / `cont_step` / `cont_admit` /
`cont_retire` / `cont_poll`) is what the broker's continuous batching
drives: `cont_step` advances all lanes by `iter_chunk` iterations in one
compiled call, and between calls the broker may admit a queued request
into a free lane or retire a finished one — per-lane algebra is
lane-local (la.cg docstrings), so admits/retires never perturb in-flight
lanes.

The request's right-hand side enters as a per-lane SCALE of the spec's
canonical benchmark RHS (the Gaussian-bump source every driver solves).
CG with a fixed iteration count is exactly linear in b — alpha/beta are
scale-invariant ratios, so x(c*b) = c*x(b) — which gives the serving
acceptance check its teeth: every response must match the SAME compiled
solver's scale-1.0 solution norm times the request scale (exact for
power-of-two scales in f32 — lanes are fully independent inside the
batched executable — and df-exact for ANY scale in df32; a non-power-
of-two f32 scale adds one input rounding, ~6e-8 relative). Unfused
responses additionally match the one-shot `cg_solve` driver bitwise;
fused responses match it to the engine family's f32 reassociation
accuracy (<= 5e-5 relative L2 — same convention as the kron engine
suite), which is why the parity oracle is per-executable, not
cross-path.

AOT artifact seam (ISSUE 13): `CompiledSolver.export_artifact()`
serializes the four checkpoint executables
(`jax.experimental.serialize_executable` — the compiled PJRT
executables themselves, not a re-lowerable recipe) so a broker replica
can warm its cache from a peer's artifact instead of recompiling:
`build_solver(spec, bucket, artifact=...)` runs ONLY the host-side
problem setup (mesh/tables/RHS assembly — deterministic from the spec)
and installs the deserialized executables, never invoking the XLA
compile path. Artifacts are pickle-carried and version-pinned
(jax/backend recorded; a mismatch raises `ArtifactIncompatible`, which
loaders treat as a cache miss): load them only from operator-owned
stores — the same trust boundary as the checkpoint files
(serve.artifacts owns the bytes + integrity discipline).

Evidence label: serving throughput numbers from this module are
CPU-measured unless a round artifact says otherwise; the fused batched
kernel's TPU VMEM tiers are design estimates until the harness
`fusedbatch` stage runs on hardware.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from .cache import ExecutableKey, nrhs_bucket

# Test/fault-injection seam: when set, called as FAULT_HOOK(spec, scales)
# at the top of every compiled-solver execution — raising here simulates
# a solve-path fault (OOM, hang, Mosaic reject) without touching the
# solver code. harness.faults.FaultySolveHook scripts it.
FAULT_HOOK = None

# Boundary fault seam (ISSUE 9): when set, called as
# BOUNDARY_HOOK(spec, boundary_iter) at every continuous-batching
# iteration boundary, INSIDE the broker's disposable solve thread —
# raising here simulates the worker thread dying mid-batch (the
# SIGKILL-adjacent crash the broker's boundary-checkpoint resume
# recovers from). Separate from FAULT_HOOK so per-boundary scripting
# never consumes a FaultySolveHook script out from under existing tests.
BOUNDARY_HOOK = None

# Silent-data-corruption seam (ISSUE 14): when set, called as
# SDC_HOOK(spec, boundary_iter, state) -> state|None after every
# continuous-batching cont_step — returning a state hands the solve a
# CORRUPTED carry (the mercurial-core model: one finite bit flip,
# harness.faults.SdcInjectionHook), which is invisible to everything
# except the retire-time audit. None leaves the state untouched; the
# unarmed path runs zero extra code.
SDC_HOOK = None

_PRECISIONS = ("f32", "f64", "df32", "bf16")

# Admission cap on problem size: a single oversized request must be
# REFUSED (classified `unsupported`, 422) rather than allowed to grind
# the worker through a multi-GB host allocation — or worse, draw the
# Linux OOM killer onto the serving process. Generous for CPU serving
# (the benchmark's own flagship is 12.5M dofs); raise deliberately for
# a TPU deployment, not by accident.
MAX_NDOFS = 50_000_000

# Iterations per continuous-batching boundary: each `cont_step` call
# advances every live lane by this many CG iterations in one compiled
# executable, then the broker gets a chance to admit/retire lanes. Small
# enough that a freed lane is refilled within a fraction of a serving
# solve (nreps is typically 12-50), large enough that the per-boundary
# host round-trip (a (bucket,) iters/done fetch) stays negligible.
ITER_CHUNK = 4

# CI probe seam (ISSUE 20): a nonempty value forces every warm-start
# scale to 0.0 at cont_init/cont_admit time — the suppressed-warm-start
# regression the perfgate forms leg must catch (iterations saved drops
# to 0, the HIGHER-gated counter fails rc 1). Never set in production.
WARM_SUPPRESS_ENV = "BENCH_SUPPRESS_WARMSTART"

# Iteration budget for the heat form's high-accuracy base solution
# (x_base = A^{-1} b, computed once at build): warm starts are scaled
# copies of it, so it must be converged well past the serve rtol.
XBASE_ITERS = 200


def _warm_suppressed() -> bool:
    import os

    return os.environ.get(WARM_SUPPRESS_ENV, "") not in ("", "0")


@dataclass(frozen=True)
class SolveSpec:
    """The request-compatibility key, pre-bucket. `nreps` is the CG
    iteration count (benchmark semantics: rtol=0, exactly nreps
    iterations — responses are comparable across requests only because
    the iteration count is part of the spec; rtol-budgeted forms like
    heat treat nreps as the iteration CAP and may retire lanes early).
    `form` is the weak-form axis (forms.registry, ISSUE 20): requests
    for different forms must never share a batch or an executable, so it
    participates in equality/hash and the cache key."""

    degree: int = 3
    ndofs: int = 50_000
    nreps: int = 30
    precision: str = "f32"
    geom_perturb_fact: float = 0.0
    form: str = "poisson"
    # Client latency budget in seconds (ISSUE 18), None = unbounded.
    # compare=False keeps it OUT of batch compatibility (`p.spec ==
    # spec`), the executable cache key and the frozen-dataclass hash —
    # a deadline changes when a request is ABANDONED, never what is
    # computed. It is also excluded from the journaled spec dict
    # (broker._spec_dict): a crash-replayed request has, by definition,
    # outlived any budget it carried.
    deadline_s: float | None = field(default=None, compare=False)

    @property
    def geom(self) -> str:
        return "perturbed" if self.geom_perturb_fact != 0.0 else "uniform"

    def validate(self) -> None:
        from ..engines.registry import GATE_REASONS, gate_reason

        if not 1 <= self.degree <= 7:
            raise UnsupportedSpec(f"degree {self.degree} unsupported (1-7)")
        if self.precision not in _PRECISIONS:
            raise UnsupportedSpec(
                gate_reason("serve-precision", precision=repr(self.precision),
                            precisions=_PRECISIONS))
        if self.precision == "df32" and self.geom != "uniform":
            raise UnsupportedSpec(GATE_REASONS["serve-df32-perturbed"])
        if self.ndofs <= 0 or self.nreps <= 0:
            raise UnsupportedSpec("ndofs and nreps must be positive")
        if self.deadline_s is not None and not self.deadline_s > 0:
            raise UnsupportedSpec("deadline_s must be positive when given")
        if self.ndofs > MAX_NDOFS:
            raise UnsupportedSpec(
                gate_reason("serve-ndofs-cap", ndofs=self.ndofs,
                            cap=MAX_NDOFS))
        if self.form != "poisson":
            from ..forms.registry import FORM_NAMES

            if self.form not in FORM_NAMES:
                raise UnsupportedSpec(
                    f"unknown form '{self.form}' "
                    f"(registered: {', '.join(FORM_NAMES)})")
            # form x engine gates (ISSUE 20): every unsupported
            # combination stamps a registered reason, never free text
            if self.precision == "df32":
                raise UnsupportedSpec(
                    gate_reason("form-df", form=self.form))
            if self.precision == "bf16":
                raise UnsupportedSpec(
                    gate_reason("form-bf16", form=self.form))


class UnsupportedSpec(ValueError):
    """A capability gate declined the spec — classified `unsupported`
    by the harness taxonomy (deterministic: retrying cannot help)."""


class ArtifactIncompatible(ValueError):
    """An AOT artifact cannot load into this runtime (jax/backend/format
    mismatch) — the loader treats it as a cache miss and rebuilds; never
    a crash (serve.artifacts catches exactly this)."""


#: artifact payload format tag: serialized PJRT executables, pickled
#: (payload, in_tree, out_tree) triples per checkpoint function
ARTIFACT_FORMAT = "pjrt-pickle-v1"

#: the four checkpoint executables every servable solver carries (f32,
#: f64 and — since ISSUE 13 — df32 all drive the same API)
ARTIFACT_FNS = ("_init_fn", "_step_fn", "_admit_fn", "_retire_fn")


def planned_engine_form(spec: SolveSpec, bucket: int) -> str:
    """The engine form the serving compile will pick for (spec, bucket)
    — a deterministic function of the spec, so it can be part of the
    cache key: the fused nrhs-native kron ring for f32 uniform specs
    whose bucket fits the per-bucket VMEM plan
    (ops.kron_cg.engine_plan_batched), else the unfused vmapped
    composition. Unified vocabulary (bench.driver.record_engine). The
    decision table lives in engines.registry; this is a thin delegate
    kept for the existing call sites. Non-poisson forms always run the
    general sum-factorised action (the forms_xla registry row), never a
    fused poisson ring."""
    if spec.form != "poisson":
        return "unfused"
    from ..engines.registry import planned_engine_form as _planned

    return _planned(spec.precision, spec.geom, spec.ndofs, spec.degree,
                    bucket)


def spec_cache_key(spec: SolveSpec, bucket: int,
                   device_mesh: tuple = (1, 1, 1)) -> ExecutableKey:
    from ..engines.registry import EngineSpec
    from ..mesh.sizing import compute_mesh_size

    cells = compute_mesh_size(spec.ndofs, spec.degree)
    return EngineSpec.cache_key(
        degree=spec.degree,
        cell_shape=tuple(int(c) for c in cells),
        precision=spec.precision,
        geom=spec.geom,
        engine_form=planned_engine_form(spec, bucket),
        nrhs_bucket=bucket,
        device_mesh=tuple(device_mesh),
        nreps=spec.nreps,
        form=spec.form,
    )


def _df_split_scales(pad: np.ndarray):
    """Host-side Dekker split of f64 lane scales into (hi, lo) f32
    arrays — the df-exact scaling input of the compiled df init/admit."""
    hi = np.asarray(pad, np.float32)
    lo = np.asarray(np.asarray(pad, np.float64)
                    - np.asarray(hi, np.float64), np.float32)
    return hi, lo


@dataclass
class BatchResult:
    """One executed batch: per-live-lane solution norms plus the
    accounting the metrics layer journals."""

    xnorms: list  # len(scales): L2 norm of each live lane's solution
    wall_s: float
    nrhs_live: int
    nrhs_bucket: int
    ndofs_global: int
    nreps: int
    gdof_per_second: float
    extra: dict = field(default_factory=dict)


class CompiledSolver:
    """One AOT-compiled batched solver: operator state + base RHS held on
    device, executables compiled for (bucket, *grid) inputs. `solve`
    scales the base RHS per lane (zero-padding dead lanes — they start
    frozen inside the batched CG), runs the solve, and returns the
    per-lane norms with throughput accounting
    (GDoF/s = ndofs * nreps * live_lanes / wall).

    f32/f64 specs additionally expose the continuous-batching checkpoint
    API (`supports_continuous`): `cont_init(scales) -> state`,
    `cont_step(state) -> state` (+`iter_chunk` iterations, one compiled
    call), `cont_poll(state) -> (iters, done)` (host numpy),
    `cont_admit(state, lane, scale)` and
    `cont_retire(state, lane) -> (state, xnorm)` — all lane-local, so
    the broker edits the batch between steps without touching in-flight
    lanes. df32 keeps the whole-solve vmapped executable
    (`continuous_gate_reason` records why)."""

    def __init__(self, spec: SolveSpec, bucket: int,
                 artifact: dict | None = None):
        import jax
        import jax.numpy as jnp

        spec.validate()
        self.spec = spec
        self.bucket = int(bucket)
        self.key = spec_cache_key(spec, self.bucket)

        from ..elements.tables import build_operator_tables
        from ..mesh.box import create_box_mesh
        from ..mesh.dofmap import dof_grid_shape, global_ndofs
        from ..mesh.sizing import compute_mesh_size
        from ..utils.compilation import compile_lowered

        t0 = time.perf_counter()
        n = compute_mesh_size(spec.ndofs, spec.degree)
        t = build_operator_tables(spec.degree, 1, "gll")
        mesh = create_box_mesh(n, geom_perturb_fact=spec.geom_perturb_fact)
        self.ndofs_global = global_ndofs(n, spec.degree)

        # Host-assembled f64 RHS (the canonical benchmark problem: the
        # drivers assemble the same b), scaled per lane at solve time.
        from ..bench.driver import BenchConfig, _setup_problem

        cfg = BenchConfig(ndofs_global=spec.ndofs, degree=spec.degree,
                          qmode=1, nreps=spec.nreps,
                          geom_perturb_fact=spec.geom_perturb_fact)
        _, _, _, _, _, _, _, b_host, _ = _setup_problem(
            cfg, n, prebuilt=(n, "gll", t, mesh))
        b64 = np.asarray(b_host, np.float64)

        nreps = spec.nreps
        # Tuned build parameters (engines.autotune): the per-key tuning
        # DB may carry a swept iter_chunk; defaults run with the reason
        # recorded in the tuning evidence stamp (never silently).
        from ..engines.autotune import tuning_stamp

        _tux: dict = {}
        tuned = tuning_stamp(_tux, self.key)
        self.tuning = _tux["tuning"]
        chunk = (int(tuned["iter_chunk"])
                 if tuned and tuned.get("iter_chunk") else ITER_CHUNK)
        self.iter_chunk = min(chunk, nreps)
        self.supports_continuous = False
        self.supports_warm = False
        self.continuous_gate_reason = None
        self.engine_form = "unfused"
        self.engine_fallback_reason = None
        self.warm_source = None  # "artifact" when loaded, else None
        if spec.precision == "df32":
            from ..la.cg import (
                batched_cg_admit_df,
                batched_cg_init_df,
                batched_cg_retire_df,
                batched_dot_df,
                make_batched_cg_step_df,
            )
            from ..la.df64 import DF, df_from_f64
            from ..ops.kron_df import build_kron_laplacian_df

            # Batched df checkpoint recurrence (ISSUE 13 — the PR 6 gate
            # closed): the same four-executable API as f32/f64, carried
            # in compensated (hi, lo) arithmetic, so df32 batches admit
            # and retire lanes at iteration boundaries like every other
            # precision. The vmapped whole-solve cg_solve_df stays the
            # parity oracle (<= 1e-13, tests/test_serve.py).
            self._op = build_kron_laplacian_df(
                mesh, spec.degree, 1, "gll", kappa=2.0, tables=t)
            bdf = df_from_f64(b64)
            self._base = DF(jnp.asarray(bdf.hi), jnp.asarray(bdf.lo))

            from ..la.cg import _df_scale_lanes

            def _init(base, shi, slo):
                shape = (self.bucket, *base.hi.shape)
                bb = DF(jnp.broadcast_to(base.hi[None], shape),
                        jnp.broadcast_to(base.lo[None], shape))
                # df-exact per-lane scaling: the f64 scale rides as its
                # own (hi, lo) pair and multiplies in df arithmetic —
                # the standing df linearity contract (any scale, ~1e-13)
                B = _df_scale_lanes(bb, DF(shi, slo))
                return batched_cg_init_df(B)

            def _step(A, state):
                step = make_batched_cg_step_df(jax.vmap(A.apply), nreps)
                return jax.lax.fori_loop(
                    0, self.iter_chunk, lambda _, s: step(s), state)

            def _admit(base, state, lane, shi, slo):
                from ..la.df64 import df_mul

                b = df_mul(base, DF(jnp.broadcast_to(shi, base.hi.shape),
                                    jnp.broadcast_to(slo, base.hi.shape)))
                return batched_cg_admit_df(state, lane, b)

            def _retire(state, lane):
                d = batched_dot_df(state.X, state.X)
                return (batched_cg_retire_df(state, lane),
                        d.hi[lane], d.lo[lane])

            f32 = np.dtype("float32")
            base_s = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, f32), self._base)
            scales_s = jax.ShapeDtypeStruct((self.bucket,), f32)
            lane_s = jax.ShapeDtypeStruct((), np.dtype(np.int32))
            scal_s = jax.ShapeDtypeStruct((), f32)
            if artifact is not None:
                self._load_artifact(artifact)
            else:
                state_s = jax.eval_shape(_init, base_s, scales_s,
                                         scales_s)
                self._init_fn = compile_lowered(
                    jax.jit(_init).lower(base_s, scales_s, scales_s),
                    None)
                self._step_fn = compile_lowered(
                    jax.jit(_step).lower(self._op, state_s), None)
                self._admit_fn = compile_lowered(
                    jax.jit(_admit).lower(base_s, state_s, lane_s,
                                          scal_s, scal_s), None)
                self._retire_fn = compile_lowered(
                    jax.jit(_retire).lower(state_s, lane_s), None)
            self.supports_continuous = True
        else:
            from ..la.cg import (
                batched_cg_admit,
                batched_cg_admit_warm,
                batched_cg_init,
                batched_cg_init_warm,
                batched_cg_retire,
                batched_cg_run,
                make_batched_cg_step,
                unfused_batch_engine,
            )
            from ..la.vector import inner_product
            from ..ops.laplacian import build_laplacian

            dtype = jnp.float64 if spec.precision == "f64" else jnp.float32
            if spec.precision == "f64" and not jax.config.jax_enable_x64:
                from ..engines.registry import GATE_REASONS

                raise UnsupportedSpec(GATE_REASONS["serve-f64-x64"])
            if spec.form != "poisson":
                # Operator-zoo forms (ISSUE 20): the general
                # sum-factorised form action, every geometry. The heat
                # row additionally bakes its rtol into the compiled step
                # (nreps becomes the iteration CAP) and precomputes the
                # high-accuracy base solution warm starts scale.
                from ..forms.operators import build_form_operator
                from ..forms.registry import form_spec as _form_spec

                fspec = _form_spec(spec.form)
                self._op = build_form_operator(
                    mesh, fspec, spec.degree, 1, "gll", dtype=dtype,
                    tables=t)
                self._rtol = float(fspec.rtol)
                self.supports_warm = self._rtol > 0.0
            else:
                # Uniform meshes take the exact Kronecker fast path;
                # general (perturbed) geometry the einsum operator.
                backend = "kron" if spec.geom == "uniform" else "xla"
                self._op = build_laplacian(
                    mesh, spec.degree, 1, "gll", kappa=2.0, dtype=dtype,
                    tables=t, backend=backend)
                self._rtol = 0.0
                self.supports_warm = False
            rtol_v = self._rtol
            if spec.precision == "bf16":
                # bf16 serving (ISSUE 17): round the HBM-resident
                # operator state to bfloat16 ONCE — every batched /
                # continuous hot-loop apply streams half-width operands
                # with f32 accumulation (vectors and scales stay f32, so
                # the checkpoint API is untouched). bf16-class answers;
                # always the unfused form (registry plans no fused bf16
                # ring yet).
                from ..ops.bf16 import to_bf16

                self._op = to_bf16(self._op)
            self._base = jnp.asarray(b64, dtype)
            self.engine_form = planned_engine_form(spec, self.bucket)

            def _engine(A, fused):
                if fused:
                    from ..ops.kron_cg import kron_batched_engine

                    return kron_batched_engine(A)
                return unfused_batch_engine(jax.vmap(A.apply))

            if self.supports_warm:
                # High-accuracy base solution x_base = A^{-1} b, solved
                # once at build well past the serve rtol: a warm start
                # is warm_scale * x_base (the previous heat step's
                # solution under the RHS-as-scale protocol).
                eng0 = unfused_batch_engine(jax.vmap(self._op.apply))
                step0 = make_batched_cg_step(eng0, XBASE_ITERS,
                                             rtol=rtol_v * 1e-2)
                st0 = jax.jit(
                    lambda s: batched_cg_run(s, step0, XBASE_ITERS))(
                        batched_cg_init(self._base[None]))
                self._xbase = st0.X[0]
                self.xbase_iters = int(np.asarray(st0.iters)[0])

                def _init(A, base, xb, scales, warms):
                    shape = (-1,) + (1,) * base.ndim
                    B = scales.reshape(shape) * base[None]
                    X0 = warms.reshape(shape) * xb[None]
                    return batched_cg_init_warm(
                        B, X0, jax.vmap(A.apply), rtol=rtol_v)

                def _admit(A, base, xb, state, lane, scale, warm):
                    return batched_cg_admit_warm(
                        state, lane, scale * base, warm * xb, A.apply,
                        rtol=rtol_v)
            else:
                def _init(base, scales):
                    B = (scales.reshape((-1,) + (1,) * base.ndim)
                         * base[None])
                    return batched_cg_init(B)

                def _admit(base, state, lane, scale):
                    return batched_cg_admit(state, lane, scale * base)

            def _make_step(fused):
                def _step(A, state):
                    step = make_batched_cg_step(_engine(A, fused), nreps,
                                                rtol=rtol_v)
                    return batched_cg_run(state, step, self.iter_chunk)

                return _step

            def _retire(state, lane):
                x = state.X[lane]
                return (batched_cg_retire(state, lane),
                        jnp.sqrt(inner_product(x, x)))

            npdt = np.dtype(dtype)
            base_s = jax.ShapeDtypeStruct(b64.shape, npdt)
            scales_s = jax.ShapeDtypeStruct((self.bucket,), npdt)
            if self.supports_warm:
                state_s = jax.eval_shape(_init, self._op, base_s, base_s,
                                         scales_s, scales_s)
            else:
                state_s = jax.eval_shape(_init, base_s, scales_s)
            lane_s = jax.ShapeDtypeStruct((), np.dtype(np.int32))
            scale_s = jax.ShapeDtypeStruct((), npdt)

            if artifact is not None:
                self._load_artifact(artifact)
            else:
                fused = self.engine_form == "one_kernel_batched"
                step_opts = None
                if fused and jax.default_backend() == "tpu":
                    from ..ops.kron_cg import engine_plan_batched
                    from ..utils.compilation import scoped_vmem_options

                    grid = dof_grid_shape(n, spec.degree)
                    step_opts = scoped_vmem_options(
                        engine_plan_batched(grid, spec.degree,
                                            self.bucket)[1])
                try:
                    self._step_fn = compile_lowered(
                        jax.jit(_make_step(fused)).lower(self._op,
                                                         state_s),
                        step_opts)
                except Exception as exc:
                    if not fused:
                        raise
                    # Mosaic rejection of the fused batched ring (a
                    # drifted per-bucket tier): fall back to the unfused
                    # composition with the reason recorded — never
                    # silently (the cache key stays the PLANNED form;
                    # responses stamp the form that actually ran, same
                    # discipline as the driver).
                    self.engine_form = "unfused"
                    self.engine_fallback_reason = (
                        f"{type(exc).__name__}: {exc}"[:500])
                    self._step_fn = compile_lowered(
                        jax.jit(_make_step(False)).lower(self._op,
                                                         state_s),
                        None)
                if self.supports_warm:
                    self._init_fn = compile_lowered(
                        jax.jit(_init).lower(self._op, base_s, base_s,
                                             scales_s, scales_s), None)
                    self._admit_fn = compile_lowered(
                        jax.jit(_admit).lower(self._op, base_s, base_s,
                                              state_s, lane_s, scale_s,
                                              scale_s), None)
                else:
                    self._init_fn = compile_lowered(
                        jax.jit(_init).lower(base_s, scales_s), None)
                    self._admit_fn = compile_lowered(
                        jax.jit(_admit).lower(base_s, state_s, lane_s,
                                              scale_s), None)
                self._retire_fn = compile_lowered(
                    jax.jit(_retire).lower(state_s, lane_s), None)
            self.supports_continuous = True
        self.compile_s = time.perf_counter() - t0

    def trace_info(self) -> dict:
        """Compact solver identity for request tracing (ISSUE 15): the
        serve_phase journal record and the request exemplars carry this
        so a trace names the engine that actually ran — achieved form,
        compile wall, whether the executables came from a peer artifact,
        and the boundary cadence the solve occupancy is measured in."""
        return {
            "engine_form": self.engine_form,
            "precision": self.spec.precision,
            "compile_s": round(self.compile_s, 6),
            "warm_source": self.warm_source,
            "iter_chunk": self.iter_chunk,
            "supports_continuous": self.supports_continuous,
        }

    # -- AOT artifact seam (ISSUE 13) ---------------------------------------

    def export_artifact(self) -> dict:
        """Serialize the four compiled checkpoint executables into an
        artifact payload a peer replica loads with `build_solver(...,
        artifact=...)` — the PJRT executables themselves, so the loader
        never re-lowers or recompiles. Returns {"meta": ..., "fns":
        {name: pickle bytes}}; serve.artifacts owns the on-disk bytes
        (content hash + CRC + tmp->fsync->rename)."""
        import pickle

        import jax
        from jax.experimental.serialize_executable import serialize

        fns = {name: pickle.dumps(serialize(getattr(self, name)))
               for name in ARTIFACT_FNS}
        spec_meta = {"degree": self.spec.degree, "ndofs": self.spec.ndofs,
                     "nreps": self.spec.nreps,
                     "precision": self.spec.precision,
                     "geom_perturb_fact": self.spec.geom_perturb_fact}
        if self.spec.form != "poisson":
            # additive: poisson artifacts keep their pre-zoo meta bytes
            spec_meta["form"] = self.spec.form
        meta = {
            "format": ARTIFACT_FORMAT,
            "spec": spec_meta,
            "bucket": self.bucket,
            "engine_form": self.engine_form,  # the ACHIEVED form
            "engine_fallback_reason": self.engine_fallback_reason,
            "tuning": self.tuning,
            "compile_s": round(self.compile_s, 6),
            "jax": jax.__version__,
            "backend": jax.default_backend(),
        }
        return {"meta": meta, "fns": fns}

    def _load_artifact(self, artifact: dict) -> None:
        """Install a peer's serialized executables instead of compiling.
        Version-pinned: a jax/backend/format mismatch raises
        ArtifactIncompatible (the loader's cache-miss signal), never a
        crash downstream of a half-installed solver."""
        import pickle

        import jax
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
        )

        meta = artifact.get("meta") or {}
        if meta.get("format") != ARTIFACT_FORMAT:
            raise ArtifactIncompatible(
                f"artifact format {meta.get('format')!r} != "
                f"{ARTIFACT_FORMAT!r}")
        if meta.get("jax") != jax.__version__ or (
                meta.get("backend") != jax.default_backend()):
            raise ArtifactIncompatible(
                f"artifact pinned jax {meta.get('jax')}/"
                f"{meta.get('backend')} but this runtime is "
                f"{jax.__version__}/{jax.default_backend()}")
        fns = artifact.get("fns") or {}
        missing = [n for n in ARTIFACT_FNS if n not in fns]
        if missing:
            raise ArtifactIncompatible(f"artifact missing {missing}")
        loaded = {}
        for name in ARTIFACT_FNS:
            try:
                payload, in_tree, out_tree = pickle.loads(fns[name])
                loaded[name] = deserialize_and_load(payload, in_tree,
                                                    out_tree)
            except ArtifactIncompatible:
                raise
            except Exception as exc:
                raise ArtifactIncompatible(
                    f"artifact executable {name} failed to load: "
                    f"{type(exc).__name__}: {exc}"[:300]) from exc
        for name, fn in loaded.items():
            setattr(self, name, fn)
        # the artifact records the form that actually compiled at the
        # publisher (including a recorded fused->unfused fallback)
        self.engine_form = meta.get("engine_form", self.engine_form)
        self.engine_fallback_reason = meta.get("engine_fallback_reason")
        self.warm_source = "artifact"

    def solve(self, scales) -> BatchResult:
        """Run one padded batch: `scales` (len <= bucket) are the live
        lanes' RHS scales; dead lanes are zero-padded and return frozen
        zeros. Norms come back per live lane."""
        import jax
        import jax.numpy as jnp

        if FAULT_HOOK is not None:
            FAULT_HOOK(self.spec, scales)
        live = len(scales)
        pad = self._pad_scales(scales)

        t0 = time.perf_counter()
        if self.spec.precision == "df32":
            # whole-batch df solve through the SAME checkpoint
            # executables continuous batching drives (init +
            # ceil(nreps/chunk) chunk steps — the per-lane df recurrence
            # of the vmapped cg_solve_df oracle, p-update reassociated;
            # parity <= 1e-13). Scaling stays df-exact: the f64 scale
            # splits into its own (hi, lo) pair host-side and multiplies
            # in df arithmetic inside the compiled init (any scale keeps
            # the 1e-13 linearity contract).
            shi, slo = _df_split_scales(pad)
            state = self._init_fn(self._base, shi, slo)
            for _ in range(-(-self.spec.nreps // self.iter_chunk)):
                state = self._step_fn(self._op, state)
            from ..la.cg import batched_dot_df

            d = jax.jit(batched_dot_df)(state.X, state.X)
            jax.block_until_ready(d)
            dhi = np.asarray(d.hi, np.float64)
            dlo = np.asarray(d.lo, np.float64)
            xn = [float(np.sqrt(max(dhi[i] + dlo[i], 0.0)))
                  for i in range(live)]
        else:
            # whole-batch solve through the SAME checkpoint executables
            # continuous batching drives (init + ceil(nreps/chunk) chunk
            # steps — bitwise the one-fori_loop solve: the extra frozen
            # steps of the last chunk are per-lane no-ops)
            if self.supports_warm:
                state = self._init_fn(
                    self._op, self._base, self._xbase,
                    jnp.asarray(pad, self._base.dtype),
                    jnp.zeros_like(jnp.asarray(pad, self._base.dtype)))
            else:
                state = self._init_fn(self._base,
                                      jnp.asarray(pad, self._base.dtype))
            for _ in range(-(-self.spec.nreps // self.iter_chunk)):
                state = self._step_fn(self._op, state)
            # vmapped scalar dot (la.cg.batched_dot): per lane the SAME
            # reduction as the one-shot driver's vdot — the parity
            # check compares norms straight across
            from ..la.cg import batched_dot

            sq = jax.jit(batched_dot)(state.X, state.X)
            jax.block_until_ready(sq)
            xn = [float(v) for v in np.sqrt(np.asarray(sq)[:live])]
        wall = time.perf_counter() - t0
        extra = {"cg_engine_form": self.engine_form,
                 "precision": self.spec.precision,
                 "geom": self.spec.geom}
        if self.continuous_gate_reason:
            extra["continuous_gate_reason"] = self.continuous_gate_reason
        if self.engine_fallback_reason:
            extra["cg_engine_error"] = self.engine_fallback_reason
        return BatchResult(
            xnorms=xn,
            wall_s=wall,
            nrhs_live=live,
            nrhs_bucket=self.bucket,
            ndofs_global=self.ndofs_global,
            nreps=self.spec.nreps,
            gdof_per_second=(
                self.ndofs_global * self.spec.nreps * live / (1e9 * wall)
                if wall > 0 else 0.0),
            extra=extra,
        )

    # -- continuous-batching checkpoint API (f32/f64) ----------------------

    def _pad_scales(self, scales) -> np.ndarray:
        live = len(scales)
        if live > self.bucket:
            raise ValueError(f"{live} scales > bucket {self.bucket}")
        pad = np.zeros(self.bucket, np.float64)
        pad[:live] = np.asarray(scales, np.float64)
        return pad

    def cont_init(self, scales, warm_scales=None):
        """Fresh checkpoint state for the initial batch (padding lanes
        born frozen). Runs the fault-injection hook — the continuous
        path must be as testable as the one-shot one.

        `warm_scales` (warm-start solvers only, same length as
        `scales`): per-lane multiplier on the precomputed base solution
        used as the initial guess x0 = warm * xbase. Zero (the default,
        and forced under BENCH_SUPPRESS_WARMSTART) reproduces the cold
        init bitwise — A·0 is exactly zero, so R = B."""
        import jax.numpy as jnp

        if FAULT_HOOK is not None:
            FAULT_HOOK(self.spec, scales)
        pad = self._pad_scales(scales)
        if self.spec.precision == "df32":
            shi, slo = _df_split_scales(pad)
            return self._init_fn(self._base, shi, slo)
        if self.supports_warm:
            if warm_scales is None or _warm_suppressed():
                wpad = np.zeros(self.bucket, np.float64)
            else:
                wpad = self._pad_scales(warm_scales)
            return self._init_fn(
                self._op, self._base, self._xbase,
                jnp.asarray(pad, self._base.dtype),
                jnp.asarray(wpad, self._base.dtype))
        return self._init_fn(self._base,
                             jnp.asarray(pad, self._base.dtype))

    def cont_step(self, state):
        """Advance every live lane by `iter_chunk` iterations (one
        compiled call; frozen lanes stay frozen)."""
        return self._step_fn(self._op, state)

    def cont_poll(self, state):
        """(iters, done) per lane as host numpy — the broker's
        retire/admit decision input (a (bucket,)-sized transfer)."""
        return np.asarray(state.iters), np.asarray(state.done)

    def cont_admit(self, state, lane: int, scale: float,
                   warm_scale: float = 0.0):
        """Admit a request into a free lane at this boundary: the lane
        restarts as scale * base RHS with its own iteration budget.
        df32 splits the f64 scale host-side (df-exact scaling).

        `warm_scale` (warm-start solvers only): the lane starts from
        x0 = warm_scale * xbase instead of zero; 0.0 (the default, and
        forced under BENCH_SUPPRESS_WARMSTART) is bitwise the cold
        admit."""
        if self.spec.precision == "df32":
            s64 = np.float64(scale)
            shi = np.float32(s64)
            slo = np.float32(s64 - np.float64(shi))
            return self._admit_fn(self._base, state, np.int32(lane),
                                  shi, slo)
        if self.supports_warm:
            warm = 0.0 if _warm_suppressed() else float(warm_scale)
            return self._admit_fn(
                self._op, self._base, self._xbase, state, np.int32(lane),
                np.asarray(scale, self._base.dtype),
                np.asarray(warm, self._base.dtype))
        return self._admit_fn(self._base, state, np.int32(lane),
                              np.asarray(scale, self._base.dtype))

    def cont_retire(self, state, lane: int):
        """Retire a finished lane: returns (state with the lane freed,
        that lane's solution L2 norm — same reduction as the one-shot
        driver's vdot; df32 folds the (hi, lo) dot pair in f64 on
        host, the oracle's norm convention)."""
        if self.spec.precision == "df32":
            state, dhi, dlo = self._retire_fn(state, np.int32(lane))
            return state, float(np.sqrt(max(
                np.float64(dhi) + np.float64(dlo), 0.0)))
        state, xn = self._retire_fn(state, np.int32(lane))
        return state, float(xn)

    # -- SDC retire-time audit (ISSUE 14) -----------------------------------

    def audit_lane(self, state, lane: int, scale: float) -> dict:
        """True-residual audit of ONE lane at an iteration boundary,
        BEFORE it retires: recompute ``‖scale·b − A x‖`` from scratch
        (one apply — off the hot path, only audited retires pay it) and
        compare against the lane's carried recurrence rnorm, normalised
        by ``‖r0‖``, against the per-precision drift envelope
        (ops.abft.RESIDUAL_ENVELOPE). A silent corruption of the lane's
        carry breaks the identity and stays broken; the broker maps an
        exceedance to the `sdc` failure class with rollback/terminal
        adjudication. Returns {"ok", "drift", "envelope"} — a dead or
        padding lane (rnorm0 == 0) audits trivially ok."""
        import jax

        from ..ops.abft import RESIDUAL_ENVELOPE

        if getattr(self, "_audit_fn", None) is None:
            import jax.numpy as jnp

            if self.spec.precision == "df32":
                from ..la.df64 import DF, df_dot, df_mul, df_sub

                def _aud(op, base, state, lane, shi, slo):
                    x = DF(state.X.hi[lane], state.X.lo[lane])
                    y = op.apply(x)
                    bl = df_mul(base, DF(
                        jnp.broadcast_to(shi, base.hi.shape),
                        jnp.broadcast_to(slo, base.hi.shape)))
                    rr = df_sub(bl, y)
                    return (df_dot(rr, rr).hi, state.rnorm.hi[lane],
                            state.rnorm0_hi[lane])
            else:
                from ..la.vector import inner_product

                def _aud(op, base, state, lane, scale):
                    x = state.X[lane]
                    rr = scale * base - op.apply(x)
                    return (inner_product(rr, rr), state.rnorm[lane],
                            state.rnorm0[lane])

            self._audit_fn = jax.jit(_aud)
        if self.spec.precision == "df32":
            s64 = np.float64(scale)
            shi = np.float32(s64)
            slo = np.float32(s64 - np.float64(shi))
            tr, carried, rn0 = self._audit_fn(
                self._op, self._base, state, np.int32(lane), shi, slo)
        else:
            tr, carried, rn0 = self._audit_fn(
                self._op, self._base, state, np.int32(lane),
                np.asarray(scale, self._base.dtype))
        tr = float(np.asarray(tr))
        carried = float(np.asarray(carried))
        rn0 = float(np.asarray(rn0))
        env = RESIDUAL_ENVELOPE[self.spec.precision]
        if rn0 <= 0.0:
            return {"ok": True, "drift": 0.0, "envelope": env}
        if not (np.isfinite(tr) and np.isfinite(carried)):
            # non-finite is the BREAKDOWN sentinel's class, not sdc's
            # (sdc = finite but inconsistent, by construction): audit
            # trivially ok and let the retire-time xnorm check answer
            # `breakdown` as it always has
            return {"ok": True, "drift": 0.0, "envelope": env,
                    "nonfinite": True}
        drift = abs(np.sqrt(max(tr, 0.0)) - np.sqrt(max(carried, 0.0))) \
            / np.sqrt(rn0)
        return {"ok": bool(drift <= env), "drift": float(drift),
                "envelope": env}


def build_solver(spec: SolveSpec, bucket: int | None = None,
                 artifact: dict | None = None) -> CompiledSolver:
    """Build + AOT-compile a batched solver for the spec at the given
    (or minimal) nrhs bucket. With `artifact` (an `export_artifact`
    payload) the XLA compile path is skipped entirely: only the
    host-side problem setup runs and the peer's serialized executables
    are installed (raises ArtifactIncompatible on a version/format
    mismatch — the caller's cache-miss signal)."""
    return CompiledSolver(spec, bucket or nrhs_bucket(1),
                          artifact=artifact)
