"""Solver construction for the serving layer: spec -> compiled batched
executable.

A `SolveSpec` is the request-compatibility class (degree, problem size,
iteration count, precision, geometry class): requests agreeing on it can
share one batch and one executable. `build_solver` assembles the
operator ONCE from the existing unfused operator builders (ops.kron /
ops.laplacian / ops.kron_df — the fused delay-ring engines have no
batched form yet, so the serving path is the recorded
`cg_engine_form: "unfused"` composition, same vocabulary as
bench.driver.record_engine) and AOT-compiles the batched multi-RHS CG
(`la.cg.cg_solve_batched`, or a vmapped `cg_solve_df` for df32 pairs)
for one nrhs bucket.

The request's right-hand side enters as a per-lane SCALE of the spec's
canonical benchmark RHS (the Gaussian-bump source every driver solves).
CG with a fixed iteration count is exactly linear in b — alpha/beta are
scale-invariant ratios, so x(c*b) = c*x(b) — which gives the serving
acceptance check its teeth: every response must match the one-shot
driver's solution norm times the request scale to the batched-parity
tolerances (<= 1e-7 f32, <= 1e-13 df32), per lane, straight off the
wire. Precision caveat: the scaling itself is exact for power-of-two
scales in f32 (what the acceptance smoke and bench.driver.batch_scales
use) and df-exact for ANY scale in df32 (the scale multiplies as a df
pair, see solve()); an f32 request with a non-power-of-two scale adds
one input rounding (~6e-8 relative) on top of the contract.

Evidence label: serving throughput numbers from this module are
CPU-measured unless a round artifact says otherwise; the TPU folded/
pallas serving path is a design note in the README, not a shipped form.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from .cache import ExecutableKey, nrhs_bucket

# Test/fault-injection seam: when set, called as FAULT_HOOK(spec, scales)
# at the top of every compiled-solver execution — raising here simulates
# a solve-path fault (OOM, hang, Mosaic reject) without touching the
# solver code. harness.faults.FaultySolveHook scripts it.
FAULT_HOOK = None

_PRECISIONS = ("f32", "f64", "df32")

# Admission cap on problem size: a single oversized request must be
# REFUSED (classified `unsupported`, 422) rather than allowed to grind
# the worker through a multi-GB host allocation — or worse, draw the
# Linux OOM killer onto the serving process. Generous for CPU serving
# (the benchmark's own flagship is 12.5M dofs); raise deliberately for
# a TPU deployment, not by accident.
MAX_NDOFS = 50_000_000


@dataclass(frozen=True)
class SolveSpec:
    """The request-compatibility key, pre-bucket. `nreps` is the CG
    iteration count (benchmark semantics: rtol=0, exactly nreps
    iterations — responses are comparable across requests only because
    the iteration count is part of the spec)."""

    degree: int = 3
    ndofs: int = 50_000
    nreps: int = 30
    precision: str = "f32"
    geom_perturb_fact: float = 0.0

    @property
    def geom(self) -> str:
        return "perturbed" if self.geom_perturb_fact != 0.0 else "uniform"

    def validate(self) -> None:
        if not 1 <= self.degree <= 7:
            raise UnsupportedSpec(f"degree {self.degree} unsupported (1-7)")
        if self.precision not in _PRECISIONS:
            raise UnsupportedSpec(
                f"precision {self.precision!r} unsupported {_PRECISIONS}")
        if self.precision == "df32" and self.geom != "uniform":
            raise UnsupportedSpec(
                "df32 serving requires a uniform mesh (the kron df path); "
                "perturbed f64-class serving is unsupported here")
        if self.ndofs <= 0 or self.nreps <= 0:
            raise UnsupportedSpec("ndofs and nreps must be positive")
        if self.ndofs > MAX_NDOFS:
            raise UnsupportedSpec(
                f"ndofs {self.ndofs} exceeds the serving cap "
                f"{MAX_NDOFS} (engine.MAX_NDOFS) — unsupported")


class UnsupportedSpec(ValueError):
    """A capability gate declined the spec — classified `unsupported`
    by the harness taxonomy (deterministic: retrying cannot help)."""


def spec_cache_key(spec: SolveSpec, bucket: int,
                   device_mesh: tuple = (1, 1, 1)) -> ExecutableKey:
    from ..mesh.sizing import compute_mesh_size

    cells = compute_mesh_size(spec.ndofs, spec.degree)
    return ExecutableKey(
        degree=spec.degree,
        cell_shape=tuple(int(c) for c in cells),
        precision=spec.precision,
        geom=spec.geom,
        engine_form="unfused",
        nrhs_bucket=bucket,
        device_mesh=tuple(device_mesh),
        nreps=spec.nreps,
    )


@dataclass
class BatchResult:
    """One executed batch: per-live-lane solution norms plus the
    accounting the metrics layer journals."""

    xnorms: list  # len(scales): L2 norm of each live lane's solution
    wall_s: float
    nrhs_live: int
    nrhs_bucket: int
    ndofs_global: int
    nreps: int
    gdof_per_second: float
    extra: dict = field(default_factory=dict)


class CompiledSolver:
    """One AOT-compiled batched solver: operator state + base RHS held on
    device, executable compiled for (bucket, *grid) inputs. `solve`
    scales the base RHS per lane (zero-padding dead lanes — they start
    frozen inside the batched CG), runs the executable, and returns the
    per-lane norms with throughput accounting
    (GDoF/s = ndofs * nreps * live_lanes / wall)."""

    def __init__(self, spec: SolveSpec, bucket: int):
        import jax
        import jax.numpy as jnp

        spec.validate()
        self.spec = spec
        self.bucket = int(bucket)
        self.key = spec_cache_key(spec, self.bucket)

        from ..elements.tables import build_operator_tables
        from ..mesh.box import create_box_mesh
        from ..mesh.dofmap import dof_grid_shape
        from ..mesh.sizing import compute_mesh_size
        from ..utils.compilation import compile_lowered

        t0 = time.perf_counter()
        n = compute_mesh_size(spec.ndofs, spec.degree)
        t = build_operator_tables(spec.degree, 1, "gll")
        mesh = create_box_mesh(n, geom_perturb_fact=spec.geom_perturb_fact)
        self.ndofs_global = int(np.prod(dof_grid_shape(n, spec.degree)))

        # Host-assembled f64 RHS (the canonical benchmark problem: the
        # drivers assemble the same b), scaled per lane at solve time.
        from ..bench.driver import BenchConfig, _setup_problem

        cfg = BenchConfig(ndofs_global=spec.ndofs, degree=spec.degree,
                          qmode=1, nreps=spec.nreps,
                          geom_perturb_fact=spec.geom_perturb_fact)
        _, _, _, _, _, _, _, b_host, _ = _setup_problem(
            cfg, n, prebuilt=(n, "gll", t, mesh))
        b64 = np.asarray(b_host, np.float64)

        nreps = spec.nreps
        if spec.precision == "df32":
            from ..la.df64 import DF, df_from_f64
            from ..ops.kron_df import build_kron_laplacian_df, cg_solve_df

            self._op = build_kron_laplacian_df(
                mesh, spec.degree, 1, "gll", kappa=2.0, tables=t)
            bdf = df_from_f64(b64)
            self._base = DF(jnp.asarray(bdf.hi), jnp.asarray(bdf.lo))

            def run(A, Bhi, Blo):
                return jax.vmap(
                    lambda bh, bl: cg_solve_df(A, DF(bh, bl), nreps)
                )(Bhi, Blo)

            Bs = jax.ShapeDtypeStruct((self.bucket, *b64.shape),
                                      np.dtype("float32"))
            self._fn = compile_lowered(
                jax.jit(run).lower(self._op, Bs, Bs), None)
        else:
            from ..la.cg import cg_solve_batched
            from ..ops.laplacian import build_laplacian

            dtype = jnp.float64 if spec.precision == "f64" else jnp.float32
            if spec.precision == "f64" and not jax.config.jax_enable_x64:
                raise UnsupportedSpec(
                    "precision 'f64' needs jax_enable_x64 (the serve CLI "
                    "enables it; in-process callers must)")
            # Uniform meshes take the exact Kronecker fast path; general
            # (perturbed) geometry the einsum operator. Both unfused
            # applies vmap cleanly over the batch axis — the Pallas
            # folded serving form is future work (design note, README).
            backend = "kron" if spec.geom == "uniform" else "xla"
            self._op = build_laplacian(
                mesh, spec.degree, 1, "gll", kappa=2.0, dtype=dtype,
                tables=t, backend=backend)
            self._base = jnp.asarray(b64, dtype)

            def run(A, B):
                return cg_solve_batched(
                    A.apply, B, jnp.zeros_like(B), nreps)

            Bs = jax.ShapeDtypeStruct((self.bucket, *b64.shape),
                                      np.dtype(dtype))
            self._fn = compile_lowered(jax.jit(run).lower(self._op, Bs),
                                       None)
        self.compile_s = time.perf_counter() - t0

    def solve(self, scales) -> BatchResult:
        """Run one padded batch: `scales` (len <= bucket) are the live
        lanes' RHS scales; dead lanes are zero-padded and return frozen
        zeros. Norms come back per live lane."""
        import jax
        import jax.numpy as jnp

        if FAULT_HOOK is not None:
            FAULT_HOOK(self.spec, scales)
        live = len(scales)
        if live > self.bucket:
            raise ValueError(f"{live} scales > bucket {self.bucket}")
        pad = np.zeros(self.bucket, np.float64)
        pad[:live] = np.asarray(scales, np.float64)

        t0 = time.perf_counter()
        if self.spec.precision == "df32":
            # df-exact per-lane scaling: the f64 scale splits into its
            # own hi/lo pair and multiplies in df arithmetic, so s*b
            # keeps df precision for ANY scale (a naive f32 s*hi drops
            # the product's rounding error and would degrade the 1e-13
            # linearity contract to ~1e-8 for non-power-of-two scales)
            from ..la.df64 import DF, df_from_f64, df_scale

            sdf = df_from_f64(pad)
            sb = DF(jnp.asarray(sdf.hi)[:, None, None, None],
                    jnp.asarray(sdf.lo)[:, None, None, None])
            shape = (self.bucket, *self._base.hi.shape)
            base_b = DF(jnp.broadcast_to(self._base.hi[None], shape),
                        jnp.broadcast_to(self._base.lo[None], shape))
            Bdf = jax.jit(df_scale)(base_b, sb)
            X = self._fn(self._op, Bdf.hi, Bdf.lo)
            jax.block_until_ready(X)
            from ..la.df64 import DF, df_dot, df_to_f64

            xn = [
                float(np.sqrt(max(float(df_to_f64(df_dot(
                    DF(X.hi[i], X.lo[i]), DF(X.hi[i], X.lo[i])))), 0.0)))
                for i in range(live)
            ]
        else:
            s = jnp.asarray(pad, self._base.dtype)[:, None, None, None]
            X = self._fn(self._op, s * self._base[None])
            jax.block_until_ready(X)
            # vmapped scalar dot (la.cg.batched_dot): per lane the SAME
            # reduction as the one-shot driver's vdot — the parity
            # check compares norms straight across
            from ..la.cg import batched_dot

            sq = jax.jit(batched_dot)(X, X)
            xn = [float(v) for v in np.sqrt(np.asarray(sq)[:live])]
        wall = time.perf_counter() - t0
        return BatchResult(
            xnorms=xn,
            wall_s=wall,
            nrhs_live=live,
            nrhs_bucket=self.bucket,
            ndofs_global=self.ndofs_global,
            nreps=self.spec.nreps,
            gdof_per_second=(
                self.ndofs_global * self.spec.nreps * live / (1e9 * wall)
                if wall > 0 else 0.0),
            extra={"cg_engine_form": "unfused",
                   "precision": self.spec.precision,
                   "geom": self.spec.geom},
        )


def build_solver(spec: SolveSpec, bucket: int | None = None) -> CompiledSolver:
    """Build + AOT-compile a batched solver for the spec at the given
    (or minimal) nrhs bucket."""
    return CompiledSolver(spec, bucket or nrhs_bucket(1))
