"""Serving metrics: counters + a crash-safe JSONL journal.

Reuses the measurement harness's journal (`harness.journal.Journal` —
fsynced append-only JSONL, torn-tail tolerant) so a served incident
leaves the same class of evidence a measurement run does: every request
admission, shed, batch execution and response is one journal record, and
`replay_serve` folds a journal back into the incident summary
("the metrics journal replays the full incident" — the backpressure
acceptance criterion).

Record schema (all lines also carry the journal's v/seq/ts):

  {"event": "serve_request",  "id": ..., "spec": {...}, "queue_depth": N}
  {"event": "serve_shed",     "id": ..., "failure_class": "transient",
                              "queue_depth": N}
  {"event": "serve_batch",    "spec": {...}, "nrhs_live": N,
                              "nrhs_bucket": B, "cache": "hit"|"miss",
                              "wall_s": ..., "gdof_per_second": ...}
  {"event": "serve_response", "id": ..., "ok": bool, "latency_s": ...,
                              "failure_class": ... (failures only),
                              "retriable": bool (failures only)}

Cache hit-rate is REQUEST-weighted (requests served from an
already-compiled executable / requests batched): a warm cache serving
64 requests in 10 batches is a 100% hit-rate story, not a 10-lookup
one. The raw cache counters ride along unweighted in `snapshot()`.
"""

from __future__ import annotations

import threading
from collections import deque

from ..harness.journal import Journal, read_records

# Bounded latency window: serving metrics must not grow without bound.
_LATENCY_WINDOW = 4096


class Metrics:
    """Thread-safe counters + optional journal. Every mutator journals
    first (evidence before bookkeeping — a crash mid-increment still
    leaves the record)."""

    def __init__(self, journal_path: str | None = None):
        self.journal = Journal(journal_path) if journal_path else None
        self._lock = threading.Lock()
        self.requests_total = 0
        self.shed_total = 0
        self.completed = 0
        self.failed = 0
        self.failed_by_class: dict[str, int] = {}
        self.batches = 0
        self.lanes_total = 0  # live lanes across batches (occupancy sum)
        self.cache_hit_requests = 0
        self.cache_miss_requests = 0
        self.gdof_samples: deque = deque(maxlen=_LATENCY_WINDOW)
        self.latencies: deque = deque(maxlen=_LATENCY_WINDOW)
        self.queue_depth = 0

    def _journal(self, rec: dict) -> None:
        if self.journal is not None:
            self.journal.append(rec)

    # -- events ------------------------------------------------------------

    def request(self, req_id: str, spec_dict: dict, queue_depth: int) -> None:
        self._journal({"event": "serve_request", "id": req_id,
                       "spec": spec_dict, "queue_depth": queue_depth})
        with self._lock:
            self.requests_total += 1
            self.queue_depth = queue_depth

    def shed(self, req_id: str, queue_depth: int,
             failure_class: str = "transient") -> None:
        self._journal({"event": "serve_shed", "id": req_id,
                       "failure_class": failure_class,
                       "queue_depth": queue_depth})
        with self._lock:
            self.shed_total += 1

    def batch(self, spec_dict: dict, nrhs_live: int, nrhs_bucket: int,
              cache_hit: bool, wall_s: float,
              gdof_per_second: float) -> None:
        self._journal({"event": "serve_batch", "spec": spec_dict,
                       "nrhs_live": nrhs_live, "nrhs_bucket": nrhs_bucket,
                       "cache": "hit" if cache_hit else "miss",
                       "wall_s": round(wall_s, 6),
                       "gdof_per_second": round(gdof_per_second, 6)})
        with self._lock:
            self.batches += 1
            self.lanes_total += nrhs_live
            if cache_hit:
                self.cache_hit_requests += nrhs_live
            else:
                self.cache_miss_requests += nrhs_live
            self.gdof_samples.append(gdof_per_second)

    def response(self, req_id: str, ok: bool, latency_s: float,
                 failure_class: str | None = None,
                 retriable: bool | None = None) -> None:
        rec = {"event": "serve_response", "id": req_id, "ok": ok,
               "latency_s": round(latency_s, 6)}
        if not ok:
            rec["failure_class"] = failure_class or "transient"
            rec["retriable"] = bool(retriable)
        self._journal(rec)
        with self._lock:
            if ok:
                self.completed += 1
            else:
                self.failed += 1
                fc = failure_class or "transient"
                self.failed_by_class[fc] = (
                    self.failed_by_class.get(fc, 0) + 1)
            self.latencies.append(latency_s)

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth

    # -- snapshot ----------------------------------------------------------

    def snapshot(self, cache_stats: dict | None = None) -> dict:
        with self._lock:
            lat = sorted(self.latencies)
            batched = self.cache_hit_requests + self.cache_miss_requests
            out = {
                "requests_total": self.requests_total,
                "shed_total": self.shed_total,
                "completed": self.completed,
                "failed": self.failed,
                "failed_by_class": dict(self.failed_by_class),
                "batches": self.batches,
                "queue_depth": self.queue_depth,
                "mean_batch_occupancy": (
                    self.lanes_total / self.batches if self.batches else 0.0
                ),
                "cache_hit_rate_requests": (
                    self.cache_hit_requests / batched if batched else 0.0
                ),
                "latency_p50_s": _pct(lat, 0.50),
                "latency_p95_s": _pct(lat, 0.95),
                "gdof_per_second_mean": (
                    sum(self.gdof_samples) / len(self.gdof_samples)
                    if self.gdof_samples else 0.0
                ),
            }
        if cache_stats is not None:
            out["cache"] = cache_stats
        return out


def _pct(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return float(sorted_vals[i])


def replay_serve(journal_path: str) -> dict:
    """Fold a serve journal back into the incident summary: per-event
    counts, per-class failure counts, occupancy and hit-rate — enough to
    reconstruct "what happened" from the file alone (the journal IS the
    incident record; this is its reader)."""
    records, corrupt = read_records(journal_path)
    out = {
        "requests": 0, "shed": 0, "batches": 0, "responses_ok": 0,
        "responses_failed": 0, "failed_by_class": {}, "lanes_total": 0,
        "cache_hits": 0, "cache_misses": 0, "corrupt_lines": len(corrupt),
    }
    for rec in records:
        ev = rec.get("event")
        if ev == "serve_request":
            out["requests"] += 1
        elif ev == "serve_shed":
            out["shed"] += 1
            fc = rec.get("failure_class", "transient")
            out["failed_by_class"][fc] = (
                out["failed_by_class"].get(fc, 0) + 1)
        elif ev == "serve_batch":
            out["batches"] += 1
            out["lanes_total"] += int(rec.get("nrhs_live", 0))
            if rec.get("cache") == "hit":
                out["cache_hits"] += int(rec.get("nrhs_live", 0))
            else:
                out["cache_misses"] += int(rec.get("nrhs_live", 0))
        elif ev == "serve_response":
            if rec.get("ok"):
                out["responses_ok"] += 1
            else:
                out["responses_failed"] += 1
                fc = rec.get("failure_class", "transient")
                out["failed_by_class"][fc] = (
                    out["failed_by_class"].get(fc, 0) + 1)
    out["mean_batch_occupancy"] = (
        out["lanes_total"] / out["batches"] if out["batches"] else 0.0)
    batched = out["cache_hits"] + out["cache_misses"]
    out["cache_hit_rate_requests"] = (
        out["cache_hits"] / batched if batched else 0.0)
    return out
